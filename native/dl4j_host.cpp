// Native host-side data runtime for deeplearning4j_tpu.
//
// Role: the CPU-bound ETL the reference delegated to native code (ND4J's
// libnd4j + Canova record readers — SURVEY §2.2). The TPU compute path is
// XLA; this library owns the host side: record parsing (CSV / SVMLight /
// idx) and a threaded read-ahead file streamer backing the prefetch
// pipeline (AsyncDataSetIterator role, datasets/iterator/
// AsyncDataSetIterator.java:44).
//
// Exposed as a plain C ABI consumed via ctypes (no pybind11 in the image).
// All buffers are malloc'd here and freed here; Python copies out into
// numpy arrays and promptly frees the handle.

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <mutex>
#include <queue>
#include <string>
#include <thread>
#include <vector>

namespace {

struct FloatBuf {
  std::vector<float> data;
  std::vector<int64_t> dims;
};

// Read a whole file into memory. Returns false on IO error.
bool read_file(const char* path, std::string* out) {
  FILE* f = std::fopen(path, "rb");
  if (!f) return false;
  std::fseek(f, 0, SEEK_END);
  long n = std::ftell(f);
  if (n < 0) { std::fclose(f); return false; }
  std::fseek(f, 0, SEEK_SET);
  out->resize(static_cast<size_t>(n));
  size_t got = n ? std::fread(&(*out)[0], 1, static_cast<size_t>(n), f) : 0;
  std::fclose(f);
  return got == static_cast<size_t>(n);
}

}  // namespace

extern "C" {

// ---------------------------------------------------------------------------
// Generic float-buffer handle
// ---------------------------------------------------------------------------

const float* dl4j_buf_data(void* h) {
  return static_cast<FloatBuf*>(h)->data.data();
}

int64_t dl4j_buf_size(void* h) {
  return static_cast<int64_t>(static_cast<FloatBuf*>(h)->data.size());
}

int dl4j_buf_ndim(void* h) {
  return static_cast<int>(static_cast<FloatBuf*>(h)->dims.size());
}

void dl4j_buf_dims(void* h, int64_t* out) {
  FloatBuf* b = static_cast<FloatBuf*>(h);
  for (size_t i = 0; i < b->dims.size(); ++i) out[i] = b->dims[i];
}

void dl4j_buf_free(void* h) { delete static_cast<FloatBuf*>(h); }

// ---------------------------------------------------------------------------
// CSV → dense [rows, cols] float matrix. Numeric cells only; returns nullptr
// on ragged rows, non-numeric cells, or IO failure (caller falls back to the
// Python text path).
// ---------------------------------------------------------------------------

void* dl4j_csv_parse(const char* path, char delim, int64_t skip_lines) {
  std::string text;
  if (!read_file(path, &text)) return nullptr;
  FloatBuf* buf = new FloatBuf();
  int64_t cols = -1, row_cols = 0, line_no = 0;
  bool row_has_data = false;
  const char* p = text.c_str();
  const char* end = p + text.size();
  const char* cell = p;

  auto fail = [&]() -> void* { delete buf; return nullptr; };

  auto flush_cell = [&](const char* cend) -> bool {
    if (line_no < skip_lines) return true;
    // empty trailing cell on an empty line: handled by caller
    char* conv_end = nullptr;
    // strtof needs NUL-terminated input; copy the (tiny) cell
    std::string s(cell, cend);
    // strip spaces
    size_t a = s.find_first_not_of(" \t\r");
    size_t b = s.find_last_not_of(" \t\r");
    if (a == std::string::npos) return false;  // blank cell
    s = s.substr(a, b - a + 1);
    float v = std::strtof(s.c_str(), &conv_end);
    if (conv_end != s.c_str() + s.size()) return false;  // non-numeric
    buf->data.push_back(v);
    ++row_cols;
    row_has_data = true;
    return true;
  };

  while (p <= end) {
    char c = (p == end) ? '\n' : *p;
    if (c == delim) {
      if (!flush_cell(p)) return fail();
      cell = p + 1;
    } else if (c == '\n' || c == '\r') {
      bool blank_line = (cell == p) && row_cols == 0;
      if (!blank_line) {
        if (!flush_cell(p)) return fail();
      }
      if (row_has_data) {
        if (cols == -1) cols = row_cols;
        else if (row_cols != cols) return fail();  // ragged
      }
      ++line_no;
      row_cols = 0;
      row_has_data = false;
      // swallow \r\n pairs
      if (c == '\r' && p + 1 < end && p[1] == '\n') ++p;
      cell = p + 1;
    }
    ++p;
  }
  if (cols <= 0) return fail();
  buf->dims = {static_cast<int64_t>(buf->data.size()) / cols, cols};
  return buf;
}

// ---------------------------------------------------------------------------
// SVMLight "label idx:val ..." → dense features [rows, n_features] followed
// by labels [rows] in one buffer (features first, then labels).
// ---------------------------------------------------------------------------

void* dl4j_svmlight_parse(const char* path, int64_t n_features,
                          int zero_based) {
  std::string text;
  if (!read_file(path, &text)) return nullptr;
  FloatBuf* buf = new FloatBuf();
  std::vector<float> labels;
  const char* p = text.c_str();
  const char* end = p + text.size();

  auto fail = [&]() -> void* { delete buf; return nullptr; };

  while (p < end) {
    // line bounds
    const char* eol = static_cast<const char*>(
        std::memchr(p, '\n', static_cast<size_t>(end - p)));
    if (!eol) eol = end;
    const char* q = p;
    while (q < eol && (*q == ' ' || *q == '\t' || *q == '\r')) ++q;
    if (q == eol || *q == '#') { p = eol + 1; continue; }  // blank/comment

    char* conv = nullptr;
    float label = std::strtof(q, &conv);
    if (conv == q) return fail();
    q = conv;
    size_t base = buf->data.size();
    buf->data.resize(base + static_cast<size_t>(n_features), 0.0f);
    while (q < eol) {
      while (q < eol && (*q == ' ' || *q == '\t' || *q == '\r')) ++q;
      if (q >= eol || *q == '#') break;
      long idx = std::strtol(q, &conv, 10);
      if (conv == q || conv >= eol || *conv != ':') return fail();
      q = conv + 1;
      float v = std::strtof(q, &conv);
      if (conv == q) return fail();
      q = conv;
      long i = idx - (zero_based ? 0 : 1);
      if (i < 0 || i >= n_features) return fail();
      buf->data[base + static_cast<size_t>(i)] = v;
    }
    labels.push_back(label);
    p = eol + 1;
  }
  int64_t rows = static_cast<int64_t>(labels.size());
  buf->data.insert(buf->data.end(), labels.begin(), labels.end());
  buf->dims = {rows, n_features};
  return buf;
}

// ---------------------------------------------------------------------------
// idx (MNIST binary) → float buffer with dims from the header. Magic:
// 0x00 0x00 <dtype> <ndim>; dims are big-endian int32; only dtype 0x08
// (unsigned byte) is needed for MNIST.
// ---------------------------------------------------------------------------

void* dl4j_idx_parse(const char* path) {
  std::string text;
  if (!read_file(path, &text) || text.size() < 4) return nullptr;
  const unsigned char* u = reinterpret_cast<const unsigned char*>(text.data());
  if (u[0] != 0 || u[1] != 0) return nullptr;
  unsigned dtype = u[2];
  unsigned ndim = u[3];
  if (dtype != 0x08 || ndim == 0 || ndim > 4) return nullptr;
  if (text.size() < 4 + 4ull * ndim) return nullptr;
  // the payload can never exceed the file size, so a corrupt header whose
  // dims multiply past it (or overflow) must fall back to the Python parser
  const int64_t max_total = static_cast<int64_t>(text.size());
  FloatBuf* buf = new FloatBuf();
  int64_t total = 1;
  for (unsigned d = 0; d < ndim; ++d) {
    const unsigned char* q = u + 4 + 4 * d;
    int64_t dim = (int64_t(q[0]) << 24) | (int64_t(q[1]) << 16) |
                  (int64_t(q[2]) << 8) | int64_t(q[3]);
    if (dim < 0 || (dim > 0 && total > max_total / dim)) {
      delete buf;
      return nullptr;
    }
    buf->dims.push_back(dim);
    total *= dim;
  }
  if (static_cast<int64_t>(text.size()) < 4 + 4 * ndim + total) {
    delete buf;
    return nullptr;
  }
  buf->data.resize(static_cast<size_t>(total));
  const unsigned char* body = u + 4 + 4 * ndim;
  for (int64_t i = 0; i < total; ++i)
    buf->data[static_cast<size_t>(i)] = static_cast<float>(body[i]);
  return buf;
}

// ---------------------------------------------------------------------------
// Threaded read-ahead streamer: a background thread reads fixed-size chunks
// of a binary file into a bounded ring so the host hides file latency from
// the training loop (the AsyncDataSetIterator prefetch role, natively).
// ---------------------------------------------------------------------------

struct Stream {
  FILE* f = nullptr;
  int64_t chunk = 0;
  size_t capacity = 0;
  std::thread reader;
  std::mutex mu;
  std::condition_variable cv_pop, cv_push;
  std::queue<std::vector<char>> q;
  bool eof = false;
  std::atomic<bool> stop{false};
};

static void stream_loop(Stream* s) {
  for (;;) {
    std::vector<char> block(static_cast<size_t>(s->chunk));
    size_t got = std::fread(block.data(), 1, block.size(), s->f);
    if (s->stop.load()) return;
    block.resize(got);
    {
      std::unique_lock<std::mutex> lk(s->mu);
      s->cv_push.wait(lk, [s] { return s->q.size() < s->capacity ||
                                       s->stop.load(); });
      if (s->stop.load()) return;
      if (got == 0) {
        s->eof = true;
        s->cv_pop.notify_all();
        return;
      }
      s->q.push(std::move(block));
      s->cv_pop.notify_one();
    }
    if (got < static_cast<size_t>(s->chunk)) {
      std::lock_guard<std::mutex> lk(s->mu);
      s->eof = true;
      s->cv_pop.notify_all();
      return;
    }
  }
}

void* dl4j_stream_open(const char* path, int64_t chunk_bytes,
                       int64_t capacity) {
  if (chunk_bytes <= 0 || capacity <= 0) return nullptr;
  FILE* f = std::fopen(path, "rb");
  if (!f) return nullptr;
  Stream* s = new Stream();
  s->f = f;
  s->chunk = chunk_bytes;
  s->capacity = static_cast<size_t>(capacity);
  s->reader = std::thread(stream_loop, s);
  return s;
}

// Blocks until a chunk is ready; copies it into out (must hold chunk_bytes).
// Returns bytes copied; 0 at EOF.
int64_t dl4j_stream_next(void* h, char* out) {
  Stream* s = static_cast<Stream*>(h);
  std::unique_lock<std::mutex> lk(s->mu);
  s->cv_pop.wait(lk, [s] { return !s->q.empty() || s->eof; });
  if (s->q.empty()) return 0;
  std::vector<char> block = std::move(s->q.front());
  s->q.pop();
  s->cv_push.notify_one();
  lk.unlock();
  std::memcpy(out, block.data(), block.size());
  return static_cast<int64_t>(block.size());
}

void dl4j_stream_close(void* h) {
  Stream* s = static_cast<Stream*>(h);
  s->stop.store(true);
  {
    std::lock_guard<std::mutex> lk(s->mu);
    s->cv_push.notify_all();
    s->cv_pop.notify_all();
  }
  if (s->reader.joinable()) s->reader.join();
  std::fclose(s->f);
  delete s;
}

}  // extern "C"
