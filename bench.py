"""Benchmark: LeNet-5 MNIST training throughput on one TPU chip.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "samples/sec", "vs_baseline": R}

The reference publishes no numbers (BASELINE.md), so the baseline is
self-measured per BASELINE.json's north star: ">2x nd4j-native CPU
throughput". Proxy for the nd4j-native CPU path: the SAME jitted LeNet train
step executed on this host's CPU backend (XLA-CPU is a strictly faster
stand-in for 2015-era ND4J op-by-op BLAS dispatch, so beating it by 2x is a
conservative bar). ``vs_baseline`` = TPU samples/sec ÷ CPU samples/sec.

Config (BASELINE.md row 2): LeNet-5, batch 256, synthetic MNIST-shaped data
(throughput does not depend on pixel values; zero-egress image rules out the
real download), bf16 compute / f32 params on TPU.
"""

from __future__ import annotations

import json
import time

import numpy as np


BATCH = 256
WARMUP = 5
STEPS = 30


def _make_batch(seed: int = 0):
    rng = np.random.default_rng(seed)
    x = rng.random((BATCH, 28, 28, 1), np.float32)
    y = np.eye(10, dtype=np.float32)[rng.integers(0, 10, BATCH)]
    return x, y


def _throughput(net, x, y, steps=STEPS, warmup=WARMUP) -> float:
    """Samples/sec through the faster of the two public training paths:
    per-step ``fit`` (one dispatch per step) and the fused ``fit_steps``
    scan driver (one dispatch per K steps). Which wins depends on model
    size and backend — conv-in-scan can be slower on XLA-CPU, while small
    models are dispatch-bound per-step — so the bench takes the max, as a
    user would."""
    import jax

    from deeplearning4j_tpu.datasets.dataset import DataSet

    ds = DataSet(x, y)

    for _ in range(warmup):
        net.fit(ds)
    jax.block_until_ready(net.params)
    t0 = time.perf_counter()
    for _ in range(steps):
        net.fit(ds)
    jax.block_until_ready(net.params)
    stepwise = BATCH * steps / (time.perf_counter() - t0)

    net.fit_steps(ds, steps)  # compile the fused program
    jax.block_until_ready(net.params)
    t0 = time.perf_counter()
    net.fit_steps(ds, steps)
    jax.block_until_ready(net.params)
    fused = BATCH * steps / (time.perf_counter() - t0)
    return max(stepwise, fused)


def main() -> None:
    import jax

    from deeplearning4j_tpu.models import lenet5

    x, y = _make_batch()

    # TPU run (bf16 compute for the MXU)
    tpu_sps = _throughput(lenet5(dtype_policy="bf16").init(), x, y)

    # CPU baseline (f32; the stand-in for the reference's nd4j-native path)
    try:
        cpu = jax.devices("cpu")[0]
        with jax.default_device(cpu):
            cpu_sps = _throughput(lenet5(dtype_policy="float32").init(), x, y,
                                  steps=10, warmup=2)
        vs_baseline = tpu_sps / cpu_sps
    except Exception:
        vs_baseline = float("nan")

    print(json.dumps({
        "metric": "lenet5_mnist_train_samples_per_sec_per_chip",
        "value": round(tpu_sps, 1),
        "unit": "samples/sec",
        "vs_baseline": round(vs_baseline, 2),
    }))


if __name__ == "__main__":
    main()
