"""Benchmark: the full BASELINE.md protocol on one TPU chip.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": R, "extras": {...}}

Headline = transformer-LM training throughput (tokens/sec/chip) — the
model-FLOP-dominated config — with ``vs_baseline`` = TPU ÷ XLA-CPU on the
same jitted step (the reference publishes no numbers — BASELINE.md — so the
baseline is the self-measured north star ">2x nd4j-native CPU throughput";
XLA-CPU is a strictly faster stand-in for 2015 ND4J op-by-op BLAS dispatch).

``extras`` carries every BASELINE.md config:
  - MNIST MLP, LeNet-5, GravesLSTM char-RNN, word2vec skip-gram,
    ResNet-18 CIFAR (bf16) — samples(/words)/sec/chip
  - transformer LM (bf16) — tokens/sec + achieved model TFLOP/s + MFU
  - GEMM sweep 512–8192 (bf16) — achieved TFLOP/s + MFU at the top end

MFU = achieved / peak, peak stated per chip (v5e: 197 TFLOP/s bf16).
Model FLOPs are analytic (formula noted per entry in "flops_source").
Training data is synthetic (zero-egress sandbox; throughput does not
depend on pixel/token values) via the same public ``fit`` APIs a user
calls. The per-step vs fused ``fit_steps`` path is benched separately and
the winner is named in the output (their listener contracts differ).
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np

PEAK_TFLOPS_BF16 = 197.0  # TPU v5e per-chip peak, bf16 MXU


def _log(msg):
    print(msg, file=sys.stderr, flush=True)


def _sync(x):
    """Hard sync: reduce one device leaf to a scalar ON DEVICE and read
    that back. block_until_ready alone is not trustworthy on every backend
    (the tunnel backend acks before the compute drains), and pulling a
    full array through the tunnel is orders of magnitude slower than the
    compute being timed — a 4-byte readback forces completion of all
    prior work (the chip executes its queue in order) without polluting
    the measurement."""
    import jax
    import jax.numpy as jnp

    for leaf in jax.tree_util.tree_leaves(x):
        if hasattr(leaf, "addressable_shards") or hasattr(leaf, "devices"):
            float(jnp.sum(jnp.ravel(leaf)[:1]).astype(jnp.float32))
            return
    # no device leaf found (e.g. a network object): sync nothing loudly
    raise TypeError(f"_sync: no device array found in {type(x)}")


def _time_loop(fn, steps, sync=None):
    """Seconds per call. ``sync`` extracts the device data to read back
    (defaults to the call's own return value)."""
    out = fn()  # warm
    _sync(sync() if sync else out)
    t0 = time.perf_counter()
    for _ in range(steps):
        out = fn()
    _sync(sync() if sync else out)
    return (time.perf_counter() - t0) / steps


# ----------------------------------------------------------------------
def bench_gemm():
    import jax
    import jax.numpy as jnp

    rng = np.random.default_rng(0)
    sizes = [512, 1024, 2048, 4096, 8192]
    results = {}
    best = 0.0
    for n in sizes:
        a = jnp.asarray(rng.normal(size=(n, n)), jnp.bfloat16)
        c = jnp.asarray(rng.normal(size=(n, n)), jnp.bfloat16)
        f = jax.jit(lambda a, b: a @ b)
        steps = 30 if n <= 2048 else 10
        c = f(a, c)
        _sync(c)
        t0 = time.perf_counter()
        for _ in range(steps):
            c = f(a, c)  # chained: each call consumes the previous result
        _sync(c)
        sec = (time.perf_counter() - t0) / steps
        tflops = 2 * n ** 3 / sec / 1e12
        if tflops > PEAK_TFLOPS_BF16 * 1.05:
            _log(f"gemm {n}: {tflops:.1f} TFLOP/s exceeds chip peak — "
                 "measurement invalid, discarding")
            results[str(n)] = None
            continue
        results[str(n)] = round(tflops, 1)
        best = max(best, tflops)
        _log(f"gemm {n}: {tflops:.1f} TFLOP/s")
    return {
        "per_size_tflops": results,
        "peak_achieved_tflops": round(best, 1),
        "mfu_pct": round(100 * best / PEAK_TFLOPS_BF16, 1),
    }


def _fit_throughput(net, ds, batch, steps):
    """Faster of per-step fit and fused fit_steps (winner named).
    Syncs by reading back a parameter leaf (fit returns the network)."""
    sync = lambda: net.params
    stepwise = 1 / _time_loop(lambda: net.fit(ds), steps, sync=sync) * batch
    try:
        fused_fn = lambda: net.fit_steps(ds, 10)
        fused = (1 / (_time_loop(fused_fn, max(2, steps // 10),
                                 sync=sync) / 10) * batch)
    except Exception:
        fused = 0.0
    winner = "fit_steps" if fused > stepwise else "fit"
    return max(stepwise, fused), winner


def bench_mlp():
    from deeplearning4j_tpu.datasets.dataset import DataSet
    from deeplearning4j_tpu.models import mnist_mlp

    rng = np.random.default_rng(0)
    batch = 4096
    x = rng.random((batch, 784), np.float32)
    y = np.eye(10, dtype=np.float32)[rng.integers(0, 10, batch)]
    net = mnist_mlp(hidden=256, dtype_policy="bf16").init()
    sps, winner = _fit_throughput(net, DataSet(x, y), batch, steps=20)
    _log(f"mlp: {sps:,.0f} samples/sec ({winner})")
    return {"samples_per_sec": round(sps, 1), "batch": batch, "path": winner}


def bench_lenet():
    from deeplearning4j_tpu.datasets.dataset import DataSet
    from deeplearning4j_tpu.models import lenet5

    rng = np.random.default_rng(0)
    batch = 1024
    x = rng.random((batch, 28, 28, 1), np.float32)
    y = np.eye(10, dtype=np.float32)[rng.integers(0, 10, batch)]
    net = lenet5(dtype_policy="bf16").init()
    sps, winner = _fit_throughput(net, DataSet(x, y), batch, steps=20)
    _log(f"lenet5: {sps:,.0f} samples/sec ({winner})")
    return {"samples_per_sec": round(sps, 1), "batch": batch, "path": winner}


def bench_char_lstm():
    from deeplearning4j_tpu.datasets.dataset import DataSet
    from deeplearning4j_tpu.models import char_lstm

    rng = np.random.default_rng(0)
    batch, t, vocab = 128, 200, 128
    idx = rng.integers(0, vocab, (batch, t))
    x = np.eye(vocab, dtype=np.float32)[idx]
    y = np.eye(vocab, dtype=np.float32)[np.roll(idx, -1, axis=1)]
    net = char_lstm(vocab_size=vocab, hidden=256, layers=2,
                    tbptt_length=50).init()
    ds = DataSet(x, y)
    sec = _time_loop(lambda: net.fit(ds), steps=5, sync=lambda: net.params)
    sps = batch / sec
    _log(f"char_lstm: {sps:,.0f} samples/sec ({sps * t:,.0f} tokens/sec)")
    return {"samples_per_sec": round(sps, 1),
            "tokens_per_sec": round(sps * t, 1),
            "batch": batch, "seq_len": t, "tbptt": 50}


def bench_word2vec():
    from deeplearning4j_tpu.nlp.sentence_iterator import (
        CollectionSentenceIterator)
    from deeplearning4j_tpu.nlp.word2vec import Word2Vec

    rng = np.random.default_rng(0)
    vocab = 5000
    n_sentences, sent_len = 2000, 40
    zipf = rng.zipf(1.3, size=(n_sentences, sent_len)) % vocab
    sentences = [" ".join(f"w{t}" for t in row) for row in zipf]
    w2v = Word2Vec(CollectionSentenceIterator(sentences),
                   layer_size=128, window_size=5, min_word_frequency=1,
                   negative=5, iterations=1, epochs=1, seed=42)
    t0 = time.perf_counter()
    w2v.fit()
    sec = time.perf_counter() - t0
    words = n_sentences * sent_len
    wps = words / sec
    _log(f"word2vec: {wps:,.0f} words/sec")
    return {"words_per_sec": round(wps, 1), "corpus_words": words,
            "vocab": vocab, "note": "includes vocab build + pair emission"}


def bench_resnet18():
    import jax

    from deeplearning4j_tpu.datasets.dataset import DataSet
    from deeplearning4j_tpu.models import resnet18

    rng = np.random.default_rng(0)
    batch = 256
    x = rng.random((batch, 32, 32, 3), np.float32)
    y = np.eye(10, dtype=np.float32)[rng.integers(0, 10, batch)]
    net = resnet18(num_classes=10, dtype_policy="bf16").init()
    ds = DataSet(x, y)
    sec = _time_loop(lambda: net.fit(ds), steps=10, sync=lambda: net.params)
    sps = batch / sec
    # analytic model FLOPs: CIFAR ResNet-18 fwd ≈ 1.11 GFLOP/sample
    # (sum over conv/dense macs × 2), train ≈ 3× fwd
    fwd_flops = 1.11e9
    tflops = 3 * fwd_flops * sps / 1e12
    _log(f"resnet18: {sps:,.0f} samples/sec, {tflops:.1f} TFLOP/s "
         f"({100 * tflops / PEAK_TFLOPS_BF16:.1f}% MFU)")
    return {"samples_per_sec": round(sps, 1), "batch": batch,
            "model_tflops": round(tflops, 1),
            "mfu_pct": round(100 * tflops / PEAK_TFLOPS_BF16, 1),
            "flops_source": "analytic 1.11 GFLOP fwd/sample x3"}


def _transformer_cfg():
    from deeplearning4j_tpu.models.transformer import TransformerLM

    return TransformerLM(vocab_size=8192, d_model=512, num_heads=8,
                         num_layers=8, max_len=1024, seed=0,
                         dtype_policy="bf16")


def bench_transformer(cpu_baseline=True):
    import jax
    import jax.numpy as jnp

    lm = _transformer_cfg().init()
    batch, t = 16, 1024
    tokens = jnp.asarray(
        np.random.default_rng(0).integers(0, 8192, (batch, t)), jnp.int32)
    step = lm.make_train_step()
    sec = _time_loop(lambda: lm.fit_batch(tokens, train_step=step),
                     steps=20, sync=lambda: lm.params)
    tps = batch * t / sec

    # model FLOPs per token: 6 FLOP per matmul param (fwd+bwd), counting
    # the tied-embedding unembed projection (d·V) like standard 6N
    # accounting, + attention's 12·L·d·t/2 causal score+pv term
    n_params_matmul = sum(
        int(np.prod(p.shape)) for blk in lm.params["blocks"]
        for grp in blk.values() for p in grp.values())
    n_params_matmul += lm.d_model * lm.vocab_size  # tied unembedding
    flops_per_token = (6 * n_params_matmul
                       + 12 * lm.num_layers * lm.d_model * t // 2)
    tflops = flops_per_token * tps / 1e12
    mfu = 100 * tflops / PEAK_TFLOPS_BF16
    _log(f"transformer: {tps:,.0f} tokens/sec, {tflops:.1f} TFLOP/s "
         f"({mfu:.1f}% MFU)")

    vs_baseline = float("nan")
    if cpu_baseline:
        try:
            cpu = jax.devices("cpu")[0]
            with jax.default_device(cpu):
                lm_cpu = _transformer_cfg().init()
                step_cpu = lm_cpu.make_train_step()
                tokens_cpu = jax.device_put(tokens, cpu)
                sec_cpu = _time_loop(
                    lambda: lm_cpu.fit_batch(tokens_cpu,
                                             train_step=step_cpu),
                    steps=2, sync=lambda: lm_cpu.params)
            cpu_tps = batch * t / sec_cpu
            vs_baseline = tps / cpu_tps
            _log(f"transformer CPU baseline: {cpu_tps:,.0f} tokens/sec "
                 f"→ vs_baseline {vs_baseline:.1f}x")
        except Exception as e:  # pragma: no cover
            _log(f"CPU baseline failed: {e}")

    return {
        "tokens_per_sec": round(tps, 1), "batch": batch, "seq_len": t,
        "model_tflops": round(tflops, 1), "mfu_pct": round(mfu, 1),
        "flops_source": "analytic 6*N/token + attention term",
        "config": "d512 L8 H8 v8192 bf16",
    }, vs_baseline


def main() -> None:
    extras = {"peak_tflops_bf16_per_chip": PEAK_TFLOPS_BF16,
              "chip": "TPU v5e (1 chip)"}
    for name, fn in [("gemm", bench_gemm), ("mnist_mlp", bench_mlp),
                     ("lenet5", bench_lenet),
                     ("char_lstm", bench_char_lstm),
                     ("word2vec", bench_word2vec),
                     ("resnet18_cifar10", bench_resnet18)]:
        try:
            extras[name] = fn()
        except Exception as e:  # keep the bench robust to one bad config
            extras[name] = {"error": str(e)[:200]}
            _log(f"{name} FAILED: {e}")

    try:
        tf, vs_baseline = bench_transformer()
        extras["transformer_lm"] = tf
        headline_value = tf["tokens_per_sec"]
    except Exception as e:
        extras["transformer_lm"] = {"error": str(e)[:200]}
        _log(f"transformer FAILED: {e}")
        headline_value = None
        vs_baseline = float("nan")

    print(json.dumps({
        "metric": "transformer_lm_1024ctx_train_tokens_per_sec_per_chip",
        "value": headline_value,
        "unit": "tokens/sec",
        "vs_baseline": round(vs_baseline, 2) if vs_baseline == vs_baseline
        else None,
        "extras": extras,
    }))


if __name__ == "__main__":
    main()
