"""Benchmark: the full BASELINE.md protocol on one TPU chip.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": R, "extras": {...}}

Headline = transformer-LM training throughput (tokens/sec/chip) — the
model-FLOP-dominated config — with ``vs_baseline`` = TPU ÷ XLA-CPU on the
same jitted step (the reference publishes no numbers — BASELINE.md — so the
baseline is the self-measured north star ">2x nd4j-native CPU throughput";
XLA-CPU is a strictly faster stand-in for 2015 ND4J op-by-op BLAS dispatch).

Measurement protocol (BENCH_NOTES.md): steady-state per-step timing after a
warm-up call, hard on-device sync before/after the timed window, batches
device-resident (transferred once — the tunnel link here moves ~37 MB/s, so
re-feeding a 3 MB batch per step would measure the link, not the chip).
Where a fused multi-step program exists, BOTH the per-dispatch and fused
numbers are reported and the fused one is the headline for that config; the
gap quantifies the host-dispatch floor (~4 ms/dispatch on this tunnel).

``extras`` carries every BASELINE.md config:
  - MNIST MLP, LeNet-5, GravesLSTM char-RNN (fused TBPTT), word2vec
    skip-gram, ResNet-18 CIFAR (bf16) — samples(/words)/sec/chip
  - transformer LM (bf16) — tokens/sec + achieved model TFLOP/s + MFU,
    per-dispatch vs fused, batch sweep, and a t=4096 config where the
    Pallas flash-attention kernel engages
  - GEMM sweep 512–8192 (bf16) — dispatch-chained AND fori-loop-fused
    TFLOP/s per size (fused isolates the chip from the dispatch floor)
  - infeed: async device-prefetch overlap vs synchronous feeding
  - epoch: HBM-cached whole-epoch fusion (fit_epochs) vs streaming
    per-step fit — samples/sec + measured dispatches-per-epoch
  - dp_epoch: the SAME fused pipeline sharded over the data mesh
    (ParallelWrapper.fit_epochs) — weak-scaling samples/sec/chip +
    dispatches-per-epoch (must stay 1 at any device count); skipped
    when only one device is visible
  - mesh_sweep: DP×TP grid under the sharding registry — step time,
    dispatches/chunk (must stay 1 over BOTH axes) and the per-chip
    HBM model per mesh shape; skipped below 4 devices
  - guard: numeric-sentinel overhead (on vs off, <3% target) + async
    checkpoint blocking time
  - telemetry: in-program metrics-pack overhead (on vs off, <3%
    target) + exporter round-trip; every artifact this bench writes —
    including partials and error lines — embeds a metrics+span summary
    block ("telemetry" key) with the grant-acquisition timeline AND
    the run-ledger goodput/badput report
  - flight: run-ledger + flight-recorder overhead (recorder on vs off,
    <3% target) + the postmortem round trip (completed run's segments
    classify "clean"); grant acquisition drops open "grant.wait"
    markers into the recorder so a wedged grant is classifiable from
    the surviving segments alone (scripts/flight_report.py)
  - serve: the continuous-batching decode server under an open-loop
    Poisson stream — p50/p99 latency, TTFT/TPOT, tokens/sec, slot
    occupancy, and compile-count flatness after warmup (plus the
    persisted XLA compilation cache's on-disk stats)
  - serve_fleet: M in-process DecodeServer replicas behind the fleet
    router, the SAME Poisson stream replayed at each fleet size on
    per-replica virtual clocks (real measured dispatch costs booked on
    chip-per-replica timelines) — aggregate tokens/sec scaling 1->2->4,
    p50/p99/TTFT vs the single-replica baseline, routing balance, and
    a failover measurement (one replica killed mid-stream: requeued
    requests must all complete, recovery time reported)

MFU = achieved / peak, peak stated per chip (v5e: 197 TFLOP/s bf16).
Model FLOPs come from the COMPILED program's ``cost_analysis()`` when the
backend provides one (monitor/profile.py), with the analytic formulas
kept as a cross-check: each entry's "flops_source" block carries both
counts and a ``flops_divergence_pct`` field, flagged above 10%. Each
profiled entry also gets a "cost_model" step-time decomposition (optimal
compute vs memory time from the roofline floors vs the measured step —
compute-/memory-bound classification + dispatch wait), and every
artifact — partials and error lines included — embeds the ProgramProfile
blocks collected so far under extras["profile"] plus chunk-boundary HBM
watermarks validating the epoch-cache budget model (the epoch section's
"hbm_budget_check"). Training data is synthetic (zero-egress sandbox;
throughput does not depend on pixel/token values) via the same public
``fit`` APIs a user calls.
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np

# stdlib-only telemetry layer (monitor/ imports no jax): safe to import
# before the backend probe — the spans it records around grant
# acquisition are exactly the wedge-timeline evidence BENCH_r04/r05
# lacked
from deeplearning4j_tpu.monitor import (
    telemetry_summary as _telemetry_summary,
    tracer as _tracer,
)

PEAK_TFLOPS_BF16 = 197.0  # TPU v5e per-chip peak, bf16 MXU
PEAK_HBM_GBPS = 819.0  # TPU v5e per-chip HBM bandwidth (roofline floor)


def _log(msg):
    print(msg, file=sys.stderr, flush=True)


def _profile_step(fn, args, name):
    """Cost/memory profile of one jitted program (``fn.lower(*args)``
    reads avals only — donated buffers are NOT consumed). The
    cost-analysis FLOPs are the measured-FLOPs source for MFU; the
    analytic formulas stay as the cross-check, with divergence >10%
    flagged in the artifact. Costs one extra XLA compile per profiled
    program; returns None (and logs) when the backend cannot analyze.
    An explicit DL4J_PROFILE=0 opt-out (main() only sets the default)
    skips the capture entirely — no extra compiles, no profile block
    entries."""
    from deeplearning4j_tpu.monitor.profile import (
        capture_program_profile, profile_enabled)

    if not profile_enabled():
        return None
    try:
        prof, _ = capture_program_profile(fn, args, name=name,
                                          key=("bench", name))
    except Exception as e:
        _log(f"profile capture for {name} failed: {e!r}")
        return None
    return prof


def _flops_entry(analytic_flops, analytic_note, prof, per: int):
    """The artifact's dual flops_source block: the analytic formula and
    the compiled cost-analysis count, per sample (or token), plus their
    divergence. ``per`` normalizes the whole-program cost-analysis count
    (one step over ``per`` samples/tokens)."""
    from deeplearning4j_tpu.monitor.profile import flops_divergence_pct

    cost = (None if prof is None or prof.flops is None
            else prof.flops / per)
    div = flops_divergence_pct(analytic_flops, cost)
    return {
        "analytic": analytic_note,
        "analytic_flops": round(float(analytic_flops), 1),
        "cost_analysis_flops": None if cost is None else round(cost, 1),
        "flops_divergence_pct": div,
        "flops_divergence_flag": (div is not None and abs(div) > 10.0),
    }


def _cost_model_entry(prof, measured_s):
    """Step-time decomposition against the compiled cost model: optimal
    device time from the roofline floors vs the measured step —
    classifies the section compute- vs memory-bound and prices the
    dispatch wait."""
    from deeplearning4j_tpu.monitor.profile import classify_boundedness

    if prof is None:
        return None
    entry = classify_boundedness(
        prof.flops, prof.bytes_accessed, measured_s,
        PEAK_TFLOPS_BF16 * 1e12, PEAK_HBM_GBPS * 1e9)
    entry["peak_hbm_bytes"] = prof.peak_bytes
    entry["compile_s"] = prof.compile_s
    return entry


def _sync(x):
    """Hard sync: reduce one device leaf to a scalar ON DEVICE and read
    that back. block_until_ready alone is not trustworthy on every backend
    (the tunnel backend acks before the compute drains), and pulling a
    full array through the tunnel is orders of magnitude slower than the
    compute being timed — a 4-byte readback forces completion of all
    prior work (the chip executes its queue in order) without polluting
    the measurement."""
    import jax
    import jax.numpy as jnp

    for leaf in jax.tree_util.tree_leaves(x):
        if hasattr(leaf, "addressable_shards") or hasattr(leaf, "devices"):
            float(jnp.sum(jnp.ravel(leaf)[:1]).astype(jnp.float32))
            return
    # no device leaf found (e.g. a network object): sync nothing loudly
    raise TypeError(f"_sync: no device array found in {type(x)}")


def _time_loop(fn, steps, sync=None):
    """Seconds per call. ``sync`` extracts the device data to read back
    (defaults to the call's own return value)."""
    out = fn()  # warm
    _sync(sync() if sync else out)
    t0 = time.perf_counter()
    for _ in range(steps):
        out = fn()
    _sync(sync() if sync else out)
    return (time.perf_counter() - t0) / steps


def _dev(*arrays):
    """Place arrays on device once, synced (steady-state protocol)."""
    import jax

    out = [jax.device_put(a) for a in arrays]
    for o in out:
        _sync(o)
    return out


# ----------------------------------------------------------------------
def bench_gemm():
    import jax
    import jax.numpy as jnp
    from jax import lax

    rng = np.random.default_rng(0)
    sizes = [512, 1024, 2048, 4096, 8192]
    chained, fused = {}, {}
    best = 0.0
    for n in sizes:
        a = jnp.asarray(rng.normal(size=(n, n)), jnp.bfloat16)
        c0 = jnp.asarray(rng.normal(size=(n, n)), jnp.bfloat16)
        f = jax.jit(lambda a, b: a @ b)
        steps = 30 if n <= 2048 else 10
        c = f(a, c0)
        _sync(c)
        t0 = time.perf_counter()
        for _ in range(steps):
            c = f(a, c)  # chained: each call consumes the previous result
        _sync(c)
        sec = (time.perf_counter() - t0) / steps
        tflops_chained = 2 * n ** 3 / sec / 1e12

        # fused: K matmuls inside ONE program — no per-call dispatch.
        # The fori_loop carry keeps each iteration dependent on the last
        # (XLA cannot elide or overlap the chain), exactly like the
        # dispatch-chained loop above minus the host round-trips.
        k = 100 if n <= 2048 else 30

        @jax.jit
        def chain(a, c):
            return lax.fori_loop(0, k, lambda i, cc: a @ cc, c)

        c = chain(a, c0)
        _sync(c)
        t0 = time.perf_counter()
        c = chain(a, c0)
        _sync(c)
        sec = (time.perf_counter() - t0) / k
        tflops_fused = 2 * n ** 3 / sec / 1e12

        for name, val, store in (("chained", tflops_chained, chained),
                                 ("fused", tflops_fused, fused)):
            if val > PEAK_TFLOPS_BF16 * 1.05:
                _log(f"gemm {n} {name}: {val:.1f} TFLOP/s exceeds chip "
                     "peak — measurement invalid, discarding")
                store[str(n)] = None
            else:
                store[str(n)] = round(val, 1)
        # headline peak considers BOTH columns: a discarded fused number
        # must not zero the headline while chained data is valid
        for val in (fused[str(n)], chained[str(n)]):
            if val:
                best = max(best, val)
        _log(f"gemm {n}: {tflops_chained:.1f} TFLOP/s chained, "
             f"{tflops_fused:.1f} fused")
    return {
        "per_size_tflops_chained": chained,
        "per_size_tflops_fused": fused,
        "peak_achieved_tflops": round(best, 1),
        "mfu_pct": round(100 * best / PEAK_TFLOPS_BF16, 1),
        "note": "fused = lax.fori_loop chain in one program; "
                "chained-vs-fused gap is the per-dispatch floor",
    }


def _fit_throughput(net, ds, batch, steps):
    """Per-step fit AND fused fit_steps samples/sec (both reported).
    Syncs by reading back a parameter leaf (fit returns the network)."""
    sync = lambda: net.params
    stepwise = 1 / _time_loop(lambda: net.fit(ds), steps, sync=sync) * batch
    try:
        fused_fn = lambda: net.fit_steps(ds, 10)
        fused = (1 / (_time_loop(fused_fn, max(2, steps // 10),
                                 sync=sync) / 10) * batch)
    except Exception as e:
        _log(f"fit_steps path FAILED (falling back to fit): {e!r}")
        fused = 0.0
    return stepwise, fused


def bench_mlp():
    from deeplearning4j_tpu.datasets.dataset import DataSet
    from deeplearning4j_tpu.models import mnist_mlp

    rng = np.random.default_rng(0)
    batch = 4096
    x = rng.random((batch, 784), np.float32)
    y = np.eye(10, dtype=np.float32)[rng.integers(0, 10, batch)]
    x, y = _dev(x, y)
    net = mnist_mlp(hidden=256, dtype_policy="bf16").init()
    stepwise, fused = _fit_throughput(net, DataSet(x, y), batch, steps=20)
    _log(f"mlp: {fused:,.0f} samples/sec fused ({stepwise:,.0f} per-step)")
    return {"samples_per_sec": round(max(stepwise, fused), 1),
            "per_step": round(stepwise, 1), "fused": round(fused, 1),
            "batch": batch}


def bench_lenet():
    from deeplearning4j_tpu.datasets.dataset import DataSet
    from deeplearning4j_tpu.models import lenet5

    rng = np.random.default_rng(0)
    batch = 1024
    x = rng.random((batch, 28, 28, 1), np.float32)
    y = np.eye(10, dtype=np.float32)[rng.integers(0, 10, batch)]
    x, y = _dev(x, y)
    net = lenet5(dtype_policy="bf16").init()
    stepwise, fused = _fit_throughput(net, DataSet(x, y), batch, steps=20)
    _log(f"lenet5: {fused:,.0f} samples/sec fused ({stepwise:,.0f} per-step)")
    return {"samples_per_sec": round(max(stepwise, fused), 1),
            "per_step": round(stepwise, 1), "fused": round(fused, 1),
            "batch": batch}


def bench_char_lstm():
    from deeplearning4j_tpu.datasets.dataset import DataSet
    from deeplearning4j_tpu.models import char_lstm

    rng = np.random.default_rng(0)
    batch, t, vocab = 128, 200, 128
    idx = rng.integers(0, vocab, (batch, t))
    x = np.eye(vocab, dtype=np.float32)[idx]
    y = np.eye(vocab, dtype=np.float32)[np.roll(idx, -1, axis=1)]
    x, y = _dev(x, y)
    net = char_lstm(vocab_size=vocab, hidden=256, layers=2,
                    tbptt_length=50, dtype_policy="bf16").init()
    ds = DataSet(x, y)
    # fit() itself now fuses all TBPTT windows into one scanned program
    sec = _time_loop(lambda: net.fit(ds), steps=5, sync=lambda: net.params)
    sps = batch / sec
    _log(f"char_lstm: {sps:,.0f} samples/sec ({sps * t:,.0f} tokens/sec, "
         "fused TBPTT scan)")
    return {"samples_per_sec": round(sps, 1),
            "tokens_per_sec": round(sps * t, 1),
            "batch": batch, "seq_len": t, "tbptt": 50,
            "path": "fused-tbptt-scan"}


def bench_word2vec():
    """Host pair-loop vs fused whole-epoch skip-gram (ISSUE 18): words/
    sec both ways, the 1-dispatch-per-chunk counter assert, and the
    row-sharded table's per-chip bytes on a 2-device model mesh."""
    import jax

    from deeplearning4j_tpu.nlp.sentence_iterator import (
        CollectionSentenceIterator)
    from deeplearning4j_tpu.nlp.word2vec import Word2Vec

    rng = np.random.default_rng(0)
    vocab = 5000
    n_sentences, sent_len = 2000, 40
    zipf = rng.zipf(1.3, size=(n_sentences, sent_len)) % vocab
    sentences = [" ".join(f"w{t}" for t in row) for row in zipf]
    words = n_sentences * sent_len

    def make(seed):
        return Word2Vec(CollectionSentenceIterator(sentences),
                        layer_size=128, window_size=5,
                        min_word_frequency=1, negative=5, iterations=1,
                        epochs=1, seed=seed)

    # --- host pair-loop baseline (cold, then warm jit) ---
    w2v = make(42)
    t0 = time.perf_counter()
    w2v.fit()
    host_cold = words / (time.perf_counter() - t0)
    w2v2 = make(43)
    t0 = time.perf_counter()
    w2v2.fit()
    host_wps = words / (time.perf_counter() - t0)

    # --- fused whole-epoch path: E epochs x N batches, ONE dispatch ---
    fused = make(44)
    fused.build_vocab()
    fused.reset_weights()
    cache = fused.build_corpus_cache()
    fused.fit_epochs(1)            # warm-up: compile + first chunk
    epochs = 3
    base = fused._train_dispatches
    t0 = time.perf_counter()
    hist = fused.fit_epochs(epochs)
    jax.block_until_ready(hist)
    sec = time.perf_counter() - t0
    fused_wps = epochs * cache.n_words / sec
    dispatches_per_epoch = (fused._train_dispatches - base) / epochs
    assert dispatches_per_epoch <= 1, (
        f"fused skip-gram dispatched {dispatches_per_epoch}/epoch — the "
        "whole-chunk contract is broken")

    # --- row-sharded tables: per-chip bytes on a data=1 x model=2 mesh
    table_bytes = int(np.asarray(fused.syn0).nbytes
                      + np.asarray(fused.syn1neg).nbytes)
    sharded_per_chip = None
    if len(jax.devices()) >= 2 and vocab % 2 == 0:
        from deeplearning4j_tpu.parallel.mesh import MeshSpec, build_mesh
        from deeplearning4j_tpu.parallel.sharding_registry import (
            ShardingRegistry)

        mesh2 = build_mesh(MeshSpec(data=1, model=2),
                           devices=jax.devices()[:2])
        reg = ShardingRegistry.for_embedding_tables(
            {"syn0": fused.syn0, "syn1neg": fused.syn1neg}, mesh2,
            row_shard=True)
        placed = reg.place({"syn0": fused.syn0,
                            "syn1neg": fused.syn1neg})
        sharded_per_chip = int(sum(
            s.data.nbytes for t in placed.values()
            for s in t.addressable_shards) // 2)

    _log(f"word2vec: host {host_wps:,.0f} words/sec, fused "
         f"{fused_wps:,.0f} ({fused_wps / max(host_wps, 1e-9):,.1f}x), "
         f"{dispatches_per_epoch:.2f} dispatches/epoch")
    return {"words_per_sec": round(fused_wps, 1),  # fused = the headline
            "host_words_per_sec": round(host_wps, 1),
            "host_cold_words_per_sec": round(host_cold, 1),
            "fused_words_per_sec": round(fused_wps, 1),
            "speedup_vs_host": round(fused_wps / max(host_wps, 1e-9), 2),
            "dispatches_per_epoch": dispatches_per_epoch,
            "table_bytes": table_bytes,
            "sharded_table_bytes_per_chip": sharded_per_chip,
            "corpus_words": words, "vocab": vocab,
            "cache": cache.describe(),
            "note": "host = pair-emitting Python loop (one dispatch per "
                    "batch, warm jit); fused = whole-epoch lax.scan "
                    "program (1 dispatch/chunk, in-program pair gen)"}


def bench_resnet18():
    import jax.numpy as jnp

    from deeplearning4j_tpu.datasets.dataset import DataSet
    from deeplearning4j_tpu.models import resnet18

    rng = np.random.default_rng(0)
    batch = 256
    x = rng.random((batch, 32, 32, 3), np.float32)
    y = np.eye(10, dtype=np.float32)[rng.integers(0, 10, batch)]
    x, y = _dev(x, y)
    net = resnet18(num_classes=10, dtype_policy="bf16").init()
    ds = DataSet(x, y)
    fwd_flops = 1.11e9  # analytic CIFAR ResNet-18 fwd GFLOP/sample
    # the x3 assumes backward ≈ 2x forward (dL/dW + dL/dx) and ignores
    # the updater math — stated here because the compiled cost analysis
    # below counts the REAL program and the divergence field quantifies
    # exactly how much that assumption is off
    analytic_note = ("analytic 1.11 GFLOP fwd/sample x3 "
                     "(assumes bwd = 2x fwd; updater math excluded)")
    prof = _profile_step(
        net._train_step,
        (net.params, net.updater_state, net.net_state,
         jnp.asarray(0, jnp.int32), jnp.asarray(1.0, jnp.float32),
         x, y, None, None, net._rng, None),
        "resnet18_train_step")
    stepwise, fused = _fit_throughput(net, ds, batch, steps=10)
    sps = max(stepwise, fused)
    flops = _flops_entry(3 * fwd_flops, analytic_note, prof, batch)
    per_sample = (flops["analytic_flops"]
                  if flops["cost_analysis_flops"] is None
                  else flops["cost_analysis_flops"])
    tflops = per_sample * sps / 1e12
    tflops_analytic = 3 * fwd_flops * sps / 1e12
    _log(f"resnet18: {sps:,.0f} samples/sec ({stepwise:,.0f} per-step, "
         f"{fused:,.0f} fused), {tflops:.1f} TFLOP/s "
         f"({100 * tflops / PEAK_TFLOPS_BF16:.1f}% MFU, "
         f"flops divergence {flops['flops_divergence_pct']}%)")
    return {"samples_per_sec": round(sps, 1),
            "per_step": round(stepwise, 1), "fused": round(fused, 1),
            "batch": batch,
            "model_tflops": round(tflops, 1),
            "mfu_pct": round(100 * tflops / PEAK_TFLOPS_BF16, 1),
            "model_tflops_analytic": round(tflops_analytic, 1),
            "mfu_pct_analytic": round(
                100 * tflops_analytic / PEAK_TFLOPS_BF16, 1),
            "flops_source": flops,
            # the profile is of the SINGLE-step program, so the
            # decomposition pairs it with the per-step measured time —
            # not the fused path's (a different program with different
            # dispatch amortization and HBM traffic)
            "cost_model": _cost_model_entry(
                prof, None if stepwise <= 0 else batch / stepwise)}


def bench_infeed():
    """Async device-prefetch overlap vs synchronous feeding on a stream of
    DISTINCT batches (infeed-bound config: the per-batch host→device
    transfer is comparable to the step time)."""
    from deeplearning4j_tpu.datasets.dataset import DataSet
    from deeplearning4j_tpu.datasets.iterator import (
        AsyncDataSetIterator, ListDataSetIterator)
    from deeplearning4j_tpu.models import mnist_mlp

    rng = np.random.default_rng(0)
    batch, n_batches = 4096, 16
    batches = [DataSet(rng.random((batch, 784), np.float32),
                       np.eye(10, dtype=np.float32)[
                           rng.integers(0, 10, batch)])
               for _ in range(n_batches)]
    net = mnist_mlp(hidden=256, dtype_policy="bf16").init()
    net.fit(batches[0])  # compile
    _sync(net.params)

    def run(make_it):
        it = make_it()
        t0 = time.perf_counter()
        net.fit(it)
        _sync(net.params)
        return batch * n_batches / (time.perf_counter() - t0)

    sync_sps = run(lambda: ListDataSetIterator(batches, batch))
    async_sps = run(lambda: AsyncDataSetIterator(
        ListDataSetIterator(batches, batch), queue_size=4,
        device_prefetch=True))
    _log(f"infeed: {sync_sps:,.0f} samples/sec sync, "
         f"{async_sps:,.0f} async-prefetch "
         f"({async_sps / sync_sps:.2f}x)")
    return {"sync_samples_per_sec": round(sync_sps, 1),
            "async_prefetch_samples_per_sec": round(async_sps, 1),
            "overlap_speedup": round(async_sps / sync_sps, 2),
            "batch": batch, "n_batches": n_batches}


def bench_epoch():
    """Epoch pipeline: HBM-cached whole-epoch fusion (fit_epochs) vs the
    streaming per-step path on the same multi-batch dataset. Reports
    samples/sec both ways plus MEASURED train-program dispatches per epoch
    — the fused path must show exactly 1 (chunk = 1 epoch) vs N for
    streaming, and the fully-fused variant (all epochs in one program)
    amortizes even that."""
    from deeplearning4j_tpu.datasets.dataset import DataSet
    from deeplearning4j_tpu.datasets.iterator import ListDataSetIterator
    from deeplearning4j_tpu.models import mnist_mlp
    from deeplearning4j_tpu.perf.epoch_cache import DeviceDataSetCache

    rng = np.random.default_rng(0)
    batch, n_batches, epochs = 2048, 16, 5
    ds = DataSet(rng.random((batch * n_batches, 784), np.float32),
                 np.eye(10, dtype=np.float32)[
                     rng.integers(0, 10, batch * n_batches)])
    total = batch * n_batches

    budget_check = {}

    def run_cached(chunk):
        net = mnist_mlp(hidden=256, dtype_policy="bf16").init()
        cache = DeviceDataSetCache.build(ListDataSetIterator(ds, batch))
        assert cache is not None, "bench dataset exceeded DL4J_DEVICE_CACHE_MB"
        if not budget_check:
            # runtime check of the per-shard HBM budget model: the
            # analytic resident bytes the build priced vs what the
            # device actually holds for these stacks
            from deeplearning4j_tpu.monitor.memory import (
                validate_cache_budget)

            budget_check.update(validate_cache_budget(cache))
        # warm the SAME chunk length as the timed run: the fused program
        # is keyed on the epoch_keys shape [k, 2], so a chunk=1 warm-up
        # would leave the k=epochs program to compile inside the timing
        net.fit_epochs(cache, chunk, chunk_epochs=chunk)
        _sync(net.params)
        d0 = net._train_dispatches
        t0 = time.perf_counter()
        net.fit_epochs(cache, epochs, chunk_epochs=chunk)
        _sync(net.params)
        sec = time.perf_counter() - t0
        return (total * epochs / sec,
                (net._train_dispatches - d0) / epochs)

    def run_streaming():
        net = mnist_mlp(hidden=256, dtype_policy="bf16").init()
        it = ListDataSetIterator(ds, batch)
        net.fit(it)  # compile
        _sync(net.params)
        d0 = net._train_dispatches
        t0 = time.perf_counter()
        net.fit(it, num_epochs=epochs)
        _sync(net.params)
        sec = time.perf_counter() - t0
        return (total * epochs / sec,
                (net._train_dispatches - d0) / epochs)

    stream_sps, stream_dpe = run_streaming()
    cached_sps, cached_dpe = run_cached(chunk=1)
    fused_sps, fused_dpe = run_cached(chunk=epochs)
    _log(f"epoch: {cached_sps:,.0f} samples/sec cached-fused "
         f"({cached_dpe:.0f} dispatches/epoch), {fused_sps:,.0f} "
         f"fully-fused ({fused_dpe:.2f}), {stream_sps:,.0f} streaming "
         f"({stream_dpe:.0f}) — {cached_sps / stream_sps:.2f}x")
    return {"cached_samples_per_sec": round(cached_sps, 1),
            "fully_fused_samples_per_sec": round(fused_sps, 1),
            "streaming_samples_per_sec": round(stream_sps, 1),
            "speedup": round(cached_sps / stream_sps, 2),
            "dispatches_per_epoch_cached": round(cached_dpe, 2),
            "dispatches_per_epoch_fully_fused": round(fused_dpe, 2),
            "dispatches_per_epoch_streaming": round(stream_dpe, 2),
            "batch": batch, "n_batches": n_batches, "epochs": epochs,
            "total_samples": total,
            "hbm_budget_check": budget_check or None}


def bench_dp_epoch():
    """Sharded epoch pipeline: whole-epoch fusion over the data mesh
    (ParallelWrapper.fit_epochs). Weak scaling — per-chip batch held
    constant as devices grow — reported as samples/sec/chip, plus the
    invariant that the cached sharded path still makes exactly ONE
    train-program dispatch per epoch chunk at ANY device count (the
    composition PERF.md §Round-8 quantifies). Skips cleanly when only
    one device is visible (the single-chip 'epoch' section covers n=1)."""
    import jax

    n = len(jax.devices())
    if n < 2:
        return {"skipped": f"only {n} device visible; dp_epoch needs >= 2",
                "devices": n}
    from deeplearning4j_tpu.datasets.dataset import DataSet
    from deeplearning4j_tpu.datasets.iterator import ListDataSetIterator
    from deeplearning4j_tpu.models import mnist_mlp
    from deeplearning4j_tpu.parallel import ParallelWrapper, build_mesh

    rng = np.random.default_rng(0)
    per_chip, n_batches, epochs = 256, 8, 5
    batch = per_chip * n  # weak scaling: global batch grows with the mesh
    total = batch * n_batches
    ds = DataSet(rng.random((total, 784), np.float32),
                 np.eye(10, dtype=np.float32)[rng.integers(0, 10, total)])
    net = mnist_mlp(hidden=256, dtype_policy="bf16").init()
    wrapper = ParallelWrapper(net, mesh=build_mesh())
    cache = wrapper.build_epoch_cache(ListDataSetIterator(ds, batch))
    if cache is None:
        return {"error": "dataset exceeded the per-shard cache budget",
                "devices": n}
    wrapper.fit_epochs(cache, 1, chunk_epochs=1)  # warm the chunk program
    _sync(net.params)
    d0 = net._train_dispatches
    t0 = time.perf_counter()
    wrapper.fit_epochs(cache, epochs, chunk_epochs=1)
    _sync(net.params)
    sec = time.perf_counter() - t0
    sps = total * epochs / sec
    dpe = (net._train_dispatches - d0) / epochs
    _log(f"dp_epoch: {n} devices, {sps:,.0f} samples/sec "
         f"({sps / n:,.0f}/chip), {dpe:.2f} dispatches/epoch "
         f"(cache sharded {cache.n_shard} ways)")
    return {"devices": n, "global_batch": batch,
            "per_chip_batch": per_chip, "n_batches": n_batches,
            "epochs": epochs,
            "samples_per_sec": round(sps, 1),
            "samples_per_sec_per_chip": round(sps / n, 1),
            "dispatches_per_epoch": round(dpe, 2),
            "cache_n_shard": cache.n_shard,
            "cache_mb_total": round(cache.nbytes / 1024 ** 2, 2)}


def bench_mesh_sweep():
    """DP×TP grid under the sharding registry: the SAME fused epoch
    program launched over each mesh shape. Per shape: dispatches/chunk
    (must stay 1 — the registry composes the axes into ONE GSPMD
    program), steady-state step time, and the per-chip HBM model
    (params + updater state actually resident on the fullest device +
    the cache's per-shard slice). The most-TP shape's step time and
    per-chip HBM are the TRACKED series: TP must shrink per-chip weights
    without breaking whole-epoch fusion. Embeds registry.describe() for
    the record."""
    import jax

    n = len(jax.devices())
    if n < 4:
        return {"skipped": f"only {n} devices visible; mesh_sweep "
                           "needs >= 4", "devices": n}
    from deeplearning4j_tpu.datasets.dataset import DataSet
    from deeplearning4j_tpu.datasets.iterator import ListDataSetIterator
    from deeplearning4j_tpu.models import mnist_mlp
    from deeplearning4j_tpu.parallel import build_mesh
    from deeplearning4j_tpu.parallel.mesh import MeshSpec

    rng = np.random.default_rng(0)
    per_chip, n_batches, epochs = 128, 8, 4
    batch = per_chip * n
    total = batch * n_batches
    ds = DataSet(rng.random((total, 784), np.float32),
                 np.eye(10, dtype=np.float32)[rng.integers(0, 10, total)])

    def per_device_mb(trees):
        # bytes on the FULLEST device — replicated leaves count fully
        # on every device, sharded leaves only their local slice
        per = {}
        for tree in trees:
            for leaf in jax.tree_util.tree_leaves(tree):
                for s in getattr(leaf, "addressable_shards", ()):
                    per[s.device.id] = (per.get(s.device.id, 0)
                                        + s.data.nbytes)
        return max(per.values(), default=0) / 1024 ** 2

    shapes = [(n, 1), (n // 2, 2)]
    if n % 4 == 0:
        shapes.append((n // 4, 4))
    grid, describe = [], None
    for dp, tp in shapes:
        net = mnist_mlp(hidden=512).init()
        mesh = build_mesh(MeshSpec(data=dp, model=tp))
        cache = net.build_epoch_cache(
            ListDataSetIterator(ds, batch), mesh=mesh)
        if cache is None:
            grid.append({"mesh": f"{dp}x{tp}",
                         "error": "cache over budget"})
            continue
        t0 = time.perf_counter()
        net.fit_epochs(cache, 1, chunk_epochs=1)  # compile + warm
        _sync(net.params)
        compile_s = time.perf_counter() - t0
        d0 = net._train_dispatches
        t0 = time.perf_counter()
        net.fit_epochs(cache, epochs, chunk_epochs=1)
        _sync(net.params)
        sec = time.perf_counter() - t0
        dpc = (net._train_dispatches - d0) / epochs
        row = {"mesh": f"{dp}x{tp}", "dp": dp, "tp": tp,
               "dispatches_per_chunk": round(dpc, 2),
               "compile_s": round(compile_s, 3),
               "step_ms": round(sec / (epochs * n_batches) * 1e3, 3),
               "samples_per_sec": round(total * epochs / sec, 1),
               "per_chip_weights_mb": round(
                   per_device_mb([net.params, net.updater_state]), 3),
               "per_chip_hbm_mb": round(
                   per_device_mb([net.params, net.updater_state])
                   + cache.nbytes / max(1, cache.n_shard) / 1024 ** 2, 3)}
        grid.append(row)
        describe = net._sharding_registry.describe()
        _log(f"mesh_sweep {row['mesh']}: {row['step_ms']} ms/step, "
             f"{row['dispatches_per_chunk']} dispatches/chunk, "
             f"{row['per_chip_hbm_mb']} MB/chip")
    good = [r for r in grid if "error" not in r]
    if not good:
        return {"devices": n, "grid": grid,
                "error": "no mesh shape fit the cache budget"}
    tp_row = max(good, key=lambda r: r["tp"])
    return {"devices": n, "grid": grid,
            "tp_mesh": tp_row["mesh"],
            "tp_step_ms": tp_row["step_ms"],
            "tp_dispatches_per_chunk": tp_row["dispatches_per_chunk"],
            "tp_per_chip_hbm_mb": tp_row["per_chip_hbm_mb"],
            "registry": describe}


def bench_guard():
    """Self-healing overhead: (1) fused-epoch throughput with the numeric
    sentinel compiled in (DL4J_NAN_GUARD=skip, the default) vs compiled
    out (=off) — the per-step isfinite-on-loss+grads and the lax.cond
    must cost <3%; (2) save_async: how long the host is blocked taking a
    checkpoint (device->host snapshot only) vs the full zip+manifest
    write that hides behind the next chunk's dispatch."""
    import tempfile

    from deeplearning4j_tpu.datasets.dataset import DataSet
    from deeplearning4j_tpu.datasets.iterator import ListDataSetIterator
    from deeplearning4j_tpu.models import mnist_mlp
    from deeplearning4j_tpu.parallel.cluster import FaultTolerantTrainer
    from deeplearning4j_tpu.perf.epoch_cache import DeviceDataSetCache

    rng = np.random.default_rng(0)
    batch, n_batches, epochs = 2048, 16, 5
    ds = DataSet(rng.random((batch * n_batches, 784), np.float32),
                 np.eye(10, dtype=np.float32)[
                     rng.integers(0, 10, batch * n_batches)])
    total = batch * n_batches

    def prep(guard):
        net = mnist_mlp(hidden=256, dtype_policy="bf16").init()
        cache = DeviceDataSetCache.build(ListDataSetIterator(ds, batch))
        assert cache is not None, "bench dataset exceeded DL4J_DEVICE_CACHE_MB"
        # chunk_epochs=1 on purpose: the guarded path must be charged
        # for chunked dispatch too (skip defers its trip read, so its
        # chunks pipeline like the unguarded path's — this verifies it)
        net.fit_epochs(cache, epochs, chunk_epochs=1, guard=guard)
        _sync(net.params)  # warm: compile outside the timing
        return net, cache

    def timed(net, cache, guard):
        t0 = time.perf_counter()
        net.fit_epochs(cache, epochs, chunk_epochs=1, guard=guard)
        _sync(net.params)
        return total * epochs / (time.perf_counter() - t0)

    off_net, off_cache = prep("off")
    net, cache = prep("skip")
    # best-of-3, interleaved: host-side timing jitter dwarfs a few-%
    # sentinel delta on a loaded machine, and min-of-N is the standard
    # way to strip it
    off_sps = max(timed(off_net, off_cache, "off") for _ in range(3))
    on_sps = max(timed(net, cache, "skip") for _ in range(3))
    overhead_pct = (off_sps / on_sps - 1.0) * 100.0

    # save_async: blocking time (snapshot) vs hidden write time
    with tempfile.TemporaryDirectory() as d:
        trainer = FaultTolerantTrainer(net, d)
        t0 = time.perf_counter()
        fut = trainer.save_async()
        blocked_ms = (time.perf_counter() - t0) * 1e3
        t1 = time.perf_counter()
        net.fit_epochs(cache, 1, chunk_epochs=1, guard="skip")
        _sync(net.params)
        chunk_ms = (time.perf_counter() - t1) * 1e3
        fut.result()
        write_ms = (time.perf_counter() - t1) * 1e3
        # "hidden" = the next dispatch never waited on the writer: the
        # host was blocked only for the device->host snapshot, a sliver
        # of the background write it overlaps
        hidden = blocked_ms < 0.05 * write_ms

    _log(f"guard: sentinel {on_sps:,.0f} samples/sec vs {off_sps:,.0f} "
         f"unguarded ({overhead_pct:+.2f}% overhead, target <3%); "
         f"save_async blocked host {blocked_ms:.1f} ms, write "
         f"{write_ms:.1f} ms vs next-chunk {chunk_ms:.1f} ms "
         f"({'hidden' if hidden else 'NOT hidden'})")
    return {"guarded_samples_per_sec": round(on_sps, 1),
            "unguarded_samples_per_sec": round(off_sps, 1),
            "sentinel_overhead_pct": round(overhead_pct, 2),
            "overhead_within_target": bool(overhead_pct < 3.0),
            "save_async_blocked_ms": round(blocked_ms, 2),
            "save_async_write_ms": round(write_ms, 2),
            "next_chunk_ms": round(chunk_ms, 2),
            "save_hidden_behind_next_chunk": bool(hidden),
            "batch": batch, "n_batches": n_batches, "epochs": epochs}


def bench_telemetry():
    """Telemetry overhead: fused-epoch throughput with the in-program
    metrics pack compiled in (grad/update/param global-norms + lr scale
    per step, DL4J_TELEMETRY=on stride 1) vs compiled out — the pack's
    budget is <3% like the NaN sentinel's. The run keeps the default
    guard (skip) on BOTH sides so the delta isolates the pack. Also
    reports the exporter round-trip (JSONL metrics record + Prometheus
    textfile per snapshot) and the host cost of draining one chunk's
    [E, N, 4] history."""
    import os
    import tempfile

    from deeplearning4j_tpu.datasets.dataset import DataSet
    from deeplearning4j_tpu.datasets.iterator import ListDataSetIterator
    from deeplearning4j_tpu.models import mnist_mlp
    from deeplearning4j_tpu.monitor import metrics
    from deeplearning4j_tpu.monitor.exporters import (
        JsonlExporter, write_prometheus_textfile)
    from deeplearning4j_tpu.perf.epoch_cache import DeviceDataSetCache

    rng = np.random.default_rng(0)
    batch, n_batches, epochs = 2048, 16, 5
    ds = DataSet(rng.random((batch * n_batches, 784), np.float32),
                 np.eye(10, dtype=np.float32)[
                     rng.integers(0, 10, batch * n_batches)])
    total = batch * n_batches

    def prep(telemetry):
        net = mnist_mlp(hidden=256, dtype_policy="bf16").init()
        cache = DeviceDataSetCache.build(ListDataSetIterator(ds, batch))
        assert cache is not None, "bench dataset exceeded DL4J_DEVICE_CACHE_MB"
        net.fit_epochs(cache, epochs, chunk_epochs=1, telemetry=telemetry)
        _sync(net.params)  # warm: compile outside the timing
        return net, cache

    def timed(net, cache, telemetry):
        t0 = time.perf_counter()
        net.fit_epochs(cache, epochs, chunk_epochs=1, telemetry=telemetry)
        _sync(net.params)
        return total * epochs / (time.perf_counter() - t0)

    off_net, off_cache = prep(False)
    on_net, on_cache = prep(1)
    # best-of-3, interleaved: host timing jitter dwarfs a few-% delta
    off_sps = max(timed(off_net, off_cache, False) for _ in range(3))
    on_sps = max(timed(on_net, on_cache, 1) for _ in range(3))
    overhead_pct = (off_sps / on_sps - 1.0) * 100.0

    # the [E, N, 4] history drain: the one host readback a per-chunk
    # metrics consumer pays
    t0 = time.perf_counter()
    hist = np.asarray(on_net._last_metrics)
    drain_ms = (time.perf_counter() - t0) * 1e3
    finite_frac = float(np.isfinite(hist).mean())

    # exporter round-trip on the live registry
    with tempfile.TemporaryDirectory() as d:
        t0 = time.perf_counter()
        JsonlExporter(os.path.join(d, "telemetry.jsonl")).write(
            {"kind": "metrics", "metrics": metrics().snapshot()})
        prom = write_prometheus_textfile(
            metrics(), os.path.join(d, "metrics.prom"))
        export_ms = (time.perf_counter() - t0) * 1e3
        prom_bytes = os.path.getsize(prom) if prom else 0

    _log(f"telemetry: {on_sps:,.0f} samples/sec with metrics pack vs "
         f"{off_sps:,.0f} without ({overhead_pct:+.2f}% overhead, target "
         f"<3%); history drain {drain_ms:.1f} ms, exporters "
         f"{export_ms:.1f} ms ({prom_bytes} B prom)")
    return {"pack_samples_per_sec": round(on_sps, 1),
            "no_pack_samples_per_sec": round(off_sps, 1),
            "pack_overhead_pct": round(overhead_pct, 2),
            "overhead_within_target": bool(overhead_pct < 3.0),
            "metrics_history_shape": list(hist.shape),
            "metrics_finite_fraction": round(finite_frac, 4),
            "history_drain_ms": round(drain_ms, 2),
            "exporter_roundtrip_ms": round(export_ms, 2),
            "prometheus_bytes": prom_bytes,
            "batch": batch, "n_batches": n_batches, "epochs": epochs}


def bench_flight():
    """Run-observability overhead: fused-epoch throughput with the
    flight recorder live (DL4J_FLIGHT-equivalent: every chunk boundary,
    span, and ledger transition streaming to the on-disk segment ring)
    vs off — the budget is <3% like the sentinel and the metrics pack.
    The run ledger itself is always on (host-side arithmetic), so the
    delta isolates the recorder. Also reports the ledger's goodput for
    the timed run, the recorder's write stats, and a postmortem round
    trip: the completed run's surviving segments must classify as
    ``clean``."""
    import tempfile

    from deeplearning4j_tpu.datasets.dataset import DataSet
    from deeplearning4j_tpu.datasets.iterator import ListDataSetIterator
    from deeplearning4j_tpu.models import mnist_mlp
    from deeplearning4j_tpu.monitor.flight import (
        FlightRecorder, classify_end_state, load_flight_records,
        set_flight)
    from deeplearning4j_tpu.monitor.ledger import run_ledger
    from deeplearning4j_tpu.perf.epoch_cache import DeviceDataSetCache

    rng = np.random.default_rng(0)
    batch, n_batches, epochs = 2048, 16, 5
    ds = DataSet(rng.random((batch * n_batches, 784), np.float32),
                 np.eye(10, dtype=np.float32)[
                     rng.integers(0, 10, batch * n_batches)])
    total = batch * n_batches

    def prep():
        net = mnist_mlp(hidden=256, dtype_policy="bf16").init()
        cache = DeviceDataSetCache.build(ListDataSetIterator(ds, batch))
        assert cache is not None, "bench dataset exceeded DL4J_DEVICE_CACHE_MB"
        net.fit_epochs(cache, epochs, chunk_epochs=1)
        _sync(net.params)  # warm: compile outside the timing
        return net, cache

    def timed(net, cache):
        t0 = time.perf_counter()
        net.fit_epochs(cache, epochs, chunk_epochs=1)
        _sync(net.params)
        return total * epochs / (time.perf_counter() - t0)

    off_net, off_cache = prep()
    on_net, on_cache = prep()
    # best-of-3, interleaved: host timing jitter dwarfs a few-% delta
    off_sps = max(timed(off_net, off_cache) for _ in range(3))
    with tempfile.TemporaryDirectory() as d:
        recorder = FlightRecorder(d)
        set_flight(recorder)
        try:
            on_sps = max(timed(on_net, on_cache) for _ in range(3))
        finally:
            set_flight(None)
            recorder.close()
        records = load_flight_records(d)
        end_state = classify_end_state(records)["end_state"]
    overhead_pct = (off_sps / on_sps - 1.0) * 100.0
    goodput = run_ledger().last_run_goodput()

    _log(f"flight: {on_sps:,.0f} samples/sec recorded vs {off_sps:,.0f} "
         f"unrecorded ({overhead_pct:+.2f}% overhead, target <3%); "
         f"{recorder.records_written} records, "
         f"{recorder.segments_rotated} rotations, goodput "
         f"{goodput if goodput is not None else float('nan'):.1f}%, "
         f"postmortem={end_state}")
    return {"recorded_samples_per_sec": round(on_sps, 1),
            "unrecorded_samples_per_sec": round(off_sps, 1),
            "flight_overhead_pct": round(overhead_pct, 2),
            "overhead_within_target": bool(overhead_pct < 3.0),
            "records_written": recorder.records_written,
            "records_dropped": recorder.records_dropped,
            "segments_rotated": recorder.segments_rotated,
            "goodput_pct": goodput,
            "postmortem_end_state": end_state,
            "batch": batch, "n_batches": n_batches, "epochs": epochs}


def bench_serve():
    """Online serving path: the continuous-batching decode server under
    an open-loop Poisson request stream (ragged prompt/generation
    lengths). Reports p50/p99 request latency, TTFT/TPOT, tokens/sec,
    occupancy, and the compile-flatness evidence: program builds during
    the warmup stream vs after a second ragged stream — the steady-state
    count MUST stay flat (one decode program + one prefill per ladder
    rung, never a compile per request shape). Also reports the persisted
    XLA compilation cache (DL4J_COMPILE_CACHE_DIR — scoped to a
    section-local temp dir when the caller set none, so cold-start
    replay is exercised without leaking cache config or disk into the
    other sections) entry counts, so a fleet replica's warm boot is
    checkable from the artifact."""
    import os
    import shutil
    import tempfile

    import jax

    from deeplearning4j_tpu.serving import compile_cache as _cc

    # respect a caller-provided cache dir; otherwise stand up a
    # section-scoped one and tear the whole configuration back down in
    # the finally (later sections must not inherit persist-everything
    # compile caching, and the bench must not orphan temp dirs)
    tmp = None
    prev_knobs = {}
    if not os.environ.get("DL4J_COMPILE_CACHE_DIR", "").strip():
        tmp = tempfile.mkdtemp(prefix="dl4j-compile-cache-")
        os.environ["DL4J_COMPILE_CACHE_DIR"] = tmp
        for knob in ("jax_compilation_cache_dir",
                     "jax_persistent_cache_min_compile_time_secs",
                     "jax_persistent_cache_min_entry_size_bytes"):
            try:
                prev_knobs[knob] = getattr(jax.config, knob)
            except AttributeError:
                pass
    try:
        return _bench_serve_run()
    finally:
        if tmp is not None:
            os.environ.pop("DL4J_COMPILE_CACHE_DIR", None)
            for knob, val in prev_knobs.items():
                try:
                    jax.config.update(knob, val)
                except Exception:
                    pass
            _cc._reset_for_tests()
            shutil.rmtree(tmp, ignore_errors=True)


def _bench_serve_run():
    from deeplearning4j_tpu.models.transformer import TransformerLM
    from deeplearning4j_tpu.serving import (
        DecodeServer, compile_cache_stats, max_slots_in_budget,
        poisson_schedule, run_open_loop)

    lm = TransformerLM(vocab_size=512, d_model=128, num_heads=8,
                       num_kv_heads=4, num_layers=2, max_len=512,
                       seed=7, dtype_policy="bf16",
                       pos_encoding="rope").init()
    slots = 8
    server = DecodeServer(lm, slots=slots, max_len=256)

    # warmup stream: cold compiles (decode + every ladder rung the
    # request mix touches) land here
    warm_sched = poisson_schedule(
        16, rate_rps=200.0, vocab_size=512,
        prompt_lens=(8, 16, 24, 48), max_new_tokens=(8, 16), seed=1)
    run_open_loop(server, warm_sched)
    builds_warm = server.engine.program_builds
    compiles_warm = dict(server.stats()["compiles"])

    # measured stream: same shape menu, 4x the requests — zero new
    # programs may appear
    sched = poisson_schedule(
        64, rate_rps=200.0, vocab_size=512,
        prompt_lens=(8, 16, 24, 48), max_new_tokens=(8, 16), seed=2)
    report = run_open_loop(server, sched)
    builds_steady = server.engine.program_builds
    flat = builds_steady == builds_warm

    summary = report.summary()
    stats = server.stats()
    _log(f"serve: {summary['tokens_per_sec']:,.0f} tokens/sec, "
         f"p50 {summary['p50_latency_ms']} ms / "
         f"p99 {summary['p99_latency_ms']} ms, TTFT p50 "
         f"{summary['ttft_p50_ms']} ms, occupancy "
         f"{summary['occupancy_mean']}; compiles warm={builds_warm} "
         f"steady={builds_steady} "
         f"({'FLAT' if flat else 'NOT FLAT — recompiling per request?'})")

    # ---- fast-path sweep: fuse_steps x kv_dtype x spec-decode -------
    # the same request stream replayed against each serve config, so
    # dispatches/token and accepted-tokens/dispatch compare apples to
    # apples; TPOT differences isolate the dispatch-amortization win
    def fast_config(name, **kw):
        srv = DecodeServer(lm, slots=slots, max_len=256, **kw)
        sched = poisson_schedule(
            24, rate_rps=200.0, vocab_size=512,
            prompt_lens=(8, 16, 24), max_new_tokens=(16,), seed=3)
        rep = run_open_loop(srv, sched).summary()
        st = srv.stats()
        row = {
            "tokens_per_sec": rep["tokens_per_sec"],
            "tpot_mean_ms": rep["tpot_mean_ms"],
            "dispatches_per_token": st["dispatches_per_token"],
            # distinct name on purpose: this one includes slot-batching
            # amortization (decode_tokens / dispatches across the whole
            # batch); the gated top-level accepted_tokens_per_dispatch
            # is the PER-SLOT figure below
            "batch_tokens_per_dispatch":
                st["accepted_tokens_per_dispatch"],
            "tokens_per_slot_dispatch": st["tokens_per_slot_dispatch"],
            "kv_pool_bytes": st["kv_pool_bytes"],
            "kv_dtype": st["kv_dtype"],
            "fuse_steps": st["fuse_steps"],
        }
        if st.get("spec_accept_rate") is not None:
            row["spec_accept_rate"] = st["spec_accept_rate"]
        _log(f"serve[{name}]: {row['tokens_per_sec']:,.0f} tok/s, "
             f"disp/tok {row['dispatches_per_token']}, "
             f"tok/slot-disp {row['tokens_per_slot_dispatch']}, "
             f"TPOT {row['tpot_mean_ms']} ms")
        return row

    sweep = {
        "fuse1": fast_config("fuse1", fuse_steps=1),
        "fuse4": fast_config("fuse4", fuse_steps=4),
        "fuse4_int8": fast_config("fuse4_int8", fuse_steps=4,
                                  kv_dtype="int8"),
        "spec_draft1": fast_config("spec_draft1", draft_layers=1,
                                   spec_tokens=3),
    }

    # max concurrency the HBM budget buys per store dtype (analytic —
    # the model validate_cache_budget checks against device bytes)
    budget = 1 << 30  # 1 GiB of pool budget at max_len=256
    max_slots = {dt: max_slots_in_budget(lm, 256, budget, dt)
                 for dt in ("float32", "bfloat16", "int8")}
    _log(f"serve: max slots in {budget >> 20} MiB pool budget: "
         + ", ".join(f"{k}={v}" for k, v in max_slots.items()))

    return {**summary,
            "slots": slots,
            "kv_pool_bytes": stats["kv_pool_bytes"],
            "compiles_after_warmup": compiles_warm,
            "program_builds_warmup": builds_warm,
            "program_builds_steady": builds_steady,
            "compile_count_flat_after_warmup": bool(flat),
            "compile_cache": compile_cache_stats(),
            # fast-path headline metrics (tracked by bench_report.py:
            # dispatches/token gates lower, accepted-tokens/dispatch
            # and int8 max-slots gate higher)
            "dispatches_per_token":
                sweep["fuse4"]["dispatches_per_token"],
            "tpot_fused_ms": sweep["fuse4"]["tpot_mean_ms"],
            "accepted_tokens_per_dispatch":
                sweep["spec_draft1"]["tokens_per_slot_dispatch"],
            "spec_accept_rate": sweep["spec_draft1"].get(
                "spec_accept_rate"),
            "max_slots_in_budget": max_slots,
            "max_slots_int8": max_slots["int8"],
            "fast_path": sweep}


def bench_serve_fleet():
    """Serve fleet: M in-process replicas behind the routing frontend,
    the same Poisson stream replayed per fleet size. One bench host has
    one backend, so in-process replicas time-slice it — the driver books
    each replica's REAL measured dispatch costs on its own virtual
    timeline (the chip-per-replica deployment model); the scaling number
    therefore measures the fleet layer (routing balance, queue spill,
    admission batching), not host parallelism the machine doesn't have.
    Alongside scaling: p50/p99/TTFT vs the single-replica baseline,
    per-replica busy-time balance, and a failover round — one replica
    killed mid-stream, controller eviction, requeue-with-re-prefill on
    the survivor — reporting recovery time and asserting zero lost
    requests + greedy token identity for every rerouted request."""
    import numpy as np

    from deeplearning4j_tpu.models.transformer import TransformerLM
    from deeplearning4j_tpu.serving import poisson_schedule
    from deeplearning4j_tpu.serving.fleet import (
        FleetController, FleetLoadDriver, FleetRouter, ServeReplica)

    lm = TransformerLM(vocab_size=512, d_model=128, num_heads=8,
                       num_kv_heads=4, num_layers=2, max_len=512,
                       seed=7, dtype_policy="bf16",
                       pos_encoding="rope").init()
    prompt_lens = (8, 16, 24)

    def build_replicas(n):
        reps = [ServeReplica(f"r{i}", lm, slots=8, max_len=256,
                             fuse_steps=4) for i in range(n)]
        for r in reps:
            # warm every prompt-ladder rung + the fused decode program
            # on the main thread, outside the measured virtual replay
            for plen in prompt_lens:
                r.server.submit(np.arange(1, plen + 1, dtype=np.int32), 2)
            r.server.drain()
            r.server.finished.clear()
            r._finished_seen = 0
        return reps

    def schedule(seed=5):
        # saturating on purpose (arrival span << 1-replica busy time):
        # an arrival-limited stream would show flat tokens/sec at every
        # fleet size and measure nothing
        return poisson_schedule(48, rate_rps=2000.0, vocab_size=512,
                                prompt_lens=prompt_lens,
                                max_new_tokens=(16,), seed=seed)

    def run_fleet_once(n):
        reps = build_replicas(n)
        router = FleetRouter(reps)
        driver = FleetLoadDriver(
            router, FleetController(router, None, evict_timeout_s=5.0))
        report = driver.run(schedule())
        s = report.summary()
        busy = driver.busy_seconds()
        vals = list(busy.values())
        s["replicas"] = n
        s["busy_seconds"] = {k: round(v, 4) for k, v in busy.items()}
        # balance = min/max busy time: 1.0 is a perfectly even split
        # (busy_seconds seeds every replica, so a starved one reads 0)
        s["balance"] = (round(min(vals) / max(vals), 4)
                        if len(vals) > 1 and max(vals) > 0 else 1.0)
        s["dispatches"] = {rid: sum(1 for r, _, _ in driver.dispatch_log
                                    if r == rid) for rid in busy}
        return s

    def run_fleet(n, rounds=2):
        # real measured dispatch costs carry single-run wall noise
        # (~10-20% on a busy host); best-of-N is the capability
        # estimate, same-schedule replay keeps it apples-to-apples
        s = max((run_fleet_once(n) for _ in range(rounds)),
                key=lambda r: r["tokens_per_sec"])
        _log(f"serve_fleet[{n}r]: {s['tokens_per_sec']:,.0f} tok/s, "
             f"p50 {s['p50_latency_ms']} ms, TTFT p50 "
             f"{s['ttft_p50_ms']} ms, balance {s['balance']} "
             f"(best of {rounds})")
        return s

    fleet = {n: run_fleet(n) for n in (1, 2, 4)}
    base = fleet[1]["tokens_per_sec"]
    scaling = {n: round(fleet[n]["tokens_per_sec"] / base, 4)
               for n in fleet}
    _log(f"serve_fleet: tokens/sec scaling vs 1 replica: "
         + ", ".join(f"{n}r={scaling[n]}" for n in sorted(scaling)))
    # the clock model books REAL measured dispatch costs: scaling above
    # the replica count is impossible from routing alone and means the
    # host was contended during one of the runs — flag it rather than
    # report an inflated win as clean
    noise_flag = any(scaling[n] > n * 1.1 for n in scaling)
    if noise_flag:
        _log("serve_fleet: WARNING — superlinear scaling measured; the "
             "baseline run's dispatch costs were likely inflated by "
             "host contention (rerun on an idle machine)")

    # ---- failover: kill one of two replicas mid-stream ---------------
    reps = build_replicas(2)
    router = FleetRouter(reps)
    controller = FleetController(router, None, evict_timeout_s=5.0)
    driver = FleetLoadDriver(router, controller)
    report = driver.run(schedule(seed=6), kill_at_s=0.08,
                        kill_replica="r0")
    lost = sum(1 for fr in router.requests if not fr.finished)
    # greedy token identity across the failover: every request's final
    # stream must equal the model's own unassisted greedy decode
    diverged = 0
    for fr in router.requests:
        ref = np.asarray(lm.generate(fr.prompt[None],
                                     fr.max_new_tokens))[0]
        if not np.array_equal(fr.output, ref):
            diverged += 1
    failover_s = (None if driver.failover_done_s is None
                  or driver.kill_time_s is None
                  else round(driver.failover_done_s
                             - driver.kill_time_s, 4))
    evic = controller.eviction_log[0] if controller.eviction_log else {}
    requeued = evic.get("failover", {}).get("victims", 0)
    _log(f"serve_fleet: failover — {requeued} requests requeued, "
         f"{lost} lost, {diverged} diverged, recovery "
         f"{failover_s}s past the kill (detection floor is "
         f"DL4J_SERVE_EVICT_S in deployment; the bench evicts at the "
         f"kill instant)")
    assert lost == 0, f"failover lost {lost} request(s)"
    assert diverged == 0, (
        f"failover broke greedy token identity on {diverged} request(s)")

    return {
        "fleet": {str(n): fleet[n] for n in fleet},
        "fleet_tokens_per_sec": fleet[2]["tokens_per_sec"],
        "single_tokens_per_sec": base,
        "tokens_per_sec_scaling_2r": scaling[2],
        "tokens_per_sec_scaling_4r": scaling[4],
        "scaling_2r_target_met": bool(scaling[2] >= 1.8),
        "scaling_noise_flag": noise_flag,
        "p50_latency_ms_2r": fleet[2]["p50_latency_ms"],
        "p99_latency_ms_2r": fleet[2]["p99_latency_ms"],
        "ttft_p50_ms_2r": fleet[2]["ttft_p50_ms"],
        "balance_2r": fleet[2]["balance"],
        "failover": {
            "requeued": requeued,
            "lost_requests": lost,
            "diverged_requests": diverged,
            "failover_complete_s": failover_s,
            "finished": report.summary()["finished"],
            "eviction_reason": evic.get("reason"),
        },
        "failover_complete_s": failover_s,
        "clock_model": "per-replica virtual timelines over real "
                       "measured dispatch costs (chip-per-replica)",
    }


def bench_eval():
    """Inference/eval path: device-resident confusion accumulation vs the
    host path (per-batch logit readback) on a stream of ragged batches.
    Reports samples/sec both ways plus jit compile counts — the device
    path must show exactly one compile per shape bucket and one host
    transfer per evaluate() call (the PERF.md eval invariants)."""
    from deeplearning4j_tpu.datasets.dataset import DataSet
    from deeplearning4j_tpu.models import mnist_mlp

    rng = np.random.default_rng(0)
    # seven full batches + a ragged tail: two shape buckets total
    sizes = [4096] * 7 + [1777]
    batches = [DataSet(rng.random((b, 784), np.float32),
                       np.eye(10, dtype=np.float32)[
                           rng.integers(0, 10, b)])
               for b in sizes]
    total = sum(sizes)
    net = mnist_mlp(hidden=256, dtype_policy="bf16").init()

    def run(device):
        t0 = time.perf_counter()
        ev = net.evaluate(batches, device_accumulation=device)
        # evaluate() ends on a host readback either way — already synced
        return total / (time.perf_counter() - t0), ev

    run(True)  # compile both bucket programs
    device_sps, ev_dev = run(True)
    run(False)
    host_sps, ev_host = run(False)
    if abs(ev_dev.accuracy() - ev_host.accuracy()) > 1e-12:
        _log(f"eval: DEVICE/HOST ACCURACY MISMATCH "
             f"{ev_dev.accuracy()} vs {ev_host.accuracy()}")
    readbacks_per_call = net._eval_readbacks / 2  # two device runs above
    _log(f"eval: {device_sps:,.0f} samples/sec device-resident, "
         f"{host_sps:,.0f} host path ({device_sps / host_sps:.2f}x), "
         f"{net._eval_step._cache_size()} compiles for "
         f"{len(set(sizes))} buckets")
    return {"device_samples_per_sec": round(device_sps, 1),
            "host_samples_per_sec": round(host_sps, 1),
            "speedup": round(device_sps / host_sps, 2),
            "eval_compiles": net._eval_step._cache_size(),
            "output_compiles": net._output_fn._cache_size(),
            "host_transfers_per_call": readbacks_per_call,
            "batches": len(sizes), "total_samples": total,
            "accuracy_match": bool(
                abs(ev_dev.accuracy() - ev_host.accuracy()) <= 1e-12)}


def _transformer(t, vocab=8192, d=512, layers=8, heads=8, attn="auto",
                 remat=False, window=None, policy="mixed_bf16"):
    from deeplearning4j_tpu.models.transformer import TransformerLM

    # mixed_bf16 = bf16 forward/backward on a per-step parameter copy
    # with f32 master weights + f32 Adam state (the training default);
    # policy="float32" builds the speedup-probe baseline
    return TransformerLM(vocab_size=vocab, d_model=d, num_heads=heads,
                         num_layers=layers, max_len=t, seed=0,
                         dtype_policy=policy, attn_impl=attn, remat=remat,
                         attn_window=window)


def _transformer_flops_per_token(lm, t):
    n_params_matmul = sum(
        int(np.prod(p.shape)) for blk in lm.params["blocks"]
        for grp in blk.values() for p in grp.values())
    n_params_matmul += lm.d_model * lm.vocab_size  # tied unembedding
    # attention term: avg keys/query is t/2 causal; banded it is the
    # exact causal-window average w·(t-(w-1)/2)/t — queries q < w-1 see
    # only q+1 keys (keeps windowed-config MFU honest: banding REMOVES
    # model FLOPs, and rounding the average UP would flatter the number)
    if lm.attn_window is None or lm.attn_window >= t:
        avg_keys = t / 2
    else:
        w = lm.attn_window
        avg_keys = w * (t - (w - 1) / 2) / t
    return int(6 * n_params_matmul
               + 12 * lm.num_layers * lm.d_model * avg_keys)


def _bench_transformer_cfg(batch, t, steps=10, fused_k=10, attn="auto",
                           remat=False, window=None, policy="mixed_bf16"):
    import jax.numpy as jnp

    lm = _transformer(t, attn=attn, remat=remat, window=window,
                      policy=policy).init()
    tokens = jnp.asarray(
        np.random.default_rng(0).integers(0, 8192, (batch, t)), jnp.int32)
    _sync(tokens)
    step = lm.make_train_step()
    prof = _profile_step(
        step, (lm.params, lm.opt_state, tokens, jnp.asarray(0, jnp.int32)),
        f"transformer_b{batch}_t{t}_{attn}")
    sec_step = _time_loop(lambda: lm.fit_batch(tokens, train_step=step, block=False),
                          steps=steps, sync=lambda: lm.params)
    try:
        multi = lm.make_multi_train_step(fused_k)
        sec_fused = _time_loop(
            lambda: lm.fit_batch_multi(tokens, multi_step=multi,
                                       k=fused_k, block=False),
            steps=max(2, steps // fused_k), sync=lambda: lm.params
        ) / fused_k
    except Exception as e:
        _log(f"transformer fused path FAILED: {e!r}")
        sec_fused = float("inf")
    sec = min(sec_step, sec_fused)
    tps = batch * t / sec
    fpt = _transformer_flops_per_token(lm, t)
    flops = _flops_entry(
        fpt, "analytic 6*N/token + attention term", prof, batch * t)
    per_token = (flops["analytic_flops"]
                 if flops["cost_analysis_flops"] is None
                 else flops["cost_analysis_flops"])
    tflops = per_token * tps / 1e12
    tflops_analytic = fpt * tps / 1e12
    mfu = 100 * tflops / PEAK_TFLOPS_BF16
    return {
        "tokens_per_sec": round(tps, 1),
        "per_step_tokens_per_sec": round(batch * t / sec_step, 1),
        "fused_tokens_per_sec": (
            0.0 if sec_fused == float("inf")
            else round(batch * t / sec_fused, 1)),
        "batch": batch, "seq_len": t, "remat": remat,
        "attn_impl": lm._attn_impl(t, train=True),
        "dtype_policy": lm.dtype_policy_name,
        "model_tflops": round(tflops, 1), "mfu_pct": round(mfu, 1),
        "model_tflops_analytic": round(tflops_analytic, 1),
        "mfu_pct_analytic": round(
            100 * tflops_analytic / PEAK_TFLOPS_BF16, 1),
        "flops_source": flops,
        "cost_model": _cost_model_entry(prof, sec_step),
    }, tps, lm


def bench_transformer(cpu_baseline=True, on_progress=None):
    """``on_progress(partial_dict)`` is called after every sub-config so
    the durable sidecar always holds the configs measured so far — a
    wedge mid-sweep (this is the longest section) no longer loses the
    whole transformer entry."""
    import jax
    import jax.numpy as jnp

    # batch sweep at t=1024 (the headline config family)
    sweep = {}
    best_tps, best_cfg = 0.0, None

    def progress(**stages):
        if on_progress is not None:
            partial = {"partial": True, "batch_sweep_t1024": dict(sweep)}
            partial.update(stages)
            on_progress(partial)
    # batch sweep on the auto attention path, plus the Pallas flash
    # kernel FORCED at the best-batch config: the flash backward kernels
    # avoid the [b,h,t,t] f32 score-matrix HBM traffic both directions,
    # so flash may win below the auto heuristic's t>=4096 crossover —
    # measure instead of guessing (entries are labeled by attn_impl)
    for label, batch, attn, remat in (("16", 16, "auto", False),
                                      ("32", 32, "auto", False),
                                      ("32_flash", 32, "flash", False),
                                      ("64", 64, "auto", True)):
        try:
            cfg, tps, _ = _bench_transformer_cfg(batch, 1024, attn=attn,
                                                 remat=remat)
            sweep[label] = cfg
            _log(f"transformer b{batch} t1024 ({cfg['attn_impl']}"
                 f"{', remat' if remat else ''}): "
                 f"{cfg['tokens_per_sec']:,.0f} tok/s "
                 f"({cfg['mfu_pct']:.1f}% MFU)")
            if tps > best_tps:
                best_tps, best_cfg = tps, cfg
        except Exception as e:
            sweep[label] = {"error": str(e)[:200]}
            _log(f"transformer b{batch} {attn} FAILED: {e}")
        progress()

    # long-context config where the Pallas flash kernel engages
    try:
        flash_cfg, _, lm4k = _bench_transformer_cfg(4, 4096, steps=6,
                                                    fused_k=6)
        flash_cfg["note"] = "flash kernel auto-engages at t>=4096"
        _log(f"transformer b4 t4096 ({flash_cfg['attn_impl']}): "
             f"{flash_cfg['tokens_per_sec']:,.0f} tok/s "
             f"({flash_cfg['mfu_pct']:.1f}% MFU)")
    except Exception as e:
        flash_cfg = {"error": str(e)[:200]}
        _log(f"transformer t4096 FAILED: {e}")
    progress(long_context_t4096=flash_cfg)

    # sliding-window at the same long-context shape: the banded flash
    # grid does O(t·window) work instead of O(t²/2) — the recorded
    # tokens/sec ratio vs the full-causal t4096 entry is the artifact
    # evidence for the banded kernels (window=1024 ⇒ ~2x fewer
    # attention FLOPs at t=4096)
    try:
        win_cfg, _, _ = _bench_transformer_cfg(4, 4096, steps=6, fused_k=6,
                                               attn="flash", window=1024)
        win_cfg["note"] = "banded flash grid, attn_window=1024"
        _log(f"transformer b4 t4096 w1024 (flash banded): "
             f"{win_cfg['tokens_per_sec']:,.0f} tok/s "
             f"({win_cfg['mfu_pct']:.1f}% MFU)")
    except Exception as e:
        win_cfg = {"error": str(e)[:200]}
        _log(f"transformer t4096 w1024 FAILED: {e}")
    # mixed-precision speedup probe: the SAME b16 t1024 config under the
    # float32 policy, PER-STEP path vs the sweep entry's PER-STEP number
    # — strictly like-for-like (the best-of-fused tokens/sec would fold
    # dispatch amortization into a dtype claim). The artifact evidence
    # that the bf16 step buys MXU rate, not just smaller buffers (gated
    # as train_step_bf16_speedup, higher is better).
    b16_step_tps = (sweep.get("16") or {}).get(
        "per_step_tokens_per_sec", 0.0) or 0.0
    bf16_speedup = None
    if b16_step_tps:
        try:
            lm32 = _transformer(1024, policy="float32").init()
            step32 = lm32.make_train_step()
            tokens32 = jnp.asarray(np.random.default_rng(0).integers(
                0, 8192, (16, 1024)), jnp.int32)
            sec32 = _time_loop(
                lambda: lm32.fit_batch(tokens32, train_step=step32,
                                       block=False),
                steps=3, sync=lambda: lm32.params)
            tps32 = 16 * 1024 / sec32
            bf16_speedup = round(b16_step_tps / tps32, 2)
            _log(f"transformer f32 per-step baseline: {tps32:,.0f} tok/s "
                 f"→ bf16 step speedup {bf16_speedup:.2f}x")
        except Exception as e:
            _log(f"transformer f32 speedup probe FAILED: {e}")
    progress(long_context_t4096=flash_cfg,
             long_context_t4096_w1024=win_cfg,
             train_step_bf16_speedup=bf16_speedup)

    # vs_baseline is strictly like-for-like: the b16 t1024 TPU number over
    # the SAME config on XLA-CPU (the sweep's best batch may differ)
    b16_tps = (sweep.get("16") or {}).get("tokens_per_sec", 0.0) or 0.0
    vs_baseline = float("nan")
    if cpu_baseline and b16_tps:
        try:
            cpu = jax.devices("cpu")[0]
            with jax.default_device(cpu):
                lm_cpu = _transformer(1024).init()
                step_cpu = lm_cpu.make_train_step()
                tokens_cpu = jax.device_put(np.random.default_rng(0).integers(
                    0, 8192, (16, 1024)).astype(np.int32), cpu)
                # ONE timed step after warm-up: the XLA-CPU step takes
                # minutes at this config (r3: 42 tok/s) and the ratio is
                # stable; keeping the baseline like-for-like matters more
                # than averaging it
                sec_cpu = _time_loop(
                    lambda: lm_cpu.fit_batch(tokens_cpu, train_step=step_cpu,
                                             block=False),
                    steps=1, sync=lambda: lm_cpu.params)
            cpu_tps = 16 * 1024 / sec_cpu
            vs_baseline = b16_tps / cpu_tps
            _log(f"transformer CPU baseline: {cpu_tps:,.0f} tokens/sec "
                 f"→ vs_baseline {vs_baseline:.1f}x")
        except Exception as e:  # pragma: no cover
            _log(f"CPU baseline failed: {e}")

    result = dict(best_cfg or {})
    if best_cfg and best_cfg is sweep.get("32_flash"):
        # headline basis change is explicit, not silent: earlier rounds'
        # headline was best-of-auto; if the forced-flash probe wins, that
        # is the signal to lower the auto crossover in models/transformer
        result["headline_basis"] = (
            "forced attn_impl=flash beat the auto path at t=1024 — "
            "auto-crossover candidate")
    # best_cfg already carries the dual analytic/cost-analysis
    # flops_source block; only fill the legacy string when the whole
    # sweep errored out and there is no per-config block to keep
    result.setdefault("flops_source",
                      "analytic 6*N/token + attention term")
    result["config"] = "d512 L8 H8 v8192 mixed_bf16 (f32 masters)"
    result["batch_sweep_t1024"] = sweep
    result["long_context_t4096"] = flash_cfg
    result["long_context_t4096_w1024"] = win_cfg
    if bf16_speedup is not None:
        result["train_step_bf16_speedup"] = bf16_speedup
    return result, vs_baseline


def _probe_backend_subprocess(timeout_s: float):
    """Probe backend liveness from a SHORT-LIVED CHILD process.

    The tunnel backend's device claim can block INDEFINITELY inside the
    PJRT C API when a previous client's grant is wedged (observed in
    round 4: >3 h, and the in-process watchdog then eats its full budget
    before reporting). A child that hangs in init can be killed safely
    (a probe blocked in init holds no grant yet), so the wedge is
    detected in ``timeout_s`` seconds without this process ever touching
    the backend. Returns (ok, detail)."""
    import subprocess
    import sys

    code = ("import jax; ds = jax.devices(); "
            "print('PROBE_OK', len(ds), ds[0].platform)")
    try:
        proc = subprocess.run([sys.executable, "-c", code],
                              capture_output=True, text=True,
                              timeout=timeout_s)
    except subprocess.TimeoutExpired:
        return False, (f"backend init did not complete in {timeout_s:.0f}s "
                       "(wedged device grant?)")
    except OSError as e:
        return False, f"probe spawn failed: {e}"
    if proc.returncode != 0 or "PROBE_OK" not in proc.stdout:
        tail = (proc.stderr or proc.stdout or "").strip()[-300:]
        return False, f"probe rc={proc.returncode}: {tail}"
    return True, proc.stdout.strip().splitlines()[-1]


class _BackendProbeFailed(RuntimeError):
    """Child probe reported the backend unavailable (wedged grant shape)."""


class _BackendInitFailed(RuntimeError):
    """In-process jax init RAISED — a sticky failure (module import state
    is process-wide), never retried under the lease."""


def _await_backend(timeout_s: float = None):
    """Initialize the accelerator backend under the grant lease protocol:
    wedge-proof, self-healing, fail-fast only as the last resort.

    Two lease-wrapped layers: (1) a short-lived CHILD process probes the
    backend, so a wedged device grant is reported in seconds — and,
    NEW in the always-on layer, a wedged probe RE-ACQUIRES under
    escalating backoff (``DL4J_GRANT_REACQUIRES`` cycles, each booked as
    ``grant_wait`` badput in the run ledger) instead of forfeiting the
    round, the BENCH_r04/r05 failure shape; (2) only after a probe
    succeeds is jax initialized in-process, on a daemon thread under the
    lease bound — a wedge there re-probes from a fresh child between
    waits (the init thread cannot be killed, but a recovered grant lets
    a later wait window complete). Only lease EXHAUSTION emits the
    honest error JSON line and exits, so the driver records the failure
    as data instead of a hang."""
    import os
    import threading

    from deeplearning4j_tpu.resilience.lease import (
        GrantLease, GrantWedgedError, grant_reacquires)

    if timeout_s is None:
        try:
            timeout_s = float(
                os.environ.get("BENCH_BACKEND_TIMEOUT_S", "300"))
        except ValueError:
            timeout_s = 300.0

    # The probe gets its own SHORT cap: healthy tunnel init is ~20-40s,
    # so 90s separates healthy from wedged without doubling the watchdog
    # budget on the wedged-between-probe-and-reclaim path.
    try:
        probe_s = float(os.environ.get("BENCH_PROBE_TIMEOUT_S",
                                       str(min(timeout_s, 90.0))))
    except ValueError:
        probe_s = min(timeout_s, 90.0)

    def _fail(phase: str, detail) -> None:
        _log(f"BACKEND UNAVAILABLE ({phase}): {detail}")
        err = {"error": f"backend unavailable: {detail}"}
        # the sidecar is the durable record: without this flush a wedged
        # backend leaves a STALE bench_partial.json from a previous round
        # masquerading as this run's result (BENCH_r05: rc=0, null metric,
        # no trace of why)
        _flush_partial(err, complete=True)
        print(_result_line(err, None, float("nan")), flush=True)
        os._exit(0)

    # -- phase 1: child probe, lease-wrapped. The lease drops the
    # grant.wait flight marker before every attempt and wraps retries in
    # grant.reacquire spans — the wedge timeline BENCH_r04/r05 lacked,
    # plus the rescue evidence flight_report classifies `reacquired` from.
    def _probe_once():
        with _tracer().span("grant.probe", timeout_s=probe_s) as sp:
            ok, detail = _probe_backend_subprocess(probe_s)
            sp.attrs["ok"] = ok
            sp.attrs["detail"] = str(detail)[:200]
        if not ok:
            raise _BackendProbeFailed(str(detail))
        return detail

    probe_lease = GrantLease(
        "bench.probe", _probe_once, bounded=False, lease_s=probe_s,
        max_reacquires=grant_reacquires(),
        retryable=(_BackendProbeFailed,))
    try:
        detail = probe_lease.acquire()
    except GrantWedgedError as e:
        _fail("child probe", e)
    _log(f"child probe ok: {detail}"
         + (f" (re-acquired after {probe_lease.reacquires} wedged "
            f"attempt(s))" if probe_lease.reacquires else ""))

    # -- phase 2: in-process init. The thread starts ONCE; each lease
    # attempt is one bounded wait window on its completion, with a child
    # re-probe between windows — a grant that wedges then recovers
    # completes init during a later window instead of costing the round.
    result = {}
    ready = threading.Event()

    def _init():
        try:
            import jax

            result["devices"] = str(jax.devices())
        except Exception as e:  # init raised: report, don't hang
            result["error"] = str(e)[:300]
        ready.set()

    threading.Thread(target=_init, daemon=True).start()

    def _await_init():
        ready.wait()  # the lease bound is the timeout
        if "error" in result:
            raise _BackendInitFailed(result["error"])
        return result["devices"]

    init_lease = GrantLease(
        "bench.acquire", _await_init, bounded=True, lease_s=timeout_s,
        max_reacquires=grant_reacquires(),
        probe=lambda: _probe_backend_subprocess(probe_s)[0],
        retryable=())  # only wedge timeouts re-acquire; a raised init
    try:                # error is sticky in-process
        devices = init_lease.acquire()
    except _BackendInitFailed as e:
        _fail("init", e)
    except GrantWedgedError:
        _fail("init", f"backend init did not complete in "
                      f"{timeout_s:.0f}s per lease window across "
                      f"{1 + init_lease.max_reacquires} attempt(s) "
                      "(grant re-wedged?)")
    _log(f"backend up: {devices}"
         + (f" (re-acquired after {init_lease.reacquires} wedged "
            f"attempt(s))" if init_lease.reacquires else ""))


def _refresh_telemetry(extras):
    """(Re)attach the metrics+span summary block AND the compiled-program
    profile block. Called at every flush and on the final result line, so
    EVERY artifact — complete, partial, or error — carries the current
    timeline and every ProgramProfile collected so far (a section that
    wedges mid-run still flushes the profiles its programs captured)."""
    try:
        extras["telemetry"] = _telemetry_summary()
    except Exception as e:  # telemetry must never break the bench
        _log(f"telemetry summary failed: {e}")
    try:
        from deeplearning4j_tpu.monitor.profile import (
            profile_enabled, profiles)

        extras["profile"] = {"enabled": profile_enabled(),
                             "programs": profiles().snapshot()}
    except Exception as e:  # profiling must never break the bench
        _log(f"profile snapshot failed: {e}")
    return extras


def _result_line(extras, headline_value, vs_baseline):
    return json.dumps({
        "metric": "transformer_lm_1024ctx_train_tokens_per_sec_per_chip",
        "value": headline_value,
        "unit": "tokens/sec",
        "vs_baseline": round(vs_baseline, 2) if vs_baseline == vs_baseline
        else None,
        "extras": _refresh_telemetry(extras),
    })


PARTIAL_PATH = "bench_partial.json"


def _flush_partial(extras, complete=False):
    """Persist the configs measured so far to a sidecar file after every
    config. The SIGTERM handler below cannot fire while the main thread
    is blocked inside a non-signal-aware PJRT/XLA call (the wedged-grant
    hang), so the sidecar — not the handler — is the durable record; the
    handler covers the kill-between-configs case on stdout."""
    try:
        with open(PARTIAL_PATH, "w") as f:
            json.dump({"complete": complete,
                       "extras": _refresh_telemetry(extras)}, f)
    except OSError as e:
        _log(f"partial flush failed: {e}")


def _install_partial_emitter(extras):
    """If the driver's timeout SIGTERMs the bench mid-run, emit the JSON
    line with every config measured so far instead of dying silently —
    a partial record beats no record (a round-4 kill mid-transformer
    lost all seven earlier configs). Restored to SIG_DFL before the
    successful final print so a late TERM can't append a second,
    contradictory line."""
    import signal

    def on_term(signum, frame):
        extras.setdefault(
            "error", f"bench terminated by signal {signum} before "
                     "completion; extras above are the configs that "
                     "finished")
        tf = extras.get("transformer_lm") or {}
        print(_result_line(extras, tf.get("tokens_per_sec"), float("nan")),
              flush=True)
        import os
        os._exit(1)

    try:
        signal.signal(signal.SIGTERM, on_term)
    except (ValueError, OSError):  # non-main thread / platform quirk
        pass


def _uninstall_partial_emitter():
    import signal

    try:
        signal.signal(signal.SIGTERM, signal.SIG_DFL)
    except (ValueError, OSError):
        pass


def main() -> None:
    import os

    # the bench IS the profiling run: capture every fused program's
    # cost/memory analysis + chunk-boundary HBM watermarks unless the
    # caller explicitly opted out (training entrypoints keep the
    # DL4J_PROFILE=0 default — the unwrapped bitwise program)
    os.environ.setdefault("DL4J_PROFILE", "1")
    _await_backend()
    extras = {"peak_tflops_bf16_per_chip": PEAK_TFLOPS_BF16,
              "chip": "TPU v5e (1 chip)"}
    _install_partial_emitter(extras)
    # seed the sidecar NOW: a stale bench_partial.json from a previous
    # run must never masquerade as this run's durable record (the
    # SIGTERM handler can't fire inside a wedged PJRT call)
    _flush_partial(extras)
    # BENCH_ONLY=transformer (or a comma list of section names) skips the
    # other sections — lets a brief tunnel-recovery window capture the
    # headline before the grant can wedge again. The transformer headline
    # ALWAYS runs (the driver's result line needs it); "transformer" is
    # accepted in the list to mean "just the headline".
    only = {s.strip() for s in os.environ.get("BENCH_ONLY", "").split(",")
            if s.strip()}
    sections = [("gemm", bench_gemm), ("mnist_mlp", bench_mlp),
                ("lenet5", bench_lenet),
                ("char_lstm", bench_char_lstm),
                ("word2vec", bench_word2vec),
                ("resnet18_cifar10", bench_resnet18),
                ("infeed", bench_infeed),
                ("eval", bench_eval),
                ("epoch", bench_epoch),
                ("dp_epoch", bench_dp_epoch),
                ("mesh_sweep", bench_mesh_sweep),
                ("serve", bench_serve),
                ("serve_fleet", bench_serve_fleet),
                ("guard", bench_guard),
                ("telemetry", bench_telemetry),
                ("flight", bench_flight)]
    if only:
        known = {n for n, _ in sections} | {"transformer"}
        unknown = sorted(only - known)
        if unknown:
            _log(f"BENCH_ONLY contains unknown section names {unknown} "
                 f"(known: {sorted(known)}) — they select nothing")
        skipped = [n for n, _ in sections if n not in only]
        sections = [(n, f) for n, f in sections if n in only]
        extras["bench_only"] = sorted(only)
        if skipped:
            _log(f"BENCH_ONLY={sorted(only)}: skipping {skipped}")
    try:
        for name, fn in sections:
            sp = None
            try:
                # the span stamps the section with tracer start/end
                # timestamps; an exception mid-section is recorded on it
                with _tracer().span(f"bench.{name}") as sp:
                    extras[name] = fn()
            except Exception as e:  # keep the bench robust to one bad config
                extras[name] = {"error": str(e)[:200]}
                _log(f"{name} FAILED: {e}")
            if sp is not None and isinstance(extras.get(name), dict):
                extras[name]["section_span"] = {
                    "start_s": round(sp.start_s, 3),
                    "end_s": round(sp.end_s, 3),
                    "wall_s": round(sp.duration_s, 3)}
            # flush on EVERY section outcome — success or exception —
            # so the sidecar is never more than one section stale
            _flush_partial(extras)

        try:
            def tf_progress(partial):
                extras["transformer_lm"] = partial
                _flush_partial(extras)

            with _tracer().span("bench.transformer") as tf_span:
                tf, vs_baseline = bench_transformer(on_progress=tf_progress)
            tf["section_span"] = {
                "start_s": round(tf_span.start_s, 3),
                "end_s": round(tf_span.end_s, 3),
                "wall_s": round(tf_span.duration_s, 3)}
            extras["transformer_lm"] = tf
            headline_value = tf.get("tokens_per_sec")
        except Exception as e:
            extras["transformer_lm"] = {"error": str(e)[:200]}
            _log(f"transformer FAILED: {e}")
            headline_value = None
            vs_baseline = float("nan")
    except BaseException as e:
        # anything that escapes the per-section nets (SystemExit,
        # KeyboardInterrupt, MemoryError) still leaves a durable record
        # with the timeline of what ran
        extras.setdefault("error",
                          f"bench aborted: {type(e).__name__}: {e}"[:300])
        _flush_partial(extras)
        raise

    _uninstall_partial_emitter()
    _flush_partial(extras, complete=True)
    print(_result_line(extras, headline_value, vs_baseline))


if __name__ == "__main__":
    main()
