"""Checked-in finding baseline: adopt a rule without a flag-day fix.

A baseline entry fingerprints a KNOWN finding so the CLI reports only
new ones. The fingerprint hashes (rule, path, enclosing symbol,
normalized source line) — deliberately NOT the line number, so edits
elsewhere in the file neither resurrect nor hide a baselined finding;
moving or rewording the offending line DOES invalidate its entry, which
is the desired pressure: touched code must come clean.

Policy for this tree (ISSUE 7): the shipped baseline stays EMPTY.
True positives get fixed; genuine exceptions get inline
``# dl4j-lint: disable=<rule> -- reason`` suppressions where the code
is, reviewable in the diff. The baseline mechanism exists for future
rule additions whose backlog cannot land in one PR.
"""

from __future__ import annotations

import functools
import hashlib
import json
import os
from typing import Dict, Iterable, List, Sequence, Tuple

from deeplearning4j_tpu.analysis.engine import Finding, REPO_ROOT

__all__ = [
    "DEFAULT_BASELINE",
    "fingerprint",
    "load_baseline",
    "save_baseline",
    "partition_findings",
]

DEFAULT_BASELINE = os.path.join(REPO_ROOT, ".dl4j-lint-baseline.json")
_VERSION = 1


@functools.lru_cache(maxsize=512)
def _read_lines(path: str, _stamp) -> Tuple[str, ...]:
    """``_stamp`` (mtime_ns, size) keys the cache so an edited file is
    re-read while fingerprinting many findings of one file costs one
    read, not one per finding."""
    try:
        with open(path, encoding="utf-8") as f:
            return tuple(f.read().splitlines())
    except OSError:
        return ()


def _line_text(finding: Finding, root: str) -> str:
    if finding.line < 1:  # parse-error findings anchor at line 0
        return ""
    path = os.path.join(root, finding.path)
    try:
        st = os.stat(path)
    except OSError:
        return ""
    lines = _read_lines(path, (st.st_mtime_ns, st.st_size))
    try:
        return lines[finding.line - 1].strip()
    except IndexError:
        return ""


def fingerprint(finding: Finding, root: str = REPO_ROOT) -> str:
    payload = "|".join((finding.rule, finding.path, finding.symbol,
                        _line_text(finding, root)))
    return hashlib.sha1(payload.encode("utf-8")).hexdigest()[:16]


def load_baseline(path: str = DEFAULT_BASELINE) -> Dict[str, dict]:
    """fingerprint -> entry; empty when the file is absent (the shipped
    state) or unreadable."""
    try:
        with open(path, encoding="utf-8") as f:
            data = json.load(f)
    except (OSError, ValueError):
        return {}
    if not isinstance(data, dict) or data.get("version") != _VERSION:
        return {}
    return {e["fingerprint"]: e for e in data.get("entries", [])
            if isinstance(e, dict) and "fingerprint" in e}


def save_baseline(findings: Sequence[Finding], path: str = DEFAULT_BASELINE,
                  root: str = REPO_ROOT,
                  preserve: Sequence[dict] = ()) -> int:
    """Snapshot ``findings`` as the new baseline; returns the entry count.
    ``preserve`` carries existing entries a narrowed run could not have
    re-found (other rules / unscanned paths) forward unchanged."""
    entries = []
    seen = set()
    for f in sorted(findings, key=lambda f: (f.path, f.line, f.rule)):
        fp = fingerprint(f, root)
        if fp in seen:
            continue
        seen.add(fp)
        entries.append({
            "fingerprint": fp,
            "rule": f.rule,
            "path": f.path,
            "symbol": f.symbol,
            "line": f.line,  # informational only; not part of the hash
            "text": _line_text(f, root),
        })
    for e in preserve:
        fp = e.get("fingerprint")
        if fp and fp not in seen:
            seen.add(fp)
            entries.append(e)
    payload = {"version": _VERSION, "entries": entries}
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(payload, fh, indent=2, sort_keys=True)
        fh.write("\n")
    return len(entries)


def partition_findings(findings: Iterable[Finding],
                       baseline: Dict[str, dict],
                       root: str = REPO_ROOT
                       ) -> Tuple[List[Finding], List[Finding]]:
    """(new, baselined) split of ``findings`` against ``baseline``."""
    new: List[Finding] = []
    old: List[Finding] = []
    for f in findings:
        (old if fingerprint(f, root) in baseline else new).append(f)
    return new, old
