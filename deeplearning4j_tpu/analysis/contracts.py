"""Program-contract checker: assert invariants of the cached fused programs.

dl4j-lint (analysis/rules.py) checks the SOURCE; this module checks the
PROGRAMS — the jaxpr and lowered StableHLO of every entry in a network's
``_epoch_steps`` cache, at test time, against the contract the whole
fused pipeline (PRs 3–6) silently relies on:

1. **No host callbacks.** ``pure_callback`` / ``io_callback`` /
   ``debug_callback`` primitives anywhere in the program would serialize
   E*N fused steps behind host round-trips (and break donation). The
   jaxpr must be free of them, recursively through scan/cond/pjit.
2. **Donation actually applied.** ``donate_argnums=(0, 1, 2)`` is a
   request, not a guarantee — XLA drops aliasing it cannot pair. Every
   params/updater/net-state leaf must carry an input-output alias
   (``tf.aliasing_output`` / ``jax.buffer_donor``) in the lowered module,
   or chunk k+1 doubles the training state's HBM footprint.
3. **Collectives stay on declared mesh axes.** Any ``psum``/
   ``all_gather``/... over an axis outside the declared set means the
   program grew a dependency on topology the caller never declared
   (single-device programs must contain none at all).
4. **Outputs match the program key.** The trip history is present iff
   the sentinel is compiled in; the ``[E, N, 4]`` metrics history iff
   telemetry is; shapes/dtypes as documented in ``_epoch_run_fn``.

``check_network_contracts(net, cache)`` runs all four against every
cached program; tier-1 wires it over FF/RNN/graph x {plain, accum,
guard, telemetry} in tests/test_analysis.py. Checks trace/lower with
``jax.ShapeDtypeStruct`` specs — no device execution, no donation of
real buffers.
"""

from __future__ import annotations

import re
from typing import Any, Dict, List, Optional, Sequence, Tuple

__all__ = [
    "ContractViolation",
    "CALLBACK_PRIMITIVES",
    "COLLECTIVE_PRIMITIVES",
    "callback_primitives",
    "collective_axes",
    "donated_arg_indices",
    "fused_program_specs",
    "check_fused_program",
    "check_network_contracts",
    "embedding_program_specs",
    "check_embedding_contracts",
]


class ContractViolation(AssertionError):
    """One or more fused-program contract checks failed."""

    def __init__(self, violations: Sequence[str]):
        self.violations = list(violations)
        super().__init__(
            "fused-program contract violated:\n  - "
            + "\n  - ".join(self.violations))


CALLBACK_PRIMITIVES = frozenset({
    "pure_callback", "io_callback", "debug_callback", "callback",
    "outside_call", "host_callback",
})
COLLECTIVE_PRIMITIVES = frozenset({
    "psum", "pmax", "pmin", "pmean", "ppermute", "pbroadcast",
    "all_gather", "all_to_all", "reduce_scatter", "axis_index",
    "pgather", "psum_scatter",
})


# ---------------------------------------------------------------------------
# jaxpr traversal
# ---------------------------------------------------------------------------


def _jax_core():
    """jax.extend.core moved ClosedJaxpr/Jaxpr out of jax.core (which
    deprecates them from 0.4.36 and drops them later); prefer the
    stable home, fall back for older jax."""
    try:
        from jax.extend import core as jcore
        jcore.ClosedJaxpr  # noqa: B018 — probe the moved symbol
    except (ImportError, AttributeError):
        import jax.core as jcore
    return jcore


def _iter_eqns(jaxpr):
    """Every equation in ``jaxpr``, recursing through call/control-flow
    sub-jaxprs (scan bodies, cond branches, pjit calls, shard_map...)."""
    jcore = _jax_core()

    seen = set()
    stack = [jaxpr]
    while stack:
        jx = stack.pop()
        if isinstance(jx, jcore.ClosedJaxpr):
            jx = jx.jaxpr
        if id(jx) in seen:
            continue
        seen.add(id(jx))
        for eqn in jx.eqns:
            yield eqn
            for val in eqn.params.values():
                stack.extend(_sub_jaxprs(val))


def _sub_jaxprs(val):
    jcore = _jax_core()

    if isinstance(val, (jcore.Jaxpr, jcore.ClosedJaxpr)):
        return [val]
    if isinstance(val, (list, tuple)):
        out = []
        for v in val:
            out.extend(_sub_jaxprs(v))
        return out
    return []


def callback_primitives(jaxpr) -> List[str]:
    """Names of host-callback primitives present in the program."""
    return sorted({eqn.primitive.name for eqn in _iter_eqns(jaxpr)
                   if eqn.primitive.name in CALLBACK_PRIMITIVES})


def collective_axes(jaxpr) -> Dict[str, List[str]]:
    """axis name -> sorted list of collective primitives using it."""
    out: Dict[str, set] = {}
    for eqn in _iter_eqns(jaxpr):
        if eqn.primitive.name not in COLLECTIVE_PRIMITIVES:
            continue
        axes: List[str] = []
        for key in ("axes", "axis_name", "axis"):
            val = eqn.params.get(key)
            if val is None:
                continue
            if isinstance(val, (tuple, list)):
                axes.extend(str(a) for a in val)
            else:
                axes.append(str(val))
        for ax in axes or ["<unnamed>"]:
            out.setdefault(ax, set()).add(eqn.primitive.name)
    return {ax: sorted(prims) for ax, prims in out.items()}


# ---------------------------------------------------------------------------
# lowered-module inspection (donation)
# ---------------------------------------------------------------------------

_ARG_HEAD_RE = re.compile(r"%arg(\d+):")
_DONOR_MARKERS = ("tf.aliasing_output", "jax.buffer_donor")


def donated_arg_indices(lowered_text: str) -> List[int]:
    """Flat argument indices carrying an input-output alias / donor mark
    in the lowered StableHLO's ``@main`` signature."""
    m = re.search(r"func\.func(?: public)? @main\((?P<sig>.*?)\)\s*->",
                  lowered_text, re.DOTALL)
    sig = m.group("sig") if m else lowered_text
    # Everything between one "%argN:" and the next belongs to argN —
    # including its attr dict. Scanning per-chunk (not regexing the attr
    # braces) survives nested/quoted braces like
    # ``mhlo.sharding = "{devices=[8,1]<=[8]}"`` on sharded programs.
    heads = list(_ARG_HEAD_RE.finditer(sig))
    out = []
    for i, am in enumerate(heads):
        end = heads[i + 1].start() if i + 1 < len(heads) else len(sig)
        chunk = sig[am.end():end]
        if any(marker in chunk for marker in _DONOR_MARKERS):
            out.append(int(am.group(1)))
    return sorted(set(out))


# ---------------------------------------------------------------------------
# spec construction + the checks
# ---------------------------------------------------------------------------


def _specs_of(tree):
    import jax
    import jax.numpy as jnp

    return jax.tree_util.tree_map(
        lambda a: jax.ShapeDtypeStruct(jnp.shape(a),
                                       jnp.result_type(a)), tree)


def _cache_fields(cache) -> Tuple[Any, Any, Any, Any]:
    """(features, labels, features_mask(s), labels_mask(s)) for either
    cache class — MLN's single arrays or CG's per-position tuples."""
    if hasattr(cache, "features_masks"):  # DeviceMultiDataSetCache
        return (cache.features, cache.labels, cache.features_masks,
                cache.labels_masks)
    return (cache.features, cache.labels, cache.features_mask,
            cache.labels_mask)


def fused_program_specs(net, cache, epochs: int = 2):
    """``jax.ShapeDtypeStruct`` argument specs matching the fused chunk
    program's signature ``(params, updater, net_state, iteration0,
    lr_scale_host, xs, ys, fms, lms, epoch_keys)`` for ``epochs``
    epochs over ``cache``."""
    import jax
    import jax.numpy as jnp

    xs, ys, fms, lms = _cache_fields(cache)
    rng = net._rng
    key_spec = jax.ShapeDtypeStruct((epochs,) + tuple(jnp.shape(rng)),
                                    jnp.result_type(rng))
    return (
        _specs_of(net.params),
        _specs_of(net.updater_state),
        _specs_of(net.net_state),
        jax.ShapeDtypeStruct((), jnp.int32),
        jax.ShapeDtypeStruct((), jnp.float32),
        _specs_of(xs),
        _specs_of(ys),
        None if fms is None else _specs_of(fms),
        _specs_of(lms),
        key_spec,
    )


def _trace_jaxpr(fn, specs):
    """ClosedJaxpr of a (possibly jitted) callable on spec args."""
    import jax

    trace = getattr(fn, "trace", None)
    if trace is not None:
        try:
            return trace(*specs).jaxpr
        except (AttributeError, TypeError):
            pass
    return jax.make_jaxpr(fn)(*specs)


def check_fused_program(fn, specs, *, guard: bool, stride: int,
                        epochs: int, n_batches: int,
                        n_state_leaves: Optional[int] = None,
                        allowed_axes: Sequence[str] = (),
                        expect_donation: bool = True) -> List[str]:
    """All contract checks against one fused program; returns violation
    strings (empty = contract holds)."""
    import jax

    violations: List[str] = []
    jaxpr = _trace_jaxpr(fn, specs)

    # 1. no host callbacks inside the program
    cbs = callback_primitives(jaxpr)
    if cbs:
        violations.append(
            f"host callback primitive(s) {cbs} inside the fused program "
            "— each fused step would round-trip to the host")

    # 2. collectives only over declared axes
    allowed = set(allowed_axes)
    for ax, prims in sorted(collective_axes(jaxpr).items()):
        if ax not in allowed:
            violations.append(
                f"collective(s) {prims} over undeclared mesh axis "
                f"'{ax}' (declared: {sorted(allowed) or 'none'})")

    # 3. donation applied to every params/updater/net-state leaf
    if expect_donation:
        if n_state_leaves is None:
            n_state_leaves = len(jax.tree_util.tree_leaves(specs[:3]))
        try:
            text = fn.lower(*specs).as_text()
        except Exception as exc:  # lowering failed — report, don't crash
            violations.append(f"could not lower program for donation "
                              f"check: {exc!r}")
        else:
            donated = set(donated_arg_indices(text))
            missing = [i for i in range(n_state_leaves)
                       if i not in donated]
            if missing:
                violations.append(
                    f"{len(missing)}/{n_state_leaves} training-state "
                    f"leaves lack an input-output alias (flat arg "
                    f"indices {missing[:8]}{'...' if len(missing) > 8 else ''}) "
                    "— donate_argnums was dropped and chunk k+1 doubles "
                    "the state footprint")

    # 4. outputs match the program key (trips iff guard, metrics iff
    #    stride, documented shapes)
    try:
        out = jax.eval_shape(fn, *specs)
    except Exception as exc:
        violations.append(f"could not eval_shape program: {exc!r}")
        return violations
    expected_len = 4 + (1 if guard else 0) + (1 if stride else 0)
    if not isinstance(out, tuple) or len(out) != expected_len:
        violations.append(
            f"program returns {len(out) if isinstance(out, tuple) else type(out).__name__} "
            f"outputs, contract says {expected_len} "
            f"(guard={guard}, metrics_stride={stride})")
        return violations
    hist = out[3]
    if tuple(hist.shape) != (epochs, n_batches):
        violations.append(
            f"loss history shape {tuple(hist.shape)} != "
            f"({epochs}, {n_batches})")
    if guard:
        trips = out[4]
        if tuple(trips.shape) != (epochs, n_batches):
            violations.append(
                f"sentinel trip history shape {tuple(trips.shape)} != "
                f"({epochs}, {n_batches})")
        if trips.dtype != jax.numpy.bool_:
            violations.append(
                f"sentinel trip history dtype {trips.dtype} != bool")
    if stride:
        mets = out[-1]
        if (len(mets.shape) != 3
                or tuple(mets.shape[:2]) != (epochs, n_batches)
                or mets.shape[2] != 4):
            violations.append(
                f"metrics history shape {tuple(mets.shape)} != "
                f"({epochs}, {n_batches}, 4)")
    # state pytrees must round-trip (donor pairing relies on it)
    in_def = jax.tree_util.tree_structure(specs[:3])
    out_def = jax.tree_util.tree_structure(out[:3])
    if in_def != out_def:
        violations.append(
            "params/updater/net-state output pytree structure differs "
            "from the input structure — donation cannot pair buffers")
    return violations


def embedding_program_specs(w2v, cache, epochs: int = 2):
    """``jax.ShapeDtypeStruct`` argument specs for the fused skip-gram
    chunk program (``nlp/epoch_kernels.make_skipgram_chunk``):
    ``(syn0, syn1neg, it0, lr0, min_lr, planned, tokens, mask,
    keep_prob, table, epoch_keys[E])``."""
    import jax
    import jax.numpy as jnp

    key = jax.random.PRNGKey(0)
    key_spec = jax.ShapeDtypeStruct((epochs,) + tuple(jnp.shape(key)),
                                    jnp.result_type(key))
    scalar = jax.ShapeDtypeStruct((), jnp.float32)
    return (
        _specs_of(w2v.syn0),
        _specs_of(w2v.syn1neg),
        scalar, scalar, scalar, scalar,
        _specs_of(cache.tokens),
        _specs_of(cache.mask),
        _specs_of(cache.keep_prob),
        _specs_of(cache.table),
        key_spec,
    )


def check_embedding_contracts(w2v, cache, *, epochs: int = 2,
                              allowed_axes: Optional[Sequence[str]] = None,
                              raise_on_violation: bool = True
                              ) -> Dict[Tuple, List[str]]:
    """Contract-check every cached fused skip-gram program on a
    ``Word2Vec``/``DistributedWord2Vec`` (``_epoch_steps``, populated by
    ``fit_epochs``): no host callbacks, collectives only over axes the
    table registry declared (or the cache mesh's axes when the tables
    were never registered; none at all single-device), donation applied
    to both tables, outputs ``(syn0, syn1neg, hist[E, n_batches])``.
    Empty ``_epoch_steps`` raises ValueError — a vacuous pass must never
    look like a checked one."""
    import jax

    programs = getattr(w2v, "_epoch_steps", None) or {}
    if not programs:
        raise ValueError(
            "no cached fused skip-gram programs on %r (_epoch_steps is "
            "empty) — run fit_epochs first" % type(w2v).__name__)
    if allowed_axes is None:
        registry = getattr(w2v, "_sharding_registry", None)
        if registry is not None:
            allowed_axes = tuple(sorted(registry.declared_axes))
        elif getattr(cache, "mesh", None) is not None:
            allowed_axes = tuple(cache.mesh.axis_names)
        else:
            allowed_axes = ()
    specs = embedding_program_specs(w2v, cache, epochs)
    results: Dict[Tuple, List[str]] = {}
    for key, fn in sorted(programs.items(), key=repr):
        violations: List[str] = []
        jaxpr = _trace_jaxpr(fn, specs)
        cbs = callback_primitives(jaxpr)
        if cbs:
            violations.append(
                f"host callback primitive(s) {cbs} inside the fused "
                "skip-gram program")
        allowed = set(allowed_axes)
        for ax, prims in sorted(collective_axes(jaxpr).items()):
            if ax not in allowed:
                violations.append(
                    f"collective(s) {prims} over undeclared mesh axis "
                    f"'{ax}' (declared: {sorted(allowed) or 'none'})")
        try:
            text = fn.lower(*specs).as_text()
        except Exception as exc:
            violations.append(
                f"could not lower program for donation check: {exc!r}")
        else:
            donated = set(donated_arg_indices(text))
            missing = [i for i in (0, 1) if i not in donated]
            if missing:
                violations.append(
                    f"table arg(s) {missing} lack an input-output alias "
                    "— donation was dropped and each chunk doubles the "
                    "tables' HBM footprint")
        try:
            out = jax.eval_shape(fn, *specs)
        except Exception as exc:
            violations.append(f"could not eval_shape program: {exc!r}")
            out = None
        if out is not None:
            if not isinstance(out, tuple) or len(out) != 3:
                violations.append(
                    "program must return (syn0, syn1neg, hist), got "
                    f"{len(out) if isinstance(out, tuple) else type(out).__name__}")
            else:
                for i, (o, ref) in enumerate(zip(out[:2],
                                                 (w2v.syn0, w2v.syn1neg))):
                    if tuple(o.shape) != tuple(ref.shape):
                        violations.append(
                            f"output {i} shape {tuple(o.shape)} != table "
                            f"shape {tuple(ref.shape)}")
                hist = out[2]
                if tuple(hist.shape) != (epochs, cache.n_batches):
                    violations.append(
                        f"loss history shape {tuple(hist.shape)} != "
                        f"({epochs}, {cache.n_batches})")
        results[key] = [f"program {key}: {v}" for v in violations]
    flat = [v for vs in results.values() for v in vs]
    if flat and raise_on_violation:
        raise ContractViolation(flat)
    return results


def check_network_contracts(net, cache, *, epochs: int = 2,
                            allowed_axes: Optional[Sequence[str]] = None,
                            expect_donation: bool = True,
                            raise_on_violation: bool = True,
                            require_programs: bool = True,
                            registry=None
                            ) -> Dict[Tuple, List[str]]:
    """Contract-check EVERY cached fused program on ``net`` (a network or
    a ``ParallelWrapper`` — the wrapper's SPMD programs cache on the
    wrapper itself, keyed identically ``(shuffle, K, guard, stride)``).
    Returns {program key: violations}; raises :class:`ContractViolation`
    listing every violation unless ``raise_on_violation=False``. An empty
    or missing ``_epoch_steps`` cache raises :class:`ValueError` unless
    ``require_programs=False`` — a vacuous pass must never look like a
    checked one.

    The declared-axes set for check 3 resolves, in order: explicit
    ``allowed_axes=``; ``registry=`` (a ``ShardingRegistry``); the
    registry the last registry-driven placement stamped on the network
    (``net._sharding_registry`` — TP/PP programs may then ONLY reduce
    over axes the registry actually mapped something to, a strictly
    tighter set than the mesh's axis names); finally every axis of the
    net/cache mesh."""
    network = getattr(net, "network", net)
    programs = getattr(net, "_epoch_steps", None) or {}
    if not programs and require_programs:
        raise ValueError(
            "no cached fused programs on %r (_epoch_steps is empty or "
            "missing) — run fit_epochs first, or pass "
            "require_programs=False to accept an empty check"
            % type(net).__name__)
    if allowed_axes is None:
        if registry is None:
            registry = (getattr(net, "_registry", None)
                        or getattr(network, "_sharding_registry", None))
        if registry is not None:
            allowed_axes = tuple(sorted(registry.declared_axes))
        else:
            mesh = (getattr(net, "mesh", None)
                    or getattr(cache, "mesh", None))
            allowed_axes = tuple(mesh.axis_names) if mesh is not None else ()
    specs = fused_program_specs(network, cache, epochs) if programs else None
    results: Dict[Tuple, List[str]] = {}
    for key, fn in sorted(programs.items(), key=repr):
        shuffle, accum, guard, stride = key
        results[key] = [
            f"program {key}: {v}" for v in check_fused_program(
                fn, specs, guard=bool(guard), stride=int(stride),
                epochs=epochs, n_batches=cache.n_batches,
                allowed_axes=allowed_axes,
                expect_donation=expect_donation)]
    flat = [v for vs in results.values() for v in vs]
    if flat and raise_on_violation:
        raise ContractViolation(flat)
    return results
