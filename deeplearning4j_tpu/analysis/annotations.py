"""Hot-path annotations consumed by dl4j-lint (stdlib-only, zero cost).

The fused training pipeline stakes correctness on a contract no test
states directly: code reachable from a traced/jitted hot path must never
touch the host (``float()``, ``.item()``, ``np.asarray``,
``jax.device_get``, ``block_until_ready``) — one such call inside the
whole-epoch program either breaks tracing outright or, worse, silently
serializes E*N fused steps behind a device sync.

``@traced`` marks a function as part of that surface.  It is a pure
marker: the decorator returns the function unchanged (so it composes
with ``jax.jit``, ``functools.cached_property`` and friends) and only
sets ``__dl4j_traced__`` for runtime introspection.  The static analyzer
(``analysis/rules.py``) does not import the code at all — it matches the
decorator *name* in the AST — so ``@traced`` works equally on code that
cannot import (fixture snippets, gated backends).

``HOT_PATH_REGISTRY`` is the second prong: function names that are hot
by convention, so pre-annotation code (and code we must not churn) is
covered without edits.  Names are matched bare, module-independent —
every ``_step_impl`` in the tree is a hot root, which is exactly right
for the MLN/CG twin implementations.
"""

from __future__ import annotations

from typing import Callable, TypeVar

F = TypeVar("F", bound=Callable)

__all__ = ["traced", "HOT_PATH_REGISTRY"]


def traced(fn: F) -> F:
    """Mark ``fn`` as running under ``jax.jit``/``lax.scan`` tracing (a
    hot root for dl4j-lint's host-sync rule). Identity at runtime."""
    fn.__dl4j_traced__ = True
    return fn


# Functions that are hot roots by NAME, wherever they are defined — the
# fused-step twins on MultiLayerNetwork/ComputationGraph, the chunk
# program factory (its nested ``run`` is hot by containment), the
# device_eval kernels, and the traced helpers they lean on. Keep this
# list in sync with docs/static_analysis.md.
#
# profile-readback note: profile collection (monitor/profile
# ``capture_program_profile``, monitor/memory ``sample_hbm_watermark``
# and friends) is a host readback and is only permitted at CHUNK
# BOUNDARIES — between fused dispatches, where drive_epoch_chunks calls
# it. The host-sync rule flags any ``PROFILE_READBACK_CALLS`` name
# (analysis/rules.py) reachable from these roots, exactly like float().
# The same contract covers the run-ledger boundary marks and flight-
# recorder writes (``LEDGER_FLIGHT_CALLS``: ledger_run_start/
# ledger_chunk_start/ledger_chunk_done/ledger_run_end/flight_record) —
# chunk-boundary-only, never inside a traced program.
HOT_PATH_REGISTRY = frozenset({
    # nn/multilayer.py + nn/graph.py fused-step surface
    "_step_impl",
    "_accum_step_impl",
    "_guarded_step_impl",
    "_telemetry_step_impl",
    "_loss_grads",
    "_accum_loss_grads",
    "_epoch_run_fn",
    # perf/epoch_cache.py — runs traced inside the chunk program
    "epoch_schedule",
    # perf/device_eval.py kernels (jitted inside the eval step)
    "confusion_update",
    "regression_update",
    "_flatten_time",
    # monitor/pack.py + resilience/guard.py traced helpers
    "step_metrics",
    "tree_global_norm",
    "tree_all_finite",
    # serving/engine.py — the decode server's jitted program bodies (a
    # host sync here would serialize every online token behind a device
    # readback; the serve loop's ONE sanctioned readback is the
    # per-dispatch token block in serving/server.py, outside these
    # roots). The fast-path roots: the K-step fused scan, the shared
    # one-step forward it scans, and the speculative draft-round /
    # multi-token-verify bodies.
    "_serve_prefill_impl",
    "_serve_decode_impl",
    "_serve_decode_fused_impl",
    "_serve_spec_impl",
    "_serve_verify_impl",
    "_decode_step_body",
    # serving/fleet/handoff.py — the prefill/decode-split slot movers:
    # pure gather/scatter programs over the pool. The handoff's host
    # readback is once-per-request at the prefill boundary (outside
    # these bodies, in export_slot) — a sync INSIDE them would ride
    # along into every compiled decode-pool program that reuses them.
    "_slot_export_impl",
    "_slot_import_impl",
    # nlp/epoch_kernels.py + nlp/glove.py — the fused embedding programs:
    # in-program pair generation, the masked segment-sum NEG updater, the
    # whole-chunk scan body, and GloVe's fused AdaGrad epoch scan. The
    # chunk DRIVER (drive_skipgram_chunks) is the host boundary — its
    # ledger/heartbeat readbacks must never be reachable from these.
    "skipgram_pair_plan",
    "skipgram_negatives",
    "skipgram_epoch_plan",
    "_neg_epoch_impl",
    "_w2v_chunk_impl",
    "_glove_epoch_impl",
})
