"""Static analysis for the fused-training contract (ISSUE 7).

Two prongs, one import-light package (stdlib at import time — jax loads
lazily inside the contract checker only, so CLI/CI shells and pre-jax
entry points can import this freely):

- **dl4j-lint** (``engine``/``rules``/``baseline``): an AST rule engine
  with inline suppressions and a checked-in baseline, shipping the
  ruleset that machine-checks what PRs 3–6 only documented — no host
  syncs in hot paths, hashable program-cache keys, single-use RNG keys,
  locked cross-thread mutation, no reads after donation, registry-backed
  counters, audited pytest markers. CLI: ``scripts/dl4j_lint.py``;
  gate: ``scripts/verify.sh --lint``.
- **program contracts** (``contracts``): jaxpr/StableHLO inspection of
  every cached fused program — callback-free, donation applied,
  collectives on declared axes, outputs matching the program key —
  wired into tier-1 via tests/test_analysis.py.

See docs/static_analysis.md for the rule catalog and workflows.
"""

from deeplearning4j_tpu.analysis.annotations import (  # noqa: F401
    HOT_PATH_REGISTRY,
    traced,
)

__all__ = [
    "HOT_PATH_REGISTRY",
    "traced",
    "Finding",
    "LintConfig",
    "run_lint",
    "check_network_contracts",
    "ContractViolation",
]

# PEP 562: only the 4-line annotations marker loads eagerly — the
# production modules that import @traced must not pay for the lint
# engine (ast/tokenize), and contracts must not pull jax
_LAZY = {
    "Finding": "engine", "LintConfig": "engine", "run_lint": "engine",
    "check_network_contracts": "contracts",
    "ContractViolation": "contracts",
}


def __getattr__(name):
    mod = _LAZY.get(name)
    if mod is not None:
        import importlib

        return getattr(importlib.import_module(
            f"deeplearning4j_tpu.analysis.{mod}"), name)
    raise AttributeError(name)
