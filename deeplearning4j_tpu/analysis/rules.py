"""The dl4j-lint ruleset: machine checks for the fused-pipeline contract.

Every rule here states an invariant PRs 3–6 rely on but no test asserts
directly (see docs/static_analysis.md for the catalog with rationale):

- ``host-sync-in-hot-path``   — no ``float()``/``.item()``/``np.asarray``/
  ``jax.device_get``/``block_until_ready`` reachable from a traced hot
  root (``@traced`` or ``HOT_PATH_REGISTRY``).
- ``recompile-hazard``        — no unhashable / object-typed values in
  jit program-cache keys (``_epoch_steps`` and friends).
- ``rng-reuse``               — no ``jax.random`` key consumed twice
  without an intervening split/reassignment.
- ``lock-discipline``         — no attribute mutated from more than one
  thread entry point without a common lock.
- ``donation-consistency``    — no read of an argument after it was
  donated to a jitted call (``donate_argnums``).
- ``bare-counter``            — no ad-hoc ``self._*_counter`` attributes
  outside ``monitor/`` (absorbed from scripts/lint_telemetry.py).
- ``marker-audit``            — chaos-behavior tests carry the ``chaos``
  marker; slow sleeps carry ``slow``; only registered markers are used.

Rules are AST heuristics scoped to this codebase's idioms — module-local
call graphs, bare-name hot registries — tuned so the shipped tree is
clean and every seeded violation in tests/test_analysis.py is caught.
They do not execute or import the code under analysis.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Sequence, Set, Tuple

from deeplearning4j_tpu.analysis.annotations import HOT_PATH_REGISTRY
from deeplearning4j_tpu.analysis.engine import (
    Finding,
    LintConfig,
    Module,
    Rule,
)

__all__ = ["ALL_RULES"]


# ---------------------------------------------------------------------------
# shared AST helpers
# ---------------------------------------------------------------------------


def dotted(node: ast.AST) -> str:
    """Dotted name of a Name/Attribute chain ('' when not a plain chain)."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def iter_defs(tree: ast.AST):
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


def own_body_walk(fn: ast.AST):
    """Walk ``fn``'s body WITHOUT descending into nested def/class bodies
    (nested defs are separate call-graph nodes)."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        yield node
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef, ast.Lambda)):
            stack.extend(ast.iter_child_nodes(node))


def has_decorator(fn, *names: str) -> bool:
    for dec in getattr(fn, "decorator_list", []):
        target = dec.func if isinstance(dec, ast.Call) else dec
        d = dotted(target)
        if d and (d in names or d.split(".")[-1] in names):
            return True
    return False


# ---------------------------------------------------------------------------
# linear statement walker (order-aware rules: rng-reuse, donation)
# ---------------------------------------------------------------------------


_MATCH = getattr(ast, "Match", None)  # 3.10+


class SeqWalker:
    """Statement-order walk of one function body. If branches are
    analyzed from a common snapshot and merged — a branch that
    TERMINATES (return/raise/break/continue) does not pollute the
    fall-through state, so mutually-exclusive ``if c: return use(key)``
    chains are not double-counted. Loop bodies are processed TWICE so
    state poisoned on iteration k is seen by reads on iteration k+1
    (the cross-iteration reuse/donation hazard class). Expressions are
    visited post-order (children first), matching evaluation order:
    a call's arguments are read BEFORE the call's effects apply."""

    def walk_function(self, fn) -> None:
        self.walk_body(fn.body)

    def walk_body(self, body: Sequence[ast.stmt]) -> bool:
        """Returns True when the body terminates control flow."""
        for stmt in body:
            if self.walk_stmt(stmt):
                return True
        return False

    def walk_stmt(self, stmt: ast.stmt) -> bool:
        if isinstance(stmt, (ast.Return, ast.Raise)):
            for child in ast.iter_child_nodes(stmt):
                if isinstance(child, ast.expr):
                    self.visit_expr(child)
            return True
        if isinstance(stmt, (ast.Break, ast.Continue)):
            return True
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            self.visit_expr(stmt.iter)
            for _ in range(2):
                self.on_bind_target(stmt.target)
                if self.walk_body(stmt.body):
                    break
            self.walk_body(stmt.orelse)
        elif isinstance(stmt, ast.While):
            self.visit_expr(stmt.test)
            for _ in range(2):
                if self.walk_body(stmt.body):
                    break
            self.walk_body(stmt.orelse)
        elif isinstance(stmt, ast.If):
            self.visit_expr(stmt.test)
            snap = self.snapshot()
            then_terminates = self.walk_body(stmt.body)
            then_state = self.snapshot()
            self.restore(snap)
            else_terminates = self.walk_body(stmt.orelse)
            if then_terminates and else_terminates:
                return True
            if else_terminates:
                self.restore(then_state)  # fall-through = then only
            elif not then_terminates:
                self.merge(then_state)
            # then_terminates alone: fall-through = else state (current)
        elif isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                self.visit_expr(item.context_expr)
                if item.optional_vars is not None:
                    self.on_bind_target(item.optional_vars)
            return self.walk_body(stmt.body)
        elif isinstance(stmt, ast.Try):
            # body and handlers are mutually exclusive paths: handlers
            # run from the PRE-try snapshot (like If branches), so a
            # try/except consumer fallback is not double-counted
            snap = self.snapshot()
            self.walk_body(stmt.body)
            body_state = self.snapshot()
            handler_states = []
            for handler in stmt.handlers:
                self.restore(snap)
                if not self.walk_body(handler.body):
                    handler_states.append(self.snapshot())
            self.restore(body_state)
            self.walk_body(stmt.orelse)
            for state in handler_states:
                self.merge(state)
            return self.walk_body(stmt.finalbody)
        elif isinstance(stmt, ast.Assign):
            self.visit_expr(stmt.value)
            for t in stmt.targets:
                self.on_bind_target(t, value=stmt.value)
        elif isinstance(stmt, ast.AnnAssign):
            if stmt.value is not None:
                self.visit_expr(stmt.value)
                self.on_bind_target(stmt.target, value=stmt.value)
        elif isinstance(stmt, ast.AugAssign):
            self.visit_expr(stmt.value)
            self.visit_expr(stmt.target)
            self.on_bind_target(stmt.target, value=stmt.value)
        elif isinstance(stmt, ast.Expr):
            self.visit_expr(stmt.value)
        elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.ClassDef)):
            self.on_nested_def(stmt)
        elif _MATCH is not None and isinstance(stmt, _MATCH):
            # cases are mutually exclusive branches (like If); no case
            # may match at all, so the pre-match state is the base and
            # every non-terminating case merges into it
            self.visit_expr(stmt.subject)
            snap = self.snapshot()
            case_states = []
            for case in stmt.cases:
                self.restore(snap)
                if case.guard is not None:
                    self.visit_expr(case.guard)
                if not self.walk_body(case.body):
                    case_states.append(self.snapshot())
            self.restore(snap)
            for state in case_states:
                self.merge(state)
        elif isinstance(stmt, (ast.Assert, ast.Delete)):
            for child in ast.iter_child_nodes(stmt):
                if isinstance(child, ast.expr):
                    self.visit_expr(child)
        # pass/import/global/nonlocal: no expr state
        return False

    # -- hooks -----------------------------------------------------------

    def visit_expr(self, expr: Optional[ast.expr]) -> None:
        if expr is None:
            return
        self._visit_ordered(expr)

    def _visit_ordered(self, node: ast.AST) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda, ast.ClassDef)):
            return  # separate scope, walked as its own function
        for child in ast.iter_child_nodes(node):
            self._visit_ordered(child)
        self.on_node(node)

    def on_node(self, node: ast.AST) -> None:
        raise NotImplementedError

    def on_bind_target(self, target: ast.expr, value=None) -> None:
        raise NotImplementedError

    def on_nested_def(self, stmt) -> None:
        pass

    def snapshot(self):
        raise NotImplementedError

    def restore(self, state) -> None:
        raise NotImplementedError

    def merge(self, other_state) -> None:
        raise NotImplementedError


def bound_names(target: ast.expr):
    """(names, attr_dotteds) bound by an assignment target."""
    names: List[str] = []
    attrs: List[str] = []
    for node in ast.walk(target):
        if isinstance(node, ast.Name):
            names.append(node.id)
        elif isinstance(node, ast.Attribute):
            d = dotted(node)
            if d:
                attrs.append(d)
    return names, attrs


# ---------------------------------------------------------------------------
# host-sync-in-hot-path
# ---------------------------------------------------------------------------

SYNC_CALL_NAMES = {
    "np.asarray", "numpy.asarray", "np.array", "numpy.array",
    "jax.device_get", "onp.asarray",
}
SYNC_ATTR_CALLS = {"item", "block_until_ready", "tolist"}

# profile-readback: the monitor/profile + monitor/memory collection
# entry points (compile introspection, device memory_stats, live-array
# walks) are host readbacks by design and are only permitted at CHUNK
# BOUNDARIES — drive_epoch_chunks calls them between dispatches. Any of
# these reachable from a hot root would serialize the fused program
# behind a host sync, so the host-sync rule flags them like float().
PROFILE_READBACK_CALLS = {
    "capture_program_profile",
    "sample_hbm_watermark",
    "validate_cache_budget",
    "cache_resident_bytes",
    "live_array_bytes",
}

# ledger/flight collection: the run-ledger boundary marks and flight-
# recorder writes (monitor/ledger, monitor/flight) are host-side
# control-plane calls permitted ONLY at chunk boundaries — the same
# contract as the profile readbacks. One of these traced into a fused
# program would compile a host callback (or a spurious constant) into
# E*N steps.
LEDGER_FLIGHT_CALLS = {
    "ledger_run_start",
    "ledger_chunk_start",
    "ledger_chunk_done",
    "ledger_run_end",
    "flight_record",
}


def hot_functions(module: Module) -> Set[ast.AST]:
    """The module's HOT function/lambda scopes: ``@traced`` defs and
    ``HOT_PATH_REGISTRY`` names, closed over the module-local call graph
    (bare callee names) and containment edges (nested defs AND lambdas
    run inside their parent's trace). Shared by the host-sync and
    implicit-f32-promotion rules so "inside a traced hot path" means
    the same thing to both."""
    defs = list(iter_defs(module.tree))
    by_name: Dict[str, List[ast.AST]] = {}
    for fn in defs:
        by_name.setdefault(fn.name, []).append(fn)

    scopes = defs + [n for n in ast.walk(module.tree)
                     if isinstance(n, ast.Lambda)]
    callees: Dict[ast.AST, Set[str]] = {}
    children: Dict[ast.AST, List[ast.AST]] = {}
    for fn in scopes:
        names: Set[str] = set()
        for node in own_body_walk(fn):
            if isinstance(node, ast.Call):
                d = dotted(node.func)
                if d:
                    names.add(d.split(".")[-1])
            elif isinstance(node, (ast.FunctionDef,
                                   ast.AsyncFunctionDef,
                                   ast.Lambda)):
                children.setdefault(fn, []).append(node)
        callees[fn] = names

    hot: Set[ast.AST] = set()
    work = [fn for fn in defs
            if fn.name in HOT_PATH_REGISTRY
            or has_decorator(fn, "traced")]
    while work:
        fn = work.pop()
        if fn in hot:
            continue
        hot.add(fn)
        work.extend(children.get(fn, []))
        for callee_name in callees.get(fn, ()):
            for target in by_name.get(callee_name, ()):
                if target not in hot:
                    work.append(target)
    return hot


class HostSyncRule(Rule):
    id = "host-sync-in-hot-path"
    doc = ("host-synchronizing call (float()/.item()/np.asarray/"
           "jax.device_get/block_until_ready/.tolist, a "
           "profile-readback like sample_hbm_watermark/"
           "capture_program_profile, or a ledger/flight collection "
           "call like ledger_chunk_done/flight_record — "
           "chunk-boundary-only by contract) reachable from a @traced "
           "function or a HOT_PATH_REGISTRY root")

    def check(self, module: Module, config: LintConfig) -> List[Finding]:
        hot = hot_functions(module)

        out: List[Finding] = []
        for fn in hot:
            for node in own_body_walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                msg = None
                d = dotted(node.func)
                if (isinstance(node.func, ast.Name)
                        and node.func.id == "float"
                        and not self._host_scalar_arg(node)):
                    msg = ("float() forces a device->host sync on traced "
                           "values")
                elif d in SYNC_CALL_NAMES:
                    msg = f"{d}() materializes device data on the host"
                elif (isinstance(node.func, ast.Attribute)
                      and node.func.attr in SYNC_ATTR_CALLS):
                    msg = (f".{node.func.attr}() forces a device->host "
                           "sync")
                elif (d and d.split(".")[-1] in PROFILE_READBACK_CALLS):
                    msg = (f"{d}() is a profile-readback (compile "
                           "introspection / device memory_stats) — "
                           "profile collection is only permitted at "
                           "chunk boundaries, never")
                elif (d and d.split(".")[-1] in LEDGER_FLIGHT_CALLS):
                    msg = (f"{d}() is a run-ledger/flight-recorder "
                           "collection call — ledger transitions and "
                           "flight records are only permitted at chunk "
                           "boundaries, never")
                if msg:
                    scope = getattr(fn, "name", "<lambda>")
                    self.emit(out, module, node,
                              f"{msg} inside hot path '{scope}' "
                              "(reachable from a traced root)")
        return out

    @staticmethod
    def _host_scalar_arg(call: ast.Call) -> bool:
        """float(len(...)) / float(<literal>) convert host scalars, not
        traced values — never a device sync."""
        if len(call.args) != 1:
            return False
        arg = call.args[0]
        if isinstance(arg, ast.Constant):
            return True
        return (isinstance(arg, ast.Call)
                and isinstance(arg.func, ast.Name)
                and arg.func.id == "len")


# ---------------------------------------------------------------------------
# implicit-f32-promotion
# ---------------------------------------------------------------------------

# contraction entry points whose operand dtype decides the MXU rate
MATMUL_CALL_NAMES = {"einsum", "matmul", "dot", "dot_general",
                     "tensordot"}
# wrappers that make the operand's dtype EXPLICIT (the policy casts, a
# direct astype, or the master-weights per-step copy)
CAST_CALL_NAMES = {"cast_compute", "cast_param", "cast_output", "astype",
                   "asarray", "compute_copy"}


class ImplicitF32PromotionRule(Rule):
    id = "implicit-f32-promotion"
    doc = ("matmul/einsum operand inside a traced hot path reaches a "
           "param leaf (a string-keyed subscript like params['W'] / "
           "blk['attn']['wq'], or a name bound from one) without "
           "passing through policy.cast_compute — under the bf16 "
           "policy the f32 leaf silently promotes the whole "
           "contraction to f32 MXU rate (the transformer "
           "residual-stream bug class)")

    def check(self, module: Module, config: LintConfig) -> List[Finding]:
        out: List[Finding] = []
        for fn in hot_functions(module):
            param_names = self._param_bound_names(fn)
            for node in own_body_walk(fn):
                operands = self._matmul_operands(node)
                for op in operands:
                    leaf = self._uncast_param_ref(op, param_names)
                    if leaf is None:
                        continue
                    scope = getattr(fn, "name", "<lambda>")
                    self.emit(
                        out, module, node,
                        f"matmul operand '{leaf}' reaches a param leaf "
                        "without policy.cast_compute inside hot path "
                        f"'{scope}' — an f32 leaf here promotes the "
                        "contraction off the bf16 MXU rate")
        return out

    @staticmethod
    def _matmul_operands(node: ast.AST) -> List[ast.expr]:
        if isinstance(node, ast.BinOp) and isinstance(node.op,
                                                      ast.MatMult):
            return [node.left, node.right]
        if isinstance(node, ast.Call):
            d = dotted(node.func)
            if d and d.split(".")[-1] in MATMUL_CALL_NAMES:
                # skip einsum specs / dimension-number tuples — only
                # array-shaped operands carry a dtype
                return [a for a in node.args
                        if not isinstance(a, (ast.Constant, ast.Tuple))]
        return []

    @classmethod
    def _param_bound_names(cls, fn: ast.AST) -> Set[str]:
        """Names bound (flow-insensitively) from a param-leaf expression
        within ``fn`` — one level of propagation, enough for the
        ``w = blk['attn']['wq']; x @ w`` idiom. A name REbound through a
        cast call does not count."""
        param: Set[str] = set()
        cast: Set[str] = set()
        for node in own_body_walk(fn):
            if not isinstance(node, ast.Assign):
                continue
            for target in node.targets:
                if not isinstance(target, ast.Name):
                    continue
                if cls._is_cast_call(node.value):
                    cast.add(target.id)
                elif cls._is_param_subscript(node.value):
                    param.add(target.id)
        return param - cast

    @staticmethod
    def _is_cast_call(expr: ast.AST) -> bool:
        if not isinstance(expr, ast.Call):
            return False
        d = dotted(expr.func)
        return bool(d) and d.split(".")[-1] in CAST_CALL_NAMES

    @staticmethod
    def _is_param_subscript(expr: ast.AST) -> bool:
        """String-keyed subscript — the pytree-leaf access idiom
        (``params['embed']``, ``blk['mlp']['w1']``). Integer/variable
        indexing (batch gathers like ``xs[i]``) is data, not params."""
        return (isinstance(expr, ast.Subscript)
                and isinstance(expr.slice, ast.Constant)
                and isinstance(expr.slice.value, str))

    @classmethod
    def _uncast_param_ref(cls, expr: ast.AST,
                          param_names: Set[str]) -> Optional[str]:
        """The offending source text when ``expr`` reaches a param leaf
        with no cast wrapper on the path; None when clean."""
        if cls._is_cast_call(expr):
            return None
        if cls._is_param_subscript(expr):
            return ast.unparse(expr) if hasattr(ast, "unparse") else "?"
        if isinstance(expr, ast.Name) and expr.id in param_names:
            return expr.id
        # unwrap transparent transforms (reshape/transpose/indexing/
        # unary) — a reshape does not change the operand's dtype
        if isinstance(expr, ast.Call):
            d = dotted(expr.func)
            attr = d.split(".")[-1] if d else ""
            if attr in ("reshape", "transpose", "ravel", "squeeze"):
                base = (expr.func.value
                        if isinstance(expr.func, ast.Attribute) else None)
                if base is not None:
                    return cls._uncast_param_ref(base, param_names)
            return None  # any other call decides its own dtype
        if isinstance(expr, ast.Subscript):
            return cls._uncast_param_ref(expr.value, param_names)
        if isinstance(expr, ast.UnaryOp):
            return cls._uncast_param_ref(expr.operand, param_names)
        return None


# ---------------------------------------------------------------------------
# recompile-hazard
# ---------------------------------------------------------------------------

CACHE_ATTR_RE = re.compile(r"^_\w*(steps|cache|programs?|jits?)\w*$")
UNHASHABLE_CTORS = {
    "list", "dict", "set", "bytearray",
    "np.asarray", "np.array", "numpy.asarray", "numpy.array",
    "jnp.asarray", "jnp.array",
}


class RecompileHazardRule(Rule):
    id = "recompile-hazard"
    doc = ("unhashable or object-typed value flowing into a jit "
           "program-cache key (_epoch_steps and friends): every lookup "
           "misses, every call recompiles")

    def check(self, module: Module, config: LintConfig) -> List[Finding]:
        out: List[Finding] = []
        for fn in iter_defs(module.tree):
            # Name -> [(lineno, value)] assignments within this fn; a use
            # resolves to the LATEST assignment at or before its line, so
            # `key = list(d); key = tuple(key)` is clean at a later use
            # and `key = (a, b); key = list(key)` is caught
            assigns: Dict[str, List[Tuple[int, ast.expr]]] = {}
            for node in own_body_walk(fn):
                if isinstance(node, ast.Assign) and len(node.targets) == 1:
                    t = node.targets[0]
                    if isinstance(t, ast.Name):
                        assigns.setdefault(t.id, []).append(
                            (node.lineno, node.value))
            for node in own_body_walk(fn):
                key_expr = cache_name = None
                if (isinstance(node, ast.Subscript)
                        and isinstance(node.value, ast.Attribute)
                        and CACHE_ATTR_RE.match(node.value.attr)):
                    key_expr = node.slice
                    cache_name = dotted(node.value)
                elif (isinstance(node, ast.Call)
                      and isinstance(node.func, ast.Attribute)
                      and node.func.attr in ("get", "setdefault", "pop")
                      and isinstance(node.func.value, ast.Attribute)
                      and CACHE_ATTR_RE.match(node.func.value.attr)
                      and node.args):
                    key_expr = node.args[0]
                    cache_name = dotted(node.func.value)
                if key_expr is None:
                    continue
                use_line = getattr(node, "lineno", 0)
                for elt, why in self._bad_elements(key_expr, assigns,
                                                   use_line):
                    self.emit(out, module, elt,
                              f"cache key for '{cache_name}' contains "
                              f"{why} — unhashable or identity-keyed "
                              "values defeat the program cache (one "
                              "recompile per call)")
        return out

    @staticmethod
    def _resolve(expr, assigns, use_line):
        """Latest assignment to a Name at or before ``use_line``."""
        if not isinstance(expr, ast.Name):
            return expr
        best = None
        for lineno, value in assigns.get(expr.id, ()):
            if lineno <= use_line and (best is None or lineno > best[0]):
                best = (lineno, value)
        return best[1] if best else expr

    def _bad_elements(self, key_expr, assigns, use_line):
        key_expr = self._resolve(key_expr, assigns, use_line)
        elts = (key_expr.elts if isinstance(key_expr, ast.Tuple)
                else [key_expr])
        for elt in elts:
            why = self._why_bad(self._resolve(elt, assigns, use_line))
            if why:
                yield elt, why

    @staticmethod
    def _why_bad(expr) -> Optional[str]:
        if isinstance(expr, (ast.List, ast.ListComp)):
            return "a list"
        if isinstance(expr, (ast.Dict, ast.DictComp)):
            return "a dict"
        if isinstance(expr, (ast.Set, ast.SetComp)):
            return "a set"
        if isinstance(expr, ast.GeneratorExp):
            return "a generator (identity-hashed)"
        if isinstance(expr, ast.Lambda):
            return "a lambda (identity-hashed: a fresh object per build)"
        if isinstance(expr, ast.Call):
            d = dotted(expr.func)
            if d in UNHASHABLE_CTORS or d.split(".")[-1] in (
                    "asarray", "tolist"):
                return f"a call to {d}() (unhashable result)"
        return None


# ---------------------------------------------------------------------------
# rng-reuse
# ---------------------------------------------------------------------------

KEY_NAME_RE = re.compile(
    r"^_?(rng|rngs|e?key|.*_keys?|keys|subkeys?\d*)$")
KEY_CONSUMER_FNS = {"epoch_schedule"}
KEY_CREATORS = {"PRNGKey", "key"}


class _RngWalker(SeqWalker):
    def __init__(self, rule: Rule, module: Module, out: List[Finding]):
        self.rule, self.module, self.out = rule, module, out
        # tracked key name -> times consumed since last (re)binding
        self.consumed: Dict[str, int] = {}
        self.reported: Set[Tuple[int, int]] = set()

    # state = copy of consumed map
    def snapshot(self):
        return dict(self.consumed)

    def restore(self, state):
        self.consumed = dict(state)

    def merge(self, other):
        for name, n in other.items():
            self.consumed[name] = max(self.consumed.get(name, 0), n)

    def track_param(self, name: str) -> None:
        if KEY_NAME_RE.match(name):
            self.consumed[name] = 0

    def on_bind_target(self, target, value=None):
        names, attrs = bound_names(target)
        fresh = value is not None and self._is_key_source(value)
        for name in names:
            if fresh or KEY_NAME_RE.match(name) or name in self.consumed:
                self.consumed[name] = 0
        for attr in attrs:
            if attr in self.consumed or KEY_NAME_RE.match(
                    attr.split(".")[-1]):
                self.consumed[attr] = 0

    @staticmethod
    def _is_key_source(value) -> bool:
        for node in ast.walk(value):
            if isinstance(node, ast.Call):
                d = dotted(node.func)
                if d.startswith("jax.random.") or d in KEY_CONSUMER_FNS:
                    return True
        return False

    def on_node(self, node):
        if not isinstance(node, ast.Call):
            return
        d = dotted(node.func)
        consumer = (d.startswith("jax.random.")
                    and d.split(".")[-1] not in KEY_CREATORS)
        consumer = consumer or d.split(".")[-1] in KEY_CONSUMER_FNS
        if consumer and node.args:
            arg = node.args[0]
            key = (arg.id if isinstance(arg, ast.Name)
                   else dotted(arg) if isinstance(arg, ast.Attribute)
                   else None)
            if key is None:
                return
            if not (key in self.consumed
                    or KEY_NAME_RE.match(key.split(".")[-1])):
                return
            count = self.consumed.get(key, 0)
            if count >= 1:
                loc = (node.lineno, node.col_offset)
                if loc not in self.reported:
                    self.reported.add(loc)
                    self.rule.emit(
                        self.out, self.module, node,
                        f"RNG key '{key}' consumed again by {d}() without "
                        "an intervening split/reassignment — identical "
                        "randomness flows to two consumers")
            self.consumed[key] = count + 1


class RngReuseRule(Rule):
    id = "rng-reuse"
    doc = ("a jax.random key used by two consumers without an "
           "intervening split: both draw identical randomness")

    def check(self, module: Module, config: LintConfig) -> List[Finding]:
        out: List[Finding] = []
        for fn in iter_defs(module.tree):
            walker = _RngWalker(self, module, out)
            for arg in (list(fn.args.posonlyargs) + list(fn.args.args)
                        + list(fn.args.kwonlyargs)):
                walker.track_param(arg.arg)
            walker.walk_function(fn)
        return out


# ---------------------------------------------------------------------------
# lock-discipline
# ---------------------------------------------------------------------------

LOCKISH_RE = re.compile(r"lock|mutex|cond|_cv\b|\bcv\b|_mu\b", re.I)
THREAD_LAUNCH_RE = re.compile(r"(^|\.)Thread$")


class LockDisciplineRule(Rule):
    id = "lock-discipline"
    doc = ("attribute mutated from more than one thread entry point "
           "(Thread target / executor submit / signal handler) without "
           "a common lock")

    def check(self, module: Module, config: LintConfig) -> List[Finding]:
        out: List[Finding] = []
        for node in ast.walk(module.tree):
            if isinstance(node, ast.ClassDef):
                self._check_class(module, node, out)
        return out

    def _check_class(self, module: Module, cls: ast.ClassDef,
                     out: List[Finding]) -> None:
        methods = {n.name: n for n in cls.body
                   if isinstance(n, (ast.FunctionDef,
                                     ast.AsyncFunctionDef))}
        # --- thread entry points -------------------------------------
        bg_seed_methods: Set[str] = set()
        bg_closures: List[ast.AST] = []  # nested defs handed to Thread()
        for m in methods.values():
            nested = {d.name: d for d in ast.walk(m)
                      if isinstance(d, (ast.FunctionDef,
                                        ast.AsyncFunctionDef))
                      and d is not m}
            for call in ast.walk(m):
                if not isinstance(call, ast.Call):
                    continue
                d = dotted(call.func)
                target = None
                if THREAD_LAUNCH_RE.search(d or ""):
                    for kw in call.keywords:
                        if kw.arg == "target":
                            target = kw.value
                elif (isinstance(call.func, ast.Attribute)
                      and call.func.attr == "submit" and call.args):
                    target = call.args[0]
                elif d == "signal.signal" and len(call.args) >= 2:
                    target = call.args[1]
                if target is None:
                    continue
                td = dotted(target)
                if td.startswith("self."):
                    bg_seed_methods.add(td.split(".", 1)[1])
                elif isinstance(target, ast.Name) and target.id in nested:
                    bg_closures.append(nested[target.id])
        if not bg_seed_methods and not bg_closures:
            return
        # --- transitive closure over self.X() calls ------------------
        def self_callees(fn) -> Set[str]:
            names = set()
            for c in ast.walk(fn):
                if isinstance(c, ast.Call):
                    d = dotted(c.func)
                    if d.startswith("self."):
                        names.add(d.split(".", 1)[1].split(".")[0])
            return names

        bg_methods: Set[str] = set()
        work = list(bg_seed_methods)
        for closure in bg_closures:
            work.extend(n for n in self_callees(closure))
        while work:
            name = work.pop()
            if name in bg_methods or name not in methods:
                continue
            bg_methods.add(name)
            work.extend(self_callees(methods[name]))

        bg_contexts: List[Tuple[str, ast.AST]] = (
            [(n, methods[n]) for n in sorted(bg_methods)]
            + [(f"<closure {c.name}>", c) for c in bg_closures])
        closure_nodes = set(bg_closures)
        fg_contexts = [
            (n, m) for n, m in methods.items()
            if n not in bg_methods and n != "__init__"]

        # --- write sites ---------------------------------------------
        def writes(ctx_fn, skip_closures: bool):
            sites = []
            stack = list(ast.iter_child_nodes(ctx_fn))
            nodes = []
            while stack:
                node = stack.pop()
                if skip_closures and node in closure_nodes:
                    continue  # that subtree runs on the bg thread and
                    # is walked as its own bg context
                nodes.append(node)
                stack.extend(ast.iter_child_nodes(node))
            for node in nodes:
                if isinstance(node, (ast.Assign, ast.AugAssign,
                                     ast.AnnAssign)):
                    targets = (node.targets
                               if isinstance(node, ast.Assign)
                               else [node.target])
                    for t in targets:
                        for sub in ast.walk(t):
                            if (isinstance(sub, ast.Attribute)
                                    and isinstance(sub.value, ast.Name)
                                    and sub.value.id == "self"):
                                attr = sub.attr
                                if LOCKISH_RE.search(attr):
                                    continue
                                sites.append(
                                    (attr, node,
                                     self._locked(module, ctx_fn, node)))
            return sites

        # closures nested in a fg method run on the bg thread: exclude
        # them from the fg method's own write set
        bg_writes: Dict[str, List[Tuple[str, ast.AST, bool]]] = {}
        for name, ctx in bg_contexts:
            for attr, node, locked in writes(ctx, skip_closures=False):
                bg_writes.setdefault(attr, []).append((name, node, locked))
        fg_writes: Dict[str, List[Tuple[str, ast.AST, bool]]] = {}
        for name, ctx in fg_contexts:
            for attr, node, locked in writes(ctx, skip_closures=True):
                fg_writes.setdefault(attr, []).append((name, node, locked))

        for attr, bsites in sorted(bg_writes.items()):
            fsites = fg_writes.get(attr, [])
            bg_names = {n for n, _, _ in bsites}
            contexts = bg_names | {n for n, _, _ in fsites}
            if len(contexts) < 2:
                continue
            unprotected = ([s for s in bsites if not s[2]]
                           + [s for s in fsites if not s[2]])
            # every unlocked site is its own finding: a suppression on
            # one (e.g. the signal-handler latch) must not silence an
            # unrelated unlocked write of the same attribute elsewhere
            for name, node, _ in unprotected:
                others = sorted(contexts - {name}) or sorted(contexts)
                self.emit(
                    out, module, node,
                    f"'{cls.name}.{attr}' is mutated from thread context "
                    f"'{name}' and also from {', '.join(others)} with at "
                    "least one unlocked write — wrap the writes in a "
                    "common lock or confine the attribute to one thread")

    @staticmethod
    def _locked(module: Module, ctx_fn, node) -> bool:
        """Is ``node`` under a ``with <something lock-ish>:`` within the
        context function?"""
        cur = module.parents.get(node)
        while cur is not None and cur is not ctx_fn:
            if isinstance(cur, (ast.With, ast.AsyncWith)):
                for item in cur.items:
                    try:
                        text = ast.unparse(item.context_expr)
                    except Exception:  # pragma: no cover - unparse safety
                        text = dotted(item.context_expr)
                    if LOCKISH_RE.search(text):
                        return True
            cur = module.parents.get(cur)
        return False


# ---------------------------------------------------------------------------
# donation-consistency
# ---------------------------------------------------------------------------

# methods/properties known to wrap a donating jax.jit. Matched by BARE
# name, so each entry lists the INTERSECTION of positions donated by
# every same-named implementation in the tree — `_train_step` donates
# (0, 1, 2) on MLN/CG but only (0, 1) on RNTN and the replicated
# data-parallel step, so position 2 is NOT listed (a name-keyed (0,1,2)
# would false-positive on correct RNTN code). `_fsdp_train_step`
# donates conditionally ((0, 1, 2) if self._donate else ()) and is
# deliberately absent: an unknown spec must not poison legal reads.
KNOWN_DONATING_ATTRS: Dict[str, Tuple[int, ...]] = {
    "_train_step": (0, 1),
    "_multi_train_step": (0, 1, 2),
    "_tbptt_train_step": (0, 1, 2),
}
# factories RETURNING a donating program: fn = self._epoch_train_step(...)
KNOWN_DONATING_FACTORIES: Dict[str, Tuple[int, ...]] = {
    "_epoch_train_step": (0, 1, 2),
    "_epoch_program": (0, 1, 2),
}


def _donate_positions(expr) -> Optional[Tuple[int, ...]]:
    """Literal donate_argnums positions, or None when indeterminate.
    ``(0, 1) if donate else ()`` and ``range(n)`` are NOT treated as
    always-donating — an unknown spec must not poison legal reads."""
    if isinstance(expr, ast.Constant) and isinstance(expr.value, int):
        return (expr.value,)
    if isinstance(expr, (ast.Tuple, ast.List)):
        out = []
        for e in expr.elts:
            if not (isinstance(e, ast.Constant)
                    and isinstance(e.value, int)):
                return None
            out.append(e.value)
        return tuple(sorted(set(out))) or None
    return None


def _decorated_donations(module: Module) -> Dict[str, Tuple[int, ...]]:
    """Function name -> donate positions for defs decorated with the
    ``@functools.partial(jax.jit, donate_argnums=...)`` idiom."""
    out: Dict[str, Tuple[int, ...]] = {}
    for fn in iter_defs(module.tree):
        for dec in fn.decorator_list:
            if not (isinstance(dec, ast.Call) and dec.args):
                continue
            if dotted(dec.func).split(".")[-1] != "partial":
                continue
            if dotted(dec.args[0]) not in ("jax.jit", "jit"):
                continue
            for kw in dec.keywords:
                if kw.arg == "donate_argnums":
                    pos = _donate_positions(kw.value)
                    if pos:
                        out[fn.name] = pos
    return out


class _DonationWalker(SeqWalker):
    def __init__(self, rule: Rule, module: Module, out: List[Finding],
                 donating_names: Optional[Dict[str, Tuple[int, ...]]]
                 = None):
        self.rule, self.module, self.out = rule, module, out
        self.donating_names = donating_names or {}
        self.jit_vars: Dict[str, Tuple[int, ...]] = {}
        # donated value name/attr -> line it was donated on
        self.poisoned: Dict[str, int] = {}
        self.reported: Set[Tuple[int, int]] = set()

    def snapshot(self):
        return (dict(self.jit_vars), dict(self.poisoned))

    def restore(self, state):
        self.jit_vars, self.poisoned = dict(state[0]), dict(state[1])

    def merge(self, other):
        self.jit_vars.update(other[0])
        for k, v in other[1].items():
            self.poisoned.setdefault(k, v)

    def on_bind_target(self, target, value=None):
        if value is not None and isinstance(target, ast.Name):
            donated = self._donating_value(value)
            if donated is not None:
                self.jit_vars[target.id] = donated
        names, attrs = bound_names(target)
        for ref in names + attrs:
            self.poisoned.pop(ref, None)

    @staticmethod
    def _donating_value(value) -> Optional[Tuple[int, ...]]:
        """donate positions when ``value`` is jax.jit(..., donate_argnums=)
        or a call to a known donating factory."""
        if not isinstance(value, ast.Call):
            return None
        d = dotted(value.func)
        if d.split(".")[-1] in KNOWN_DONATING_FACTORIES and d.startswith(
                ("self.", "net.", "network.")):
            return KNOWN_DONATING_FACTORIES[d.split(".")[-1]]
        if d not in ("jax.jit", "jit"):
            return None
        for kw in value.keywords:
            if kw.arg == "donate_argnums":
                return _donate_positions(kw.value)
        return None

    def on_node(self, node):
        # post-order: a call's argument reads are checked BEFORE the
        # call's own donation poisons them
        if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
            self._check_read(node.id, node)
        elif isinstance(node, ast.Attribute) and isinstance(
                node.ctx, ast.Load):
            d = dotted(node)
            if d in self.poisoned:
                self._check_read(d, node)
        if isinstance(node, ast.Call):
            self._visit_call(node)

    def _check_read(self, ref: str, node) -> None:
        if ref not in self.poisoned:
            return
        loc = (node.lineno, node.col_offset)
        if loc in self.reported:
            return
        self.reported.add(loc)
        self.rule.emit(
            self.out, self.module, node,
            f"'{ref}' was donated to a jitted call on line "
            f"{self.poisoned[ref]} (donate_argnums) and is read "
            "afterwards — its buffer may already be aliased/overwritten")

    def _visit_call(self, call: ast.Call) -> None:
        positions = None
        if isinstance(call.func, ast.Name):
            positions = (self.jit_vars.get(call.func.id)
                         or self.donating_names.get(call.func.id))
        else:
            d = dotted(call.func)
            if (d.startswith(("self.", "net.", "network."))
                    and d.split(".")[-1] in KNOWN_DONATING_ATTRS):
                positions = KNOWN_DONATING_ATTRS[d.split(".")[-1]]
        if not positions:
            return
        for p in positions:
            if p >= len(call.args):
                continue
            arg = call.args[p]
            ref = (arg.id if isinstance(arg, ast.Name)
                   else dotted(arg) if isinstance(arg, ast.Attribute)
                   else None)
            if ref:
                self.poisoned.setdefault(ref, call.lineno)


class DonationConsistencyRule(Rule):
    id = "donation-consistency"
    doc = ("an argument listed in donate_argnums is referenced after "
           "the jitted call: the donated buffer may be aliased or "
           "already overwritten")

    def check(self, module: Module, config: LintConfig) -> List[Finding]:
        out: List[Finding] = []
        donating = _decorated_donations(module)
        for fn in iter_defs(module.tree):
            _DonationWalker(self, module, out,
                            donating_names=donating).walk_function(fn)
        return out


# ---------------------------------------------------------------------------
# bare-counter (absorbed from scripts/lint_telemetry.py)
# ---------------------------------------------------------------------------

BARE_COUNTER_RE = re.compile(r"^_\w*_counter$")


class BareCounterRule(Rule):
    id = "bare-counter"
    doc = ("new bare self._*_counter attribute outside monitor/ — use "
           "monitor.record_counter()/metrics() so the value reaches the "
           "exporters")

    def check(self, module: Module, config: LintConfig) -> List[Finding]:
        if module.rel.startswith("deeplearning4j_tpu/monitor/"):
            return []
        if not module.rel.startswith("deeplearning4j_tpu/"):
            return []  # tests/fixtures may assign counters freely
        out: List[Finding] = []
        for node in ast.walk(module.tree):
            if not isinstance(node, (ast.Assign, ast.AugAssign,
                                     ast.AnnAssign)):
                continue
            targets = (node.targets if isinstance(node, ast.Assign)
                       else [node.target])
            for t in targets:
                for sub in ast.walk(t):
                    if (isinstance(sub, ast.Attribute)
                            and isinstance(sub.value, ast.Name)
                            and sub.value.id == "self"
                            and BARE_COUNTER_RE.match(sub.attr)):
                        self.emit(
                            out, module, node,
                            f"bare counter attribute 'self.{sub.attr}' "
                            "outside monitor/ — route it through "
                            "monitor.record_counter()/metrics() instead")
        return out


# ---------------------------------------------------------------------------
# marker-audit
# ---------------------------------------------------------------------------

PYTEST_BUILTIN_MARKS = {
    "parametrize", "skip", "skipif", "xfail", "usefixtures",
    "filterwarnings", "timeout", "flaky", "no_cover",
}
SLOW_SLEEP_S = 1.0


class MarkerAuditRule(Rule):
    id = "marker-audit"
    doc = ("pytest-marker audit: chaos-behavior tests must carry the "
           "registered 'chaos' marker, >=1s sleeps need 'slow'/'chaos', "
           "and only markers registered in pyproject.toml may be used")

    def check(self, module: Module, config: LintConfig) -> List[Finding]:
        parts = module.rel.split("/")
        if "tests" not in parts or not parts[-1].startswith("test_"):
            return []
        registered = config.markers() | PYTEST_BUILTIN_MARKS
        out: List[Finding] = []
        module_marks = self._module_marks(module)
        for node in ast.walk(module.tree):
            if isinstance(node, ast.Attribute):
                base = dotted(node.value)
                if base == "pytest.mark" and node.attr not in registered:
                    self.emit(
                        out, module, node,
                        f"marker '{node.attr}' is not registered in "
                        "pyproject.toml [tool.pytest.ini_options] "
                        "markers (typo, or register it)")
        for fn in iter_defs(module.tree):
            if not fn.name.startswith("test_"):
                continue
            marks = (module_marks | self._own_marks(fn)
                     | self._class_marks(module, fn))
            if self._drives_chaos(fn) and "chaos" not in marks:
                self.emit(
                    out, module, fn,
                    f"test '{fn.name}' drives fault injection "
                    "(DL4J_FAULTS/faults.install/fault_point) but lacks "
                    "@pytest.mark.chaos — chaos selection (-m chaos) "
                    "will miss it")
            if not marks & {"slow", "chaos"}:
                for call in ast.walk(fn):
                    if (isinstance(call, ast.Call)
                            and dotted(call.func) in ("time.sleep",
                                                      "sleep")
                            and call.args
                            and isinstance(call.args[0], ast.Constant)
                            and isinstance(call.args[0].value,
                                           (int, float))
                            and call.args[0].value >= SLOW_SLEEP_S):
                        self.emit(
                            out, module, call,
                            f"test '{fn.name}' sleeps "
                            f"{call.args[0].value}s without "
                            "@pytest.mark.slow — tier-1 pays that wall "
                            "clock on every run")
        return out

    @staticmethod
    def _drives_chaos(fn) -> bool:
        """True when the test CODE drives fault injection — calls to
        fault_point/install_from_env/faults.install/FaultSpec or a
        DL4J_FAULTS string constant. AST-based so a docstring or comment
        that merely MENTIONS these names never demands a chaos marker."""
        doc = None
        if (fn.body and isinstance(fn.body[0], ast.Expr)
                and isinstance(fn.body[0].value, ast.Constant)
                and isinstance(fn.body[0].value.value, str)):
            doc = fn.body[0].value
        for node in ast.walk(fn):
            if node is doc:
                continue
            if isinstance(node, ast.Call):
                d = dotted(node.func)
                if (d.split(".")[-1] in ("fault_point",
                                         "install_from_env",
                                         "FaultSpec")
                        or d == "faults.install"
                        or d.endswith(".faults.install")):
                    return True
            elif isinstance(node, ast.Name) and node.id == "FaultSpec":
                return True
            elif (isinstance(node, ast.Constant)
                  and isinstance(node.value, str)
                  and "DL4J_FAULTS" in node.value):
                return True
        return False

    @staticmethod
    def _marks_from_decorators(decorators) -> Set[str]:
        marks = set()
        for dec in decorators:
            target = dec.func if isinstance(dec, ast.Call) else dec
            d = dotted(target)
            if d.startswith("pytest.mark."):
                marks.add(d.split(".")[2])
        return marks

    def _own_marks(self, fn) -> Set[str]:
        return self._marks_from_decorators(fn.decorator_list)

    def _class_marks(self, module: Module, fn) -> Set[str]:
        marks: Set[str] = set()
        for scope in module.enclosing_scopes(fn):
            if isinstance(scope, ast.ClassDef):
                marks |= self._marks_from_decorators(scope.decorator_list)
        return marks

    def _module_marks(self, module: Module) -> Set[str]:
        marks: Set[str] = set()
        for node in module.tree.body:
            if (isinstance(node, ast.Assign)
                    and any(isinstance(t, ast.Name)
                            and t.id == "pytestmark"
                            for t in node.targets)):
                for sub in ast.walk(node.value):
                    if isinstance(sub, ast.Attribute):
                        d = dotted(sub)
                        if d.startswith("pytest.mark."):
                            marks.add(d.split(".")[2])
        return marks


# ---------------------------------------------------------------------------
# ad-hoc out_shardings / NamedSharding construction
# ---------------------------------------------------------------------------

REGISTRY_MODULE = "deeplearning4j_tpu/parallel/sharding_registry.py"


class AdhocOutShardingsRule(Rule):
    id = "adhoc-out-shardings"
    doc = ("NamedSharding constructed / out_shardings= passed outside "
           "parallel/sharding_registry.py — placement decisions belong "
           "in the per-model sharding registry (one mesh, one spec per "
           "leaf); sanctioned low-level builders carry per-site "
           "suppressions with reasons")

    def check(self, module: Module, config: LintConfig) -> List[Finding]:
        if module.rel == REGISTRY_MODULE:
            return []
        out: List[Finding] = []
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            d = dotted(node.func)
            if d == "NamedSharding" or d.endswith(".NamedSharding"):
                self.emit(
                    out, module, node,
                    "ad-hoc NamedSharding construction — route placement "
                    "through parallel/sharding_registry (named()/"
                    "ShardingRegistry) or suppress with a reason")
            for kw in node.keywords:
                if kw.arg == "out_shardings":
                    self.emit(
                        out, module, node,
                        "ad-hoc out_shardings= pin — source the shardings "
                        "from the model's ShardingRegistry "
                        "(epoch_out_shardings/param_shardings) or "
                        "suppress with a reason")
        return out


ALL_RULES: Tuple[Rule, ...] = (
    HostSyncRule(),
    ImplicitF32PromotionRule(),
    RecompileHazardRule(),
    RngReuseRule(),
    LockDisciplineRule(),
    DonationConsistencyRule(),
    BareCounterRule(),
    MarkerAuditRule(),
    AdhocOutShardingsRule(),
)
