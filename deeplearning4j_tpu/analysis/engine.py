"""dl4j-lint rule engine: AST modules, suppressions, findings, driver.

Stdlib-only by design (like ``monitor/``): the linter runs in CI shells
and pre-jax entry points where importing the package under analysis —
let alone jax — is off the table.  Rules work on the AST plus raw source
lines; they never execute the code they check.

Suppression syntax (inline, reviewable, reason REQUIRED)::

    self._flag = val  # dl4j-lint: disable=lock-discipline -- set before
                      # the thread starts

A suppression on a ``def``/``class`` header line covers the whole body;
anywhere else it covers that line only.  ``disable=all`` mutes every
rule.  A suppression without the ``-- reason`` tail is inert and is
itself reported (``suppression-missing-reason``): the whole point is
that every silenced finding carries its justification in the diff.

A fixture corpus (a file whose PURPOSE is to contain seeded violations,
like tests/test_analysis.py) opts out wholesale with a file-level pragma
in its first 10 lines — reason required, same as inline suppressions::

    # dl4j-lint: skip-file -- rule-fixture corpus; snippets ARE violations

Baseline workflow (for adopting a rule onto a codebase with existing
findings): ``scripts/dl4j_lint.py --update-baseline`` snapshots current
findings into ``.dl4j-lint-baseline.json``; subsequent runs report only
NEW findings.  Fingerprints hash (rule, path, enclosing symbol,
normalized line text) — not line numbers — so unrelated edits above a
baselined finding do not resurrect it.  The shipped tree keeps the
baseline EMPTY: real findings get fixed, genuine exceptions get inline
suppressions with reasons (see ISSUE 7 / docs/static_analysis.md).
"""

from __future__ import annotations

import ast
import dataclasses
import io
import os
import re
import tokenize
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

__all__ = [
    "Finding",
    "LintConfig",
    "Module",
    "Rule",
    "iter_py_files",
    "run_lint",
]

REPO_ROOT = os.path.abspath(
    os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", ".."))

_SUPPRESS_RE = re.compile(
    r"#\s*dl4j-lint:\s*disable=([A-Za-z0-9_,\-]+)"
    r"(?:\s*--\s*(?P<reason>\S.*))?")
_SKIPFILE_RE = re.compile(
    r"#\s*dl4j-lint:\s*skip-file(?:\s*--\s*(?P<reason>\S.*))?")
_SKIPFILE_SCAN_LINES = 10


@dataclasses.dataclass(frozen=True)
class Finding:
    """One lint finding, anchored to a source location."""

    rule: str
    path: str  # repo-relative, POSIX separators
    line: int
    col: int
    message: str
    symbol: str = ""  # dotted enclosing scope, e.g. "MLN._epoch_run_fn"

    def format(self) -> str:
        where = f"{self.path}:{self.line}:{self.col}"
        sym = f" [{self.symbol}]" if self.symbol else ""
        return f"{where}: {self.rule}: {self.message}{sym}"


@dataclasses.dataclass
class LintConfig:
    """Cross-file context handed to every rule."""

    root: str = REPO_ROOT
    # pytest markers registered in pyproject.toml (None = parse the
    # root's pyproject; tests inject their own set)
    registered_markers: Optional[Set[str]] = None

    def markers(self) -> Set[str]:
        if self.registered_markers is None:
            self.registered_markers = _parse_pyproject_markers(
                os.path.join(self.root, "pyproject.toml"))
        return self.registered_markers


def _parse_pyproject_markers(path: str) -> Set[str]:
    """Registered marker names from ``[tool.pytest.ini_options] markers``.
    Hand-parsed: tomllib is 3.11+ and the linter must stay stdlib-only
    on 3.10. Quote-aware bracket tracking, so a ``]`` inside a marker
    DESCRIPTION does not truncate the list, and only the pre-``:`` name
    of each string element registers (quoted words in descriptions do
    not)."""
    try:
        with open(path, encoding="utf-8") as f:
            text = f.read()
    except OSError:
        return set()
    m = re.search(r"markers\s*=\s*\[", text)
    if not m:
        return set()
    i, depth = m.end(), 1
    items: List[str] = []
    buf: Optional[str] = None
    quote: Optional[str] = None
    while i < len(text) and depth:
        c = text[i]
        if quote is not None:
            if c == quote:
                items.append(buf or "")
                buf = quote = None
            else:
                buf = (buf or "") + c
        elif c in "\"'":
            quote, buf = c, ""
        elif c == "[":
            depth += 1
        elif c == "]":
            depth -= 1
        i += 1
    out = set()
    for item in items:
        name = item.split(":", 1)[0].strip()
        if re.fullmatch(r"[A-Za-z_][A-Za-z0-9_]*", name):
            out.add(name)
    return out


class Module:
    """One parsed source file: AST + parent/scope maps + suppressions."""

    def __init__(self, path: str, root: str = REPO_ROOT):
        self.path = os.path.abspath(path)
        self.rel = os.path.relpath(self.path, root).replace(os.sep, "/")
        with open(self.path, encoding="utf-8") as f:
            self.source = f.read()
        self.lines = self.source.splitlines()
        self.tree = ast.parse(self.source, filename=self.rel)
        self.parents: Dict[ast.AST, ast.AST] = {}
        for node in ast.walk(self.tree):
            for child in ast.iter_child_nodes(node):
                self.parents[child] = node
        # pragmas live in COMMENT tokens only — a docstring QUOTING the
        # pragma syntax (usage examples, this engine's own docstring)
        # must never register as a live suppression or skip the file
        comments: Dict[int, str] = {}
        try:
            for tok in tokenize.generate_tokens(
                    io.StringIO(self.source).readline):
                if tok.type == tokenize.COMMENT:
                    comments[tok.start[0]] = tok.string
        except (tokenize.TokenError, IndentationError,
                SyntaxError):  # pragma: no cover - ast.parse succeeded
            comments = {i: line for i, line in enumerate(self.lines, 1)
                        if "#" in line}
        # file-level opt-out for fixture corpora; reasonless pragma is
        # inert (and reported), exactly like inline suppressions
        self.skip_file = False
        self.skip_file_inert_line = 0
        for lineno in sorted(comments):
            if lineno > _SKIPFILE_SCAN_LINES:
                break
            m = _SKIPFILE_RE.search(comments[lineno])
            if m:
                if m.group("reason") is not None:
                    self.skip_file = True
                else:
                    self.skip_file_inert_line = lineno
                break
        # line -> (rules, has_reason); "all" mutes every rule
        self.line_suppressions: Dict[int, Tuple[Set[str], bool]] = {}
        for lineno, text in comments.items():
            m = _SUPPRESS_RE.search(text)
            if m:
                rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
                self.line_suppressions[lineno] = (
                    rules, m.group("reason") is not None)

    # -- scope helpers ---------------------------------------------------

    def enclosing_scopes(self, node: ast.AST) -> List[ast.AST]:
        """Innermost-first chain of enclosing def/class nodes."""
        out = []
        cur = self.parents.get(node)
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.ClassDef)):
                out.append(cur)
            cur = self.parents.get(cur)
        return out

    def symbol_for(self, node: ast.AST) -> str:
        names = [s.name for s in self.enclosing_scopes(node)]
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            names.insert(0, node.name)
        return ".".join(reversed(names))

    # -- suppression resolution ------------------------------------------

    def suppressed(self, rule: str, node_or_line) -> bool:
        line = (node_or_line if isinstance(node_or_line, int)
                else getattr(node_or_line, "lineno", 0))
        cands = [line]
        if not isinstance(node_or_line, int):
            # a multi-line statement/expression accepts the suppression
            # on ANY of its lines (the natural spot is the closing one);
            # def/class anchors stay header-only — a comment deep in the
            # body must not mute a def-level finding
            end = getattr(node_or_line, "end_lineno", None)
            if (end is not None and end > line
                    and not isinstance(node_or_line,
                                       (ast.FunctionDef,
                                        ast.AsyncFunctionDef,
                                        ast.ClassDef))):
                cands.extend(range(line + 1, end + 1))
            scopes = self.enclosing_scopes(node_or_line)
            if isinstance(node_or_line, (ast.FunctionDef,
                                         ast.AsyncFunctionDef,
                                         ast.ClassDef)):
                # a finding anchored ON a def/class (e.g. marker-audit)
                # honors that header's own decorator lines too
                scopes = [node_or_line] + scopes
            for scope in scopes:
                cands.append(scope.lineno)
                # decorators sit above the def line; the comment may ride
                # on any decorator line of the scope header
                for dec in getattr(scope, "decorator_list", []):
                    cands.append(dec.lineno)
        for ln in cands:
            entry = self.line_suppressions.get(ln)
            if entry is None:
                continue
            rules, has_reason = entry
            if has_reason and (rule in rules or "all" in rules):
                return True
        return False

    def finding(self, rule: str, node: ast.AST, message: str) -> Finding:
        return Finding(rule=rule, path=self.rel,
                       line=getattr(node, "lineno", 0),
                       col=getattr(node, "col_offset", 0),
                       message=message, symbol=self.symbol_for(node))


class Rule:
    """Base rule: subclasses set ``id``/``doc`` and implement ``check``."""

    id: str = ""
    doc: str = ""

    def check(self, module: Module, config: LintConfig) -> List[Finding]:
        raise NotImplementedError

    def emit(self, out: List[Finding], module: Module, node: ast.AST,
             message: str) -> None:
        """Append a finding unless an inline suppression (on the line or
        on an enclosing def/class header) mutes this rule there."""
        if not module.suppressed(self.id, node):
            out.append(module.finding(self.id, node, message))


SKIP_DIRS = {"__pycache__", ".git", ".dl4j_worktrees", "node_modules"}
# repo-relative roots a no-argument run scans; the CLI's partial
# --update-baseline derives its "what did this run re-check" set from
# the SAME list, so the two can never drift
DEFAULT_SCAN_DIRS = ("deeplearning4j_tpu", "tests")


def default_scan_paths(root: str = REPO_ROOT) -> List[str]:
    return [os.path.join(root, d) for d in DEFAULT_SCAN_DIRS]


def iter_py_files(paths: Sequence[str]) -> Iterable[str]:
    for p in paths:
        p = os.path.abspath(p)
        if os.path.isfile(p):
            if p.endswith(".py"):
                yield p
            continue
        for root, dirs, files in os.walk(p):
            dirs[:] = sorted(d for d in dirs if d not in SKIP_DIRS)
            for name in sorted(files):
                if name.endswith(".py"):
                    yield os.path.join(root, name)


def _suppression_hygiene(module: Module) -> List[Finding]:
    """Inert suppressions (no ``-- reason``) are themselves findings."""
    out = []
    if module.skip_file_inert_line:
        out.append(Finding(
            rule="suppression-missing-reason", path=module.rel,
            line=module.skip_file_inert_line, col=0,
            message=("skip-file pragma has no '-- reason' tail and is "
                     "ignored; a whole-file opt-out must say why")))
    for line, (rules, has_reason) in sorted(
            module.line_suppressions.items()):
        if not has_reason:
            out.append(Finding(
                rule="suppression-missing-reason", path=module.rel,
                line=line, col=0,
                message=("suppression for %s has no '-- reason' tail and "
                         "is ignored; every silenced finding must say why"
                         % ",".join(sorted(rules)))))
    return out


def run_lint(paths: Optional[Sequence[str]] = None,
             select: Optional[Sequence[str]] = None,
             config: Optional[LintConfig] = None) -> List[Finding]:
    """Run the (selected) ruleset over ``paths``; suppressions applied,
    baseline NOT applied (that is the CLI's job — callers that want raw
    findings, like the fixture tests, get them here)."""
    from deeplearning4j_tpu.analysis.rules import ALL_RULES

    config = config or LintConfig()
    if paths is None:
        paths = default_scan_paths(config.root)
    rules = [r for r in ALL_RULES
             if select is None or r.id in set(select)]
    findings: List[Finding] = []
    for path in iter_py_files(paths):
        try:
            module = Module(path, root=config.root)
        except (SyntaxError, UnicodeDecodeError) as exc:
            findings.append(Finding(
                rule="parse-error",
                path=os.path.relpath(path, config.root).replace(os.sep, "/"),
                line=getattr(exc, "lineno", 0) or 0, col=0,
                message=f"cannot parse: {exc}"))
            continue
        if module.skip_file:
            continue  # fixture corpus: neither rules nor hygiene apply
        if select is None or "suppression-missing-reason" in set(select):
            findings.extend(_suppression_hygiene(module))
        for rule in rules:
            findings.extend(rule.check(module, config))
    return findings
