"""Deterministic, seeded fault injection for the control plane.

The reference dl4j's distributed story was *tested* by real failures (Akka
kills actors, YARN restarts containers). Our control plane
(statetracker/cluster/registry/fetchers) is plain Python, so faults are
injected at named **fault points** — call sites that the production code
threads through :func:`fault_point`. When no schedule is installed the call
is a dict lookup on an empty dict guarded by a module-level flag: zero
overhead in production.

Usage (tests)::

    with inject("statetracker.write", fail_nth(3, exc=OSError)):
        ...          # the 3rd tracker write raises OSError("injected ...")

    with inject("heartbeat.post", delay(50)):
        ...          # every heartbeat post sleeps 50 ms

Usage (process-level, e.g. chaos runs of the CLI)::

    DL4J_FAULTS="checkpoint.save=fail_nth:2;fetcher.download=fail_rate:0.5:123"

Well-known sites (grep for ``fault_point(`` for the authoritative list):

- ``statetracker.write``   — every FileStateTracker atomic publish
- ``checkpoint.save``      — FaultTolerantTrainer.save/save_async, before
  the write
- ``checkpoint.restore``   — FaultTolerantTrainer.resume, per candidate
- ``heartbeat.post``       — every heartbeat post (monitor + workers)
- ``distributed.init``     — each jax.distributed.initialize attempt
- ``fetcher.download``     — each dataset download attempt
- ``registry.retrieve``    — ConfigRegistry reads (wait_for polls)
- ``epoch.chunk``          — before every fused epoch-chunk dispatch
  (drive_epoch_chunks)
- ``preempt.chunk``        — polled at every chunk boundary by
  PreemptionGuard.check; an injected fault here IS a preemption notice

Schedules are deterministic: ``fail_nth`` counts invocations,
``fail_rate`` draws from its own seeded RNG — re-running a test replays
the identical fault sequence.
"""

from __future__ import annotations

import os
import random
import threading
import time
from typing import Callable, Dict, Optional

__all__ = [
    "FaultInjected",
    "FaultPoint",
    "fault_point",
    "inject",
    "install",
    "uninstall",
    "clear",
    "active",
    "fail_nth",
    "fail_times",
    "fail_rate",
    "delay",
    "install_from_env",
    "parse_spec",
]


class FaultInjected(Exception):
    """Default exception raised by failure schedules."""


# A schedule is any callable taking the site name; it raises/sleeps/no-ops.
Schedule = Callable[[str], None]

_lock = threading.RLock()
_active: Dict[str, Schedule] = {}
# fast-path flag: production code pays one attribute read + truth test
_armed: bool = False


def fault_point(name: str) -> None:
    """Declare a named injection site. No-op unless a schedule is
    installed for ``name`` (zero overhead when the registry is empty).
    Armed sites count every evaluation in the metrics registry
    (``fault_site_fires_total``, labeled raised=true/false) so a chaos
    run's artifact shows which sites actually fired."""
    if not _armed:
        return
    sched = _active.get(name)
    if sched is not None:
        from deeplearning4j_tpu.monitor import record_counter

        try:
            sched(name)
        except BaseException:
            record_counter("fault_site_fires_total", site=name,
                           raised="true")
            raise
        record_counter("fault_site_fires_total", site=name,
                       raised="false")


class FaultPoint:
    """First-class handle on a site name; ``FaultPoint("x")()`` fires it.

    Lets a module hoist its site into a constant and call it like a
    function, keeping the site name greppable in one place."""

    __slots__ = ("name",)

    def __init__(self, name: str):
        self.name = name

    def __call__(self) -> None:
        fault_point(self.name)

    def __repr__(self) -> str:
        return f"FaultPoint({self.name!r})"


def install(name: str, schedule: Schedule) -> None:
    global _armed
    with _lock:
        _active[name] = schedule
        _armed = True


def uninstall(name: str) -> None:
    global _armed
    with _lock:
        _active.pop(name, None)
        _armed = bool(_active)


def clear() -> None:
    """Remove every installed schedule."""
    global _armed
    with _lock:
        _active.clear()
        _armed = False


def active() -> Dict[str, Schedule]:
    with _lock:
        return dict(_active)


class inject:
    """Context manager installing ``schedule`` at ``name`` for the body.

    Restores the previous schedule (if any) on exit, so nested injections
    at the same site compose."""

    def __init__(self, name: str, schedule: Schedule):
        self.name = name
        self.schedule = schedule
        self._prev: Optional[Schedule] = None
        self._had_prev = False

    def __enter__(self) -> "inject":
        with _lock:
            self._had_prev = self.name in _active
            self._prev = _active.get(self.name)
            install(self.name, self.schedule)
        return self

    def __exit__(self, *exc) -> None:
        with _lock:
            if self._had_prev and self._prev is not None:
                install(self.name, self._prev)
            else:
                uninstall(self.name)


# ---------------------------------------------------------------------------
# schedules
# ---------------------------------------------------------------------------


def fail_nth(n: int, exc: Callable[[str], BaseException] = None) -> Schedule:
    """Fail exactly the ``n``-th invocation (1-based); all others pass.

    ``exc``: exception *type or factory* called with a message — inject
    ``OSError`` to exercise paths whose retry filters treat I/O errors as
    transient."""
    counter = {"n": 0}
    make = exc or FaultInjected

    def sched(name: str) -> None:
        with _lock:
            counter["n"] += 1
            hit = counter["n"] == n
        if hit:
            raise make(f"injected fault at {name} (call #{n})")

    return sched


def fail_times(k: int, exc: Callable[[str], BaseException] = None) -> Schedule:
    """Fail the first ``k`` invocations, then succeed forever — the
    canonical transient-fault shape for retry tests."""
    counter = {"n": 0}
    make = exc or FaultInjected

    def sched(name: str) -> None:
        with _lock:
            counter["n"] += 1
            hit = counter["n"] <= k
        if hit:
            raise make(f"injected fault at {name} "
                       f"(call #{counter['n']} of first {k})")

    return sched


def fail_rate(p: float, seed: int = 0,
              exc: Callable[[str], BaseException] = None) -> Schedule:
    """Fail with probability ``p`` from a private seeded RNG — the fault
    sequence is a pure function of ``seed``, so runs replay exactly."""
    rng = random.Random(seed)
    make = exc or FaultInjected

    def sched(name: str) -> None:
        with _lock:
            hit = rng.random() < p
        if hit:
            raise make(f"injected fault at {name} (rate={p}, seed={seed})")

    return sched


def delay(ms: float) -> Schedule:
    """Sleep ``ms`` milliseconds on every invocation (slow-host / hung-step
    simulation — pair with StepWatchdog tests)."""

    def sched(name: str) -> None:
        time.sleep(ms / 1000.0)

    return sched


# ---------------------------------------------------------------------------
# DL4J_FAULTS env spec
# ---------------------------------------------------------------------------

_SCHEDULES = {
    "fail_nth": lambda *a: fail_nth(int(a[0])),
    "fail_times": lambda *a: fail_times(int(a[0])),
    "fail_rate": lambda *a: fail_rate(float(a[0]),
                                      int(a[1]) if len(a) > 1 else 0),
    "delay": lambda *a: delay(float(a[0])),
}


def parse_spec(spec: str) -> Dict[str, Schedule]:
    """Parse a ``DL4J_FAULTS`` spec:
    ``site=schedule:arg[:arg...]`` entries joined by ``;``. Example::

        statetracker.write=fail_nth:3;heartbeat.post=delay:100
    """
    out: Dict[str, Schedule] = {}
    for entry in spec.split(";"):
        entry = entry.strip()
        if not entry:
            continue
        try:
            site, rhs = entry.split("=", 1)
            parts = rhs.split(":")
            kind, args = parts[0], parts[1:]
            out[site.strip()] = _SCHEDULES[kind](*args)
        except (ValueError, KeyError, IndexError):
            raise ValueError(
                f"bad DL4J_FAULTS entry {entry!r}: expected "
                f"site=schedule:arg[:arg], schedule one of "
                f"{sorted(_SCHEDULES)}") from None
    return out


def install_from_env(env_var: str = "DL4J_FAULTS") -> int:
    """Install schedules from the environment; returns how many. Called at
    ``deeplearning4j_tpu.resilience`` import so chaos runs need only the
    env var set before the process starts."""
    spec = os.environ.get(env_var)
    if not spec:
        return 0
    parsed = parse_spec(spec)
    for site, sched in parsed.items():
        install(site, sched)
    return len(parsed)
