"""Resilience layer: deterministic fault injection, unified retry/backoff,
hung-step watchdog.

The control plane (``parallel/cluster.py``, ``parallel/statetracker.py``,
``parallel/registry.py``, ``datasets/fetchers.py``) programs against this
package instead of hand-rolling sleeps and bare ``except`` clauses:

- :mod:`~deeplearning4j_tpu.resilience.faults` — named injection sites
  activated per-test (``inject``) or per-process (``DL4J_FAULTS=``), with
  deterministic schedules; zero overhead when inactive.
- :mod:`~deeplearning4j_tpu.resilience.retry` — one ``RetryPolicy``
  (exponential backoff + full jitter, deadline, retryable filter,
  injectable sleep) replacing every ad-hoc retry loop.
- :mod:`~deeplearning4j_tpu.resilience.watchdog` — ``StepWatchdog`` flags
  hung training steps past a deadline (the slow/hung-host detector SPMD
  needs, since a blocked collective never crashes).
- :mod:`~deeplearning4j_tpu.resilience.guard` — the ``DL4J_NAN_GUARD``
  divergence policy behind the fused pipeline's in-program numeric
  sentinel (``skip``/``halve_lr``/``raise``/``off``) and
  :class:`TrainingDivergedError`.
- :mod:`~deeplearning4j_tpu.resilience.preemption` — ``PreemptionGuard``
  latches SIGTERM / injected ``preempt.chunk`` faults so fused training
  checkpoints and stops at a chunk boundary instead of dying mid-run.
- :mod:`~deeplearning4j_tpu.resilience.lease` — ``GrantLease`` bounded
  watchdog around every backend acquisition (bench probe, dryrun child,
  serve replica warm-up): a wedged grant releases and re-acquires under
  escalating backoff instead of recording an error line and dying.
- :mod:`~deeplearning4j_tpu.resilience.autopilot` —
  ``GoodputAutopilot`` closes the observe→act loop over the PR-9 fleet
  gauges: goodput below floor / straggler flagged / heartbeat silence
  become evict/reshard/re-admit decisions, each evidence-logged as an
  ``autopilot.decision`` event.

Checkpoint integrity verification lives with its writer
(``parallel.cluster.FaultTolerantTrainer``): sha256 manifest sidecars on
save, verify + fall back to the next-older checkpoint on resume. See
``docs/resilience.md`` for the failure model.
"""

from deeplearning4j_tpu.resilience.faults import (  # noqa: F401
    FaultInjected,
    FaultPoint,
    clear,
    delay,
    fail_nth,
    fail_rate,
    fail_times,
    fault_point,
    inject,
    install,
    install_from_env,
    parse_spec,
    uninstall,
)
from deeplearning4j_tpu.resilience.autopilot import (  # noqa: F401
    AutopilotDecision,
    GoodputAutopilot,
    autopilot_enabled,
    goodput_floor,
)
from deeplearning4j_tpu.resilience.guard import (  # noqa: F401
    TrainingDivergedError,
    nan_guard_policy,
    tree_all_finite,
)
from deeplearning4j_tpu.resilience.lease import (  # noqa: F401
    GrantLease,
    GrantWedgedError,
    grant_lease_s,
    grant_reacquires,
)
from deeplearning4j_tpu.resilience.preemption import (  # noqa: F401
    PreemptionGuard,
)
from deeplearning4j_tpu.resilience.retry import (  # noqa: F401
    RetryError,
    RetryPolicy,
    no_jitter,
)
from deeplearning4j_tpu.resilience.watchdog import StepWatchdog  # noqa: F401

# chaos runs of real entry points: DL4J_FAULTS takes effect on first import
install_from_env()
