"""Grant lease protocol: bounded, self-healing backend acquisition.

Rounds r04/r05 lost their entire on-chip bench runs to wedged device
grants: the PJRT claim blocked for hours, the watchdog eventually
reported it, and the process recorded one error line and died. PR 9 made
that failure class *observable* (grant spans, `grant.watchdog` events,
`grant_wait` badput in the run ledger, the flight recorder's wedge
classification); this module makes the system *act* on it.

A :class:`GrantLease` wraps any backend acquisition — the bench's child
probe + in-process init, the dryrun's bootstrap subprocess, a serve
replica's program warm-up — in a bounded-watchdog lease:

- every attempt is **bounded** (``lease_s``, default
  ``DL4J_GRANT_LEASE_S``): a blocking acquisition runs on a daemon
  thread and the lease stops waiting at the bound instead of hanging
  the process (the wedged-PJRT shape: the thread cannot be killed, but
  nothing above it needs to keep waiting);
- a wedged or failed attempt **releases and re-acquires** instead of
  dying: best-effort ``release()``, an escalating backoff
  (``grant.backoff`` span — the run ledger books it as ``grant_wait``
  badput, exactly like the blocked probe itself), an optional
  ``probe()`` re-check (the bench re-probes from a short-lived
  subprocess, which holds no grant and can always be killed), then a
  fresh attempt under a ``grant.reacquire`` span;
- attempts are bounded by ``max_reacquires`` (``DL4J_GRANT_REACQUIRES``)
  — exhaustion raises :class:`GrantWedgedError` and the caller falls
  back to its honest-error path (the bench's partial-flush error line);
- a rescue leaves evidence: the ``grant.reacquired`` event (forwarded
  into the flight ring like every tracer event) is what
  ``flight_report`` classifies the ``reacquired`` end state from —
  clean-with-recovery, not wedged.

State machine (see docs/resilience.md §always-on operation)::

    unheld --acquire()--> acquiring --ok--> held
                 ^            |
                 |         wedge/fail (attempt <= max_reacquires)
                 |            v
                 +-- backoff/release/probe  --exhausted--> GrantWedgedError

Chaos hook: every attempt declares the ``grant.lease`` fault site, so a
``DL4J_FAULTS=grant.lease=fail_times:1`` schedule deterministically
wedges the first acquisition and exercises the re-acquire path without
any real backend.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from typing import Callable, Optional, Tuple

from deeplearning4j_tpu.resilience import faults
from deeplearning4j_tpu.resilience.retry import RetryableSpec, is_retryable

logger = logging.getLogger(__name__)

__all__ = [
    "GrantLease",
    "GrantWedgedError",
    "grant_lease_s",
    "grant_reacquires",
]

DEFAULT_LEASE_S = 90.0
DEFAULT_REACQUIRES = 2


class GrantWedgedError(RuntimeError):
    """Every lease attempt wedged or failed. ``attempts`` is how many
    were made; ``last`` the final exception (None when the last attempt
    timed out rather than raised)."""

    def __init__(self, message: str, attempts: int,
                 last: Optional[BaseException] = None):
        super().__init__(message)
        self.attempts = attempts
        self.last = last


def grant_lease_s() -> float:
    """Per-attempt watchdog bound for a grant acquisition
    (``DL4J_GRANT_LEASE_S``, default 90 s — healthy tunnel init is
    ~20–40 s, so the bound separates healthy from wedged without
    stalling a whole bench round on one attempt)."""
    raw = os.environ.get("DL4J_GRANT_LEASE_S", "")
    try:
        return float(raw) if raw else DEFAULT_LEASE_S
    except ValueError:
        return DEFAULT_LEASE_S


def grant_reacquires() -> int:
    """How many release-and-re-acquire cycles a lease attempts after the
    first wedge (``DL4J_GRANT_REACQUIRES``, default 2) before giving up
    with :class:`GrantWedgedError`."""
    raw = os.environ.get("DL4J_GRANT_REACQUIRES", "")
    try:
        return max(0, int(raw)) if raw else DEFAULT_REACQUIRES
    except ValueError:
        return DEFAULT_REACQUIRES


class GrantLease:
    """Bounded-watchdog lease around one backend acquisition.

    - ``acquire``: the acquisition; may block indefinitely (run on a
      daemon thread under the ``lease_s`` bound when ``bounded=True``)
      or self-bound (subprocess probes pass ``bounded=False`` — they
      enforce their own timeout and raise on it).
    - ``release``: best-effort cleanup after a wedged/failed attempt
      (kill a probe child, drop a half-claim). Exceptions are logged,
      never raised — release runs on the way to a retry.
    - ``probe``: optional liveness pre-check run before every
      RE-acquire (never before the first attempt): return falsy or
      raise to count the cycle as wedged without paying the full
      acquisition. The bench passes its short-lived subprocess probe.
    - ``retryable``: exception types (tuple) or predicate deciding
      which acquisition failures re-acquire; anything else propagates
      immediately (a code bug must not burn the backoff budget).
      Timeouts of a bounded attempt always count as wedges.
    - ``sleep`` / ``clock``: injectable for deterministic tests.

    ``acquire()`` returns the acquisition's value and sets
    ``state == "held"``; ``reacquires`` counts the wedge→re-acquire
    cycles the rescue cost (0 on a clean first attempt).
    """

    def __init__(self, name: str, acquire: Callable[[], object], *,
                 release: Optional[Callable[[], None]] = None,
                 probe: Optional[Callable[[], object]] = None,
                 lease_s: Optional[float] = None,
                 max_reacquires: Optional[int] = None,
                 bounded: bool = True,
                 base_backoff_s: float = 2.0,
                 backoff_multiplier: float = 2.0,
                 max_backoff_s: float = 30.0,
                 retryable: RetryableSpec = (Exception,),
                 sleep: Callable[[float], None] = time.sleep,
                 clock: Callable[[], float] = time.monotonic):
        self.name = name
        self._acquire = acquire
        self._release = release
        self._probe = probe
        self.lease_s = grant_lease_s() if lease_s is None else float(lease_s)
        self.max_reacquires = (grant_reacquires() if max_reacquires is None
                               else max(0, int(max_reacquires)))
        self.bounded = bounded
        self.base_backoff_s = base_backoff_s
        self.backoff_multiplier = backoff_multiplier
        self.max_backoff_s = max_backoff_s
        self.retryable = retryable
        self._sleep = sleep
        self._clock = clock
        self.state = "unheld"
        self.reacquires = 0
        self.last_detail: Optional[str] = None

    # ------------------------------------------------------------------
    def backoff_for(self, cycle: int) -> float:
        """Escalating (deterministic) backoff before re-acquire cycle
        ``cycle`` (1-based). Determinism over jitter here: lease retries
        are rare, serial, and per-process — there is no thundering herd
        to de-synchronize, and a replayable chaos run wants replayable
        waits."""
        return min(self.max_backoff_s,
                   self.base_backoff_s
                   * self.backoff_multiplier ** (cycle - 1))

    # ------------------------------------------------------------------
    def _attempt_bounded(self):
        """Run the acquisition on a daemon thread under the lease bound.
        Returns (ok, value, exc). A timed-out thread is left behind — it
        may be blocked inside a non-interruptible PJRT call — and a
        retry starts a FRESH attempt rather than re-joining it."""
        box: dict = {}
        done = threading.Event()

        def run():
            try:
                faults.fault_point("grant.lease")
                box["value"] = self._acquire()
            except BaseException as e:  # noqa: BLE001 — reported below
                box["exc"] = e
            done.set()

        threading.Thread(target=run, daemon=True,
                         name=f"grant-lease-{self.name}").start()
        if not done.wait(self.lease_s):
            return False, None, None  # wedged: no exception, no value
        if "exc" in box:
            return False, None, box["exc"]
        return True, box.get("value"), None

    def _attempt_unbounded(self):
        try:
            faults.fault_point("grant.lease")
            return True, self._acquire(), None
        except BaseException as e:  # noqa: BLE001 — filtered by caller
            return False, None, e

    def _do_release(self) -> None:
        self.state = "releasing"
        if self._release is None:
            return
        try:
            self._release()
        except Exception:  # noqa: BLE001 — release is best-effort
            logger.warning("grant lease %s: release failed", self.name,
                           exc_info=True)

    def _do_probe(self) -> Tuple[bool, Optional[str]]:
        if self._probe is None:
            return True, None
        try:
            ok = self._probe()
        except Exception as e:  # noqa: BLE001 — a raising probe = wedged
            return False, f"probe raised: {e}"
        if not ok:
            return False, "probe reported backend unavailable"
        return True, None

    # ------------------------------------------------------------------
    def acquire(self):
        """Acquire under the lease protocol; returns the acquisition's
        value or raises :class:`GrantWedgedError` after
        ``1 + max_reacquires`` wedged/failed attempts (non-retryable
        acquisition exceptions propagate as-is)."""
        from deeplearning4j_tpu.monitor import record_counter, tracer

        last_exc: Optional[BaseException] = None
        for attempt in range(1 + self.max_reacquires):
            if attempt > 0:
                ok, detail = self._do_probe()
                if not ok:
                    self.last_detail = detail
                    tracer().event("grant.watchdog", phase=self.name,
                                   attempt=attempt,
                                   detail=str(detail)[:200])
                    record_counter("grant_wedges_total", phase=self.name)
                    if attempt < self.max_reacquires:
                        self._backoff(attempt + 1, tracer)
                    continue
            self.state = "acquiring"
            span_name = "grant.acquire" if attempt == 0 else "grant.reacquire"
            # the flight marker lands BEFORE the (possibly blocking)
            # attempt — spans only record on completion, so a grant that
            # never returns leaves the open marker as the wedge evidence
            _flight_marker(phase=self.name, attempt=attempt,
                           timeout_s=self.lease_s)
            with tracer().span(span_name, lease=self.name,
                               attempt=attempt,
                               timeout_s=self.lease_s) as sp:
                if self.bounded:
                    ok, value, exc = self._attempt_bounded()
                else:
                    ok, value, exc = self._attempt_unbounded()
                sp.attrs["ok"] = ok
            # an injected grant.lease fault is ALWAYS a wedge, whatever
            # the retryable filter says: the documented chaos contract
            # (DL4J_FAULTS="grant.lease=fail_times:1") must exercise the
            # re-acquire path on every lease, including the bench/dryrun
            # leases whose filters name only their real failure types
            if isinstance(exc, faults.FaultInjected):
                retryable_exc = True
            else:
                retryable_exc = exc is None or is_retryable(
                    exc, self.retryable)
            if ok:
                self.state = "held"
                self.reacquires = attempt
                record_counter("grant_lease_acquired_total",
                               phase=self.name,
                               reacquired=str(attempt > 0).lower())
                if attempt > 0:
                    # the rescue record: flight_report classifies a run
                    # whose timeline carries this as `reacquired`
                    # (clean-with-recovery), not wedged
                    tracer().event("grant.reacquired", lease=self.name,
                                   attempts=attempt)
                    logger.warning(
                        "grant lease %s: re-acquired after %d wedged "
                        "attempt(s)", self.name, attempt)
                return value
            if not retryable_exc:
                self.state = "unheld"
                raise exc
            last_exc = exc
            detail = ("no completion within lease bound "
                      f"{self.lease_s:.0f}s" if exc is None
                      else f"{type(exc).__name__}: {exc}")
            self.last_detail = detail
            tracer().event("grant.watchdog", phase=self.name,
                           attempt=attempt, timeout_s=self.lease_s,
                           detail=str(detail)[:200])
            record_counter("grant_wedges_total", phase=self.name)
            self._do_release()
            if attempt < self.max_reacquires:
                self._backoff(attempt + 1, tracer)
        self.state = "wedged"
        raise GrantWedgedError(
            f"grant lease {self.name!r} wedged: "
            f"{1 + self.max_reacquires} attempt(s) exhausted "
            f"(last: {self.last_detail})",
            attempts=1 + self.max_reacquires, last=last_exc)

    def _backoff(self, cycle: int, tracer) -> None:
        self.state = "backoff"
        delay = self.backoff_for(cycle)
        # its own span name (not retry.sleep): the ledger books lease
        # backoff as grant_wait — the round lost this time to the GRANT,
        # and the goodput breakdown should say so
        with tracer().span("grant.backoff", lease=self.name,
                           cycle=cycle, delay_s=round(delay, 3)):
            self._sleep(delay)


def _flight_marker(**payload) -> None:
    try:
        from deeplearning4j_tpu.monitor.flight import flight_record

        flight_record("grant.wait", **payload)
    except Exception:  # telemetry must never block an acquisition
        pass
