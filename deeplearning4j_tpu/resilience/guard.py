"""In-program numeric sentinel + ``DL4J_NAN_GUARD`` divergence policy.

The fused epoch pipeline (perf/epoch_cache.py) runs E epochs x N optimizer
steps as ONE XLA program — by the time the host sees the loss history, a
single non-finite microbatch has already poisoned every subsequent step of
the chunk. The per-step paths could react on host (and
``optimize/function.py`` did, with an ad-hoc branch); the fused path needs
the reaction IN the program.

The sentinel is a per-step finite check on the loss and on every gradient
leaf (a non-finite global grad-norm <=> some non-finite gradient element;
checking leaves directly avoids the f32 overflow a naive sum-of-squares
norm would add on healthy-but-large gradients). A tripped step applies a
``lax.cond``-guarded identity — params, updater state and net state carry
through unchanged, so one poisoned batch costs exactly one skipped update —
and the ``[E, N]`` trip history returns with the loss history for the host
to enforce the policy per chunk:

- ``skip`` (default) — log and continue; the in-program identity already
  contained the damage.
- ``halve_lr`` — additionally halve the host LR scale for subsequent
  chunks (divergence is often a too-hot schedule, not bad data).
- ``raise`` — replay the chunk per-step from the last-good snapshot to
  localize the offending batch, then raise :class:`TrainingDivergedError`
  naming the exact epoch/step/batch.
- ``off`` — compile the fused program without the guard (the pre-sentinel
  behavior; the bench's overhead baseline).

A skipped step still advances the in-program iteration counter, so LR
schedules stay aligned with an uninterrupted run.
"""

from __future__ import annotations

import functools
import logging
import os
from deeplearning4j_tpu.analysis.annotations import traced

logger = logging.getLogger(__name__)

__all__ = [
    "NAN_GUARD_POLICIES",
    "TrainingDivergedError",
    "nan_guard_policy",
    "tree_all_finite",
]

NAN_GUARD_POLICIES = ("skip", "halve_lr", "raise", "off")
DEFAULT_POLICY = "skip"


class TrainingDivergedError(RuntimeError):
    """Raised under ``DL4J_NAN_GUARD=raise`` when a fused (or host-side)
    optimizer step produces a non-finite loss or gradient.

    Carries the exact location: ``epoch``/``step`` index the sentinel
    tripped at (step = position in that epoch's batch order), plus —
    when the per-step replay could localize it — the ``batch_index``
    into the dataset's batch list and the offending ``loss`` value."""

    def __init__(self, epoch: int, step: int, batch_index=None, loss=None,
                 n_trips: int = 1, where: str = "fused epoch program"):
        self.epoch = int(epoch)
        self.step = int(step)
        self.batch_index = batch_index
        self.loss = loss
        self.n_trips = int(n_trips)
        msg = (f"training diverged in the {where}: non-finite step at "
               f"epoch {epoch}, step {step}")
        if batch_index is not None:
            msg += f" (dataset batch #{batch_index}"
            if loss is not None:
                msg += f", loss={loss}"
            msg += ")"
        if n_trips > 1:
            msg += f"; {n_trips} step(s) tripped in total"
        msg += " [DL4J_NAN_GUARD=raise]"
        super().__init__(msg)


def nan_guard_policy() -> str:
    """Resolve ``DL4J_NAN_GUARD`` (default ``skip``). Unknown values log
    once and fall back to the default rather than killing a training run
    over a typo'd env var."""
    raw = os.environ.get("DL4J_NAN_GUARD", "").strip().lower()
    if not raw:
        return DEFAULT_POLICY
    if raw not in NAN_GUARD_POLICIES:
        logger.warning("DL4J_NAN_GUARD=%r is not one of %s; using %r",
                       raw, NAN_GUARD_POLICIES, DEFAULT_POLICY)
        return DEFAULT_POLICY
    return raw


@traced
def tree_all_finite(tree):
    """Traced scalar bool: every leaf of ``tree`` is everywhere finite.
    Integer leaves (updater step counters) are vacuously finite and
    skipped, so the check is O(float params) elementwise — cheap next to
    the forward+backward that produced the gradients."""
    import jax
    import jax.numpy as jnp

    checks = [jnp.all(jnp.isfinite(leaf))
              for leaf in jax.tree_util.tree_leaves(tree)
              if jnp.issubdtype(jnp.asarray(leaf).dtype, jnp.floating)]
    if not checks:
        return jnp.bool_(True)
    return functools.reduce(jnp.logical_and, checks)
