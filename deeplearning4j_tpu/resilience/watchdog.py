"""StepWatchdog: flag hung training steps.

GSPMD-style multi-host SPMD makes one hung host everyone's problem — the
collective blocks the whole pod, and nothing crashes, so nothing restarts.
The watchdog is the liveness complement to ``HeartbeatMonitor``: the
training loop calls :meth:`StepWatchdog.beat` after every step; a
background thread (same shape as HeartbeatMonitor's) fires ``on_stall``
when no beat lands within ``deadline_s``. The callback decides the policy
— log, evict via the tracker, or abort the process.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Callable, Optional

logger = logging.getLogger(__name__)

__all__ = ["StepWatchdog"]


def _log_stall(stalled_s: float) -> None:
    logger.warning("training step hung: no progress for %.1fs", stalled_s)


class StepWatchdog:
    """Fire ``on_stall(stalled_seconds)`` when no :meth:`beat` arrives
    within ``deadline_s``.

    ``on_stall`` fires once per stall episode (re-armed by the next beat),
    so a log-only callback does not spam while a long step compiles —
    except with ``repeat_every_s`` set, which re-fires that often during
    one continuing stall (escalation policies).

    Context-manager protocol starts/stops the thread; ``beats`` and
    ``stalls`` counters are exposed for tests and metrics.
    """

    def __init__(self, deadline_s: float,
                 on_stall: Optional[Callable[[float], None]] = None,
                 poll_s: Optional[float] = None,
                 repeat_every_s: Optional[float] = None,
                 clock: Callable[[], float] = time.monotonic):
        if deadline_s <= 0:
            raise ValueError(f"deadline_s must be > 0, got {deadline_s}")
        self.deadline_s = deadline_s
        self.on_stall = on_stall or _log_stall
        self.poll_s = poll_s if poll_s is not None else min(deadline_s / 4,
                                                            1.0)
        self.repeat_every_s = repeat_every_s
        self._clock = clock
        self._lock = threading.Lock()
        self._last_beat = clock()
        self._thread: Optional[threading.Thread] = None
        self._stop: Optional[threading.Event] = None
        self.beats = 0
        self.stalls = 0

    # ------------------------------------------------------------------
    def beat(self) -> None:
        """Record progress; re-arms the stall trigger."""
        with self._lock:
            self._last_beat = self._clock()
            self.beats += 1

    def stalled_for(self) -> float:
        with self._lock:
            return self._clock() - self._last_beat

    def set_deadline(self, deadline_s: float) -> None:
        """Rescale the stall deadline mid-run (and re-arm the trigger).
        The chunk driver calls this after an elastic reshard changes the
        per-chunk step count / device width — a legitimate post-shrink
        chunk must not be flagged against the old, wider mesh's budget."""
        if deadline_s <= 0:
            raise ValueError(f"deadline_s must be > 0, got {deadline_s}")
        with self._lock:
            self.deadline_s = deadline_s
            self._last_beat = self._clock()

    # ------------------------------------------------------------------
    def start(self) -> "StepWatchdog":
        if self._thread is not None:
            return self
        stop = threading.Event()
        self._stop = stop
        self.beat()  # the clock starts now, not at construction

        def run():
            fired_at: Optional[float] = None  # beat timestamp last fired on
            last_fire = 0.0
            while not stop.wait(self.poll_s):
                with self._lock:
                    last = self._last_beat
                    stalled = self._clock() - last
                if stalled < self.deadline_s:
                    fired_at = None
                    continue
                refire = (self.repeat_every_s is not None
                          and self._clock() - last_fire
                          >= self.repeat_every_s)
                if fired_at == last and not refire:
                    continue  # already flagged this stall episode
                fired_at = last
                last_fire = self._clock()
                self.stalls += 1
                try:
                    # telemetry first: even an on_stall that aborts the
                    # process leaves the stall on the timeline
                    from deeplearning4j_tpu.monitor import (
                        record_counter, tracer)

                    record_counter("watchdog_stalls_total")
                    tracer().event("watchdog.stall",
                                   stalled_s=round(stalled, 3),
                                   deadline_s=self.deadline_s)
                    self.on_stall(stalled)
                except Exception:  # noqa: BLE001 — callback must not
                    logger.exception("StepWatchdog on_stall raised")

        self._thread = threading.Thread(target=run, daemon=True,
                                        name="step-watchdog")
        self._thread.start()
        return self

    def stop(self) -> None:
        thread, stop = self._thread, self._stop
        if thread is None:
            return  # idempotent, same contract as HeartbeatMonitor.stop
        self._thread = None
        stop.set()
        thread.join(timeout=self.poll_s + 1.0)

    def __enter__(self) -> "StepWatchdog":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()
