"""One retry policy for the whole control plane.

The seed grew ad-hoc retry shapes — ``initialize_distributed`` slept a
fixed 5 s, ``ConfigRegistry.wait_for`` hand-rolled a poll loop,
``FileStateTracker`` and the dataset fetchers had none. ``RetryPolicy``
replaces all of them: exponential backoff with **full jitter** (AWS
architecture-blog shape: each delay is uniform in ``[0, cap]``, which
de-synchronizes a pod's worth of workers hammering one shared filesystem),
a max-attempt bound, an overall deadline, a retryable-exception filter,
and an ``on_retry`` hook for logging/metrics.

Both the sleeper and the jitter RNG are injectable, so tests assert the
exact delay sequence under a seed without ever sleeping.
"""

from __future__ import annotations

import logging
import random
import time
from dataclasses import dataclass, field
from typing import Callable, Optional, Tuple, Type, Union

logger = logging.getLogger(__name__)

__all__ = ["RetryPolicy", "RetryError", "is_retryable", "no_jitter"]

RetryableSpec = Union[Tuple[Type[BaseException], ...],
                      Callable[[BaseException], bool]]


class RetryError(RuntimeError):
    """All attempts exhausted. ``last`` holds the final exception (also
    chained as ``__cause__``); ``attempts`` how many were made."""

    def __init__(self, message: str, last: BaseException, attempts: int):
        super().__init__(message)
        self.last = last
        self.attempts = attempts


def no_jitter(lo: float, hi: float) -> float:
    """Deterministic 'jitter' pinning each delay to its cap — use in tests
    that want the raw exponential sequence."""
    return hi


def is_retryable(exc: BaseException, spec: RetryableSpec) -> bool:
    """Shared retryable test (RetryPolicy AND GrantLease): a bare
    exception class/tuple is a membership test, NOT a predicate —
    treating it as one would call OSError(exc) (always truthy) and retry
    everything, Ctrl-C included."""
    if isinstance(spec, tuple) or (isinstance(spec, type)
                                   and issubclass(spec, BaseException)):
        return isinstance(exc, spec)
    return bool(spec(exc))


@dataclass
class RetryPolicy:
    """Exponential backoff + full jitter.

    Delay before attempt ``k`` (k = 1 is the first *retry*) is drawn
    uniformly from ``[0, min(max_delay_s, base_delay_s * multiplier**(k-1))]``.
    ``multiplier=1.0`` gives fixed-interval polling (registry watch loops).

    - ``max_attempts``: total tries including the first (None = unbounded,
      bound by ``deadline_s`` instead; at least one bound is required).
    - ``deadline_s``: overall wall-clock budget; once exceeded, no further
      attempt is made.
    - ``retryable``: exception types (tuple) or a predicate; anything else
      propagates immediately.
    - ``on_retry(attempt, exc, delay_s)``: observability hook, called
      before each backoff sleep.
    - ``sleep`` / ``rng``: injectable for deterministic tests; ``seed``
      builds a private ``random.Random`` so two policies with the same
      seed produce identical jitter sequences.
    """

    max_attempts: Optional[int] = 5
    base_delay_s: float = 0.1
    max_delay_s: float = 30.0
    multiplier: float = 2.0
    deadline_s: Optional[float] = None
    retryable: RetryableSpec = (Exception,)
    on_retry: Optional[Callable[[int, BaseException, float], None]] = None
    sleep: Callable[[float], None] = time.sleep
    seed: Optional[int] = None
    rng: Optional[Callable[[float, float], float]] = None
    monotonic: Callable[[], float] = field(default=time.monotonic)

    def __post_init__(self):
        if self.max_attempts is None and self.deadline_s is None:
            raise ValueError("RetryPolicy needs max_attempts or deadline_s "
                             "(otherwise it would retry forever)")
        if self.max_attempts is not None and self.max_attempts < 1:
            raise ValueError(f"max_attempts must be >= 1, "
                             f"got {self.max_attempts}")
        if self.rng is None:
            self.rng = random.Random(self.seed).uniform

    # ------------------------------------------------------------------
    def _is_retryable(self, exc: BaseException) -> bool:
        return is_retryable(exc, self.retryable)

    def delay_for(self, attempt: int) -> float:
        """Backoff delay after failed attempt ``attempt`` (1-based)."""
        cap = min(self.max_delay_s,
                  self.base_delay_s * self.multiplier ** (attempt - 1))
        return self.rng(0.0, cap)

    # ------------------------------------------------------------------
    def call(self, fn: Callable, *args, **kwargs):
        """Run ``fn`` under this policy; returns its value or raises
        :class:`RetryError` (non-retryable exceptions propagate as-is)."""
        start = self.monotonic()
        attempt = 0
        while True:
            attempt += 1
            try:
                return fn(*args, **kwargs)
            except BaseException as e:  # noqa: BLE001 — filtered below
                if not self._is_retryable(e):
                    raise
                out_of_attempts = (self.max_attempts is not None
                                   and attempt >= self.max_attempts)
                wait = self.delay_for(attempt)
                out_of_time = (self.deadline_s is not None
                               and self.monotonic() - start + wait
                               > self.deadline_s)
                if out_of_attempts or out_of_time:
                    raise RetryError(
                        f"{getattr(fn, '__name__', fn)!r} failed after "
                        f"{attempt} attempt(s)"
                        + (" (deadline exceeded)" if out_of_time else "")
                        + f": {e}", last=e, attempts=attempt) from e
                fname = getattr(fn, "__name__", repr(fn))
                if self.on_retry is not None:
                    self.on_retry(attempt, e, wait)
                else:
                    logger.debug("retry %d of %r in %.3fs after %s",
                                 attempt, fname, wait, e)
                # telemetry: every retry counts, every backoff sleep is a
                # span — a run that spent its wall clock backing off shows
                # it on the timeline instead of looking wedged
                from deeplearning4j_tpu.monitor import (record_counter,
                                                        tracer)

                record_counter("retry_attempts_total", fn=fname)
                with tracer().span("retry.sleep", fn=fname,
                                   attempt=attempt,
                                   delay_s=round(wait, 4)):
                    self.sleep(wait)

    def retrying(self, fn: Callable) -> Callable:
        """Decorator form of :meth:`call`."""

        def wrapped(*args, **kwargs):
            return self.call(fn, *args, **kwargs)

        wrapped.__name__ = getattr(fn, "__name__", "retrying")
        return wrapped
