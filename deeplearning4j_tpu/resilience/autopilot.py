"""Goodput autopilot: the observe→act loop over the PR-9 fleet gauges.

PR 9 made fleet health *observable* — the master tick aggregates every
worker's heartbeat payload into gauges (step time, ledger goodput %,
last loss), flags stragglers against the fleet median, and logs
eviction evidence — but nothing *acted* on the evidence: a flagged
straggler kept dragging the barrier, a silent worker waited out the
full static timeout, and a goodput collapse was a postmortem finding
instead of a scheduling event. TensorFlow's design (arXiv 1605.08695)
treats worker failure as a scheduling event; this module is that
scheduler for our fleet.

:class:`GoodputAutopilot` consumes exactly what the master tick already
aggregates — the per-worker payload map, the straggler flag set, the
last-beat timestamps, the run-ledger goodput — and issues three kinds of
decision through caller-provided **actuators** (so every action flows
through the same evidence-logged path the master tick uses: the
trainer's eviction log, the fused driver's ``request_reshard``, the
serve controller's ``evict``):

- ``evict``   — a member silent past ``silence_s``, or flagged as a
  straggler for ``straggler_ticks`` consecutive observations (one noisy
  tick never evicts);
- ``reshard`` — fleet goodput below the floor (``DL4J_GOODPUT_FLOOR``):
  shrink the mesh to the healthy members instead of letting the whole
  run pace at the sick one (actuator wired by the caller that owns the
  network — see the class docstring);
- ``readmit`` — a previously evicted member beating again with a
  healthy payload rejoins (the scheduling event is reversible).

Every decision is recorded as an ``autopilot.decision`` tracer event
carrying the gauge values that triggered it (forwarded into the flight
ring like every event — a chaos soak's artifact shows WHY each action
fired), appended to :attr:`GoodputAutopilot.decisions`, and counted in
``autopilot_decisions_total`` (labeled by action). Actuator failures
mark the decision ``acted=False`` and never crash the control loop.

``DL4J_AUTOPILOT=1`` opts the built-in integrations in
(``DistributedTrainer`` and ``FleetController`` also accept an explicit
``autopilot=`` instance); a ``cooldown_s`` throttle keeps a persistent
condition from flapping decisions every tick.
"""

from __future__ import annotations

import logging
import os
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence

logger = logging.getLogger(__name__)

__all__ = [
    "AutopilotDecision",
    "GoodputAutopilot",
    "autopilot_enabled",
    "goodput_floor",
]

DEFAULT_GOODPUT_FLOOR = 50.0
DEFAULT_SILENCE_S = 30.0
DEFAULT_STRAGGLER_TICKS = 2
# a persistent goodput collapse must not emit a reshard decision per
# tick (~1 s cadence): one decision, then silence until the cooldown
# passes — the condition either resolves (the reshard worked) or the
# next decision fires with fresh gauges
DEFAULT_COOLDOWN_S = 30.0


def autopilot_enabled() -> bool:
    """``DL4J_AUTOPILOT`` opts the built-in control-loop integrations in
    (default off: observe-only fleets behave exactly as before)."""
    return os.environ.get("DL4J_AUTOPILOT", "").strip().lower() in (
        "1", "on", "true")


def goodput_floor() -> float:
    """Fleet goodput floor in percent (``DL4J_GOODPUT_FLOOR``, default
    50): sustained goodput below it triggers a reshard decision."""
    raw = os.environ.get("DL4J_GOODPUT_FLOOR", "")
    try:
        return float(raw) if raw else DEFAULT_GOODPUT_FLOOR
    except ValueError:
        return DEFAULT_GOODPUT_FLOOR


@dataclass
class AutopilotDecision:
    """One evidence-carrying decision. ``gauges`` holds the values that
    triggered it (the observe side); ``acted`` whether the actuator ran
    cleanly (the act side)."""

    action: str            # "evict" | "reshard" | "readmit"
    target: Optional[str]  # worker/replica id (None for fleet-wide)
    reason: str
    gauges: dict = field(default_factory=dict)
    t_wall: float = 0.0
    acted: bool = True

    def to_json(self) -> dict:
        return {"action": self.action, "target": self.target,
                "reason": self.reason, "gauges": dict(self.gauges),
                "t_wall": self.t_wall, "acted": self.acted}


class GoodputAutopilot:
    """Turn fleet gauges into evict/reshard/re-admit decisions.

    Actuators (all optional — a decision with no actuator is still
    evidence-logged, it just isn't executed):

    - ``evict(member_id, decision)`` — drop a member; the trainer wires
      ``DistributedTrainer.evict_worker`` (tracker eviction + the same
      eviction-log entry the master tick writes), the serve fleet wires
      ``FleetController.evict`` (kill + failover).
    - ``reshard(healthy_ids, decision)`` — resize around the sick
      members. NOT auto-wired by the built-in integrations (the
      control-plane trainer and serve controller own no fused network):
      the caller that owns the run wires
      ``reshard=lambda healthy, d: net.request_reshard(...)`` so the
      resize lands at the next chunk boundary through the elastic
      reshard path (the chaos soak in ``tests/test_autopilot.py`` is
      the worked example). Unwired, the decision is still
      evidence-logged with ``acted=False``.
    - ``readmit(member_id, decision)`` — restore an evicted member the
      autopilot sees beating healthily again.

    ``observe()`` is the tick: pass the payload map the master tick
    aggregated plus the straggler set and last-beat timestamps it
    already holds. The autopilot keeps only the cross-tick state the
    gauges cannot carry (straggler streaks, its own evicted set, the
    last decision time for the cooldown).
    """

    def __init__(self, *,
                 floor: Optional[float] = None,
                 silence_s: float = DEFAULT_SILENCE_S,
                 straggler_ticks: int = DEFAULT_STRAGGLER_TICKS,
                 cooldown_s: float = DEFAULT_COOLDOWN_S,
                 clock: Callable[[], float] = time.time,
                 evict: Optional[Callable] = None,
                 reshard: Optional[Callable] = None,
                 readmit: Optional[Callable] = None):
        self.floor = goodput_floor() if floor is None else float(floor)
        self.silence_s = float(silence_s)
        self.straggler_ticks = max(1, int(straggler_ticks))
        self.cooldown_s = float(cooldown_s)
        self.clock = clock
        self._evict = evict
        self._reshard = reshard
        self._readmit = readmit
        self.decisions: List[AutopilotDecision] = []
        self.evicted: set = set()
        self._evicted_at: Dict[str, float] = {}
        self._streaks: Dict[str, int] = {}
        self._last_reshard_t: Optional[float] = None

    def bind(self, *, evict: Optional[Callable] = None,
             reshard: Optional[Callable] = None,
             readmit: Optional[Callable] = None) -> "GoodputAutopilot":
        """Late actuator wiring for integrations that construct the
        autopilot before the object its decisions act on (the trainer
        binds its own evidence-logged evict path here). Only unset
        actuators are filled — an explicitly provided one wins."""
        if self._evict is None:
            self._evict = evict
        if self._reshard is None:
            self._reshard = reshard
        if self._readmit is None:
            self._readmit = readmit
        return self

    # ------------------------------------------------------------------
    def _issue(self, decision: AutopilotDecision,
               actuator: Optional[Callable], *args) -> AutopilotDecision:
        from deeplearning4j_tpu.monitor import record_counter, tracer

        decision.t_wall = self.clock()
        if actuator is not None:
            try:
                actuator(*args, decision)
            except Exception:  # noqa: BLE001 — the control loop survives
                logger.exception("autopilot %s actuator failed for %s",
                                 decision.action, decision.target)
                decision.acted = False
        else:
            decision.acted = False
        # the decision event carries the triggering gauge values — the
        # flight ring gets it via event forwarding, so a postmortem can
        # audit every action against the evidence that justified it
        tracer().event("autopilot.decision", action=decision.action,
                       target=decision.target, reason=decision.reason,
                       acted=decision.acted,
                       **{k: v for k, v in decision.gauges.items()
                          if isinstance(v, (str, int, float, bool))})
        record_counter("autopilot_decisions_total",
                       action=decision.action)
        self.decisions.append(decision)
        return decision

    def _latch_eviction(self, member: str, decision: AutopilotDecision,
                        now: float) -> bool:
        """Record the eviction ONLY when it happened: the actuator ran
        cleanly, or none is bound (advisory mode — latching avoids
        re-advising every tick). A bound actuator that RAISED leaves
        the member un-latched so the next tick retries — a wedged
        worker must not be permanently forgotten over one transient
        tracker error."""
        if decision.acted or self._evict is None:
            self.evicted.add(member)
            self._evicted_at[member] = now
            return True
        return False

    # ------------------------------------------------------------------
    def observe(self, fleet: Dict[str, dict], *,
                stragglers: Sequence[str] = (),
                last_beat: Optional[Dict[str, float]] = None,
                goodput_pct: Optional[float] = None,
                now: Optional[float] = None) -> List[AutopilotDecision]:
        """One observe→act pass. ``fleet`` is the master tick's payload
        map; ``stragglers`` its current flag set; ``last_beat`` the
        wall-clock timestamp of each member's newest beat; ``goodput_pct``
        an explicit fleet goodput override (default: the minimum of the
        members' reported ``goodput_pct`` gauges). Returns the decisions
        issued this pass (also appended to :attr:`decisions`)."""
        now = self.clock() if now is None else now
        out: List[AutopilotDecision] = []
        last_beat = last_beat or {}

        # -- silence ⇒ evict (the wedged-member shape: alive-or-dead,
        #    nothing has told us anything for too long)
        for member, t in sorted(last_beat.items()):
            if member in self.evicted or t is None:
                continue
            silent = now - t
            if silent >= self.silence_s:
                d = self._issue(AutopilotDecision(
                    action="evict", target=member,
                    reason="heartbeat_silence",
                    gauges={"silent_s": round(silent, 3),
                            "silence_timeout_s": self.silence_s,
                            **_compact(fleet.get(member))}),
                    self._evict, member)
                self._latch_eviction(member, d, now)
                out.append(d)

        # -- straggler streak ⇒ evict (one noisy tick never evicts; a
        #    member slow for straggler_ticks consecutive passes does)
        flagged = set(stragglers) - self.evicted
        for member in list(self._streaks):
            if member not in flagged:
                del self._streaks[member]
        for member in sorted(flagged):
            self._streaks[member] = self._streaks.get(member, 0) + 1
            if self._streaks[member] >= self.straggler_ticks:
                d = self._issue(AutopilotDecision(
                    action="evict", target=member,
                    reason="straggler_streak",
                    gauges={"streak_ticks": self.straggler_ticks,
                            **_compact(fleet.get(member))}),
                    self._evict, member)
                if self._latch_eviction(member, d, now):
                    del self._streaks[member]
                else:
                    # actuator raised: hold the streak at the threshold
                    # so the NEXT flagged tick retries the eviction
                    self._streaks[member] = self.straggler_ticks - 1
                out.append(d)

        # -- previously evicted member beating again healthily ⇒
        #    readmit. The beat must be NEWER than the eviction: the
        #    snapshot that justified a straggler eviction this very pass
        #    still carries that member's (fresh) beat, and readmitting
        #    off it would instantly contradict the eviction
        for member in sorted(set(fleet) & self.evicted):
            t = last_beat.get(member)
            if (t is not None and now - t < self.silence_s
                    and t > self._evicted_at.get(member, float("-inf"))):
                self.evicted.discard(member)
                self._evicted_at.pop(member, None)
                self._streaks.pop(member, None)
                out.append(self._issue(AutopilotDecision(
                    action="readmit", target=member,
                    reason="healthy_beat_after_eviction",
                    gauges={"silent_s": round(now - t, 3),
                            **_compact(fleet.get(member))}),
                    self._readmit, member))

        # -- goodput floor ⇒ reshard around the healthy members
        gp = goodput_pct
        if gp is None:
            reported = [float(m["goodput_pct"]) for m in fleet.values()
                        if isinstance(m.get("goodput_pct"), (int, float))]
            gp = min(reported) if reported else None
        if gp is not None and gp < self.floor:
            cooled = (self._last_reshard_t is None
                      or now - self._last_reshard_t >= self.cooldown_s)
            if cooled:
                self._last_reshard_t = now
                healthy = sorted(set(fleet) - self.evicted - flagged)
                out.append(self._issue(AutopilotDecision(
                    action="reshard", target=None,
                    reason="goodput_below_floor",
                    gauges={"goodput_pct": round(float(gp), 2),
                            "floor_pct": self.floor,
                            "healthy": ",".join(healthy),
                            "n_healthy": len(healthy)}),
                    self._reshard, healthy))
        return out


def _compact(payload: Optional[dict]) -> dict:
    """The scalar slice of a heartbeat payload — the gauge values a
    decision event can carry verbatim."""
    if not payload:
        return {}
    return {k: v for k, v in payload.items()
            if isinstance(v, (str, int, float, bool))}
