"""PreemptionGuard: turn SIGTERM (and injected preemptions) into a clean
chunk-boundary stop instead of a killed process.

TPU VMs and spot/preemptible instances get a termination signal with a
grace window; the reference's YARN story was "the container dies, the AM
restarts it". Under whole-epoch fusion the unit of lost work is an entire
E x N chunk, so the guard's job is: notice the request, let the in-flight
chunk finish, checkpoint (params + updater state + epoch RNG key +
epoch/step cursors — see ``FaultTolerantTrainer.save``/``save_async``),
and stop. ``resume()`` then re-derives the epoch permutation from the pure
``epoch_schedule`` key stream and continues exactly where the dead process
stopped — bitwise, because the per-chunk key splits are a pure function of
the restored RNG key.

Two trigger paths:

- **SIGTERM/SIGINT** — ``install()`` chains a handler that sets a flag
  (and re-raises KeyboardInterrupt semantics are NOT preserved: the guard
  is for orderly preemption, not ctrl-C debugging — pass ``signals=()``
  to opt out).
- **``fault_point("preempt.chunk")``** — every :meth:`check` polls the
  named fault site, so chaos tests (and ``DL4J_FAULTS``) inject a
  deterministic preemption at an exact chunk boundary:
  ``DL4J_FAULTS="preempt.chunk=fail_nth:2"`` preempts at the second
  boundary.

The guard is poll-based on purpose: a signal can land mid-XLA-dispatch,
and the only safe reaction point is the host decision point between
chunks.
"""

from __future__ import annotations

import logging
import signal
import threading
from typing import Optional, Sequence

from deeplearning4j_tpu.resilience import faults

logger = logging.getLogger(__name__)

__all__ = ["PreemptionGuard", "PREEMPT_CHUNK_SITE"]

PREEMPT_CHUNK_SITE = "preempt.chunk"


class PreemptionGuard:
    """Latches a preemption request from SIGTERM or the
    ``preempt.chunk`` fault site; callers poll :meth:`check` at chunk
    boundaries.

    Context-manager protocol installs/uninstalls the signal handlers;
    previous handlers are chained (a framework above us — e.g. a cluster
    launcher's own SIGTERM hook — still sees the signal)."""

    def __init__(self, signals: Sequence[int] = (signal.SIGTERM,)):
        self.signals = tuple(signals)
        self._requested = threading.Event()
        self._pending_latch = None
        self._prev = {}
        self._installed = False

    # ------------------------------------------------------------------
    def install(self) -> "PreemptionGuard":
        if self._installed:
            return self
        for sig in self.signals:
            try:
                self._prev[sig] = signal.signal(sig, self._on_signal)
            except ValueError:
                # signal.signal only works in the main thread; a guard
                # created on a worker thread degrades to fault-site +
                # request() triggering only
                logger.debug("PreemptionGuard: cannot install handler "
                             "for signal %s off the main thread", sig)
        self._installed = True
        return self

    def uninstall(self) -> None:
        if not self._installed:
            return
        for sig, prev in self._prev.items():
            try:
                signal.signal(sig, prev)
            except ValueError:
                pass
        self._prev.clear()
        self._installed = False

    def __enter__(self) -> "PreemptionGuard":
        return self.install()

    def __exit__(self, *exc) -> None:
        self.uninstall()

    # ------------------------------------------------------------------
    def _on_signal(self, signum, frame) -> None:
        logger.warning("preemption signal %s received; will checkpoint "
                       "and stop at the next chunk boundary", signum)
        self._requested.set()
        # telemetry is NOT recorded here: the handler runs on the main
        # thread, and record_counter/tracer take non-reentrant locks the
        # interrupted frame may already hold — a latch must never
        # deadlock the preemption it reports. The next requested()/
        # check() poll (every chunk boundary) flushes it.
        # CPython delivers signal handlers on the main thread only, so
        # _pending_latch is main-thread-confined; a lock here could
        # deadlock the very frame the handler interrupted.
        self._pending_latch = (  # dl4j-lint: disable=lock-discipline -- signal handlers run on the main thread: no concurrent writer exists
            "signal", {"signum": signum})
        prev = self._prev.get(signum)
        if callable(prev):
            prev(signum, frame)

    def _flush_pending_latch(self) -> None:
        pending = self._pending_latch
        if pending is not None:
            self._pending_latch = None  # dl4j-lint: disable=lock-discipline -- main-thread-confined: the only other writer is the signal handler, which CPython delivers on this same thread
            _latch_telemetry(pending[0], **pending[1])

    def request(self) -> None:
        """Programmatic preemption (tests, cloud metadata watchers)."""
        self._requested.set()
        _latch_telemetry("request")

    def requested(self) -> bool:
        self._flush_pending_latch()
        return self._requested.is_set()

    def check(self) -> bool:
        """Poll both trigger paths; returns True once preemption has been
        requested. An injected fault at ``preempt.chunk`` counts as a
        request (the injection IS the preemption notice)."""
        self._flush_pending_latch()
        if not self._requested.is_set():
            try:
                faults.fault_point(PREEMPT_CHUNK_SITE)
            except Exception:  # noqa: BLE001 — any injected exception
                logger.warning("injected preemption at %s; will "
                               "checkpoint and stop at this chunk "
                               "boundary", PREEMPT_CHUNK_SITE)
                self._requested.set()
                _latch_telemetry("fault")
        return self._requested.is_set()


def _latch_telemetry(source: str, **attrs) -> None:
    """Count + timeline-stamp a preemption latch. Best-effort: it can run
    inside a signal handler, where telemetry must never raise."""
    try:
        from deeplearning4j_tpu.monitor import record_counter, tracer

        record_counter("preemption_latches_total", source=source)
        tracer().event("preemption.latch", source=source, **attrs)
    except Exception:  # noqa: BLE001
        pass
