"""Compiled-program performance observatory: per-program cost/memory profiles.

Every TFLOP/s and MFU figure the bench has ever printed came from a
hand-written analytic formula, and the per-shard HBM budget model
(``perf/epoch_cache.py``) has never been checked against what the compiler
actually allocates. This module closes both gaps at the source of truth —
the compiled XLA artifact:

- :class:`ProgramProfile` — one cached fused program's identity (model
  name + the ``(shuffle, K, guard, stride)`` cache key + the arg-shape
  signature) and its compiled-artifact numbers: ``cost_analysis()`` FLOPs
  and bytes-accessed, ``memory_analysis()`` argument/output/temp/alias/
  generated-code HBM (and the derived peak), and the lowering + compile
  wall times.
- :class:`ProfiledProgram` — the wrapper the ``_epoch_steps`` caches on
  both network classes and ``ParallelWrapper`` store. With
  ``DL4J_PROFILE`` off (the default) every call passes straight through
  to the wrapped ``jax.jit`` function: the executed program is the
  unwrapped program, bit for bit. With it on, the first call per
  arg-shape signature AOT-lowers and compiles the SAME function, harvests
  the profile, and runs the compiled executable from then on — exactly
  one compile per signature either way, so profiling changes WHEN the
  numbers are read, never WHAT runs.
- :func:`capture_program_profile` — the one-shot harvest for programs
  outside the epoch caches (``bench.py`` profiles the single-step and
  transformer programs with it).
- :func:`classify_boundedness` — the cost model's step-time
  decomposition: optimal compute time (FLOPs / peak FLOP/s) vs optimal
  memory time (bytes accessed / peak HBM bandwidth) vs the measured step
  time; the gap above the optimum is dispatch/overhead, and the larger
  optimum names the section compute- or memory-bound.

Profiles land in a process-global :class:`ProfileStore` (``profiles()``)
and are mirrored into the :class:`MetricsRegistry` (``program_flops``,
``program_bytes_accessed``, ``program_peak_hbm_bytes`` gauges +
``program_compile_seconds`` histogram, labeled by program/key) so every
exporter — and every bench artifact, including error-path partial flushes
— carries them beside the spans.

Profile collection is a HOST-side readback (compile introspection,
device ``memory_stats``). It is only permitted at chunk boundaries —
dl4j-lint's host-sync rule flags any profile-collection call reachable
from a hot path (see ``analysis/rules.py`` ``PROFILE_READBACK_CALLS``).

This module is stdlib-only at import (jax loads lazily inside the
capture paths) so ``deeplearning4j_tpu.monitor`` stays importable before
— or without — a backend.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

logger = logging.getLogger(__name__)

__all__ = [
    "ProgramProfile",
    "ProfileStore",
    "ProfiledProgram",
    "capture_program_profile",
    "classify_boundedness",
    "flops_divergence_pct",
    "profile_enabled",
    "profiles",
]

_ON = ("1", "on", "true", "yes")


def profile_enabled() -> bool:
    """``DL4J_PROFILE``: ``on`` captures a :class:`ProgramProfile` for
    every cached fused program (AOT lower + compile on first call per
    signature) and samples HBM watermarks at chunk boundaries. Default
    OFF — the fused program and its call path are the unwrapped
    ``jax.jit`` program, bit for bit."""
    return os.environ.get("DL4J_PROFILE", "").strip().lower() in _ON


class ProgramProfile:
    """One compiled program's cost/memory analysis + compile timing."""

    __slots__ = ("name", "key", "signature", "flops", "bytes_accessed",
                 "optimal_seconds", "argument_bytes", "output_bytes",
                 "temp_bytes", "alias_bytes", "generated_code_bytes",
                 "peak_bytes", "lower_s", "compile_s", "n_devices",
                 "error")

    def __init__(self, name: str, key: Any, signature: Any):
        self.name = name
        self.key = key
        self.signature = signature
        self.flops: Optional[float] = None
        self.bytes_accessed: Optional[float] = None
        self.optimal_seconds: Optional[float] = None
        self.argument_bytes: Optional[int] = None
        self.output_bytes: Optional[int] = None
        self.temp_bytes: Optional[int] = None
        self.alias_bytes: Optional[int] = None
        self.generated_code_bytes: Optional[int] = None
        self.peak_bytes: Optional[int] = None
        self.lower_s: Optional[float] = None
        self.compile_s: Optional[float] = None
        self.n_devices: Optional[int] = None
        self.error: Optional[str] = None

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "key": list(self.key) if isinstance(self.key, tuple)
            else self.key,
            "signature": str(self.signature),
            "flops": self.flops,
            "bytes_accessed": self.bytes_accessed,
            "optimal_seconds": self.optimal_seconds,
            "argument_bytes": self.argument_bytes,
            "output_bytes": self.output_bytes,
            "temp_bytes": self.temp_bytes,
            "alias_bytes": self.alias_bytes,
            "generated_code_bytes": self.generated_code_bytes,
            "peak_bytes": self.peak_bytes,
            "lower_s": self.lower_s,
            "compile_s": self.compile_s,
            "n_devices": self.n_devices,
            "error": self.error,
        }

    def __repr__(self) -> str:
        return (f"ProgramProfile({self.name!r}, key={self.key}, "
                f"flops={self.flops}, peak_bytes={self.peak_bytes}, "
                f"compile_s={self.compile_s})")


class ProfileStore:
    """Thread-safe collection of captured profiles (process-global via
    ``profiles()``; tests construct private stores)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._profiles: List[ProgramProfile] = []

    def add(self, profile: ProgramProfile) -> None:
        with self._lock:
            self._profiles.append(profile)

    def all(self) -> List[ProgramProfile]:
        with self._lock:
            return list(self._profiles)

    def find(self, name: Optional[str] = None,
             key: Optional[Any] = None) -> List[ProgramProfile]:
        return [p for p in self.all()
                if (name is None or p.name == name)
                and (key is None or p.key == key)]

    def snapshot(self) -> List[dict]:
        """JSON-ready list — the ``extras["profile"]["programs"]`` block
        bench artifacts (and their error-path partial flushes) embed."""
        return [p.to_dict() for p in self.all()]

    def reset(self) -> None:
        with self._lock:
            self._profiles.clear()


_STORE: Optional[ProfileStore] = None
_STORE_LOCK = threading.Lock()


def profiles() -> ProfileStore:
    """The process-global profile store every capture lands in."""
    global _STORE
    if _STORE is None:
        with _STORE_LOCK:
            if _STORE is None:
                _STORE = ProfileStore()
    return _STORE


# ---------------------------------------------------------------------------
# harvest helpers
# ---------------------------------------------------------------------------


def _signature_of(args) -> Tuple:
    """Hashable (shape, dtype) tuple over the arg pytree's leaves — the
    per-compilation identity a jitted function re-specializes on."""
    import jax

    return tuple(
        (tuple(getattr(leaf, "shape", ())),
         str(getattr(leaf, "dtype", type(leaf).__name__)))
        for leaf in jax.tree_util.tree_leaves(args))


def _harvest_cost(compiled, profile: ProgramProfile) -> None:
    """``compiled.cost_analysis()`` → FLOPs / bytes-accessed / optimal
    seconds (a list of per-partition dicts on some jax versions, a dict
    on others; missing keys stay None)."""
    try:
        ca = compiled.cost_analysis()
    except Exception as e:  # backend without cost analysis
        profile.error = f"cost_analysis: {type(e).__name__}: {e}"[:200]
        return
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    if not ca:
        return
    profile.flops = _maybe_float(ca.get("flops"))
    profile.bytes_accessed = _maybe_float(ca.get("bytes accessed"))
    profile.optimal_seconds = _maybe_float(ca.get("optimal_seconds"))


def _harvest_memory(compiled, profile: ProgramProfile) -> None:
    """``compiled.memory_analysis()`` → argument/output/temp/alias/code
    bytes and the derived peak: arguments + outputs + temporaries +
    generated code, minus aliased (donated) buffers, which XLA reuses
    in place — a conservative model of the program's HBM high-water."""
    try:
        ma = compiled.memory_analysis()
    except Exception as e:
        profile.error = f"memory_analysis: {type(e).__name__}: {e}"[:200]
        return
    if ma is None:
        return
    profile.argument_bytes = _maybe_int(
        getattr(ma, "argument_size_in_bytes", None))
    profile.output_bytes = _maybe_int(
        getattr(ma, "output_size_in_bytes", None))
    profile.temp_bytes = _maybe_int(
        getattr(ma, "temp_size_in_bytes", None))
    profile.alias_bytes = _maybe_int(
        getattr(ma, "alias_size_in_bytes", None))
    profile.generated_code_bytes = _maybe_int(
        getattr(ma, "generated_code_size_in_bytes", None))
    parts = [profile.argument_bytes, profile.output_bytes,
             profile.temp_bytes, profile.generated_code_bytes]
    if any(p is not None for p in parts):
        peak = sum(p or 0 for p in parts) - (profile.alias_bytes or 0)
        profile.peak_bytes = max(0, peak)


def _maybe_float(v) -> Optional[float]:
    try:
        return None if v is None else float(v)
    except (TypeError, ValueError):
        return None


def _maybe_int(v) -> Optional[int]:
    try:
        return None if v is None else int(v)
    except (TypeError, ValueError):
        return None


def _register(profile: ProgramProfile) -> None:
    """Mirror the profile into the global MetricsRegistry so exporters
    (JSONL, Prometheus, the bench telemetry block) see it beside spans."""
    from deeplearning4j_tpu.monitor import record_counter
    from deeplearning4j_tpu.monitor.registry import metrics

    reg = metrics()
    labels = {"program": profile.name, "key": str(profile.key)}
    if profile.flops is not None:
        reg.gauge("program_flops",
                  "cost-analysis FLOPs per program execution").set(
            profile.flops, **labels)
    if profile.bytes_accessed is not None:
        reg.gauge("program_bytes_accessed",
                  "cost-analysis bytes accessed per execution").set(
            profile.bytes_accessed, **labels)
    if profile.peak_bytes is not None:
        reg.gauge("program_peak_hbm_bytes",
                  "memory-analysis peak (arg+out+temp+code-alias)").set(
            profile.peak_bytes, **labels)
    if profile.compile_s is not None:
        reg.histogram("program_compile_seconds",
                      "XLA compile wall time per profiled program"
                      ).observe(profile.compile_s, program=profile.name)
    record_counter("program_profiles_total", program=profile.name,
                   outcome="error" if profile.error else "ok")


def capture_program_profile(fn, args, *, name: str, key: Any = (),
                            store: Optional[ProfileStore] = None):
    """AOT-lower and compile jitted ``fn`` on ``args``, harvest its
    cost/memory analysis and compile timing, register the profile, and
    return ``(profile, compiled)``. ``lower`` only reads the args'
    avals — donated buffers are NOT consumed; only executing the
    returned ``compiled`` does that. Runs inside a ``profile.capture``
    span (compile-cache visibility: the wall times land on the
    timeline)."""
    from deeplearning4j_tpu.monitor import tracer

    profile = ProgramProfile(name, key, _signature_of(args))
    with tracer().span("profile.capture", program=name,
                       key=str(key)) as sp:
        t0 = time.perf_counter()
        lowered = fn.lower(*args)
        profile.lower_s = round(time.perf_counter() - t0, 6)
        t1 = time.perf_counter()
        compiled = lowered.compile()
        profile.compile_s = round(time.perf_counter() - t1, 6)
        _harvest_cost(compiled, profile)
        _harvest_memory(compiled, profile)
        try:
            import jax

            profile.n_devices = len(jax.devices())
        except Exception:
            pass
        sp.attrs.update(flops=profile.flops,
                        peak_bytes=profile.peak_bytes,
                        compile_s=profile.compile_s)
    (store if store is not None else profiles()).add(profile)
    _register(profile)
    return profile, compiled


class ProfiledProgram:
    """The ``_epoch_steps`` cache entry: a jitted fused program plus its
    observatory.

    Transparent by construction: attribute access (``lower``, ``trace``
    — the program-contract checker's surface) delegates to the wrapped
    jit function, tracer-valued calls (``jax.eval_shape`` /
    ``make_jaxpr`` re-tracing) pass straight through, and with
    ``DL4J_PROFILE`` off so does every execution. With it on, the first
    call per arg-shape signature compiles via the AOT path (one compile,
    same program) and captures the :class:`ProgramProfile`; later calls
    run the cached executable. A capture failure logs once and falls
    back to the plain jit path — profiling must never kill training."""

    def __init__(self, fn, *, name: str, key: Any):
        self._fn = fn
        self.name = name
        self.key = key
        self._compiled: Dict[Tuple, Any] = {}
        self.profiles: List[ProgramProfile] = []

    def __call__(self, *args):
        if not profile_enabled():
            return self._fn(*args)
        import jax

        if any(isinstance(leaf, jax.core.Tracer)
               for leaf in jax.tree_util.tree_leaves(args)):
            return self._fn(*args)  # being re-traced, not executed
        sig = _signature_of(args)
        compiled = self._compiled.get(sig)
        if compiled is None:
            try:
                prof, compiled = capture_program_profile(
                    self._fn, args, name=self.name, key=self.key)
                self.profiles.append(prof)
            except Exception as e:
                logger.warning(
                    "profile capture for %s%s failed (%s); falling back "
                    "to the plain jit path", self.name, self.key, e)
                compiled = False
            self._compiled[sig] = compiled
        if compiled is False:
            return self._fn(*args)
        return compiled(*args)

    def __getattr__(self, attr):
        return getattr(self._fn, attr)

    def __repr__(self) -> str:
        return (f"ProfiledProgram({self.name!r}, key={self.key}, "
                f"profiles={len(self.profiles)})")


# ---------------------------------------------------------------------------
# the cost model's step-time decomposition
# ---------------------------------------------------------------------------


def classify_boundedness(flops: Optional[float],
                         bytes_accessed: Optional[float],
                         measured_s: Optional[float],
                         peak_flops_per_s: float,
                         peak_bytes_per_s: float) -> dict:
    """Decompose a measured step time against the compiled cost model.

    ``optimal_compute_s`` = FLOPs / peak FLOP/s and ``optimal_memory_s``
    = bytes accessed / peak HBM bandwidth are the two roofline floors;
    the larger one is the program's optimal device time and names it
    compute- or memory-bound. Whatever the measured step time spends
    ABOVE that optimum is dispatch/overhead wait (host launch, link,
    queueing) — the decomposition that tells a perf PR whether to chase
    kernels or dispatch."""
    out = {
        "flops": flops,
        "bytes_accessed": bytes_accessed,
        "measured_s": measured_s,
        "optimal_compute_s": None,
        "optimal_memory_s": None,
        "optimal_s": None,
        "dispatch_wait_s": None,
        "dispatch_wait_pct": None,
        "arithmetic_intensity": None,
        "bound": None,
    }
    if flops is not None and peak_flops_per_s > 0:
        out["optimal_compute_s"] = flops / peak_flops_per_s
    if bytes_accessed is not None and peak_bytes_per_s > 0:
        out["optimal_memory_s"] = bytes_accessed / peak_bytes_per_s
    if flops is not None and bytes_accessed:
        out["arithmetic_intensity"] = flops / bytes_accessed
    floors = [s for s in (out["optimal_compute_s"],
                          out["optimal_memory_s"]) if s is not None]
    if floors:
        out["optimal_s"] = max(floors)
        if (out["optimal_compute_s"] is not None
                and out["optimal_memory_s"] is not None):
            out["bound"] = ("compute"
                            if out["optimal_compute_s"]
                            >= out["optimal_memory_s"] else "memory")
        elif out["optimal_compute_s"] is not None:
            out["bound"] = "compute"
        else:
            out["bound"] = "memory"
    if measured_s is not None and out["optimal_s"] is not None:
        out["dispatch_wait_s"] = max(0.0, measured_s - out["optimal_s"])
        if measured_s > 0:
            out["dispatch_wait_pct"] = round(
                100.0 * out["dispatch_wait_s"] / measured_s, 2)
    return out


def flops_divergence_pct(analytic: Optional[float],
                         cost_analysis: Optional[float]
                         ) -> Optional[float]:
    """Signed divergence of the compiled cost-analysis FLOPs from the
    analytic formula, as a percentage of the analytic value (positive:
    the compiler counts MORE work than the formula). None when either
    side is missing or the analytic value is zero."""
    if not analytic or cost_analysis is None:
        return None
    return round(100.0 * (cost_analysis - analytic) / analytic, 2)
