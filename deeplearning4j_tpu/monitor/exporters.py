"""Telemetry exporters: JSONL event log, Prometheus textfile, summary block.

Three sinks, one source of truth (the :mod:`registry` snapshot and the
:mod:`trace` ring):

- **JSONL** (``DL4J_TELEMETRY_DIR/telemetry.jsonl``) — append-only event
  log; each line is ``{"kind": "span"|"metrics", "t_wall": <unix>, ...}``.
  The span sink streams every finished span; ``export_metrics_jsonl``
  appends a registry snapshot on demand (drive/bench call it per run).
- **Prometheus textfile** (``DL4J_TELEMETRY_DIR/metrics.prom``) — the
  node-exporter textfile-collector dialect, one snapshot per write; a
  scraper (or a human) reads counters/gauges/histograms with labels.
- **Summary block** (``telemetry_summary()``) — the dict embedded in
  every ``BENCH_*.json`` / ``bench_partial.json``: metrics snapshot +
  per-span-name aggregates + the recent-span timeline, so a wedged grant
  leaves a diagnosable artifact instead of a bare error line.

All exporters degrade silently on I/O errors — telemetry must never be
the thing that kills a training run.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from typing import Callable, Optional

logger = logging.getLogger(__name__)

__all__ = [
    "JsonlExporter",
    "export_metrics_jsonl",
    "span_sink_from_env",
    "telemetry_dir",
    "telemetry_summary",
    "write_prometheus_textfile",
]

JSONL_NAME = "telemetry.jsonl"
PROM_NAME = "metrics.prom"


def telemetry_dir() -> Optional[str]:
    """``DL4J_TELEMETRY_DIR`` — directory for the JSONL event log and the
    Prometheus textfile; unset disables file export entirely."""
    d = os.environ.get("DL4J_TELEMETRY_DIR", "").strip()
    return d or None


class JsonlExporter:
    """Append-only JSON-lines writer (thread-safe, best-effort I/O).

    Disk use is BOUNDED: once the file exceeds ``max_bytes`` it rotates
    through the flight recorder's shift mechanism (``path`` →
    ``path.1`` → … up to ``backups`` files, oldest overwritten), so an
    always-on span sink can no longer grow ``telemetry.jsonl`` without
    limit. Defaults come from the shared segment knobs
    (``DL4J_FLIGHT_SEGMENT_KB`` / ``DL4J_FLIGHT_SEGMENTS``); pass
    ``max_bytes=0`` for the legacy unbounded behavior."""

    def __init__(self, path: str, max_bytes: Optional[int] = None,
                 backups: Optional[int] = None):
        from deeplearning4j_tpu.monitor.flight import (
            max_segments, segment_bytes)

        self.path = path
        self.max_bytes = (segment_bytes() if max_bytes is None
                          else int(max_bytes))
        self.backups = (max_segments() - 1 if backups is None
                        else max(0, int(backups)))
        self._lock = threading.Lock()
        self._size: Optional[int] = None
        self._warned = False

    def write(self, record: dict) -> None:
        from deeplearning4j_tpu.monitor.flight import shift_rotate

        line = json.dumps(record, default=_json_default) + "\n"
        try:
            with self._lock:
                os.makedirs(os.path.dirname(self.path) or ".",
                            exist_ok=True)
                if self._size is None:
                    try:
                        self._size = os.path.getsize(self.path)
                    except OSError:
                        self._size = 0
                if (self.max_bytes > 0 and self._size > 0
                        and self._size + len(line) > self.max_bytes):
                    try:
                        shift_rotate(self.path, self.backups)
                    except FileNotFoundError:
                        # the live file vanished externally (operator
                        # cleanup, foreign logrotate): nothing to
                        # rotate — fall through and recreate it
                        pass
                    self._size = 0
                with open(self.path, "a") as f:
                    f.write(line)
                self._size += len(line)
        except OSError as e:
            if not self._warned:  # complain once, not per event
                self._warned = True
                logger.warning("telemetry JSONL write to %s failed: %s "
                               "(further failures silent)", self.path, e)


def _json_default(o):
    try:
        return float(o)
    except (TypeError, ValueError):
        return str(o)


def span_sink_from_env() -> Optional[Callable[[dict], None]]:
    """A span sink streaming to ``DL4J_TELEMETRY_DIR/telemetry.jsonl``,
    or None when the env var is unset (tracing stays in-memory only)."""
    d = telemetry_dir()
    if d is None:
        return None
    exporter = JsonlExporter(os.path.join(d, JSONL_NAME))

    def sink(span_dict: dict) -> None:
        exporter.write({"kind": "span", "t_wall": time.time(),
                        **span_dict})

    return sink


def export_metrics_jsonl(registry=None, path: Optional[str] = None
                         ) -> Optional[str]:
    """Append one registry snapshot to the JSONL log; returns the path
    written (None when no directory is configured and no path given)."""
    if registry is None:
        from deeplearning4j_tpu.monitor.registry import metrics

        registry = metrics()
    if path is None:
        d = telemetry_dir()
        if d is None:
            return None
        path = os.path.join(d, JSONL_NAME)
    JsonlExporter(path).write({"kind": "metrics", "t_wall": time.time(),
                               "metrics": registry.snapshot()})
    return path


def write_prometheus_textfile(registry=None, path: Optional[str] = None
                              ) -> Optional[str]:
    """Write the registry as a Prometheus textfile snapshot (atomic
    tmp+rename, so a scraper never reads a torn file). Returns the path,
    or None when no directory is configured and no path given."""
    if registry is None:
        from deeplearning4j_tpu.monitor.registry import metrics

        registry = metrics()
    if path is None:
        d = telemetry_dir()
        if d is None:
            return None
        path = os.path.join(d, PROM_NAME)
    try:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            f.write(registry.to_prometheus())
        os.replace(tmp, path)
    except OSError as e:
        logger.warning("prometheus textfile write to %s failed: %s",
                       path, e)
        return None
    return path


def telemetry_summary(registry=None, span_tracer=None,
                      recent_spans: int = 40) -> dict:
    """The metrics+span summary block bench artifacts embed: registry
    snapshot, per-span-name aggregates, the recent-span timeline, and
    the run ledger's goodput/badput report."""
    if registry is None:
        from deeplearning4j_tpu.monitor.registry import metrics

        registry = metrics()
    if span_tracer is None:
        from deeplearning4j_tpu.monitor.trace import tracer

        span_tracer = tracer()
    out = {
        "metrics": registry.snapshot(),
        "spans": span_tracer.summary(recent=recent_spans),
    }
    try:
        from deeplearning4j_tpu.monitor.ledger import run_ledger

        out["ledger"] = run_ledger().report(spans=span_tracer.spans())
    except Exception as e:  # the ledger must never break an artifact
        logger.warning("run-ledger report failed: %s", e)
    return out
