"""The in-program metrics pack: device-side per-step training diagnostics.

The fused epoch pipeline collapses E x N optimizer steps into one XLA
dispatch — per-step host listeners cannot observe gradient health without
breaking the fusion with E*N device syncs. The metrics pack moves the
observation INTO the program, exactly like the NaN sentinel: each fused
step optionally emits a ``[4]`` f32 vector

    [grad global-norm, update global-norm, param global-norm, lr scale]

which the epoch scan stacks into an ``[E, N, 4]`` history returned beside
the loss (and sentinel) histories — one readback per chunk, zero extra
syncs. ``DL4J_TELEMETRY=off`` (the default) compiles the pack out
entirely: the program is the PR-5 program, bitwise
(``tests/test_telemetry.py`` asserts it). A stride > 1
(``DL4J_TELEMETRY_STRIDE``) computes the norms only on every stride-th
iteration via ``lax.cond`` (off-stride rows are NaN — unmistakably "not
measured", never confusable with a zero norm), bounding the overhead on
models where three global norms per step are not already noise.

Semantics under the sentinel: a tripped (skipped) step carries params
unchanged, so its update norm is exactly 0 and its param norm equals the
pre-step norm; the grad norm is whatever non-finite value tripped it —
the diagnostic signal the skip policy's end-of-run warning points at.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from deeplearning4j_tpu.analysis.annotations import traced

__all__ = ["METRIC_NAMES", "N_METRICS", "step_metrics",
           "tree_global_norm"]

# column order of the [E, N, 4] metrics history
METRIC_NAMES = ("grad_norm", "update_norm", "param_norm", "lr_scale")
N_METRICS = len(METRIC_NAMES)


@traced
def tree_global_norm(tree):
    """Traced f32 global L2 norm over every floating leaf of ``tree``
    (integer leaves — updater step counters — are skipped). Accumulates
    in f32 regardless of leaf dtype so bf16 params do not overflow the
    sum of squares."""
    sq = [jnp.sum(jnp.square(leaf.astype(jnp.float32)))
          for leaf in jax.tree_util.tree_leaves(tree)
          if jnp.issubdtype(jnp.asarray(leaf).dtype, jnp.floating)]
    if not sq:
        return jnp.float32(0.0)
    return jnp.sqrt(functools.reduce(jnp.add, sq))


@traced
def step_metrics(params, new_params, grads, lr_scale, iteration,
                 stride: int):
    """The ``[4]`` f32 metrics vector for one fused optimizer step.

    ``params``/``new_params`` are the pre-/post-step trees (their
    difference is the applied update — the optimizer-adapted direction
    actually taken, not the raw gradient), ``lr_scale`` the traced
    effective LR multiplier. ``stride > 1`` gates the norm computation
    behind ``lax.cond`` on the traced iteration counter; skipped rows
    are NaN."""

    def compute(_):
        upd = jax.tree_util.tree_map(
            lambda a, b: a.astype(jnp.float32) - b.astype(jnp.float32),
            new_params, params)
        return jnp.stack([
            tree_global_norm(grads),
            tree_global_norm(upd),
            tree_global_norm(new_params),
            jnp.asarray(lr_scale, jnp.float32),
        ])

    if stride <= 1:
        return compute(None)
    return jax.lax.cond(
        iteration % stride == 0, compute,
        lambda _: jnp.full((N_METRICS,), jnp.nan, jnp.float32), None)
