"""HBM watermarks: runtime device-memory accounting at chunk boundaries.

The per-shard HBM budget model (``perf/epoch_cache.py``:
``total/n_shard + 2·step_bytes/(n_shard·K)``) decides whether a dataset
takes the fused path — but until now nothing ever compared that analytic
model to what the device actually holds. This module is the measurement
side:

- :func:`sample_hbm_watermark` — one point-in-time sample per local
  device: the backend's ``memory_stats()`` (``bytes_in_use`` /
  ``peak_bytes_in_use``, available on TPU) with a live-array accounting
  fallback (summing the device-local bytes of every live ``jax.Array``
  shard — exact for what THIS client allocated, blind to other clients)
  for backends like CPU that report no stats. Samples land in the
  MetricsRegistry as ``hbm_bytes_in_use`` / ``hbm_peak_bytes`` gauges
  and on the tracer as an ``hbm.watermark`` event, so the timeline
  carries the memory high-water beside the dispatch spans.
- :func:`cache_resident_bytes` — the measured per-device footprint of a
  ``DeviceDataSetCache``'s stacks (metadata walk over addressable
  shards; no transfer).
- :func:`validate_cache_budget` — the runtime check the budget model
  never had: predicted per-shard resident bytes (``cache.nbytes /
  n_shard``) vs the measured per-device maximum, with a relative
  tolerance. ``bench.py``'s epoch section embeds the verdict and
  ``tests/test_profile.py`` asserts it.

Everything here is a HOST-side readback. It is only permitted at chunk
boundaries — dl4j-lint's host-sync rule flags any of these calls
reachable from a hot path (``analysis/rules.py``
``PROFILE_READBACK_CALLS``). ``drive_epoch_chunks`` samples after each
chunk dispatch when ``DL4J_PROFILE`` is on; the default path never calls
in here.

Stdlib-only at import (jax loads lazily inside each sampler).
"""

from __future__ import annotations

import logging
from typing import Any, Dict, List, Optional

logger = logging.getLogger(__name__)

__all__ = [
    "cache_resident_bytes",
    "live_array_bytes",
    "sample_hbm_watermark",
    "validate_cache_budget",
]


def live_array_bytes() -> Dict[str, int]:
    """Per-device bytes held by live ``jax.Array``s of THIS process —
    the accounting fallback when the backend reports no memory stats.
    Metadata-only: addressable-shard sizes are host-side attributes, no
    device sync."""
    import jax

    per_device: Dict[str, int] = {}
    for arr in jax.live_arrays():
        shards = getattr(arr, "addressable_shards", None)
        if shards:
            for sh in shards:
                key = str(sh.device)
                per_device[key] = (per_device.get(key, 0)
                                   + int(sh.data.nbytes))
        else:  # pragma: no cover - non-sharded array types
            try:
                dev = str(next(iter(arr.devices())))
            except Exception:
                continue
            per_device[dev] = per_device.get(dev, 0) + int(arr.nbytes)
    return per_device


def sample_hbm_watermark(tag: Optional[str] = None,
                         record: bool = True) -> dict:
    """One watermark sample across the local devices.

    Per device: ``bytes_in_use`` and ``peak_bytes_in_use`` from the
    backend's ``memory_stats()`` when it provides them (TPU does), else
    live-array accounting (``source`` says which; the live-array walk
    runs lazily, only when some device lacks stats — a stats-capable
    backend never pays the O(live arrays) host walk per sample).
    ``record=True`` mirrors the sample into the registry gauges and
    stamps an ``hbm.watermark`` tracer event."""
    import jax

    live: Optional[Dict[str, int]] = None
    devices = []
    for d in jax.local_devices():
        stats = None
        try:
            stats = d.memory_stats()
        except Exception:  # backend without the PJRT stats API
            stats = None
        key = str(d)
        if stats:
            entry = {
                "device": key,
                "bytes_in_use": int(stats.get("bytes_in_use", 0)),
                "peak_bytes_in_use": int(
                    stats.get("peak_bytes_in_use", 0)) or None,
                "bytes_limit": int(stats.get("bytes_limit", 0)) or None,
                "source": "memory_stats",
            }
        else:
            if live is None:
                live = live_array_bytes()
            entry = {
                "device": key,
                "bytes_in_use": int(live.get(key, 0)),
                "peak_bytes_in_use": None,
                "bytes_limit": None,
                "source": "live_arrays",
            }
            entry["live_array_bytes"] = entry["bytes_in_use"]
        devices.append(entry)
    sample = {
        "tag": tag,
        "devices": devices,
        "total_bytes_in_use": sum(e["bytes_in_use"] for e in devices),
        "max_bytes_in_use": max(
            (e["bytes_in_use"] for e in devices), default=0),
    }
    if record:
        from deeplearning4j_tpu.monitor import tracer
        from deeplearning4j_tpu.monitor.registry import metrics

        reg = metrics()
        in_use = reg.gauge("hbm_bytes_in_use",
                           "per-device bytes in use at the last "
                           "watermark sample")
        peak = reg.gauge("hbm_peak_bytes",
                         "per-device peak bytes (backend-reported)")
        for e in devices:
            in_use.set(e["bytes_in_use"], device=e["device"],
                       source=e["source"])
            if e["peak_bytes_in_use"] is not None:
                peak.set(e["peak_bytes_in_use"], device=e["device"])
        tracer().event("hbm.watermark", tag=tag,
                       total_bytes=sample["total_bytes_in_use"],
                       max_device_bytes=sample["max_bytes_in_use"])
    return sample


def cache_resident_bytes(cache) -> Dict[str, int]:
    """Measured per-device bytes of a device cache's stacks. Walks the
    dataset-cache attributes (features/labels/masks; DataSet and
    MultiDataSet cache shapes both) AND the serving slot-pool attributes
    (``k``/``v`` plus the int8 ``k_scale``/``v_scale`` sidecars), so
    ``validate_cache_budget`` prices a quantized ``SlotKVCache`` —
    predicted nbytes vs what the device actually holds — the same way
    it prices an epoch cache. Metadata-only, no transfer."""
    per_device: Dict[str, int] = {}
    arrays: List[Any] = []
    for attr in ("features", "labels", "features_mask", "labels_mask",
                 "features_masks", "labels_masks",
                 "k", "v", "k_scale", "v_scale"):
        val = getattr(cache, attr, None)
        if val is None:
            continue
        arrays.extend(val if isinstance(val, tuple) else [val])
    for arr in arrays:
        if arr is None:
            continue
        shards = getattr(arr, "addressable_shards", None)
        if shards:
            for sh in shards:
                key = str(sh.device)
                per_device[key] = (per_device.get(key, 0)
                                   + int(sh.data.nbytes))
        else:  # pragma: no cover - host-backed fallback caches
            per_device["host"] = (per_device.get("host", 0)
                                  + int(arr.nbytes))
    return per_device


def validate_cache_budget(cache, tolerance: float = 0.25) -> dict:
    """Check the epoch cache's analytic per-shard budget model against
    the bytes the devices actually hold.

    Predicted: ``cache.nbytes / cache.n_shard`` — the resident term of
    the PERF.md §Round-8 model (the working-set term is transient and
    not resident between chunks). Measured: the per-device maximum over
    the cache's own shards. ``within_tolerance`` is the verdict at
    relative ``tolerance`` (padding and replicated indivisible buckets
    are modeled, so the two should track closely; a drift beyond
    tolerance means the budget model no longer prices what the runtime
    allocates)."""
    predicted = cache.nbytes / max(1, cache.n_shard)
    per_device = cache_resident_bytes(cache)
    measured = max(per_device.values(), default=0)
    ratio = measured / predicted if predicted else None
    out = {
        "predicted_per_shard_bytes": int(predicted),
        "measured_per_device_bytes": int(measured),
        "n_shard": cache.n_shard,
        "n_devices_holding": len(per_device),
        "ratio": None if ratio is None else round(ratio, 4),
        "tolerance": tolerance,
        "within_tolerance": (ratio is not None
                             and abs(ratio - 1.0) <= tolerance),
    }
    if not out["within_tolerance"]:
        logger.warning(
            "epoch-cache budget model drift: predicted %d B/shard, "
            "measured %d B on the fullest device (ratio %s, tolerance "
            "%.0f%%)", out["predicted_per_shard_bytes"],
            out["measured_per_device_bytes"], out["ratio"],
            100 * tolerance)
    return out
