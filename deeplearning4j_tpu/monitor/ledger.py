"""RunLedger: classify every wall-clock second of a training run.

PR 6 made a *step* observable (the metrics pack) and PR 8 a *program*
(ProgramProfile); nothing accounts for a *run*: no artifact says what
fraction of a ``fit_epochs`` / ``FaultTolerantTrainer`` run's wall time
was spent actually training versus building caches, backing off retries,
writing checkpoints, stalled behind a hung dispatch, or waiting on a
device grant. Large-scale systems treat that goodput/badput ledger as
first-class infrastructure; this module is ours.

The ledger consumes the EXISTING span taxonomy (it adds no new hot-path
instrumentation): the chunk driver marks run/chunk boundaries
(``ledger_run_start`` / ``ledger_chunk_start`` / ``ledger_chunk_done`` /
``ledger_run_end`` — chunk-boundary-only, dl4j-lint-enforced), and
``report()`` sweeps the tracer's span ring, classifying wall time into
states by priority:

| state | source spans/marks |
|---|---|
| ``compute`` | inside a run window (dispatch + device execution), unless overridden below |
| ``cache_build`` | ``cache.build`` |
| ``checkpoint`` | ``checkpoint.write``/``verify``/``snapshot`` — EXCEPT background writes (``attrs.background``), which overlap compute and are reported separately as ``hidden_checkpoint_s`` |
| ``retry_backoff`` | ``retry.sleep`` |
| ``watchdog_stall`` | ``watchdog.stall`` events (interval re-derived from ``stalled_s``) |
| ``preemption_recovery`` | ``checkpoint.resume`` |
| ``reshard`` | ``reshard.elastic`` — the chunk-boundary device snapshot → respec → continue of a mid-run mesh grow/shrink |
| ``grant_wait`` | ``grant.probe`` / ``grant.acquire`` / ``grant.reacquire`` / ``grant.backoff`` / ``grant.subprocess`` — including every lease re-acquire cycle, so a rescued wedge is booked as grant badput instead of a lost round |
| ``idle`` | outside any run window and any classified span |

Goodput % is ``compute / (window − idle)``; the badput breakdown is the
rest. Everything is host-side arithmetic over the bounded span ring —
free at the <3% overhead bar; ``telemetry_summary()`` embeds the report
in every bench artifact.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

__all__ = [
    "RunLedger",
    "ledger_chunk_done",
    "ledger_chunk_start",
    "ledger_run_end",
    "ledger_run_start",
    "run_ledger",
    "set_run_ledger",
]

GOODPUT_STATE = "compute"
IDLE_STATE = "idle"

#: span name -> badput state (spans that BLOCK the training thread)
BADPUT_SPAN_STATES = {
    "cache.build": "cache_build",
    "checkpoint.write": "checkpoint",
    "checkpoint.verify": "checkpoint",
    "checkpoint.snapshot": "checkpoint",
    "checkpoint.resume": "preemption_recovery",
    "retry.sleep": "retry_backoff",
    "reshard.elastic": "reshard",
    "grant.probe": "grant_wait",
    "grant.acquire": "grant_wait",
    "grant.reacquire": "grant_wait",
    "grant.backoff": "grant_wait",
    "grant.subprocess": "grant_wait",
}

#: overlap resolution: a second covered by several intervals takes the
#: highest-priority state (a stalled chunk is a stall, not compute)
STATE_PRIORITY = {
    IDLE_STATE: 0,
    GOODPUT_STATE: 1,
    "cache_build": 2,
    "checkpoint": 3,
    "reshard": 4,
    "retry_backoff": 5,
    "watchdog_stall": 6,
    "preemption_recovery": 7,
    "grant_wait": 8,
}

BADPUT_STATES = tuple(s for s in STATE_PRIORITY
                      if s not in (IDLE_STATE, GOODPUT_STATE))


def _sweep(intervals: List[Tuple[float, float, str]],
           t0: float, t1: float) -> Dict[str, float]:
    """Elementary-segment sweep: per-state seconds over ``[t0, t1]``
    with priority overlap resolution. O(n log n) in interval count."""
    totals = {s: 0.0 for s in STATE_PRIORITY}
    if t1 <= t0:
        return totals
    events: List[Tuple[float, int, str]] = []
    for start, end, state in intervals:
        start, end = max(start, t0), min(end, t1)
        if end > start:
            events.append((start, 1, state))
            events.append((end, -1, state))
    if not events:
        totals[IDLE_STATE] = t1 - t0
        return totals
    events.sort(key=lambda e: e[0])
    active = {s: 0 for s in STATE_PRIORITY}
    prev = t0
    i = 0
    while i < len(events):
        t = events[i][0]
        if t > prev:
            state = IDLE_STATE
            best = -1
            for s, n in active.items():
                if n > 0 and STATE_PRIORITY[s] > best:
                    best = STATE_PRIORITY[s]
                    state = s
            totals[state] += t - prev
            prev = t
        while i < len(events) and events[i][0] == t:
            _, delta, s = events[i]
            active[s] += delta
            i += 1
    if t1 > prev:
        state = IDLE_STATE
        best = -1
        for s, n in active.items():
            if n > 0 and STATE_PRIORITY[s] > best:
                best = STATE_PRIORITY[s]
                state = s
        totals[state] += t1 - prev
    return totals


class RunLedger:
    """Run/chunk boundary marks + span-ring classification.

    The chunk driver calls :meth:`run_start` / :meth:`chunk_start` /
    :meth:`chunk_done` / :meth:`run_end` (all O(1) dict work — nothing
    here belongs anywhere near a fused dispatch except at chunk
    boundaries); :meth:`report` does the wall-time sweep on demand.
    ``clock`` must be the same monotonic clock the span tracer uses so
    intervals line up (both default to ``time.monotonic``).
    """

    def __init__(self, clock: Callable[[], float] = time.monotonic,
                 span_source: Optional[Callable[[], list]] = None,
                 keep_runs: int = 8):
        self._clock = clock
        self._span_source = span_source
        self._keep = max(1, keep_runs)
        self._lock = threading.Lock()
        self.reset()

    def reset(self) -> None:
        with self._lock:
            self._t0 = self._clock()
            self._runs: List[dict] = []
            self._active: Optional[dict] = None
            self._chunk_t0: Optional[float] = None
            self._n_runs = 0

    # -- boundary marks (chunk-boundary-only on training paths) ---------
    def run_start(self, **attrs) -> None:
        with self._lock:
            self._active = {"start_s": self._clock(), "end_s": None,
                            "status": None, "chunks": 0,
                            "dispatch_s": 0.0, "attrs": dict(attrs)}

    def chunk_start(self, **attrs) -> None:
        with self._lock:
            self._chunk_t0 = self._clock()

    def chunk_done(self, **attrs) -> None:
        with self._lock:
            now = self._clock()
            if self._active is not None:
                self._active["chunks"] += 1
                if self._chunk_t0 is not None:
                    self._active["dispatch_s"] += now - self._chunk_t0
            self._chunk_t0 = None

    def run_end(self, status: str = "clean", **attrs) -> Optional[dict]:
        """Close the active run and cache its classified report (one
        span-ring sweep per run — the cheap read the fleet heartbeat
        payload uses). Returns the per-run report, or None if no run was
        open."""
        with self._lock:
            run = self._active
            self._active = None
            if run is None:
                return None
            run["end_s"] = self._clock()
            run["status"] = status
            run["attrs"].update(attrs)
        run["report"] = self._classify(run["start_s"], run["end_s"],
                                       runs=[run])
        with self._lock:
            self._runs.append(run)
            self._n_runs += 1
            del self._runs[:-self._keep]
        return run["report"]

    # -- reads -----------------------------------------------------------
    def last_run_goodput(self) -> Optional[float]:
        """Goodput percentage of the most recently finished run (cached
        at ``run_end`` — no sweep)."""
        with self._lock:
            if not self._runs:
                return None
            return self._runs[-1]["report"]["goodput_pct"]

    def _spans(self, spans: Optional[list] = None) -> list:
        if spans is not None:
            return spans
        if self._span_source is not None:
            return self._span_source()
        from deeplearning4j_tpu.monitor.trace import tracer

        return tracer().spans()

    def _classify(self, t0: float, t1: float,
                  runs: Optional[List[dict]] = None,
                  spans: Optional[list] = None) -> dict:
        if runs is None:
            with self._lock:
                runs = list(self._runs)
                if self._active is not None:
                    runs.append(dict(self._active))
        intervals: List[Tuple[float, float, str]] = []
        for run in runs:
            intervals.append((run["start_s"],
                              t1 if run["end_s"] is None else run["end_s"],
                              GOODPUT_STATE))
        hidden_ckpt = 0.0
        for sp in self._spans(spans):
            end = t1 if sp.end_s is None else sp.end_s
            state = BADPUT_SPAN_STATES.get(sp.name)
            if state == "checkpoint" and sp.attrs.get("background"):
                # a background write overlaps compute by design — it is
                # hidden, not badput, but the postmortem wants to know
                hidden_ckpt += max(0.0, min(end, t1)
                                   - max(sp.start_s, t0))
                continue
            if state is not None:
                intervals.append((sp.start_s, end, state))
            elif sp.name == "watchdog.stall":
                stalled = float(sp.attrs.get("stalled_s", 0.0))
                if stalled > 0:
                    intervals.append((end - stalled, end,
                                      "watchdog_stall"))
        totals = _sweep(intervals, t0, t1)
        window = t1 - t0
        accounted = window - totals[IDLE_STATE]
        goodput = (100.0 * totals[GOODPUT_STATE] / accounted
                   if accounted > 0 else None)
        return {
            "window_s": round(window, 6),
            "goodput_pct": None if goodput is None else round(goodput, 2),
            "states": {s: round(v, 6) for s, v in totals.items()},
            "badput": {s: round(totals[s], 6) for s in BADPUT_STATES
                       if totals[s] > 0},
            "hidden_checkpoint_s": round(hidden_ckpt, 6),
        }

    def report(self, spans: Optional[list] = None) -> dict:
        """The JSON-ready ledger block ``telemetry_summary()`` embeds:
        whole-window classification plus the per-run detail (last
        ``keep_runs`` runs, each with its own goodput and badput
        breakdown)."""
        now = self._clock()
        out = self._classify(self._t0, now, spans=spans)
        with self._lock:
            runs = list(self._runs)
            active = self._active
            n_runs = self._n_runs
        out["n_runs"] = n_runs
        out["run_in_flight"] = active is not None
        out["runs"] = [{
            "status": r["status"],
            "wall_s": round(r["end_s"] - r["start_s"], 6),
            "chunks": r["chunks"],
            "host_dispatch_s": round(r["dispatch_s"], 6),
            "goodput_pct": r["report"]["goodput_pct"],
            "badput": r["report"]["badput"],
            **{k: v for k, v in r["attrs"].items()
               if isinstance(v, (str, int, float, bool))},
        } for r in runs]
        return out


_LEDGER: Optional[RunLedger] = None
_LEDGER_LOCK = threading.Lock()


def run_ledger() -> RunLedger:
    """The process-global ledger (window starts at first use)."""
    global _LEDGER
    if _LEDGER is None:
        with _LEDGER_LOCK:
            if _LEDGER is None:
                _LEDGER = RunLedger()
    return _LEDGER


def set_run_ledger(ledger: Optional[RunLedger]) -> None:
    """Swap the global ledger (tests install fakes; ``None`` re-creates
    fresh on next use)."""
    global _LEDGER
    with _LEDGER_LOCK:
        _LEDGER = ledger


# ---------------------------------------------------------------------------
# the chunk-boundary helpers drive_epoch_chunks calls (and dl4j-lint
# keeps OUT of traced programs — see LEDGER_FLIGHT_CALLS in
# analysis/rules.py)
# ---------------------------------------------------------------------------


def _flight(kind: str, **payload) -> None:
    from deeplearning4j_tpu.monitor.flight import flight_record

    flight_record(kind, **payload)


def ledger_run_start(**attrs) -> None:
    run_ledger().run_start(**attrs)
    _flight("run.start", **attrs)


def ledger_chunk_start(**attrs) -> None:
    run_ledger().chunk_start(**attrs)
    _flight("chunk.launch", **attrs)


def ledger_chunk_done(**attrs) -> None:
    run_ledger().chunk_done(**attrs)
    _flight("chunk.done", **attrs)


def ledger_run_end(status: str = "clean", **attrs) -> None:
    rep = run_ledger().run_end(status=status, **attrs)
    _flight("run.end", status=status,
            goodput_pct=None if rep is None else rep["goodput_pct"],
            **attrs)
