"""Span tracer: timestamped, nested spans over the training control plane.

The fused pipeline's failure mode is a TIMELINE problem: a wedged device
grant (BENCH_r04/r05) or a stalled chunk leaves a bare error line with no
record of what the host was doing or for how long. Spans fix that: every
interesting host-side operation — chunk dispatch, sentinel readback, cache
build, checkpoint save/verify, backend/grant acquisition, retry sleeps —
runs inside ``tracer().span(name, **attrs)``; the tracer keeps a bounded
ring of finished spans with monotonic start/end timestamps and parent ids
(a thread-local stack provides the nesting), and exporters turn the ring
into a JSONL event log or the summary block embedded in bench artifacts.

The clock is injectable (tests drive a fake), span recording is a deque
append under a lock (no I/O on the hot path — a ``sink`` callback, when
configured, forwards each finished span to the JSONL exporter), and a
tracer with no sink and no reader costs two clock reads per span.
"""

from __future__ import annotations

import itertools
import threading
import time
from contextlib import contextmanager
from collections import deque
from typing import Callable, Dict, List, Optional

__all__ = ["Span", "SpanTracer", "tracer", "set_tracer"]

DEFAULT_CAPACITY = 4096


class Span:
    """One finished (or in-flight) operation: ``[start_s, end_s]`` on the
    tracer's monotonic clock, a ``parent_id`` giving the nesting, and
    free-form ``attrs``."""

    __slots__ = ("name", "span_id", "parent_id", "start_s", "end_s",
                 "attrs")

    def __init__(self, name: str, span_id: int, parent_id: Optional[int],
                 start_s: float, attrs: Dict):
        self.name = name
        self.span_id = span_id
        self.parent_id = parent_id
        self.start_s = start_s
        self.end_s: Optional[float] = None
        self.attrs = attrs

    @property
    def duration_s(self) -> float:
        return 0.0 if self.end_s is None else self.end_s - self.start_s

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "start_s": round(self.start_s, 6),
            "end_s": None if self.end_s is None else round(self.end_s, 6),
            "duration_s": round(self.duration_s, 6),
            "attrs": self.attrs,
        }

    def __repr__(self) -> str:
        return (f"Span({self.name!r}, id={self.span_id}, "
                f"parent={self.parent_id}, dur={self.duration_s:.6f}s)")


class SpanTracer:
    """Bounded ring of finished spans + a thread-local open-span stack.

    - ``span(name, **attrs)`` — context manager; yields the live
      :class:`Span` so callers can add attrs discovered mid-operation.
      An exception inside the body stamps ``attrs["error"]`` before the
      span closes (the timeline records WHAT failed, not just that
      something did).
    - ``event(name, **attrs)`` — zero-duration span, recorded
      immediately (watchdog fired, preemption latched).
    - ``clock`` is injectable; ``sink(span_dict)`` forwards each
      finished span (the JSONL exporter wires in here).
    """

    def __init__(self, clock: Callable[[], float] = time.monotonic,
                 capacity: int = DEFAULT_CAPACITY,
                 sink: Optional[Callable[[dict], None]] = None):
        self._clock = clock
        self._sink = sink
        self._lock = threading.Lock()
        self._spans: deque = deque(maxlen=capacity)
        self._ids = itertools.count(1)
        self._local = threading.local()

    # ------------------------------------------------------------------
    def _stack(self) -> List[Span]:
        st = getattr(self._local, "stack", None)
        if st is None:
            st = self._local.stack = []
        return st

    def current(self) -> Optional[Span]:
        st = self._stack()
        return st[-1] if st else None

    def _record(self, span: Span) -> None:
        with self._lock:
            self._spans.append(span)
        if self._sink is not None:
            try:
                self._sink(span.to_dict())
            except Exception:
                # the sink is best-effort I/O; a full disk must not turn
                # into a training failure
                pass
        # the flight recorder (when enabled) gets every finished span —
        # the postmortem timeline a crash is reconstructed from.
        # (import from the submodule: the package re-exports a `flight`
        # FUNCTION that shadows the module attribute of the same name)
        try:
            from deeplearning4j_tpu.monitor.flight import (
                flight as _active_flight)

            rec = _active_flight()
            if rec is not None:
                rec.record_span(span.to_dict())
        except Exception:
            pass

    # ------------------------------------------------------------------
    @contextmanager
    def span(self, name: str, **attrs):
        parent = self.current()
        sp = Span(name, next(self._ids),
                  None if parent is None else parent.span_id,
                  self._clock(), attrs)
        stack = self._stack()
        stack.append(sp)
        try:
            yield sp
        except BaseException as e:
            sp.attrs.setdefault("error", f"{type(e).__name__}: {e}"[:200])
            raise
        finally:
            sp.end_s = self._clock()
            if stack and stack[-1] is sp:
                stack.pop()
            else:  # defensive: unbalanced exit must not corrupt nesting
                try:
                    stack.remove(sp)
                except ValueError:
                    pass
            self._record(sp)

    def event(self, name: str, **attrs) -> Span:
        now = self._clock()
        parent = self.current()
        sp = Span(name, next(self._ids),
                  None if parent is None else parent.span_id, now, attrs)
        sp.end_s = now
        self._record(sp)
        return sp

    # ------------------------------------------------------------------
    def spans(self) -> List[Span]:
        with self._lock:
            return list(self._spans)

    def clear(self) -> None:
        with self._lock:
            self._spans.clear()

    def summary(self, recent: int = 40) -> dict:
        """Aggregate view for artifact embedding: per span name count /
        total / max seconds, plus the ``recent`` newest span dicts — the
        timeline a wedged run is diagnosed from."""
        spans = self.spans()
        agg: Dict[str, dict] = {}
        for sp in spans:
            a = agg.setdefault(sp.name,
                               {"count": 0, "total_s": 0.0, "max_s": 0.0})
            a["count"] += 1
            a["total_s"] += sp.duration_s
            a["max_s"] = max(a["max_s"], sp.duration_s)
        for a in agg.values():
            a["total_s"] = round(a["total_s"], 6)
            a["max_s"] = round(a["max_s"], 6)
        return {
            "n_spans": len(spans),
            "by_name": agg,
            "recent": [sp.to_dict() for sp in spans[-recent:]],
        }


_TRACER: Optional[SpanTracer] = None
_TRACER_LOCK = threading.Lock()


def tracer() -> SpanTracer:
    """The process-global tracer. First use wires the JSONL sink when
    ``DL4J_TELEMETRY_DIR`` is set (see ``monitor.exporters``)."""
    global _TRACER
    if _TRACER is None:
        with _TRACER_LOCK:
            if _TRACER is None:
                from deeplearning4j_tpu.monitor import exporters

                _TRACER = SpanTracer(sink=exporters.span_sink_from_env())
    return _TRACER


def set_tracer(t: Optional[SpanTracer]) -> None:
    """Swap the global tracer (tests install fakes; ``None`` re-derives
    from the environment on next use)."""
    global _TRACER
    with _TRACER_LOCK:
        _TRACER = t
