"""MetricsRegistry: counters / gauges / histograms with labels.

The seed grew observability the way the reference grew retries: ad-hoc.
``net._train_dispatches`` and ``net._eval_readbacks`` are bare attributes
per network instance, retry attempts only exist as debug log lines,
watchdog stalls live on the watchdog object, checkpoint write latency is
invisible. The registry is the one API those signals land behind: any
module does ``metrics().counter("retry_attempts_total").inc(fn="init")``
and every exporter (JSONL, Prometheus textfile, the bench summary block)
reads the same snapshot.

Design rules:

- **Process-global by default** (``metrics()``), injectable everywhere a
  caller wants isolation (tests construct private registries).
- **Instruments are cheap**: an ``inc``/``set``/``observe`` is a dict
  lookup plus a lock — safe on control-plane paths (dispatches, retries,
  checkpoints). Nothing here belongs INSIDE a jitted program; the
  device-side metrics pack (``monitor.pack``) covers that and flushes
  into this registry's world per chunk.
- **Labels are kwargs**, stored as a sorted tuple key, so
  ``c.inc(model="MLN")`` and ``c.value(model="MLN")`` always agree.
- **Type conflicts fail loudly**: re-registering a name as a different
  instrument kind raises instead of silently splitting the series.
"""

from __future__ import annotations

import threading
from typing import Dict, Iterable, List, Optional, Tuple

__all__ = ["Counter", "Gauge", "Histogram", "MetricsRegistry", "metrics"]

LabelKey = Tuple[Tuple[str, str], ...]

DEFAULT_BUCKETS = (0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 10.0,
                   30.0, 60.0, float("inf"))


def _label_key(labels: dict) -> LabelKey:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


class _Instrument:
    """Shared label-series plumbing. Subclasses define what a series
    value is and how it mutates."""

    kind = "untyped"

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self._lock = threading.Lock()
        self._series: Dict[LabelKey, object] = {}

    def _zero(self):
        return 0.0

    def remove(self, **labels) -> bool:
        """Drop one labeled series (e.g. an evicted fleet replica's
        per-replica gauge): a source that no longer exists must stop
        reporting as current, or dashboards and eviction audits read a
        corpse's last value as live. Returns whether the series
        existed."""
        with self._lock:
            return self._series.pop(_label_key(labels), None) is not None

    def labels(self) -> List[dict]:
        with self._lock:
            return [dict(k) for k in self._series]

    def value(self, **labels):
        with self._lock:
            return self._series.get(_label_key(labels), self._zero())

    def series(self) -> Dict[LabelKey, object]:
        with self._lock:
            return dict(self._series)


class Counter(_Instrument):
    """Monotonically increasing count (negative increments rejected)."""

    kind = "counter"

    def inc(self, amount: float = 1.0, **labels) -> None:
        if amount < 0:
            raise ValueError(f"counter {self.name} cannot decrease "
                             f"(inc {amount})")
        key = _label_key(labels)
        with self._lock:
            self._series[key] = self._series.get(key, 0.0) + amount


class Gauge(_Instrument):
    """Point-in-time value (can go up or down)."""

    kind = "gauge"

    def set(self, value: float, **labels) -> None:
        with self._lock:
            self._series[_label_key(labels)] = float(value)

    def inc(self, amount: float = 1.0, **labels) -> None:
        key = _label_key(labels)
        with self._lock:
            self._series[key] = self._series.get(key, 0.0) + amount


class Histogram(_Instrument):
    """Cumulative-bucket histogram (Prometheus semantics: each bucket
    counts observations ``<= le``; ``sum``/``count`` ride along)."""

    kind = "histogram"

    def __init__(self, name: str, help: str = "",
                 buckets: Iterable[float] = DEFAULT_BUCKETS):
        super().__init__(name, help)
        bounds = sorted(float(b) for b in buckets)
        if not bounds or bounds[-1] != float("inf"):
            bounds.append(float("inf"))
        self.buckets = tuple(bounds)

    def _zero(self):
        return {"buckets": [0] * len(self.buckets), "sum": 0.0, "count": 0}

    def observe(self, value: float, **labels) -> None:
        value = float(value)
        key = _label_key(labels)
        with self._lock:
            s = self._series.get(key)
            if s is None:
                s = self._zero()
                self._series[key] = s
            for i, b in enumerate(self.buckets):
                if value <= b:
                    s["buckets"][i] += 1
            s["sum"] += value
            s["count"] += 1

    @staticmethod
    def _copy(s):
        return {"buckets": list(s["buckets"]), "sum": s["sum"],
                "count": s["count"]}

    def value(self, **labels):
        with self._lock:
            s = self._series.get(_label_key(labels))
            return self._copy(s) if s is not None else self._zero()

    def series(self):
        # deep-copy under the lock: exporters iterate these dicts while a
        # background writer may be observe()-ing — a snapshot must be the
        # point-in-time view it claims, not a live (tearable) reference
        with self._lock:
            return {k: self._copy(s) for k, s in self._series.items()}


class MetricsRegistry:
    """Name -> instrument registry with snapshot/Prometheus export."""

    def __init__(self):
        self._lock = threading.Lock()
        self._instruments: Dict[str, _Instrument] = {}

    def _get(self, cls, name: str, help: str, **kw):
        with self._lock:
            inst = self._instruments.get(name)
            if inst is None:
                inst = cls(name, help, **kw)
                self._instruments[name] = inst
            elif not isinstance(inst, cls):
                raise TypeError(
                    f"metric {name!r} already registered as {inst.kind}, "
                    f"not {cls.kind}")
            return inst

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get(Counter, name, help)

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._get(Gauge, name, help)

    def histogram(self, name: str, help: str = "",
                  buckets: Iterable[float] = DEFAULT_BUCKETS) -> Histogram:
        return self._get(Histogram, name, help, buckets=buckets)

    def instruments(self) -> List[_Instrument]:
        with self._lock:
            return list(self._instruments.values())

    def reset(self) -> None:
        """Drop every instrument (tests; a long-lived process keeps its
        counters for the life of the process, like any metrics agent)."""
        with self._lock:
            self._instruments.clear()

    # ------------------------------------------------------------------
    def snapshot(self) -> dict:
        """JSON-ready view: ``{name: {type, help, values: [{labels,
        value}, ...]}}`` — the payload the JSONL exporter and the bench
        summary block embed."""
        out = {}
        for inst in self.instruments():
            values = []
            for key, val in inst.series().items():
                values.append({"labels": dict(key), "value": val})
            out[inst.name] = {"type": inst.kind, "help": inst.help,
                              "values": values}
        return out

    def to_prometheus(self, prefix: str = "dl4j_") -> str:
        """Prometheus text exposition format (the node-exporter textfile
        collector dialect — one snapshot, no timestamps)."""
        lines = []
        for inst in self.instruments():
            full = prefix + inst.name
            if inst.help:
                lines.append(f"# HELP {full} {inst.help}")
            lines.append(f"# TYPE {full} {inst.kind}")
            for key, val in sorted(inst.series().items()):
                base_labels = dict(key)
                if inst.kind == "histogram":
                    for b, c in zip(inst.buckets, val["buckets"]):
                        le = "+Inf" if b == float("inf") else repr(b)
                        lines.append(
                            f"{full}_bucket"
                            f"{_fmt_labels({**base_labels, 'le': le})} {c}")
                    lines.append(
                        f"{full}_sum{_fmt_labels(base_labels)} "
                        f"{val['sum']}")
                    lines.append(
                        f"{full}_count{_fmt_labels(base_labels)} "
                        f"{val['count']}")
                else:
                    lines.append(
                        f"{full}{_fmt_labels(base_labels)} {val}")
        return "\n".join(lines) + ("\n" if lines else "")


def _fmt_labels(labels: dict) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{_escape(v)}"'
                     for k, v in sorted(labels.items()))
    return "{" + inner + "}"


def _escape(v) -> str:
    return str(v).replace("\\", r"\\").replace('"', r"\"").replace(
        "\n", r"\n")


_REGISTRY: Optional[MetricsRegistry] = None
_REGISTRY_LOCK = threading.Lock()


def metrics() -> MetricsRegistry:
    """The process-global registry every in-tree instrument lands in."""
    global _REGISTRY
    if _REGISTRY is None:
        with _REGISTRY_LOCK:
            if _REGISTRY is None:
                _REGISTRY = MetricsRegistry()
    return _REGISTRY
