"""Flight recorder: a crash-surviving on-disk ring of run events.

The telemetry layer (PR 6) answers "what is the run doing" while the
process is alive; nothing answers "what WAS the run doing" after a
SIGKILL or a wedged device grant takes the process with it — rounds
r04/r05 died leaving one error line and no timeline. The flight recorder
is the black box: a bounded, segment-rotated JSONL ring on disk that
receives every finished span, every run-ledger transition (run start /
chunk launch / chunk done / run end), periodic writer heartbeats with
counter deltas, and free-form events. ``scripts/flight_report.py`` reads
the surviving segments of a dead run, reconstructs the final timeline,
and classifies the end state (clean / preempted / wedged / crashed).

Durability model: records are enqueued from the training thread (a dict
append — never blocks, never raises; a full queue drops and counts) and
written by ONE background writer thread, the ``save_async`` shape. The
writer flushes after every drain, so a SIGKILL loses only the few
records still in the queue; segment ROTATION applies the
``atomic_write_text`` fsync discipline (fsync the finished segment, then
the directory) so completed segments survive even a machine crash — the
bound on loss is one segment. Disk use is capped at
``segments × segment_bytes``: rotation unlinks the oldest segment past
the count, exactly the cap the PR-6 JSONL exporter lacked (it now
routes through :func:`shift_rotate` below).

Env surface (see docs/env.md): ``DL4J_FLIGHT`` (``1``/``on`` records
under ``$DL4J_TELEMETRY_DIR/flight``; any other value is an explicit
directory; unset/off disables), ``DL4J_FLIGHT_SEGMENT_KB`` /
``DL4J_FLIGHT_SEGMENTS`` (segment size / count, shared with the JSONL
exporter's cap), ``DL4J_FLIGHT_HEARTBEAT_S`` (writer heartbeat period —
the signal that separates "process died" from "process alive but
stuck" in the postmortem).

Stdlib-only at import, like the rest of ``monitor/``.
"""

from __future__ import annotations

import json
import logging
import os
import queue
import re
import threading
import time
from typing import Dict, List, Optional

from deeplearning4j_tpu.monitor.exporters import _json_default
from deeplearning4j_tpu.utils.fileio import _fsync_dir

logger = logging.getLogger(__name__)

__all__ = [
    "FlightRecorder",
    "classify_end_state",
    "flight",
    "flight_dir",
    "flight_record",
    "load_flight_records",
    "max_segments",
    "segment_bytes",
    "set_flight",
    "shift_rotate",
]

DEFAULT_SEGMENT_KB = 256
DEFAULT_SEGMENTS = 8
DEFAULT_HEARTBEAT_S = 1.0

SEGMENT_RE = re.compile(r"^flight-(\d{8})\.jsonl$")

_ON = ("1", "on", "true", "yes")
_OFF = ("", "0", "off", "false", "no")


def flight_dir() -> Optional[str]:
    """Resolve ``DL4J_FLIGHT``: on-values record under
    ``$DL4J_TELEMETRY_DIR/flight``; any other non-off value is taken as
    an explicit directory; off/unset disables (None)."""
    raw = os.environ.get("DL4J_FLIGHT", "").strip()
    if raw.lower() in _OFF:
        return None
    if raw.lower() in _ON:
        from deeplearning4j_tpu.monitor.exporters import telemetry_dir

        d = telemetry_dir()
        if d is None:
            logger.warning("DL4J_FLIGHT is on but DL4J_TELEMETRY_DIR is "
                           "unset; flight recording disabled")
            return None
        return os.path.join(d, "flight")
    return raw


def segment_bytes() -> int:
    """``DL4J_FLIGHT_SEGMENT_KB`` (default 256 KB): rotation threshold
    for one flight segment — also the JSONL exporter's cap unit."""
    raw = os.environ.get("DL4J_FLIGHT_SEGMENT_KB", "")
    try:
        kb = int(raw) if raw else DEFAULT_SEGMENT_KB
    except ValueError:
        kb = DEFAULT_SEGMENT_KB
    return max(1, kb) * 1024


def max_segments() -> int:
    """``DL4J_FLIGHT_SEGMENTS`` (default 8): how many segments the ring
    keeps; rotation unlinks the oldest beyond it."""
    raw = os.environ.get("DL4J_FLIGHT_SEGMENTS", "")
    try:
        n = int(raw) if raw else DEFAULT_SEGMENTS
    except ValueError:
        n = DEFAULT_SEGMENTS
    return max(2, n)


def heartbeat_s() -> float:
    """``DL4J_FLIGHT_HEARTBEAT_S`` (default 1 s): writer heartbeat
    period."""
    raw = os.environ.get("DL4J_FLIGHT_HEARTBEAT_S", "")
    try:
        v = float(raw) if raw else DEFAULT_HEARTBEAT_S
    except ValueError:
        v = DEFAULT_HEARTBEAT_S
    return max(0.01, v)


def shift_rotate(path: str, backups: int) -> None:
    """Logrotate-style shift for a single append file: ``path`` becomes
    ``path.1``, ``path.1`` becomes ``path.2``, …; the oldest backup is
    overwritten, so total files never exceed ``backups + 1``. The PR-6
    JSONL exporter routes through this to cap telemetry disk use."""
    if backups <= 0:
        try:
            os.unlink(path)
        except FileNotFoundError:
            pass
        return
    for i in range(backups - 1, 0, -1):
        src = f"{path}.{i}"
        if os.path.exists(src):
            os.replace(src, f"{path}.{i + 1}")
    os.replace(path, f"{path}.1")


class FlightRecorder:
    """Segment-rotated JSONL ring with a single background writer.

    ``record(kind, **payload)`` enqueues one event (never blocks, never
    raises — a full queue drops and counts); the writer thread drains
    the queue, appends JSON lines to the active ``flight-%08d.jsonl``
    segment (flushed per drain), stamps a ``flight.heartbeat`` record
    every ``heartbeat_s`` seconds carrying the counter totals that
    changed since the last beat, and rotates segments with
    fsync-file-then-directory durability. A fresh recorder always opens
    a NEW segment (never appends to a possibly-torn one).
    """

    _QUEUE_MAX = 8192

    def __init__(self, directory: str,
                 segment_bytes_: Optional[int] = None,
                 max_segments_: Optional[int] = None,
                 heartbeat_s_: Optional[float] = None,
                 metric_deltas: bool = True):
        self.directory = directory
        self.segment_bytes = (segment_bytes() if segment_bytes_ is None
                              else int(segment_bytes_))
        self.max_segments = (max_segments() if max_segments_ is None
                             else max(2, int(max_segments_)))
        self.heartbeat_s = (heartbeat_s() if heartbeat_s_ is None
                            else max(0.01, float(heartbeat_s_)))
        self.metric_deltas = metric_deltas
        self.records_written = 0
        self.segments_rotated = 0
        self.records_dropped = 0
        self.heartbeats_written = 0
        os.makedirs(directory, exist_ok=True)
        existing = _segment_indices(directory)
        self._index = (existing[-1] + 1) if existing else 1
        self._file = None
        self._size = 0
        self._last_counters: Dict[str, float] = {}
        self._q: "queue.Queue" = queue.Queue(maxsize=self._QUEUE_MAX)
        self._stop = threading.Event()
        self._closed = False
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="flight-writer")
        self._thread.start()

    # -- producer side --------------------------------------------------
    def record(self, kind: str, **payload) -> None:
        """Enqueue one event. Safe from any thread; never raises."""
        if self._closed:
            return
        rec = {"kind": kind, "t_wall": time.time()}
        rec.update(payload)
        try:
            self._q.put_nowait(rec)
        except queue.Full:
            self.records_dropped += 1

    def record_span(self, span_dict: dict) -> None:
        """Forward one finished tracer span (``trace._record`` wires in
        here via :func:`flight`)."""
        self.record("span", **span_dict)

    def flush(self, timeout: float = 5.0) -> bool:
        """Block until everything queued so far is on disk (tests and
        the bench use this before reading segments back)."""
        if self._closed:
            return True
        ev = threading.Event()
        try:
            self._q.put_nowait({"kind": "__flush__", "_event": ev})
        except queue.Full:
            return False
        return ev.wait(timeout)

    def close(self, timeout: float = 5.0) -> None:
        """Stamp a ``flight.close`` record, drain, fsync, and retire the
        writer. Idempotent."""
        if self._closed:
            return
        self.record("flight.close")
        self._closed = True
        self._stop.set()
        try:  # wake a blocked writer
            self._q.put_nowait(None)
        except queue.Full:
            pass
        self._thread.join(timeout=timeout)

    # -- writer side -----------------------------------------------------
    def _run(self) -> None:
        next_beat = time.monotonic() + self.heartbeat_s
        while True:
            timeout = max(0.01, next_beat - time.monotonic())
            batch: List[dict] = []
            try:
                item = self._q.get(timeout=timeout)
                if item is not None:
                    batch.append(item)
            except queue.Empty:
                pass
            while True:  # drain whatever else is queued, non-blocking
                try:
                    item = self._q.get_nowait()
                    if item is not None:
                        batch.append(item)
                except queue.Empty:
                    break
            try:
                if batch:
                    self._write(batch)
                if time.monotonic() >= next_beat:
                    self._write([self._heartbeat_record()])
                    self.heartbeats_written += 1
                    next_beat = time.monotonic() + self.heartbeat_s
            except Exception:  # a full disk must not kill the writer
                logger.warning("flight writer error (continuing)",
                               exc_info=True)
            if self._stop.is_set() and self._q.empty():
                break
        self._finalize()

    def _heartbeat_record(self) -> dict:
        rec = {"kind": "flight.heartbeat", "t_wall": time.time(),
               "interval_s": self.heartbeat_s}
        if self.metric_deltas:
            try:
                totals = _counter_totals()
                changed = {k: v for k, v in totals.items()
                           if self._last_counters.get(k) != v}
                self._last_counters = totals
                if changed:
                    rec["counters"] = changed
            except Exception:  # registry access is best-effort here
                pass
        return rec

    def _write(self, batch: List[dict]) -> None:
        for rec in batch:
            if rec.get("kind") == "__flush__":
                ev = rec.get("_event")
                self._sync_file(fsync=False)
                if ev is not None:
                    ev.set()
                continue
            line = json.dumps(rec, default=_json_default) + "\n"
            if self._file is not None and self._size > 0 \
                    and self._size + len(line) > self.segment_bytes:
                self._rotate()
            if self._file is None:
                self._open_segment()
            self._file.write(line)
            self._size += len(line)  # dl4j-lint: disable=lock-discipline -- writer-thread-confined: only _run() and its callees touch _size after __init__
            self.records_written += 1
        self._sync_file(fsync=False)

    def _sync_file(self, fsync: bool) -> None:
        if self._file is None:
            return
        self._file.flush()
        if fsync:
            os.fsync(self._file.fileno())

    def _segment_path(self, index: int) -> str:
        return os.path.join(self.directory, f"flight-{index:08d}.jsonl")

    def _open_segment(self) -> None:
        self._file = open(self._segment_path(self._index), "a")  # dl4j-lint: disable=lock-discipline -- writer-thread-confined: only _run() and its callees touch _file after __init__
        self._size = 0  # dl4j-lint: disable=lock-discipline -- writer-thread-confined: only _run() and its callees touch _size after __init__

    def _rotate(self) -> None:
        # the atomic_write_text durability ritual at the segment grain:
        # the finished segment's bytes are fsynced, then its directory
        # entry — a machine crash after this point cannot lose it
        self._sync_file(fsync=True)
        self._file.close()
        _fsync_dir(self.directory)
        self._file = None  # dl4j-lint: disable=lock-discipline -- writer-thread-confined: only _run() and its callees touch _file after __init__
        self._index += 1
        self.segments_rotated += 1
        # the segment about to open counts against the cap too
        for idx in _segment_indices(self.directory)[:-(self.max_segments
                                                       - 1)]:
            try:
                os.unlink(self._segment_path(idx))
            except FileNotFoundError:
                pass

    def _finalize(self) -> None:
        try:
            self._sync_file(fsync=True)
            if self._file is not None:
                self._file.close()
                self._file = None  # dl4j-lint: disable=lock-discipline -- writer-thread-confined: _finalize runs on the writer thread itself
            _fsync_dir(self.directory)
        except OSError:
            logger.warning("flight finalize failed", exc_info=True)


def _segment_indices(directory: str) -> List[int]:
    out = []
    try:
        names = os.listdir(directory)
    except OSError:
        return out
    for name in names:
        m = SEGMENT_RE.match(name)
        if m:
            out.append(int(m.group(1)))
    return sorted(out)


def _counter_totals() -> Dict[str, float]:
    """Label-summed counter totals — the compact delta payload the
    heartbeat records (full snapshots would bloat the ring)."""
    from deeplearning4j_tpu.monitor.registry import metrics

    totals: Dict[str, float] = {}
    for inst in metrics().instruments():
        if inst.kind != "counter":
            continue
        totals[inst.name] = float(sum(inst.series().values()))
    return totals


# ---------------------------------------------------------------------------
# the process-global recorder
# ---------------------------------------------------------------------------

_RECORDER: Optional[FlightRecorder] = None
_DERIVED = False
_LOCK = threading.Lock()


def flight() -> Optional[FlightRecorder]:
    """The process-global recorder, derived from ``DL4J_FLIGHT`` on
    first use; None when disabled."""
    global _RECORDER, _DERIVED
    if not _DERIVED:
        with _LOCK:
            if not _DERIVED:
                d = flight_dir()
                if d is not None:
                    try:
                        _RECORDER = FlightRecorder(d)
                    except OSError as e:
                        logger.warning("flight recorder disabled: cannot "
                                       "open %s: %s", d, e)
                        _RECORDER = None
                _DERIVED = True
    return _RECORDER


def set_flight(recorder: Optional[FlightRecorder]) -> None:
    """Install a recorder explicitly (bench, tests); ``None`` resets to
    env derivation on next use. Does NOT close the previous recorder —
    the caller that created it owns its lifecycle."""
    global _RECORDER, _DERIVED
    with _LOCK:
        _RECORDER = recorder
        _DERIVED = recorder is not None


def flight_record(kind: str, **payload) -> None:
    """One-line event record against the global recorder; no-op when
    flight recording is disabled. Chunk-boundary-only on training paths
    (dl4j-lint's host-sync rule enforces it like the profile
    readbacks)."""
    rec = flight()
    if rec is not None:
        rec.record(kind, **payload)


# ---------------------------------------------------------------------------
# postmortem side: load segments, classify the end state
# ---------------------------------------------------------------------------

#: record kinds that do NOT count as forward progress
_NON_PROGRESS_KINDS = ("flight.heartbeat",)
#: span/event names that are evidence of a stuck (not dead) process
WEDGE_EVIDENCE_NAMES = ("watchdog.stall", "grant.watchdog")
#: span/event names that mark a grant-lease RESCUE: the grant wedged and
#: was re-acquired (resilience/lease.py). A run that then finishes clean
#: classifies as ``reacquired`` — clean-with-recovery, not wedged.
REACQUIRE_EVIDENCE_NAMES = ("grant.reacquired",)
#: serve-fleet overload evidence: a graceful drain (planned retire with
#: KV-slab migration) vs. overload shedding (deadline/displacement
#: drops). Both are ORDERLY endings — the run closed clean — but a
#: postmortem must distinguish "we chose to shrink" and "we shed load"
#: from a genuinely uneventful run.
DRAIN_EVIDENCE_NAMES = ("serve.drain",)
SHED_EVIDENCE_NAMES = ("serve.shed",)
#: factor of the heartbeat interval after which continued beats with no
#: progress classify as a wedge
WEDGE_SILENCE_FACTOR = 3.0


def load_flight_records(directory: str) -> List[dict]:
    """Parse every surviving segment in index order. Torn lines (the
    write the crash interrupted) are skipped, not fatal — the postmortem
    reads what survived."""
    records: List[dict] = []
    for idx in _segment_indices(directory):
        path = os.path.join(directory, f"flight-{idx:08d}.jsonl")
        try:
            with open(path) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        rec = json.loads(line)
                    except json.JSONDecodeError:
                        continue  # torn tail of a killed writer
                    if isinstance(rec, dict):
                        rec["_segment"] = idx
                        records.append(rec)
        except OSError:
            continue
    return records


def _is_wedge_evidence(rec: dict) -> bool:
    if rec.get("kind") in WEDGE_EVIDENCE_NAMES:
        return True
    return (rec.get("kind") == "span"
            and rec.get("name") in WEDGE_EVIDENCE_NAMES)


def _is_reacquire_evidence(rec: dict) -> bool:
    if rec.get("kind") in REACQUIRE_EVIDENCE_NAMES:
        return True
    return (rec.get("kind") == "span"
            and rec.get("name") in REACQUIRE_EVIDENCE_NAMES)


def _is_drain_evidence(rec: dict) -> bool:
    if rec.get("kind") in DRAIN_EVIDENCE_NAMES:
        return True
    return (rec.get("kind") == "span"
            and rec.get("name") in DRAIN_EVIDENCE_NAMES)


def _is_shed_evidence(rec: dict) -> bool:
    if rec.get("kind") in SHED_EVIDENCE_NAMES:
        return True
    return (rec.get("kind") == "span"
            and rec.get("name") in SHED_EVIDENCE_NAMES)


def _is_progress(rec: dict) -> bool:
    return (rec.get("kind") not in _NON_PROGRESS_KINDS
            and not _is_wedge_evidence(rec))


def classify_end_state(records: List[dict],
                       wedge_factor: float = WEDGE_SILENCE_FACTOR) -> dict:
    """Classify how the recorded process ended, from surviving records
    alone.

    - ``clean``     — the last run closed in an orderly way (status
      ``clean``, or ``stopped`` by a user's ``on_chunk`` callback with
      no preemption latch on the timeline), or the recorder closed with
      no run in flight.
    - ``preempted`` — the run closed with a preemption latch on the
      timeline after the last run start (the latch — not the
      ``stopped`` status, which any on_chunk early-stop sets — is the
      preemption signal).
    - ``wedged``    — no closing record, and either explicit wedge
      evidence (watchdog stall / grant watchdog) follows the last
      progress record, or heartbeats kept arriving for longer than
      ``wedge_factor × interval`` after progress stopped — the process
      was alive but stuck (the BENCH_r04/r05 grant-wedge shape).
    - ``crashed``   — records stop abruptly (heartbeats die with the
      progress), or the run closed with an error status: the process
      (or the program) died mid-work.
    - ``reacquired`` — an otherwise-clean ending whose timeline carries
      ``grant.reacquired`` evidence: a grant wedged mid-run and the
      lease rescued it. Operationally clean-with-recovery — the round
      survived — but flagged so a fleet quietly re-acquiring every run
      is visible, not folded into ``clean``.
    - ``drained``  — clean-and-planned: the timeline carries
      ``serve.drain`` evidence (a replica was gracefully retired with
      its streams migrated). Outranks ``shed-overload`` — the
      operator's decision names the run.
    - ``shed-overload`` — clean-but-degraded: the run closed orderly
      but ``serve.shed`` evidence shows load was dropped (deadline
      expiry or criticality displacement) on the way.
    """
    if not records:
        return {"end_state": "unknown", "evidence": "no records survived"}
    open_run = None
    last_close = None
    preempted = False
    for rec in records:
        kind = rec.get("kind")
        if kind == "run.start":
            open_run = rec
            preempted = False
        elif kind == "run.end":
            open_run = None
            last_close = rec
        elif (kind == "preemption.latch"
              or (kind == "span"
                  and rec.get("name") == "preemption.latch")):
            preempted = True
    last = records[-1]
    progress = [r for r in records if _is_progress(r)]
    last_progress = progress[-1] if progress else records[0]
    evidence = {
        "n_records": len(records),
        "last_record": {k: v for k, v in last.items()
                        if k not in ("_segment",)},
        "last_progress": {k: v for k, v in last_progress.items()
                          if k not in ("_segment",)},
    }
    # an orderly ending needs positive evidence: either a run actually
    # closed (run.end) with nothing started after it, or the recorder
    # itself closed with nothing in flight. A timeline with NO run and
    # no close — the BENCH_r04/r05 shape, where the grant wedges before
    # any section starts — falls through to the stuck-or-dead analysis.
    orderly = (open_run is None
               and (last_close is not None
                    or last_progress.get("kind") == "flight.close"))
    if orderly:
        status = (last_close or {}).get("status", "clean")
        # only the latch means preemption: status "stopped" alone is any
        # on_chunk callback returning True (e.g. a user's convergence
        # early-stop) — an orderly ending, not an eviction story
        if preempted:
            return {"end_state": "preempted", "evidence": evidence,
                    "status": status}
        if str(status).startswith("error"):
            return {"end_state": "crashed", "evidence": evidence,
                    "status": status}
        reacquires = sum(1 for r in records if _is_reacquire_evidence(r))
        if reacquires:
            evidence["n_reacquires"] = reacquires
            return {"end_state": "reacquired", "evidence": evidence,
                    "status": status}
        # serve-fleet orderly variants, most deliberate first: a
        # PLANNED drain outranks shedding (a drained run that also
        # shed classifies by the operator's decision, with the shed
        # count still in the evidence)
        drains = sum(1 for r in records if _is_drain_evidence(r))
        sheds = sum(1 for r in records if _is_shed_evidence(r))
        if sheds:
            evidence["n_sheds"] = sheds
        if drains:
            evidence["n_drains"] = drains
            return {"end_state": "drained", "evidence": evidence,
                    "status": status}
        if sheds:
            return {"end_state": "shed-overload", "evidence": evidence,
                    "status": status}
        return {"end_state": "clean", "evidence": evidence,
                "status": status}
    # work was in flight (a run, or a pre-run phase like grant
    # acquisition) when the records stop: stuck or dead?
    if open_run is not None:
        evidence["open_run"] = {k: v for k, v in open_run.items()
                                if k not in ("_segment",)}
    if preempted:
        # latched but never reached the chunk boundary that would have
        # stopped it cleanly — the preemption killed it mid-chunk
        evidence["note"] = "preemption latched but the run never closed"
    wedge_after_progress = any(
        _is_wedge_evidence(r) and r.get("t_wall", 0)
        >= last_progress.get("t_wall", 0) for r in records)
    # an open grant.wait marker IS wedge evidence: it is written
    # immediately before a call that can block indefinitely, and a
    # grant that returned would have produced further progress records
    open_grant = last_progress.get("kind") == "grant.wait"
    interval = DEFAULT_HEARTBEAT_S
    for r in reversed(records):
        if r.get("kind") == "flight.heartbeat":
            interval = float(r.get("interval_s", interval))
            break
    silent_s = float(last.get("t_wall", 0.0)) - float(
        last_progress.get("t_wall", 0.0))
    evidence["silent_s"] = round(silent_s, 3)
    evidence["heartbeat_interval_s"] = interval
    if (wedge_after_progress or open_grant
            or silent_s >= wedge_factor * interval):
        return {"end_state": "wedged", "evidence": evidence}
    return {"end_state": "crashed", "evidence": evidence}
