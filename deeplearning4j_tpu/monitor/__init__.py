"""Telemetry subsystem: metrics registry, span tracer, exporters, and the
in-program metrics pack.

The reference dl4j treats listeners + the web UI as a first-class
observability bus; our fused pipeline collapsed E x N optimizer steps into
one opaque XLA dispatch and left only ad-hoc per-class counters behind.
This package is the cross-cutting layer that fixes it (see
``docs/observability.md`` for the metric catalog, span taxonomy, and
exporter formats):

- :mod:`~deeplearning4j_tpu.monitor.registry` — ``MetricsRegistry``
  (counters / gauges / histograms with labels); ``metrics()`` is the
  process-global instance the scattered counters land behind.
- :mod:`~deeplearning4j_tpu.monitor.trace` — ``SpanTracer``
  (context-manager spans, monotonic timestamps, parent ids, injectable
  clock); ``tracer()`` is the process-global instance instrumenting
  chunk dispatch, readbacks, cache builds, checkpoints, grant
  acquisition, and retry sleeps.
- :mod:`~deeplearning4j_tpu.monitor.exporters` — JSONL event log +
  Prometheus textfile (``DL4J_TELEMETRY_DIR``) and the
  ``telemetry_summary()`` block bench artifacts embed.
- :mod:`~deeplearning4j_tpu.monitor.pack` — the DEVICE-side per-step
  metrics pack the fused epoch program optionally carries (grad/update/
  param global-norms + lr scale as an ``[E, N, 4]`` history). Imported
  separately by the network classes; this ``__init__`` stays
  stdlib-only so control-plane modules can import it before (or
  without) jax.
- :mod:`~deeplearning4j_tpu.monitor.profile` — the compiled-program
  observatory: per-program ``cost_analysis()``/``memory_analysis()``
  profiles of every cached fused program (``DL4J_PROFILE``), compile
  wall times, and the cost model's step-time decomposition.
- :mod:`~deeplearning4j_tpu.monitor.memory` — HBM watermark sampling at
  chunk boundaries (device ``memory_stats()`` / live-array accounting)
  and the runtime check of the epoch-cache per-shard budget model.
- :mod:`~deeplearning4j_tpu.monitor.ledger` — the run-level goodput/
  badput ledger: every wall-clock second of a fused run classified by
  state from the span taxonomy plus chunk-boundary marks; the report
  rides in ``telemetry_summary()``.
- :mod:`~deeplearning4j_tpu.monitor.flight` — the crash-surviving
  flight recorder (``DL4J_FLIGHT``): a bounded segment-rotated on-disk
  ring of spans/events/ledger transitions; ``scripts/flight_report.py``
  classifies a dead run's end state from the surviving segments.

Env surface: ``DL4J_TELEMETRY`` (``on`` compiles the metrics pack into
the fused step; default off = bitwise PR-5 program),
``DL4J_TELEMETRY_STRIDE`` (compute the pack every N-th iteration), and
``DL4J_TELEMETRY_DIR`` (enable file exporters). Registry + tracer are
always live — they are host-side and effectively free.
"""

from __future__ import annotations

import os

from deeplearning4j_tpu.monitor.registry import (  # noqa: F401
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    metrics,
)
from deeplearning4j_tpu.monitor.trace import (  # noqa: F401
    Span,
    SpanTracer,
    set_tracer,
    tracer,
)
from deeplearning4j_tpu.monitor.exporters import (  # noqa: F401
    JsonlExporter,
    export_metrics_jsonl,
    telemetry_dir,
    telemetry_summary,
    write_prometheus_textfile,
)
from deeplearning4j_tpu.monitor.profile import (  # noqa: F401
    ProfiledProgram,
    ProgramProfile,
    capture_program_profile,
    classify_boundedness,
    flops_divergence_pct,
    profile_enabled,
    profiles,
)
from deeplearning4j_tpu.monitor.memory import (  # noqa: F401
    sample_hbm_watermark,
    validate_cache_budget,
)
from deeplearning4j_tpu.monitor.ledger import (  # noqa: F401
    RunLedger,
    ledger_chunk_done,
    ledger_chunk_start,
    ledger_run_end,
    ledger_run_start,
    run_ledger,
    set_run_ledger,
)
from deeplearning4j_tpu.monitor.flight import (  # noqa: F401
    FlightRecorder,
    classify_end_state,
    flight,
    flight_record,
    load_flight_records,
    set_flight,
)

__all__ = [
    "Counter", "Gauge", "Histogram", "MetricsRegistry", "metrics",
    "Span", "SpanTracer", "set_tracer", "tracer",
    "JsonlExporter", "export_metrics_jsonl", "telemetry_dir",
    "telemetry_summary", "write_prometheus_textfile",
    "telemetry_enabled", "metrics_stride", "fused_metrics_stride",
    "record_counter",
    "ProfiledProgram", "ProgramProfile", "capture_program_profile",
    "classify_boundedness", "flops_divergence_pct", "profile_enabled",
    "profiles", "sample_hbm_watermark", "validate_cache_budget",
    "RunLedger", "ledger_chunk_done", "ledger_chunk_start",
    "ledger_run_end", "ledger_run_start", "run_ledger", "set_run_ledger",
    "FlightRecorder", "classify_end_state", "flight", "flight_record",
    "load_flight_records", "set_flight",
]

_ON = ("1", "on", "true", "yes")
_OFF = ("", "0", "off", "false", "no")


def telemetry_enabled() -> bool:
    """``DL4J_TELEMETRY``: ``on`` compiles the in-program metrics pack
    into the fused epoch step. Default OFF — the fused program stays
    bitwise-identical to the pre-telemetry build."""
    raw = os.environ.get("DL4J_TELEMETRY", "").strip().lower()
    if raw in _ON:
        return True
    if raw not in _OFF:
        import logging

        logging.getLogger(__name__).warning(
            "DL4J_TELEMETRY=%r is not on/off; treating as off", raw)
    return False


def metrics_stride() -> int:
    """``DL4J_TELEMETRY_STRIDE`` (default 1): compute the metrics pack on
    every stride-th iteration of the fused program; off-stride history
    rows are NaN. Only meaningful with ``DL4J_TELEMETRY=on``."""
    raw = os.environ.get("DL4J_TELEMETRY_STRIDE", "")
    try:
        return max(1, int(raw)) if raw else 1
    except ValueError:
        return 1


def fused_metrics_stride(override=None) -> int:
    """Resolve a ``fit_epochs(telemetry=...)`` override to the static
    stride baked into the fused program: 0 = pack compiled out.
    ``None`` -> the env (``DL4J_TELEMETRY`` / ``DL4J_TELEMETRY_STRIDE``),
    ``False`` -> 0, ``True`` -> the env stride, an int -> that stride
    (0 disables)."""
    if override is None:
        return metrics_stride() if telemetry_enabled() else 0
    if override is False:
        return 0
    if override is True:
        return metrics_stride()
    return max(0, int(override))


def record_counter(name: str, amount: float = 1.0, **labels) -> None:
    """One-line counter bump against the global registry — the idiom the
    control plane uses instead of growing new bare ``_*_counter``
    attributes (dl4j-lint's ``bare-counter`` rule enforces it)."""
    metrics().counter(name).inc(amount, **labels)
