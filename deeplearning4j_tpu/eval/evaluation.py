"""Evaluation / ConfusionMatrix / RegressionEvaluation implementations."""

from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np


class ConfusionMatrix:
    """Counts of (actual, predicted) pairs (eval/ConfusionMatrix.java).

    Array-backed: one [C, C] int64 grid, so lookups are O(1), row/column
    totals are O(C), and whole-batch count arrays (numpy bincount or the
    device confusion matrix readback) fold in as a single vectorized add —
    the dict-of-dicts walk the reference uses is O(C²) per ``to_array``
    and O(batch) python-loop ``add`` calls per eval. Classes outside the
    declared range grow the grid (the dict accepted them silently)."""

    def __init__(self, classes: Sequence[int]):
        self.classes = list(classes)
        n = len(self.classes)
        self._counts = np.zeros((n, n), np.int64)

    def _ensure_size(self, idx: int):
        n = self._counts.shape[0]
        if idx < n:
            return
        grown = np.zeros((idx + 1, idx + 1), np.int64)
        grown[:n, :n] = self._counts
        self._counts = grown
        self.classes.extend(range(n, idx + 1))

    def add(self, actual: int, predicted: int, count: int = 1):
        a, p = int(actual), int(predicted)
        self._ensure_size(max(a, p))
        self._counts[a, p] += count

    def add_array(self, counts: np.ndarray):
        """Fold a [C', C'] count grid in (vectorized ``add``)."""
        counts = np.asarray(counts, np.int64)
        self._ensure_size(counts.shape[0] - 1)
        self._counts[:counts.shape[0], :counts.shape[1]] += counts

    def get_count(self, actual: int, predicted: int) -> int:
        a, p = int(actual), int(predicted)
        if a >= self._counts.shape[0] or p >= self._counts.shape[1]:
            return 0
        return int(self._counts[a, p])

    def actual_total(self, actual: int) -> int:
        a = int(actual)
        if a >= self._counts.shape[0]:
            return 0
        return int(self._counts[a].sum())

    def predicted_total(self, predicted: int) -> int:
        p = int(predicted)
        if p >= self._counts.shape[1]:
            return 0
        return int(self._counts[:, p].sum())

    @property
    def matrix(self):
        """Dict-of-dicts view of the nonzero counts — the seed's internal
        representation, kept read-only for callers that iterate it."""
        out: dict = {}
        for a, p in zip(*np.nonzero(self._counts)):
            out.setdefault(int(a), {})[int(p)] = int(self._counts[a, p])
        return out

    def merge(self, other: "ConfusionMatrix"):
        self.add_array(other._counts)

    def to_array(self) -> np.ndarray:
        n = len(self.classes)
        if self._counts.shape[0] == n:
            return self._counts.copy()
        out = np.zeros((n, n), np.int64)
        m = min(n, self._counts.shape[0])
        out[:m, :m] = self._counts[:m, :m]
        return out


class Evaluation:
    """Multi-class classification metrics (eval/Evaluation.java)."""

    def __init__(self, num_classes: Optional[int] = None,
                 labels: Optional[List[str]] = None):
        self.num_classes = num_classes
        self.label_names = labels
        self.confusion: Optional[ConfusionMatrix] = None

    def _ensure(self, n: int):
        if self.confusion is None:
            self.num_classes = self.num_classes or n
            self.confusion = ConfusionMatrix(list(range(self.num_classes)))

    def eval(self, labels: np.ndarray, predictions: np.ndarray,
             mask: Optional[np.ndarray] = None):
        """labels/predictions: one-hot or probability arrays [b, c] or
        time-series [b, t, c]; mask [b] / [b, t]."""
        labels = np.asarray(labels)
        predictions = np.asarray(predictions)
        if labels.ndim == 3:  # flatten time into batch, honoring the mask
            b, t, c = labels.shape
            labels = labels.reshape(b * t, c)
            predictions = predictions.reshape(b * t, c)
            if mask is not None:
                mask = np.asarray(mask).reshape(b * t)
        self._ensure(labels.shape[-1])
        actual = np.argmax(labels, axis=-1)
        predicted = np.argmax(predictions, axis=-1)
        if mask is not None:
            keep = np.asarray(mask).astype(bool)
            actual, predicted = actual[keep], predicted[keep]
        # one bincount over actual*C + predicted replaces the per-example
        # python loop; C covers any out-of-range class so the flat index
        # stays collision-free (add_array grows the grid to match)
        c = max(int(self.num_classes),
                int(actual.max()) + 1 if actual.size else 0,
                int(predicted.max()) + 1 if predicted.size else 0)
        flat = actual.astype(np.int64) * c + predicted.astype(np.int64)
        counts = np.bincount(flat, minlength=c * c).reshape(c, c)
        self.confusion.add_array(counts)

    def eval_confusion(self, counts):
        """Fold a precomputed [C, C] count grid (rows=actual) into this
        Evaluation — the fold-in point for the DEVICE confusion matrix
        read back once per ``evaluate()`` call (perf/device_eval)."""
        counts = np.asarray(counts)
        self._ensure(counts.shape[0])
        self.confusion.add_array(counts)

    # --- per-class counts ---
    def true_positives(self, cls: int) -> int:
        return self.confusion.get_count(cls, cls)

    def false_positives(self, cls: int) -> int:
        return self.confusion.predicted_total(cls) - self.true_positives(cls)

    def false_negatives(self, cls: int) -> int:
        return self.confusion.actual_total(cls) - self.true_positives(cls)

    # --- aggregate metrics ---
    def accuracy(self) -> float:
        total = sum(self.confusion.actual_total(c) for c in self.confusion.classes)
        correct = sum(self.true_positives(c) for c in self.confusion.classes)
        return correct / total if total else 0.0

    def precision(self, cls: Optional[int] = None) -> float:
        if cls is not None:
            tp, fp = self.true_positives(cls), self.false_positives(cls)
            return tp / (tp + fp) if tp + fp else 0.0
        vals = [self.precision(c) for c in self.confusion.classes
                if self.confusion.actual_total(c) > 0]
        return float(np.mean(vals)) if vals else 0.0

    def recall(self, cls: Optional[int] = None) -> float:
        if cls is not None:
            tp, fn = self.true_positives(cls), self.false_negatives(cls)
            return tp / (tp + fn) if tp + fn else 0.0
        vals = [self.recall(c) for c in self.confusion.classes
                if self.confusion.actual_total(c) > 0]
        return float(np.mean(vals)) if vals else 0.0

    def f1(self, cls: Optional[int] = None) -> float:
        p, r = self.precision(cls), self.recall(cls)
        return 2 * p * r / (p + r) if p + r else 0.0

    def merge(self, other: "Evaluation"):
        """Distributed eval reduce (Evaluation.merge :684)."""
        if other.confusion is None:
            return self
        if self.confusion is None:
            self.num_classes = other.num_classes
            self.confusion = ConfusionMatrix(list(range(other.num_classes)))
        self.confusion.merge(other.confusion)
        return self

    def stats(self) -> str:
        """Text report (Evaluation.stats())."""
        if self.confusion is None:
            return "Evaluation: no data"
        lines = ["==========================Scores========================================"]
        lines.append(f" Accuracy:  {self.accuracy():.4f}")
        lines.append(f" Precision: {self.precision():.4f}")
        lines.append(f" Recall:    {self.recall():.4f}")
        lines.append(f" F1 Score:  {self.f1():.4f}")
        lines.append("========================================================================")
        lines.append("Confusion matrix (rows=actual, cols=predicted):")
        arr = self.confusion.to_array()
        for i, row in enumerate(arr):
            name = (self.label_names[i] if self.label_names
                    and i < len(self.label_names) else str(i))
            lines.append(f"  {name:>8}: " + " ".join(f"{v:6d}" for v in row))
        return "\n".join(lines)


class RegressionEvaluation:
    """Per-column regression metrics (eval/RegressionEvaluation.java)."""

    def __init__(self, num_columns: Optional[int] = None):
        self.num_columns = num_columns
        self._labels: List[np.ndarray] = []
        self._preds: List[np.ndarray] = []

    def eval(self, labels, predictions, mask=None):
        labels = np.asarray(labels, np.float64)
        predictions = np.asarray(predictions, np.float64)
        if labels.ndim == 3:
            b, t, c = labels.shape
            labels = labels.reshape(b * t, c)
            predictions = predictions.reshape(b * t, c)
            if mask is not None:
                keep = np.asarray(mask).reshape(b * t).astype(bool)
                labels, predictions = labels[keep], predictions[keep]
        elif mask is not None:
            keep = np.asarray(mask).astype(bool)
            labels, predictions = labels[keep], predictions[keep]
        self._labels.append(labels)
        self._preds.append(predictions)

    def _stacked(self):
        return np.concatenate(self._labels), np.concatenate(self._preds)

    def mean_squared_error(self, col: int) -> float:
        y, p = self._stacked()
        return float(np.mean((y[:, col] - p[:, col]) ** 2))

    def mean_absolute_error(self, col: int) -> float:
        y, p = self._stacked()
        return float(np.mean(np.abs(y[:, col] - p[:, col])))

    def root_mean_squared_error(self, col: int) -> float:
        return float(np.sqrt(self.mean_squared_error(col)))

    def correlation_r2(self, col: int) -> float:
        y, p = self._stacked()
        ss_res = np.sum((y[:, col] - p[:, col]) ** 2)
        ss_tot = np.sum((y[:, col] - np.mean(y[:, col])) ** 2)
        return float(1.0 - ss_res / ss_tot) if ss_tot else 0.0

    def pearson_correlation(self, col: int) -> float:
        y, p = self._stacked()
        if np.std(y[:, col]) == 0 or np.std(p[:, col]) == 0:
            return 0.0
        return float(np.corrcoef(y[:, col], p[:, col])[0, 1])

    def stats(self) -> str:
        y, _ = self._stacked()
        cols = y.shape[1]
        lines = ["Column    MSE        MAE        RMSE       R^2        Corr"]
        for c in range(cols):
            lines.append(
                f"{c:6d} {self.mean_squared_error(c):10.5f} "
                f"{self.mean_absolute_error(c):10.5f} "
                f"{self.root_mean_squared_error(c):10.5f} "
                f"{self.correlation_r2(c):10.5f} "
                f"{self.pearson_correlation(c):10.5f}"
            )
        return "\n".join(lines)
