"""Evaluation: classification + regression metrics.

Mirror of ``eval/Evaluation.java`` (771 LoC: accuracy/precision/recall/F1 via
ConfusionMatrix, eval(INDArray,INDArray) :90-147, stats() text report,
merge :684 for distributed map-side eval) and RegressionEvaluation.java
(MSE/MAE/RMSE/R²/correlation per column).
"""

from deeplearning4j_tpu.eval.evaluation import (  # noqa: F401
    ConfusionMatrix,
    Evaluation,
    RegressionEvaluation,
)
