"""Cloud provisioning (reference: deeplearning4j-aws — EC2/S3 → TPU VM/GCS)."""

from deeplearning4j_tpu.cloud.provision import (  # noqa: F401
    GcsTransfer,
    TpuProvisioner,
    TpuVmSpec,
)
