"""TPU VM provisioning — the deeplearning4j-aws replacement.

The reference's cloud module (``deeplearning4j-aws``, 1,579 LoC:
``ec2/Ec2BoxCreator`` boots EC2 instances, ``provision/HostProvisioner``
scp/ssh-bootstraps each box, ``s3/`` up/downloads datasets) maps on GCP TPU
to: create a TPU VM (possibly multi-host pod slice), run a bootstrap command
on every worker, and move data via GCS. This module builds the exact
``gcloud``/``gsutil`` invocations and (optionally) executes them — command
construction is pure and unit-testable in a zero-egress environment;
execution shells out only when the operator asks.
"""

from __future__ import annotations

import shlex
import subprocess
from dataclasses import dataclass, field
from typing import List, Optional, Sequence


@dataclass
class TpuVmSpec:
    """The Ec2BoxCreator analogue: what to boot."""

    name: str
    zone: str
    accelerator_type: str = "v5litepod-8"
    runtime_version: str = "tpu-ubuntu2204-base"
    project: Optional[str] = None
    preemptible: bool = False
    network: Optional[str] = None
    tags: List[str] = field(default_factory=list)


class TpuProvisioner:
    """Builds gcloud commands for TPU VM lifecycle + bootstrap
    (Ec2BoxCreator.create → create(); HostProvisioner's scp/ssh/bootstrap →
    copy_to/run_on; blowupBoxes → delete)."""

    def __init__(self, spec: TpuVmSpec, dry_run: bool = True):
        self.spec = spec
        self.dry_run = dry_run
        self.commands_issued: List[List[str]] = []

    # -- command builders (pure) ---------------------------------------
    def _base(self) -> List[str]:
        cmd = ["gcloud", "compute", "tpus", "tpu-vm"]
        return cmd

    def _common_flags(self) -> List[str]:
        flags = [f"--zone={self.spec.zone}"]
        if self.spec.project:
            flags.append(f"--project={self.spec.project}")
        return flags

    def create_command(self) -> List[str]:
        cmd = self._base() + ["create", self.spec.name] + self._common_flags()
        cmd.append(f"--accelerator-type={self.spec.accelerator_type}")
        cmd.append(f"--version={self.spec.runtime_version}")
        if self.spec.preemptible:
            cmd.append("--preemptible")
        if self.spec.network:
            cmd.append(f"--network={self.spec.network}")
        if self.spec.tags:
            cmd.append("--tags=" + ",".join(self.spec.tags))
        return cmd

    def delete_command(self) -> List[str]:
        return (self._base() + ["delete", self.spec.name]
                + self._common_flags() + ["--quiet"])

    def run_command(self, shell_cmd: str,
                    worker: str = "all") -> List[str]:
        """ssh a command to worker(s) (HostProvisioner.runRemoteCommand)."""
        return (self._base() + ["ssh", self.spec.name] + self._common_flags()
                + [f"--worker={worker}", f"--command={shell_cmd}"])

    def copy_command(self, local_path: str, remote_path: str,
                     worker: str = "all",
                     recurse: bool = False) -> List[str]:
        """scp files to worker(s) (HostProvisioner.uploadFile)."""
        cmd = self._base() + ["scp"]
        if recurse:
            cmd.append("--recurse")
        return (cmd + [local_path, f"{self.spec.name}:{remote_path}"]
                + self._common_flags() + [f"--worker={worker}"])

    def bootstrap_commands(self, repo_dir: str,
                           extra_setup: Sequence[str] = ()) -> List[List[str]]:
        """Full bring-up: copy the framework + install + sanity-check
        (HostProvisioner.bootstrap). Failures propagate: the install runs
        unmuffled and the sanity check imports the framework itself."""
        cmds = [
            self.copy_command(repo_dir, "~/deeplearning4j_tpu", recurse=True),
            self.run_command("pip install -e ~/deeplearning4j_tpu"),
        ]
        for setup in extra_setup:
            cmds.append(self.run_command(setup))
        cmds.append(self.run_command(
            "python -c 'import deeplearning4j_tpu, jax; "
            "print(jax.device_count())'"))
        return cmds

    # -- execution ------------------------------------------------------
    def _issue(self, cmd: List[str]) -> Optional[str]:
        self.commands_issued.append(cmd)
        if self.dry_run:
            return None
        out = subprocess.run(cmd, check=True, capture_output=True, text=True)
        return out.stdout

    def create(self) -> Optional[str]:
        return self._issue(self.create_command())

    def delete(self) -> Optional[str]:
        return self._issue(self.delete_command())

    def run(self, shell_cmd: str, worker: str = "all") -> Optional[str]:
        return self._issue(self.run_command(shell_cmd, worker))

    def copy_to(self, local: str, remote: str,
                worker: str = "all") -> Optional[str]:
        return self._issue(self.copy_command(local, remote, worker))

    def bootstrap(self, repo_dir: str,
                  extra_setup: Sequence[str] = ()) -> None:
        for cmd in self.bootstrap_commands(repo_dir, extra_setup):
            self._issue(cmd)

    def script(self) -> str:
        """Render issued commands as a reviewable shell script."""
        return "\n".join(" ".join(shlex.quote(a) for a in c)
                         for c in self.commands_issued)


class GcsTransfer:
    """Dataset up/download (s3/reader/S3Downloader.java,
    s3/uploader/S3Uploader.java) via gsutil commands; ``dry_run`` records
    the commands without executing, keeping tests hermetic. gs:// URIs
    only."""

    def __init__(self, dry_run: bool = True):
        self.dry_run = dry_run
        self.commands_issued: List[List[str]] = []

    def upload_command(self, local: str, gcs_uri: str) -> List[str]:
        if not gcs_uri.startswith("gs://"):
            raise ValueError("destination must be a gs:// URI")
        return ["gsutil", "-m", "cp", "-r", local, gcs_uri]

    def download_command(self, gcs_uri: str, local: str) -> List[str]:
        if not gcs_uri.startswith("gs://"):
            raise ValueError("source must be a gs:// URI")
        return ["gsutil", "-m", "cp", "-r", gcs_uri, local]

    def _issue(self, cmd: List[str]) -> None:
        self.commands_issued.append(cmd)
        if not self.dry_run:
            subprocess.run(cmd, check=True)

    def upload(self, local: str, gcs_uri: str) -> None:
        self._issue(self.upload_command(local, gcs_uri))

    def download(self, gcs_uri: str, local: str) -> None:
        self._issue(self.download_command(gcs_uri, local))
