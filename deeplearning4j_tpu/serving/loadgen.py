"""Open-loop Poisson load generator + latency report for the serve bench.

Open loop means arrivals come from a schedule, not from completions —
the load a server actually faces (users do not wait for each other), and
the one that exposes queueing collapse. A closed loop would hide an
under-provisioned server behind its own backpressure.

The schedule is generated up front (deterministic in the seed) so the
same stream can replay against different server configs; the driver
submits every arrival whose time has come, steps the server, and sleeps
only when idle with arrivals still pending. Clock and sleep are
injectable: tests drive a fake clock, the bench uses wall time.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Callable, List, Optional, Sequence

import numpy as np

from deeplearning4j_tpu.serving.scheduler import ServeQueueFull

__all__ = ["Arrival", "poisson_schedule", "run_open_loop", "LoadReport"]


@dataclass
class Arrival:
    arrival_s: float
    prompt: np.ndarray
    max_new_tokens: int
    seed: int
    # RELATIVE deadline budget (seconds from arrival); the driver
    # converts to the absolute instant at submit. None = no deadline.
    deadline_s: Optional[float] = None
    criticality: str = "interactive"


def poisson_schedule(n_requests: int, rate_rps: float, *,
                     vocab_size: int,
                     prompt_lens: Sequence[int] = (8, 16, 24, 48),
                     max_new_tokens: Sequence[int] = (4, 8, 16),
                     criticality_mix: Optional[dict] = None,
                     deadlines_s: Optional[dict] = None,
                     seed: int = 0) -> List[Arrival]:
    """Ragged request stream: exponential interarrivals at ``rate_rps``,
    prompt lengths / generation lengths drawn uniformly from the given
    menus (several ladder rungs on purpose — the compile-flatness claim
    is only interesting under shape raggedness).

    ``criticality_mix`` maps class -> weight (e.g. ``{"interactive":
    0.3, "batch": 0.7}``; default all-interactive) and ``deadlines_s``
    maps class -> RELATIVE deadline budget (classes absent get none) —
    together they shape the overload-storm workloads the serve-SLO soak
    drives."""
    if n_requests < 1 or rate_rps <= 0:
        raise ValueError("need n_requests >= 1 and rate_rps > 0")
    rng = np.random.default_rng(seed)
    gaps = rng.exponential(1.0 / rate_rps, n_requests)
    arrivals = np.cumsum(gaps)
    classes, weights = None, None
    if criticality_mix:
        classes = list(criticality_mix)
        total = float(sum(criticality_mix.values()))
        weights = [criticality_mix[c] / total for c in classes]
    deadlines_s = deadlines_s or {}
    out = []
    for i in range(n_requests):
        plen = int(rng.choice(prompt_lens))
        crit = (str(rng.choice(classes, p=weights))
                if classes else "interactive")
        out.append(Arrival(
            arrival_s=float(arrivals[i]),
            prompt=rng.integers(0, vocab_size, plen, dtype=np.int32),
            max_new_tokens=int(rng.choice(max_new_tokens)),
            seed=int(rng.integers(0, 2**31 - 1)),
            deadline_s=deadlines_s.get(crit),
            criticality=crit))
    return out


@dataclass
class LoadReport:
    """Aggregated open-loop run: per-request latency/TTFT/TPOT samples
    plus the stream-level occupancy trace."""

    latencies_s: List[float] = field(default_factory=list)
    ttfts_s: List[float] = field(default_factory=list)
    tpots_s: List[float] = field(default_factory=list)
    occupancy: List[float] = field(default_factory=list)
    # per-drop timestamps (seconds since stream start): overflow drops
    # used to survive only as a count, which made a fleet that sheds
    # load at t=0.1s indistinguishable from one that sheds at t=9.9s —
    # the series lets fleet-vs-single comparisons see WHEN capacity ran
    # out, not just how often
    drop_times_s: List[float] = field(default_factory=list)
    submitted: int = 0
    rejected: int = 0
    finished: int = 0
    tokens: int = 0
    wall_s: float = 0.0
    # overload-control accounting: sheds (admitted then dropped by
    # deadline/displacement — distinct from rejected-at-admission),
    # split by class and by where the deadline caught them, plus
    # per-class submission/completion/TTFT splits so the SLO gate can
    # assert "interactive held while batch absorbed the storm"
    shed: int = 0
    shed_by_class: dict = field(default_factory=dict)
    expired_in_queue: int = 0
    expired_in_flight: int = 0
    submitted_by_class: dict = field(default_factory=dict)
    finished_by_class: dict = field(default_factory=dict)
    ttfts_by_class: dict = field(default_factory=dict)
    # retry-amplification evidence: total placements (first + re-
    # dispatch) and hedges across the run
    placements: int = 0
    hedges: int = 0

    @staticmethod
    def _pct(xs: List[float], q: float) -> Optional[float]:
        return float(np.percentile(xs, q)) if xs else None

    def summary(self) -> dict:
        """The bench's ``serve`` section fields (ms where latency)."""
        ms = 1e3
        wall = self.wall_s or float("nan")
        return {
            "submitted": self.submitted,
            "rejected": self.rejected,
            "finished": self.finished,
            "tokens": self.tokens,
            "wall_s": round(self.wall_s, 3),
            "requests_per_sec": round(self.finished / wall, 2),
            "tokens_per_sec": round(self.tokens / wall, 1),
            "p50_latency_ms": _r(self._pct(self.latencies_s, 50), ms),
            "p99_latency_ms": _r(self._pct(self.latencies_s, 99), ms),
            "ttft_p50_ms": _r(self._pct(self.ttfts_s, 50), ms),
            "ttft_p99_ms": _r(self._pct(self.ttfts_s, 99), ms),
            "tpot_mean_ms": _r(float(np.mean(self.tpots_s))
                               if self.tpots_s else None, ms),
            "occupancy_mean": (round(float(np.mean(self.occupancy)), 3)
                               if self.occupancy else None),
            # shed load, accounted in time: the sorted drop timestamps
            "dropped_request_seconds": [round(t, 3)
                                        for t in sorted(self.drop_times_s)],
            "shed": self.shed,
            "shed_by_class": dict(self.shed_by_class),
            "expired_in_queue": self.expired_in_queue,
            "expired_in_flight": self.expired_in_flight,
            "submitted_by_class": dict(self.submitted_by_class),
            "finished_by_class": dict(self.finished_by_class),
            "ttft_p50_ms_by_class": {
                c: _r(self._pct(xs, 50), ms)
                for c, xs in self.ttfts_by_class.items()},
            # placements + hedges over submissions: the amplification
            # the retry budget bounds (1.0 = no retries at all)
            "retry_amplification": (
                round((self.placements + self.hedges)
                      / self.submitted, 3)
                if self.submitted else None),
        }


def _r(v: Optional[float], scale: float) -> Optional[float]:
    return None if v is None else round(v * scale, 3)


def run_open_loop(server, schedule: List[Arrival], *,
                  clock: Optional[Callable[[], float]] = None,
                  sleep: Optional[Callable[[float], None]] = None,
                  idle_wait_s: float = 0.001) -> LoadReport:
    """Drive ``server`` through ``schedule`` open-loop. Rejected submits
    (queue full) are counted, not retried — open loop drops, it does not
    secretly become closed loop. Runs until every arrival was offered
    and the server drained."""
    clock = clock or time.monotonic
    sleep = sleep or time.sleep
    report = LoadReport()
    t0 = clock()
    i = 0
    reqs = []
    while i < len(schedule) or server.busy():
        now = clock() - t0
        while i < len(schedule) and schedule[i].arrival_s <= now:
            a = schedule[i]
            i += 1
            try_submit = getattr(server, "try_submit", None)
            if try_submit is not None:
                # the arrival's deadline is a budget from NOW; the
                # server wants the absolute instant on ITS clock axis
                # (the same injected clock, before the t0 re-base)
                deadline = (None if a.deadline_s is None
                            else clock() + a.deadline_s)
                verdict = try_submit(a.prompt, a.max_new_tokens,
                                     seed=a.seed, deadline_s=deadline,
                                     criticality=a.criticality)
                admitted = verdict.admitted
                req = verdict.request
            else:
                # a server without the non-blocking surface: legacy path
                try:
                    req = server.submit(a.prompt, a.max_new_tokens,
                                        seed=a.seed)
                    admitted = True
                except ServeQueueFull:
                    admitted, req = False, None
            if admitted:
                report.submitted += 1
                report.submitted_by_class[a.criticality] = (
                    report.submitted_by_class.get(a.criticality, 0) + 1)
                reqs.append(req)
            else:
                # open loop drops, it does not retry — but it records
                # WHEN it dropped, so shed load is visible in time
                report.rejected += 1
                report.drop_times_s.append(now)
        progressed = server.step()
        report.occupancy.append(server.occupancy())
        if not progressed and i < len(schedule):
            # idle with arrivals pending: wait out the gap
            gap = schedule[i].arrival_s - (clock() - t0)
            if gap > 0:
                sleep(min(gap, 0.05) if gap > idle_wait_s else idle_wait_s)
    report.wall_s = clock() - t0
    for req in reqs:
        if req.state == "shed":
            # admitted, then dropped by deadline or displacement: the
            # shed instant joins the drop series (t0-relative)
            report.shed += 1
            report.shed_by_class[req.criticality] = (
                report.shed_by_class.get(req.criticality, 0) + 1)
            if req.finish_s is not None:
                report.drop_times_s.append(req.finish_s - t0)
            continue
        if req.state != "finished":
            continue
        report.finished += 1
        report.finished_by_class[req.criticality] = (
            report.finished_by_class.get(req.criticality, 0) + 1)
        report.tokens += len(req.tokens)
        if req.latency_s is not None:
            report.latencies_s.append(req.latency_s)
        if req.ttft_s is not None:
            report.ttfts_s.append(req.ttft_s)
            report.ttfts_by_class.setdefault(
                req.criticality, []).append(req.ttft_s)
        if req.first_token_s is not None and req.finish_s is not None \
                and len(req.tokens) > 1:
            report.tpots_s.append((req.finish_s - req.first_token_s)
                                  / (len(req.tokens) - 1))
    stats = getattr(server, "stats", None)
    if stats is not None:
        s = stats()
        report.expired_in_queue = s.get("expired_in_queue", 0)
        report.expired_in_flight = s.get("expired_in_flight", 0)
    return report
