"""DecodeServer: continuous batching over the slot pool.

The online counterpart of the batch-oriented eval path (PR 2): a
persistent server object that compiles its program set once, keeps all
state device-resident (TensorFlow-paper serving/training split), and
multiplexes S concurrent requests through ONE jitted decode dispatch.

The loop, per ``step()`` (a step IS a fusion boundary):

1. **admit** — pop queued requests into free slots; each admission runs
   the bucket-compiled prefill (``serve.prefill`` span), records TTFT,
   and may retire immediately when ``max_new_tokens == 1``. Admission
   happens ONLY here: with ``fuse_steps=K`` a request arriving mid-scan
   waits for the dispatch in flight to finish (the admission-boundary
   trade — bounded added TTFT, in exchange for K tokens per dispatch).
2. **decode** — if any slot is live, run ONE decode dispatch: the plain
   single-step program (``fuse_steps=1``, the PR-10 path, bitwise), the
   K-step fused program, or K speculative rounds when a draft is
   configured. Every live slot appends up to its remaining tokens;
   finished requests retire and free their slots.

The host sees one token-block readback per dispatch ([S] at K=1,
[K, S] fused, [K, S, G+2] speculative) — that is the decode loop's
entire host/device chatter, and it is also the synchronization point
the per-request results come from. Everything else (queue, slot table)
is host bookkeeping the scheduler needs anyway; the per-slot cursors
live ON DEVICE and advance in-program.

Observability: queue depth / occupancy gauges, token + dispatch
counters (``serve_decode_steps_total`` counts DISPATCHES — with fusion
one dispatch covers up to K·(G+1) tokens; ``stats()`` derives
dispatches/token and accepted-tokens/dispatch, the fast-path headline
metrics), speculative proposed/accepted counters, TTFT/TPOT/latency
histograms (``monitor/registry``), ``serve.step`` and ``serve.prefill``
spans (``monitor/trace`` — forwarded to the flight recorder when one is
live, like every span).
"""

from __future__ import annotations

import time
from collections import deque
from typing import Callable, Deque, List, Optional, Sequence, Tuple

import numpy as np

from deeplearning4j_tpu.monitor import metrics, tracer
from deeplearning4j_tpu.serving.engine import DecodeEngine
from deeplearning4j_tpu.serving.scheduler import (
    AdmissionVerdict, RequestQueue, ServeQueueFull, ServeRequest,
    criticality_rank, serve_deadline_s, serve_draft_layers,
    serve_fuse_steps, serve_kv_dtype, serve_max_queue, serve_slots)

__all__ = ["DecodeServer"]

# histogram buckets tuned for online latency (the default registry
# ladder tops out too coarse below 10 ms)
_LATENCY_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
                    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0,
                    float("inf"))


class DecodeServer:
    """Slot-batched online decode server for a :class:`TransformerLM`."""

    def __init__(self, model, *, slots: Optional[int] = None,
                 max_queue: Optional[int] = None,
                 max_len: Optional[int] = None,
                 temperature: float = 0.0, top_k: Optional[int] = None,
                 buckets: Optional[Sequence[int]] = None,
                 fuse_steps: Optional[int] = None,
                 kv_dtype: Optional[str] = None,
                 draft_model=None, draft_layers: Optional[int] = None,
                 spec_tokens: int = 3, mesh=None,
                 clock=time.monotonic):
        self.fuse_steps = (fuse_steps if fuse_steps is not None
                           else serve_fuse_steps())
        if self.fuse_steps < 1:
            raise ValueError(f"fuse_steps={fuse_steps} must be >= 1")
        if mesh is None:
            from deeplearning4j_tpu.parallel.sharding_registry import (
                mesh_from_env)

            mesh = mesh_from_env()
        self.engine = DecodeEngine(
            model, slots if slots is not None else serve_slots(),
            max_len=max_len, temperature=temperature, top_k=top_k,
            buckets=buckets,
            kv_dtype=kv_dtype if kv_dtype is not None else serve_kv_dtype(),
            draft_model=draft_model,
            draft_layers=(draft_layers if draft_layers is not None
                          else (0 if draft_model is not None
                                else serve_draft_layers())),
            spec_tokens=spec_tokens, mesh=mesh)
        self.model = model
        self.slots = self.engine.slots
        self.max_len = self.engine.max_len
        self.queue = RequestQueue(
            max_queue if max_queue is not None else serve_max_queue())
        self.clock = clock
        self._slot_req: List[Optional[ServeRequest]] = [None] * self.slots
        self._last_tok = np.zeros(self.slots, np.int32)
        self._last_tok_s = np.zeros(self.slots, np.float64)
        self._keys = self._zero_keys()
        self._draft_keys = self._zero_keys() if self.engine.spec else None
        # externally-prefilled requests waiting for a free slot: each
        # entry carries an ``install(engine, slot) -> (last_tok, key)``
        # that lands the handed-off KV slab + cursor into the slot
        # (serving/fleet/handoff.py builds these)
        self._handoffs: Deque[Tuple[ServeRequest, Callable]] = deque()
        self.finished: List[ServeRequest] = []
        # overload-control ledger: every shed request + the decision
        # evidence behind it (mirrored to the serve.shed tracer event)
        self.shed: List[ServeRequest] = []
        self.shed_log: List[dict] = []
        self.shed_by_class: dict = {}
        self.expired_in_queue = 0
        self.expired_in_flight = 0
        self.steps = 0
        self.decode_tokens = 0
        self.slot_dispatches = 0
        self.spec_proposed = 0
        self.spec_accepted = 0
        self._reg = metrics()

    def _zero_keys(self):
        import jax
        import jax.numpy as jnp

        return jnp.zeros((self.slots,) + jax.random.PRNGKey(0).shape,
                         jax.random.PRNGKey(0).dtype)

    # ------------------------------------------------------------------
    # submission
    # ------------------------------------------------------------------
    def submit(self, prompt, max_new_tokens: int, *, seed: int = 0,
               deadline_s: Optional[float] = None,
               criticality: str = "interactive") -> ServeRequest:
        """Enqueue one request. Validates against the slot capacity the
        way ``generate`` validates against its cache size; raises
        :class:`~.scheduler.ServeQueueFull` at the queue bound."""
        verdict = self.try_submit(prompt, max_new_tokens, seed=seed,
                                  deadline_s=deadline_s,
                                  criticality=criticality)
        if not verdict.admitted:
            raise ServeQueueFull(
                f"serve queue at max depth {self.queue.max_depth}")
        return verdict.request

    def try_submit(self, prompt, max_new_tokens: int, *,
                   seed: int = 0,
                   deadline_s: Optional[float] = None,
                   criticality: str = "interactive",
                   displace: bool = True) -> AdmissionVerdict:
        """Non-blocking ``submit``: returns an
        :class:`~.scheduler.AdmissionVerdict` instead of raising at the
        queue bound, so a routing frontend can place across replicas
        without exception-driven control flow. Malformed requests
        (empty prompt, capacity overflow, unknown criticality) still
        raise — those are caller bugs, not load conditions.

        ``deadline_s`` is the ABSOLUTE expiry instant on this server's
        clock (None falls back to ``DL4J_SERVE_DEADLINE_S`` as a budget
        from now); an already-expired submit is shed on the spot
        (reason ``"expired"``). At the queue bound, ``displace=True``
        lets this arrival shed the costliest queued request of a
        strictly lower criticality class (the victim rides back on the
        verdict's ``displaced`` field); the router's first placement
        pass disables it so plain spill is tried fleet-wide before
        anything is shed."""
        criticality_rank(criticality)
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.shape[0] < 1:
            raise ValueError("prompt must hold at least one token")
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        total = int(prompt.shape[0]) + max_new_tokens
        # speculative verify writes up to spec_tokens candidate K/V past
        # the live cursor, so the slot needs that slack in the pool
        slack = self.engine.spec_tokens if self.engine.spec else 0
        if total + slack > self.max_len:
            raise ValueError(
                f"prompt_len + max_new_tokens = {total}"
                + (f" (+ {slack} speculative slack)" if slack else "")
                + f" exceeds the server's slot capacity "
                f"max_len={self.max_len}")
        now = self.clock()
        if deadline_s is None:
            budget = serve_deadline_s()
            deadline_s = None if budget is None else now + budget
        req = ServeRequest(prompt=prompt, max_new_tokens=max_new_tokens,
                           seed=seed, deadline_s=deadline_s,
                           criticality=criticality)
        req.submit_s = now
        if req.expired(now):
            # a deadline already in the past: shed at the earliest
            # possible point — before it ever costs a queue entry
            self._shed(req, where="admission", reason="deadline", now=now)
            self._reg.counter("serve_requests_total").inc(event="rejected")
            return AdmissionVerdict(admitted=False, reason="expired",
                                    queue_depth=len(self.queue))
        if not self.queue.try_push(req):
            victim = None
            if displace:
                admitted, victim = self.queue.displace(req)
            else:
                admitted = False
            if not admitted:
                self._reg.counter("serve_requests_total").inc(
                    event="rejected")
                return AdmissionVerdict(admitted=False,
                                        reason="queue_full",
                                        queue_depth=len(self.queue))
            if victim is not None:
                self._shed(victim, where="queue", reason="shed_overload",
                           now=now, displaced_by=req.id)
            self._reg.counter("serve_requests_total").inc(
                event="submitted")
            self._reg.gauge("serve_queue_depth").set(len(self.queue))
            return AdmissionVerdict(admitted=True, request=req,
                                    queue_depth=len(self.queue),
                                    displaced=victim)
        self._reg.counter("serve_requests_total").inc(event="submitted")
        self._reg.gauge("serve_queue_depth").set(len(self.queue))
        return AdmissionVerdict(admitted=True, request=req,
                                queue_depth=len(self.queue))

    def _shed(self, req: ServeRequest, *, where: str, reason: str,
              now: float, displaced_by: Optional[int] = None) -> None:
        """Shed one request with its evidence: state flips to ``shed``,
        the decision lands in ``shed_log`` AND on the tracer timeline
        (``serve.shed`` event → flight recorder), and the
        ``serve_shed_total`` counter / ``serve_shed_by_class`` gauge
        move — nothing is dropped silently."""
        req.state = "shed"
        req.shed_reason = reason
        req.finish_s = now    # when it was shed (drop-series timestamp)
        self.shed.append(req)
        self.shed_by_class[req.criticality] = (
            self.shed_by_class.get(req.criticality, 0) + 1)
        if where == "queue" and reason == "deadline":
            self.expired_in_queue += 1
        elif where == "in_flight":
            self.expired_in_flight += 1
        decision = {"request": req.id, "criticality": req.criticality,
                    "where": where, "reason": reason, "t": now}
        if displaced_by is not None:
            decision["displaced_by"] = displaced_by
        self.shed_log.append(decision)
        self._reg.counter("serve_shed_total").inc(
            criticality=req.criticality, where=where)
        self._reg.gauge("serve_shed_by_class").set(
            float(self.shed_by_class[req.criticality]),
            criticality=req.criticality)
        tracer().event("serve.shed", **decision)

    def admit_external(self, req: ServeRequest,
                       install: Callable) -> None:
        """Queue an externally-prefilled request (prefill/decode split):
        at the next step boundary a free slot is claimed and
        ``install(engine, slot) -> (last_token, rng_key)`` lands the
        handed-off KV slab + cursor into it — the request then decodes
        exactly like a locally-prefilled one. ``req`` must already carry
        its first token (the prefill replica sampled it); its TTFT was
        recorded at prefill time, so this path never re-observes it."""
        if self.engine.spec:
            raise ValueError(
                "handoff into a speculative decode server is "
                "unsupported: the draft pool holds no prompt K/V for "
                "the handed-off slot")
        if not req.tokens:
            raise ValueError(
                "admit_external needs a prefilled request (its first "
                "token sampled by the prefill replica)")
        self._handoffs.append((req, install))

    def handoff_headroom(self) -> int:
        """Free slots not yet spoken for by queued handoffs — the
        router's can-this-replica-take-a-slab signal."""
        return self.free_slot_count() - len(self._handoffs)

    # ------------------------------------------------------------------
    # the serve loop
    # ------------------------------------------------------------------
    def _free_slots(self) -> List[int]:
        return [s for s, r in enumerate(self._slot_req) if r is None]

    def _live_slots(self) -> List[int]:
        return [s for s, r in enumerate(self._slot_req) if r is not None]

    def free_slot_count(self) -> int:
        """How many slots the next step boundary can admit into — the
        router's least-loaded placement signal."""
        return len(self._free_slots())

    def occupancy(self) -> float:
        return len(self._live_slots()) / self.slots

    def busy(self) -> bool:
        return (bool(self._live_slots()) or len(self.queue) > 0
                or bool(self._handoffs))

    def _admit_handoff(self, slot: int) -> None:
        req, install = self._handoffs.popleft()
        with tracer().span("serve.handoff.install", request=req.id,
                           slot=slot):
            last_tok, key = install(self.engine, slot)
        now = self.clock()
        req.state = "running"
        req.handoff = True
        req.slot = slot
        self._slot_req[slot] = req
        self._last_tok[slot] = int(last_tok)
        self._last_tok_s[slot] = now
        self._keys = self._keys.at[slot].set(key)
        # TTFT was recorded by the prefill replica; the installed slab
        # already covers every emitted token, so a request that arrived
        # complete just retires
        if len(req.tokens) >= req.max_new_tokens:
            self._retire(slot, now)

    def _admit(self) -> int:
        import jax

        admitted = 0
        for slot in self._free_slots():
            # handed-off slabs first: their prefill compute is already
            # spent — a queued prompt admitted ahead of them would idle
            # a finished prefill while burning a slot on new work
            if self._handoffs:
                self._admit_handoff(slot)
                admitted += 1
                continue
            # pop past corpses: an expired request sheds HERE — before
            # its prefill burns the slot — and a canceled hedge loser
            # vanishes without a trace in the finished ledger
            req = self.queue.pop()
            while req is not None:
                now = self.clock()
                if req.canceled:
                    req.state = "canceled"
                elif req.expired(now):
                    self._shed(req, where="queue", reason="deadline",
                               now=now)
                else:
                    break
                req = self.queue.pop()
            if req is None:
                break
            with tracer().span("serve.prefill", request=req.id,
                               slot=slot,
                               prompt_len=int(req.prompt.shape[0])):
                key = jax.random.PRNGKey(req.seed)
                if self.engine.spec:
                    # an independent per-slot draft stream (only the
                    # sampled speculative path consumes it)
                    self._draft_keys = self._draft_keys.at[slot].set(
                        jax.random.fold_in(key, 0x5bec))
                tok, key = self.engine.prefill(req.prompt, slot, key)
                tok = int(tok)
            now = self.clock()
            req.state = "running"
            req.slot = slot
            req.first_token_s = now
            req.tokens.append(tok)
            self._slot_req[slot] = req
            self._last_tok[slot] = tok
            self._last_tok_s[slot] = now
            self._keys = self._keys.at[slot].set(key)
            if req.ttft_s is not None:
                self._reg.histogram("serve_ttft_seconds",
                                    buckets=_LATENCY_BUCKETS
                                    ).observe(req.ttft_s)
            self._reg.counter("serve_tokens_total").inc()
            admitted += 1
            if len(req.tokens) >= req.max_new_tokens:
                self._retire(slot, now)
        return admitted

    def _retire(self, slot: int, now: float) -> None:
        req = self._slot_req[slot]
        req.state = "finished"
        req.finish_s = now
        self._slot_req[slot] = None
        self.finished.append(req)
        self._reg.counter("serve_requests_total").inc(event="finished")
        if req.latency_s is not None:
            self._reg.histogram("serve_request_latency_seconds",
                                buckets=_LATENCY_BUCKETS
                                ).observe(req.latency_s)

    def _dispatch(self, live: List[int]):
        """ONE decode dispatch for the current live set. Returns
        ``(toks [K, S], counts [K, S] or None)`` as host arrays — the
        loop's one sanctioned readback. ``counts`` is None outside the
        speculative path (every fused row emits exactly one token)."""
        remaining = np.zeros(self.slots, np.int32)
        for slot in live:
            req = self._slot_req[slot]
            remaining[slot] = req.max_new_tokens - len(req.tokens)
        if self.engine.spec:
            block, self._keys, self._draft_keys = self.engine.decode_spec(
                self._last_tok, remaining, self._keys, self._draft_keys,
                self.fuse_steps)
            block = np.asarray(block)            # [K, S, G+2]
            return block[:, :, 1:], block[:, :, 0]
        if self.fuse_steps > 1:
            toks, self._keys = self.engine.decode_fused(
                self._last_tok, remaining, self._keys, self.fuse_steps)
            return np.asarray(toks), None        # [K, S]
        toks, self._keys = self.engine.decode(
            self._last_tok, self.engine.cache.cursors, self._keys)
        live_mask = np.zeros(self.slots, bool)
        live_mask[live] = True
        self.engine.cache.advance(live_mask)
        return np.asarray(toks)[None], None      # [1, S]

    def _sweep_expired(self) -> None:
        """The retirement loop's deadline check: an in-flight request
        past its deadline frees its slot NOW (shed, ``in_flight``), and
        a canceled hedge loser retires quietly — both before admission,
        so the freed slots take new work this very boundary."""
        now = self.clock()
        for slot in self._live_slots():
            req = self._slot_req[slot]
            if req.canceled:
                req.state = "canceled"
                self._slot_req[slot] = None
                self._reg.counter("serve_requests_total").inc(
                    event="canceled")
            elif req.expired(now):
                self._slot_req[slot] = None
                self._shed(req, where="in_flight", reason="deadline",
                           now=now)

    def step(self) -> bool:
        """One scheduler iteration: shed expired/canceled slots, admit
        at the fusion boundary, then one decode dispatch (1, K, or K
        speculative rounds of tokens). Returns False when nothing was
        live (the caller may idle)."""
        with tracer().span("serve.step") as sp:
            self._sweep_expired()
            self._admit()
            live = self._live_slots()
            self._reg.gauge("serve_queue_depth").set(len(self.queue))
            self._reg.gauge("serve_slot_occupancy").set(
                len(live) / self.slots)
            if not live:
                return False
            toks, counts = self._dispatch(live)
            now = self.clock()
            self.steps += 1
            self.slot_dispatches += len(live)
            sp.attrs["live"] = len(live)
            self._reg.counter("serve_decode_steps_total").inc()
            tpot = self._reg.histogram("serve_tpot_seconds",
                                       buckets=_LATENCY_BUCKETS)
            emitted_total = 0
            proposed0, accepted0 = self.spec_proposed, self.spec_accepted
            for slot in live:
                req = self._slot_req[slot]
                rem = req.max_new_tokens - len(req.tokens)
                got: List[int] = []
                if counts is None:
                    for r in range(min(toks.shape[0], rem)):
                        got.append(int(toks[r, slot]))
                else:
                    for r in range(toks.shape[0]):
                        c = int(counts[r, slot])
                        if c <= 0:
                            continue
                        take = min(c, rem - len(got))
                        got.extend(int(t) for t in toks[r, slot, :take])
                        self.spec_proposed += self.engine.spec_tokens
                        self.spec_accepted += c - 1
                        if len(got) >= rem:
                            break
                req.tokens.extend(got)
                emitted_total += len(got)
                # with fusion the K tokens land together: spread the
                # dispatch interval evenly so TPOT keeps one observation
                # per token and sums to the true wall span
                interval = (now - self._last_tok_s[slot]) / max(
                    1, len(got))
                for _ in got:
                    tpot.observe(interval)
                self._last_tok[slot] = got[-1]
                self._last_tok_s[slot] = now
                if len(req.tokens) >= req.max_new_tokens:
                    self._retire(slot, now)
            self.decode_tokens += emitted_total
            self._reg.counter("serve_tokens_total").inc(emitted_total)
            if self.engine.spec:
                if self.spec_proposed > proposed0:
                    self._reg.counter("serve_spec_proposed_total").inc(
                        self.spec_proposed - proposed0)
                if self.spec_accepted > accepted0:
                    self._reg.counter("serve_spec_accepted_total").inc(
                        self.spec_accepted - accepted0)
            # re-publish after retirement: a drained server must read 0,
            # not the pre-retirement batch width
            self._reg.gauge("serve_slot_occupancy").set(self.occupancy())
            return True

    def drain(self, max_steps: Optional[int] = None) -> int:
        """Step until queue and slots are empty; returns steps taken."""
        taken = 0
        while self.busy():
            self.step()
            taken += 1
            if max_steps is not None and taken >= max_steps:
                break
        return taken

    # ------------------------------------------------------------------
    def stats(self) -> dict:
        """Artifact-ready snapshot: compile counts, pool footprint,
        request/dispatch totals, and the fast-path headline ratios
        (dispatches/token, accepted-tokens/dispatch)."""
        pool_bytes = self.engine.cache.nbytes
        per_slot = self.engine.cache.per_slot_nbytes
        if self.engine.draft_cache is not None:
            pool_bytes += self.engine.draft_cache.nbytes
            per_slot += self.engine.draft_cache.per_slot_nbytes
        out = {
            "slots": self.slots,
            "max_len": self.max_len,
            "queue_depth": len(self.queue),
            "occupancy": self.occupancy(),
            "steps": self.steps,
            "finished": len(self.finished),
            "shed": len(self.shed),
            "shed_by_class": dict(self.shed_by_class),
            "expired_in_queue": self.expired_in_queue,
            "expired_in_flight": self.expired_in_flight,
            "fuse_steps": self.fuse_steps,
            "kv_dtype": self.engine.kv_dtype,
            "kv_pool_bytes": pool_bytes,
            # what one concurrent request costs in pool HBM — includes
            # the draft pool's share when speculative (kv_per_slot_bytes
            # * slots == kv_pool_bytes holds in every configuration)
            "kv_per_slot_bytes": per_slot,
            # TP serving: the pool shards its head axis over ``model``,
            # so the per-chip footprint is kv_pool_bytes / kv_shards
            "kv_shards": self.engine.cache.n_shard,
            "decode_dispatches": self.steps,
            "decode_tokens": self.decode_tokens,
            "dispatches_per_token": (
                round(self.steps / self.decode_tokens, 4)
                if self.decode_tokens else None),
            # tokens one dispatch yields across the whole batch (slot
            # batching amortizes on top of fusion/speculation) ...
            "accepted_tokens_per_dispatch": (
                round(self.decode_tokens / self.steps, 4)
                if self.steps else None),
            # ... vs per live slot: exactly 1.0 on the unfused
            # non-speculative path, > 1 ONLY through fusion (up to K)
            # or accepted speculation (up to K*(spec_tokens+1)) — the
            # isolated fast-path signal
            "tokens_per_slot_dispatch": (
                round(self.decode_tokens / self.slot_dispatches, 4)
                if self.slot_dispatches else None),
            "speculative": self.engine.spec,
            "compiles": self.engine.compile_counts(),
        }
        if self.engine.spec:
            out["spec_tokens"] = self.engine.spec_tokens
            out["spec_proposed"] = self.spec_proposed
            out["spec_accepted"] = self.spec_accepted
            out["spec_accept_rate"] = (
                round(self.spec_accepted / self.spec_proposed, 4)
                if self.spec_proposed else None)
        return out
