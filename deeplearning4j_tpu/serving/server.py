"""DecodeServer: continuous batching over the slot pool.

The online counterpart of the batch-oriented eval path (PR 2): a
persistent server object that compiles its program set once, keeps all
state device-resident (TensorFlow-paper serving/training split), and
multiplexes S concurrent requests through ONE jitted decode step.

The loop, per ``step()``:

1. **admit** — pop queued requests into free slots; each admission runs
   the bucket-compiled prefill (``serve.prefill`` span), records TTFT,
   and may retire immediately when ``max_new_tokens == 1``.
2. **decode** — if any slot is live, run the batched decode program
   once; every live slot appends a token (TPOT per slot), finished
   requests retire and free their slots.

The host sees one [S] token readback per step — that is the decode
loop's entire host/device chatter, and it is also the synchronization
point the per-request results come from. Everything else (queue, slot
table, cursors) is host bookkeeping the scheduler needs anyway.

Observability: queue depth / occupancy gauges, token + step counters,
TTFT/TPOT/latency histograms (``monitor/registry``), ``serve.step`` and
``serve.prefill`` spans (``monitor/trace`` — forwarded to the flight
recorder when one is live, like every span).
"""

from __future__ import annotations

import time
from typing import List, Optional, Sequence

import numpy as np

from deeplearning4j_tpu.monitor import metrics, tracer
from deeplearning4j_tpu.serving.engine import DecodeEngine
from deeplearning4j_tpu.serving.scheduler import (
    RequestQueue, ServeRequest, serve_max_queue, serve_slots)

__all__ = ["DecodeServer"]

# histogram buckets tuned for online latency (the default registry
# ladder tops out too coarse below 10 ms)
_LATENCY_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
                    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0,
                    float("inf"))


class DecodeServer:
    """Slot-batched online decode server for a :class:`TransformerLM`."""

    def __init__(self, model, *, slots: Optional[int] = None,
                 max_queue: Optional[int] = None,
                 max_len: Optional[int] = None,
                 temperature: float = 0.0, top_k: Optional[int] = None,
                 buckets: Optional[Sequence[int]] = None,
                 clock=time.monotonic):
        self.engine = DecodeEngine(
            model, slots if slots is not None else serve_slots(),
            max_len=max_len, temperature=temperature, top_k=top_k,
            buckets=buckets)
        self.model = model
        self.slots = self.engine.slots
        self.max_len = self.engine.max_len
        self.queue = RequestQueue(
            max_queue if max_queue is not None else serve_max_queue())
        self.clock = clock
        self._slot_req: List[Optional[ServeRequest]] = [None] * self.slots
        self._last_tok = np.zeros(self.slots, np.int32)
        self._last_tok_s = np.zeros(self.slots, np.float64)
        self._keys = self._zero_keys()
        self.finished: List[ServeRequest] = []
        self.steps = 0
        self._reg = metrics()

    def _zero_keys(self):
        import jax
        import jax.numpy as jnp

        return jnp.zeros((self.slots,) + jax.random.PRNGKey(0).shape,
                         jax.random.PRNGKey(0).dtype)

    # ------------------------------------------------------------------
    # submission
    # ------------------------------------------------------------------
    def submit(self, prompt, max_new_tokens: int, *,
               seed: int = 0) -> ServeRequest:
        """Enqueue one request. Validates against the slot capacity the
        way ``generate`` validates against its cache size; raises
        :class:`~.scheduler.ServeQueueFull` at the queue bound."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.shape[0] < 1:
            raise ValueError("prompt must hold at least one token")
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        total = int(prompt.shape[0]) + max_new_tokens
        if total > self.max_len:
            raise ValueError(
                f"prompt_len + max_new_tokens = {total} exceeds the "
                f"server's slot capacity max_len={self.max_len}")
        req = ServeRequest(prompt=prompt, max_new_tokens=max_new_tokens,
                           seed=seed)
        req.submit_s = self.clock()
        try:
            self.queue.push(req)
        except Exception:
            self._reg.counter("serve_requests_total").inc(event="rejected")
            raise
        self._reg.counter("serve_requests_total").inc(event="submitted")
        self._reg.gauge("serve_queue_depth").set(len(self.queue))
        return req

    # ------------------------------------------------------------------
    # the serve loop
    # ------------------------------------------------------------------
    def _free_slots(self) -> List[int]:
        return [s for s, r in enumerate(self._slot_req) if r is None]

    def _live_slots(self) -> List[int]:
        return [s for s, r in enumerate(self._slot_req) if r is not None]

    def occupancy(self) -> float:
        return len(self._live_slots()) / self.slots

    def busy(self) -> bool:
        return bool(self._live_slots()) or len(self.queue) > 0

    def _admit(self) -> int:
        import jax

        admitted = 0
        for slot in self._free_slots():
            req = self.queue.pop()
            if req is None:
                break
            with tracer().span("serve.prefill", request=req.id,
                               slot=slot,
                               prompt_len=int(req.prompt.shape[0])):
                key = jax.random.PRNGKey(req.seed)
                tok, key = self.engine.prefill(req.prompt, slot, key)
                tok = int(tok)
            now = self.clock()
            req.state = "running"
            req.slot = slot
            req.first_token_s = now
            req.tokens.append(tok)
            self._slot_req[slot] = req
            self._last_tok[slot] = tok
            self._last_tok_s[slot] = now
            self._keys = self._keys.at[slot].set(key)
            if req.ttft_s is not None:
                self._reg.histogram("serve_ttft_seconds",
                                    buckets=_LATENCY_BUCKETS
                                    ).observe(req.ttft_s)
            self._reg.counter("serve_tokens_total").inc()
            admitted += 1
            if len(req.tokens) >= req.max_new_tokens:
                self._retire(slot, now)
        return admitted

    def _retire(self, slot: int, now: float) -> None:
        req = self._slot_req[slot]
        req.state = "finished"
        req.finish_s = now
        self._slot_req[slot] = None
        self.finished.append(req)
        self._reg.counter("serve_requests_total").inc(event="finished")
        if req.latency_s is not None:
            self._reg.histogram("serve_request_latency_seconds",
                                buckets=_LATENCY_BUCKETS
                                ).observe(req.latency_s)

    def step(self) -> bool:
        """One scheduler iteration: admit, then one batched decode step.
        Returns False when nothing was live (the caller may idle)."""
        with tracer().span("serve.step") as sp:
            self._admit()
            live = self._live_slots()
            self._reg.gauge("serve_queue_depth").set(len(self.queue))
            self._reg.gauge("serve_slot_occupancy").set(
                len(live) / self.slots)
            if not live:
                return False
            toks, self._keys = self.engine.decode(
                self._last_tok, self.engine.cache.cursors, self._keys)
            toks = np.asarray(toks)
            now = self.clock()
            self.steps += 1
            sp.attrs["live"] = len(live)
            self._reg.counter("serve_decode_steps_total").inc()
            self._reg.counter("serve_tokens_total").inc(len(live))
            tpot = self._reg.histogram("serve_tpot_seconds",
                                       buckets=_LATENCY_BUCKETS)
            for slot in live:
                req = self._slot_req[slot]
                req.tokens.append(int(toks[slot]))
                self.engine.cache.cursors[slot] += 1
                tpot.observe(now - self._last_tok_s[slot])
                self._last_tok[slot] = toks[slot]
                self._last_tok_s[slot] = now
                if len(req.tokens) >= req.max_new_tokens:
                    self._retire(slot, now)
            # re-publish after retirement: a drained server must read 0,
            # not the pre-retirement batch width
            self._reg.gauge("serve_slot_occupancy").set(self.occupancy())
            return True

    def drain(self, max_steps: Optional[int] = None) -> int:
        """Step until queue and slots are empty; returns steps taken."""
        taken = 0
        while self.busy():
            self.step()
            taken += 1
            if max_steps is not None and taken >= max_steps:
                break
        return taken

    # ------------------------------------------------------------------
    def stats(self) -> dict:
        """Artifact-ready snapshot: compile counts, pool footprint,
        request/step totals."""
        return {
            "slots": self.slots,
            "max_len": self.max_len,
            "queue_depth": len(self.queue),
            "occupancy": self.occupancy(),
            "steps": self.steps,
            "finished": len(self.finished),
            "kv_pool_bytes": self.engine.cache.nbytes,
            "compiles": self.engine.compile_counts(),
        }
