"""Serve fleet: multi-replica routing, failover, prefill/decode split.

PRs 10-11 built the single-replica online engine (slot-batched KV pool,
continuous batching, fused K-step decode, speculative decoding); this
package scales it OUT — ROADMAP item 3's fleet phase, the TensorFlow-
paper serving/training split (arXiv 1605.08695) taken to fleet scale on
the cluster primitives that already exist (``parallel/statetracker``,
PR-9 heartbeat metric payloads, the master-tick eviction pattern):

- :mod:`~deeplearning4j_tpu.serving.fleet.replica` —
  :class:`ServeReplica`: a ``DecodeServer`` in a worker loop that
  registers with the ``StateTracker`` and posts per-beat serve payloads
  ``{occupancy, queue_depth, free_slots, ttft_p50, tpot_s,
  tokens_per_sec}``.
- :mod:`~deeplearning4j_tpu.serving.fleet.router` —
  :class:`FleetRouter`: least-loaded admission (free-slots-first,
  TTFT-aware tiebreak), bounded per-replica queues with overflow spill,
  sticky affinity, and failover requeue with the prompt re-prefilled
  (greedy streams keep their emitted prefix; completed output is
  token-identical to an unfailed run).
- :mod:`~deeplearning4j_tpu.serving.fleet.controller` —
  :class:`FleetController`: the master tick — aggregate fleet gauges,
  flag TPOT stragglers (shared outlier rule with the training master),
  evict silent/crashed replicas with evidence-logged decisions, requeue
  their in-flight requests onto survivors.
- :mod:`~deeplearning4j_tpu.serving.fleet.handoff` — the
  prefill/decode split (``DL4J_SERVE_ROLE``): prefill replicas export
  ``(kv_slab, cursor, rng_key)`` packages a decode replica installs
  into a free slot (``_slot_export_impl``/``_slot_import_impl`` are
  ``@traced`` hot roots).
- :mod:`~deeplearning4j_tpu.serving.fleet.driver` —
  :class:`FleetLoadDriver`: the bench's per-replica virtual-clock
  replay (real measured dispatch costs, chip-per-replica timelines).

See ``docs/inference.md`` §Serve fleet for the architecture, routing
policy, and failover contract; ``docs/observability.md`` for the
fleet-serve metric/span catalog.
"""

from deeplearning4j_tpu.serving.fleet.controller import (  # noqa: F401
    FleetController,
)
from deeplearning4j_tpu.serving.fleet.driver import (  # noqa: F401
    FleetLoadDriver,
)
from deeplearning4j_tpu.serving.fleet.handoff import (  # noqa: F401
    SlotHandoff,
    export_slot,
    install_slot,
    make_install,
)
from deeplearning4j_tpu.serving.fleet.replica import (  # noqa: F401
    ServeReplica,
)
from deeplearning4j_tpu.serving.fleet.router import (  # noqa: F401
    FleetRequest,
    FleetRouter,
    FleetSaturated,
)

__all__ = [
    "FleetController", "FleetLoadDriver", "FleetRequest", "FleetRouter",
    "FleetSaturated", "ServeReplica", "SlotHandoff", "export_slot",
    "install_slot", "make_install",
]
