"""Slot handoff: move one request's KV state between replicas.

The prefill/decode split (the stretch of ROADMAP item 3's fleet phase,
after the DistBelief/TensorFlow serving-split lineage) separates the two
phases with opposite hardware profiles: prefill is one big compute-bound
forward over the whole prompt, decode is a long memory-bound stream of
single-token steps. A ``prefill`` replica computes the prompt's K/V into
a scratch slot, exports the slot as a host-resident
:class:`SlotHandoff` — ``(kv_slab, cursor, rng_key)`` plus the first
sampled token — and a ``decode`` replica installs it into a free slot of
its own pool and streams the rest.

Device programs: ``_slot_export_impl`` / ``_slot_import_impl`` are
``@traced`` hot roots (``HOT_PATH_REGISTRY``) compiled once per engine
through the engine's bounded program cache — the export's host readback
(the slab leaves the device by definition of a handoff) happens OUTSIDE
the traced bodies, in :func:`export_slot`, where dl4j-lint's host-sync
rule can see it is not on the per-token path: handoffs happen once per
request, prefill-side, never inside the decode loop.

Numerics: the installed slab is bit-identical to what a local prefill of
the same prompt would have written (same program, same math; the export/
import round trip is a pure gather/scatter), so a handed-off greedy
stream is token-identical to a locally-served one — asserted in
tests/test_serving_fleet.py.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict

import numpy as np

from deeplearning4j_tpu.analysis.annotations import traced

__all__ = ["SlotHandoff", "export_slot", "export_live_slot",
           "install_slot", "make_install"]


@traced
def _slot_export_impl(state, slot):
    """Gather one slot's K/V (+ int8 scale rows) out of the pool:
    ``[L, S, T, Hkv, Dh]`` pools yield ``[L, T, Hkv, Dh]`` slabs,
    ``[L, S, Hkv]`` scale sidecars yield ``[L, Hkv]`` rows. ``slot`` is
    traced — one compiled program per engine, any slot."""
    import jax.numpy as jnp

    return {name: jnp.take(pool, slot, axis=1)
            for name, pool in state.items()}


@traced
def _slot_import_impl(state, slabs, slot):
    """Scatter a handed-off slab back into pool slot ``slot`` (the
    inverse of ``_slot_export_impl``); every other slot's K/V carries
    unchanged (the pool buffers are donated)."""
    from jax import lax

    out = {}
    for name, pool in state.items():
        slab = slabs[name][:, None]          # re-insert the slot axis
        start = (0, slot) + (0,) * (pool.ndim - 2)
        out[name] = lax.dynamic_update_slice(
            pool, slab.astype(pool.dtype), start)
    return out


@dataclass
class SlotHandoff:
    """One prefilled request's portable decode state: the host-side
    ``(kv_slab, cursor, rng_key)`` package a prefill replica ships to a
    decode replica's free slot, plus the first token (sampled at
    prefill, so TTFT is stamped prefill-side) and the compatibility
    fields the install validates against the target pool."""

    slabs: Dict[str, np.ndarray]   # k/v [L, T, Hkv, Dh] (+ *_scale [L, Hkv])
    # next write position: prompt_len for a prefill handoff,
    # prompt_len + emitted for a drain-time mid-stream migration
    cursor: int
    key: np.ndarray                # per-slot RNG key, mid-chain
    # the last token fed back into decode: the prefill's first sampled
    # token, or — mid-stream — the newest token the source emitted
    first_token: int
    kv_dtype: str
    max_len: int

    @property
    def nbytes(self) -> int:
        return int(sum(s.nbytes for s in self.slabs.values()))


def export_slot(engine, slot: int) -> Dict[str, np.ndarray]:
    """Pull one slot's pool state to host numpy (the handoff's wire
    format). The readback is sanctioned here — once per request at the
    prefill/decode boundary, never per token."""
    import jax
    import jax.numpy as jnp

    run = engine._program(
        ("handoff_export", engine.slots),
        lambda: jax.jit(_slot_export_impl))
    device = run(engine.cache.state, jnp.asarray(slot, jnp.int32))
    return {name: np.asarray(v) for name, v in device.items()}


def install_slot(engine, slot: int, handoff: SlotHandoff):
    """Land a handoff into ``slot`` of ``engine``'s pool and start the
    cursor; returns the device RNG key to continue the stream with.
    Validates pool compatibility — a silent dtype or capacity mismatch
    would decode garbage with no error."""
    import jax
    import jax.numpy as jnp

    if handoff.kv_dtype != engine.kv_dtype:
        raise ValueError(
            f"handoff kv_dtype={handoff.kv_dtype!r} != target pool "
            f"{engine.kv_dtype!r}")
    if handoff.max_len != engine.max_len:
        raise ValueError(
            f"handoff max_len={handoff.max_len} != target pool "
            f"max_len={engine.max_len}")
    run = engine._program(
        ("handoff_import", engine.slots),
        lambda: jax.jit(_slot_import_impl, donate_argnums=(0,)))
    state = run(engine.cache.state,
                {k: jnp.asarray(v) for k, v in handoff.slabs.items()},
                jnp.asarray(slot, jnp.int32))
    engine.cache.install(state)
    engine.cache.set_cursor(slot, handoff.cursor)
    return jnp.asarray(handoff.key)


def export_live_slot(server, slot: int) -> SlotHandoff:
    """Package a RUNNING slot's full decode state for migration — the
    graceful-drain counterpart of the prefill-side handoff. The slab
    covers every token decoded so far (cursor = prompt_len + emitted),
    the RNG key is the slot's mid-chain key, and ``first_token`` is the
    newest emitted token — installing this on a survivor continues the
    stream with ZERO recompute and zero lost tokens, where failover
    would re-prefill prompt + emitted from scratch."""
    engine = server.engine
    return SlotHandoff(
        slabs=export_slot(engine, slot),
        cursor=engine.cursor_of(slot),
        key=np.asarray(server._keys[slot]),
        first_token=int(server._last_tok[slot]),
        kv_dtype=engine.kv_dtype,
        max_len=engine.max_len)


def make_install(handoff: SlotHandoff):
    """The ``install(engine, slot) -> (last_token, key)`` callable
    ``DecodeServer.admit_external`` runs at the step boundary that
    claims a free slot."""

    def install(engine, slot):
        key = install_slot(engine, slot, handoff)
        return handoff.first_token, key

    return install
