"""Fleet load driver: replay an arrival schedule on per-replica clocks.

The bench problem: an M-replica fleet deploys as M chips, but the bench
host has ONE backend — in-process replicas time-slice it, so measuring
fleet throughput on a single wall clock would show zero scaling no
matter how good the routing is (the host can only run one dispatch at a
time). The honest fix is the one discrete-event simulation has always
used: **book real measured costs on virtual per-replica timelines**.
Every replica step runs for real (its wall duration is measured), but
the duration lands on that replica's own clock — exactly how M chips
would overlap — and every request timestamp (submit/TTFT/finish) is
read off the virtual timeline. What the scaling number then measures is
the fleet layer itself: routing balance, queue spill, admission
batching, failover cost. What it deliberately does NOT measure is
host parallelism the bench machine doesn't have.

The same driver measures failover: kill a replica at a virtual time,
let the controller evict + requeue, and read the recovery off the
survivors' timelines.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional

from deeplearning4j_tpu.serving.fleet.controller import FleetController
from deeplearning4j_tpu.serving.fleet.replica import ServeReplica
from deeplearning4j_tpu.serving.fleet.router import FleetRouter
from deeplearning4j_tpu.serving.loadgen import Arrival, LoadReport

__all__ = ["FleetLoadDriver"]


def _wall_step_timer(replica: ServeReplica) -> float:
    """Default step cost: the step's real wall duration."""
    t0 = time.perf_counter()
    replica.step_once()
    return time.perf_counter() - t0


class FleetLoadDriver:
    """Replays one :func:`poisson_schedule` against a routed fleet.

    ``step_timer(replica) -> seconds`` runs ONE replica step and
    returns its cost — injectable so tests can pin deterministic costs
    (the default measures real wall time). The driver owns every
    clock: it points each server/replica clock at that replica's
    virtual timeline and the router clock at the event frontier, so
    all recorded latencies are virtual-timeline consistent."""

    def __init__(self, router: FleetRouter,
                 controller: Optional[FleetController] = None, *,
                 step_timer=_wall_step_timer):
        self.router = router
        self.controller = controller
        self.step_timer = step_timer
        self._now = 0.0
        self.vt: Dict[str, float] = {
            r.replica_id: 0.0 for r in router.replicas}
        self.dispatch_log: List[tuple] = []   # (replica, t_start, cost)
        router.clock = lambda: self._now
        for r in router.replicas:
            rid = r.replica_id
            clock = (lambda rid=rid: self.vt[rid])
            r.clock = clock
            r.server.clock = clock
            # the replica's rate window was stamped from the wall clock
            # at construction; re-base it on the virtual timeline or
            # `elapsed` stays negative and tokens_per_sec reads 0
            r._rate_t0 = 0.0
            r._rate_tokens0 = r.server.decode_tokens
        if controller is not None:
            controller.clock = lambda: self._now

    # ------------------------------------------------------------------
    def busy_seconds(self) -> Dict[str, float]:
        """Per-replica time spent dispatching (the balance evidence).
        Seeded with EVERY replica at 0.0 — a replica routing starved
        entirely must show up as the imbalance it is, not vanish from
        the evidence."""
        out: Dict[str, float] = {
            r.replica_id: 0.0 for r in self.router.replicas}
        for rid, _, cost in self.dispatch_log:
            out[rid] += cost
        return out

    def run(self, schedule: List[Arrival], *,
            kill_at_s: Optional[float] = None,
            kill_replica: Optional[str] = None,
            drain_at_s: Optional[float] = None,
            drain_replica: Optional[str] = None,
            max_events: int = 2_000_000) -> LoadReport:
        """Drive the schedule to completion. With ``kill_at_s`` /
        ``kill_replica`` set, that replica dies at the first event past
        the virtual time and the controller (required then) evicts +
        fails over; ``drain_at_s`` / ``drain_replica`` instead retire
        the replica GRACEFULLY at that instant (quiesce + KV-slab
        migration, zero recompute). The report still covers every
        request. Returns the standard :class:`LoadReport` read off the
        virtual timelines."""
        for t_s, rid, what in ((kill_at_s, kill_replica, "kill"),
                               (drain_at_s, drain_replica, "drain")):
            if t_s is None:
                continue
            if self.controller is None:
                raise ValueError(f"{what}_at_s needs a controller")
            if rid not in self.router._by_id:
                raise ValueError(
                    f"{what}_replica={rid!r} is not in the fleet "
                    f"({sorted(self.router._by_id)})")
        report = LoadReport()
        i = 0
        killed = drained = False
        self.failover_done_s: Optional[float] = None
        self.kill_time_s: Optional[float] = None
        self.drain_time_s: Optional[float] = None
        self.drain_summary: Optional[dict] = None
        failover_victims: List = []
        for _ in range(max_events):
            alive = [r for r in self.router.replicas if r.alive]
            if self.router._pending:
                # parked failovers retry whenever a survivor may have
                # freed up (the controller tick does this in real-time
                # fleets; the driver IS the tick here) — placements
                # resume on the current frontier, not in a stale past
                if self.router.retry_pending():
                    for rr in alive:
                        if rr.busy():
                            self.vt[rr.replica_id] = max(
                                self.vt[rr.replica_id], self._now)
            busy = [r for r in alive if r.busy()]
            pending = self.router._pending
            if i >= len(schedule) and not busy and not pending:
                break
            # ---- next event: an arrival or a replica coming free
            events = []
            if i < len(schedule):
                events.append((schedule[i].arrival_s, 0, "arrive", None))
            for r in busy:
                events.append((self.vt[r.replica_id], 1, "step", r))
            if not events:
                break  # pending failovers with nowhere to go
            t, _, kind, r = min(events, key=lambda e: (e[0], e[1]))
            self._now = max(self._now, t)
            # ---- scheduled kill fires at the first event past its time
            if (not killed and kill_at_s is not None
                    and self._now >= kill_at_s):
                killed = True
                self.kill_time_s = self._now
                victim = self.router._by_id[kill_replica]
                failover_victims = [
                    fr for fr in self.router.requests
                    if fr.replica_id == kill_replica and not fr.finished]
                # evict() kills the victim itself (loop + beats down)
                self.controller.evict(
                    kill_replica, reason="bench-kill",
                    last_metrics=victim.heartbeat_payload())
                # requeued work starts no earlier than the kill instant
                for rr in self.router.replicas:
                    if rr.alive and rr.busy():
                        self.vt[rr.replica_id] = max(
                            self.vt[rr.replica_id], self._now)
                continue
            # ---- scheduled drain: graceful retire, mid-storm
            if (not drained and drain_at_s is not None
                    and self._now >= drain_at_s):
                drained = True
                self.drain_time_s = self._now
                # migrated streams continue no earlier than the later
                # of the drain instant and the victim's own frontier
                # (its already-booked steps produced those tokens)
                t_resume = max(self._now, self.vt[drain_replica])
                self.drain_summary = self.controller.drain(
                    drain_replica, reason="bench-drain")
                for rr in self.router.replicas:
                    if rr.alive and rr.busy():
                        self.vt[rr.replica_id] = max(
                            self.vt[rr.replica_id], t_resume)
                continue
            # hedging rides the driver loop the way it rides the
            # controller tick in real-time fleets
            if self.router.maybe_hedge():
                for rr in self.router.replicas:
                    if rr.alive and rr.busy():
                        self.vt[rr.replica_id] = max(
                            self.vt[rr.replica_id], self._now)
            if kind == "arrive":
                a = schedule[i]
                i += 1
                deadline = (None if a.deadline_s is None
                            else self._now + a.deadline_s)
                freq = self.router.try_submit(
                    a.prompt, a.max_new_tokens, seed=a.seed,
                    deadline_s=deadline, criticality=a.criticality)
                if freq is None:
                    report.rejected += 1
                    report.drop_times_s.append(self._now)
                else:
                    report.submitted += 1
                    report.submitted_by_class[a.criticality] = (
                        report.submitted_by_class.get(a.criticality, 0)
                        + 1)
                # whoever just went from idle to busy resumes its
                # timeline here, not in its past
                for rr in self.router.replicas:
                    if rr.alive and rr.busy():
                        self.vt[rr.replica_id] = max(
                            self.vt[rr.replica_id], self._now)
                continue
            # ---- one replica step, booked on its own timeline
            rid = r.replica_id
            was_busy = {rr.replica_id for rr in self.router.replicas
                        if rr.busy()}
            cost = self.step_timer(r)
            self.dispatch_log.append((rid, self.vt[rid], cost))
            self.vt[rid] += cost
            # work this step handed elsewhere (a prefill replica's slab
            # landing on a decode replica) cannot start before it was
            # produced: an idle receiver resumes its timeline here
            for rr in self.router.replicas:
                if (rr is not r and rr.alive and rr.busy()
                        and rr.replica_id not in was_busy):
                    self.vt[rr.replica_id] = max(
                        self.vt[rr.replica_id], self.vt[rid])
            if killed and self.failover_done_s is None \
                    and failover_victims \
                    and all(fr.finished for fr in failover_victims):
                self.failover_done_s = self.vt[rid]
        # ---- fold the fleet's request ledger into the report
        report.wall_s = max([self._now] + list(self.vt.values()))
        for fr in self.router.requests:
            report.placements += fr.attempts
            if fr.state == "shed":
                # admitted then shed (deadline or displacement): joins
                # the drop series at the instant the decision was made
                report.shed += 1
                report.shed_by_class[fr.criticality] = (
                    report.shed_by_class.get(fr.criticality, 0) + 1)
                if fr.finish_s is not None:
                    report.drop_times_s.append(fr.finish_s)
                continue
            if not fr.finished:
                continue
            report.finished += 1
            report.finished_by_class[fr.criticality] = (
                report.finished_by_class.get(fr.criticality, 0) + 1)
            report.tokens += len(fr.tokens)
            if fr.latency_s is not None:
                report.latencies_s.append(fr.latency_s)
            if fr.ttft_s is not None:
                report.ttfts_s.append(fr.ttft_s)
                report.ttfts_by_class.setdefault(
                    fr.criticality, []).append(fr.ttft_s)
            if fr.first_token_s is not None and fr.finish_s is not None \
                    and len(fr.tokens) > 1:
                report.tpots_s.append(
                    (fr.finish_s - fr.first_token_s)
                    / (len(fr.tokens) - 1))
        report.hedges = len(self.router.hedge_log)
        for r in self.router.replicas:
            s = r.server.stats()
            report.expired_in_queue += s.get("expired_in_queue", 0)
            report.expired_in_flight += s.get("expired_in_flight", 0)
        return report
