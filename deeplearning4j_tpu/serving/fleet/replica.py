"""ServeReplica: one DecodeServer as a fleet worker.

The serving analogue of ``DistributedTrainer``'s worker loop: a replica
wraps a :class:`~deeplearning4j_tpu.serving.server.DecodeServer` in a
poll loop, registers with the cluster's :class:`StateTracker` through a
:class:`HeartbeatMonitor`, and posts the compact serve payload the
router and controller consume on every beat::

    {occupancy, queue_depth, free_slots, ttft_p50, tpot_s,
     tokens_per_sec, role}

Beats ride the PR-9 ``heartbeat(metrics=)`` channel, so the fleet view
works over either tracker backend (in-memory for in-process fleets,
file-backed across processes/hosts) and a dead replica goes silent
exactly like a dead training worker — the controller's eviction logic
is the same silence-past-timeout rule with the same evidence logging.

Roles (``DL4J_SERVE_ROLE``): ``mixed`` replicas run the full request
lifecycle; ``prefill`` replicas only drain prompt-prefill jobs into
:class:`~.handoff.SlotHandoff` packages for the router to place;
``decode`` replicas only accept handoffs + continue streams. The loop
body (:meth:`step_once`) is shared by the real-time thread and the
bench's virtual-clock driver, and declares the ``serve.replica.step``
fault site (plus a per-replica ``serve.replica.step.<id>`` site) so
chaos tests can kill or wedge one specific replica mid-stream.
"""

from __future__ import annotations

import statistics
import threading
import time
from collections import deque
from typing import Deque, Optional

import numpy as np

from deeplearning4j_tpu.monitor import metrics, tracer
from deeplearning4j_tpu.resilience import faults
from deeplearning4j_tpu.serving.fleet.handoff import SlotHandoff, export_slot
from deeplearning4j_tpu.serving.scheduler import SERVE_ROLES, serve_role
from deeplearning4j_tpu.serving.server import _LATENCY_BUCKETS, DecodeServer

__all__ = ["ServeReplica"]

#: scratch slot a prefill-role replica reuses for every prompt: it never
#: decodes, so the slot is always free again the moment the slab exports
_PREFILL_SCRATCH_SLOT = 0


class ServeReplica:
    """One decode server + its worker-loop/heartbeat/handoff plumbing."""

    def __init__(self, replica_id: str, model, *,
                 tracker=None, role: Optional[str] = None,
                 heartbeat_interval_s: float = 1.0,
                 poll_s: float = 0.002,
                 clock=time.monotonic, server: Optional[DecodeServer] = None,
                 lease=None, **server_kw):
        self.replica_id = str(replica_id)
        self.role = role if role is not None else serve_role()
        if self.role not in SERVE_ROLES:
            raise ValueError(
                f"role={self.role!r} must be one of {SERVE_ROLES}")
        self.server = server if server is not None else DecodeServer(
            model, clock=clock, **server_kw)
        self.tracker = tracker
        self.heartbeat_interval_s = heartbeat_interval_s
        self.poll_s = poll_s
        self.clock = clock
        # grant lease around this replica's backend acquisition (program
        # warm-up / device claim): a wedged acquisition re-acquires under
        # the lease's bounded watchdog instead of hanging the replica
        # thread; exhaustion marks the replica dead so the controller
        # evicts it and fails its requests over — the fleet loses one
        # member, never the run. None = acquire-free start (default).
        self.lease = lease
        self.monitor = None
        self.dead = False
        self.dead_reason: Optional[str] = None
        # planned removal (graceful drain): alive goes False without
        # the dead flag — drained is not crashed, and the controller's
        # crash-evict pass must not treat it as a corpse
        self.retired = False
        self._stop: Optional[threading.Event] = None
        self._thread: Optional[threading.Thread] = None
        # prefill-role work: (FleetRequest, on_handoff) jobs the router
        # assigned; on_handoff(freq, SlotHandoff) places the result
        self._prefill_jobs: Deque = deque()
        self._jobs_lock = threading.Lock()
        # rolling quality-of-service samples for the heartbeat payload
        self._ttfts: Deque[float] = deque(maxlen=128)
        self._tpots: Deque[float] = deque(maxlen=128)
        self._ttft_seen: set = set()
        self._finished_seen = 0
        self._rate_t0 = clock()
        self._rate_tokens0 = 0
        self._rate = 0.0
        self.prefills_done = 0

    # ------------------------------------------------------------------
    # load / QoS view (the router reads these directly in-process; the
    # heartbeat payload carries the same numbers across processes)
    # ------------------------------------------------------------------
    @property
    def alive(self) -> bool:
        return not self.dead and not self.retired

    def ttft_p50(self) -> Optional[float]:
        return statistics.median(self._ttfts) if self._ttfts else None

    def tpot_p50(self) -> Optional[float]:
        return statistics.median(self._tpots) if self._tpots else None

    def queue_depth(self) -> int:
        with self._jobs_lock:
            jobs = len(self._prefill_jobs)
        return len(self.server.queue) + jobs

    def busy(self) -> bool:
        with self._jobs_lock:
            jobs = bool(self._prefill_jobs)
        return jobs or self.server.busy()

    def heartbeat_payload(self) -> dict:
        """The compact fleet-view payload each beat carries."""
        s = self.server
        ttft = self.ttft_p50()
        tpot = self.tpot_p50()
        return {
            "role": self.role,
            "occupancy": round(s.occupancy(), 4),
            "queue_depth": self.queue_depth(),
            "free_slots": s.free_slot_count(),
            "ttft_p50": None if ttft is None else round(ttft, 6),
            "tpot_s": None if tpot is None else round(tpot, 6),
            "tokens_per_sec": round(self._rate, 2),
        }

    # ------------------------------------------------------------------
    # the worker loop body (shared by the thread and the virtual driver)
    # ------------------------------------------------------------------
    def step_once(self) -> bool:
        """One loop iteration: drain one prefill job (prefill role) or
        run one server step (decode-capable roles), then harvest QoS
        samples. Returns False when nothing progressed (caller may
        idle). Declares the chaos fault sites."""
        faults.fault_point("serve.replica.step")
        faults.fault_point(f"serve.replica.step.{self.replica_id}")
        progressed = False
        # prefill jobs are a prefill-ROLE surface only: _do_prefill
        # writes into the fixed scratch slot, which on a decode-capable
        # replica could hold a live stream mid-decode
        if self.role == "prefill":
            with self._jobs_lock:
                job = (self._prefill_jobs.popleft()
                       if self._prefill_jobs else None)
            if job is not None:
                self._do_prefill(*job)
                progressed = True
        else:
            progressed = self.server.step()
        self._harvest()
        return progressed

    def _do_prefill(self, freq, on_handoff) -> None:
        """Run one prompt prefill into the scratch slot, export the
        slab, stamp TTFT, and hand the package to the router's
        placement callback."""
        import jax

        engine = self.server.engine
        req = freq.inner
        with tracer().span("serve.handoff.prefill", request=req.id,
                           replica=self.replica_id,
                           prompt_len=int(req.prompt.shape[0])):
            key = jax.random.PRNGKey(req.seed)
            tok, key = engine.prefill(req.prompt, _PREFILL_SCRATCH_SLOT,
                                      key)
            slabs = export_slot(engine, _PREFILL_SCRATCH_SLOT)
            tok = int(tok)
        now = self.clock()
        req.state = "running"
        req.first_token_s = now
        req.tokens.append(tok)
        self.prefills_done += 1
        if req.ttft_s is not None:
            self._ttfts.append(req.ttft_s)
            # same histogram (and bucket ladder) the single-server
            # admission path feeds — TTFT is stamped wherever the first
            # token is sampled
            metrics().histogram("serve_ttft_seconds",
                                buckets=_LATENCY_BUCKETS
                                ).observe(req.ttft_s)
        metrics().counter("serve_tokens_total").inc()
        handoff = SlotHandoff(
            slabs=slabs, cursor=int(req.prompt.shape[0]),
            key=np.asarray(key), first_token=tok,
            kv_dtype=engine.kv_dtype, max_len=engine.max_len)
        on_handoff(freq, handoff)

    def enqueue_prefill(self, freq, on_handoff) -> None:
        """Router-side: assign one prefill job to this replica.
        Prefill-role only — the scratch slot a job prefills into is
        free by construction there, and could be a live stream's slot
        anywhere else."""
        if self.role != "prefill":
            raise ValueError(
                f"replica {self.replica_id} has role {self.role!r}; "
                "prefill jobs only run on role='prefill' replicas")
        with self._jobs_lock:
            self._prefill_jobs.append((freq, on_handoff))

    def _harvest(self) -> None:
        """Pull QoS samples out of the server's bookkeeping: TTFTs of
        newly-first-tokened requests, per-token latency of newly
        finished ones, and the rolling token rate."""
        s = self.server
        for req in s._slot_req:
            # handed-off requests' TTFT belongs to the prefill replica
            # that stamped it — re-collecting it here would attribute
            # another replica's latency to this one (and double-count
            # it fleet-wide)
            if req is not None and req.ttft_s is not None \
                    and not req.handoff \
                    and req.id not in self._ttft_seen:
                self._ttft_seen.add(req.id)
                self._ttfts.append(req.ttft_s)
        new = s.finished[self._finished_seen:]
        self._finished_seen = len(s.finished)
        for req in new:
            if (req.id not in self._ttft_seen and not req.handoff
                    and req.ttft_s is not None):
                self._ttft_seen.add(req.id)
                self._ttfts.append(req.ttft_s)
            self._ttft_seen.discard(req.id)
            if (req.first_token_s is not None and req.finish_s is not None
                    and len(req.tokens) > 1):
                self._tpots.append((req.finish_s - req.first_token_s)
                                   / (len(req.tokens) - 1))
        now = self.clock()
        elapsed = now - self._rate_t0
        if elapsed >= 1.0:
            self._rate = (s.decode_tokens - self._rate_tokens0) / elapsed
            self._rate_t0 = now
            self._rate_tokens0 = s.decode_tokens

    # ------------------------------------------------------------------
    # real-time lifecycle (threads; the bench's virtual driver calls
    # step_once directly instead)
    # ------------------------------------------------------------------
    def start(self) -> "ServeReplica":
        if self._thread is not None and self._thread.is_alive():
            return self
        if self.lease is not None:
            from deeplearning4j_tpu.resilience.lease import (
                GrantWedgedError)

            try:
                self.lease.acquire()
            except GrantWedgedError as e:
                # a replica that never got its grant is a dead replica:
                # the controller's crash path evicts it with the lease's
                # evidence and fails its (zero) requests over — the
                # fleet shrinks by one instead of wedging on it
                self._die(f"grant wedged: {e}")
                return self
        if self.tracker is not None and self.monitor is None:
            from deeplearning4j_tpu.parallel.cluster import HeartbeatMonitor

            self.monitor = HeartbeatMonitor(
                self.tracker, self.replica_id,
                interval_s=self.heartbeat_interval_s,
                payload_fn=self.heartbeat_payload).start()
        stop = threading.Event()
        self._stop = stop

        def run():
            while not stop.is_set():
                try:
                    progressed = self.step_once()
                except BaseException as e:  # noqa: BLE001 — a dying
                    # replica must look dead: stop beating (the monitor
                    # thread would otherwise keep a corpse "alive") and
                    # leave the reason for the eviction evidence
                    self._die(f"{type(e).__name__}: {e}")
                    return
                if not progressed:
                    time.sleep(self.poll_s)

        self._thread = threading.Thread(
            target=run, daemon=True, name=f"serve-{self.replica_id}")
        self._thread.start()
        return self

    def _die(self, reason: str) -> None:
        self.dead = True
        if self.dead_reason is None:  # first cause wins (a crash's
            self.dead_reason = reason  # exception beats a later evict)
        if self.monitor is not None:
            self.monitor.stop()

    def kill(self, reason: str = "killed") -> None:
        """Make this replica dead the way a crashed one is — loop
        stopped, beats stopped, dead flag up. The controller's evict
        path calls this too: a silence-evicted replica may still be
        RUNNING, and its loop must not keep decoding requests the
        survivors now own."""
        if self._stop is not None:
            self._stop.set()
        self._die(reason)
        if (self._thread is not None
                and self._thread is not threading.current_thread()):
            self._thread.join(timeout=5.0)

    def wedge(self) -> None:
        """Test/bench hook: alive-but-stuck — the loop stops making
        progress AND the beats stop, but the dead flag stays down, so
        only heartbeat-silence-past-timeout can catch it (the wedged-
        grant failure shape from BENCH_r04/r05, serve-side)."""
        if self._stop is not None:
            self._stop.set()
        if self.monitor is not None:
            self.monitor.stop()

    def retire(self) -> None:
        """Planned removal (graceful drain): clean shutdown PLUS the
        retired flag, so ``alive`` goes False — the router stops
        placing, the driver stops stepping — without the dead flag a
        crash would raise."""
        self.stop()
        self.retired = True

    def stop(self) -> None:
        """Clean shutdown (not an eviction): loop joined, beats off."""
        if self._stop is not None:
            self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        if self.monitor is not None:
            self.monitor.stop()
            self.monitor = None
