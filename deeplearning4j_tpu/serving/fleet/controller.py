"""FleetController: the master tick for the serve fleet.

Closes the loop the way ``DistributedTrainer``'s master tick does for
training workers, on the same PR-9 heartbeat channel:

- **aggregate** — every tick reads each replica's newest beat payload
  and publishes the fleet gauges (``fleet_serve_replicas``, per-replica
  ``fleet_serve_occupancy`` / ``fleet_serve_queue_depth`` /
  ``fleet_serve_free_slots`` / ``fleet_serve_ttft_p50_s`` /
  ``fleet_serve_tokens_per_sec``).
- **flag stragglers** — a replica whose TPOT exceeds
  ``straggler_ratio`` x the fleet median (≥3 reporting) is flagged via
  the SAME outlier rule the training master uses
  (``parallel/workrouter.update_straggler_flags``), with the evidence
  on the timeline (``serve.straggler`` event).
- **evict + requeue** — a replica silent past ``DL4J_SERVE_EVICT_S``
  (wedged: beats stopped, nobody told us why) or one whose in-process
  loop died (crashed: the dead flag is honest local knowledge) is
  evicted with the decision's evidence — silence, timeout, last
  payload — appended to ``controller.eviction_log`` exactly like the
  training master's eviction log, its per-replica gauges dropped (a
  dead replica must stop reporting as current), and its unfinished
  requests requeued onto survivors through
  :meth:`FleetRouter.failover` (``serve.failover`` span). The
  correctness contract rides on deterministic prefill: a killed
  replica's requests complete token-identical to an unfailed run.
- **drain** — :meth:`FleetController.drain` is the PLANNED way out:
  quiesce admission, stop the step loop, migrate in-flight streams to
  survivors wholesale (KV slab + cursor + RNG via the handoff path —
  zero recompute, zero lost tokens), retire the replica. Eviction is
  for corpses; drain is for maintenance.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional

from deeplearning4j_tpu.monitor import metrics, record_counter, tracer
from deeplearning4j_tpu.parallel.workrouter import update_straggler_flags
from deeplearning4j_tpu.serving.fleet.router import FleetRouter
from deeplearning4j_tpu.serving.scheduler import serve_evict_s

__all__ = ["FleetController"]

#: per-replica gauges the controller owns (published on tick, removed
#: on eviction so a dead replica stops reporting as current)
_REPLICA_GAUGES = {
    "fleet_serve_occupancy": "occupancy",
    "fleet_serve_queue_depth": "queue_depth",
    "fleet_serve_free_slots": "free_slots",
    "fleet_serve_ttft_p50_s": "ttft_p50",
    "fleet_serve_tokens_per_sec": "tokens_per_sec",
}


class FleetController:
    """Aggregate, flag, evict, requeue — one tick at a time."""

    def __init__(self, router: FleetRouter, tracker=None, *,
                 evict_timeout_s: Optional[float] = None,
                 straggler_ratio: float = 3.0,
                 clock=time.time, autopilot=None):
        self.router = router
        self.tracker = tracker
        self.evict_timeout_s = (evict_timeout_s
                                if evict_timeout_s is not None
                                else serve_evict_s())
        self.straggler_ratio = float(straggler_ratio)
        self.clock = clock
        # the goodput autopilot rides the controller tick the same way
        # it rides the training master's: DL4J_AUTOPILOT=1 builds the
        # default policy, autopilot= passes an explicit one. Its evict
        # actuator is the controller's own evidence-logged evict — the
        # audit trail shows one eviction path regardless of who decided.
        if autopilot is None:
            from deeplearning4j_tpu.resilience.autopilot import (
                GoodputAutopilot, autopilot_enabled)

            if autopilot_enabled():
                autopilot = GoodputAutopilot(
                    silence_s=self.evict_timeout_s, clock=clock)
        self.autopilot = autopilot
        if autopilot is not None:
            autopilot.bind(evict=lambda rid, d: self.evict(
                rid, reason=f"autopilot:{d.reason}",
                silent_s=d.gauges.get("silent_s"),
                last_metrics={k: v for k, v in d.gauges.items()
                              if k not in ("silent_s",
                                           "silence_timeout_s")}))
        self.stragglers: set = set()
        self.evicted: List[str] = []
        self.eviction_log: List[dict] = []
        self.drained: List[str] = []
        self.drain_log: List[dict] = []
        # tick-skip set: drained replicas join it too (retired is not
        # crashed, but neither reports as current)
        self._evicted_set: set = set()
        self._stop: Optional[threading.Event] = None
        self._thread: Optional[threading.Thread] = None
        self._reg = metrics()

    # ------------------------------------------------------------------
    def _payload(self, replica) -> Optional[dict]:
        """Newest beat payload — from the tracker when one is wired
        (the cross-process path), else straight from the in-process
        replica (same dict, no beat in between)."""
        if self.tracker is not None:
            return self.tracker.heartbeat_metrics(replica.replica_id)
        return replica.heartbeat_payload() if replica.alive else None

    def tick(self) -> Dict[str, dict]:
        """One aggregation + health pass; returns the per-replica
        payload map (tests and dashboards read it)."""
        fleet: Dict[str, dict] = {}
        now = self.clock()
        for r in self.router.replicas:
            if r.replica_id in self._evicted_set:
                continue
            m = self._payload(r)
            if m:
                fleet[r.replica_id] = m
                for gauge, key in _REPLICA_GAUGES.items():
                    if isinstance(m.get(key), (int, float)):
                        self._reg.gauge(gauge).set(float(m[key]),
                                                   replica=r.replica_id)
        tpots = {rid: float(m["tpot_s"]) for rid, m in fleet.items()
                 if isinstance(m.get("tpot_s"), (int, float))}
        update_straggler_flags(
            tpots, self.stragglers, self.straggler_ratio,
            id_label="replica", value_key="tpot_s",
            counter_name="fleet_serve_stragglers_total",
            event_name="serve.straggler")
        self._evict_pass(now, fleet)
        if self.autopilot is not None:
            try:
                live = [r.replica_id for r in self.router.replicas
                        if r.replica_id not in self._evicted_set]
                self.autopilot.observe(
                    fleet, stragglers=set(self.stragglers),
                    last_beat=(
                        {rid: self.tracker.last_heartbeat(rid)
                         for rid in live}
                        if self.tracker is not None else None),
                    now=now)
            except Exception:  # noqa: BLE001 — observe-only must not
                import logging  # take the serve control loop down

                logging.getLogger(__name__).exception(
                    "serve autopilot observe pass failed")
        alive = [r for r in self.router.replicas
                 if r.replica_id not in self._evicted_set and r.alive]
        self._reg.gauge("fleet_serve_replicas",
                        "decode-serving replicas currently alive"
                        ).set(float(len(alive)))
        self._reg.gauge("fleet_serve_stragglers").set(
            float(len(self.stragglers)))
        self.router.retry_pending()
        self.router.maybe_hedge()
        return fleet

    # ------------------------------------------------------------------
    def _evict_pass(self, now: float, fleet: Dict[str, dict]) -> None:
        for r in list(self.router.replicas):
            rid = r.replica_id
            if rid in self._evicted_set:
                continue
            if r.dead:
                # in-process crash: the loop died and told us why —
                # no need to wait out the silence timeout
                self.evict(rid, reason=f"crashed: {r.dead_reason}",
                           silent_s=None, last_metrics=fleet.get(rid))
                continue
            if self.tracker is None:
                continue
            t = self.tracker.last_heartbeat(rid)
            if t is None:
                continue  # never beat yet (still booting) — grace
            silent = now - t
            if silent >= self.evict_timeout_s:
                self.evict(rid, reason="heartbeat_silence",
                           silent_s=round(silent, 3),
                           last_metrics=self.tracker.heartbeat_metrics(
                               rid) or fleet.get(rid))

    def evict(self, replica_id: str, *, reason: str,
              silent_s: Optional[float] = None,
              last_metrics: Optional[dict] = None) -> dict:
        """Evict one replica: evidence-logged decision, gauges dropped,
        in-flight requests failed over. Also the bench/dryrun's forced-
        eviction hook. Idempotent: the silence sweep and an
        autopilot-directed eviction may both reach the same corpse —
        only the first one acts."""
        if replica_id in self._evicted_set:
            return {"replica": replica_id, "reason": "already_evicted"}
        replica = self.router._by_id[replica_id]
        # kill, don't just flag: a silence-evicted replica may still be
        # RUNNING (stalled beats, live loop) — leaving its loop up would
        # have a zombie decoding the same requests the survivors now own
        replica.kill(reason)
        self._evicted_set.add(replica_id)
        self.evicted.append(replica_id)
        self.stragglers.discard(replica_id)
        for gauge in _REPLICA_GAUGES:
            self._reg.gauge(gauge).remove(replica=replica_id)
        decision = {"replica": replica_id, "reason": reason,
                    "silent_s": silent_s,
                    "timeout_s": self.evict_timeout_s,
                    "t_wall": self.clock(),
                    "last_metrics": last_metrics}
        record_counter("fleet_serve_evictions_total", replica=replica_id)
        # the tracer event forwards into the flight ring on its own
        # (span forwarding) — no explicit flight write
        tracer().event("serve.evict", **decision)
        summary = self.router.failover(replica_id, reason=reason)
        decision["failover"] = summary
        self.eviction_log.append(decision)
        return decision

    def drain(self, replica_id: str, *,
              reason: str = "operator_drain") -> dict:
        """Gracefully retire one replica: quiesce admission, stop its
        step loop, migrate every in-flight stream to survivors via
        KV-slab handoff (zero recompute, zero lost tokens — contrast
        :meth:`evict`, which re-prefills because a dead replica's KV is
        gone), and mark it retired. Evidence-logged like an eviction;
        idempotent against evict/drain races the same way."""
        if replica_id in self._evicted_set:
            return {"replica": replica_id, "reason": "already_evicted"}
        replica = self.router._by_id.get(replica_id)
        if replica is None:
            raise KeyError(f"unknown replica {replica_id!r}")
        self._evicted_set.add(replica_id)
        self.drained.append(replica_id)
        self.stragglers.discard(replica_id)
        # 1) no new work lands on it (placement, spill, hedges,
        #    affinity all skip a quiesced replica)
        self.router.quiesce(replica_id)
        # 2) stop the step loop CLEANLY before touching device state —
        #    migrate_out exports live cursors a concurrent step would
        #    advance; retired is not dead, so no failover fires
        replica.retire()
        # 3) move everything off with zero recompute
        summary = self.router.migrate_out(replica_id)
        for gauge in _REPLICA_GAUGES:
            self._reg.gauge(gauge).remove(replica=replica_id)
        migrated = (summary["handoffs"] + summary["queued"]
                    + summary["live"])
        decision = {"replica": replica_id, "reason": reason,
                    "t_wall": self.clock(), "migrated": migrated,
                    **summary}
        record_counter("fleet_serve_drains_total", replica=replica_id)
        self._reg.gauge("serve_drain_migrated").set(
            float(migrated), replica=replica_id)
        tracer().event("serve.drain", **decision)
        self.drain_log.append(decision)
        return decision

    # ------------------------------------------------------------------
    # real-time loop (the in-process fleet's master thread)
    # ------------------------------------------------------------------
    def start(self, interval_s: Optional[float] = None
              ) -> "FleetController":
        if self._thread is not None and self._thread.is_alive():
            return self
        interval = (interval_s if interval_s is not None
                    else max(0.05, self.evict_timeout_s / 4))
        stop = threading.Event()
        self._stop = stop

        def run():
            while not stop.wait(interval):
                self.tick()

        self._thread = threading.Thread(target=run, daemon=True,
                                        name="serve-fleet-controller")
        self._thread.start()
        return self

    def stop(self) -> None:
        if self._stop is not None:
            self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
