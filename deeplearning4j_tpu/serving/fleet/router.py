"""FleetRouter: the admission frontend in front of N replicas.

The serving counterpart of ``parallel/workrouter.py``'s dispatch
policies: where the training router decides when worker updates become
global parameters, the serve router decides which replica a request
lands on. Policy (stated so it can be changed deliberately):

- **least-loaded placement** — free-slots-first (a replica with an open
  slot starts decoding at its next step boundary; one with a deep queue
  makes the request wait), with a TTFT-aware tiebreak: at equal free
  slots the replica whose recent TTFT p50 is lower wins (it is
  admitting faster, whatever the reason), then replica id for
  determinism.
- **bounded queues + spill** — each replica's own admission queue bound
  (``DL4J_SERVE_MAX_QUEUE``) is the per-replica backpressure edge; a
  full replica spills to the next-least-loaded one, and only when EVERY
  alive replica is full does the router report a drop (open-loop load
  sheds it; the loadgen's drop series records when).
- **sticky affinity** — an in-flight stream never migrates (its slot
  holds its KV); optionally, a caller-provided ``affinity`` key pins
  future requests to the replica that served the key before (session
  cache reuse), falling back to least-loaded when that replica died.
- **failover** — when the controller evicts a replica, its unfinished
  requests requeue onto survivors with the prompt re-prefilled. Greedy
  streams keep the tokens already emitted and re-prefill
  ``prompt + emitted`` (deterministic prefill ⇒ the continuation is the
  exact suffix the dead replica would have produced); sampled streams
  replay from scratch with the original seed (the per-request RNG chain
  is a pure function of the seed, so the replayed stream is identical
  too — it just cannot resume mid-chain). Either way a killed replica
  costs recompute, never tokens: completed output is token-identical
  to an unfailed run.

In a role-split fleet (any ``prefill`` replicas present) new requests
route to the least-loaded prefill replica, whose finished slab the
router then places on the least-loaded decode replica
(``place_handoff``), and failover re-enters the same pipeline.

Spans: every placement runs under ``serve.route`` and every eviction
recovery under ``serve.failover`` — both feed the flight recorder via
the standard span forwarding, so a postmortem can replay routing
decisions around a death.
"""

from __future__ import annotations

import itertools
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

import numpy as np

from deeplearning4j_tpu.monitor import metrics, tracer
from deeplearning4j_tpu.serving.fleet.handoff import (
    SlotHandoff, export_live_slot, make_install)
from deeplearning4j_tpu.serving.fleet.replica import ServeReplica
from deeplearning4j_tpu.serving.scheduler import (
    CRITICALITIES, RetryBudget, ServeRequest, criticality_rank,
    serve_hedge_s, serve_replicas)

__all__ = ["FleetRequest", "FleetRouter", "FleetSaturated"]

_FLEET_IDS = itertools.count(1)


class FleetSaturated(RuntimeError):
    """Every alive replica's queue is at its bound."""


@dataclass
class FleetRequest:
    """One request at fleet level: survives replica failover by
    stitching the tokens emitted before the death (``emitted``) to the
    current replica-local segment (``inner``)."""

    prompt: np.ndarray
    max_new_tokens: int
    seed: int = 0
    affinity: Optional[str] = None
    # absolute deadline on the router's clock axis; None = no deadline
    deadline_s: Optional[float] = None
    criticality: str = "interactive"
    id: int = field(default_factory=lambda: next(_FLEET_IDS))
    replica_id: Optional[str] = None
    inner: Optional[ServeRequest] = None
    emitted: List[int] = field(default_factory=list)
    attempts: int = 0
    submit_s: Optional[float] = None
    _first_token_s: Optional[float] = None
    # a finished prefill slab waiting for decode headroom (split mode)
    _parked_handoff: Optional[SlotHandoff] = None
    # hedge copy: a second replica racing the same (greedy) stream for
    # a tail-latency-stuck interactive request; first winner cancels
    # the loser (token-identical, so either copy's output is THE output)
    hedge: Optional[ServeRequest] = None
    hedge_replica_id: Optional[str] = None
    # stamped when the fleet sheds the request (displacement victim or
    # past-deadline); mirrors the inner request's shed_reason when the
    # shed happened replica-side
    shed_reason: Optional[str] = None

    # stamped by the router when a requeue discovers everything was
    # already streamed before the death (no inner segment remains to
    # carry a finish timestamp)
    _finish_s: Optional[float] = None
    # retry-budget denial evidence is logged once per request
    _denied_logged: bool = False

    @property
    def tokens(self) -> List[int]:
        inner = self.inner.tokens if self.inner is not None else []
        return self.emitted + list(inner)

    @property
    def finished(self) -> bool:
        if (self.inner is None
                and len(self.emitted) >= self.max_new_tokens):
            # a failover found every token already emitted: complete
            # with no live segment
            return True
        return (self.inner is not None
                and self.inner.state == "finished"
                and len(self.tokens) >= self.max_new_tokens)

    @property
    def state(self) -> str:
        if self.finished:
            return "finished"
        if self.shed_reason is not None:
            return "shed"
        return "queued" if self.inner is None else self.inner.state

    @property
    def cost(self) -> int:
        """Work estimate for shedding decisions (same scale as
        ``ServeRequest.cost``)."""
        return int(self.prompt.size) + int(self.max_new_tokens)

    @property
    def first_token_s(self) -> Optional[float]:
        if self._first_token_s is not None:
            return self._first_token_s
        return None if self.inner is None else self.inner.first_token_s

    @property
    def finish_s(self) -> Optional[float]:
        if self._finish_s is not None:
            return self._finish_s
        return None if self.inner is None else self.inner.finish_s

    @property
    def ttft_s(self) -> Optional[float]:
        ft = self.first_token_s
        if self.submit_s is None or ft is None:
            return None
        return ft - self.submit_s

    @property
    def latency_s(self) -> Optional[float]:
        if self.submit_s is None or self.finish_s is None \
                or not self.finished:
            return None
        return self.finish_s - self.submit_s

    @property
    def output(self) -> np.ndarray:
        """``prompt + generated`` — the ``generate()`` shape, for the
        token-identity contract across failover."""
        return np.concatenate(
            [self.prompt, np.asarray(self.tokens, self.prompt.dtype)])


class FleetRouter:
    """Route requests across replicas; requeue them across deaths."""

    @classmethod
    def build(cls, model, *, replicas: Optional[int] = None,
              tracker=None, role: Optional[str] = None,
              clock=time.monotonic, **server_kw) -> "FleetRouter":
        """Stand up a uniform in-process fleet: ``DL4J_SERVE_REPLICAS``
        (or ``replicas=``) workers named ``replica-<i>``, each reading
        its role from ``DL4J_SERVE_ROLE`` (or ``role=``) and its server
        config from the usual ``DL4J_SERVE_*`` knobs / ``server_kw``.
        The operator entry point the env rows document; callers needing
        heterogeneous roles construct :class:`ServeReplica` lists
        themselves."""
        n = replicas if replicas is not None else serve_replicas()
        reps = [ServeReplica(f"replica-{i}", model, tracker=tracker,
                             role=role, clock=clock, **server_kw)
                for i in range(n)]
        return cls(reps, clock=clock)

    def __init__(self, replicas: Sequence[ServeReplica], *,
                 clock=time.monotonic):
        if not replicas:
            raise ValueError("a fleet needs at least one replica")
        ids = [r.replica_id for r in replicas]
        if len(set(ids)) != len(ids):
            raise ValueError(f"duplicate replica ids: {ids}")
        self.replicas = list(replicas)
        self._by_id: Dict[str, ServeReplica] = {
            r.replica_id: r for r in replicas}
        self.prefill_replicas = [r for r in replicas
                                 if r.role == "prefill"]
        self.decode_replicas = [r for r in replicas
                                if r.role in ("decode", "mixed")]
        if not self.decode_replicas:
            raise ValueError("a fleet needs at least one decode-capable "
                             "(mixed/decode) replica")
        self.split = bool(self.prefill_replicas)
        temps = {r.server.engine.temperature for r in replicas}
        if len(temps) > 1:
            raise ValueError(
                f"replicas disagree on sampling temperature ({temps}): "
                "failover token-identity needs one fleet-wide config")
        self.greedy = temps.pop() == 0.0
        # pool config must be fleet-uniform too: a failover continuation
        # or a handoff landing on a smaller/differently-quantized pool
        # would raise mid-recovery (or mid-step, killing a healthy
        # replica) — reject the misconfiguration at construction
        for attr in ("max_len", "kv_dtype"):
            vals = {getattr(r.server.engine, attr) for r in replicas}
            if len(vals) > 1:
                raise ValueError(
                    f"replicas disagree on {attr} ({vals}): failover "
                    "and handoff need one fleet-wide pool config")
        if self.prefill_replicas:
            spec = [r.replica_id for r in self.decode_replicas
                    if r.server.engine.spec]
            if spec:
                raise ValueError(
                    f"decode replicas {spec} run speculative decoding, "
                    "which cannot accept handoffs (no draft-pool prompt "
                    "K/V) — a split fleet needs non-speculative decode "
                    "replicas")
        self.clock = clock
        self.requests: List[FleetRequest] = []
        self._affinity: Dict[str, str] = {}
        # failover parking lot: requeues that found every survivor full
        # wait here and retry on the next controller tick / submission
        self._pending: List[FleetRequest] = []
        # overload control: per-class retry budget (failover re-dispatch,
        # spill probes past the first-ranked candidate, and hedges all
        # draw from it — bounding retry amplification under storm),
        # hedge latency threshold, quiesced replicas (draining: admit
        # nothing new), and the inner-request -> fleet-request index the
        # displacement/drain paths settle through
        self.retry_budget = RetryBudget()
        self.hedge_after_s = serve_hedge_s()
        self._quiesced: set = set()
        self._owner: Dict[int, FleetRequest] = {}
        self.shed_log: List[dict] = []
        self.hedge_log: List[dict] = []
        self.hedge_wins = 0
        self._lock = threading.RLock()
        self._reg = metrics()

    # ------------------------------------------------------------------
    # placement
    # ------------------------------------------------------------------
    def _alive_decode(self) -> List[ServeReplica]:
        return [r for r in self.decode_replicas
                if r.alive and r.replica_id not in self._quiesced]

    def _alive_prefill(self) -> List[ServeReplica]:
        return [r for r in self.prefill_replicas
                if r.alive and r.replica_id not in self._quiesced]

    def quiesce(self, replica_id: str) -> None:
        """Stop routing NEW work to ``replica_id`` (first step of a
        graceful drain): the replica keeps stepping its in-flight
        streams until ``migrate_out`` moves them, but placement,
        spill, hedging and affinity pinning all skip it."""
        with self._lock:
            self._quiesced.add(replica_id)
            self._affinity = {k: v for k, v in self._affinity.items()
                              if v != replica_id}

    @staticmethod
    def _rank(replicas: List[ServeReplica]) -> List[ServeReplica]:
        """Least-loaded first: headroom = free slots MINUS queued
        requests (queued work claims a slot at the next boundary — free
        slots alone would send a whole arrival burst to one replica,
        since admission only moves the count at step boundaries), then
        recent TTFT p50 ascending (no samples = no traffic yet = 0, so
        fresh replicas absorb load), then id for determinism."""
        return sorted(replicas,
                      key=lambda r: (-(r.server.free_slot_count()
                                       - r.queue_depth()),
                                     r.ttft_p50() or 0.0,
                                     r.replica_id))

    def submit(self, prompt, max_new_tokens: int, *, seed: int = 0,
               affinity: Optional[str] = None,
               deadline_s: Optional[float] = None,
               criticality: str = "interactive") -> FleetRequest:
        """Admit one request into the fleet; raises
        :class:`FleetSaturated` when every alive replica is full."""
        freq = self.try_submit(prompt, max_new_tokens, seed=seed,
                               affinity=affinity, deadline_s=deadline_s,
                               criticality=criticality)
        if freq is None:
            raise FleetSaturated(
                "every alive replica's queue is at its bound")
        return freq

    def try_submit(self, prompt, max_new_tokens: int, *, seed: int = 0,
                   affinity: Optional[str] = None,
                   deadline_s: Optional[float] = None,
                   criticality: str = "interactive"
                   ) -> Optional[FleetRequest]:
        """Non-raising admission: ``None`` means the fleet shed the
        request (every alive replica full, even after criticality
        displacement) — open-loop callers record the drop and move on.
        ``deadline_s`` is ABSOLUTE on the router's clock axis."""
        criticality_rank(criticality)     # fail fast on a typo'd class
        with self._lock:
            self.retry_pending()
            freq = FleetRequest(
                prompt=np.asarray(prompt, np.int32).reshape(-1),
                max_new_tokens=int(max_new_tokens), seed=int(seed),
                affinity=affinity, deadline_s=deadline_s,
                criticality=criticality)
            freq.submit_s = self.clock()
            # every accepted submission funds future retries for its
            # class — the token-bucket side of the retry-amplification
            # bound (retries <= ratio * submissions + burst)
            self.retry_budget.deposit(criticality)
            self._publish_budget()
            if self._place(freq, freq.prompt, freq.max_new_tokens):
                self.requests.append(freq)
                return freq
            self._reg.counter("serve_route_total").inc(outcome="dropped")
            if freq.shed_reason is None:
                # fleet-level decision (every replica full even after
                # displacement); past-deadline sheds were already
                # evidence-logged by the replica that refused them
                freq.shed_reason = "fleet_saturated"
                decision = {"request": freq.id,
                            "criticality": criticality,
                            "where": "admission",
                            "reason": "fleet_saturated",
                            "t": freq.submit_s}
                self.shed_log.append(decision)
                tracer().event("serve.shed", **decision)
            return None

    def _publish_budget(self) -> None:
        for c in CRITICALITIES:
            self._reg.gauge("serve_retry_budget_remaining").set(
                self.retry_budget.remaining(c), criticality=c)

    def _place(self, freq: FleetRequest, prompt,
               max_new_tokens: int) -> bool:
        """One routing decision under a ``serve.route`` span: prefill
        pipeline in split mode, else direct decode placement with
        affinity-first + least-loaded + spill."""
        with tracer().span("serve.route", request=freq.id) as sp:
            if self.split:
                # the mixed path gets this check from try_submit; the
                # prefill pipeline builds its ServeRequest directly, so
                # validate here or an oversized request would scatter
                # past T_max on the decode side (silently clipped) —
                # or kill a prefill replica's worker thread
                total = int(np.asarray(prompt).size) + max_new_tokens
                cap = self.decode_replicas[0].server.max_len
                if total > cap:
                    raise ValueError(
                        f"prompt_len + max_new_tokens = {total} exceeds "
                        f"the fleet's slot capacity max_len={cap}")
                # each prefill replica's job queue is bounded by the
                # same DL4J_SERVE_MAX_QUEUE edge as decode admission —
                # without it, split-mode overload would grow host
                # memory (queued prompts + parked slabs) without ever
                # shedding, while a mixed fleet correctly drops
                pre = [r for r in sorted(
                    self._alive_prefill(),
                    key=lambda r: (r.queue_depth(), r.replica_id))
                    if r.queue_depth() < r.server.queue.max_depth]
                if not pre:
                    sp.attrs["outcome"] = "prefill_saturated"
                    return False
                req = ServeRequest(
                    prompt=np.asarray(prompt, np.int32).reshape(-1),
                    max_new_tokens=max_new_tokens, seed=freq.seed,
                    deadline_s=freq.deadline_s,
                    criticality=freq.criticality)
                req.submit_s = freq.submit_s
                freq.inner = req
                freq.replica_id = pre[0].replica_id
                freq.attempts += 1
                self._owner[req.id] = freq
                pre[0].enqueue_prefill(freq, self.place_handoff)
                sp.attrs.update(outcome="prefill",
                                replica=pre[0].replica_id)
                self._reg.counter("serve_route_total").inc(
                    outcome="prefill")
                return True
            cands = self._rank(self._alive_decode())
            if freq.affinity is not None:
                pinned = self._by_id.get(self._affinity.get(freq.affinity))
                if pinned is not None and pinned.alive:
                    cands = [pinned] + [r for r in cands if r is not pinned]
            # pass 1: plain spill — least-loaded first, no one harmed
            spilled = 0
            for r in cands:
                verdict = r.server.try_submit(
                    prompt, max_new_tokens, seed=freq.seed,
                    deadline_s=freq.deadline_s,
                    criticality=freq.criticality, displace=False)
                if verdict.admitted:
                    self._settle_placement(freq, r, verdict)
                    sp.attrs.update(outcome="placed",
                                    replica=r.replica_id,
                                    spilled=spilled,
                                    queue_depth=verdict.queue_depth)
                    self._reg.counter("serve_route_total").inc(
                        outcome="placed")
                    if spilled:
                        self._reg.counter(
                            "fleet_serve_spills_total").inc(spilled)
                    return True
                if verdict.reason == "expired":
                    # the replica shed it at admission (past deadline) —
                    # probing further replicas cannot un-expire it
                    freq.shed_reason = "deadline"
                    freq._finish_s = self.clock()
                    sp.attrs["outcome"] = "expired"
                    return False
                spilled += 1
            # pass 2: criticality displacement — every queue is at its
            # bound, so try to buy a seat by shedding the costliest
            # queued request of a STRICTLY lower class (the replica
            # picks the victim; same-or-higher class is never
            # displaced, so an all-interactive overload still sheds
            # the newcomer, not a peer)
            for r in cands:
                verdict = r.server.try_submit(
                    prompt, max_new_tokens, seed=freq.seed,
                    deadline_s=freq.deadline_s,
                    criticality=freq.criticality, displace=True)
                if verdict.admitted:
                    if verdict.displaced is not None:
                        self._on_displaced(verdict.displaced, freq)
                    self._settle_placement(freq, r, verdict)
                    sp.attrs.update(outcome="displaced",
                                    replica=r.replica_id,
                                    spilled=spilled)
                    self._reg.counter("serve_route_total").inc(
                        outcome="placed")
                    return True
            sp.attrs.update(outcome="saturated", spilled=spilled)
            return False

    def _settle_placement(self, freq: FleetRequest, r: ServeReplica,
                          verdict) -> None:
        freq.inner = verdict.request
        freq.replica_id = r.replica_id
        freq.attempts += 1
        self._owner[verdict.request.id] = freq
        if freq.affinity is not None:
            self._affinity[freq.affinity] = r.replica_id

    def _on_displaced(self, victim: ServeRequest,
                      by: FleetRequest) -> None:
        """Settle a displacement victim at fleet level. The replica
        already marked it shed and logged the evidence; here the owning
        :class:`FleetRequest` (if fleet-routed) drops its claim: a shed
        hedge copy just disappears (the primary still runs), a shed
        primary marks the whole fleet request shed and cancels any
        hedge it had in flight."""
        fr = self._owner.pop(victim.id, None)
        self._reg.counter("fleet_serve_displacements_total").inc(
            victim=victim.criticality, by=by.criticality)
        if fr is None:
            return
        if fr.hedge is victim:
            fr.hedge = None
            fr.hedge_replica_id = None
            return
        fr.shed_reason = victim.shed_reason or "shed_overload"
        self._pending = [p for p in self._pending if p is not fr]
        if fr.hedge is not None:
            self._cancel_inner(fr.hedge, fr.hedge_replica_id)
            fr.hedge = None
            fr.hedge_replica_id = None

    def place_handoff(self, freq: FleetRequest,
                      handoff: SlotHandoff) -> bool:
        """Place a prefilled slab on the least-loaded decode replica
        (headroom = free slots minus already-queued handoffs); parks the
        request for retry when every decode replica is packed."""
        with self._lock, tracer().span("serve.handoff",
                                       request=freq.id) as sp:
            cands = sorted(
                (r for r in self._alive_decode()
                 if not r.server.engine.spec),
                key=lambda r: (-r.server.handoff_headroom(),
                               r.replica_id))
            for r in cands:
                if r.server.handoff_headroom() <= 0:
                    continue
                r.server.admit_external(freq.inner, make_install(handoff))
                freq.replica_id = r.replica_id
                sp.attrs.update(outcome="placed", replica=r.replica_id)
                return True
            # no headroom anywhere: hold the finished prefill and retry
            # at the next tick (the slab is host-resident — it costs
            # memory, not a slot)
            freq._parked_handoff = handoff
            if freq not in self._pending:
                self._pending.append(freq)
            sp.attrs["outcome"] = "parked"
            return False

    # ------------------------------------------------------------------
    # failover
    # ------------------------------------------------------------------
    def failover(self, replica_id: str, *,
                 reason: str = "evicted") -> dict:
        """Requeue the dead replica's unfinished requests onto
        survivors. Returns a summary for the eviction evidence log."""
        with self._lock, tracer().span("serve.failover",
                                       replica=replica_id,
                                       reason=reason) as sp:
            victims = [fr for fr in self.requests
                       if fr.replica_id == replica_id and not fr.finished]
            # a victim may ALSO sit in the parking lot (its handoff
            # found no headroom before the death): drop it there first,
            # or the next retry would place the same request twice
            drop = set(map(id, victims))
            self._pending = [fr for fr in self._pending
                             if id(fr) not in drop]
            requeued = parked = 0
            for fr in victims:
                if self._requeue(fr):
                    requeued += 1
                else:
                    parked += 1
            sp.attrs.update(requeued=requeued, parked=parked)
            if victims:
                self._reg.counter(
                    "fleet_serve_failover_requests_total").inc(
                    len(victims))
            return {"victims": len(victims), "requeued": requeued,
                    "parked": parked}

    def _requeue(self, fr: FleetRequest, *, charge: bool = True) -> bool:
        inner = fr.inner
        if self.greedy and inner is not None and inner.tokens:
            # keep what was already streamed; re-prefill prompt+prefix —
            # deterministic prefill makes the continuation the exact
            # suffix of the unfailed stream
            fr._first_token_s = fr.first_token_s
            fr.emitted.extend(inner.tokens)
        else:
            # sampled (or nothing emitted): replay from scratch with the
            # original seed — the per-request RNG chain is a pure
            # function of the seed, so the replayed stream is identical
            fr.emitted = []
            fr._first_token_s = None
        fr.inner = None
        fr.replica_id = None
        fr._parked_handoff = None
        if len(fr.emitted) >= fr.max_new_tokens:
            # everything already streamed before the death (e.g. a
            # prefill-complete max_new=1 request whose handoff never
            # installed): complete it here — no survivor has work to do
            fr._finish_s = self.clock()
            return True
        return self._place_continuation(fr, charge=charge)

    def _place_continuation(self, fr: FleetRequest, *,
                            charge: bool = True) -> bool:
        """Re-dispatch a failed-over request. ``charge=True`` draws one
        token from the class's retry budget — spent only when the
        placement actually lands (a re-dispatch is the recompute the
        budget bounds; a parked request costs nothing until it does).
        A dry budget parks the request instead of re-dispatching it:
        under storm, retries must not amplify load past the bound.
        ``charge=False`` is for drain migrations — deliberate operator
        moves, not retries."""
        if charge and not self.retry_budget.has(fr.criticality):
            if not fr._denied_logged:     # once per request, not per tick
                fr._denied_logged = True
                self._reg.counter("serve_retry_denied_total").inc(
                    kind="failover", criticality=fr.criticality)
                tracer().event("serve.retry_denied", request=fr.id,
                               kind="failover",
                               criticality=fr.criticality,
                               t=self.clock())
            self._pending.append(fr)
            return False
        prompt = (np.concatenate(
            [fr.prompt, np.asarray(fr.emitted, np.int32)])
            if fr.emitted else fr.prompt)
        remaining = fr.max_new_tokens - len(fr.emitted)
        if self._place(fr, prompt, remaining):
            if charge:
                self.retry_budget.try_spend(fr.criticality)
                self._publish_budget()
            return True
        if fr.shed_reason is not None:
            # the placement attempt discovered the deadline passed —
            # the request is shed, not parked
            return False
        self._pending.append(fr)
        return False

    def retry_pending(self) -> int:
        """Drain the failover parking lot (called on every tick and
        submission); returns how many found a home. Failures re-park
        themselves (``place_handoff`` / ``_place_continuation`` both
        append back on a miss); past-deadline parkers shed instead of
        retrying — the earliest point that looks at a parked deadline."""
        with self._lock:
            now = self.clock()
            pending, self._pending = self._pending, []
            placed = 0
            for fr in pending:
                if fr.deadline_s is not None and now > fr.deadline_s:
                    self._shed_fleet(fr, where="parked",
                                     reason="deadline")
                    continue
                handoff, fr._parked_handoff = fr._parked_handoff, None
                if handoff is not None:
                    ok = self.place_handoff(fr, handoff)
                else:
                    ok = self._place_continuation(fr)
                placed += int(ok)
            return placed

    def _shed_fleet(self, fr: FleetRequest, *, where: str,
                    reason: str) -> None:
        """Shed a request the fleet (not a replica) owns right now —
        same evidence shape as the replica-side shed."""
        fr.shed_reason = reason
        fr._finish_s = self.clock()
        fr._parked_handoff = None
        decision = {"request": fr.id, "criticality": fr.criticality,
                    "where": where, "reason": reason, "t": fr._finish_s}
        self.shed_log.append(decision)
        self._reg.counter("serve_shed_total").inc(
            criticality=fr.criticality, where=where)
        tracer().event("serve.shed", **decision)

    # ------------------------------------------------------------------
    # hedging
    # ------------------------------------------------------------------
    def maybe_hedge(self) -> int:
        """Tail-latency hedging pass (called from the controller tick
        and the load driver's event loop): an ``interactive`` request
        still QUEUED ``hedge_after_s`` after submit places a second
        copy on a different replica — greedy token identity makes both
        copies produce THE stream, so whichever starts first wins and
        the loser cancels. Hedges draw from the interactive retry
        budget (a hedge is speculative extra load; under storm the
        budget keeps it from amplifying the overload). Also reconciles
        existing hedge pairs. Returns how many new hedges were placed.

        Disabled unless ``DL4J_SERVE_HEDGE_S`` (or ``hedge_after_s``)
        is set — and meaningless for sampled fleets, where the two
        copies would diverge, so it refuses those at the gate."""
        with self._lock:
            for fr in self.requests:
                if fr.hedge is not None:
                    self._reconcile_hedge(fr)
            if self.hedge_after_s is None or not self.greedy:
                return 0
            now = self.clock()
            placed = 0
            for fr in self.requests:
                if (fr.criticality != "interactive"
                        or fr.hedge is not None
                        or fr.inner is None
                        or fr.inner.state != "queued"
                        or fr.shed_reason is not None
                        or fr.submit_s is None
                        or now - fr.submit_s < self.hedge_after_s):
                    continue
                if fr.deadline_s is not None and now > fr.deadline_s:
                    continue        # the expiry sweeps will shed it
                if not self.retry_budget.try_spend("interactive"):
                    break           # budget dry: no hedging this pass
                self._publish_budget()
                placed += int(self._place_hedge(fr, now))
            return placed

    def _place_hedge(self, fr: FleetRequest, now: float) -> bool:
        cands = [r for r in self._rank(self._alive_decode())
                 if r.replica_id != fr.replica_id]
        for r in cands[:1]:       # one extra bet, on the best candidate
            verdict = r.server.try_submit(
                fr.prompt, fr.max_new_tokens, seed=fr.seed,
                deadline_s=fr.deadline_s, criticality=fr.criticality,
                displace=False)   # a hedge must not shed anyone
            if verdict.admitted:
                fr.hedge = verdict.request
                fr.hedge_replica_id = r.replica_id
                self._owner[verdict.request.id] = fr
                ev = {"request": fr.id, "from": fr.replica_id,
                      "to": r.replica_id, "t": now}
                self.hedge_log.append(ev)
                self._reg.counter("fleet_serve_hedges_total").inc()
                tracer().event("serve.hedge", **ev)
                return True
        # nowhere to hedge: the spent token goes back
        self.retry_budget.refund("interactive")
        self._publish_budget()
        return False

    def _reconcile_hedge(self, fr: FleetRequest) -> None:
        """First winner cancels the loser: whichever copy reached a
        slot (running/finished) first keeps the stream; the other is
        canceled (pulled from its queue, or flagged for the server's
        cancel sweep if already in a slot)."""
        pri, h = fr.inner, fr.hedge
        if h is None:
            return
        if h.state in ("shed", "canceled"):
            self._owner.pop(h.id, None)
            fr.hedge = None
            fr.hedge_replica_id = None
            return
        if pri is None or pri.state in ("shed", "canceled"):
            self._promote_hedge(fr)
            return
        if pri.state == "finished":
            # primary delivered the stream: the hedge copy is moot
            if h.state != "finished":
                self._cancel_inner(h, fr.hedge_replica_id)
            else:
                self._owner.pop(h.id, None)
            fr.hedge = None
            fr.hedge_replica_id = None
            return
        pri_live = pri.state == "running"
        h_live = h.state in ("running", "finished")
        if h_live and not pri_live:
            # hedge won the race: primary is still queued — cancel it
            # and promote the hedge to be THE segment
            self._cancel_inner(pri, fr.replica_id)
            self._promote_hedge(fr)
            self.hedge_wins += 1
            self._reg.counter("fleet_serve_hedge_wins_total").inc()
            self._reg.gauge("serve_hedge_wins").set(
                float(self.hedge_wins))
            tracer().event("serve.hedge_win", request=fr.id,
                           replica=fr.replica_id, t=self.clock())
            return
        if pri_live and not h_live:
            # primary won: drop the hedge copy
            self._cancel_inner(h, fr.hedge_replica_id)
            fr.hedge = None
            fr.hedge_replica_id = None
        # both queued (keep racing) or both live (greedy token identity:
        # let the primary finish; the hedge cancels on the next pass
        # once the primary is done) — nothing to do this pass

    def _promote_hedge(self, fr: FleetRequest) -> None:
        if fr.inner is not None:
            self._owner.pop(fr.inner.id, None)
        fr.inner = fr.hedge
        fr.replica_id = fr.hedge_replica_id
        fr.hedge = None
        fr.hedge_replica_id = None

    def _cancel_inner(self, req: ServeRequest,
                      replica_id: Optional[str]) -> None:
        """Cancel one replica-local segment: flag it (the server's
        sweep retires a running slot) and best-effort pull it from the
        admission queue so it stops holding a seat."""
        req.canceled = True
        self._owner.pop(req.id, None)
        r = self._by_id.get(replica_id) if replica_id else None
        if r is not None and req.state == "queued":
            if r.server.queue.remove(req):
                req.state = "canceled"

    # ------------------------------------------------------------------
    # graceful drain
    # ------------------------------------------------------------------
    def migrate_out(self, replica_id: str) -> dict:
        """Move every request off a RETIRED replica with zero recompute
        and zero lost tokens — the drain counterpart of :meth:`failover`
        (which re-prefills because a dead replica's KV is gone; a
        drained replica's KV is intact, so live slots export wholesale
        via :func:`export_live_slot`). The replica's step loop must be
        stopped (``retire()``) before calling: the export reads device
        state that a concurrent step would advance.

        Three populations, in order: parked prefill handoffs re-home
        directly (the slab is already host-resident); queued-never-
        admitted requests re-place on survivors (nothing was computed,
        so nothing is recomputed); live slots export mid-stream and
        re-enter through the handoff install path. Hedge copies on the
        draining replica are dropped, not moved (the primary still
        runs — a hedge is redundant by construction). Speculative
        survivors cannot accept handoffs; when no non-spec survivor
        exists the live slots fall back to failover re-prefill,
        reported as ``fallback_failovers`` (recompute, never tokens)."""
        victim = self._by_id.get(replica_id)
        if victim is None:
            raise KeyError(f"unknown replica {replica_id!r}")
        server = victim.server
        with self._lock:
            moved_handoffs = moved_queued = moved_live = 0
            dropped_hedges = fallback = 0
            # (i) parked prefill handoffs queued on the victim
            while server._handoffs:
                req, install = server._handoffs.popleft()
                fr = self._owner.get(req.id)
                survivors = sorted(
                    (r for r in self._alive_decode()
                     if r.server.handoff_headroom() > 0),
                    key=lambda r: (-r.server.handoff_headroom(),
                                   r.replica_id))
                if survivors:
                    survivors[0].server.admit_external(req, install)
                    if fr is not None:
                        fr.replica_id = survivors[0].replica_id
                    moved_handoffs += 1
                elif fr is not None:
                    # no headroom anywhere right now: the install
                    # closure owns the slab, so we cannot re-park it
                    # fleet-side — fall back to re-prefill (recompute,
                    # never tokens)
                    fr.inner = req
                    self._requeue(fr, charge=False)
                    fallback += 1
            # (ii) queued, never admitted: re-place (zero compute done,
            # zero recomputed); drain moves are deliberate, not retries
            while True:
                req = server.queue.pop()
                if req is None:
                    break
                fr = self._owner.get(req.id)
                if fr is None:
                    continue          # direct server user; nothing to do
                if fr.hedge is req:
                    self._owner.pop(req.id, None)
                    fr.hedge = None
                    fr.hedge_replica_id = None
                    dropped_hedges += 1
                    continue
                self._owner.pop(req.id, None)
                fr.inner = None
                fr.replica_id = None
                if self._place_continuation(fr, charge=False):
                    moved_queued += 1
            # (iii) live slots: export mid-stream KV + cursor + RNG and
            # re-install on a survivor — the zero-recompute move
            non_spec = [r for r in self._alive_decode()
                        if not r.server.engine.spec]
            for slot in list(server._live_slots()):
                req = server._slot_req[slot]
                fr = self._owner.get(req.id)
                if fr is None:
                    continue
                if fr.hedge is req:
                    self._owner.pop(req.id, None)
                    fr.hedge = None
                    fr.hedge_replica_id = None
                    dropped_hedges += 1
                    server._slot_req[slot] = None
                    continue
                if not non_spec:
                    # no survivor can install a handoff: failover-style
                    # re-prefill (costs recompute, never tokens)
                    self._owner.pop(req.id, None)
                    server._slot_req[slot] = None
                    self._requeue(fr, charge=False)
                    fallback += 1
                    continue
                handoff = export_live_slot(server, slot)
                # detach WITHOUT retiring: the stream continues
                # elsewhere (same ServeRequest object, same tokens
                # list), this replica just stops owning it
                server._slot_req[slot] = None
                fr.replica_id = None
                self.place_handoff(fr, handoff)
                moved_live += 1
            return {"handoffs": moved_handoffs, "queued": moved_queued,
                    "live": moved_live, "dropped_hedges": dropped_hedges,
                    "fallback_failovers": fallback}

    # ------------------------------------------------------------------
    def unfinished(self) -> List[FleetRequest]:
        with self._lock:
            return [fr for fr in self.requests if not fr.finished]

    def stats(self) -> dict:
        with self._lock:
            return {
                "replicas": len(self.replicas),
                "alive": sum(1 for r in self.replicas if r.alive),
                "split": self.split,
                "requests": len(self.requests),
                "finished": sum(1 for fr in self.requests if fr.finished),
                "pending_failover": len(self._pending),
                "quiesced": sorted(self._quiesced),
                "shed": len(self.shed_log),
                "hedges": len(self.hedge_log),
                "hedge_wins": self.hedge_wins,
                "retry_budget": {c: self.retry_budget.remaining(c)
                                 for c in CRITICALITIES},
            }
