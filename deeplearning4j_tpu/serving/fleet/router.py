"""FleetRouter: the admission frontend in front of N replicas.

The serving counterpart of ``parallel/workrouter.py``'s dispatch
policies: where the training router decides when worker updates become
global parameters, the serve router decides which replica a request
lands on. Policy (stated so it can be changed deliberately):

- **least-loaded placement** — free-slots-first (a replica with an open
  slot starts decoding at its next step boundary; one with a deep queue
  makes the request wait), with a TTFT-aware tiebreak: at equal free
  slots the replica whose recent TTFT p50 is lower wins (it is
  admitting faster, whatever the reason), then replica id for
  determinism.
- **bounded queues + spill** — each replica's own admission queue bound
  (``DL4J_SERVE_MAX_QUEUE``) is the per-replica backpressure edge; a
  full replica spills to the next-least-loaded one, and only when EVERY
  alive replica is full does the router report a drop (open-loop load
  sheds it; the loadgen's drop series records when).
- **sticky affinity** — an in-flight stream never migrates (its slot
  holds its KV); optionally, a caller-provided ``affinity`` key pins
  future requests to the replica that served the key before (session
  cache reuse), falling back to least-loaded when that replica died.
- **failover** — when the controller evicts a replica, its unfinished
  requests requeue onto survivors with the prompt re-prefilled. Greedy
  streams keep the tokens already emitted and re-prefill
  ``prompt + emitted`` (deterministic prefill ⇒ the continuation is the
  exact suffix the dead replica would have produced); sampled streams
  replay from scratch with the original seed (the per-request RNG chain
  is a pure function of the seed, so the replayed stream is identical
  too — it just cannot resume mid-chain). Either way a killed replica
  costs recompute, never tokens: completed output is token-identical
  to an unfailed run.

In a role-split fleet (any ``prefill`` replicas present) new requests
route to the least-loaded prefill replica, whose finished slab the
router then places on the least-loaded decode replica
(``place_handoff``), and failover re-enters the same pipeline.

Spans: every placement runs under ``serve.route`` and every eviction
recovery under ``serve.failover`` — both feed the flight recorder via
the standard span forwarding, so a postmortem can replay routing
decisions around a death.
"""

from __future__ import annotations

import itertools
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

import numpy as np

from deeplearning4j_tpu.monitor import metrics, tracer
from deeplearning4j_tpu.serving.fleet.handoff import SlotHandoff, make_install
from deeplearning4j_tpu.serving.fleet.replica import ServeReplica
from deeplearning4j_tpu.serving.scheduler import (
    ServeRequest, serve_replicas)

__all__ = ["FleetRequest", "FleetRouter", "FleetSaturated"]

_FLEET_IDS = itertools.count(1)


class FleetSaturated(RuntimeError):
    """Every alive replica's queue is at its bound."""


@dataclass
class FleetRequest:
    """One request at fleet level: survives replica failover by
    stitching the tokens emitted before the death (``emitted``) to the
    current replica-local segment (``inner``)."""

    prompt: np.ndarray
    max_new_tokens: int
    seed: int = 0
    affinity: Optional[str] = None
    id: int = field(default_factory=lambda: next(_FLEET_IDS))
    replica_id: Optional[str] = None
    inner: Optional[ServeRequest] = None
    emitted: List[int] = field(default_factory=list)
    attempts: int = 0
    submit_s: Optional[float] = None
    _first_token_s: Optional[float] = None
    # a finished prefill slab waiting for decode headroom (split mode)
    _parked_handoff: Optional[SlotHandoff] = None

    # stamped by the router when a requeue discovers everything was
    # already streamed before the death (no inner segment remains to
    # carry a finish timestamp)
    _finish_s: Optional[float] = None

    @property
    def tokens(self) -> List[int]:
        inner = self.inner.tokens if self.inner is not None else []
        return self.emitted + list(inner)

    @property
    def finished(self) -> bool:
        if (self.inner is None
                and len(self.emitted) >= self.max_new_tokens):
            # a failover found every token already emitted: complete
            # with no live segment
            return True
        return (self.inner is not None
                and self.inner.state == "finished"
                and len(self.tokens) >= self.max_new_tokens)

    @property
    def state(self) -> str:
        if self.finished:
            return "finished"
        return "queued" if self.inner is None else self.inner.state

    @property
    def first_token_s(self) -> Optional[float]:
        if self._first_token_s is not None:
            return self._first_token_s
        return None if self.inner is None else self.inner.first_token_s

    @property
    def finish_s(self) -> Optional[float]:
        if self._finish_s is not None:
            return self._finish_s
        return None if self.inner is None else self.inner.finish_s

    @property
    def ttft_s(self) -> Optional[float]:
        ft = self.first_token_s
        if self.submit_s is None or ft is None:
            return None
        return ft - self.submit_s

    @property
    def latency_s(self) -> Optional[float]:
        if self.submit_s is None or self.finish_s is None \
                or not self.finished:
            return None
        return self.finish_s - self.submit_s

    @property
    def output(self) -> np.ndarray:
        """``prompt + generated`` — the ``generate()`` shape, for the
        token-identity contract across failover."""
        return np.concatenate(
            [self.prompt, np.asarray(self.tokens, self.prompt.dtype)])


class FleetRouter:
    """Route requests across replicas; requeue them across deaths."""

    @classmethod
    def build(cls, model, *, replicas: Optional[int] = None,
              tracker=None, role: Optional[str] = None,
              clock=time.monotonic, **server_kw) -> "FleetRouter":
        """Stand up a uniform in-process fleet: ``DL4J_SERVE_REPLICAS``
        (or ``replicas=``) workers named ``replica-<i>``, each reading
        its role from ``DL4J_SERVE_ROLE`` (or ``role=``) and its server
        config from the usual ``DL4J_SERVE_*`` knobs / ``server_kw``.
        The operator entry point the env rows document; callers needing
        heterogeneous roles construct :class:`ServeReplica` lists
        themselves."""
        n = replicas if replicas is not None else serve_replicas()
        reps = [ServeReplica(f"replica-{i}", model, tracker=tracker,
                             role=role, clock=clock, **server_kw)
                for i in range(n)]
        return cls(reps, clock=clock)

    def __init__(self, replicas: Sequence[ServeReplica], *,
                 clock=time.monotonic):
        if not replicas:
            raise ValueError("a fleet needs at least one replica")
        ids = [r.replica_id for r in replicas]
        if len(set(ids)) != len(ids):
            raise ValueError(f"duplicate replica ids: {ids}")
        self.replicas = list(replicas)
        self._by_id: Dict[str, ServeReplica] = {
            r.replica_id: r for r in replicas}
        self.prefill_replicas = [r for r in replicas
                                 if r.role == "prefill"]
        self.decode_replicas = [r for r in replicas
                                if r.role in ("decode", "mixed")]
        if not self.decode_replicas:
            raise ValueError("a fleet needs at least one decode-capable "
                             "(mixed/decode) replica")
        self.split = bool(self.prefill_replicas)
        temps = {r.server.engine.temperature for r in replicas}
        if len(temps) > 1:
            raise ValueError(
                f"replicas disagree on sampling temperature ({temps}): "
                "failover token-identity needs one fleet-wide config")
        self.greedy = temps.pop() == 0.0
        # pool config must be fleet-uniform too: a failover continuation
        # or a handoff landing on a smaller/differently-quantized pool
        # would raise mid-recovery (or mid-step, killing a healthy
        # replica) — reject the misconfiguration at construction
        for attr in ("max_len", "kv_dtype"):
            vals = {getattr(r.server.engine, attr) for r in replicas}
            if len(vals) > 1:
                raise ValueError(
                    f"replicas disagree on {attr} ({vals}): failover "
                    "and handoff need one fleet-wide pool config")
        if self.prefill_replicas:
            spec = [r.replica_id for r in self.decode_replicas
                    if r.server.engine.spec]
            if spec:
                raise ValueError(
                    f"decode replicas {spec} run speculative decoding, "
                    "which cannot accept handoffs (no draft-pool prompt "
                    "K/V) — a split fleet needs non-speculative decode "
                    "replicas")
        self.clock = clock
        self.requests: List[FleetRequest] = []
        self._affinity: Dict[str, str] = {}
        # failover parking lot: requeues that found every survivor full
        # wait here and retry on the next controller tick / submission
        self._pending: List[FleetRequest] = []
        self._lock = threading.RLock()
        self._reg = metrics()

    # ------------------------------------------------------------------
    # placement
    # ------------------------------------------------------------------
    def _alive_decode(self) -> List[ServeReplica]:
        return [r for r in self.decode_replicas if r.alive]

    def _alive_prefill(self) -> List[ServeReplica]:
        return [r for r in self.prefill_replicas if r.alive]

    @staticmethod
    def _rank(replicas: List[ServeReplica]) -> List[ServeReplica]:
        """Least-loaded first: headroom = free slots MINUS queued
        requests (queued work claims a slot at the next boundary — free
        slots alone would send a whole arrival burst to one replica,
        since admission only moves the count at step boundaries), then
        recent TTFT p50 ascending (no samples = no traffic yet = 0, so
        fresh replicas absorb load), then id for determinism."""
        return sorted(replicas,
                      key=lambda r: (-(r.server.free_slot_count()
                                       - r.queue_depth()),
                                     r.ttft_p50() or 0.0,
                                     r.replica_id))

    def submit(self, prompt, max_new_tokens: int, *, seed: int = 0,
               affinity: Optional[str] = None) -> FleetRequest:
        """Admit one request into the fleet; raises
        :class:`FleetSaturated` when every alive replica is full."""
        freq = self.try_submit(prompt, max_new_tokens, seed=seed,
                               affinity=affinity)
        if freq is None:
            raise FleetSaturated(
                "every alive replica's queue is at its bound")
        return freq

    def try_submit(self, prompt, max_new_tokens: int, *, seed: int = 0,
                   affinity: Optional[str] = None
                   ) -> Optional[FleetRequest]:
        """Non-raising admission: ``None`` means the fleet shed the
        request (every alive replica full) — open-loop callers record
        the drop and move on."""
        with self._lock:
            self.retry_pending()
            freq = FleetRequest(
                prompt=np.asarray(prompt, np.int32).reshape(-1),
                max_new_tokens=int(max_new_tokens), seed=int(seed),
                affinity=affinity)
            freq.submit_s = self.clock()
            if self._place(freq, freq.prompt, freq.max_new_tokens):
                self.requests.append(freq)
                return freq
            self._reg.counter("serve_route_total").inc(outcome="dropped")
            return None

    def _place(self, freq: FleetRequest, prompt,
               max_new_tokens: int) -> bool:
        """One routing decision under a ``serve.route`` span: prefill
        pipeline in split mode, else direct decode placement with
        affinity-first + least-loaded + spill."""
        with tracer().span("serve.route", request=freq.id) as sp:
            if self.split:
                # the mixed path gets this check from try_submit; the
                # prefill pipeline builds its ServeRequest directly, so
                # validate here or an oversized request would scatter
                # past T_max on the decode side (silently clipped) —
                # or kill a prefill replica's worker thread
                total = int(np.asarray(prompt).size) + max_new_tokens
                cap = self.decode_replicas[0].server.max_len
                if total > cap:
                    raise ValueError(
                        f"prompt_len + max_new_tokens = {total} exceeds "
                        f"the fleet's slot capacity max_len={cap}")
                # each prefill replica's job queue is bounded by the
                # same DL4J_SERVE_MAX_QUEUE edge as decode admission —
                # without it, split-mode overload would grow host
                # memory (queued prompts + parked slabs) without ever
                # shedding, while a mixed fleet correctly drops
                pre = [r for r in sorted(
                    self._alive_prefill(),
                    key=lambda r: (r.queue_depth(), r.replica_id))
                    if r.queue_depth() < r.server.queue.max_depth]
                if not pre:
                    sp.attrs["outcome"] = "prefill_saturated"
                    return False
                req = ServeRequest(
                    prompt=np.asarray(prompt, np.int32).reshape(-1),
                    max_new_tokens=max_new_tokens, seed=freq.seed)
                req.submit_s = freq.submit_s
                freq.inner = req
                freq.replica_id = pre[0].replica_id
                freq.attempts += 1
                pre[0].enqueue_prefill(freq, self.place_handoff)
                sp.attrs.update(outcome="prefill",
                                replica=pre[0].replica_id)
                self._reg.counter("serve_route_total").inc(
                    outcome="prefill")
                return True
            cands = self._rank(self._alive_decode())
            if freq.affinity is not None:
                pinned = self._by_id.get(self._affinity.get(freq.affinity))
                if pinned is not None and pinned.alive:
                    cands = [pinned] + [r for r in cands if r is not pinned]
            spilled = 0
            for r in cands:
                verdict = r.server.try_submit(prompt, max_new_tokens,
                                              seed=freq.seed)
                if verdict.admitted:
                    freq.inner = verdict.request
                    freq.replica_id = r.replica_id
                    freq.attempts += 1
                    if freq.affinity is not None:
                        self._affinity[freq.affinity] = r.replica_id
                    sp.attrs.update(outcome="placed",
                                    replica=r.replica_id,
                                    spilled=spilled,
                                    queue_depth=verdict.queue_depth)
                    self._reg.counter("serve_route_total").inc(
                        outcome="placed")
                    if spilled:
                        self._reg.counter(
                            "fleet_serve_spills_total").inc(spilled)
                    return True
                spilled += 1
            sp.attrs.update(outcome="saturated", spilled=spilled)
            return False

    def place_handoff(self, freq: FleetRequest,
                      handoff: SlotHandoff) -> bool:
        """Place a prefilled slab on the least-loaded decode replica
        (headroom = free slots minus already-queued handoffs); parks the
        request for retry when every decode replica is packed."""
        with self._lock, tracer().span("serve.handoff",
                                       request=freq.id) as sp:
            cands = sorted(
                self._alive_decode(),
                key=lambda r: (-r.server.handoff_headroom(),
                               r.replica_id))
            for r in cands:
                if r.server.handoff_headroom() <= 0:
                    continue
                r.server.admit_external(freq.inner, make_install(handoff))
                freq.replica_id = r.replica_id
                sp.attrs.update(outcome="placed", replica=r.replica_id)
                return True
            # no headroom anywhere: hold the finished prefill and retry
            # at the next tick (the slab is host-resident — it costs
            # memory, not a slot)
            freq._parked_handoff = handoff
            if freq not in self._pending:
                self._pending.append(freq)
            sp.attrs["outcome"] = "parked"
            return False

    # ------------------------------------------------------------------
    # failover
    # ------------------------------------------------------------------
    def failover(self, replica_id: str, *,
                 reason: str = "evicted") -> dict:
        """Requeue the dead replica's unfinished requests onto
        survivors. Returns a summary for the eviction evidence log."""
        with self._lock, tracer().span("serve.failover",
                                       replica=replica_id,
                                       reason=reason) as sp:
            victims = [fr for fr in self.requests
                       if fr.replica_id == replica_id and not fr.finished]
            # a victim may ALSO sit in the parking lot (its handoff
            # found no headroom before the death): drop it there first,
            # or the next retry would place the same request twice
            drop = set(map(id, victims))
            self._pending = [fr for fr in self._pending
                             if id(fr) not in drop]
            requeued = parked = 0
            for fr in victims:
                if self._requeue(fr):
                    requeued += 1
                else:
                    parked += 1
            sp.attrs.update(requeued=requeued, parked=parked)
            if victims:
                self._reg.counter(
                    "fleet_serve_failover_requests_total").inc(
                    len(victims))
            return {"victims": len(victims), "requeued": requeued,
                    "parked": parked}

    def _requeue(self, fr: FleetRequest) -> bool:
        inner = fr.inner
        if self.greedy and inner is not None and inner.tokens:
            # keep what was already streamed; re-prefill prompt+prefix —
            # deterministic prefill makes the continuation the exact
            # suffix of the unfailed stream
            fr._first_token_s = fr.first_token_s
            fr.emitted.extend(inner.tokens)
        else:
            # sampled (or nothing emitted): replay from scratch with the
            # original seed — the per-request RNG chain is a pure
            # function of the seed, so the replayed stream is identical
            fr.emitted = []
            fr._first_token_s = None
        fr.inner = None
        fr.replica_id = None
        fr._parked_handoff = None
        if len(fr.emitted) >= fr.max_new_tokens:
            # everything already streamed before the death (e.g. a
            # prefill-complete max_new=1 request whose handoff never
            # installed): complete it here — no survivor has work to do
            fr._finish_s = self.clock()
            return True
        return self._place_continuation(fr)

    def _place_continuation(self, fr: FleetRequest) -> bool:
        prompt = (np.concatenate(
            [fr.prompt, np.asarray(fr.emitted, np.int32)])
            if fr.emitted else fr.prompt)
        remaining = fr.max_new_tokens - len(fr.emitted)
        if self._place(fr, prompt, remaining):
            return True
        self._pending.append(fr)
        return False

    def retry_pending(self) -> int:
        """Drain the failover parking lot (called on every tick and
        submission); returns how many found a home. Failures re-park
        themselves (``place_handoff`` / ``_place_continuation`` both
        append back on a miss)."""
        with self._lock:
            pending, self._pending = self._pending, []
            placed = 0
            for fr in pending:
                handoff, fr._parked_handoff = fr._parked_handoff, None
                if handoff is not None:
                    ok = self.place_handoff(fr, handoff)
                else:
                    ok = self._place_continuation(fr)
                placed += int(ok)
            return placed

    # ------------------------------------------------------------------
    def unfinished(self) -> List[FleetRequest]:
        with self._lock:
            return [fr for fr in self.requests if not fr.finished]

    def stats(self) -> dict:
        with self._lock:
            return {
                "replicas": len(self.replicas),
                "alive": sum(1 for r in self.replicas if r.alive),
                "split": self.split,
                "requests": len(self.requests),
                "finished": sum(1 for fr in self.requests if fr.finished),
                "pending_failover": len(self._pending),
            }
