"""Persisted XLA compilation cache for the serving fleet's cold start.

A decode server amortizes compilation over a process lifetime, but a
FLEET amortizes it over deployments: every replica that boots compiles
the same (slot-count, prefill-bucket) program set from scratch unless the
compiled artifacts persist. ``DL4J_COMPILE_CACHE_DIR`` points jax's
persistent compilation cache at a shared directory so a cold replica
replays compiles from disk instead of paying XLA again (the
serving/training split of the TensorFlow paper: the server process is
long-lived state, and here even its *programs* outlive the process).

Configuration is LAZY — ``ensure_compile_cache()`` runs before the
serving layer's first compile, never at import (jax must not be dragged
in by control-plane imports, and the env must be readable right up to
first use). Idempotent; re-pointing at a new directory reconfigures.
"""

from __future__ import annotations

import logging
import os
import threading
from typing import Optional

logger = logging.getLogger(__name__)

__all__ = ["compile_cache_dir", "ensure_compile_cache",
           "compile_cache_stats"]

_LOCK = threading.Lock()
_CONFIGURED_DIR: Optional[str] = None


def compile_cache_dir() -> Optional[str]:
    """``DL4J_COMPILE_CACHE_DIR``: directory for jax's persistent
    compilation cache (unset = no persistence, in-process caching only)."""
    raw = os.environ.get("DL4J_COMPILE_CACHE_DIR", "").strip()
    return raw or None


def ensure_compile_cache() -> Optional[str]:
    """Point ``jax_compilation_cache_dir`` at ``DL4J_COMPILE_CACHE_DIR``
    if set, before the caller's first compile. Returns the configured
    directory (or None when the env is unset / the jax build lacks the
    knob). Every compile is persisted (min-compile-time and min-entry-
    size floors zeroed): serving cold-start wants the whole program set
    replayed, not just the slow members."""
    global _CONFIGURED_DIR
    d = compile_cache_dir()
    if d is None:
        return None
    with _LOCK:
        if _CONFIGURED_DIR == d:
            return d
        os.makedirs(d, exist_ok=True)
        import jax

        try:
            jax.config.update("jax_compilation_cache_dir", d)
        except Exception as e:  # older jax without the persistent cache
            logger.warning("DL4J_COMPILE_CACHE_DIR=%s ignored: this jax "
                           "has no jax_compilation_cache_dir (%s)", d, e)
            return None
        for knob, val in (("jax_persistent_cache_min_compile_time_secs", 0.0),
                          ("jax_persistent_cache_min_entry_size_bytes", -1)):
            try:
                jax.config.update(knob, val)
            except Exception:  # knob spelling varies across jax versions
                pass
        _CONFIGURED_DIR = d
        from deeplearning4j_tpu.monitor import record_counter, tracer

        tracer().event("serve.compile_cache", dir=d)
        record_counter("serve_compile_cache_configured_total")
        logger.info("persistent XLA compilation cache at %s", d)
        return d


def compile_cache_stats() -> dict:
    """On-disk view of the persistent cache: ``{dir, configured,
    entries, bytes}`` — what a bench artifact reports so warm-start
    claims are checkable."""
    d = compile_cache_dir()
    entries = 0
    size = 0
    if d and os.path.isdir(d):
        for root, _dirs, files in os.walk(d):
            for f in files:
                entries += 1
                try:
                    size += os.path.getsize(os.path.join(root, f))
                except OSError:
                    pass
    return {"dir": d, "configured": _CONFIGURED_DIR == d and d is not None,
            "entries": entries, "bytes": size}


def _reset_for_tests() -> None:
    global _CONFIGURED_DIR
    with _LOCK:
        _CONFIGURED_DIR = None
