"""Slot-based batched KV cache: the device-resident state of the server.

The single-request decoder (``TransformerLM.make_generate``) builds a
fresh ``prompt_len + max_new`` cache per call — right for one stream,
wrong for a server: S concurrent requests would run S separate programs
with S dispatches per emitted token. The slot pool turns that inside
out: ONE ``[L, S, T_max, Hkv, Dh]`` pair of K/V arrays lives in HBM for
the server's lifetime, each of the S slots holds one in-flight request
at its own decode position, and a single jitted step advances all of
them (``serving/engine.py``).

Slot lifecycle (the scheduler in ``serving/server.py`` drives it):

- **free** — garbage contents, cursor frozen. Safe by construction: the
  decode mask admits only keys ``<= cursor`` of slots whose rows anyone
  reads, and a freed slot's rows are never read.
- **prefill** — an admitted request's bucket-padded prompt runs one
  batched forward; its per-layer K/V land in ``[slot, 0:P_bucket)`` and
  the cursor starts at ``prompt_len`` (the pad tail ``[prompt_len,
  P_bucket)`` sits beyond the mask until generated tokens overwrite it).
- **decoding** — each step writes the consumed token's K/V at ``cursor``
  then attends keys ``<= cursor``; the cursor advances by one.
- **retired** — the request finished; the slot returns to free with its
  stale contents in place (the next prefill overwrites them, and the
  mask keeps them unreachable meanwhile).

Cursors are HOST state (plain numpy): the scheduler needs them for
admission decisions every step boundary, so keeping them device-resident
would buy one small transfer and cost a readback.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

__all__ = ["SlotKVCache"]


class SlotKVCache:
    """``[L, S, T_max, Hkv, Dh]`` K/V pools + per-slot write cursors."""

    def __init__(self, model, slots: int, max_len: Optional[int] = None):
        import jax.numpy as jnp

        if slots < 1:
            raise ValueError(f"slots={slots} must be >= 1")
        self.slots = int(slots)
        self.max_len = int(max_len or model.max_len)
        if self.max_len < 2:
            raise ValueError(f"max_len={self.max_len} must be >= 2")
        if (model.pos_encoding == "learned"
                and self.max_len > model.max_len):
            raise ValueError(
                f"max_len={self.max_len} exceeds the model's learned "
                f"position table ({model.max_len}); use "
                "pos_encoding='rope' to serve past it")
        dh = model.d_model // model.num_heads
        shape = (model.num_layers, self.slots, self.max_len,
                 model.num_kv_heads, dh)
        cdt = model.policy.compute_dtype
        self.k = jnp.zeros(shape, cdt)
        self.v = jnp.zeros(shape, cdt)
        # per-slot write cursor: the position the NEXT consumed token's
        # K/V lands at (== the absolute position of the last emitted,
        # not-yet-consumed token)
        self.cursors = np.zeros(self.slots, np.int32)

    @property
    def nbytes(self) -> int:
        """Device footprint of the pool pair (capacity planning: the
        serving analogue of the epoch cache's HBM budget)."""
        return int(self.k.nbytes) + int(self.v.nbytes)

    def swap(self, new_k, new_v) -> None:
        """Install the pools a jitted program returned (the old buffers
        were donated into it)."""
        self.k = new_k
        self.v = new_v
