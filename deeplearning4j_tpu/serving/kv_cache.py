"""Slot-based batched KV cache: the device-resident state of the server.

The single-request decoder (``TransformerLM.make_generate``) builds a
fresh ``prompt_len + max_new`` cache per call — right for one stream,
wrong for a server: S concurrent requests would run S separate programs
with S dispatches per emitted token. The slot pool turns that inside
out: ONE ``[L, S, T_max, Hkv, Dh]`` pair of K/V arrays lives in HBM for
the server's lifetime, each of the S slots holds one in-flight request
at its own decode position, and a single jitted step advances all of
them (``serving/engine.py``).

Slot lifecycle (the scheduler in ``serving/server.py`` drives it):

- **free** — garbage contents, cursor frozen. Safe by construction: the
  decode mask admits only keys ``<= cursor`` of slots whose rows anyone
  reads, and a freed slot's rows are never read.
- **prefill** — an admitted request's bucket-padded prompt runs one
  batched forward; its per-layer K/V land in ``[slot, 0:P_bucket)`` and
  the cursor starts at ``prompt_len`` (the pad tail ``[prompt_len,
  P_bucket)`` sits beyond the mask until generated tokens overwrite it).
- **decoding** — each step writes the consumed token's K/V at ``cursor``
  then attends keys ``<= cursor``; the cursor advances by one.
- **retired** — the request finished; the slot returns to free with its
  stale contents in place (the next prefill overwrites them, and the
  mask keeps them unreachable meanwhile).

Cursors are a DEVICE ``[S]`` int32 array: the fused multi-token decode
program (``("decode_fused", S, K)``) advances them in-program across K
scan steps — per-slot active masks freeze retired/short slots mid-scan —
so the host never reads them back. The scheduler's admission decisions
come from its own slot table (which request occupies which slot), not
from cursor values; cursor writes happen only at fusion boundaries
(``set_cursor`` at prefill, ``advance`` on the unfused K=1 path).

Quantized pool (``DL4J_SERVE_KV_DTYPE`` / ``kv_dtype=``): the pool is
the dominant HBM term at high slot counts, so the store dtype is a
capacity lever — ``float32``, ``bfloat16``, or ``int8``. int8 keeps
per-(layer, slot, head) absmax scales beside the pool (f32 ``[L, S,
Hkv]``, a ``1/(T_max·Dh)``-sized sidecar) and dequantizes inside the
attention body; the pool shrinks 4x vs f32 and ``max_slots_in_budget``
rises accordingly. Scales are running maxima: a write whose absmax
exceeds the slot-head's scale requantizes that row in-program
(``requant_write_slab``), so streamed decode writes never clip.
"""

from __future__ import annotations

import os
from typing import Optional

import numpy as np

from deeplearning4j_tpu.analysis.annotations import traced

__all__ = [
    "SlotKVCache",
    "resolve_kv_dtype",
    "kv_pool_nbytes",
    "max_slots_in_budget",
    "dequant_slab",
    "requant_write_slab",
]

_KV_DTYPES = ("float32", "bfloat16", "int8")
_ALIASES = {"f32": "float32", "bf16": "bfloat16"}


def resolve_kv_dtype(kv_dtype: Optional[str], model) -> str:
    """Canonical store-dtype name for the pool: an explicit ``kv_dtype``
    wins, else ``DL4J_SERVE_KV_DTYPE``, else the model's compute dtype
    (the pre-quantization default — today's behavior, bitwise)."""
    raw = kv_dtype
    if raw is None:
        raw = os.environ.get("DL4J_SERVE_KV_DTYPE", "").strip() or None
    if raw is None:
        import jax.numpy as jnp

        return str(jnp.dtype(model.policy.compute_dtype))
    name = _ALIASES.get(str(raw).lower(), str(raw).lower())
    if name not in _KV_DTYPES:
        raise ValueError(
            f"kv_dtype={raw!r} must be one of {_KV_DTYPES} "
            "(DL4J_SERVE_KV_DTYPE)")
    return name


def _elem_bytes(name: str) -> int:
    return {"float32": 4, "bfloat16": 2, "int8": 1}.get(name, 4)


def _pool_dims(model, slots: int, max_len: int):
    dh = model.d_model // model.num_heads
    return (model.num_layers, slots, max_len, model.num_kv_heads, dh)


def kv_pool_nbytes(model, slots: int, max_len: Optional[int] = None,
                   kv_dtype: Optional[str] = None) -> int:
    """Analytic device footprint of the K/V pool pair (+ int8 scale
    sidecars) — the serving term of the HBM budget model. Matches
    ``SlotKVCache.nbytes`` exactly (asserted in tests)."""
    name = resolve_kv_dtype(kv_dtype, model)
    ll, ss, tt, hkv, dh = _pool_dims(model, slots,
                                     int(max_len or model.max_len))
    total = 2 * ll * ss * tt * hkv * dh * _elem_bytes(name)
    if name == "int8":
        total += 2 * ll * ss * hkv * 4  # f32 per-(layer, slot, head) scales
    return total


def max_slots_in_budget(model, max_len: int, budget_bytes: int,
                        kv_dtype: Optional[str] = None) -> int:
    """How many concurrent slots an HBM budget can hold at ``max_len``
    context — the capacity planning answer quantization multiplies
    (int8 fits ~4x the slots of float32)."""
    per_slot = kv_pool_nbytes(model, 1, max_len, kv_dtype)
    return max(0, int(budget_bytes) // per_slot)


# ---------------------------------------------------------------------------
# int8 codec: traced helpers the engine's program bodies call
# ---------------------------------------------------------------------------
@traced
def dequant_slab(slab, scale, dtype):
    """Dequantize one layer's pool slab ``[S, T, Hkv, Dh]`` to ``dtype``
    for the attention body. ``scale is None`` = unquantized store (the
    slab IS the values; cast only if the store dtype differs)."""
    import jax.numpy as jnp

    if scale is None:
        return slab if slab.dtype == dtype else slab.astype(dtype)
    return (slab.astype(jnp.float32)
            * (scale[:, None, :, None] / 127.0)).astype(dtype)


@traced
def requant_write_slab(slab, scale, values, rows, positions):
    """Write ``values [S, q, Hkv, Dh]`` at ``(rows [S], positions
    [S, q])`` into one layer's slab; returns ``(slab, scale)``.

    Unquantized (``scale is None``): a plain scatter in the store dtype.
    int8: per-(slot, head) running-absmax scales — when a write's absmax
    exceeds the stored scale, the slot-head's existing entries are
    requantized to the grown scale in the same program (slots whose
    scale did not grow multiply by exactly 1.0 — an int8→f32→round→int8
    identity), then the new values quantize and scatter. Out-of-range
    scatter positions (frozen slots riding along near ``T_max``) are
    dropped by XLA's scatter semantics, never written."""
    import jax.numpy as jnp

    if scale is None:
        return slab.at[rows[:, None], positions].set(
            values.astype(slab.dtype)), None
    from jax import lax

    vals = values.astype(jnp.float32)
    m = jnp.max(jnp.abs(vals), axis=(1, 3))                 # [S, Hkv]
    new_scale = jnp.maximum(scale, m)
    denom = jnp.where(new_scale > 0, new_scale, 1.0)
    factor = jnp.where(new_scale > 0, scale / denom, 1.0)
    # the requant pass rewrites the whole slab, so gate it on any scale
    # actually growing: in the steady state (absmax already seen) every
    # factor is 1.0 and the identity rewrite would burn a full
    # pool-read+write of bandwidth per layer per step for nothing —
    # cond keeps the common case scatter-only
    slab = lax.cond(
        jnp.any(new_scale > scale),
        lambda s: jnp.round(s.astype(jnp.float32)
                            * factor[:, None, :, None]).astype(jnp.int8),
        lambda s: s,
        slab)
    q = jnp.clip(jnp.round(vals / denom[:, None, :, None] * 127.0),
                 -127, 127).astype(jnp.int8)
    return slab.at[rows[:, None], positions].set(q), new_scale


class SlotKVCache:
    """``[L, S, T_max, Hkv, Dh]`` K/V pools + device per-slot cursors."""

    # validate_cache_budget (monitor/memory.py) prices any cache as
    # nbytes/n_shard vs measured per-device bytes; the slot pool is
    # single-replica device state
    n_shard = 1

    def __init__(self, model, slots: int, max_len: Optional[int] = None,
                 kv_dtype: Optional[str] = None, registry=None):
        """``registry=`` (a ``ShardingRegistry``) shards the pool over the
        mesh ``model`` axis with the SAME head split the attention params
        use — each TP shard holds ``Hkv/tp`` heads of every slot, so the
        pool budget (``nbytes / n_shard``) becomes per-shard."""
        import jax.numpy as jnp

        if slots < 1:
            raise ValueError(f"slots={slots} must be >= 1")
        self.slots = int(slots)
        self.max_len = int(max_len or model.max_len)
        if self.max_len < 2:
            raise ValueError(f"max_len={self.max_len} must be >= 2")
        if (model.pos_encoding == "learned"
                and self.max_len > model.max_len):
            raise ValueError(
                f"max_len={self.max_len} exceeds the model's learned "
                f"position table ({model.max_len}); use "
                "pos_encoding='rope' to serve past it")
        self.kv_dtype = resolve_kv_dtype(kv_dtype, model)
        shape = _pool_dims(model, self.slots, self.max_len)
        if self.kv_dtype == "int8":
            self.k = jnp.zeros(shape, jnp.int8)
            self.v = jnp.zeros(shape, jnp.int8)
            self.k_scale = jnp.zeros(shape[:2] + (shape[3],), jnp.float32)
            self.v_scale = jnp.zeros(shape[:2] + (shape[3],), jnp.float32)
        else:
            self.k = jnp.zeros(shape, jnp.dtype(self.kv_dtype))
            self.v = jnp.zeros(shape, jnp.dtype(self.kv_dtype))
            self.k_scale = None
            self.v_scale = None
        # per-slot write cursor: the position the NEXT consumed token's
        # K/V lands at (== the absolute position of the last emitted,
        # not-yet-consumed token). DEVICE state: the fused decode scan
        # advances it in-program; the host only writes it at fusion
        # boundaries and never reads it back.
        self.cursors = jnp.zeros(self.slots, jnp.int32)
        self.registry = registry
        if registry is not None:
            import jax
            from jax.sharding import PartitionSpec as P

            from deeplearning4j_tpu.parallel.sharding_registry import (
                model_axis_size, named, replicated_sharding)

            pool_spec = registry.kv_pool_spec(model.num_kv_heads)
            pool = named(registry.mesh, pool_spec)
            self.k = jax.device_put(self.k, pool)
            self.v = jax.device_put(self.v, pool)
            if self.k_scale is not None:
                sc = named(registry.mesh,
                           registry.kv_scale_spec(model.num_kv_heads))
                self.k_scale = jax.device_put(self.k_scale, sc)
                self.v_scale = jax.device_put(self.v_scale, sc)
            self.cursors = jax.device_put(
                self.cursors, replicated_sharding(registry.mesh))
            if pool_spec != P():
                # instance attr shadows the class default 1:
                # validate_cache_budget prices nbytes/n_shard per device
                self.n_shard = model_axis_size(registry.mesh)

    @property
    def quantized(self) -> bool:
        return self.k_scale is not None

    @property
    def state(self) -> dict:
        """The pool pytree a jitted program consumes (and is donated):
        ``{k, v}`` plus the int8 scale sidecars when quantized."""
        st = {"k": self.k, "v": self.v}
        if self.k_scale is not None:
            st["k_scale"] = self.k_scale
            st["v_scale"] = self.v_scale
        return st

    def install(self, state: dict) -> None:
        """Install the pool state a jitted program returned (the old
        buffers were donated into it)."""
        self.k = state["k"]
        self.v = state["v"]
        self.k_scale = state.get("k_scale")
        self.v_scale = state.get("v_scale")

    def set_cursor(self, slot: int, value: int) -> None:
        """Admission-boundary cursor write (prefill lands a request)."""
        import jax.numpy as jnp

        self.cursors = self.cursors.at[slot].set(jnp.int32(value))

    def advance(self, live_mask) -> None:
        """Unfused (K=1) path: advance live slots' cursors by one after
        a decode dispatch. Fused programs advance cursors in-program."""
        import jax.numpy as jnp

        self.cursors = self.cursors + jnp.asarray(
            np.asarray(live_mask, np.int32))

    @property
    def nbytes(self) -> int:
        """Device footprint of the pool state (capacity planning: the
        serving analogue of the epoch cache's HBM budget). Includes the
        int8 scale sidecars."""
        total = int(self.k.nbytes) + int(self.v.nbytes)
        if self.k_scale is not None:
            total += int(self.k_scale.nbytes) + int(self.v_scale.nbytes)
        return total

    @property
    def per_slot_nbytes(self) -> int:
        """The pool bytes one concurrent request costs — what int8
        shrinks ~4x vs float32 (max concurrency multiplies by the
        inverse)."""
        return self.nbytes // self.slots
