"""Batched decode engine: the jitted programs behind the decode server.

A bounded program set serves any request stream, and the engine never
compiles outside it:

- ``("prefill", P_bucket)`` — one bucket-padded prompt forward ([1, P])
  through the SAME ``TransformerLM._block`` math as training, writing the
  per-layer K/V into one slot of the ``[L, S, T_max, Hkv, Dh]`` pool and
  sampling the request's first token from position ``prompt_len - 1``.
  One compile per prompt-ladder rung (``perf/bucketing.prompt_bucket``).
- ``("decode", S)`` — ONE step for ALL S slots at their own positions:
  scatter the consumed tokens' K/V at each slot's cursor, attend each row
  against its own masked cache history (GQA-aware — the pool stores
  ``num_kv_heads``), sample one token per row from per-slot RNG streams.
  The ``fuse_steps=1`` path: one dispatch per token, exactly the PR-10
  program.
- ``("decode_fused", S, K)`` — K decode steps as one ``lax.scan``: the
  single-step body runs K times in-program (per-slot cursors advance on
  device, RNG streams split in-program, K/V scatters land per step) and
  the host sees ONE dispatch + one ``[K, S]`` token block per K tokens.
  Per-slot ``remaining`` counts freeze retired/short slots mid-scan: a
  frozen slot's token/cursor/key carry unchanged while its rows ride
  along computing garbage no one reads.
- ``("decode_spec", S, K, G)`` — speculative decoding: K rounds per
  dispatch, each round drafting G tokens with the draft model (its own
  slot pool, positions derived from the shared cursors), verifying all
  G+1 candidates with ONE multi-token target forward
  (``_serve_verify_impl``), and accept/resample-ing per the standard
  speculative-sampling rule — greedy streams are token-identical to the
  target model's greedy decode, sampled streams draw from the target
  model's exact sampling distribution. Each round emits ``accepted + 1``
  tokens per slot (the +1 is the target's correction/bonus token), so
  accepted-tokens/dispatch — the headline serve metric — exceeds 1
  whenever the draft agrees at all.

All program bodies are ``@traced`` hot roots
(``analysis/annotations.HOT_PATH_REGISTRY``) so dl4j-lint's host-sync
rule guards the decode loop: a ``float()`` / ``np.asarray`` slipped into
this module's program bodies is a lint finding, not a silent per-token
device sync. The one sanctioned readback is the per-dispatch token block
in ``server.py``.

Numerics contract (tests/test_serving.py): a slot's token sequence is
IDENTICAL to ``TransformerLM.generate`` on the same prompt — greedy and
sampled (each slot replays the exact ``sample``/``split`` chain of a
single-request ``generate(seed=...)``), at every ``fuse_steps`` and
under greedy speculative decoding. Slot rows are computationally
independent (every op is row-wise; masked pad keys contribute exactly
zero attention weight), so batching requests changes no request's
tokens. Quantized pools (``kv_dtype="int8"``) trade bounded logit error
(``<= absmax/127`` per K/V element) for 4x capacity.
"""

from __future__ import annotations

import functools
from typing import Dict, Optional, Sequence, Tuple

import numpy as np

from deeplearning4j_tpu.analysis.annotations import traced
from deeplearning4j_tpu.perf.bucketing import (
    DEFAULT_PROMPT_BUCKETS, pad_prompt, prompt_bucket)
from deeplearning4j_tpu.serving.compile_cache import ensure_compile_cache
from deeplearning4j_tpu.serving.kv_cache import (
    SlotKVCache, dequant_slab, requant_write_slab)

__all__ = ["DecodeEngine"]


def _row_sampler(temperature: float, top_k: Optional[int]):
    """Per-row sampler ``(logits [V], key [2]) -> (tok, key)`` replaying
    the exact op sequence of ``make_generate``'s batch-of-one ``sample``
    (logits lifted to [1, V] so the categorical draw consumes the same
    random bits a single-request decode would). Filtering goes through
    ``_filtered_logits_fn`` — the SAME ops the speculative accept-ratio
    distributions use, so q(d) is by construction the probability the
    sampler draws ``d`` with (the two cannot drift)."""
    import jax
    import jax.numpy as jnp

    filt = (None if temperature == 0.0
            else _filtered_logits_fn(temperature, top_k))

    def one(logits, key):
        if temperature == 0.0:
            return jnp.argmax(logits[None], axis=-1)[0].astype(jnp.int32), \
                key
        scaled = filt(logits[None])
        key, sub = jax.random.split(key)
        return jax.random.categorical(sub, scaled, axis=-1)[0].astype(
            jnp.int32), key

    return one


def _filtered_logits_fn(temperature: float, top_k: Optional[int]):
    """Vectorized ``logits [..., V] -> filtered scaled logits`` — the
    argument ``sample``'s categorical draws from, shared by the draft
    proposal draw and the accept-ratio distributions so q(d) is exactly
    the probability the draft sampled ``d`` with."""
    import jax.numpy as jnp
    from jax import lax

    def f(logits):
        scaled = logits / temperature
        if top_k is not None:
            kth = lax.top_k(scaled, top_k)[0][..., -1:]
            scaled = jnp.where(scaled >= kth, scaled, -jnp.inf)
        return scaled

    return f


@traced
def _serve_prefill_impl(model, sample_row, quantized, params, kv,
                        prompt, prompt_len, slot, key):
    """Prefill one bucket-padded prompt ([1, P]) into pool slot ``slot``.

    Causality makes the pad tail inert: position ``i < prompt_len``
    attends keys ``0..i`` — all real tokens — so the K/V written at real
    positions (and the ``prompt_len - 1`` hidden state the first token is
    sampled from) are the unpadded prefill's values. ``prompt_len`` and
    ``slot`` are traced: one compile per bucket, not per request.

    Quantized pools: the slot's per-(layer, head) scales RESET here to
    the prompt K/V absmax (pad positions masked out of the max — their
    quantized garbage clips and sits beyond the cursor until real decode
    writes requantize past it), so a recycled slot never inherits a
    stale scale."""
    import jax.numpy as jnp
    from jax import lax

    policy = model.policy
    cdt = policy.compute_dtype
    p = prompt.shape[1]
    h = jnp.take(params["embed"], prompt, axis=0)
    if model.pos_encoding == "learned":
        h = h + params["pos"][:p][None]
    h = policy.cast_compute(h)
    ks, vs = [], []
    for blk in params["blocks"]:
        h, kk, vv = model._block(blk, h)
        ks.append(kk.astype(cdt))
        vs.append(vv.astype(cdt))
    kcat = jnp.stack(ks)                     # [L, 1, P, Hkv, Dh]
    vcat = jnp.stack(vs)
    if quantized:
        real = (jnp.arange(p) < prompt_len)[None, None, :, None, None]

        def quant(cat, pool, scale):
            m = jnp.max(jnp.where(real, jnp.abs(cat.astype(jnp.float32)),
                                  0.0), axis=(1, 2, 4))     # [L, Hkv]
            denom = jnp.where(m > 0, m, 1.0)
            q = jnp.clip(jnp.round(cat.astype(jnp.float32)
                                   / denom[:, None, None, :, None]
                                   * 127.0), -127, 127).astype(jnp.int8)
            pool = lax.dynamic_update_slice(pool, q, (0, slot, 0, 0, 0))
            scale = lax.dynamic_update_slice(
                scale, m[:, None, :], (0, slot, 0))
            return pool, scale

        pool_k, k_scale = quant(kcat, kv["k"], kv["k_scale"])
        pool_v, v_scale = quant(vcat, kv["v"], kv["v_scale"])
        new_kv = {"k": pool_k, "v": pool_v,
                  "k_scale": k_scale, "v_scale": v_scale}
    else:
        new_kv = {
            "k": lax.dynamic_update_slice(
                kv["k"], kcat.astype(kv["k"].dtype), (0, slot, 0, 0, 0)),
            "v": lax.dynamic_update_slice(
                kv["v"], vcat.astype(kv["v"].dtype), (0, slot, 0, 0, 0)),
        }
    h_last = jnp.take(h[0], prompt_len - 1, axis=0)        # [D]
    tok, key = sample_row(model._unembed(params, h_last), key)
    return tok, key, new_kv


def _decode_step_body(model, params, kv, tok, positions):
    """ONE decode forward for all S slots: consume ``tok[s]`` at
    ``positions[s]``, write its (de/re)quantized K/V at that cursor,
    attend keys ``<= positions[s]`` (window-clipped like training).
    Returns ``(logits [S, V], new_kv)`` — sampling happens in the
    callers so the draft path can keep the proposal distribution. Free
    slots ride along computing garbage no one reads — their rows are
    masked out of nothing (rows are independent) and their pool writes
    land at frozen cursors the admission prefill overwrites."""
    import jax.numpy as jnp
    from deeplearning4j_tpu.ops.attention import grouped_query_attention

    policy = model.policy
    cdt = policy.compute_dtype
    s = tok.shape[0]
    t_max = kv["k"].shape[2]
    k_scale = kv.get("k_scale")
    v_scale = kv.get("v_scale")
    h = jnp.take(params["embed"], tok, axis=0)             # [S, D]
    if model.pos_encoding == "learned":
        h = h + params["pos"][positions]
    h = policy.cast_compute(h)[:, None, :]                 # [S, 1, D]
    live = jnp.arange(t_max)[None, :] <= positions[:, None]
    if model.attn_window is not None:
        live &= (jnp.arange(t_max)[None, :]
                 > positions[:, None] - model.attn_window)
    new_k, new_v, new_ks, new_vs = [], [], [], []
    rows = jnp.arange(s)

    def cached_attention(li):
        def attn(q, kk, vv):
            ck, cks = requant_write_slab(
                kv["k"][li], None if k_scale is None else k_scale[li],
                kk, rows, positions[:, None])
            cv, cvs = requant_write_slab(
                kv["v"][li], None if v_scale is None else v_scale[li],
                vv, rows, positions[:, None])
            new_k.append(ck)
            new_v.append(cv)
            if cks is not None:
                new_ks.append(cks)
                new_vs.append(cvs)
            return grouped_query_attention(
                q, dequant_slab(ck, cks, cdt), dequant_slab(cv, cvs, cdt),
                mask=live)
        return attn

    for li, blk in enumerate(params["blocks"]):
        h, _, _ = model._block(blk, h, attention=cached_attention(li),
                               positions=positions[:, None])
    logits = model._unembed(params, h[:, 0])               # [S, V]
    out = {"k": jnp.stack(new_k), "v": jnp.stack(new_v)}
    if new_ks:
        out["k_scale"] = jnp.stack(new_ks)
        out["v_scale"] = jnp.stack(new_vs)
    return logits, out


@traced
def _serve_decode_impl(model, sample_row, params, kv, tok, positions,
                       keys):
    """The PR-10 single-step program: one batched forward + per-slot
    sampling. One host dispatch per token — the ``fuse_steps=1`` path,
    kept bitwise."""
    import jax

    logits, new_kv = _decode_step_body(model, params, kv, tok, positions)
    toks, keys = jax.vmap(sample_row)(logits, keys)
    return toks, keys, new_kv


@traced
def _serve_decode_fused_impl(model, sample_row, k_steps, params, kv,
                             cursors, tok, remaining, keys):
    """K decode steps as ONE ``lax.scan``: sampling, per-slot RNG
    splits, K/V scatter writes, and cursor advancement all move
    in-program. ``remaining[s]`` tokens still owed per slot gates an
    active mask each step: a slot that hits zero mid-scan self-freezes —
    token/key/cursor/remaining carry unchanged (its rows still compute,
    writing garbage at its frozen cursor: a position beyond its mask
    that the next prefill rewrites). Emits the ``[K, S]`` token block;
    rows past a slot's remaining repeat its final token and the host
    truncates by its own bookkeeping."""
    import jax.numpy as jnp
    from jax import lax

    def body(carry, _):
        kv, cursors, tok, remaining, keys = carry
        act = remaining > 0
        ntok, nkeys, nkv = _serve_decode_impl(
            model, sample_row, params, kv, tok, cursors, keys)
        tok = jnp.where(act, ntok, tok)
        keys = jnp.where(act[:, None], nkeys, keys)
        cursors = jnp.where(act, cursors + 1, cursors)
        remaining = jnp.where(act, remaining - 1, remaining)
        return (nkv, cursors, tok, remaining, keys), tok

    (kv, cursors, _, _, keys), toks = lax.scan(
        body, (kv, cursors, tok, remaining, keys), None, length=k_steps)
    return toks, cursors, keys, kv


@traced
def _serve_verify_impl(model, params, kv, toks, positions):
    """Multi-token target forward for the speculative verify: consume
    ``toks [S, Q]`` at per-row ``positions [S, Q]`` against the slot
    pool, scatter-writing every candidate's K/V at its position (the
    accepted prefix becomes permanent; rejected tails sit beyond the
    rewound cursor, masked until overwritten). Per-query masks keep
    causality at ragged per-slot offsets: query q attends pool keys
    ``<= positions[s, q]``. Returns ``(logits [S, Q, V], new_kv)``."""
    import jax.numpy as jnp
    from deeplearning4j_tpu.ops.attention import grouped_query_attention

    policy = model.policy
    cdt = policy.compute_dtype
    s = toks.shape[0]
    t_max = kv["k"].shape[2]
    k_scale = kv.get("k_scale")
    v_scale = kv.get("v_scale")
    h = jnp.take(params["embed"], toks, axis=0)            # [S, Q, D]
    if model.pos_encoding == "learned":
        h = h + params["pos"][positions]
    h = policy.cast_compute(h)
    live = (jnp.arange(t_max)[None, None, :]
            <= positions[:, :, None])                      # [S, Q, T]
    if model.attn_window is not None:
        live &= (jnp.arange(t_max)[None, None, :]
                 > positions[:, :, None] - model.attn_window)
    new_k, new_v, new_ks, new_vs = [], [], [], []
    rows = jnp.arange(s)

    def cached_attention(li):
        def attn(q, kk, vv):
            ck, cks = requant_write_slab(
                kv["k"][li], None if k_scale is None else k_scale[li],
                kk, rows, positions)
            cv, cvs = requant_write_slab(
                kv["v"][li], None if v_scale is None else v_scale[li],
                vv, rows, positions)
            new_k.append(ck)
            new_v.append(cv)
            if cks is not None:
                new_ks.append(cks)
                new_vs.append(cvs)
            return grouped_query_attention(
                q, dequant_slab(ck, cks, cdt), dequant_slab(cv, cvs, cdt),
                mask=live)
        return attn

    for li, blk in enumerate(params["blocks"]):
        h, _, _ = model._block(blk, h, attention=cached_attention(li),
                               positions=positions)
    logits = model._unembed(params, h)                     # [S, Q, V]
    out = {"k": jnp.stack(new_k), "v": jnp.stack(new_v)}
    if new_ks:
        out["k_scale"] = jnp.stack(new_ks)
        out["v_scale"] = jnp.stack(new_vs)
    return logits, out


@traced
def _serve_spec_impl(model, draft_model, sample_filtered, gamma, greedy,
                     k_rounds, params, draft_params, kv, draft_kv,
                     cursors, tok, remaining, keys, draft_keys):
    """K speculative rounds as ONE program. Per round and live slot:

    1. **draft** — ``gamma + 1`` draft-model steps from the shared
       cursors (step j consumes candidate j-1), proposing ``d_1..d_G``
       and writing every candidate's draft K/V so the draft pool covers
       the accepted prefix whatever the acceptance turns out to be (the
       G+1-th step writes ``d_G``'s K/V; its proposal is discarded).
    2. **verify** — ONE target forward over ``[tok, d_1..d_G]`` at
       positions ``c..c+G`` (``_serve_verify_impl``), yielding target
       distributions for every candidate plus the bonus position.
    3. **accept/resample** — greedy: accept the longest prefix where the
       target's argmax equals the proposal, then emit the target's own
       next token (token-identity with unassisted greedy decode by
       construction). Sampled: the standard speculative-sampling rule —
       accept ``d_i`` with probability ``min(1, p(d_i)/q(d_i))``, on the
       first rejection resample from ``norm(max(p - q, 0))``, after full
       acceptance sample the bonus from ``p`` — which draws from the
       target model's exact (temperature/top-k filtered) distribution.

    Cursors advance by ``accepted + 1``; the draft pool needs no cursor
    of its own (positions derive from the shared cursors, and rejected
    candidates' draft K/V sit beyond the rewound cursor exactly like the
    target pool's). Emits ``[K, S, G + 2]`` blocks: per round,
    ``[count, e_1..e_{G+1}]`` per slot (count = 0 for frozen slots)."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    i32 = jnp.int32

    def round_body(carry, _):
        kv, draft_kv, cursors, tok, remaining, keys, draft_keys = carry
        act = remaining > 0

        # ---- draft: propose gamma candidates, write gamma+1 K/V
        def dstep(dc, i):
            dkv, dtok, dkeys = dc
            logits, dkv = _decode_step_body(
                draft_model, draft_params, dkv, dtok, cursors + i)
            if greedy:
                prop = jnp.argmax(logits, axis=-1).astype(i32)
                qdist = logits  # unused; placeholder keeps the scan pytree
            else:
                scaled = sample_filtered(logits)           # [S, V]
                qdist = jax.nn.softmax(scaled, axis=-1)

                def draw(key, lg):
                    key, sub = jax.random.split(key)
                    return key, jax.random.categorical(sub, lg)

                dkeys, prop = jax.vmap(draw)(dkeys, scaled)
                prop = prop.astype(i32)
            return (dkv, prop, dkeys), (prop, qdist)

        (draft_kv, _, draft_keys), (props, qdists) = lax.scan(
            dstep, (draft_kv, tok, draft_keys), jnp.arange(gamma + 1))
        d = jnp.swapaxes(props[:gamma], 0, 1)              # [S, G]

        # ---- verify: one multi-token target forward over tok + d_1..d_G
        vtoks = jnp.concatenate([tok[:, None], d], axis=1)  # [S, G+1]
        vpos = cursors[:, None] + jnp.arange(gamma + 1)[None, :]
        logits, kv = _serve_verify_impl(model, params, kv, vtoks, vpos)

        # ---- accept / resample
        if greedy:
            t = jnp.argmax(logits, axis=-1).astype(i32)    # [S, G+1]
            accept = t[:, :gamma] == d                     # [S, G]
            a = jnp.sum(jnp.cumprod(accept.astype(i32), axis=1), axis=1)
            corr = jnp.take_along_axis(t, a[:, None], axis=1)[:, 0]
        else:
            p = jax.nn.softmax(sample_filtered(logits), axis=-1)
            q = jnp.swapaxes(qdists[:gamma], 0, 1)         # [S, G, V]
            p_d = jnp.take_along_axis(
                p[:, :gamma], d[..., None], axis=-1)[..., 0]
            q_d = jnp.take_along_axis(q, d[..., None], axis=-1)[..., 0]

            def consume(key):
                key, su = jax.random.split(key)
                u = jax.random.uniform(su, (gamma,))
                key, sc = jax.random.split(key)
                return key, u, sc

            keys, us, subs = jax.vmap(consume)(keys)
            # u < min(1, p/q)  <=>  u*q < p  (q=0 proposals never drawn)
            accept = us * q_d < p_d
            a = jnp.sum(jnp.cumprod(accept.astype(i32), axis=1), axis=1)
            p_a = jnp.take_along_axis(
                p, a[:, None, None], axis=1)[:, 0]         # [S, V]
            q_pad = jnp.concatenate(
                [q, jnp.zeros_like(q[:, :1])], axis=1)
            q_a = jnp.take_along_axis(
                q_pad, a[:, None, None], axis=1)[:, 0]
            res = jnp.maximum(p_a - q_a, 0.0)
            has_res = jnp.sum(res, axis=-1, keepdims=True) > 0
            res = jnp.where(has_res, res, p_a)
            corr = jax.vmap(
                lambda s_, r: jax.random.categorical(
                    s_, jnp.log(jnp.maximum(r, 1e-38))))(subs, res)
            corr = corr.astype(i32)

        count = jnp.where(act, a + 1, 0).astype(i32)
        idx = jnp.arange(gamma + 1)[None, :]
        d_pad = jnp.concatenate(
            [d, jnp.zeros_like(d[:, :1])], axis=1)         # [S, G+1]
        emit = jnp.where(idx < a[:, None], d_pad,
                         jnp.where(idx == a[:, None], corr[:, None], 0))
        block = jnp.concatenate([count[:, None], emit], axis=1)

        tok = jnp.where(act, corr, tok)
        cursors = jnp.where(act, cursors + count, cursors)
        remaining = jnp.where(act, jnp.maximum(remaining - count, 0),
                              remaining)
        return (kv, draft_kv, cursors, tok, remaining, keys,
                draft_keys), block

    (kv, draft_kv, cursors, _, _, keys, draft_keys), blocks = lax.scan(
        round_body, (kv, draft_kv, cursors, tok, remaining, keys,
                     draft_keys), None, length=k_rounds)
    return blocks, cursors, keys, draft_keys, kv, draft_kv


class DecodeEngine:
    """Owns the slot pool(s) + the per-signature program cache.

    ``temperature``/``top_k`` are server-level (baked into the compiled
    programs — a per-request sampling config would be a program
    signature per config, exactly the recompile hazard the server
    exists to avoid); per-request randomness rides in per-slot keys.

    Speculative decoding: pass ``draft_layers=n`` for a shallow self-
    draft (the target's first n blocks + its final norm/unembedding —
    zero extra parameters) or ``draft_model=`` for an independently
    trained draft ``TransformerLM`` (same vocab). Either builds a second
    slot pool for the draft's K/V on the same slot machinery.
    """

    def __init__(self, model, slots: int, *,
                 max_len: Optional[int] = None,
                 temperature: float = 0.0, top_k: Optional[int] = None,
                 buckets: Optional[Sequence[int]] = None,
                 kv_dtype: Optional[str] = None,
                 draft_model=None, draft_layers: int = 0,
                 spec_tokens: int = 3, mesh=None):
        if temperature < 0.0:
            raise ValueError(f"temperature={temperature} must be >= 0")
        if top_k is not None and not 1 <= top_k <= model.vocab_size:
            raise ValueError(
                f"top_k={top_k} must be in [1, vocab={model.vocab_size}]")
        model._ensure_init()
        self.model = model
        # ``mesh=`` serves tensor-parallel: the model's sharding registry
        # (the SAME Megatron specs training uses) places the params over
        # ``model`` and the slot pool shards its head axis to match —
        # decode/prefill programs are partitioned by GSPMD from the input
        # shardings, so a model bigger than one chip's HBM serves on a
        # TP slice with token-identical greedy streams.
        self.mesh = mesh
        self.registry = None
        if mesh is not None:
            from deeplearning4j_tpu.parallel.sharding_registry import (
                ShardingRegistry)

            self.registry = ShardingRegistry.for_transformer(model, mesh)
            model.params = self.registry.place(model.params)
        self.cache = SlotKVCache(model, slots, max_len, kv_dtype,
                                 registry=self.registry)
        self.slots = self.cache.slots
        self.max_len = self.cache.max_len
        self.kv_dtype = self.cache.kv_dtype
        self.temperature = float(temperature)
        self.top_k = top_k
        self.buckets = tuple(b for b in (buckets or DEFAULT_PROMPT_BUCKETS)
                             if b <= self.max_len) or (self.max_len,)
        self._sample_row = _row_sampler(self.temperature, top_k)
        self._programs: Dict[tuple, object] = {}
        self.program_builds = 0

        # ---- speculative-decoding configuration
        if draft_model is not None and draft_layers:
            raise ValueError(
                "pass draft_model= OR draft_layers=, not both")
        self.spec_tokens = int(spec_tokens)
        if self.spec_tokens < 1:
            raise ValueError(f"spec_tokens={spec_tokens} must be >= 1")
        self.draft_model = None
        if draft_layers:
            if not 1 <= draft_layers <= model.num_layers:
                raise ValueError(
                    f"draft_layers={draft_layers} must be in "
                    f"[1, num_layers={model.num_layers}]")
            self.draft_model = self._shallow_draft(model, draft_layers)
        elif draft_model is not None:
            draft_model._ensure_init()
            if draft_model.vocab_size != model.vocab_size:
                raise ValueError(
                    f"draft vocab {draft_model.vocab_size} != target "
                    f"vocab {model.vocab_size}")
            self.draft_model = draft_model
        self.draft_cache = None
        if self.draft_model is not None:
            draft_reg = self.registry
            if self.registry is not None and draft_model is not None:
                # independent draft: its own registry (own layer count /
                # head split); the shallow self-draft shares the target's
                # already-placed buffers, so the target registry applies
                from deeplearning4j_tpu.parallel.sharding_registry import (
                    ShardingRegistry)

                draft_reg = ShardingRegistry.for_transformer(
                    self.draft_model, self.mesh)
                self.draft_model.params = draft_reg.place(
                    self.draft_model.params)
            # same slot count/positions as the target pool (the
            # SlotKVCache ctor re-validates learned-table capacity for
            # the draft's own position table)
            self.draft_cache = SlotKVCache(
                self.draft_model, self.slots, self.max_len, kv_dtype,
                registry=draft_reg)
        # the fleet story: point jax's persistent compilation cache at
        # DL4J_COMPILE_CACHE_DIR before this engine's first compile
        ensure_compile_cache()

    @property
    def spec(self) -> bool:
        return self.draft_model is not None

    @staticmethod
    def _shallow_draft(model, n: int):
        """Self-draft by layer truncation: the target's first ``n``
        blocks + its embedding/position/final-norm/unembedding, sharing
        the target's parameter buffers (a view, not a copy)."""
        from deeplearning4j_tpu.models.transformer import TransformerLM

        cfg = dict(model.get_config())
        cfg["num_layers"] = n
        draft = TransformerLM(**cfg)
        draft.params = {k: v for k, v in model.params.items()
                        if k != "blocks"}
        draft.params["blocks"] = model.params["blocks"][:n]
        return draft

    # ------------------------------------------------------------------
    def _program(self, sig: tuple, factory):
        """One jitted program per signature for the engine's lifetime —
        the build count IS the compile count (fixed shapes per
        signature), mirrored into the registry so the bench and the
        soak test can assert flatness after warmup."""
        fn = self._programs.get(sig)
        if fn is None:
            from deeplearning4j_tpu.monitor import record_counter

            fn = self._programs[sig] = factory()
            self.program_builds += 1
            record_counter("serve_program_builds_total", kind=sig[0])
        return fn

    def compile_counts(self) -> dict:
        """``{decode, prefill_buckets, total}`` — the warmup-flatness
        evidence serving artifacts embed (``decode`` counts every
        decode-family program: plain, fused, speculative)."""
        pre = sorted(s[1] for s in self._programs
                     if s[0].startswith("prefill"))
        return {"decode": sum(1 for s in self._programs
                              if s[0].startswith("decode")),
                "prefill_buckets": pre,
                "total": self.program_builds}

    def cursor_of(self, slot: int) -> int:
        """Host readback of one slot's live cursor — sanctioned ONLY at
        migration boundaries (graceful drain exports a mid-stream slot
        once per request, like the prefill/decode handoff's export),
        never inside the decode loop where cursors advance on device."""
        return int(np.asarray(self.cache.cursors)[slot])

    # ------------------------------------------------------------------
    def prompt_bucket(self, n: int) -> int:
        return prompt_bucket(n, self.buckets, max_len=self.max_len)

    def _prefill_one(self, kind, model, cache, padded, plen, slot, key):
        import jax
        import jax.numpy as jnp

        def build():
            fn = functools.partial(_serve_prefill_impl, model,
                                   self._sample_row, cache.quantized)
            return jax.jit(fn, donate_argnums=(1,))

        run = self._program((kind, int(padded.shape[0])), build)
        tok, key, state = run(model.params, cache.state,
                              jnp.asarray(padded)[None],
                              jnp.asarray(plen, jnp.int32),
                              jnp.asarray(slot, jnp.int32), key)
        cache.install(state)
        return tok, key

    def prefill(self, prompt, slot: int, key) -> Tuple[object, object]:
        """Admit one prompt ([t] int) into ``slot``: bucket-pad, run the
        prefill program (plus the draft-pool prefill when speculative
        decoding is on), start the cursor at ``prompt_len``. Returns
        ``(first_token, new_key)`` (device scalars)."""
        import jax

        prompt = np.asarray(prompt, np.int32)
        if prompt.ndim != 1:
            raise ValueError(f"prompt must be [t] (got {prompt.shape})")
        bucket = self.prompt_bucket(int(prompt.shape[0]))
        padded, plen = pad_prompt(prompt, bucket)
        tok, key = self._prefill_one("prefill", self.model, self.cache,
                                     padded, plen, slot, key)
        if self.spec:
            # the draft pool must hold the prompt's K/V too; its sampled
            # token (and the dummy key) are discarded — the served first
            # token is the TARGET prefill's
            self._prefill_one("prefill_draft", self.draft_model,
                              self.draft_cache, padded, plen, slot,
                              jax.random.PRNGKey(0))
        self.cache.set_cursor(slot, plen)
        return tok, key

    def decode(self, tok, positions, keys):
        """One batched step (the ``fuse_steps=1`` / PR-10 path):
        ``tok``/``positions`` [S], ``keys`` [S, 2]. Returns
        ``(next_tokens [S], new_keys)``; the pool advances in place
        (donated buffers) and the CALLER advances the cursors."""
        import jax
        import jax.numpy as jnp

        def build():
            fn = functools.partial(_serve_decode_impl, self.model,
                                   self._sample_row)
            return jax.jit(fn, donate_argnums=(1,))

        run = self._program(("decode", self.slots), build)
        toks, keys, state = run(self.model.params, self.cache.state,
                                jnp.asarray(tok, jnp.int32),
                                jnp.asarray(positions, jnp.int32), keys)
        self.cache.install(state)
        return toks, keys

    def decode_fused(self, tok, remaining, keys, k_steps: int):
        """K decode steps as ONE dispatch: returns the ``[K, S]`` token
        block (device) + new keys; pool and cursors advance in place."""
        import jax
        import jax.numpy as jnp

        def build():
            fn = functools.partial(_serve_decode_fused_impl, self.model,
                                   self._sample_row, k_steps)
            return jax.jit(fn, donate_argnums=(1, 2))

        run = self._program(("decode_fused", self.slots, k_steps), build)
        toks, cursors, keys, state = run(
            self.model.params, self.cache.state, self.cache.cursors,
            jnp.asarray(tok, jnp.int32),
            jnp.asarray(remaining, jnp.int32), keys)
        self.cache.install(state)
        self.cache.cursors = cursors
        return toks, keys

    def decode_spec(self, tok, remaining, keys, draft_keys,
                    k_rounds: int):
        """K speculative rounds as ONE dispatch: returns the
        ``[K, S, spec_tokens + 2]`` block (per round and slot:
        ``[count, tokens...]``) + new target/draft keys; both pools and
        the cursors advance in place."""
        import jax
        import jax.numpy as jnp

        greedy = self.temperature == 0.0

        def build():
            fn = functools.partial(
                _serve_spec_impl, self.model, self.draft_model,
                None if greedy else _filtered_logits_fn(
                    self.temperature, self.top_k),
                self.spec_tokens, greedy, k_rounds)
            return jax.jit(fn, donate_argnums=(2, 3, 4))

        run = self._program(
            ("decode_spec", self.slots, k_rounds, self.spec_tokens),
            build)
        blocks, cursors, keys, draft_keys, state, dstate = run(
            self.model.params, self.draft_model.params,
            self.cache.state, self.draft_cache.state, self.cache.cursors,
            jnp.asarray(tok, jnp.int32),
            jnp.asarray(remaining, jnp.int32), keys, draft_keys)
        self.cache.install(state)
        self.draft_cache.install(dstate)
        self.cache.cursors = cursors
        return blocks, keys, draft_keys
