"""Batched decode engine: the jitted programs behind the decode server.

Two programs serve any request stream, and the engine never compiles a
third:

- ``("prefill", P_bucket)`` — one bucket-padded prompt forward ([1, P])
  through the SAME ``TransformerLM._block`` math as training, writing the
  per-layer K/V into one slot of the ``[L, S, T_max, Hkv, Dh]`` pool and
  sampling the request's first token from position ``prompt_len - 1``.
  One compile per prompt-ladder rung (``perf/bucketing.prompt_bucket``).
- ``("decode", S)`` — ONE step for ALL S slots at their own positions:
  scatter the consumed tokens' K/V at each slot's cursor, attend each row
  against its own masked cache history (GQA-aware — the pool stores
  ``num_kv_heads``), sample one token per row from per-slot RNG streams.
  One compile per slot count, i.e. one for the server's lifetime.

Both are ``@traced`` hot roots (``analysis/annotations.HOT_PATH_REGISTRY``)
so dl4j-lint's host-sync rule guards the decode loop: a ``float()`` /
``np.asarray`` slipped into this module's program bodies is a lint
finding, not a silent per-token device sync.

Numerics contract (tests/test_serving.py): a slot's token sequence is
IDENTICAL to ``TransformerLM.generate`` on the same prompt — greedy and
sampled (each slot replays the exact ``sample``/``split`` chain of a
single-request ``generate(seed=...)``). Slot rows are computationally
independent (every op is row-wise; masked pad keys contribute exactly
zero attention weight), so batching requests changes no request's tokens.
"""

from __future__ import annotations

import functools
from typing import Dict, Optional, Sequence, Tuple

import numpy as np

from deeplearning4j_tpu.analysis.annotations import traced
from deeplearning4j_tpu.perf.bucketing import (
    DEFAULT_PROMPT_BUCKETS, pad_prompt, prompt_bucket)
from deeplearning4j_tpu.serving.compile_cache import ensure_compile_cache
from deeplearning4j_tpu.serving.kv_cache import SlotKVCache

__all__ = ["DecodeEngine"]


def _row_sampler(temperature: float, top_k: Optional[int]):
    """Per-row sampler ``(logits [V], key [2]) -> (tok, key)`` replaying
    the exact op sequence of ``make_generate``'s batch-of-one ``sample``
    (logits lifted to [1, V] so the categorical draw consumes the same
    random bits a single-request decode would)."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    def one(logits, key):
        if temperature == 0.0:
            return jnp.argmax(logits[None], axis=-1)[0].astype(jnp.int32), \
                key
        scaled = logits[None] / temperature
        if top_k is not None:
            kth = lax.top_k(scaled, top_k)[0][:, -1]
            scaled = jnp.where(scaled >= kth[:, None], scaled, -jnp.inf)
        key, sub = jax.random.split(key)
        return jax.random.categorical(sub, scaled, axis=-1)[0].astype(
            jnp.int32), key

    return one


@traced
def _serve_prefill_impl(model, sample_row, params, pool_k, pool_v,
                        prompt, prompt_len, slot, key):
    """Prefill one bucket-padded prompt ([1, P]) into pool slot ``slot``.

    Causality makes the pad tail inert: position ``i < prompt_len``
    attends keys ``0..i`` — all real tokens — so the K/V written at real
    positions (and the ``prompt_len - 1`` hidden state the first token is
    sampled from) are the unpadded prefill's values. ``prompt_len`` and
    ``slot`` are traced: one compile per bucket, not per request."""
    import jax.numpy as jnp
    from jax import lax

    policy = model.policy
    cdt = policy.compute_dtype
    p = prompt.shape[1]
    h = jnp.take(params["embed"], prompt, axis=0)
    if model.pos_encoding == "learned":
        h = h + params["pos"][:p][None]
    h = policy.cast_compute(h)
    ks, vs = [], []
    for blk in params["blocks"]:
        h, kk, vv = model._block(blk, h)
        ks.append(kk.astype(cdt))
        vs.append(vv.astype(cdt))
    # [L, 1, P, Hkv, Dh] written at (layer 0, slot, position 0)
    pool_k = lax.dynamic_update_slice(
        pool_k, jnp.stack(ks), (0, slot, 0, 0, 0))
    pool_v = lax.dynamic_update_slice(
        pool_v, jnp.stack(vs), (0, slot, 0, 0, 0))
    h_last = jnp.take(h[0], prompt_len - 1, axis=0)        # [D]
    tok, key = sample_row(model._unembed(params, h_last), key)
    return tok, key, pool_k, pool_v


@traced
def _serve_decode_impl(model, sample_row, params, pool_k, pool_v,
                       tok, positions, keys):
    """ONE decode step for all S slots: consume ``tok[s]`` at
    ``positions[s]``, write its K/V at that cursor, attend keys
    ``<= positions[s]`` (window-clipped like training), emit the next
    token per slot from its own RNG stream. Free slots ride along
    computing garbage no one reads — their rows are masked out of
    nothing (rows are independent) and their pool writes land at frozen
    cursors the admission prefill overwrites."""
    import jax
    import jax.numpy as jnp
    from deeplearning4j_tpu.ops.attention import grouped_query_attention

    policy = model.policy
    cdt = policy.compute_dtype
    s = tok.shape[0]
    t_max = pool_k.shape[2]
    h = jnp.take(params["embed"], tok, axis=0)             # [S, D]
    if model.pos_encoding == "learned":
        h = h + params["pos"][positions]
    h = policy.cast_compute(h)[:, None, :]                 # [S, 1, D]
    live = jnp.arange(t_max)[None, :] <= positions[:, None]
    if model.attn_window is not None:
        live &= (jnp.arange(t_max)[None, :]
                 > positions[:, None] - model.attn_window)
    new_k, new_v = [], []
    rows = jnp.arange(s)

    def cached_attention(li):
        def attn(q, kk, vv):
            ck = pool_k[li].at[rows, positions].set(kk[:, 0].astype(cdt))
            cv = pool_v[li].at[rows, positions].set(vv[:, 0].astype(cdt))
            new_k.append(ck)
            new_v.append(cv)
            return grouped_query_attention(q, ck, cv, mask=live)
        return attn

    for li, blk in enumerate(params["blocks"]):
        h, _, _ = model._block(blk, h, attention=cached_attention(li),
                               positions=positions[:, None])
    logits = model._unembed(params, h[:, 0])               # [S, V]
    toks, keys = jax.vmap(sample_row)(logits, keys)
    return toks, keys, jnp.stack(new_k), jnp.stack(new_v)


class DecodeEngine:
    """Owns the slot pool + the per-signature program cache.

    ``temperature``/``top_k`` are server-level (baked into the compiled
    programs — a per-request sampling config would be a program
    signature per config, exactly the recompile hazard the server
    exists to avoid); per-request randomness rides in per-slot keys.
    """

    def __init__(self, model, slots: int, *,
                 max_len: Optional[int] = None,
                 temperature: float = 0.0, top_k: Optional[int] = None,
                 buckets: Optional[Sequence[int]] = None):
        if temperature < 0.0:
            raise ValueError(f"temperature={temperature} must be >= 0")
        if top_k is not None and not 1 <= top_k <= model.vocab_size:
            raise ValueError(
                f"top_k={top_k} must be in [1, vocab={model.vocab_size}]")
        model._ensure_init()
        self.model = model
        self.cache = SlotKVCache(model, slots, max_len)
        self.slots = self.cache.slots
        self.max_len = self.cache.max_len
        self.temperature = float(temperature)
        self.top_k = top_k
        self.buckets = tuple(b for b in (buckets or DEFAULT_PROMPT_BUCKETS)
                             if b <= self.max_len) or (self.max_len,)
        self._sample_row = _row_sampler(self.temperature, top_k)
        self._programs: Dict[tuple, object] = {}
        self.program_builds = 0
        # the fleet story: point jax's persistent compilation cache at
        # DL4J_COMPILE_CACHE_DIR before this engine's first compile
        ensure_compile_cache()

    # ------------------------------------------------------------------
    def _program(self, sig: tuple, factory):
        """One jitted program per signature for the engine's lifetime —
        the build count IS the compile count (fixed shapes per
        signature), mirrored into the registry so the bench and the
        soak test can assert flatness after warmup."""
        fn = self._programs.get(sig)
        if fn is None:
            from deeplearning4j_tpu.monitor import record_counter

            fn = self._programs[sig] = factory()
            self.program_builds += 1
            record_counter("serve_program_builds_total", kind=sig[0])
        return fn

    def compile_counts(self) -> dict:
        """``{decode, prefill_buckets, total}`` — the warmup-flatness
        evidence serving artifacts embed."""
        pre = sorted(s[1] for s in self._programs if s[0] == "prefill")
        return {"decode": sum(1 for s in self._programs
                              if s[0] == "decode"),
                "prefill_buckets": pre,
                "total": self.program_builds}

    # ------------------------------------------------------------------
    def prompt_bucket(self, n: int) -> int:
        return prompt_bucket(n, self.buckets, max_len=self.max_len)

    def prefill(self, prompt, slot: int, key) -> Tuple[object, object]:
        """Admit one prompt ([t] int) into ``slot``: bucket-pad, run the
        prefill program, start the cursor at ``prompt_len``. Returns
        ``(first_token, new_key)`` (device scalars)."""
        import jax
        import jax.numpy as jnp

        prompt = np.asarray(prompt, np.int32)
        if prompt.ndim != 1:
            raise ValueError(f"prompt must be [t] (got {prompt.shape})")
        bucket = self.prompt_bucket(int(prompt.shape[0]))
        padded, plen = pad_prompt(prompt, bucket)

        def build():
            fn = functools.partial(_serve_prefill_impl, self.model,
                                   self._sample_row)
            return jax.jit(fn, donate_argnums=(1, 2))

        run = self._program(("prefill", bucket), build)
        tok, key, k, v = run(self.model.params, self.cache.k,
                             self.cache.v, jnp.asarray(padded)[None],
                             jnp.asarray(plen, jnp.int32),
                             jnp.asarray(slot, jnp.int32), key)
        self.cache.swap(k, v)
        self.cache.cursors[slot] = plen
        return tok, key

    def decode(self, tok, positions, keys):
        """One batched step: ``tok``/``positions`` [S], ``keys`` [S, 2].
        Returns ``(next_tokens [S], new_keys)``; the pool advances in
        place (donated buffers)."""
        import jax
        import jax.numpy as jnp

        def build():
            fn = functools.partial(_serve_decode_impl, self.model,
                                   self._sample_row)
            return jax.jit(fn, donate_argnums=(1, 2))

        run = self._program(("decode", self.slots), build)
        toks, keys, k, v = run(self.model.params, self.cache.k,
                               self.cache.v,
                               jnp.asarray(tok, jnp.int32),
                               jnp.asarray(positions, jnp.int32), keys)
        self.cache.swap(k, v)
        return toks, keys
