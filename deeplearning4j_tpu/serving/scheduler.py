"""Request model + admission queue for the decode server.

Scheduler policy (stated so it can be changed deliberately): admission
at step boundaries from a bounded queue (``DL4J_SERVE_MAX_QUEUE``;
overflow rejects at submit — backpressure belongs at the edge, not as
unbounded memory), ordered by criticality class then FIFO within a
class. No preemption of running slots, no prompt-length reordering:
continuous batching already removes the head-of-line blocking that
matters (a long generation never stalls admission — new requests join
mid-flight the moment any slot frees), and class-then-FIFO keeps
per-request latency analyzable under the open-loop load the bench
drives while letting ``interactive`` traffic hold its TTFT through an
overload storm.

Overload control (the request-level half of the fleet's robustness
story — the replica-level half is failover/eviction):

- **deadlines** — ``ServeRequest.deadline_s`` is an ABSOLUTE instant on
  the server's clock; an expired request sheds at the earliest point
  that looks at it (admission, queue pop, or the in-flight sweep)
  instead of burning decode slots on an answer nobody waits for.
- **criticality** — :data:`CRITICALITIES` orders the classes; when the
  queue is at bound an arriving request may displace the costliest
  queued request of a STRICTLY lower class (cost estimate
  ``prompt_len + max_new_tokens``), so ``batch`` absorbs the storm
  while ``interactive`` holds.
- **retry budgets** — :class:`RetryBudget` is the per-class token
  bucket the router's failover/hedge retries draw from: each
  submitted request deposits ``DL4J_SERVE_RETRY_RATIO`` tokens, each
  retry spends one, so retry amplification is bounded by construction
  (≈ ``1 + ratio`` long-run) instead of melting the fleet under the
  very overload that caused the retries.
"""

from __future__ import annotations

import itertools
import os
import threading
from collections import deque
from dataclasses import dataclass, field
from typing import Deque, Dict, List, Optional

import numpy as np

__all__ = ["ServeRequest", "ServeQueueFull", "RequestQueue",
           "AdmissionVerdict", "RetryBudget", "serve_slots",
           "serve_max_queue", "serve_fuse_steps", "serve_kv_dtype",
           "serve_draft_layers", "serve_replicas", "serve_role",
           "serve_evict_s", "serve_deadline_s", "serve_retry_ratio",
           "serve_retry_burst", "serve_hedge_s", "SERVE_ROLES",
           "CRITICALITIES", "criticality_rank", "request_cost"]

_IDS = itertools.count(1)


def serve_slots(default: int = 8) -> int:
    """``DL4J_SERVE_SLOTS``: concurrent decode slots S (the batch width
    of the one compiled decode program)."""
    raw = os.environ.get("DL4J_SERVE_SLOTS", "")
    try:
        return max(1, int(raw)) if raw else default
    except ValueError:
        return default


def serve_fuse_steps(default: int = 1) -> int:
    """``DL4J_SERVE_FUSE_STEPS``: decode steps fused per dispatch (K).
    1 (default) = one host dispatch per token, the PR-10 behavior,
    bitwise; K > 1 runs K steps as one ``lax.scan`` program and admits
    new requests only at fusion boundaries."""
    raw = os.environ.get("DL4J_SERVE_FUSE_STEPS", "")
    try:
        return max(1, int(raw)) if raw else default
    except ValueError:
        return default


def serve_kv_dtype(default=None):
    """``DL4J_SERVE_KV_DTYPE``: the KV pool's store dtype
    (``float32``/``bfloat16``/``int8``); unset = the model's compute
    dtype (pre-quantization behavior). Validation happens in
    ``kv_cache.resolve_kv_dtype`` (model-aware)."""
    raw = os.environ.get("DL4J_SERVE_KV_DTYPE", "").strip()
    return raw or default


def serve_draft_layers(default: int = 0) -> int:
    """``DL4J_SERVE_DRAFT_LAYERS``: speculative decoding via a shallow
    self-draft of the target's first N layers. 0 (default) = off."""
    raw = os.environ.get("DL4J_SERVE_DRAFT_LAYERS", "")
    try:
        return max(0, int(raw)) if raw else default
    except ValueError:
        return default


def serve_max_queue(default: int = 64) -> int:
    """``DL4J_SERVE_MAX_QUEUE``: admission queue bound; submits beyond
    it raise :class:`ServeQueueFull`."""
    raw = os.environ.get("DL4J_SERVE_MAX_QUEUE", "")
    try:
        return max(1, int(raw)) if raw else default
    except ValueError:
        return default


# ---------------------------------------------------------------------------
# fleet knobs (serving/fleet/)
# ---------------------------------------------------------------------------

#: replica roles for the prefill/decode split: ``mixed`` replicas run the
#: whole request lifecycle (the single-replica behavior), ``prefill``
#: replicas only compute prompt K/V slabs and hand them off, ``decode``
#: replicas only accept handed-off slabs and stream tokens.
SERVE_ROLES = ("mixed", "prefill", "decode")


def serve_replicas(default: int = 2) -> int:
    """``DL4J_SERVE_REPLICAS``: how many ``DecodeServer`` replicas a
    fleet builder stands up (``serving/fleet``)."""
    raw = os.environ.get("DL4J_SERVE_REPLICAS", "")
    try:
        return max(1, int(raw)) if raw else default
    except ValueError:
        return default


def serve_role(default: str = "mixed") -> str:
    """``DL4J_SERVE_ROLE``: this process's replica role in a
    prefill/decode-disaggregated fleet (``mixed``/``prefill``/
    ``decode``). An unknown value raises — a replica silently falling
    back to ``mixed`` would serve decode traffic a router believes it
    routed elsewhere."""
    raw = os.environ.get("DL4J_SERVE_ROLE", "").strip().lower()
    if not raw:
        return default
    if raw not in SERVE_ROLES:
        raise ValueError(
            f"DL4J_SERVE_ROLE={raw!r} must be one of {SERVE_ROLES}")
    return raw


def serve_evict_s(default: float = 10.0) -> float:
    """``DL4J_SERVE_EVICT_S``: heartbeat-silence timeout after which the
    fleet controller evicts a replica and requeues its in-flight
    requests onto survivors."""
    raw = os.environ.get("DL4J_SERVE_EVICT_S", "")
    try:
        return max(0.1, float(raw)) if raw else default
    except ValueError:
        return default


# ---------------------------------------------------------------------------
# overload-control knobs
# ---------------------------------------------------------------------------

#: criticality classes, most to least critical. Shedding walks this
#: list from the BACK (``best_effort`` goes first); queue admission
#: pops from the FRONT (``interactive`` jumps the line).
CRITICALITIES = ("interactive", "batch", "best_effort")

_CRIT_RANK = {c: i for i, c in enumerate(CRITICALITIES)}


def criticality_rank(criticality: str) -> int:
    """0 = most critical; raises on an unknown class (silently treating
    a typo as lowest-priority would shed traffic the caller believed
    was interactive)."""
    try:
        return _CRIT_RANK[criticality]
    except KeyError:
        raise ValueError(
            f"criticality={criticality!r} must be one of {CRITICALITIES}")


def request_cost(prompt_len: int, max_new_tokens: int) -> int:
    """The shed-ordering cost estimate: prefill work scales with the
    prompt, decode occupancy with the generation budget — their sum is
    the slot-seconds a request would claim."""
    return int(prompt_len) + int(max_new_tokens)


def serve_deadline_s(default: Optional[float] = None) -> Optional[float]:
    """``DL4J_SERVE_DEADLINE_S``: default per-request deadline BUDGET
    (seconds from submit) applied when a request carries none. Unset =
    no deadline (requests wait forever, the pre-overload-control
    behavior)."""
    raw = os.environ.get("DL4J_SERVE_DEADLINE_S", "")
    try:
        return max(0.0, float(raw)) if raw else default
    except ValueError:
        return default


def serve_retry_ratio(default: float = 0.1) -> float:
    """``DL4J_SERVE_RETRY_RATIO``: retry-budget tokens each submitted
    request deposits into its class's bucket. 0.1 bounds long-run retry
    amplification at ~1.1x submitted."""
    raw = os.environ.get("DL4J_SERVE_RETRY_RATIO", "")
    try:
        return max(0.0, float(raw)) if raw else default
    except ValueError:
        return default


def serve_retry_burst(default: float = 10.0) -> float:
    """``DL4J_SERVE_RETRY_BURST``: retry-budget bucket cap (and initial
    fill) per class — the burst of retries a cold fleet may spend
    before the deposit stream has accrued."""
    raw = os.environ.get("DL4J_SERVE_RETRY_BURST", "")
    try:
        return max(0.0, float(raw)) if raw else default
    except ValueError:
        return default


def serve_hedge_s(default: Optional[float] = None) -> Optional[float]:
    """``DL4J_SERVE_HEDGE_S``: latency threshold past which a
    still-queued ``interactive`` request may hedge to a second replica
    (first winner cancels the loser). Unset/0 = hedging off."""
    raw = os.environ.get("DL4J_SERVE_HEDGE_S", "")
    try:
        v = float(raw) if raw else None
    except ValueError:
        return default
    if v is None:
        return default
    return v if v > 0 else None


class RetryBudget:
    """Per-class token bucket bounding retry amplification.

    Every submitted request deposits ``ratio`` tokens into its class's
    bucket (capped at ``burst``, which is also the initial fill); every
    retry — a failover re-dispatch (however many replicas the spill
    probes on its way to a seat), a hedge — spends one. First-time
    placement is free: routing a fresh request is not a retry, only
    re-doing work is. When a bucket is dry
    the retry simply does not happen: during the overload that caused
    the failures, retries are the amplifier that melts fleets, and the
    budget caps total attempts at ``submitted * (1 + ratio) + burst``
    per class by construction. Thread-safe (router + controller tick)."""

    def __init__(self, ratio: Optional[float] = None,
                 burst: Optional[float] = None):
        self.ratio = serve_retry_ratio() if ratio is None else float(ratio)
        self.burst = serve_retry_burst() if burst is None else float(burst)
        self._tokens: Dict[str, float] = {
            c: self.burst for c in CRITICALITIES}
        self._lock = threading.Lock()

    def deposit(self, criticality: str) -> None:
        criticality_rank(criticality)
        with self._lock:
            self._tokens[criticality] = min(
                self.burst, self._tokens[criticality] + self.ratio)

    def has(self, criticality: str, n: float = 1.0) -> bool:
        with self._lock:
            return self._tokens[criticality] >= n

    def try_spend(self, criticality: str, n: float = 1.0) -> bool:
        """Spend ``n`` tokens if available; False (and no change) when
        the bucket is dry — the caller skips the retry."""
        criticality_rank(criticality)
        with self._lock:
            if self._tokens[criticality] < n:
                return False
            self._tokens[criticality] -= n
            return True

    def refund(self, criticality: str, n: float = 1.0) -> None:
        """Return tokens a spent retry never used (e.g. a hedge that
        found no replica to land on); capped at ``burst``."""
        criticality_rank(criticality)
        with self._lock:
            self._tokens[criticality] = min(
                self.burst, self._tokens[criticality] + float(n))

    def remaining(self, criticality: str) -> float:
        with self._lock:
            return self._tokens[criticality]


class ServeQueueFull(RuntimeError):
    """Backpressure signal: the admission queue is at its bound."""


@dataclass(frozen=True)
class AdmissionVerdict:
    """Outcome of a non-blocking ``DecodeServer.try_submit``: either the
    request was enqueued (``admitted``, ``request`` set) or the server
    reported why not (``reason``) — so a routing frontend can place
    against many replicas without exception-driven control flow.
    ``queue_depth`` is the admission queue's depth at decision time
    (the spill signal). ``displaced`` carries the lower-criticality
    victim this admission shed from a full queue (criticality
    displacement), so the router can settle the victim's fleet-level
    bookkeeping."""

    admitted: bool
    reason: Optional[str] = None     # None | "queue_full" | "expired"
    request: Optional["ServeRequest"] = None
    queue_depth: int = 0
    displaced: Optional["ServeRequest"] = None


@dataclass(eq=False)  # identity semantics: a request IS its object —
class ServeRequest:   # field-wise eq would compare prompt arrays
    """One generation request and its measured lifecycle.

    Timestamps are the server clock's (injectable, monotonic):
    ``submit_s`` at enqueue, ``first_token_s`` when the prefill emits
    the first token (TTFT), ``finish_s`` at retirement. ``tokens`` are
    the generated tokens only (the caller owns its prompt)."""

    prompt: np.ndarray
    max_new_tokens: int
    seed: int = 0
    id: int = field(default_factory=lambda: next(_IDS))
    state: str = "queued"   # queued | running | finished | shed | canceled
    # True once the request entered a server through a slab handoff:
    # its TTFT belongs to the PREFILL side (stamped there), so the
    # decode side must not re-attribute it to itself
    handoff: bool = False
    slot: Optional[int] = None
    submit_s: Optional[float] = None
    first_token_s: Optional[float] = None
    finish_s: Optional[float] = None
    tokens: List[int] = field(default_factory=list)
    # overload control: ABSOLUTE expiry instant on the server's clock
    # (None = no deadline), criticality class, and — once shed — why
    # ("deadline" | "shed_overload") for the evidence trail
    deadline_s: Optional[float] = None
    criticality: str = "interactive"
    shed_reason: Optional[str] = None
    # a hedged duplicate that lost the race: the server retires it
    # without counting it finished the next time it looks at it
    canceled: bool = False

    def expired(self, now: float) -> bool:
        return self.deadline_s is not None and now > self.deadline_s

    @property
    def cost(self) -> int:
        return request_cost(self.prompt.shape[0], self.max_new_tokens)

    @property
    def ttft_s(self) -> Optional[float]:
        if self.submit_s is None or self.first_token_s is None:
            return None
        return self.first_token_s - self.submit_s

    @property
    def latency_s(self) -> Optional[float]:
        if self.submit_s is None or self.finish_s is None:
            return None
        return self.finish_s - self.submit_s

    @property
    def output(self) -> np.ndarray:
        """``prompt + generated`` — the shape ``generate()`` returns,
        for equivalence checks."""
        return np.concatenate(
            [self.prompt, np.asarray(self.tokens, self.prompt.dtype)])


class RequestQueue:
    """Bounded class-then-FIFO admission queue; thread-safe so
    producers may submit while the serve loop runs on another thread.

    One FIFO deque per criticality class: ``pop`` serves the most
    critical non-empty class first (FIFO within it — a single-class
    workload sees exactly the old FIFO behavior), and at the bound
    ``displace`` lets an arrival shed the costliest queued request of a
    strictly lower class instead of being rejected."""

    def __init__(self, max_depth: int):
        self.max_depth = int(max_depth)
        self._lock = threading.Lock()
        self._qs: Dict[str, Deque[ServeRequest]] = {
            c: deque() for c in CRITICALITIES}

    def push(self, req: ServeRequest) -> None:
        if not self.try_push(req):
            raise ServeQueueFull(
                f"serve queue at max depth {self.max_depth}")

    def try_push(self, req: ServeRequest) -> bool:
        """Non-raising ``push``: False when the queue is at its bound."""
        with self._lock:
            if self._size() >= self.max_depth:
                return False
            self._qs[req.criticality].append(req)
            return True

    def displace(self, req: ServeRequest
                 ) -> "tuple[bool, Optional[ServeRequest]]":
        """Admission at the bound: evict the costliest queued request
        of the LOWEST class strictly below ``req``'s and enqueue
        ``req`` in its place. Returns ``(admitted, victim)`` — the
        victim (for the caller to shed with evidence) is None when the
        queue had room, and ``admitted`` is False when every queued
        request is at least as critical as the arrival (the arrival is
        then the one to reject)."""
        with self._lock:
            if self._size() < self.max_depth:
                self._qs[req.criticality].append(req)
                return True, None
            rank = criticality_rank(req.criticality)
            for c in reversed(CRITICALITIES):
                if _CRIT_RANK[c] <= rank or not self._qs[c]:
                    continue
                victim = max(self._qs[c], key=lambda r: (r.cost, r.id))
                self._qs[c].remove(victim)
                self._qs[req.criticality].append(req)
                return True, victim
            return False, None

    def pop(self) -> Optional[ServeRequest]:
        with self._lock:
            for c in CRITICALITIES:
                if self._qs[c]:
                    return self._qs[c].popleft()
            return None

    def remove(self, req: ServeRequest) -> bool:
        """Pull a specific request back out (hedge-loser cancellation);
        False when it was already popped into a slot."""
        with self._lock:
            q = self._qs[req.criticality]
            try:
                q.remove(req)
                return True
            except ValueError:
                return False

    def _size(self) -> int:
        return sum(len(q) for q in self._qs.values())

    def __len__(self) -> int:
        with self._lock:
            return self._size()
