"""Request model + FIFO admission queue for the decode server.

Scheduler policy (deliberately simple, stated so it can be changed
deliberately): FIFO admission at step boundaries. A request waits in a
bounded queue (``DL4J_SERVE_MAX_QUEUE``; overflow rejects at submit —
backpressure belongs at the edge, not as unbounded memory), and the
server moves it into the first free slot at the next step boundary. No
preemption, no priority classes, no prompt-length reordering: continuous
batching already removes the head-of-line blocking that matters (a long
generation never stalls admission — new requests join mid-flight the
moment any slot frees), and FIFO keeps per-request latency analyzable
under the open-loop load the bench drives.
"""

from __future__ import annotations

import itertools
import os
import threading
from collections import deque
from dataclasses import dataclass, field
from typing import Deque, List, Optional

import numpy as np

__all__ = ["ServeRequest", "ServeQueueFull", "RequestQueue",
           "AdmissionVerdict", "serve_slots", "serve_max_queue",
           "serve_fuse_steps", "serve_kv_dtype", "serve_draft_layers",
           "serve_replicas", "serve_role", "serve_evict_s",
           "SERVE_ROLES"]

_IDS = itertools.count(1)


def serve_slots(default: int = 8) -> int:
    """``DL4J_SERVE_SLOTS``: concurrent decode slots S (the batch width
    of the one compiled decode program)."""
    raw = os.environ.get("DL4J_SERVE_SLOTS", "")
    try:
        return max(1, int(raw)) if raw else default
    except ValueError:
        return default


def serve_fuse_steps(default: int = 1) -> int:
    """``DL4J_SERVE_FUSE_STEPS``: decode steps fused per dispatch (K).
    1 (default) = one host dispatch per token, the PR-10 behavior,
    bitwise; K > 1 runs K steps as one ``lax.scan`` program and admits
    new requests only at fusion boundaries."""
    raw = os.environ.get("DL4J_SERVE_FUSE_STEPS", "")
    try:
        return max(1, int(raw)) if raw else default
    except ValueError:
        return default


def serve_kv_dtype(default=None):
    """``DL4J_SERVE_KV_DTYPE``: the KV pool's store dtype
    (``float32``/``bfloat16``/``int8``); unset = the model's compute
    dtype (pre-quantization behavior). Validation happens in
    ``kv_cache.resolve_kv_dtype`` (model-aware)."""
    raw = os.environ.get("DL4J_SERVE_KV_DTYPE", "").strip()
    return raw or default


def serve_draft_layers(default: int = 0) -> int:
    """``DL4J_SERVE_DRAFT_LAYERS``: speculative decoding via a shallow
    self-draft of the target's first N layers. 0 (default) = off."""
    raw = os.environ.get("DL4J_SERVE_DRAFT_LAYERS", "")
    try:
        return max(0, int(raw)) if raw else default
    except ValueError:
        return default


def serve_max_queue(default: int = 64) -> int:
    """``DL4J_SERVE_MAX_QUEUE``: admission queue bound; submits beyond
    it raise :class:`ServeQueueFull`."""
    raw = os.environ.get("DL4J_SERVE_MAX_QUEUE", "")
    try:
        return max(1, int(raw)) if raw else default
    except ValueError:
        return default


# ---------------------------------------------------------------------------
# fleet knobs (serving/fleet/)
# ---------------------------------------------------------------------------

#: replica roles for the prefill/decode split: ``mixed`` replicas run the
#: whole request lifecycle (the single-replica behavior), ``prefill``
#: replicas only compute prompt K/V slabs and hand them off, ``decode``
#: replicas only accept handed-off slabs and stream tokens.
SERVE_ROLES = ("mixed", "prefill", "decode")


def serve_replicas(default: int = 2) -> int:
    """``DL4J_SERVE_REPLICAS``: how many ``DecodeServer`` replicas a
    fleet builder stands up (``serving/fleet``)."""
    raw = os.environ.get("DL4J_SERVE_REPLICAS", "")
    try:
        return max(1, int(raw)) if raw else default
    except ValueError:
        return default


def serve_role(default: str = "mixed") -> str:
    """``DL4J_SERVE_ROLE``: this process's replica role in a
    prefill/decode-disaggregated fleet (``mixed``/``prefill``/
    ``decode``). An unknown value raises — a replica silently falling
    back to ``mixed`` would serve decode traffic a router believes it
    routed elsewhere."""
    raw = os.environ.get("DL4J_SERVE_ROLE", "").strip().lower()
    if not raw:
        return default
    if raw not in SERVE_ROLES:
        raise ValueError(
            f"DL4J_SERVE_ROLE={raw!r} must be one of {SERVE_ROLES}")
    return raw


def serve_evict_s(default: float = 10.0) -> float:
    """``DL4J_SERVE_EVICT_S``: heartbeat-silence timeout after which the
    fleet controller evicts a replica and requeues its in-flight
    requests onto survivors."""
    raw = os.environ.get("DL4J_SERVE_EVICT_S", "")
    try:
        return max(0.1, float(raw)) if raw else default
    except ValueError:
        return default


class ServeQueueFull(RuntimeError):
    """Backpressure signal: the admission queue is at its bound."""


@dataclass(frozen=True)
class AdmissionVerdict:
    """Outcome of a non-blocking ``DecodeServer.try_submit``: either the
    request was enqueued (``admitted``, ``request`` set) or the server
    reported why not (``reason``) — so a routing frontend can place
    against many replicas without exception-driven control flow.
    ``queue_depth`` is the admission queue's depth at decision time
    (the spill signal)."""

    admitted: bool
    reason: Optional[str] = None          # None | "queue_full"
    request: Optional["ServeRequest"] = None
    queue_depth: int = 0


@dataclass
class ServeRequest:
    """One generation request and its measured lifecycle.

    Timestamps are the server clock's (injectable, monotonic):
    ``submit_s`` at enqueue, ``first_token_s`` when the prefill emits
    the first token (TTFT), ``finish_s`` at retirement. ``tokens`` are
    the generated tokens only (the caller owns its prompt)."""

    prompt: np.ndarray
    max_new_tokens: int
    seed: int = 0
    id: int = field(default_factory=lambda: next(_IDS))
    state: str = "queued"          # queued | running | finished
    # True once the request entered a server through a slab handoff:
    # its TTFT belongs to the PREFILL side (stamped there), so the
    # decode side must not re-attribute it to itself
    handoff: bool = False
    slot: Optional[int] = None
    submit_s: Optional[float] = None
    first_token_s: Optional[float] = None
    finish_s: Optional[float] = None
    tokens: List[int] = field(default_factory=list)

    @property
    def ttft_s(self) -> Optional[float]:
        if self.submit_s is None or self.first_token_s is None:
            return None
        return self.first_token_s - self.submit_s

    @property
    def latency_s(self) -> Optional[float]:
        if self.submit_s is None or self.finish_s is None:
            return None
        return self.finish_s - self.submit_s

    @property
    def output(self) -> np.ndarray:
        """``prompt + generated`` — the shape ``generate()`` returns,
        for equivalence checks."""
        return np.concatenate(
            [self.prompt, np.asarray(self.tokens, self.prompt.dtype)])


class RequestQueue:
    """Bounded FIFO; thread-safe so producers may submit while the
    serve loop runs on another thread."""

    def __init__(self, max_depth: int):
        self.max_depth = int(max_depth)
        self._lock = threading.Lock()
        self._q: Deque[ServeRequest] = deque()

    def push(self, req: ServeRequest) -> None:
        if not self.try_push(req):
            raise ServeQueueFull(
                f"serve queue at max depth {self.max_depth}")

    def try_push(self, req: ServeRequest) -> bool:
        """Non-raising ``push``: False when the queue is at its bound."""
        with self._lock:
            if len(self._q) >= self.max_depth:
                return False
            self._q.append(req)
            return True

    def pop(self) -> Optional[ServeRequest]:
        with self._lock:
            return self._q.popleft() if self._q else None

    def __len__(self) -> int:
        with self._lock:
            return len(self._q)
