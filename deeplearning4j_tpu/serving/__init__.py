"""Online serving subsystem: continuous batching + batched KV-cache decode.

Every inference surface before this one was batch/offline-oriented (PR
2's shape-bucketed, device-resident eval). This package is the ONLINE
path the north star's "heavy traffic" needs — the TensorFlow-paper
serving/training split applied to the fused-program framework:

- :mod:`~deeplearning4j_tpu.serving.kv_cache` — the slot-based batched
  KV pool: ``[L, S, T_max, Hkv, Dh]`` device-resident K/V with per-slot
  write cursors, so S concurrent requests at different decode positions
  are ONE program's batch dimension.
- :mod:`~deeplearning4j_tpu.serving.engine` — the jitted program set
  (bucket-padded prefill, batched decode step, K-step fused decode,
  speculative draft/verify rounds) built on the SAME
  ``TransformerLM._block`` math as training; ``@traced`` hot roots for
  dl4j-lint's host-sync rule. The fast path: ``fuse_steps=K`` turns K
  tokens into one dispatch, ``kv_dtype="int8"`` shrinks the pool 4x,
  and a draft model (``draft_layers=N`` shallow self-draft or a
  provided ``TransformerLM``) makes accepted-tokens/dispatch the
  headline metric.
- :mod:`~deeplearning4j_tpu.serving.scheduler` — request model + bounded
  FIFO admission queue (``DL4J_SERVE_SLOTS``/``DL4J_SERVE_MAX_QUEUE``).
- :mod:`~deeplearning4j_tpu.serving.server` — :class:`DecodeServer`,
  the continuous-batching loop: admit into free slots at step
  boundaries, one batched decode step, retire finished sequences; never
  recompiles past one program per (slot-count, prefill-bucket).
- :mod:`~deeplearning4j_tpu.serving.compile_cache` — persisted XLA
  compilation cache (``DL4J_COMPILE_CACHE_DIR``) so fleet cold-start
  replays compiles from disk.
- :mod:`~deeplearning4j_tpu.serving.loadgen` — open-loop Poisson load
  generator + p50/p99/TTFT/TPOT report with per-drop timestamps (the
  ``serve`` bench section).
- :mod:`~deeplearning4j_tpu.serving.fleet` — the multi-replica serve
  fleet (imported explicitly, not re-exported here): replica workers
  under the cluster layer's heartbeat channel, a least-loaded routing
  frontend with failover requeue, the controller's master tick, and
  the ``DL4J_SERVE_ROLE`` prefill/decode split.

See ``docs/inference.md`` §Serving for the architecture and the slot
lifecycle, ``docs/observability.md`` for the serve metric/span taxonomy.
"""

from deeplearning4j_tpu.serving.compile_cache import (  # noqa: F401
    compile_cache_dir,
    compile_cache_stats,
    ensure_compile_cache,
)
from deeplearning4j_tpu.serving.kv_cache import (  # noqa: F401
    SlotKVCache,
    kv_pool_nbytes,
    max_slots_in_budget,
    resolve_kv_dtype,
)
from deeplearning4j_tpu.serving.engine import DecodeEngine  # noqa: F401
from deeplearning4j_tpu.serving.scheduler import (  # noqa: F401
    CRITICALITIES,
    AdmissionVerdict,
    RequestQueue,
    RetryBudget,
    ServeQueueFull,
    ServeRequest,
    criticality_rank,
    request_cost,
    serve_deadline_s,
    serve_draft_layers,
    serve_evict_s,
    serve_fuse_steps,
    serve_hedge_s,
    serve_kv_dtype,
    serve_max_queue,
    serve_replicas,
    serve_retry_burst,
    serve_retry_ratio,
    serve_role,
    serve_slots,
)
from deeplearning4j_tpu.serving.server import DecodeServer  # noqa: F401
from deeplearning4j_tpu.serving.loadgen import (  # noqa: F401
    Arrival,
    LoadReport,
    poisson_schedule,
    run_open_loop,
)

__all__ = [
    "AdmissionVerdict", "Arrival", "CRITICALITIES", "DecodeEngine",
    "DecodeServer", "LoadReport", "RequestQueue", "RetryBudget",
    "ServeQueueFull", "ServeRequest", "SlotKVCache",
    "compile_cache_dir", "compile_cache_stats", "criticality_rank",
    "ensure_compile_cache", "kv_pool_nbytes", "max_slots_in_budget",
    "poisson_schedule", "request_cost", "resolve_kv_dtype",
    "run_open_loop", "serve_deadline_s", "serve_draft_layers",
    "serve_evict_s", "serve_fuse_steps", "serve_hedge_s",
    "serve_kv_dtype", "serve_max_queue", "serve_replicas",
    "serve_retry_burst", "serve_retry_ratio", "serve_role",
    "serve_slots",
]
