"""Scaled dot-product / multi-head attention ops.

Greenfield relative to the reference (a pre-transformer codebase — SURVEY §5:
"No attention of any kind exists"), but the long-context stack (ring
attention, transformer blocks) builds on these primitives.

Layouts: q/k/v are [batch, time, heads, head_dim] ("BTHD"); attention
contracts over time with optional causal and padding masks. Inside jit the
whole thing fuses; for long sequences on TPU the Pallas flash kernel
(``deeplearning4j_tpu.pallas.flash_attention.flash_attention``, same BTHD
signature, causal + scale only) streams K/V blocks through VMEM instead of
materializing the [t, t] score matrix — measured 2x faster than this op at
t=8192 on v5e and exact on the cases both support.
"""

from __future__ import annotations

from typing import Optional

import numpy as np
import jax
import jax.numpy as jnp

NEG_INF = -1e30


def _lift_mask(mask: jnp.ndarray, rank: int) -> jnp.ndarray:
    """Broadcast a keep-mask to a logits rank. ``[b, t_kv]`` padding
    masks broadcast over heads and queries (the classic shape);
    ``[b, t_q, t_kv]`` per-query masks additionally vary along the query
    axis — the KV-cache serving paths need them when every batch row
    sits at its own ragged position set (speculative verify)."""
    m = mask.astype(bool)
    if m.ndim == 2:                      # [b, k]
        idx = (slice(None),) + (None,) * (rank - 2) + (slice(None),)
    elif m.ndim == 3:                    # [b, q, k]
        idx = (slice(None),) + (None,) * (rank - 3) + \
            (slice(None), slice(None))
    else:
        raise ValueError(
            f"mask must be [b, t_kv] or [b, t_q, t_kv] (got {m.shape})")
    return m[idx]


def causal_band_mask(tq: int, tkv: int, *, window: Optional[int] = None,
                     q_offset=0, k_offset=0) -> jnp.ndarray:
    """[tq, tkv] bool keep-mask for causal attention, optionally banded to
    the sliding window ``k in (q - window, q]``. The ONE definition of the
    band convention — dot_product/grouped attention, ring `_block_attn`,
    and ulysses `_local_attention` all build their masks here, so the
    three paths cannot drift. Offsets are the absolute positions of
    q[0]/k[0] (may be traced) for blockwise callers."""
    qi = q_offset + jnp.arange(tq)[:, None]
    ki = k_offset + jnp.arange(tkv)[None, :]
    keep = qi >= ki
    if window is not None:
        keep &= qi - ki < window
    return keep


def dot_product_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    causal: bool = False,
    mask: Optional[jnp.ndarray] = None,  # [b, t_kv] or [b, t_q, t_kv] keep-mask
    bias: Optional[jnp.ndarray] = None,  # [b, h, t_q, t_kv] additive
    scale: Optional[float] = None,
    window: Optional[int] = None,  # sliding window: k in (q-window, q]
) -> jnp.ndarray:
    """Reference (non-blockwise) attention: softmax(q·kᵀ/√d + bias)·v.

    q: [b, tq, h, d]; k/v: [b, tkv, h, d] → [b, tq, h, d]. ``window``
    (requires ``causal``) limits each query to the last ``window`` keys
    — sliding-window local attention.
    """
    if window is not None and (not causal or window < 1):
        raise ValueError("window requires causal=True and window >= 1")
    d = q.shape[-1]
    scale = scale if scale is not None else float(1.0 / np.sqrt(d))
    # bf16 inputs feed the MXU; logits accumulate in f32
    # (preferred_element_type) so the softmax runs at full precision
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * scale
    if bias is not None:
        logits = logits + bias
    if causal:
        logits = jnp.where(causal_band_mask(q.shape[1], k.shape[1],
                                            window=window),
                           logits, NEG_INF)
    if mask is not None:
        logits = jnp.where(_lift_mask(mask, 4), logits, NEG_INF)
    weights = jax.nn.softmax(logits, axis=-1)
    # cast probabilities back to the value dtype: the PV contraction runs
    # on the MXU at the bf16 rate with f32 accumulation
    return jnp.einsum("bhqk,bkhd->bqhd", weights.astype(v.dtype), v,
                      preferred_element_type=jnp.float32).astype(v.dtype)


def grouped_query_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    causal: bool = False,
    mask: Optional[jnp.ndarray] = None,  # [b, t_kv] or [b, t_q, t_kv] keep-mask
    scale: Optional[float] = None,
    window: Optional[int] = None,
) -> jnp.ndarray:
    """GQA/MQA attention: q [b, tq, H, d] against k/v [b, tkv, Hkv, d]
    with H a multiple of Hkv. Each kv head serves a GROUP of query heads
    via broadcasting — the repeated K/V is never materialized (the whole
    point of GQA's decode-bandwidth saving). Same numerics/masking as
    :func:`dot_product_attention`; delegates to it when H == Hkv."""
    if window is not None and (not causal or window < 1):
        raise ValueError("window requires causal=True and window >= 1")
    b, tq, H, d = q.shape
    hkv = k.shape[2]
    if H == hkv:
        return dot_product_attention(q, k, v, causal=causal, mask=mask,
                                     scale=scale, window=window)
    if H % hkv:
        raise ValueError(f"num query heads {H} not a multiple of kv "
                         f"heads {hkv}")
    rep = H // hkv
    scale = scale if scale is not None else float(1.0 / np.sqrt(d))
    qg = q.reshape(b, tq, hkv, rep, d)
    logits = jnp.einsum("bqhrd,bkhd->bhrqk", qg, k,
                        preferred_element_type=jnp.float32) * scale
    if causal:
        logits = jnp.where(causal_band_mask(tq, k.shape[1], window=window),
                           logits, NEG_INF)
    if mask is not None:
        logits = jnp.where(_lift_mask(mask, 5), logits, NEG_INF)
    weights = jax.nn.softmax(logits, axis=-1)
    o = jnp.einsum("bhrqk,bkhd->bqhrd", weights.astype(v.dtype), v,
                   preferred_element_type=jnp.float32).astype(v.dtype)
    return o.reshape(b, tq, H, d)


def multi_head_attention(
    x: jnp.ndarray,
    wq: jnp.ndarray,
    wk: jnp.ndarray,
    wv: jnp.ndarray,
    wo: jnp.ndarray,
    *,
    num_heads: int,
    causal: bool = False,
    mask: Optional[jnp.ndarray] = None,
) -> jnp.ndarray:
    """Full MHA block: project → attend → merge. x: [b, t, f]."""
    b, t, f = x.shape
    d = wq.shape[-1] // num_heads
    q = (x @ wq).reshape(b, t, num_heads, d)
    k = (x @ wk).reshape(b, t, num_heads, d)
    v = (x @ wv).reshape(b, t, num_heads, d)
    o = dot_product_attention(q, k, v, causal=causal, mask=mask)
    return o.reshape(b, t, num_heads * d) @ wo
