"""Loss functions with masking support.

Covers the nd4j ``LossFunctions.LossFunction`` kinds the reference uses (56
import sites; MCXENT / NEGATIVELOGLIKELIHOOD / RMSE_XENT /
RECONSTRUCTION_CROSSENTROPY plus the rest of the enum — SURVEY §2.2) as pure
jax functions over [batch, ...] activations.

Masking: every loss takes an optional ``mask`` broadcastable to
[batch] or [batch, time] (per-example / per-timestep), mirroring the
reference's variable-length time-series handling
(nn/multilayer/MultiLayerNetwork.java mask plumbing, TestVariableLengthTS).
Score is the mask-weighted mean over examples, matching the reference's
minibatch-size division in BaseUpdater.update.
"""

from __future__ import annotations

import enum
from typing import Callable, Optional

import jax.numpy as jnp

_EPS = 1e-8


class LossFunction(str, enum.Enum):
    MSE = "MSE"
    SQUARED_LOSS = "SQUARED_LOSS"
    L1 = "L1"
    XENT = "XENT"  # binary cross entropy (sigmoid outputs)
    MCXENT = "MCXENT"  # multi-class cross entropy (softmax outputs)
    NEGATIVELOGLIKELIHOOD = "NEGATIVELOGLIKELIHOOD"
    RMSE_XENT = "RMSE_XENT"
    RECONSTRUCTION_CROSSENTROPY = "RECONSTRUCTION_CROSSENTROPY"
    EXPLL = "EXPLL"  # exponential log likelihood (Poisson-style)
    COSINE_PROXIMITY = "COSINE_PROXIMITY"
    HINGE = "HINGE"
    SQUARED_HINGE = "SQUARED_HINGE"
    KL_DIVERGENCE = "KL_DIVERGENCE"
    MEAN_ABSOLUTE_PERCENTAGE_ERROR = "MEAN_ABSOLUTE_PERCENTAGE_ERROR"
    POISSON = "POISSON"
    CUSTOM = "CUSTOM"


# Per-example loss: (output, labels) -> [batch, ...] elementwise/row scores
# reduced over the feature axis only; batch/time reduction happens centrally
# so masking is applied uniformly.


def _mse(out, y):
    return jnp.sum((out - y) ** 2, axis=-1) / out.shape[-1]


def _squared(out, y):
    return jnp.sum((out - y) ** 2, axis=-1)


def _l1(out, y):
    return jnp.sum(jnp.abs(out - y), axis=-1)


def _xent(out, y):
    out = jnp.clip(out, _EPS, 1.0 - _EPS)
    return -jnp.sum(y * jnp.log(out) + (1.0 - y) * jnp.log1p(-out), axis=-1)


def _mcxent(out, y):
    out = jnp.clip(out, _EPS, 1.0)
    return -jnp.sum(y * jnp.log(out), axis=-1)


def _rmse_xent(out, y):
    return jnp.sqrt(_mse(out, y) + _EPS)


def _expll(out, y):
    out = jnp.clip(out, _EPS, None)
    return jnp.sum(out - y * jnp.log(out), axis=-1)


def _cosine(out, y):
    num = jnp.sum(out * y, axis=-1)
    den = jnp.linalg.norm(out, axis=-1) * jnp.linalg.norm(y, axis=-1) + _EPS
    return -num / den


def _hinge(out, y):
    # labels in {0,1} one-hot or {-1,1}; map one-hot to +/-1
    sign = jnp.where(y > 0, 1.0, -1.0)
    return jnp.sum(jnp.maximum(0.0, 1.0 - sign * out), axis=-1)


def _squared_hinge(out, y):
    sign = jnp.where(y > 0, 1.0, -1.0)
    return jnp.sum(jnp.maximum(0.0, 1.0 - sign * out) ** 2, axis=-1)


def _kld(out, y):
    out = jnp.clip(out, _EPS, 1.0)
    yc = jnp.clip(y, _EPS, 1.0)
    return jnp.sum(yc * (jnp.log(yc) - jnp.log(out)), axis=-1)


def _mape(out, y):
    return 100.0 * jnp.sum(jnp.abs((y - out) / (jnp.abs(y) + _EPS)), axis=-1) / out.shape[-1]


def _poisson(out, y):
    out = jnp.clip(out, _EPS, None)
    return jnp.sum(out - y * jnp.log(out), axis=-1)


_TABLE: dict[LossFunction, Callable] = {
    LossFunction.MSE: _mse,
    LossFunction.SQUARED_LOSS: _squared,
    LossFunction.L1: _l1,
    LossFunction.XENT: _xent,
    LossFunction.MCXENT: _mcxent,
    # In the reference NLL over softmax outputs is computed identically to
    # MCXENT (nd4j LossCalculation); keep that equivalence.
    LossFunction.NEGATIVELOGLIKELIHOOD: _mcxent,
    LossFunction.RMSE_XENT: _rmse_xent,
    LossFunction.RECONSTRUCTION_CROSSENTROPY: _xent,
    LossFunction.EXPLL: _expll,
    LossFunction.COSINE_PROXIMITY: _cosine,
    LossFunction.HINGE: _hinge,
    LossFunction.SQUARED_HINGE: _squared_hinge,
    LossFunction.KL_DIVERGENCE: _kld,
    LossFunction.MEAN_ABSOLUTE_PERCENTAGE_ERROR: _mape,
    LossFunction.POISSON: _poisson,
}

_CUSTOM: dict[str, Callable] = {}


def register_loss(name: str, fn: Callable) -> None:
    """Register a CUSTOM loss: fn(output, labels) -> per-example scores."""
    _CUSTOM[name] = fn


def compute_loss(
    loss: LossFunction | str,
    output: jnp.ndarray,
    labels: jnp.ndarray,
    mask: Optional[jnp.ndarray] = None,
    custom_name: Optional[str] = None,
) -> jnp.ndarray:
    """Mask-weighted mean per-example loss (scalar).

    ``output``/``labels``: [batch, features] or [batch, time, features].
    ``mask``: broadcastable to the per-example score shape ([batch] or
    [batch, time]); masked-out entries contribute nothing and the mean is
    over the mask sum (so padded timesteps don't dilute the score).
    """
    if isinstance(loss, str):
        loss = LossFunction(loss)
    if loss is LossFunction.CUSTOM:
        if custom_name is None or custom_name not in _CUSTOM:
            raise ValueError(f"CUSTOM loss requires a registered name, got {custom_name!r}")
        per_example = _CUSTOM[custom_name](output, labels)
    else:
        per_example = _TABLE[loss](output, labels)
    if mask is not None:
        mask = jnp.asarray(mask, per_example.dtype)
        mask = jnp.broadcast_to(mask.reshape(mask.shape + (1,) * (per_example.ndim - mask.ndim)), per_example.shape)
        total = jnp.sum(per_example * mask)
        denom = jnp.maximum(jnp.sum(mask), 1.0)
        return total / denom
    return jnp.mean(per_example)


def per_example_loss(loss: LossFunction | str, output, labels,
                     custom_name: Optional[str] = None):
    """Unreduced per-example scores (used by score_examples / listeners)."""
    if isinstance(loss, str):
        loss = LossFunction(loss)
    if loss is LossFunction.CUSTOM:
        if custom_name is None or custom_name not in _CUSTOM:
            raise ValueError(f"CUSTOM loss requires a registered name, got {custom_name!r}")
        return _CUSTOM[custom_name](output, labels)
    return _TABLE[loss](output, labels)
