"""Weight initialization schemes.

Mirrors the reference's ``WeightInit`` enum (nn/weights/WeightInit.java:
DISTRIBUTION, NORMALIZED, SIZE, UNIFORM, VI, ZERO, XAVIER, RELU) and
``WeightInitUtil.java:81-106`` semantics, expressed with jax's functional PRNG
instead of a global ND4J RNG.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp


def init_weights(
    key: jax.Array,
    shape: Sequence[int],
    scheme: str = "XAVIER",
    fan_in: Optional[int] = None,
    fan_out: Optional[int] = None,
    distribution: Optional[dict] = None,
    dtype=jnp.float32,
) -> jnp.ndarray:
    """Sample a weight tensor.

    ``fan_in``/``fan_out`` default to shape[0]/shape[-1] for 2-D matrices; conv
    layers pass receptive-field-scaled fans explicitly.
    """
    shape = tuple(int(s) for s in shape)
    if fan_in is None:
        fan_in = shape[0] if len(shape) >= 1 else 1
    if fan_out is None:
        fan_out = shape[-1] if len(shape) >= 2 else shape[0]
    scheme = scheme.upper()

    if scheme == "ZERO":
        return jnp.zeros(shape, dtype)
    if scheme == "ONES":
        return jnp.ones(shape, dtype)
    if scheme == "UNIFORM":
        # reference: U(-a, a) with a = 1/sqrt(fanIn)
        a = 1.0 / jnp.sqrt(float(fan_in))
        return jax.random.uniform(key, shape, dtype, -a, a)
    if scheme == "XAVIER":
        # reference WeightInitUtil: N(0,1) * sqrt(2/(fanIn+fanOut))
        return jax.random.normal(key, shape, dtype) * jnp.sqrt(2.0 / (fan_in + fan_out)).astype(dtype)
    if scheme == "XAVIER_UNIFORM":
        a = jnp.sqrt(6.0 / (fan_in + fan_out))
        return jax.random.uniform(key, shape, dtype, -a, a)
    if scheme == "RELU":
        # He init: N(0,1) * sqrt(2/fanIn)
        return jax.random.normal(key, shape, dtype) * jnp.sqrt(2.0 / fan_in).astype(dtype)
    if scheme == "LECUN":
        return jax.random.normal(key, shape, dtype) * jnp.sqrt(1.0 / fan_in).astype(dtype)
    if scheme == "VI":
        # reference: U(-r, r), r = 4 * sqrt(6/(fanIn+fanOut))
        r = 4.0 * jnp.sqrt(6.0 / (fan_in + fan_out))
        return jax.random.uniform(key, shape, dtype, -r, r)
    if scheme == "SIZE":
        # reference SIZE: uniform scaled by sqrt of shape product heuristic
        r = jnp.sqrt(6.0 / (fan_in + fan_out))
        return jax.random.uniform(key, shape, dtype, -r, r)
    if scheme == "NORMALIZED":
        # reference: U(0,1) - 0.5 scaled by 1/shape heuristic
        return (jax.random.uniform(key, shape, dtype) - 0.5) / jnp.asarray(float(shape[0]), dtype)
    if scheme == "DISTRIBUTION":
        return _from_distribution(key, shape, distribution or {}, dtype)
    raise ValueError(f"unknown weight init scheme {scheme!r}")


def _from_distribution(key, shape, dist: dict, dtype):
    """DISTRIBUTION init from a config dict: the reference's nd4j Distribution
    polymorphic configs (NormalDistribution/UniformDistribution/
    BinomialDistribution — nn/conf serde)."""
    kind = dist.get("type", "normal").lower()
    if kind in ("normal", "gaussian"):
        mean = float(dist.get("mean", 0.0))
        std = float(dist.get("std", dist.get("sd", 1.0)))
        return mean + std * jax.random.normal(key, shape, dtype)
    if kind == "uniform":
        lower = float(dist.get("lower", -1.0))
        upper = float(dist.get("upper", 1.0))
        return jax.random.uniform(key, shape, dtype, lower, upper)
    if kind == "binomial":
        n = int(dist.get("n", dist.get("numberOfTrials", 1)))
        p = float(dist.get("p", dist.get("probabilityOfSuccess", 0.5)))
        return jnp.asarray(
            jax.random.binomial(key, n, p, shape=shape), dtype
        )
    raise ValueError(f"unknown distribution {kind!r}")


def conv_fans(kernel_shape: Tuple[int, ...]) -> Tuple[int, int]:
    """fan_in/fan_out for a conv kernel in HWIO layout [kh, kw, in_c, out_c]."""
    receptive = 1
    for k in kernel_shape[:-2]:
        receptive *= int(k)
    fan_in = receptive * int(kernel_shape[-2])
    fan_out = receptive * int(kernel_shape[-1])
    return fan_in, fan_out
