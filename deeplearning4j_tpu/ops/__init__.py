"""Tensor/op substrate — the framework's equivalent of the ND4J op catalog.

The reference delegates every tensor op to the external ND4J library
(activation transforms, losses, updater math, GEMM/conv; see SURVEY §2.2,
citing deeplearning4j-core/pom.xml:153-158). Here the op catalog is a thin,
typed layer over ``jax.numpy``/``jax.lax`` that XLA fuses into single TPU
programs — there is no per-op dispatch at runtime.
"""

from deeplearning4j_tpu.ops.activations import (  # noqa: F401
    get_activation,
    activation_names,
)
from deeplearning4j_tpu.ops.losses import (  # noqa: F401
    LossFunction,
    compute_loss,
)
from deeplearning4j_tpu.ops.initializers import init_weights  # noqa: F401
