"""Activation registry.

The reference configures activations as strings and executes them through
``Nd4j.getExecutioner().execAndReturn(createTransform(name, ...))`` (SURVEY
§2.2: sigmoid/softmax/tanh/relu/identity/softsign call-site counts). Here each
activation is a pure jax function; inside ``jit`` XLA fuses it into the
surrounding matmul, so the registry is a config-time concern only.

String names mirror the reference's config DSL (``activation("tanh")`` etc. in
nn/conf/layers/Layer.java:307) so JSON configs written against the reference
vocabulary load unchanged.
"""

from __future__ import annotations

from typing import Callable, Dict

import jax
import jax.numpy as jnp

Activation = Callable[[jnp.ndarray], jnp.ndarray]


def _identity(x):
    return x


def _leakyrelu(x, alpha=0.01):
    return jnp.where(x >= 0, x, alpha * x)


def _softsign(x):
    return x / (1.0 + jnp.abs(x))


def _hardtanh(x):
    return jnp.clip(x, -1.0, 1.0)


def _hardsigmoid(x):
    return jnp.clip(0.2 * x + 0.5, 0.0, 1.0)


def _cube(x):
    return x * x * x


def _rationaltanh(x):
    # Rational approximation of tanh used by nd4j's RationalTanh transform:
    # 1.7159 * softsign-style rational curve; cheap on scalar units, but on
    # TPU we keep it mainly for config parity.
    a = 0.6666667 * x
    return 1.7159 * a / (1.0 + jnp.abs(a))


def _softmax(x):
    # Row-wise softmax over the feature (last) axis, matching nd4j SoftMax
    # semantics on [batch, features] activations.
    return jax.nn.softmax(x, axis=-1)


_REGISTRY: Dict[str, Activation] = {
    "identity": _identity,
    "linear": _identity,
    "sigmoid": jax.nn.sigmoid,
    "tanh": jnp.tanh,
    "relu": jax.nn.relu,
    "relu6": jax.nn.relu6,
    "leakyrelu": _leakyrelu,
    "elu": jax.nn.elu,
    "selu": jax.nn.selu,
    "gelu": jax.nn.gelu,
    "silu": jax.nn.silu,
    "swish": jax.nn.silu,
    "softmax": _softmax,
    "logsoftmax": lambda x: jax.nn.log_softmax(x, axis=-1),
    "softsign": _softsign,
    "softplus": jax.nn.softplus,
    "hardtanh": _hardtanh,
    "hardsigmoid": _hardsigmoid,
    "cube": _cube,
    "rationaltanh": _rationaltanh,
    "abs": jnp.abs,
    "sign": jnp.sign,
    "exp": jnp.exp,
}


def get_activation(name: str) -> Activation:
    """Look up an activation by its config-DSL name (case-insensitive)."""
    fn = _REGISTRY.get(name.lower())
    if fn is None:
        raise ValueError(
            f"unknown activation {name!r}; known: {sorted(_REGISTRY)}"
        )
    return fn


def activation_names() -> list[str]:
    return sorted(_REGISTRY)


def register_activation(name: str, fn: Activation) -> None:
    """Register a custom activation (the reference's CUSTOM escape hatch)."""
    _REGISTRY[name.lower()] = fn
