"""Archive extraction (util/ArchiveUtils.java, 161 LoC: unzip/untar/gunzip
with path traversal left to the JVM). Stdlib zipfile/tarfile/gzip, with
explicit zip-slip protection the reference lacked."""

from __future__ import annotations

import gzip
import os
import shutil
import tarfile
import zipfile


def _check_within(base: str, target: str) -> None:
    base = os.path.abspath(base)
    if os.path.commonpath([base, os.path.abspath(target)]) != base:
        raise ValueError(f"archive entry escapes destination: {target}")


def unzip_file_to(archive: str, dest_dir: str) -> None:
    """ArchiveUtils.unzipFileTo — dispatches on extension."""
    os.makedirs(dest_dir, exist_ok=True)
    if archive.endswith(".zip"):
        with zipfile.ZipFile(archive) as zf:
            for name in zf.namelist():
                _check_within(dest_dir, os.path.join(dest_dir, name))
            zf.extractall(dest_dir)
    elif archive.endswith((".tar.gz", ".tgz", ".tar")):
        mode = "r:gz" if archive.endswith(("gz", "tgz")) else "r"
        with tarfile.open(archive, mode) as tf:
            for member in tf.getmembers():
                _check_within(dest_dir, os.path.join(dest_dir, member.name))
            tf.extractall(dest_dir, filter="data")
    elif archive.endswith(".gz"):
        out = os.path.join(dest_dir,
                           os.path.basename(archive)[: -len(".gz")])
        with gzip.open(archive, "rb") as src, open(out, "wb") as dst:
            shutil.copyfileobj(src, dst)
    else:
        raise ValueError(f"unsupported archive type: {archive}")
