"""Host-side utilities (serialization, misc math)."""

from deeplearning4j_tpu.utils.serializer import ModelSerializer  # noqa: F401
