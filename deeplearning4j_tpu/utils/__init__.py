"""Host-side utilities (reference: deeplearning4j-core util/ — ModelSerializer,
ImageLoader, ArchiveUtils, DiskBasedQueue, StringGrid, MathUtils)."""

from deeplearning4j_tpu.utils.serializer import ModelSerializer  # noqa: F401
from deeplearning4j_tpu.utils.archive import unzip_file_to  # noqa: F401
from deeplearning4j_tpu.utils.diskqueue import DiskBasedQueue  # noqa: F401
from deeplearning4j_tpu.utils.stringgrid import StringGrid  # noqa: F401
from deeplearning4j_tpu.utils.image import (  # noqa: F401
    as_matrix,
    as_row_vector,
    decode_png,
    load_image,
    resize,
    save_pgm,
)
