"""MovingWindowMatrix: sliding-window tiling of a 2-D array.

Re-design of the reference's ``util/MovingWindowMatrix.java`` (window
extraction + optional rot90 augmentation feeding
MovingWindowDataSetFetcher). Windows tile the matrix with stride equal to
the window size; ragged edges are dropped, matching the reference's
whole-window semantics.
"""

from __future__ import annotations

from typing import List

import numpy as np


class MovingWindowMatrix:
    def __init__(self, to_slice: np.ndarray, window_rows: int = 28,
                 window_cols: int = 28, add_rotate: bool = False):
        self.to_slice = np.asarray(to_slice)
        if self.to_slice.ndim != 2:
            raise ValueError(
                f"MovingWindowMatrix expects a 2-D matrix, got shape "
                f"{self.to_slice.shape}")
        self.window_rows = int(window_rows)
        self.window_cols = int(window_cols)
        self.add_rotate = bool(add_rotate)

    def windows(self, flattened: bool = False) -> List[np.ndarray]:
        """All whole window tiles in row-major order; with ``add_rotate``
        each tile is followed by its three rot90 orientations."""
        h, w = self.to_slice.shape
        wr, wc = self.window_rows, self.window_cols
        out: List[np.ndarray] = []
        for r in range(0, h - wr + 1, wr):
            for c in range(0, w - wc + 1, wc):
                tile = self.to_slice[r:r + wr, c:c + wc]
                variants = [tile]
                if self.add_rotate and wr == wc:
                    # rot90 keeps shape only for square windows
                    cur = tile
                    for _ in range(3):
                        cur = np.rot90(cur)
                        variants.append(cur)
                for v in variants:
                    out.append(v.ravel().copy() if flattened else v.copy())
        return out
