"""Image loading to arrays (util/ImageLoader.java, 196 LoC).

The reference wraps javax.imageio into ``asRowVector``/``asMatrix`` plus
nearest-neighbor resizing. Here: PIL when present; otherwise built-in
decoders for PNG (8-bit gray/RGB/RGBA, non-interlaced — stdlib zlib) and
binary PPM/PGM, which covers the framework's own outputs and common test
fixtures without native deps.
"""

from __future__ import annotations

import struct
import zlib
from typing import Optional, Tuple

import numpy as np


def load_image(path: str) -> np.ndarray:
    """File → uint8 array [H, W] (gray) or [H, W, C]."""
    with open(path, "rb") as f:
        data = f.read()
    if data[:8] == b"\x89PNG\r\n\x1a\n":
        return decode_png(data)
    if data[:2] in (b"P5", b"P6"):
        return _decode_pnm(data)
    try:  # other formats (JPEG…): delegate to PIL when present
        from PIL import Image, UnidentifiedImageError
    except ImportError:
        raise ValueError(f"unsupported image format: {path}")
    try:
        return np.asarray(Image.open(path))
    except UnidentifiedImageError:
        raise ValueError(f"unsupported image format: {path}")


def as_matrix(path: str) -> np.ndarray:
    """ImageLoader.asMatrix: float32 in [0, 1]."""
    return np.asarray(load_image(path), np.float32) / 255.0


def as_row_vector(path: str) -> np.ndarray:
    """ImageLoader.asRowVector: flattened float32."""
    return as_matrix(path).ravel()


def resize(img: np.ndarray, height: int, width: int) -> np.ndarray:
    """Nearest-neighbor resize (the reference's scaling strategy)."""
    img = np.asarray(img)
    rows = (np.arange(height) * img.shape[0] // height).clip(
        0, img.shape[0] - 1)
    cols = (np.arange(width) * img.shape[1] // width).clip(
        0, img.shape[1] - 1)
    return img[rows][:, cols]


def decode_png(data: bytes) -> np.ndarray:
    """Minimal PNG decoder: 8-bit grayscale/RGB/RGBA, non-interlaced."""
    pos = 8
    width = height = None
    color_type = None
    idat = b""
    while pos < len(data):
        (length,) = struct.unpack(">I", data[pos:pos + 4])
        kind = data[pos + 4:pos + 8]
        chunk = data[pos + 8:pos + 8 + length]
        pos += 12 + length
        if kind == b"IHDR":
            width, height, bit_depth, color_type, _, _, interlace = \
                struct.unpack(">IIBBBBB", chunk)
            if bit_depth != 8 or interlace != 0:
                raise ValueError("only 8-bit non-interlaced PNG supported")
        elif kind == b"IDAT":
            idat += chunk
        elif kind == b"IEND":
            break
    if width is None:
        raise ValueError("no IHDR chunk")
    channels = {0: 1, 2: 3, 6: 4}.get(color_type)
    if channels is None:
        raise ValueError(f"unsupported PNG color type {color_type}")
    raw = zlib.decompress(idat)
    stride = width * channels
    out = np.zeros((height, stride), np.uint8)
    prev = np.zeros(stride, np.int32)
    pos = 0
    for r in range(height):
        filt = raw[pos]
        row = np.frombuffer(raw[pos + 1:pos + 1 + stride],
                            np.uint8).astype(np.int32)
        pos += 1 + stride
        if filt == 0:
            cur = row
        elif filt == 2:  # Up
            cur = (row + prev) % 256
        elif filt in (1, 3, 4):  # Sub / Average / Paeth need a scalar loop
            cur = np.zeros(stride, np.int32)
            for i in range(stride):
                a = cur[i - channels] if i >= channels else 0
                b = prev[i]
                cpx = prev[i - channels] if i >= channels else 0
                if filt == 1:
                    pred = a
                elif filt == 3:
                    pred = (a + b) // 2
                else:
                    p = a + b - cpx
                    pa, pb, pc = abs(p - a), abs(p - b), abs(p - cpx)
                    pred = a if pa <= pb and pa <= pc else (
                        b if pb <= pc else cpx)
                cur[i] = (row[i] + pred) % 256
        else:
            raise ValueError(f"unknown PNG filter {filt}")
        out[r] = cur.astype(np.uint8)
        prev = cur
    img = out.reshape(height, width, channels)
    return img[:, :, 0] if channels == 1 else img


def _decode_pnm(data: bytes) -> np.ndarray:
    """Binary PGM (P5) / PPM (P6)."""
    parts = []
    pos = 2
    while len(parts) < 3:
        while pos < len(data) and data[pos:pos + 1].isspace():
            pos += 1
        if data[pos:pos + 1] == b"#":  # comment line
            while data[pos:pos + 1] not in (b"\n", b""):
                pos += 1
            continue
        start = pos
        while pos < len(data) and not data[pos:pos + 1].isspace():
            pos += 1
        parts.append(int(data[start:pos]))
    pos += 1  # single whitespace after maxval
    width, height, maxval = parts
    if not 0 < maxval <= 255:
        raise ValueError(f"only 8-bit PNM supported (maxval {maxval})")
    channels = 3 if data[:2] == b"P6" else 1
    pixels = np.frombuffer(data, np.uint8, count=width * height * channels,
                           offset=pos)
    img = pixels.reshape(height, width, channels)
    if maxval != 255:  # rescale so as_matrix's /255 is correct
        img = (img.astype(np.uint16) * 255 // maxval).astype(np.uint8)
    return img[:, :, 0] if channels == 1 else img


def save_pgm(path: str, img: np.ndarray) -> None:
    """Write grayscale uint8 as binary PGM (for tests/visualization)."""
    img = np.ascontiguousarray(img, np.uint8)
    if img.ndim != 2:
        raise ValueError("PGM is grayscale-only")
    with open(path, "wb") as f:
        f.write(f"P5\n{img.shape[1]} {img.shape[0]}\n255\n".encode())
        f.write(img.tobytes())
