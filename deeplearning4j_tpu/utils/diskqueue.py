"""Disk-backed FIFO queue (util/DiskBasedQueue.java, 205 LoC).

The reference spills queued items to one file per element under a temp dir
so unbounded producer queues don't exhaust the heap (used by the NLP vocab
pipeline). Same design: pickle per element, FIFO by monotonically increasing
file index, thread-safe, iterable-drainable.
"""

from __future__ import annotations

import os
import pickle
import tempfile
import threading
from typing import Any, Iterator, Optional


class DiskBasedQueue:
    def __init__(self, dir_path: Optional[str] = None):
        self._dir = dir_path or tempfile.mkdtemp(prefix="dl4j-queue-")
        os.makedirs(self._dir, exist_ok=True)
        self._lock = threading.Lock()
        self._head = 0  # next index to pop
        self._tail = 0  # next index to write

    def _path(self, i: int) -> str:
        return os.path.join(self._dir, f"{i:012d}.pkl")

    def add(self, item: Any) -> None:
        if item is None:
            raise ValueError("None cannot be queued (poll's empty sentinel)")
        # serialize outside the lock; claim the index AND publish the file
        # under it, so poll can never reserve an index whose file is missing
        fd, tmp = tempfile.mkstemp(dir=self._dir, suffix=".tmp")
        with os.fdopen(fd, "wb") as f:
            pickle.dump(item, f, protocol=pickle.HIGHEST_PROTOCOL)
        with self._lock:
            idx = self._tail
            os.replace(tmp, self._path(idx))
            self._tail += 1

    def poll(self) -> Optional[Any]:
        """Pop the oldest item; None when empty (Queue.poll semantics)."""
        with self._lock:
            if self._head >= self._tail:
                return None
            idx = self._head
            self._head += 1
        path = self._path(idx)
        with open(path, "rb") as f:
            item = pickle.load(f)
        os.unlink(path)
        return item

    def size(self) -> int:
        with self._lock:
            return self._tail - self._head

    def is_empty(self) -> bool:
        return self.size() == 0

    def drain(self) -> Iterator[Any]:
        while True:
            item = self.poll()
            if item is None:
                return
            yield item

    def close(self) -> None:
        with self._lock:
            for i in range(self._head, self._tail):
                try:
                    os.unlink(self._path(i))
                except FileNotFoundError:
                    pass
            self._head = self._tail
        try:
            os.rmdir(self._dir)
        except OSError:
            pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
