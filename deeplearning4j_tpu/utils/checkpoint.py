"""Sharding-aware pytree checkpoints via Orbax.

The TPU-native complement to ``utils.ModelSerializer`` (which keeps the
reference's zip format — SURVEY §5 "checkpoint/resume"): Orbax writes each
array once from wherever it is sharded and restores onto any mesh layout,
which is what multi-host elastic restart actually needs (the role HDFS
model IO played for the reference's YARN runtime). State = any pytree —
typically ``{"params": ..., "updater_state": ..., "iteration": ...}``.
"""

from __future__ import annotations

import os
from typing import Any, Optional

import jax


def _manager(directory: str, keep: int = 3):
    import orbax.checkpoint as ocp

    return ocp.CheckpointManager(
        os.path.abspath(directory),
        options=ocp.CheckpointManagerOptions(max_to_keep=keep,
                                             create=True),
    )


def _strip_empty(tree: Any) -> Any:
    """Replace zero-size array leaves with None (Orbax refuses to
    serialize empty arrays). The SGD/NONE updaters use ``zeros((0,))``
    state placeholders, so network states routinely contain them;
    ``restore_checkpoint(target=...)`` reinstates them from the target."""
    return jax.tree_util.tree_map(
        lambda x: None if getattr(x, "size", 1) == 0 else x, tree)


def _has_nonempty_leaves(tree: Any) -> bool:
    return any(getattr(leaf, "size", 1) != 0
               for leaf in jax.tree_util.tree_leaves(tree))


def _reinstate_empty(restored: Any, target: Any, path: str = "") -> Any:
    """Paired walk: wherever ``target`` holds a zero-size array (stripped
    to None at save time), keep the target's placeholder; everywhere else
    take the restored value. A restored tree missing a subtree that
    should carry DATA is a structure mismatch and raises (all-empty
    subtrees are legitimately absent)."""
    if isinstance(target, dict):
        rd = restored if isinstance(restored, dict) else {}
        out = {}
        for k, v in target.items():
            sub_path = f"{path}/{k}" if path else str(k)
            if k not in rd:
                if _has_nonempty_leaves(v):
                    raise ValueError(
                        f"restored checkpoint is missing entry "
                        f"{sub_path!r} (incompatible target?)")
                out[k] = v  # all-empty subtree: target placeholders
                continue
            out[k] = _reinstate_empty(rd[k], v, sub_path)
        return out
    if isinstance(target, (list, tuple)):
        rl = restored if isinstance(restored, (list, tuple)) \
            else [None] * len(target)
        merged = [_reinstate_empty(r, t, f"{path}/[{i}]")
                  for i, (r, t) in enumerate(zip(rl, target))]
        if isinstance(target, tuple) and hasattr(target, "_fields"):
            return type(target)(*merged)  # namedtuple protocol
        return type(target)(merged)
    if getattr(target, "size", 1) == 0:
        return target
    return restored


class NetworkCheckpointer:
    """Persistent manager for PERIODIC in-training saves: one Orbax
    CheckpointManager per directory, saves run asynchronously (training
    overlaps the write; Orbax serializes overlapping saves), and
    ``close()`` drains the queue. One-shot callers should keep using
    :func:`save_network`, which waits and closes per call."""

    def __init__(self, directory: str, keep: int = 3):
        self._mgr = _manager(directory, keep)

    def save(self, network, step: int) -> None:
        import orbax.checkpoint as ocp

        self._mgr.save(step, args=ocp.args.StandardSave(
            _strip_empty(_network_state(network))))

    def close(self) -> None:
        self._mgr.wait_until_finished()
        self._mgr.close()


def save_checkpoint(directory: str, state: Any, step: int,
                    keep: int = 3) -> None:
    """Write ``state`` (pytree of arrays/scalars) as step ``step``."""
    import orbax.checkpoint as ocp

    mgr = _manager(directory, keep)
    mgr.save(step, args=ocp.args.StandardSave(_strip_empty(state)))
    mgr.wait_until_finished()
    mgr.close()


def latest_step(directory: str) -> Optional[int]:
    import orbax.checkpoint as ocp

    if not os.path.isdir(directory):
        return None
    mgr = _manager(directory)
    try:
        return mgr.latest_step()
    finally:
        mgr.close()


def restore_checkpoint(directory: str, target: Any = None,
                       step: Optional[int] = None) -> Any:
    """Restore a checkpoint. ``target``: an example pytree (arrays may be
    abstract ``jax.ShapeDtypeStruct`` with shardings) that fixes structure,
    dtypes, and placement; None restores as plain arrays. ``step``: None →
    newest."""
    import orbax.checkpoint as ocp

    mgr = _manager(directory)
    try:
        if step is None:
            step = mgr.latest_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoints under {directory}")
        if target is None:
            return mgr.restore(step)
        import numpy as np

        def _abstract(x):
            if isinstance(x, jax.ShapeDtypeStruct):
                return x
            dtype = getattr(x, "dtype", None)
            if dtype is not None:
                # jax/np arrays: shape/dtype without touching the data —
                # sharded leaves may span non-addressable devices.
                return jax.ShapeDtypeStruct(
                    x.shape, dtype, sharding=getattr(x, "sharding", None))
            # scalar python leaves (int/float) lack a dtype; np.asarray
            # supplies one. Bare dtype=None made StandardRestore
            # unconditionally fail on them.
            arr = np.asarray(x)
            return jax.ShapeDtypeStruct(arr.shape, arr.dtype)

        abstract = jax.tree_util.tree_map(_abstract, _strip_empty(target))
        restored = mgr.restore(step,
                               args=ocp.args.StandardRestore(abstract))
        return _reinstate_empty(restored, target)
    finally:
        mgr.close()


def _network_state(network) -> dict:
    """The training-state pytree for any supported model class.

    MultiLayerNetwork/ComputationGraph carry (params, updater_state,
    net_state, iteration_count); TransformerLM carries (params, opt_state,
    step_count). Sharded TransformerLM states (TP via shard_params, FSDP)
    checkpoint as-is — Orbax writes each shard from where it lives, which
    is exactly the multi-host path ModelSerializer's zip format refuses.
    """
    ensure = getattr(network, "_ensure_init", None)
    if ensure is None:
        raise TypeError(
            f"cannot checkpoint {type(network).__name__}: expected a "
            "MultiLayerNetwork/ComputationGraph/TransformerLM (for the "
            "FSDP trainer, checkpoint the wrapped model)")
    ensure()
    if hasattr(network, "opt_state") and hasattr(network, "step_count"):
        # TransformerLM (the FSDP wrapper also has opt_state but no
        # step_count — it is not a model; checkpoint the model it wraps)
        return {
            "params": network.params,
            "updater_state": network.opt_state,
            "iteration": network.step_count,
        }
    if not hasattr(network, "updater_state"):
        raise TypeError(
            f"cannot checkpoint {type(network).__name__}: expected a "
            "MultiLayerNetwork/ComputationGraph/TransformerLM (for the "
            "FSDP trainer, checkpoint the wrapped model)")
    return {
        "params": network.params,
        "updater_state": network.updater_state,
        "net_state": network.net_state,
        "iteration": network.iteration_count,
    }


def save_network(directory: str, network, step: Optional[int] = None,
                 keep: int = 3) -> None:
    """Checkpoint a MultiLayerNetwork/ComputationGraph/TransformerLM's
    training state."""
    state = _network_state(network)
    save_checkpoint(directory, state,
                    step if step is not None else int(state["iteration"]),
                    keep=keep)


def restore_network(directory: str, network,
                    step: Optional[int] = None):
    """Restore training state saved by ``save_network`` into ``network``."""
    target = _network_state(network)
    target["iteration"] = 0
    state = restore_checkpoint(directory, target=target, step=step)
    network.params = state["params"]
    if hasattr(network, "opt_state"):
        network.opt_state = state["updater_state"]
        network.step_count = int(state["iteration"])
    else:
        network.updater_state = state["updater_state"]
        network.net_state = state["net_state"]
        network.iteration_count = int(state["iteration"])
    return network
