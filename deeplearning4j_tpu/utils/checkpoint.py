"""Sharding-aware pytree checkpoints via Orbax.

The TPU-native complement to ``utils.ModelSerializer`` (which keeps the
reference's zip format — SURVEY §5 "checkpoint/resume"): Orbax writes each
array once from wherever it is sharded and restores onto any mesh layout,
which is what multi-host elastic restart actually needs (the role HDFS
model IO played for the reference's YARN runtime). State = any pytree —
typically ``{"params": ..., "updater_state": ..., "iteration": ...}``.
"""

from __future__ import annotations

import os
from typing import Any, Optional

import jax


def _manager(directory: str, keep: int = 3):
    import orbax.checkpoint as ocp

    return ocp.CheckpointManager(
        os.path.abspath(directory),
        options=ocp.CheckpointManagerOptions(max_to_keep=keep,
                                             create=True),
    )


def save_checkpoint(directory: str, state: Any, step: int,
                    keep: int = 3) -> None:
    """Write ``state`` (pytree of arrays/scalars) as step ``step``."""
    import orbax.checkpoint as ocp

    mgr = _manager(directory, keep)
    mgr.save(step, args=ocp.args.StandardSave(state))
    mgr.wait_until_finished()
    mgr.close()


def latest_step(directory: str) -> Optional[int]:
    import orbax.checkpoint as ocp

    if not os.path.isdir(directory):
        return None
    mgr = _manager(directory)
    try:
        return mgr.latest_step()
    finally:
        mgr.close()


def restore_checkpoint(directory: str, target: Any = None,
                       step: Optional[int] = None) -> Any:
    """Restore a checkpoint. ``target``: an example pytree (arrays may be
    abstract ``jax.ShapeDtypeStruct`` with shardings) that fixes structure,
    dtypes, and placement; None restores as plain arrays. ``step``: None →
    newest."""
    import orbax.checkpoint as ocp

    mgr = _manager(directory)
    try:
        if step is None:
            step = mgr.latest_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoints under {directory}")
        if target is None:
            return mgr.restore(step)
        import numpy as np

        def _abstract(x):
            if isinstance(x, jax.ShapeDtypeStruct):
                return x
            dtype = getattr(x, "dtype", None)
            if dtype is not None:
                # jax/np arrays: shape/dtype without touching the data —
                # sharded leaves may span non-addressable devices.
                return jax.ShapeDtypeStruct(
                    x.shape, dtype, sharding=getattr(x, "sharding", None))
            # scalar python leaves (int/float) lack a dtype; np.asarray
            # supplies one. Bare dtype=None made StandardRestore
            # unconditionally fail on them.
            arr = np.asarray(x)
            return jax.ShapeDtypeStruct(arr.shape, arr.dtype)

        abstract = jax.tree_util.tree_map(_abstract, target)
        return mgr.restore(step, args=ocp.args.StandardRestore(abstract))
    finally:
        mgr.close()


def save_network(directory: str, network, step: Optional[int] = None,
                 keep: int = 3) -> None:
    """Checkpoint a MultiLayerNetwork/ComputationGraph's training state."""
    save_checkpoint(directory, {
        "params": network.params,
        "updater_state": network.updater_state,
        "net_state": network.net_state,
        "iteration": network.iteration_count,
    }, step if step is not None else network.iteration_count, keep=keep)


def restore_network(directory: str, network,
                    step: Optional[int] = None):
    """Restore training state saved by ``save_network`` into ``network``."""
    network._ensure_init()
    state = restore_checkpoint(directory, target={
        "params": network.params,
        "updater_state": network.updater_state,
        "net_state": network.net_state,
        "iteration": 0,
    }, step=step)
    network.params = state["params"]
    network.updater_state = state["updater_state"]
    network.net_state = state["net_state"]
    network.iteration_count = int(state["iteration"])
    return network
