"""Atomic file publication shared by the coordination modules (statetracker,
config registry): write to a tempfile on the same filesystem, then
``os.replace`` — readers never observe partial content."""

from __future__ import annotations

import os
import tempfile
from typing import Optional


def atomic_write_text(path: str, data: str,
                      tmp_dir: Optional[str] = None,
                      durable: bool = True) -> None:
    """Write ``data`` to ``path`` atomically. ``tmp_dir`` (default: the
    target's directory) must be on the same filesystem as ``path``.

    ``durable=True`` fsyncs the tempfile before the rename so a crash
    right after publication cannot leave the *new name* pointing at
    zero-length/partial content (rename is atomic in the namespace, not
    in the data journal); the containing directory is fsynced best-effort
    so the rename itself survives too.
    """
    # bare filenames: dirname() == "" and mkstemp(dir="") fails — stage in
    # the CWD the target resolves against
    fd, tmp = tempfile.mkstemp(dir=tmp_dir or os.path.dirname(path) or ".")
    try:
        with os.fdopen(fd, "w") as f:
            f.write(data)
            if durable:
                f.flush()
                os.fsync(f.fileno())
        os.replace(tmp, path)
        if durable:
            _fsync_dir(os.path.dirname(path) or ".")
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise


def atomic_write_bytes(path: str, writer,
                       tmp_dir: Optional[str] = None,
                       durable: bool = True) -> None:
    """Binary twin of :func:`atomic_write_text`: ``writer(fileobj)``
    produces the content (streaming downloads, ``np.save``, …) into a
    tempfile which is then published with ``os.replace``. Same durability
    contract (fsync-before-rename when ``durable``); the tempfile is
    removed on any failure."""
    fd, tmp = tempfile.mkstemp(dir=tmp_dir or os.path.dirname(path) or ".")
    try:
        with os.fdopen(fd, "wb") as f:
            writer(f)
            if durable:
                f.flush()
                os.fsync(f.fileno())
        os.replace(tmp, path)
        if durable:
            _fsync_dir(os.path.dirname(path) or ".")
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise


def _fsync_dir(dirname: str) -> None:
    """Persist a directory entry (rename/creat) — best-effort: some
    filesystems (and platforms) refuse O_RDONLY fsync on directories."""
    try:
        dfd = os.open(dirname, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(dfd)
    except OSError:
        pass
    finally:
        os.close(dfd)
