"""Atomic file publication shared by the coordination modules (statetracker,
config registry): write to a tempfile on the same filesystem, then
``os.replace`` — readers never observe partial content."""

from __future__ import annotations

import os
import tempfile
from typing import Optional


def atomic_write_text(path: str, data: str,
                      tmp_dir: Optional[str] = None) -> None:
    """Write ``data`` to ``path`` atomically. ``tmp_dir`` (default: the
    target's directory) must be on the same filesystem as ``path``."""
    fd, tmp = tempfile.mkstemp(dir=tmp_dir or os.path.dirname(path))
    try:
        with os.fdopen(fd, "w") as f:
            f.write(data)
        os.replace(tmp, path)
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise
