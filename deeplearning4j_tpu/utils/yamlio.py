"""Minimal YAML emitter/parser for configuration documents.

The reference round-trips configurations through SnakeYAML via Jackson's
``mapperYaml()`` (NeuralNetConfiguration.java:214-239 toYaml/fromYaml). This
sandbox has no pyyaml, so this module implements the YAML subset those
documents actually use — block mappings, block sequences, JSON-style
scalars (strings, ints, floats, booleans, null) — with deterministic
emission. It is NOT a general YAML parser: anchors, tags, multi-line
scalars, and flow collections beyond empty ``{}``/``[]`` are rejected
loudly rather than mis-parsed.
"""

from __future__ import annotations

import json
import re
from typing import Any, List, Tuple

_PLAIN_RE = re.compile(r"^[A-Za-z0-9_][A-Za-z0-9_./+-]*$")


# ---------------------------------------------------------------------------
# emit
# ---------------------------------------------------------------------------

def _scalar(v: Any) -> str:
    if v is None:
        return "null"
    if v is True:
        return "true"
    if v is False:
        return "false"
    if isinstance(v, (int, float)):
        return json.dumps(v)
    s = str(v)
    # quote anything YAML could reinterpret (numbers, booleans, null,
    # nan/inf spellings, leading specials, colons/hashes)
    if _PLAIN_RE.match(s) and s.lower() not in (
            "null", "true", "false", "yes", "no", "on", "off",
            "nan", "inf", "infinity", ".nan", ".inf") \
            and not re.match(r"^[0-9.+-]", s):
        return s
    return json.dumps(s)


def dump(obj: Any, indent: int = 0) -> str:
    """Emit ``obj`` (dict/list/scalar tree) as block-style YAML."""
    lines: List[str] = []
    _emit(obj, indent, lines)
    return "\n".join(lines) + "\n"


def _emit(obj: Any, indent: int, lines: List[str]) -> None:
    pad = "  " * indent
    if isinstance(obj, dict):
        if not obj:
            lines.append(f"{pad}{{}}")
            return
        for k, v in obj.items():
            key = _scalar(k)
            if isinstance(v, dict) and v:
                lines.append(f"{pad}{key}:")
                _emit(v, indent + 1, lines)
            elif isinstance(v, (list, tuple)) and len(v):
                lines.append(f"{pad}{key}:")
                _emit(list(v), indent + 1, lines)
            elif isinstance(v, dict):
                lines.append(f"{pad}{key}: {{}}")
            elif isinstance(v, (list, tuple)):
                lines.append(f"{pad}{key}: []")
            else:
                lines.append(f"{pad}{key}: {_scalar(v)}")
    elif isinstance(obj, (list, tuple)):
        for item in obj:
            if isinstance(item, dict) and item:
                # first key inline with the dash, rest indented under it
                sub: List[str] = []
                _emit(item, indent + 1, sub)
                first = sub[0].lstrip()
                lines.append(f"{pad}- {first}")
                lines.extend(sub[1:])
            elif isinstance(item, (list, tuple)) and len(item):
                lines.append(f"{pad}-")
                _emit(list(item), indent + 1, lines)
            elif isinstance(item, dict):
                lines.append(f"{pad}- {{}}")
            elif isinstance(item, (list, tuple)):
                lines.append(f"{pad}- []")
            else:
                lines.append(f"{pad}- {_scalar(item)}")
    else:
        lines.append(f"{pad}{_scalar(obj)}")


# ---------------------------------------------------------------------------
# parse
# ---------------------------------------------------------------------------

class YamlError(ValueError):
    pass


def load(text: str) -> Any:
    """Parse the YAML subset emitted by :func:`dump` (and by typical
    Jackson/SnakeYAML block output)."""
    rows: List[Tuple[int, str]] = []
    for raw in text.splitlines():
        if raw.strip() in ("", "---") or raw.lstrip().startswith("#"):
            continue
        stripped = raw.lstrip(" ")
        rows.append((len(raw) - len(stripped), stripped))
    if not rows:
        return None
    value, nxt = _parse_block(rows, 0, rows[0][0])
    if nxt != len(rows):
        raise YamlError(f"trailing content at line {nxt}: {rows[nxt][1]!r}")
    return value


def _parse_scalar(tok: str) -> Any:
    tok = tok.strip()
    if tok.startswith('"'):
        return json.loads(tok)
    if tok.startswith("'") and tok.endswith("'") and len(tok) >= 2:
        return tok[1:-1].replace("''", "'")
    low = tok.lower()
    if low in ("null", "~", ""):
        return None
    if low == "true":
        return True
    if low == "false":
        return False
    if tok in ("{}",):
        return {}
    if tok in ("[]",):
        return []
    if tok.startswith("[") or tok.startswith("{"):
        try:
            return json.loads(tok)  # flow collections in JSON form
        except json.JSONDecodeError as e:
            raise YamlError(f"unsupported flow collection {tok!r}") from e
    if tok.startswith(("&", "*", "!", "|", ">")):
        raise YamlError(f"unsupported YAML feature in {tok!r}")
    try:
        return int(tok)
    except ValueError:
        pass
    try:
        return float(tok)
    except ValueError:
        pass
    return tok


_KEY_RE = re.compile(r'^(?P<key>"(?:[^"\\]|\\.)*"|[^:#]+?):(?:\s+(?P<rest>.*))?$')


def _parse_block(rows, i: int, indent: int):
    """Parse rows[i:] at exactly ``indent``; returns (value, next_index)."""
    first = rows[i][1]
    if first.startswith("- "):
        return _parse_seq(rows, i, indent)
    if first == "-":
        return _parse_seq(rows, i, indent)
    return _parse_map(rows, i, indent)


def _parse_map(rows, i: int, indent: int):
    out = {}
    n = len(rows)
    while i < n:
        ind, line = rows[i]
        if ind < indent:
            break
        if ind > indent:
            raise YamlError(f"bad indentation at {line!r}")
        m = _KEY_RE.match(line)
        if not m or line.startswith("- "):
            raise YamlError(f"expected mapping entry, got {line!r}")
        key = _parse_scalar(m.group("key"))
        rest = m.group("rest")
        i += 1
        if rest is None or rest == "":
            # nested block (or empty value)
            if i < n and rows[i][0] > indent:
                out[key], i = _parse_block(rows, i, rows[i][0])
            elif i < n and rows[i][0] == indent and rows[i][1].startswith("-"):
                out[key], i = _parse_seq(rows, i, indent)
            else:
                out[key] = None
        else:
            out[key] = _parse_scalar(rest)
    return out, i


def _parse_seq(rows, i: int, indent: int):
    out = []
    n = len(rows)
    while i < n:
        ind, line = rows[i]
        if ind < indent or not line.startswith("-"):
            break
        if ind > indent:
            raise YamlError(f"bad sequence indentation at {line!r}")
        body = line[1:].lstrip()
        if body == "":
            i += 1
            if i < n and rows[i][0] > indent:
                item, i = _parse_block(rows, i, rows[i][0])
            else:
                item = None
            out.append(item)
            continue
        # a quoted scalar item ('- "conv: 1"') must not be mistaken for a
        # mapping: _KEY_RE would lazily match a prefix of the quoted token
        if body.startswith('"'):
            qm = re.match(r'^("(?:[^"\\]|\\.)*")\s*(.*)$', body)
            if qm and qm.group(2) == "":
                out.append(json.loads(qm.group(1)))
                i += 1
                continue
        # inline first entry: '- key: value' starts a nested map whose other
        # keys sit indented under the dash; '- scalar' is a plain item
        m = _KEY_RE.match(body)
        if m and m.group("rest") is not None or (m and body.endswith(":")):
            # re-inject as a virtual row at dash-body indentation
            virtual = [(ind + 2, body)]
            j = i + 1
            while j < n and rows[j][0] >= ind + 2:
                virtual.append(rows[j])
                j += 1
            item, used = _parse_map(virtual, 0, ind + 2)
            if used != len(virtual):
                raise YamlError(f"bad nested mapping under {line!r}")
            out.append(item)
            i = j
        else:
            out.append(_parse_scalar(body))
            i += 1
    return out, i
