"""Math helpers (util/MathUtils.java, 1,308 LoC — the subset the framework
actually exercises: normalization, correlation/regression-error stats,
entropy/information, rounding/discretization, combinatorics). Vectorized
numpy instead of the reference's scalar loops."""

from __future__ import annotations

import math
from typing import Sequence

import numpy as np


# -- scaling / normalization -------------------------------------------
def normalize(value: float, min_v: float, max_v: float) -> float:
    """MathUtils.normalize: scale into [0, 1]; errors when max <= min."""
    if max_v <= min_v:
        raise ValueError("max must exceed min")
    return (value - min_v) / (max_v - min_v)


def normalize_array(x, low: float = 0.0, high: float = 1.0) -> np.ndarray:
    x = np.asarray(x, np.float64)
    lo, hi = x.min(), x.max()
    if hi == lo:
        return np.full_like(x, low)
    return low + (x - lo) * (high - low) / (hi - lo)


def clamp(value: float, low: float, high: float) -> float:
    return max(low, min(high, value))


# -- information theory -------------------------------------------------
def entropy(probabilities) -> float:
    """Shannon entropy in bits over a probability vector."""
    p = np.asarray(probabilities, np.float64)
    p = p[p > 0]
    return float(-np.sum(p * np.log2(p)))


def information_gain(parent_counts, split_counts) -> float:
    """Entropy(parent) - Σ weight·Entropy(child) over a candidate split."""
    parent = np.asarray(parent_counts, np.float64)
    h_parent = entropy(parent / parent.sum())
    total = parent.sum()
    h_children = 0.0
    for child in split_counts:
        child = np.asarray(child, np.float64)
        if child.sum() == 0:
            continue
        h_children += (child.sum() / total) * entropy(child / child.sum())
    return h_parent - h_children


def log2(x: float) -> float:
    return math.log2(x)


# -- regression / correlation statistics --------------------------------
def sum_of_squares(x) -> float:
    return float(np.sum(np.square(np.asarray(x, np.float64))))


def sum_of_products(x, y) -> float:
    return float(np.dot(np.asarray(x, np.float64), np.asarray(y, np.float64)))


def ss_reg(predicted, actual) -> float:
    """Regression sum of squares vs the mean of actual."""
    a = np.asarray(actual, np.float64)
    p = np.asarray(predicted, np.float64)
    return float(np.sum((p - a.mean()) ** 2))


def ss_error(predicted, actual) -> float:
    """Residual sum of squares (MathUtils.ssError)."""
    a = np.asarray(actual, np.float64)
    p = np.asarray(predicted, np.float64)
    return float(np.sum((a - p) ** 2))


def correlation(x, y) -> float:
    """Pearson correlation (MathUtils.correlation)."""
    x = np.asarray(x, np.float64)
    y = np.asarray(y, np.float64)
    sx, sy = x.std(), y.std()
    if sx == 0 or sy == 0:
        return 0.0
    return float(np.mean((x - x.mean()) * (y - y.mean())) / (sx * sy))


def euclidean_distance(a, b) -> float:
    return float(np.linalg.norm(np.asarray(a, np.float64)
                                - np.asarray(b, np.float64)))


def manhattan_distance(a, b) -> float:
    return float(np.sum(np.abs(np.asarray(a, np.float64)
                               - np.asarray(b, np.float64))))


# -- rounding / discretization -------------------------------------------
def round_to_decimals(value: float, decimals: int) -> float:
    factor = 10.0 ** decimals
    return math.floor(value * factor + 0.5) / factor


def discretize(value: float, min_v: float, max_v: float,
               bins: int) -> int:
    """Bin index in [0, bins) for a value in [min, max]."""
    if bins <= 0:
        raise ValueError("bins must be positive")
    frac = normalize(clamp(value, min_v, max_v), min_v,
                     max_v) if max_v > min_v else 0.0
    return min(int(frac * bins), bins - 1)


def next_power_of_2(n: int) -> int:
    if n <= 1:
        return 1
    return 1 << (n - 1).bit_length()


# -- combinatorics -------------------------------------------------------
def factorial(n: int) -> float:
    return float(math.factorial(n))


def permutation(n: int, r: int) -> float:
    return float(math.perm(n, r))


def combination(n: int, r: int) -> float:
    return float(math.comb(n, r))


# -- misc ---------------------------------------------------------------
def sigmoid(x: float) -> float:
    if x >= 0:
        return 1.0 / (1.0 + math.exp(-x))
    e = math.exp(x)
    return e / (1.0 + e)


def bernoullis(successes: float, trials: float, p: float) -> float:
    """Probability of k successes in n Bernoulli(p) trials."""
    n, k = int(trials), int(successes)
    return float(math.comb(n, k) * p ** k * (1 - p) ** (n - k))


def uniform(rng, a: float, b: float) -> float:
    return a + (b - a) * rng.random()


def weights_for(counts: Sequence[float]) -> np.ndarray:
    """Inverse-frequency class weights, normalized to sum 1."""
    c = np.asarray(counts, np.float64)
    w = np.where(c > 0, 1.0 / np.maximum(c, 1e-12), 0.0)
    total = w.sum()
    return w / total if total > 0 else w
