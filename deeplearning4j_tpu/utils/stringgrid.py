"""StringGrid — a grid of strings with filter/dedup/sort operations
(util/StringGrid.java, 748 LoC: fromFile/fromInput, getColumn,
filterRowsByColumn, removeRowsWithEmptyColumn, sortColumnsByWordLikelihood,
split/merge). The useful surface, list-of-lists backed."""

from __future__ import annotations

from typing import Callable, Iterable, List, Optional, Sequence


class StringGrid:
    def __init__(self, sep: str, rows: Optional[Iterable[Sequence[str]]] = None):
        self.sep = sep
        self._rows: List[List[str]] = [list(r) for r in (rows or [])]
        if self._rows:
            width = len(self._rows[0])
            for r in self._rows:
                if len(r) != width:
                    raise ValueError("ragged rows")

    # -- construction ---------------------------------------------------
    @staticmethod
    def from_file(path: str, sep: str = ",") -> "StringGrid":
        with open(path) as f:
            return StringGrid.from_input(f.read().splitlines(), sep)

    @staticmethod
    def from_input(lines: Iterable[str], sep: str = ",") -> "StringGrid":
        rows = [line.split(sep) for line in lines if line.strip()]
        return StringGrid(sep, rows)

    # -- accessors ------------------------------------------------------
    def num_rows(self) -> int:
        return len(self._rows)

    def num_columns(self) -> int:
        return len(self._rows[0]) if self._rows else 0

    def get_row(self, i: int) -> List[str]:
        return list(self._rows[i])

    def get_column(self, j: int) -> List[str]:
        return [r[j] for r in self._rows]

    def rows(self) -> List[List[str]]:
        return [list(r) for r in self._rows]

    # -- transforms (all return new grids; the reference mutates) -------
    def filter_rows_by_column(self, j: int,
                              keep: Callable[[str], bool]) -> "StringGrid":
        return StringGrid(self.sep, [r for r in self._rows if keep(r[j])])

    def filter_by_value(self, j: int, value: str) -> "StringGrid":
        return self.filter_rows_by_column(j, lambda v: v == value)

    def remove_rows_with_empty_column(self, j: int) -> "StringGrid":
        return self.filter_rows_by_column(j, lambda v: v.strip() != "")

    def dedupe_rows(self) -> "StringGrid":
        seen = set()
        out = []
        for r in self._rows:
            key = tuple(r)
            if key not in seen:
                seen.add(key)
                out.append(r)
        return StringGrid(self.sep, out)

    def sort_by_column(self, j: int, reverse: bool = False) -> "StringGrid":
        return StringGrid(self.sep,
                          sorted(self._rows, key=lambda r: r[j],
                                 reverse=reverse))

    def select_columns(self, cols: Sequence[int]) -> "StringGrid":
        return StringGrid(self.sep, [[r[j] for j in cols]
                                     for r in self._rows])

    def append_column(self, values: Sequence[str]) -> "StringGrid":
        if len(values) != len(self._rows):
            raise ValueError("column length mismatch")
        return StringGrid(self.sep, [r + [v] for r, v in
                                     zip(self._rows, values)])

    # -- output ---------------------------------------------------------
    def to_lines(self) -> List[str]:
        return [self.sep.join(r) for r in self._rows]

    def write_file(self, path: str) -> None:
        with open(path, "w") as f:
            f.write("\n".join(self.to_lines()) + "\n")

    def __eq__(self, other):
        return (isinstance(other, StringGrid)
                and self._rows == other._rows)

    def __repr__(self):
        return f"StringGrid({self.num_rows()}x{self.num_columns()})"
