"""ModelSerializer: checkpoint write/restore.

Mirror of ``util/ModelSerializer.java:31-96`` — a zip archive holding the
full configuration JSON, the parameters, and the updater state (the
reference's configuration.json + coefficients.bin + updater.bin; updater
state is part of the checkpoint contract, SURVEY §5). We add the
non-trainable network state (batchnorm running stats) and training metadata,
which the reference loses on save.

Entries:
- ``configuration.json``  — MultiLayerConfiguration / ComputationGraphConfiguration JSON
- ``coefficients.npz``    — named param arrays (flat "0_W"-style keys)
- ``updater.npz``         — named updater-state arrays (optional)
- ``state.npz``           — named net-state arrays (optional)
- ``metadata.json``       — model type, iteration count, format version
"""

from __future__ import annotations

import io
import json
import zipfile
from typing import Any, Dict

import numpy as np
import jax.numpy as jnp


def _escape(component: str) -> str:
    """Escape '%' and '/' so user-chosen layer names containing '/' cannot
    collide with the path delimiter."""
    return component.replace("%", "%25").replace("/", "%2F")


def _unescape(component: str) -> str:
    return component.replace("%2F", "/").replace("%25", "%")


def _flatten_tree(tree: Any, prefix: str = "") -> Dict[str, np.ndarray]:
    out: Dict[str, np.ndarray] = {}
    if isinstance(tree, dict):
        for k in sorted(tree):
            esc = _escape(str(k))
            sub_prefix = f"{prefix}/{esc}" if prefix else esc
            out.update(_flatten_tree(tree[k], sub_prefix))
    else:
        out[prefix] = np.asarray(tree)
    return out


def _unflatten_tree(flat: Dict[str, np.ndarray]) -> Any:
    root: Dict[str, Any] = {}
    for key, value in flat.items():
        parts = [_unescape(p) for p in key.split("/")]
        node = root
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = jnp.asarray(value)
    return root


def _write_npz(zf: zipfile.ZipFile, name: str, tree: Any) -> None:
    flat = _flatten_tree(tree)
    buf = io.BytesIO()
    np.savez(buf, **flat)
    zf.writestr(name, buf.getvalue())


def _read_npz(zf: zipfile.ZipFile, name: str) -> Any:
    with zf.open(name) as f:
        data = np.load(io.BytesIO(f.read()))
        return _unflatten_tree({k: data[k] for k in data.files})


def _merge_into(template: Any, loaded: Any, path: str = "") -> Any:
    """Overlay loaded leaves onto the freshly-initialized structure.

    Empty-dict slots (param-less layers) are allowed to be absent from the
    archive — np.savez drops them entirely — but a missing *array* leaf means
    a truncated/corrupt checkpoint and raises rather than silently keeping
    fresh-random-init values."""
    if isinstance(template, dict):
        out = {}
        for k in template:
            sub_path = f"{path}/{k}" if path else str(k)
            sub_loaded = loaded.get(k) if isinstance(loaded, dict) else None
            if sub_loaded is None and _has_array_leaves(template[k]):
                raise ValueError(
                    f"checkpoint is missing parameter entry {sub_path!r} "
                    "(truncated or incompatible archive)")
            out[k] = _merge_into(template[k], sub_loaded, sub_path)
        return out
    if loaded is None:
        return template
    return jnp.asarray(loaded, template.dtype) if hasattr(template, "dtype") else loaded


def _has_array_leaves(tree: Any) -> bool:
    if isinstance(tree, dict):
        return any(_has_array_leaves(v) for v in tree.values())
    return True


class ModelSerializer:
    FORMAT_VERSION = 1

    @staticmethod
    def write_model(model, path: str, save_updater: bool = True) -> None:
        from deeplearning4j_tpu.nn.graph import ComputationGraph
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

        model._ensure_init()
        if isinstance(model, MultiLayerNetwork):
            mtype = "MultiLayerNetwork"
        elif isinstance(model, ComputationGraph):
            mtype = "ComputationGraph"
        else:
            raise TypeError(f"cannot serialize {type(model).__name__}")
        with zipfile.ZipFile(path, "w", zipfile.ZIP_DEFLATED) as zf:
            zf.writestr("configuration.json", model.conf.to_json())
            _write_npz(zf, "coefficients.npz", model.params)
            if save_updater:
                _write_npz(zf, "updater.npz", model.updater_state)
            _write_npz(zf, "state.npz", model.net_state)
            zf.writestr(
                "metadata.json",
                json.dumps({
                    "format_version": ModelSerializer.FORMAT_VERSION,
                    "model_type": mtype,
                    "iteration_count": model.iteration_count,
                }),
            )

    @staticmethod
    def restore_multi_layer_network(path: str, load_updater: bool = True):
        return ModelSerializer._restore(path, load_updater,
                                        expect="MultiLayerNetwork")

    @staticmethod
    def restore_computation_graph(path: str, load_updater: bool = True):
        return ModelSerializer._restore(path, load_updater,
                                        expect="ComputationGraph")

    @staticmethod
    def restore(path: str, load_updater: bool = True):
        """Type-dispatching restore (single archive open)."""
        return ModelSerializer._restore(path, load_updater, expect=None)

    @staticmethod
    def _restore(path: str, load_updater: bool, expect):
        from deeplearning4j_tpu.nn.conf.graph import ComputationGraphConfiguration
        from deeplearning4j_tpu.nn.conf.neural_net import MultiLayerConfiguration
        from deeplearning4j_tpu.nn.graph import ComputationGraph
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

        with zipfile.ZipFile(path, "r") as zf:
            meta = json.loads(zf.read("metadata.json"))
            mtype = meta.get("model_type")
            if mtype not in ("MultiLayerNetwork", "ComputationGraph"):
                raise ValueError(
                    f"unknown model_type {mtype!r} in checkpoint metadata")
            if expect is not None and mtype != expect:
                other = ("restore_computation_graph" if mtype == "ComputationGraph"
                         else "restore_multi_layer_network")
                raise TypeError(f"checkpoint holds a {mtype}, use {other}")
            conf_json = zf.read("configuration.json").decode()
            if mtype == "MultiLayerNetwork":
                net = MultiLayerNetwork(
                    MultiLayerConfiguration.from_json(conf_json)).init()
            else:
                net = ComputationGraph(
                    ComputationGraphConfiguration.from_json(conf_json)).init()
            net.params = _merge_into(net.params, _read_npz(zf, "coefficients.npz"))
            if load_updater and "updater.npz" in zf.namelist():
                net.updater_state = _merge_into(
                    net.updater_state, _read_npz(zf, "updater.npz"))
            if "state.npz" in zf.namelist():
                net.net_state = _merge_into(net.net_state, _read_npz(zf, "state.npz"))
            net.iteration_count = meta.get("iteration_count", 0)
        return net
