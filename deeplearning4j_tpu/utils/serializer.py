"""ModelSerializer: checkpoint write/restore.

Mirror of ``util/ModelSerializer.java:31-96`` — a zip archive holding the
full configuration JSON, the parameters, and the updater state (the
reference's configuration.json + coefficients.bin + updater.bin; updater
state is part of the checkpoint contract, SURVEY §5). We add the
non-trainable network state (batchnorm running stats) and training metadata,
which the reference loses on save.

Entries:
- ``configuration.json``  — MultiLayerConfiguration / ComputationGraphConfiguration JSON
- ``coefficients.npz``    — named param arrays (flat "0_W"-style keys)
- ``updater.npz``         — named updater-state arrays (optional)
- ``state.npz``           — named net-state arrays (optional)
- ``metadata.json``       — model type, iteration count, format version
"""

from __future__ import annotations

import io
import json
import zipfile
from typing import Any, Dict

import numpy as np
import jax.numpy as jnp


def _escape(component: str) -> str:
    """Escape '%', '/' and '[' so user-chosen layer names cannot collide
    with the path delimiter or the '[i]' list-index encoding."""
    return (component.replace("%", "%25").replace("/", "%2F")
            .replace("[", "%5B"))


def _unescape(component: str) -> str:
    return (component.replace("%5B", "[").replace("%2F", "/")
            .replace("%25", "%"))


def _flatten_tree(tree: Any, prefix: str = "") -> Dict[str, np.ndarray]:
    out: Dict[str, np.ndarray] = {}
    if isinstance(tree, dict):
        for k in sorted(tree):
            esc = _escape(str(k))
            sub_prefix = f"{prefix}/{esc}" if prefix else esc
            out.update(_flatten_tree(tree[k], sub_prefix))
    elif isinstance(tree, (list, tuple)):
        # lists (e.g. TransformerLM's per-layer blocks) flatten under
        # "[i]" components; _unflatten_tree rebuilds them by pattern
        for i, item in enumerate(tree):
            comp = f"[{i}]"
            sub_prefix = f"{prefix}/{comp}" if prefix else comp
            out.update(_flatten_tree(item, sub_prefix))
    else:
        if getattr(tree, "is_fully_addressable", True) is False:
            raise ValueError(
                f"cannot serialize leaf {prefix!r}: array is sharded "
                "across hosts (not fully addressable). Use "
                "utils.checkpoint.save_network (Orbax writes each shard "
                "from where it lives), or gather with jax.experimental."
                "multihost_utils.process_allgather and write from "
                "process 0.")
        out[prefix] = np.asarray(tree)
    return out


def _listify_and_unescape(node: Any) -> Any:
    """Convert dict nodes whose (ESCAPED) keys are all '[N]' back into
    lists, then unescape the remaining dict keys. Working in escaped
    space makes list markers unambiguous: _escape maps '[' to '%5B', so
    a user dict key literally named '[0]' can never look like a list
    index here."""
    if not isinstance(node, dict):
        return node
    if node and all(k.startswith("[") and k.endswith("]") for k in node):
        try:
            return [_listify_and_unescape(node[f"[{i}]"])
                    for i in range(len(node))]
        except KeyError:
            raise ValueError(
                f"corrupt archive: list entries {sorted(node)} are not "
                f"contiguous [0..{len(node) - 1}] indices") from None
    return {_unescape(k): _listify_and_unescape(v)
            for k, v in node.items()}


def _unflatten_tree(flat: Dict[str, np.ndarray]) -> Any:
    root: Dict[str, Any] = {}
    for key, value in flat.items():
        # components stay ESCAPED until _listify_and_unescape
        parts = key.split("/")
        node = root
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = jnp.asarray(value)
    return _listify_and_unescape(root)


def _write_npz(zf: zipfile.ZipFile, name: str, tree: Any) -> None:
    """Serialize a param tree. Sharded-but-single-host arrays (TP/FSDP on
    one host) are gathered to full host tensors here — correct, but the
    full model must fit host RAM. Arrays that are NOT fully addressable
    (multi-host meshes) cannot be gathered by np.asarray at all; raise a
    targeted error instead of np's cryptic one. Multi-host checkpointing
    should gather via jax.experimental.multihost_utils (process-0 writes)
    before calling the serializer."""
    flat = _flatten_tree(tree)
    buf = io.BytesIO()
    np.savez(buf, **flat)
    zf.writestr(name, buf.getvalue())


def _read_npz(zf: zipfile.ZipFile, name: str) -> Any:
    with zf.open(name) as f:
        data = np.load(io.BytesIO(f.read()))
        return _unflatten_tree({k: data[k] for k in data.files})


def _merge_into(template: Any, loaded: Any, path: str = "") -> Any:
    """Overlay loaded leaves onto the freshly-initialized structure.

    Empty-dict slots (param-less layers) are allowed to be absent from the
    archive — np.savez drops them entirely — but a missing *array* leaf means
    a truncated/corrupt checkpoint and raises rather than silently keeping
    fresh-random-init values."""
    if isinstance(template, dict):
        out = {}
        for k in template:
            sub_path = f"{path}/{k}" if path else str(k)
            sub_loaded = loaded.get(k) if isinstance(loaded, dict) else None
            if sub_loaded is None and _has_array_leaves(template[k]):
                raise ValueError(
                    f"checkpoint is missing parameter entry {sub_path!r} "
                    "(truncated or incompatible archive)")
            out[k] = _merge_into(template[k], sub_loaded, sub_path)
        return out
    if isinstance(template, (list, tuple)):
        if not template and loaded is None:
            # empty lists produce no npz keys, like empty dicts
            return template
        if not isinstance(loaded, list) or len(loaded) != len(template):
            raise ValueError(
                f"checkpoint entry {path!r} has {0 if loaded is None else len(loaded)}"
                f" items, expected {len(template)}")
        return type(template)(
            _merge_into(t, l, f"{path}/[{i}]")
            for i, (t, l) in enumerate(zip(template, loaded)))
    if loaded is None:
        return template
    return jnp.asarray(loaded, template.dtype) if hasattr(template, "dtype") else loaded


def _has_array_leaves(tree: Any) -> bool:
    if isinstance(tree, dict):
        return any(_has_array_leaves(v) for v in tree.values())
    if isinstance(tree, (list, tuple)):
        return any(_has_array_leaves(v) for v in tree)
    return True


class ModelSerializer:
    FORMAT_VERSION = 1

    @staticmethod
    def write_model(model, path: str, save_updater: bool = True) -> None:
        from deeplearning4j_tpu.models.transformer import TransformerLM
        from deeplearning4j_tpu.nn.graph import ComputationGraph
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

        model._ensure_init()
        # resolve the per-type pieces; ONE shared archive-writing block
        if isinstance(model, TransformerLM):
            mtype = "TransformerLM"
            conf_json = json.dumps(model.get_config())
            updater_tree = model.opt_state
            net_state = None  # stateless apart from params/opt
            iteration = model.step_count
        elif isinstance(model, (MultiLayerNetwork, ComputationGraph)):
            mtype = type(model).__name__
            conf_json = model.conf.to_json()
            updater_tree = model.updater_state
            net_state = model.net_state
            iteration = model.iteration_count
        else:
            raise TypeError(f"cannot serialize {type(model).__name__}")
        meta = {
            "format_version": ModelSerializer.FORMAT_VERSION,
            "model_type": mtype,
            "iteration_count": iteration,
        }
        # training_state: everything a mid-run resume needs beyond the
        # weights — the epoch RNG key (the per-chunk key splits and the
        # per-epoch permutations are a pure function of it, so a restored
        # key reproduces the uninterrupted run's exact stream), the host
        # LR scale (SCORE policy / halve_lr guard), and the epoch/step
        # cursors a preemption-safe checkpoint was taken at. Absent on
        # pre-v2 archives and on model types without an RNG stream.
        if hasattr(model, "_rng"):
            meta["training_state"] = {
                "rng_key": np.asarray(model._rng).tolist(),
                "lr_scale_host": float(getattr(model, "_lr_scale_host",
                                               1.0)),
                "epoch_cursor": int(getattr(model, "_epoch_cursor", 0)),
                "step_cursor": int(getattr(model, "_step_cursor", 0)),
            }
        with zipfile.ZipFile(path, "w", zipfile.ZIP_DEFLATED) as zf:
            zf.writestr("configuration.json", conf_json)
            _write_npz(zf, "coefficients.npz", model.params)
            if save_updater:
                _write_npz(zf, "updater.npz", updater_tree)
            if net_state is not None:
                _write_npz(zf, "state.npz", net_state)
            zf.writestr("metadata.json", json.dumps(meta))

    @staticmethod
    def restore_multi_layer_network(path: str, load_updater: bool = True):
        return ModelSerializer._restore(path, load_updater,
                                        expect="MultiLayerNetwork")

    @staticmethod
    def restore_computation_graph(path: str, load_updater: bool = True):
        return ModelSerializer._restore(path, load_updater,
                                        expect="ComputationGraph")

    @staticmethod
    def restore_transformer_lm(path: str, load_updater: bool = True):
        return ModelSerializer._restore(path, load_updater,
                                        expect="TransformerLM")

    @staticmethod
    def restore(path: str, load_updater: bool = True):
        """Type-dispatching restore (single archive open)."""
        return ModelSerializer._restore(path, load_updater, expect=None)

    @staticmethod
    def _restore(path: str, load_updater: bool, expect):
        from deeplearning4j_tpu.nn.conf.graph import ComputationGraphConfiguration
        from deeplearning4j_tpu.nn.conf.neural_net import MultiLayerConfiguration
        from deeplearning4j_tpu.nn.graph import ComputationGraph
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

        with zipfile.ZipFile(path, "r") as zf:
            meta = json.loads(zf.read("metadata.json"))
            mtype = meta.get("model_type")
            if mtype not in ("MultiLayerNetwork", "ComputationGraph",
                             "TransformerLM"):
                raise ValueError(
                    f"unknown model_type {mtype!r} in checkpoint metadata")
            if expect is not None and mtype != expect:
                other = {
                    "ComputationGraph": "restore_computation_graph",
                    "MultiLayerNetwork": "restore_multi_layer_network",
                    "TransformerLM": "restore_transformer_lm",
                }[mtype]
                raise TypeError(f"checkpoint holds a {mtype}, use {other}")
            conf_json = zf.read("configuration.json").decode()
            if mtype == "TransformerLM":
                from deeplearning4j_tpu.models.transformer import (
                    TransformerLM)

                lm = TransformerLM(**json.loads(conf_json)).init()
                lm.params = _merge_into(lm.params,
                                        _read_npz(zf, "coefficients.npz"))
                if load_updater and "updater.npz" in zf.namelist():
                    lm.opt_state = _merge_into(
                        lm.opt_state, _read_npz(zf, "updater.npz"))
                lm.step_count = meta.get("iteration_count", 0)
                return lm
            if mtype == "MultiLayerNetwork":
                net = MultiLayerNetwork(
                    MultiLayerConfiguration.from_json(conf_json)).init()
            else:
                net = ComputationGraph(
                    ComputationGraphConfiguration.from_json(conf_json)).init()
            net.params = _merge_into(net.params, _read_npz(zf, "coefficients.npz"))
            if load_updater and "updater.npz" in zf.namelist():
                net.updater_state = _merge_into(
                    net.updater_state, _read_npz(zf, "updater.npz"))
            if "state.npz" in zf.namelist():
                net.net_state = _merge_into(net.net_state, _read_npz(zf, "state.npz"))
            net.iteration_count = meta.get("iteration_count", 0)
            ts = meta.get("training_state")
            if ts:
                net._rng = jnp.asarray(np.asarray(ts["rng_key"],
                                                  np.uint32))
                net._lr_scale_host = float(ts.get("lr_scale_host", 1.0))
                net._epoch_cursor = int(ts.get("epoch_cursor", 0))
                net._step_cursor = int(ts.get("step_cursor", 0))
        return net
