"""Pipeline parallelism: GPipe-style microbatch schedule over a ``pipe`` axis.

Greenfield relative to the reference (SURVEY §2.5: "NOT present in the
reference: ... pipeline parallelism"), but required of a modern TPU
framework. Expressed the SPMD way: every device runs the SAME program under
``shard_map``; stage identity comes from ``lax.axis_index`` and activations
hop stage→stage with ``lax.ppermute`` over ICI. There is no per-stage Python
program — XLA compiles one step for all stages.

Schedule: GPipe with M microbatches over S stages — T = M + S - 1 ticks.
Each tick every stage (a) selects its input (stage 0 ingests microbatch t,
others take the activation handed to them last tick), (b) applies its stage
fn, (c) permutes the result one hop down the ring. Bubble fraction is
(S-1)/T, so choose M >> S. Gradients flow through ``ppermute`` natively, so
``jax.grad`` of a pipelined loss is the pipelined backward pass — the
backward schedule mirrors the forward automatically.

Stages must be homogeneous (same activation shape in/out), the natural
regime for stacked transformer blocks / equal-width dense towers. Stage
params are stored stacked on a leading [S, ...] axis sharded over ``pipe``,
so each device materializes only its own stage's weights.
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from deeplearning4j_tpu.compat import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deeplearning4j_tpu.parallel.mesh import PIPE_AXIS


def stack_stage_params(per_stage_params) -> Any:
    """[{...}, {...}, ...] per-stage pytrees → one pytree with leading [S]
    axis on every leaf (the layout ``spmd_pipeline`` consumes)."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *per_stage_params)


def shard_stage_params(stacked, mesh: Mesh, axis_name: str = PIPE_AXIS):
    """Place stacked stage params so each device holds only its stage."""
    from deeplearning4j_tpu.parallel.mesh import shard_leading_axis
    return shard_leading_axis(stacked, mesh, axis_name)


def spmd_pipeline(
    stage_fn: Callable[[Any, jnp.ndarray], jnp.ndarray],
    stage_params: Any,
    x_microbatches: jnp.ndarray,
    mesh: Mesh,
    *,
    axis_name: str = PIPE_AXIS,
) -> jnp.ndarray:
    """Run microbatches through the stage pipeline.

    - ``stage_fn(params, x) -> y`` with y.shape == x.shape (homogeneous).
    - ``stage_params``: pytree whose leaves have leading dim S (stacked
      stages), sharded over ``axis_name``.
    - ``x_microbatches``: [M, mb, ...] microbatches (replicated; only stage 0
      reads them).

    Returns [M, mb, ...] outputs, replicated across the pipe axis.
    """
    if axis_name not in mesh.shape:
        # size-1 pipe axis is dropped from the mesh: run stages sequentially
        n = jax.tree.leaves(stage_params)[0].shape[0]
        out = x_microbatches
        for s in range(n):
            p = jax.tree.map(lambda a: a[s], stage_params)
            out = jax.vmap(lambda xb: stage_fn(p, xb))(out)
        return out
    n_stages = mesh.shape[axis_name]
    n_micro = x_microbatches.shape[0]
    leaves = jax.tree.leaves(stage_params)
    if leaves and leaves[0].shape[0] != n_stages:
        raise ValueError(
            f"stage_params stack {leaves[0].shape[0]} stages but mesh axis "
            f"'{axis_name}' has {n_stages} devices")
    # Remaining mesh axes (e.g. 'data') shard the microbatch rows: each
    # replica row of the mesh pipelines its own slice of the batch.
    extra_axes = tuple(n for n in mesh.axis_names if n != axis_name)
    x_spec = P(None, extra_axes) if extra_axes else P()

    def body(params, x):
        # params leaves arrive as [1, ...] (this device's stage) — unstack.
        params = jax.tree.map(lambda p: p[0], params)
        stage = lax.axis_index(axis_name)
        n_ticks = n_micro + n_stages - 1
        state = jnp.zeros_like(x[0])          # activation handed to me
        outputs = jnp.zeros_like(x)           # filled on the last stage
        fwd = [(i, (i + 1) % n_stages) for i in range(n_stages)]

        def tick(t, carry):
            state, outputs = carry
            ingest = lax.dynamic_index_in_dim(
                x, jnp.clip(t, 0, n_micro - 1), 0, keepdims=False)
            cur = jnp.where(stage == 0, ingest, state)
            out = stage_fn(params, cur)
            mb_idx = t - (n_stages - 1)
            upd = lax.dynamic_update_index_in_dim(
                outputs, out, jnp.clip(mb_idx, 0, n_micro - 1), 0)
            valid = jnp.logical_and(stage == n_stages - 1, mb_idx >= 0)
            outputs = jnp.where(valid, upd, outputs)
            state = lax.ppermute(out, axis_name, fwd)
            return state, outputs

        _, outputs = lax.fori_loop(0, n_ticks, tick, (state, outputs))
        # Only the last stage holds real outputs; replicate via masked psum.
        outputs = jnp.where(stage == n_stages - 1, outputs, 0.0)
        return lax.psum(outputs, axis_name)

    p_spec = jax.tree.map(
        lambda p: P(axis_name, *([None] * (p.ndim - 1))), stage_params)
    return shard_map(
        body, mesh=mesh,
        in_specs=(p_spec, x_spec),
        out_specs=x_spec,
        check_vma=False,
    )(stage_params, x_microbatches)


def split_microbatches(x: jnp.ndarray, n_micro: int) -> jnp.ndarray:
    """[B, ...] → [M, B/M, ...]."""
    if x.shape[0] % n_micro:
        raise ValueError(
            f"batch {x.shape[0]} not divisible by {n_micro} microbatches")
    return x.reshape((n_micro, x.shape[0] // n_micro) + x.shape[1:])


def pipeline_train_step(
    stage_fn: Callable[[Any, jnp.ndarray], jnp.ndarray],
    loss_fn: Callable[[jnp.ndarray, jnp.ndarray], jnp.ndarray],
    mesh: Mesh,
    *,
    n_microbatches: int,
    learning_rate: float = 0.1,
    axis_name: str = PIPE_AXIS,
):
    """Build a jitted SGD train step for a pipelined tower.

    ``loss_fn(y_pred, y_true) -> scalar`` is applied to the re-flattened
    last-stage outputs. ``jax.grad`` differentiates through the pipeline
    (ppermute transposes to the reverse permute), yielding the backward
    pipeline schedule for free.
    """
    def loss_of(params, x, y):
        xm = split_microbatches(x, n_microbatches)
        out = spmd_pipeline(stage_fn, params, xm, mesh, axis_name=axis_name)
        return loss_fn(out.reshape((-1,) + out.shape[2:]), y)

    @jax.jit
    def step(params, x, y):
        loss, grads = jax.value_and_grad(loss_of)(params, x, y)
        params = jax.tree.map(lambda p, g: p - learning_rate * g,
                              params, grads)
        return params, loss

    return step
