"""Expert parallelism: mixture-of-experts FFN sharded over an ``expert`` axis.

Greenfield relative to the reference (SURVEY §2.5: "NOT present in the
reference: ... expert parallelism"). GShard-style dense dispatch: tokens are
routed to experts with top-k gating under a capacity limit, dispatched with
one einsum into an [E, C, d] expert-major buffer, processed by per-expert
FFNs, and combined back. The expert dimension carries a sharding constraint
over the ``expert`` mesh axis, so GSPMD partitions the per-expert FFNs
across devices and inserts the all-to-alls at the dispatch/combine einsums —
the collectives are compiler-derived from shardings, not hand-written.

Load balancing follows the Switch/GShard auxiliary loss
(E · Σ_e fraction_tokens(e) · mean_gate_prob(e)).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deeplearning4j_tpu.parallel.mesh import EXPERT_AXIS


@dataclasses.dataclass(frozen=True)
class MoEConfig:
    d_model: int
    d_ff: int
    n_experts: int
    top_k: int = 2
    capacity_factor: float = 1.25
    aux_loss_weight: float = 1e-2

    def __post_init__(self):
        if self.top_k > self.n_experts:
            raise ValueError(
                f"top_k={self.top_k} > n_experts={self.n_experts}: a token "
                "would be dispatched to the same expert twice")


def init_moe_params(cfg: MoEConfig, key) -> Dict[str, jnp.ndarray]:
    """Router + stacked per-expert FFN weights ([E, ...] leading axis)."""
    kg, k1, k2 = jax.random.split(key, 3)
    s_in = 1.0 / math.sqrt(cfg.d_model)
    s_ff = 1.0 / math.sqrt(cfg.d_ff)
    return {
        "Wg": jax.random.normal(kg, (cfg.d_model, cfg.n_experts)) * s_in,
        "W1": jax.random.normal(k1, (cfg.n_experts, cfg.d_model, cfg.d_ff)) * s_in,
        "b1": jnp.zeros((cfg.n_experts, cfg.d_ff)),
        "W2": jax.random.normal(k2, (cfg.n_experts, cfg.d_ff, cfg.d_model)) * s_ff,
        "b2": jnp.zeros((cfg.n_experts, cfg.d_model)),
    }


def shard_moe_params(params, mesh: Mesh, axis_name: str = EXPERT_AXIS):  # dl4j-lint: disable=adhoc-out-shardings -- sanctioned expert-axis placement builder; registry covers data/model/pipe
    """Shard the stacked expert weights over the expert axis; router is
    replicated (every device routes its own tokens)."""
    from deeplearning4j_tpu.parallel.mesh import shard_leading_axis
    out = shard_leading_axis(
        {k: v for k, v in params.items() if k != "Wg"}, mesh, axis_name)
    out["Wg"] = jax.device_put(params["Wg"], NamedSharding(mesh, P()))
    return out


def expert_capacity(n_tokens: int, cfg: MoEConfig) -> int:
    cap = int(math.ceil(cfg.capacity_factor * cfg.top_k * n_tokens
                        / cfg.n_experts))
    return max(cap, 1)


def _top_k_dispatch(gates: jnp.ndarray, capacity: int, top_k: int):
    """Build dispatch/combine tensors from gate probabilities.

    gates: [T, E] softmax router outputs. Returns
    (dispatch [T, E, C] bool-ish, combine [T, E, C] weights, aux_loss).
    """
    n_tokens, n_experts = gates.shape
    dispatch = jnp.zeros((n_tokens, n_experts, capacity), gates.dtype)
    combine = jnp.zeros((n_tokens, n_experts, capacity), gates.dtype)
    # Position counters per expert accumulate across the k routing rounds so
    # a token's 2nd-choice slot never collides with 1st-choice traffic.
    fill = jnp.zeros((n_experts,), jnp.int32)
    remaining = gates
    for _ in range(top_k):
        choice = jnp.argmax(remaining, axis=-1)                   # [T]
        onehot = jax.nn.one_hot(choice, n_experts, dtype=gates.dtype)
        # position of each token within its chosen expert's buffer
        pos_in_expert = (jnp.cumsum(onehot, axis=0) - onehot)     # [T, E]
        pos = jnp.sum(pos_in_expert * onehot, axis=-1).astype(jnp.int32)
        pos = pos + jnp.take(fill, choice)                        # [T]
        keep = pos < capacity
        gate_val = jnp.sum(gates * onehot, axis=-1) * keep        # [T]
        pos_c = jnp.clip(pos, 0, capacity - 1)
        posh = jax.nn.one_hot(pos_c, capacity, dtype=gates.dtype)  # [T, C]
        contrib = (onehot * keep[:, None])[:, :, None] * posh[:, None, :]
        dispatch = dispatch + contrib
        combine = combine + gate_val[:, None, None] * contrib
        fill = fill + jnp.sum(onehot * keep[:, None], axis=0).astype(jnp.int32)
        remaining = remaining * (1.0 - onehot)
    # Switch-style load-balance loss on 1st-choice assignment fractions.
    first = jax.nn.one_hot(jnp.argmax(gates, -1), n_experts, dtype=gates.dtype)
    frac_tokens = jnp.mean(first, axis=0)
    mean_prob = jnp.mean(gates, axis=0)
    aux = n_experts * jnp.sum(frac_tokens * mean_prob)
    return dispatch, combine, aux


def moe_ffn(  # dl4j-lint: disable=adhoc-out-shardings -- in-program expert-axis constraints; the registry scopes data/model/pipe placement
    params: Dict[str, jnp.ndarray],
    x: jnp.ndarray,
    cfg: MoEConfig,
    mesh: Optional[Mesh] = None,
    *,
    axis_name: str = EXPERT_AXIS,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """MoE feed-forward. x: [..., d_model] → (y [..., d_model], aux_loss).

    With ``mesh`` given, the [E, C, d] expert-major intermediates carry
    shardings on the expert axis — under jit over that mesh, GSPMD turns the
    dispatch/combine einsums into all-to-alls over ICI.
    """
    lead = x.shape[:-1]
    xt = x.reshape((-1, cfg.d_model))
    n_tokens = xt.shape[0]
    cap = expert_capacity(n_tokens, cfg)

    gates = jax.nn.softmax(xt @ params["Wg"], axis=-1)            # [T, E]
    dispatch, combine, aux = _top_k_dispatch(gates, cap, cfg.top_k)

    exp_in = jnp.einsum("td,tec->ecd", xt, dispatch)              # [E, C, d]
    if mesh is not None and axis_name in mesh.shape:
        exp_in = lax.with_sharding_constraint(
            exp_in, NamedSharding(mesh, P(axis_name, None, None)))
    h = jax.nn.relu(
        jnp.einsum("ecd,edf->ecf", exp_in, params["W1"])
        + params["b1"][:, None, :])
    exp_out = (jnp.einsum("ecf,efd->ecd", h, params["W2"])
               + params["b2"][:, None, :])
    if mesh is not None and axis_name in mesh.shape:
        exp_out = lax.with_sharding_constraint(
            exp_out, NamedSharding(mesh, P(axis_name, None, None)))
    y = jnp.einsum("ecd,tec->td", exp_out, combine)               # [T, d]
    return y.reshape(lead + (cfg.d_model,)), cfg.aux_loss_weight * aux
