"""Cluster state tracking: jobs, worker updates, heartbeats.

TPU-native re-design of the reference's StateTracker SPI
(deeplearning4j-scaleout-api/.../statetracker/StateTracker.java) and its
Hazelcast implementation (BaseHazelCastStateTracker.java, 972 LoC of
distributed maps for jobs/updates/heartbeats). On TPU pods the data plane is
XLA collectives over ICI, so the tracker's job shrinks to the *control*
plane: work assignment, liveness, and replicated metadata. Two backends:

- ``InMemoryStateTracker`` — thread-safe in-process maps (the embedded-
  Hazelcast role; used by single-host tests the way the reference uses
  ``BaseTestDistributed``).
- ``FileStateTracker`` — a directory on a shared filesystem (GCS fuse / NFS
  on TPU VMs) with atomic rename writes; processes on different hosts
  coordinate through it without any extra service (the client-Hazelcast /
  ZooKeeper role, SURVEY §2.5 "ZooKeeper config registry").

Job lifecycle mirrors the reference (pending → claimed → done, with requeue
on failure — JobFailed/ClearWorker protocol, actor/core/protocol/).

Resilience: every FileStateTracker publish goes through the shared
``RetryPolicy`` (transient I/O errors on GCS-fuse/NFS retry with jittered
backoff instead of killing a worker) and declares the
``statetracker.write`` fault point; ``heartbeat.post`` fires on every
heartbeat of either backend so chaos tests can starve liveness
tracker-agnostically.
"""

from __future__ import annotations

import fcntl
import json
import logging
import os
import threading
import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from deeplearning4j_tpu.resilience import (
    FaultInjected,
    RetryError,
    RetryPolicy,
    faults,
)
from deeplearning4j_tpu.utils.fileio import (
    atomic_write_bytes,
    atomic_write_text,
)

logger = logging.getLogger(__name__)

#: transient classes a shared-filesystem tracker may hit and injected
#: faults tests raise; ValueError covers torn non-atomic media reads
#: (json decode errors subclass it)
_TRANSIENT = (OSError, FaultInjected, ValueError)


def default_tracker_retry_policy() -> RetryPolicy:
    """Small/fast: control-plane writes are tiny, so four attempts inside
    ~0.3 s catches transient shared-fs hiccups without stalling training."""
    return RetryPolicy(max_attempts=4, base_delay_s=0.01, max_delay_s=0.1,
                       retryable=_TRANSIENT)


@dataclass
class Job:
    """A unit of work (the reference's job/Job.java: work + worker id)."""

    job_id: str
    payload: Any = None
    worker_id: Optional[str] = None
    status: str = "pending"  # pending | claimed | done | failed
    attempts: int = 0
    result: Any = None

    def to_json(self) -> Dict[str, Any]:
        return {"job_id": self.job_id, "payload": self.payload,
                "worker_id": self.worker_id, "status": self.status,
                "attempts": self.attempts, "result": self.result}

    @staticmethod
    def from_json(d: Dict[str, Any]) -> "Job":
        return Job(**d)


class StateTracker:
    """SPI: what every backend provides (StateTracker.java contract —
    jobs, workerUpdates, heartbeats, replication)."""

    # --- jobs ---
    def add_job(self, payload: Any, job_id: Optional[str] = None) -> str:
        raise NotImplementedError

    def claim_job(self, worker_id: str) -> Optional[Job]:
        raise NotImplementedError

    def complete_job(self, job_id: str, result: Any = None) -> None:
        raise NotImplementedError

    def fail_job(self, job_id: str, requeue: bool = True) -> None:
        raise NotImplementedError

    def jobs(self, status: Optional[str] = None) -> List[Job]:
        raise NotImplementedError

    # --- heartbeats / liveness ---
    def heartbeat(self, worker_id: str,
                  metrics: Optional[Dict[str, Any]] = None) -> None:
        """Post liveness; ``metrics`` (optional) is a COMPACT payload
        the master's fleet view aggregates. Two payload schemas ride
        this channel today (free-form dicts by contract; these are the
        keys the aggregators look for): training workers post
        ``{step_s, jobs, last_loss, goodput_pct}``
        (``DistributedTrainer``'s fleet tick), serve replicas post
        ``{role, occupancy, queue_depth, free_slots, ttft_p50, tpot_s,
        tokens_per_sec}`` (``serving/fleet``'s router + controller).
        Payload-less beats remain fully supported (and are the cheap
        path); backends that predate the parameter still satisfy the
        liveness half of the contract."""
        raise NotImplementedError

    def last_heartbeat(self, worker_id: str) -> Optional[float]:
        raise NotImplementedError

    def heartbeat_metrics(self, worker_id: str
                          ) -> Optional[Dict[str, Any]]:
        """The metrics payload of the worker's newest beat, or None
        (payload-less beat, unknown worker, or a backend without
        payload support — the default)."""
        return None

    def workers(self) -> List[str]:
        raise NotImplementedError

    def evict_stale(self, timeout_s: float = 120.0) -> List[str]:
        """Remove workers silent for >= timeout_s and requeue their claimed
        jobs (MasterActor.java:141-171: 120 s stale-worker eviction)."""
        raise NotImplementedError

    def evict_worker(self, worker_id: str) -> bool:
        """Evict ONE named worker regardless of beat age and requeue its
        claimed jobs — the autopilot's targeted-eviction primitive (a
        flagged straggler is still beating, so ``evict_stale`` cannot
        reach it). Returns True when the worker was registered."""
        raise NotImplementedError

    # --- replicated key/value metadata (config registry role) ---
    def put_meta(self, key: str, value: Any) -> None:
        raise NotImplementedError

    def get_meta(self, key: str, default: Any = None) -> Any:
        raise NotImplementedError

    # -- worker updates (StateTracker.java workerUpdates; arrays) --------
    # every post gets its own entry (worker@nonce): a worker finishing two
    # jobs in one barrier round must contribute TWO updates, not overwrite
    def post_update(self, worker_id: str, update) -> None:
        raise NotImplementedError

    def updates(self) -> Dict[str, Any]:
        """Non-destructive snapshot (barrier peek) — loads the arrays."""
        raise NotImplementedError

    def posted_update_keys(self) -> List[str]:
        """Cheap peek: entry keys only, no array deserialization."""
        raise NotImplementedError

    @staticmethod
    def update_worker(key: str) -> str:
        """Worker id from an update-entry key (``worker@nonce``)."""
        return key.rsplit("@", 1)[0]

    def drain_updates(self) -> Dict[str, Any]:
        """Atomically take-and-remove all posted updates: an update is
        either returned to exactly one drainer or left for the next one —
        never silently dropped (the check-then-clear race)."""
        raise NotImplementedError

    def clear_updates(self) -> None:
        self.drain_updates()

    # -- binary array metadata (global params channel) -------------------
    def put_array(self, key: str, value) -> None:
        raise NotImplementedError

    def get_array(self, key: str, default: Any = None) -> Any:
        raise NotImplementedError


class InMemoryStateTracker(StateTracker):
    """Thread-safe in-process tracker (embedded-Hazelcast role)."""

    def __init__(self):
        self._lock = threading.RLock()
        self._jobs: Dict[str, Job] = {}
        self._order: List[str] = []
        self._beats: Dict[str, float] = {}
        self._beat_metrics: Dict[str, Dict[str, Any]] = {}
        self._meta: Dict[str, Any] = {}
        self._updates: Dict[str, Any] = {}
        self._arrays: Dict[str, Any] = {}

    def add_job(self, payload: Any, job_id: Optional[str] = None) -> str:
        with self._lock:
            jid = job_id or uuid.uuid4().hex
            self._jobs[jid] = Job(jid, payload)
            self._order.append(jid)
            return jid

    def claim_job(self, worker_id: str) -> Optional[Job]:
        with self._lock:
            for jid in self._order:
                j = self._jobs[jid]
                if j.status == "pending":
                    j.status = "claimed"
                    j.worker_id = worker_id
                    j.attempts += 1
                    return Job(**j.to_json())
            return None

    def complete_job(self, job_id: str, result: Any = None) -> None:
        with self._lock:
            j = self._jobs[job_id]
            j.status = "done"
            j.result = result

    def fail_job(self, job_id: str, requeue: bool = True) -> None:
        with self._lock:
            j = self._jobs[job_id]
            j.status = "pending" if requeue else "failed"
            j.worker_id = None

    def jobs(self, status: Optional[str] = None) -> List[Job]:
        with self._lock:
            out = [self._jobs[j] for j in self._order]
            if status is not None:
                out = [j for j in out if j.status == status]
            return [Job(**j.to_json()) for j in out]

    def heartbeat(self, worker_id: str,
                  metrics: Optional[Dict[str, Any]] = None) -> None:
        faults.fault_point("heartbeat.post")
        with self._lock:
            self._beats[worker_id] = time.time()
            if metrics is not None:
                self._beat_metrics[worker_id] = dict(metrics)
            else:
                # a payload-less beat REPLACES the previous payload
                # (same overwrite semantics as the file backend's beat
                # file): heartbeat_metrics reports the newest beat, not
                # a stale snapshot from a worker whose payload_fn died
                self._beat_metrics.pop(worker_id, None)

    def last_heartbeat(self, worker_id: str) -> Optional[float]:
        with self._lock:
            return self._beats.get(worker_id)

    def heartbeat_metrics(self, worker_id: str
                          ) -> Optional[Dict[str, Any]]:
        with self._lock:
            m = self._beat_metrics.get(worker_id)
            return None if m is None else dict(m)

    def workers(self) -> List[str]:
        with self._lock:
            return sorted(self._beats)

    def evict_stale(self, timeout_s: float = 120.0) -> List[str]:
        with self._lock:
            now = time.time()
            stale = [w for w, t in self._beats.items()
                     if now - t >= timeout_s]
            for w in stale:
                del self._beats[w]
                self._beat_metrics.pop(w, None)
                for j in self._jobs.values():
                    if j.worker_id == w and j.status == "claimed":
                        j.status = "pending"
                        j.worker_id = None
            return stale

    def evict_worker(self, worker_id: str) -> bool:
        with self._lock:
            known = worker_id in self._beats
            self._beats.pop(worker_id, None)
            self._beat_metrics.pop(worker_id, None)
            for j in self._jobs.values():
                if j.worker_id == worker_id and j.status == "claimed":
                    j.status = "pending"
                    j.worker_id = None
            return known

    def put_meta(self, key: str, value: Any) -> None:
        with self._lock:
            self._meta[key] = value

    def get_meta(self, key: str, default: Any = None) -> Any:
        with self._lock:
            return self._meta.get(key, default)

    def post_update(self, worker_id: str, update) -> None:
        import numpy as np

        with self._lock:
            self._updates[f"{worker_id}@{uuid.uuid4().hex[:8]}"] = (
                np.asarray(update))

    def updates(self) -> Dict[str, Any]:
        with self._lock:
            return dict(self._updates)

    def posted_update_keys(self) -> List[str]:
        with self._lock:
            return sorted(self._updates)

    def drain_updates(self) -> Dict[str, Any]:
        with self._lock:
            out = dict(self._updates)
            self._updates.clear()
            return out

    def put_array(self, key: str, value) -> None:
        import numpy as np

        with self._lock:
            self._arrays[key] = np.asarray(value)

    def get_array(self, key: str, default: Any = None) -> Any:
        with self._lock:
            return self._arrays.get(key, default)


class FileStateTracker(StateTracker):
    """Directory-backed tracker for multi-process/multi-host coordination.

    Layout: ``<root>/jobs/<id>.json``, ``<root>/beats/<worker>``,
    ``<root>/meta/<key>.json``. All writes are atomic (tempfile + rename on
    the same filesystem), so concurrent readers never see partial JSON.
    Claims use kernel advisory locks (``flock``) on per-job lock files — the
    same first-writer-wins discipline the reference gets from Hazelcast
    distributed locks, with crash-release handled by the kernel (a dead
    process's lock vanishes with its fd, so no stale-lock breaking is
    needed and no two claimers can ever hold the same job).
    """

    def __init__(self, root: str,
                 retry_policy: Optional[RetryPolicy] = None):
        self.root = root
        self.retry_policy = retry_policy or default_tracker_retry_policy()
        self._lock_fds: Dict[str, int] = {}
        for sub in ("jobs", "beats", "meta", "locks", "tmp"):
            os.makedirs(os.path.join(root, sub), exist_ok=True)

    # -- helpers --
    def _atomic_write(self, path: str, data: str,
                      durable: bool = True) -> None:
        # staged in a separate tmp/ dir so directory listings of jobs/ and
        # beats/ never see half-written entries; transient I/O failures
        # (and injected ones) retry under the policy
        def write():
            faults.fault_point("statetracker.write")
            atomic_write_text(path, data,
                              tmp_dir=os.path.join(self.root, "tmp"),
                              durable=durable)

        self.retry_policy.call(write)

    def _job_path(self, jid: str) -> str:
        return os.path.join(self.root, "jobs", jid + ".json")

    def _read_job(self, jid: str) -> Optional[Job]:
        # a decode error is a torn read on non-atomic shared media (rename
        # is atomic locally; gcsfuse/NFS caching is not) — retry it as
        # transient before concluding the job is unreadable. A missing
        # file is a definitive answer, not a fault: never retried.
        def read():
            try:
                with open(self._job_path(jid)) as f:
                    return Job.from_json(json.load(f))
            except FileNotFoundError:
                return None

        try:
            return self.retry_policy.call(read)
        except RetryError as e:  # transient class exhausted its retries
            logger.warning("job %s unreadable after retries: %s", jid, e)
            return None
        # anything non-retryable (e.g. TypeError from a schema-mismatched
        # job file) propagates: a real bug must crash loudly, not make the
        # job silently vanish from jobs()/claim_job()

    def _write_job(self, job: Job) -> None:
        self._atomic_write(self._job_path(job.job_id),
                           json.dumps(job.to_json()))

    def _try_lock(self, name: str) -> bool:
        path = os.path.join(self.root, "locks", name)
        fd = os.open(path, os.O_CREAT | os.O_RDWR)
        try:
            fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
        except OSError:
            os.close(fd)
            return False
        self._lock_fds[name] = fd
        return True

    def _unlock(self, name: str) -> None:
        fd = self._lock_fds.pop(name, None)
        if fd is not None:
            fcntl.flock(fd, fcntl.LOCK_UN)
            os.close(fd)

    # -- jobs --
    def add_job(self, payload: Any, job_id: Optional[str] = None) -> str:
        # time-prefixed ids preserve FIFO claim order via sorted listing
        jid = job_id or f"{time.time_ns():020d}-{uuid.uuid4().hex[:8]}"
        self._write_job(Job(jid, payload))
        return jid

    def _job_ids(self) -> List[str]:
        return sorted(p[:-5] for p in os.listdir(os.path.join(self.root, "jobs"))
                      if p.endswith(".json"))

    def claim_job(self, worker_id: str) -> Optional[Job]:
        for jid in self._job_ids():
            j = self._read_job(jid)
            if j is None or j.status != "pending":
                continue
            if not self._try_lock("claim-" + jid):
                continue
            try:
                j = self._read_job(jid)  # re-read under lock
                if j is None or j.status != "pending":
                    continue
                j.status = "claimed"
                j.worker_id = worker_id
                j.attempts += 1
                self._write_job(j)
                return j
            finally:
                self._unlock("claim-" + jid)
        return None

    def complete_job(self, job_id: str, result: Any = None) -> None:
        j = self._read_job(job_id)
        if j is None:
            raise KeyError(job_id)
        j.status = "done"
        j.result = result
        self._write_job(j)

    def fail_job(self, job_id: str, requeue: bool = True) -> None:
        j = self._read_job(job_id)
        if j is None:
            raise KeyError(job_id)
        j.status = "pending" if requeue else "failed"
        j.worker_id = None
        self._write_job(j)

    def jobs(self, status: Optional[str] = None) -> List[Job]:
        out = []
        for jid in self._job_ids():
            j = self._read_job(jid)
            if j is not None and (status is None or j.status == status):
                out.append(j)
        return out

    # -- heartbeats --
    def _beat_path(self, worker_id: str) -> str:
        return os.path.join(self.root, "beats", worker_id)

    def heartbeat(self, worker_id: str,
                  metrics: Optional[Dict[str, Any]] = None) -> None:
        faults.fault_point("heartbeat.post")

        # beats bypass the statetracker.write fault point: background
        # monitor threads post them continuously, and letting them bump a
        # count-based schedule (fail_nth) installed for DATA writes would
        # make that site nondeterministic. heartbeat.post is the beats'
        # own injection site. durable=False: beats are ephemeral liveness
        # data overwritten every interval — two fsyncs per beat would
        # throttle the control plane on NFS/gcsfuse for durability nobody
        # reads back.
        #
        # Payload-less beats keep the legacy bare-float format (cheap,
        # and readable by any older coordinator); a metrics payload
        # upgrades the file to one JSON object. last_heartbeat parses
        # both, so fleets mix old and new workers freely. The timestamp
        # is stamped INSIDE write(): a beat that lands only after retry
        # backoffs must report when it landed, or the retry duration
        # ages the worker toward eviction when the filesystem — not the
        # worker — was slow.
        def write():
            body = (repr(time.time()) if metrics is None
                    else json.dumps({"t": time.time(),
                                     "metrics": dict(metrics)}))
            atomic_write_text(self._beat_path(worker_id), body,
                              tmp_dir=os.path.join(self.root, "tmp"),
                              durable=False)

        self.retry_policy.call(write)

    def _read_beat(self, worker_id: str) -> Optional[Dict[str, Any]]:
        try:
            with open(self._beat_path(worker_id)) as f:
                raw = f.read()
        except OSError:
            return None
        try:
            return {"t": float(raw), "metrics": None}
        except ValueError:
            pass
        try:
            d = json.loads(raw)
            return {"t": float(d["t"]), "metrics": d.get("metrics")}
        except (ValueError, TypeError, KeyError):
            return None  # torn write on non-atomic media: treat as absent

    def last_heartbeat(self, worker_id: str) -> Optional[float]:
        beat = self._read_beat(worker_id)
        return None if beat is None else beat["t"]

    def heartbeat_metrics(self, worker_id: str
                          ) -> Optional[Dict[str, Any]]:
        beat = self._read_beat(worker_id)
        return None if beat is None else beat["metrics"]

    def workers(self) -> List[str]:
        return sorted(os.listdir(os.path.join(self.root, "beats")))

    def evict_stale(self, timeout_s: float = 120.0) -> List[str]:
        now = time.time()
        stale = []
        for w in self.workers():
            t = self.last_heartbeat(w)
            if t is None or now - t >= timeout_s:
                stale.append(w)
                try:
                    os.unlink(self._beat_path(w))
                except FileNotFoundError:
                    # benign race: another evictor removed the beat first
                    logger.debug("beat file for %s already removed", w)
        if stale:
            dead = set(stale)
            for j in self.jobs(status="claimed"):
                if j.worker_id not in dead:
                    continue
                # requeue under the claim lock with a status re-check: a
                # merely-slow worker may complete the job concurrently, and
                # its result must not be clobbered back to pending
                if not self._try_lock("claim-" + j.job_id):
                    continue
                try:
                    cur = self._read_job(j.job_id)
                    if (cur is not None and cur.status == "claimed"
                            and cur.worker_id in dead):
                        cur.status = "pending"
                        cur.worker_id = None
                        self._write_job(cur)
                finally:
                    self._unlock("claim-" + j.job_id)
        return stale

    def evict_worker(self, worker_id: str) -> bool:
        known = worker_id in self.workers()
        try:
            os.unlink(self._beat_path(worker_id))
        except FileNotFoundError:
            pass
        dead = {worker_id}
        for j in self.jobs(status="claimed"):
            if j.worker_id not in dead:
                continue
            # same claim-lock + status re-check as evict_stale: a
            # merely-slow worker may complete the job concurrently
            if not self._try_lock("claim-" + j.job_id):
                continue
            try:
                cur = self._read_job(j.job_id)
                if (cur is not None and cur.status == "claimed"
                        and cur.worker_id in dead):
                    cur.status = "pending"
                    cur.worker_id = None
                    self._write_job(cur)
            finally:
                self._unlock("claim-" + j.job_id)
        return known

    # -- meta --
    def put_meta(self, key: str, value: Any) -> None:
        self._atomic_write(os.path.join(self.root, "meta", key + ".json"),
                           json.dumps(value))

    def get_meta(self, key: str, default: Any = None) -> Any:
        try:
            with open(os.path.join(self.root, "meta", key + ".json")) as f:
                return json.load(f)
        except (FileNotFoundError, json.JSONDecodeError):
            return default

    # -- worker updates (updates/ dir of .npy; staged in tmp/, published
    # with os.replace, consumed with os.rename — every transition atomic) --
    def _updates_dir(self) -> str:
        d = os.path.join(self.root, "updates")
        os.makedirs(d, exist_ok=True)
        return d

    def _save_array(self, target: str, value) -> None:
        import numpy as np

        def write():
            faults.fault_point("statetracker.write")
            atomic_write_bytes(target,
                               lambda f: np.save(f, np.asarray(value)),
                               tmp_dir=os.path.join(self.root, "tmp"))

        self.retry_policy.call(write)

    def post_update(self, worker_id: str, update) -> None:
        name = f"{worker_id}@{uuid.uuid4().hex[:8]}.npy"
        self._save_array(os.path.join(self._updates_dir(), name), update)

    def updates(self) -> Dict[str, Any]:
        import numpy as np

        out: Dict[str, Any] = {}
        for name in self.posted_update_keys():
            try:
                out[name] = np.load(
                    os.path.join(self._updates_dir(), name + ".npy"))
            except (OSError, ValueError) as e:
                # drained or torn under concurrency: skip, but say so
                logger.warning("skipping unreadable update %s: %s", name, e)
                continue
        return out

    def posted_update_keys(self) -> List[str]:
        return sorted(n[:-4] for n in os.listdir(self._updates_dir())
                      if n.endswith(".npy"))

    def drain_updates(self) -> Dict[str, Any]:
        import numpy as np

        out: Dict[str, Any] = {}
        for name in sorted(os.listdir(self._updates_dir())):
            if not name.endswith(".npy"):
                continue
            path = os.path.join(self._updates_dir(), name)
            # rename-to-take: a concurrent replace either lands before (we
            # take the new file) or after (it stays for the next drain)
            grave = os.path.join(self.root, "tmp",
                                 f"drain-{os.getpid()}-{uuid.uuid4().hex[:8]}")
            try:
                os.rename(path, grave)
            except FileNotFoundError:
                continue  # another drainer took it
            try:
                out[name[:-4]] = np.load(grave)
            except (OSError, ValueError) as e:
                # a torn/corrupt update is DROPPED here — make that visible
                logger.warning("dropping unreadable update %s: %s", name, e)
            finally:
                try:
                    os.unlink(grave)
                except FileNotFoundError:
                    logger.debug("drain grave %s already unlinked", grave)
        return out

    # -- binary array metadata --
    def put_array(self, key: str, value) -> None:
        d = os.path.join(self.root, "arrays")
        os.makedirs(d, exist_ok=True)
        self._save_array(os.path.join(d, key + ".npy"), value)

    def get_array(self, key: str, default: Any = None) -> Any:
        import numpy as np

        try:
            return np.load(os.path.join(self.root, "arrays", key + ".npy"))
        except (OSError, ValueError):
            return default
