"""Cluster configuration registry.

Re-design of ``deeplearning4j-scaleout-zookeeper`` (725 LoC:
ZooKeeperConfigurationRegister/Retriever, ZookeeperBuilder, PathBuilder):
the reference serializes a Canova ``Configuration`` into a ZooKeeper znode
path ``/<host>/<task>`` so cluster members can fetch their runtime config.
On a TPU pod the equivalent shared medium is the filesystem every worker
already mounts (GCS fuse / NFS / local for tests), so this registry stores
JSON configs under a root directory with atomic publish (tempfile +
``os.replace``), mtime-based watches, and the same register/retrieve
surface. No quorum service needed: JAX's single-controller model means the
registry is written by the launcher and read by workers.
"""

from __future__ import annotations

import json
import os
import re
import time
from typing import Any, Callable, Dict, List, Optional

from deeplearning4j_tpu.resilience import (
    FaultInjected,
    RetryError,
    RetryPolicy,
    faults,
    no_jitter,
)
from deeplearning4j_tpu.utils.fileio import atomic_write_text

_NAME_RE = re.compile(r"\A[A-Za-z0-9._-]+\Z")


class ConfigRegistry:
    """register/retrieve/list/watch named JSON configs
    (ZooKeeperConfigurationRegister.java / ZooKeeperConfigurationRetriever)."""

    def __init__(self, root: str):
        self.root = root
        os.makedirs(root, exist_ok=True)

    def _path(self, host: str, task: str) -> str:
        # the reference's znode path scheme: /<host>/<task>; names are
        # validated so no value can escape the registry root
        for name in (host, task):
            if not name or not _NAME_RE.match(name) or name in (".", ".."):
                raise ValueError(
                    f"invalid registry name {name!r}: use letters, digits, "
                    f"'.', '_', '-'")
        return os.path.join(self.root, host, task + ".json")

    # -- write ----------------------------------------------------------
    def register(self, host: str, task: str,
                 config: Dict[str, Any]) -> None:
        path = self._path(host, task)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        atomic_write_text(path, json.dumps(config))

    def unregister(self, host: str, task: str) -> None:
        try:
            os.unlink(self._path(host, task))
        except FileNotFoundError:
            pass

    # -- read -----------------------------------------------------------
    def retrieve(self, host: str, task: str) -> Dict[str, Any]:
        faults.fault_point("registry.retrieve")
        try:
            with open(self._path(host, task)) as f:
                return json.load(f)
        except FileNotFoundError:
            raise KeyError(f"no config registered for {host}/{task}")

    def exists(self, host: str, task: str) -> bool:
        return os.path.exists(self._path(host, task))

    def tasks(self, host: str) -> List[str]:
        d = os.path.join(self.root, host)
        if not os.path.isdir(d):
            return []
        return sorted(p[:-5] for p in os.listdir(d) if p.endswith(".json"))

    def hosts(self) -> List[str]:
        return sorted(h for h in os.listdir(self.root)
                      if os.path.isdir(os.path.join(self.root, h)))

    # -- watch ----------------------------------------------------------
    def wait_for(self, host: str, task: str, timeout_s: float = 30.0,
                 poll_s: float = 0.1,
                 policy: Optional[RetryPolicy] = None) -> Dict[str, Any]:
        """Block until a config appears (the worker-side retrieve-with-retry
        the reference does against ZooKeeper). The poll loop is the shared
        :class:`RetryPolicy` — by default a fixed ``poll_s`` interval
        (multiplier=1, no jitter) bounded by ``timeout_s``; pass ``policy``
        for backoff/jitter or an injectable sleep in tests. Transient read
        faults (injected or real) are retried like not-yet-registered."""
        self._path(host, task)  # invalid names fail NOW, not after the
        # full timeout — only transient conditions belong in the poll loop
        if policy is None:
            policy = RetryPolicy(max_attempts=None, deadline_s=timeout_s,
                                 base_delay_s=poll_s, multiplier=1.0,
                                 rng=no_jitter,
                                 retryable=(KeyError, OSError,
                                            json.JSONDecodeError,
                                            FaultInjected))
        try:
            return policy.call(self.retrieve, host, task)
        except RetryError as e:
            raise TimeoutError(f"config {host}/{task} not registered "
                               f"within {timeout_s}s") from e.last

    def watch(self, host: str, task: str,
              callback: Callable[[Optional[Dict[str, Any]]], None],
              timeout_s: float = 30.0,
              poll_s: float = 0.1) -> None:
        """Invoke ``callback`` on the next change (mtime watch). Deletion is
        a change too: the callback receives ``None`` when the config was
        unregistered."""
        path = self._path(host, task)

        def _sig():
            # mtime alone misses same-tick rewrites on coarse-granularity
            # shared media (GCS-fuse/NFS): fold in size. (Not st_ino —
            # gcsfuse inodes are synthetic and churn on cache eviction,
            # which would fire spurious change callbacks.)
            try:
                st = os.stat(path)
            except FileNotFoundError:
                return None
            return (st.st_mtime_ns, st.st_size)

        last = _sig()
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            sig = _sig()
            if sig != last:
                try:
                    payload = (self.retrieve(host, task)
                               if sig is not None else None)
                except KeyError:  # deleted between stat and read
                    payload = None
                callback(payload)
                return
            time.sleep(poll_s)
        raise TimeoutError(f"no change on {host}/{task} within {timeout_s}s")
