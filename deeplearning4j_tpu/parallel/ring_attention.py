"""Ring attention: context parallelism over a ``sequence`` mesh axis.

Long-context mechanism (Liu et al., "Ring Attention with Blockwise
Transformers") — greenfield relative to the reference, whose only
long-sequence tool was truncated BPTT (SURVEY §5). The sequence axis is
sharded across devices; each device keeps its Q block resident and K/V
blocks rotate around the ring via ``ppermute`` over ICI, overlapping the
collective with the local blockwise attention. Softmax is computed online
(flash-style running max/normalizer), so the full [t, t] score matrix never
materializes and sequence length scales linearly with the number of devices.

Implementation: ``shard_map`` over the mesh; the per-device body is a
``lax.fori_loop`` over ring steps with carry (o, m, l, k, v).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from jax import shard_map

from deeplearning4j_tpu.ops.attention import NEG_INF
from deeplearning4j_tpu.parallel.mesh import SEQUENCE_AXIS


def _block_attn(q, k, v, q_offset, k_offset, *, causal, scale):
    """Blockwise attention logits for absolute positions; returns
    (scores·v contribution, running-max, normalizer pieces)."""
    # q: [b, tq, h, d]; k/v: [b, tk, h, d]
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if causal:
        tq, tk = q.shape[1], k.shape[1]
        qi = q_offset + jnp.arange(tq)[:, None]
        ki = k_offset + jnp.arange(tk)[None, :]
        logits = jnp.where(qi >= ki, logits, NEG_INF)
    m = jnp.max(logits, axis=-1)  # [b, h, tq]
    p = jnp.exp(logits - m[..., None])
    l = jnp.sum(p, axis=-1)  # [b, h, tq]
    pv = jnp.einsum("bhqk,bkhd->bqhd", p, v)
    return pv, m, l


def ring_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    mesh: Mesh,
    *,
    causal: bool = False,
    scale: Optional[float] = None,
    axis_name: str = SEQUENCE_AXIS,
) -> jnp.ndarray:
    """Ring attention over ``axis_name``. q/k/v: [b, t, h, d] GLOBAL arrays
    (sharded or shardable on the time axis); returns [b, t, h, d] sharded the
    same way. Requires t % mesh.shape[axis_name] == 0.
    """
    d = q.shape[-1]
    scale_val = scale if scale is not None else float(1.0 / (d ** 0.5))
    if axis_name not in mesh.shape:
        # size-1 sequence axis is dropped from the mesh: no ring, plain
        # blockwise attention on the single device
        pv, m, l = _block_attn(q, k, v, 0, 0, causal=causal, scale=scale_val)
        denom = jnp.maximum(jnp.swapaxes(l, 1, 2)[..., None], 1e-30)
        return (pv.astype(jnp.float32) / denom).astype(q.dtype)
    n_ring = mesh.shape[axis_name]
    t_local = q.shape[1] // n_ring

    def body(q_blk, k_blk, v_blk):
        # q_blk/k_blk/v_blk: [b, t_local, h, d] — this device's shard
        my_idx = lax.axis_index(axis_name)
        b, tq, h, dd = q_blk.shape
        o = jnp.zeros((b, tq, h, dd), jnp.float32)
        m = jnp.full((b, h, tq), NEG_INF, jnp.float32)
        l = jnp.zeros((b, h, tq), jnp.float32)
        perm = [(i, (i - 1) % n_ring) for i in range(n_ring)]

        def step(s, carry):
            o, m, l, kc, vc = carry
            # kc currently holds the block originally owned by (my_idx + s)
            k_owner = (my_idx + s) % n_ring
            pv, m_blk, l_blk = _block_attn(
                q_blk, kc, vc,
                q_offset=my_idx * t_local,
                k_offset=k_owner * t_local,
                causal=causal, scale=scale_val)
            # online softmax merge
            m_new = jnp.maximum(m, m_blk)
            alpha = jnp.exp(m - m_new)        # rescale old accumulators
            beta = jnp.exp(m_blk - m_new)     # rescale new block
            l_new = l * alpha + l_blk * beta
            o_new = (o * jnp.swapaxes(alpha, 1, 2)[..., None]
                     + pv.astype(jnp.float32) * jnp.swapaxes(beta, 1, 2)[..., None])
            # rotate k/v to the next device (overlaps with next block's math)
            kc = lax.ppermute(kc, axis_name, perm)
            vc = lax.ppermute(vc, axis_name, perm)
            return (o_new, m_new, l_new, kc, vc)

        o, m, l, _, _ = lax.fori_loop(
            0, n_ring, step, (o, m, l, k_blk.astype(jnp.float32),
                              v_blk.astype(jnp.float32)))
        denom = jnp.maximum(jnp.swapaxes(l, 1, 2)[..., None], 1e-30)
        return (o / denom).astype(q_blk.dtype)

    spec = P(None, axis_name, None, None)
    sharded = shard_map(
        body, mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )
    return sharded(q, k, v)


def ring_self_attention_sharded(mesh: Mesh):
    """Convenience: returns a jitted fn(q, k, v, causal) bound to ``mesh``."""

    @functools.partial(jax.jit, static_argnames=("causal",))
    def fn(q, k, v, causal=False):
        return ring_attention(q, k, v, mesh, causal=causal)

    return fn
