"""Ring attention: context parallelism over a ``sequence`` mesh axis.

Long-context mechanism (Liu et al., "Ring Attention with Blockwise
Transformers") — greenfield relative to the reference, whose only
long-sequence tool was truncated BPTT (SURVEY §5). The sequence axis is
sharded across devices; each device keeps its Q block resident and K/V
blocks rotate around the ring via ``ppermute`` over ICI, overlapping the
collective with the local blockwise attention. Softmax is computed online
(flash-style running max/normalizer), so the full [t, t] score matrix never
materializes and sequence length scales linearly with the number of devices.

Implementation: ``shard_map`` over the mesh; the per-device body is a
``lax.fori_loop`` over ring steps with carry (o, m, l, k, v).

Two per-block implementations:

- ``impl="xla"`` — blockwise jnp math, XLA-fused (default; differentiable
  by plain autodiff).
- ``impl="flash"`` — the Pallas flash kernel (pallas/flash_attention.py)
  runs each (local q, visiting k/v) block, and blocks merge via their
  log-sum-exp; a ring-level ``custom_vjp`` implements the matching
  backward as a second ring pass (each block's gradient contribution is
  independent given the merged lse, so dk/dv accumulators travel around
  the ring with their k/v blocks).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from deeplearning4j_tpu.compat import shard_map

from deeplearning4j_tpu.ops.attention import NEG_INF, causal_band_mask
from deeplearning4j_tpu.parallel.mesh import SEQUENCE_AXIS


def _block_attn(q, k, v, q_offset, k_offset, *, causal, scale,
                window=None):
    """Blockwise attention logits for absolute positions; returns
    (scores·v contribution, running-max, normalizer pieces). ``window``
    (requires causal) keeps k in ``(q - window, q]`` — same sliding-window
    convention as ``ops.attention``."""
    # q: [b, tq, h, d]; k/v: [b, tk, h, d]
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if causal:
        keep = causal_band_mask(q.shape[1], k.shape[1], window=window,
                                q_offset=q_offset, k_offset=k_offset)
        logits = jnp.where(keep, logits, NEG_INF)
    m = jnp.max(logits, axis=-1)  # [b, h, tq]
    p = jnp.exp(logits - m[..., None])
    l = jnp.sum(p, axis=-1)  # [b, h, tq]
    pv = jnp.einsum("bhqk,bkhd->bqhd", p, v)
    return pv, m, l


class _StaticConfig:
    """Base for hashable static-config objects passed as custom_vjp
    nondiff args: identity is (concrete type, slot values)."""

    __slots__ = ()

    def _key(self):
        return tuple(getattr(self, s) for s in type(self).__slots__)

    def __hash__(self):
        return hash((type(self).__name__, self._key()))

    def __eq__(self, other):
        return type(other) is type(self) and other._key() == self._key()


def _lse_merge(o, lse, o_blk, lse_blk):
    """Merge a new normalized attention block into the running (o, lse)
    accumulator via log-sum-exp: the ONE implementation both flash rings
    share. o accumulates in f32; fully-masked blocks carry lse = -inf-ish
    and underflow to zero weight."""
    lse_new = jnp.logaddexp(lse, lse_blk)
    w_old = jnp.exp(lse - lse_new)
    w_new = jnp.exp(lse_blk - lse_new)
    o_new = (o * jnp.swapaxes(w_old, 1, 2)[..., None]
             + o_blk.astype(jnp.float32)
             * jnp.swapaxes(w_new, 1, 2)[..., None])
    return o_new, lse_new


class _RingFlashConfig(_StaticConfig):
    """Hashable statics for the ring-level custom_vjp."""

    __slots__ = ("causal", "scale", "n_ring", "axis_name", "interpret")

    def __init__(self, causal, scale, n_ring, axis_name, interpret):
        self.causal = causal
        self.scale = scale
        self.n_ring = n_ring
        self.axis_name = axis_name
        self.interpret = interpret


def _ring_flash_fwd_impl(cfg, q_blk, k_blk, v_blk):
    """Forward ring pass with the Pallas kernel per block. Per-device
    shards [b, t_local, h, d] → (out, lse [b, h, t_local])."""
    from deeplearning4j_tpu.pallas.flash_attention import (
        MASK_VALUE, flash_attention_fwd)

    n = cfg.n_ring
    axis = cfg.axis_name
    my_idx = lax.axis_index(axis)
    b, tq, h, d = q_blk.shape
    perm = [(i, (i - 1) % n) for i in range(n)]
    o0 = jnp.zeros((b, tq, h, d), jnp.float32)
    lse0 = jnp.full((b, h, tq), MASK_VALUE, jnp.float32)

    def block(kc, vc, causal_mode):
        def full(_):
            return flash_attention_fwd(
                q_blk, kc, vc, causal=False, scale=cfg.scale,
                interpret=cfg.interpret)

        def diag(_):
            # same-owner block: relative positions align, plain causal
            return flash_attention_fwd(
                q_blk, kc, vc, causal=True, scale=cfg.scale,
                interpret=cfg.interpret)

        def skip(_):
            return (jnp.zeros((b, tq, h, d), q_blk.dtype),
                    jnp.full((b, h, tq), MASK_VALUE, jnp.float32))

        if not cfg.causal:
            return full(None)
        return lax.switch(causal_mode, [full, diag, skip], None)

    def step(s, carry):
        o, lse, kc, vc = carry
        k_owner = (my_idx + s) % n
        causal_mode = jnp.where(k_owner < my_idx, 0,
                                jnp.where(k_owner == my_idx, 1, 2))
        o_blk, lse_blk = block(kc, vc, causal_mode)
        o, lse = _lse_merge(o, lse, o_blk, lse_blk)
        kc = lax.ppermute(kc, axis, perm)
        vc = lax.ppermute(vc, axis, perm)
        return (o, lse, kc, vc)

    o, lse, _, _ = lax.fori_loop(0, n, step, (o0, lse0, k_blk, v_blk))
    return o.astype(q_blk.dtype), lse


@functools.partial(jax.custom_vjp, nondiff_argnums=(0,))
def _ring_flash(cfg, q_blk, k_blk, v_blk):
    out, _ = _ring_flash_fwd_impl(cfg, q_blk, k_blk, v_blk)
    return out


def _ring_flash_fwd_rule(cfg, q_blk, k_blk, v_blk):
    out, lse = _ring_flash_fwd_impl(cfg, q_blk, k_blk, v_blk)
    return out, (q_blk, k_blk, v_blk, out, lse)


def _ring_flash_bwd_rule(cfg, res, do):
    """Second ring pass: dq accumulates locally; (dk, dv) accumulators
    travel with their k/v blocks and arrive home after n rotations.
    Per-block gradients run through the Pallas backward kernels (score
    tiles stay in VMEM); blocks never need position offsets because the
    ring visits each block as full (below diagonal), diag (aligned
    spans), or skip."""
    from deeplearning4j_tpu.pallas.flash_attention import (
        flash_backward_pallas)

    q_blk, k_blk, v_blk, out, lse = res
    n = cfg.n_ring
    axis = cfg.axis_name
    my_idx = lax.axis_index(axis)
    b, tq, h, d = q_blk.shape
    perm = [(i, (i - 1) % n) for i in range(n)]

    def block_grads(kc, vc, causal_mode):
        def run(causal):
            return flash_backward_pallas(q_blk, kc, vc, out, lse, do,
                                         causal=causal, scale=cfg.scale,
                                         interpret=cfg.interpret)

        def full(_):
            return run(False)

        def diag(_):
            return run(True)

        def skip(_):
            return (jnp.zeros((b, tq, h, d), jnp.float32),
                    jnp.zeros_like(kc, jnp.float32),
                    jnp.zeros_like(vc, jnp.float32))

        if not cfg.causal:
            return full(None)
        return lax.switch(causal_mode, [full, diag, skip], None)

    def step(s, carry):
        dq, kc, vc, dkc, dvc = carry
        k_owner = (my_idx + s) % n
        causal_mode = jnp.where(k_owner < my_idx, 0,
                                jnp.where(k_owner == my_idx, 1, 2))
        dq_c, dk_c, dv_c = block_grads(kc, vc, causal_mode)
        dq = dq + dq_c
        dkc = dkc + dk_c
        dvc = dvc + dv_c
        kc = lax.ppermute(kc, axis, perm)
        vc = lax.ppermute(vc, axis, perm)
        dkc = lax.ppermute(dkc, axis, perm)
        dvc = lax.ppermute(dvc, axis, perm)
        return (dq, kc, vc, dkc, dvc)

    dq0 = jnp.zeros((b, tq, h, d), jnp.float32)
    dq, _, _, dk, dv = lax.fori_loop(
        0, n, step,
        (dq0, k_blk, v_blk, jnp.zeros_like(k_blk, shape=k_blk.shape,
                                           dtype=jnp.float32),
         jnp.zeros_like(v_blk, dtype=jnp.float32)))
    return (dq.astype(q_blk.dtype), dk.astype(k_blk.dtype),
            dv.astype(v_blk.dtype))


_ring_flash.defvjp(_ring_flash_fwd_rule, _ring_flash_bwd_rule)


def ring_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    mesh: Mesh,
    *,
    causal: bool = False,
    scale: Optional[float] = None,
    axis_name: str = SEQUENCE_AXIS,
    impl: str = "xla",
    interpret: Optional[bool] = None,
    window: Optional[int] = None,
) -> jnp.ndarray:
    """Ring attention over ``axis_name``. q/k/v: [b, t, h, d] GLOBAL arrays
    (sharded or shardable on the time axis); returns [b, t, h, d] sharded the
    same way. Requires t % mesh.shape[axis_name] == 0.

    ``impl="flash"`` runs each block through the Pallas flash kernel with a
    ring-level custom VJP; ``"xla"`` (default) uses fused jnp blockwise math.

    ``window`` (requires ``causal=True``) composes sliding-window attention
    with the ring: each q block's band ``(q - window, q]`` intersects at most
    ``ceil((window-1)/t_local) + 1`` owner blocks, so the ring runs only that
    many hops — rotating AGAINST the causal direction so the needed
    previous-neighbor blocks arrive first and the loop stops as soon as the
    band is covered (a windowed ring is strictly cheaper than a full ring).
    With ``impl="flash"`` the hop loop is unrolled, which makes each hop's
    q↔k offset static: the diagonal hop runs the causal BANDED Pallas
    kernel, fully-in-band hops run the unmasked kernel, and only the ≤2
    band-edge hops use blockwise XLA math (``_win_ring_flash`` custom_vjp
    mirrors the same trichotomy in the backward ring pass).
    """
    if impl not in ("xla", "flash"):
        raise ValueError(f"unknown ring attention impl {impl!r}")
    if window is not None and (not causal or window < 1):
        raise ValueError("window requires causal=True and window >= 1")
    if window is not None and window >= q.shape[1]:
        # a band at least as long as the sequence IS plain causal
        # attention — take the rolled full-ring path instead of unrolling
        # n_ring identical "full" hops
        window = None
    d = q.shape[-1]
    scale_val = scale if scale is not None else float(1.0 / (d ** 0.5))
    if axis_name not in mesh.shape:
        # size-1 sequence axis is dropped from the mesh: no ring, plain
        # single-device attention
        if impl == "flash":
            from deeplearning4j_tpu.pallas.flash_attention import (
                flash_attention)

            return flash_attention(q, k, v, causal=causal, scale=scale_val,
                                   window=window, interpret=interpret)
        pv, m, l = _block_attn(q, k, v, 0, 0, causal=causal, scale=scale_val,
                               window=window)
        denom = jnp.maximum(jnp.swapaxes(l, 1, 2)[..., None], 1e-30)
        return (pv.astype(jnp.float32) / denom).astype(q.dtype)
    n_ring = mesh.shape[axis_name]
    t_local = q.shape[1] // n_ring

    if window is not None:
        if impl == "flash":
            return _windowed_ring_flash(
                q, k, v, mesh, axis_name=axis_name, scale=scale_val,
                window=window, n_ring=n_ring, t_local=t_local,
                interpret=interpret)
        return _windowed_ring(q, k, v, mesh, axis_name=axis_name,
                              scale=scale_val, window=window,
                              n_ring=n_ring, t_local=t_local)

    if impl == "flash":
        cfg = _RingFlashConfig(causal, scale_val, n_ring, axis_name,
                               interpret)
        spec = P(None, axis_name, None, None)
        return shard_map(
            functools.partial(_ring_flash, cfg), mesh=mesh,
            in_specs=(spec, spec, spec), out_specs=spec,
            check_vma=False,
        )(q, k, v)

    def body(q_blk, k_blk, v_blk):
        # q_blk/k_blk/v_blk: [b, t_local, h, d] — this device's shard
        my_idx = lax.axis_index(axis_name)
        b, tq, h, dd = q_blk.shape
        o = jnp.zeros((b, tq, h, dd), jnp.float32)
        m = jnp.full((b, h, tq), NEG_INF, jnp.float32)
        l = jnp.zeros((b, h, tq), jnp.float32)
        perm = [(i, (i - 1) % n_ring) for i in range(n_ring)]

        def step(s, carry):
            o, m, l, kc, vc = carry
            # kc currently holds the block originally owned by (my_idx + s)
            k_owner = (my_idx + s) % n_ring
            pv, m_blk, l_blk = _block_attn(
                q_blk, kc, vc,
                q_offset=my_idx * t_local,
                k_offset=k_owner * t_local,
                causal=causal, scale=scale_val)
            # online softmax merge
            m_new = jnp.maximum(m, m_blk)
            alpha = jnp.exp(m - m_new)        # rescale old accumulators
            beta = jnp.exp(m_blk - m_new)     # rescale new block
            l_new = l * alpha + l_blk * beta
            o_new = (o * jnp.swapaxes(alpha, 1, 2)[..., None]
                     + pv.astype(jnp.float32) * jnp.swapaxes(beta, 1, 2)[..., None])
            # rotate k/v to the next device (overlaps with next block's math)
            kc = lax.ppermute(kc, axis_name, perm)
            vc = lax.ppermute(vc, axis_name, perm)
            return (o_new, m_new, l_new, kc, vc)

        o, m, l, _, _ = lax.fori_loop(
            0, n_ring, step, (o, m, l, k_blk.astype(jnp.float32),
                              v_blk.astype(jnp.float32)))
        denom = jnp.maximum(jnp.swapaxes(l, 1, 2)[..., None], 1e-30)
        return (o / denom).astype(q_blk.dtype)

    spec = P(None, axis_name, None, None)
    sharded = shard_map(
        body, mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )
    return sharded(q, k, v)


def _win_steps(window: int, t_local: int, n_ring: int) -> int:
    """Ring hops a causal band ``(q-window, q]`` can touch: the diagonal
    block plus ``ceil((window-1)/t_local)`` previous neighbors, capped at
    the ring size."""
    return min(n_ring, -(-(window - 1) // t_local) + 1)


def _windowed_ring(q, k, v, mesh, *, axis_name, scale, window, n_ring,
                   t_local):
    """Causal sliding-window ring: only the ``n_steps`` hops whose k blocks
    can intersect any band run at all. The ring rotates so device i holds
    the block of owner ``(i - s) mod n`` at step s (previous neighbors
    first); owners "behind" the wrap are future blocks and contribute
    nothing (their merge weight is exp(-inf) = 0)."""
    # hops back to reach the band floor of a q block's FIRST position:
    # lowest visible k = i*t_local - window + 1 → owner i - ceil((w-1)/tl)
    n_steps = _win_steps(window, t_local, n_ring)
    # send i → i+1, so each device RECEIVES its predecessor's block
    perm = [(i, (i + 1) % n_ring) for i in range(n_ring)]

    def body(q_blk, k_blk, v_blk):
        my_idx = lax.axis_index(axis_name)
        b, tq, h, dd = q_blk.shape
        o = jnp.zeros((b, tq, h, dd), jnp.float32)
        m = jnp.full((b, h, tq), NEG_INF, jnp.float32)
        l = jnp.zeros((b, h, tq), jnp.float32)

        def step(s, carry):
            o, m, l, kc, vc = carry
            k_owner = (my_idx - s) % n_ring

            def compute(_):
                return _block_attn(
                    q_blk, kc, vc,
                    q_offset=my_idx * t_local,
                    k_offset=k_owner * t_local,
                    causal=True, scale=scale, window=window)

            def skip(_):
                return (jnp.zeros((b, tq, h, dd), jnp.float32),
                        jnp.full((b, h, tq), NEG_INF, jnp.float32),
                        jnp.zeros((b, h, tq), jnp.float32))

            # wrapped owners sit in the causal future of every local q
            pv, m_blk, l_blk = lax.cond(k_owner <= my_idx, compute, skip,
                                        None)
            m_new = jnp.maximum(m, m_blk)
            alpha = jnp.exp(m - m_new)
            beta = jnp.exp(m_blk - m_new)
            l_new = l * alpha + l_blk * beta
            o_new = (o * jnp.swapaxes(alpha, 1, 2)[..., None]
                     + pv * jnp.swapaxes(beta, 1, 2)[..., None])
            kc = lax.ppermute(kc, axis_name, perm)
            vc = lax.ppermute(vc, axis_name, perm)
            return (o_new, m_new, l_new, kc, vc)

        o, m, l, _, _ = lax.fori_loop(
            0, n_steps, step, (o, m, l, k_blk.astype(jnp.float32),
                               v_blk.astype(jnp.float32)))
        denom = jnp.maximum(jnp.swapaxes(l, 1, 2)[..., None], 1e-30)
        return (o / denom).astype(q_blk.dtype)

    spec = P(None, axis_name, None, None)
    return shard_map(body, mesh=mesh, in_specs=(spec, spec, spec),
                     out_specs=spec, check_vma=False)(q, k, v)


class _WinRingConfig(_StaticConfig):
    """Hashable statics for the windowed flash-ring custom_vjp."""

    __slots__ = ("scale", "window", "n_ring", "t_local", "axis_name",
                 "interpret")

    def __init__(self, scale, window, n_ring, t_local, axis_name, interpret):
        self.scale = scale
        self.window = window
        self.n_ring = n_ring
        self.t_local = t_local
        self.axis_name = axis_name
        self.interpret = interpret

    @property
    def n_steps(self):
        return _win_steps(self.window, self.t_local, self.n_ring)

    def hop_kind(self, s: int) -> str:
        """STATIC per-hop classification (offset δ = s·t_local is
        device-independent in the reversed ring): "diag" (δ=0: the
        existing causal banded kernel applies), "full" (every (q, k) pair
        in-band: unmasked kernel, peak MXU), or "partial" (the band edge
        crosses this block: blockwise XLA math — at most two such hops,
        since the edge spans t_local positions)."""
        if s == 0:
            return "diag"
        # all pairs satisfy qi + δ - ki < window ⟺ (t_local-1) + δ < w
        return "full" if (s + 1) * self.t_local <= self.window else "partial"


def _win_partial_hop(cfg, q_blk, kc, vc, s):
    """One partial-band hop via blockwise XLA math → (o, lse) in the
    flash merge convention."""
    pv, m, l = _block_attn(q_blk.astype(jnp.float32), kc.astype(jnp.float32),
                           vc.astype(jnp.float32),
                           q_offset=s * cfg.t_local, k_offset=0,
                           causal=True, scale=cfg.scale, window=cfg.window)
    l_safe = jnp.maximum(l, 1e-30)
    o = pv / jnp.swapaxes(l_safe, 1, 2)[..., None]
    lse = m + jnp.log(l_safe)
    return o, lse


def _win_ring_fwd_impl(cfg, q_blk, k_blk, v_blk):
    """Forward windowed flash ring. The hop loop is UNROLLED (n_steps is
    small by construction), making each hop's q↔k offset a static
    s·t_local — which is what lets hops use the Pallas kernels: the diag
    hop runs the causal banded kernel, fully-in-band hops run the
    unmasked kernel, and only band-edge hops fall back to fused XLA
    blockwise math."""
    from deeplearning4j_tpu.pallas.flash_attention import (
        MASK_VALUE, flash_attention_fwd)

    axis = cfg.axis_name
    my_idx = lax.axis_index(axis)
    b, tq, h, d = q_blk.shape
    # reversed rotation: device i receives its predecessor's block
    perm = [(i, (i + 1) % cfg.n_ring) for i in range(cfg.n_ring)]

    def hop(s, kc, vc):
        kind = cfg.hop_kind(s)

        def compute(kv):
            kc, vc = kv
            if kind == "diag":
                return flash_attention_fwd(
                    q_blk, kc, vc, causal=True, window=cfg.window,
                    scale=cfg.scale, interpret=cfg.interpret)
            if kind == "full":
                return flash_attention_fwd(
                    q_blk, kc, vc, causal=False, scale=cfg.scale,
                    interpret=cfg.interpret)
            o, lse = _win_partial_hop(cfg, q_blk, kc, vc, s)
            return o.astype(q_blk.dtype), lse

        def skip(kv):
            return (jnp.zeros((b, tq, h, d), q_blk.dtype),
                    jnp.full((b, h, tq), MASK_VALUE, jnp.float32))

        # wrapped owners (my_idx < s) sit in the causal future: skip
        return lax.cond(my_idx >= s, compute, skip, (kc, vc))

    o = jnp.zeros((b, tq, h, d), jnp.float32)
    lse = jnp.full((b, h, tq), MASK_VALUE, jnp.float32)
    kc, vc = k_blk, v_blk
    for s in range(cfg.n_steps):
        o_blk, lse_blk = hop(s, kc, vc)
        o, lse = _lse_merge(o, lse, o_blk, lse_blk)
        if s + 1 < cfg.n_steps:
            kc = lax.ppermute(kc, axis, perm)
            vc = lax.ppermute(vc, axis, perm)
    return o.astype(q_blk.dtype), lse


@functools.partial(jax.custom_vjp, nondiff_argnums=(0,))
def _win_ring_flash(cfg, q_blk, k_blk, v_blk):
    out, _ = _win_ring_fwd_impl(cfg, q_blk, k_blk, v_blk)
    return out


def _win_ring_fwd_rule(cfg, q_blk, k_blk, v_blk):
    out, lse = _win_ring_fwd_impl(cfg, q_blk, k_blk, v_blk)
    return out, (q_blk, k_blk, v_blk, out, lse)


def _win_ring_bwd_rule(cfg, res, do):
    """Second windowed ring pass: dq accumulates locally; (dk, dv)
    accumulators travel with their k/v blocks through the same n_steps
    hops, then ONE ppermute of offset n_steps-1 carries them home (the
    full flash ring completes the circle instead; a windowed ring
    doesn't, so the trip home is explicit). Per-hop grads mirror the
    forward trichotomy: Pallas banded/unmasked kernels for diag/full
    hops, the offset-aware XLA scan backward for band-edge hops."""
    from deeplearning4j_tpu.pallas.flash_attention import (
        flash_backward, flash_backward_pallas)

    q_blk, k_blk, v_blk, out, lse = res
    axis = cfg.axis_name
    my_idx = lax.axis_index(axis)
    b, tq, h, d = q_blk.shape
    perm = [(i, (i + 1) % cfg.n_ring) for i in range(cfg.n_ring)]

    def hop_grads(s, kc, vc):
        kind = cfg.hop_kind(s)

        def compute(kv):
            kc, vc = kv
            if kind == "diag":
                return flash_backward_pallas(
                    q_blk, kc, vc, out, lse, do, causal=True,
                    window=cfg.window, scale=cfg.scale,
                    interpret=cfg.interpret)
            if kind == "full":
                return flash_backward_pallas(
                    q_blk, kc, vc, out, lse, do, causal=False,
                    scale=cfg.scale, interpret=cfg.interpret)
            dq, dk, dv = flash_backward(
                q_blk, kc, vc, out, lse, do, causal=True,
                window=cfg.window, q_offset=s * cfg.t_local, k_offset=0,
                scale=cfg.scale)
            return dq, dk, dv

        def skip(kv):
            return (jnp.zeros((b, tq, h, d), jnp.float32),
                    jnp.zeros((b, tq, h, d), jnp.float32),
                    jnp.zeros((b, tq, h, d), jnp.float32))

        return lax.cond(my_idx >= s, compute, skip, (kc, vc))

    dq = jnp.zeros((b, tq, h, d), jnp.float32)
    dkc = jnp.zeros((b, tq, h, d), jnp.float32)
    dvc = jnp.zeros((b, tq, h, d), jnp.float32)
    kc, vc = k_blk, v_blk
    for s in range(cfg.n_steps):
        dq_c, dk_c, dv_c = hop_grads(s, kc, vc)
        dq = dq + dq_c
        dkc = dkc + dk_c.astype(jnp.float32)
        dvc = dvc + dv_c.astype(jnp.float32)
        if s + 1 < cfg.n_steps:
            kc = lax.ppermute(kc, axis, perm)
            vc = lax.ppermute(vc, axis, perm)
            dkc = lax.ppermute(dkc, axis, perm)
            dvc = lax.ppermute(dvc, axis, perm)
    # after n_steps-1 rotations device i's accumulators belong to owner
    # (i - (n_steps-1)) mod n — send them home in one hop
    if cfg.n_steps > 1:
        home = [(i, (i - (cfg.n_steps - 1)) % cfg.n_ring)
                for i in range(cfg.n_ring)]
        dkc = lax.ppermute(dkc, axis, home)
        dvc = lax.ppermute(dvc, axis, home)
    return (dq.astype(q_blk.dtype), dkc.astype(k_blk.dtype),
            dvc.astype(v_blk.dtype))


_win_ring_flash.defvjp(_win_ring_fwd_rule, _win_ring_bwd_rule)


def _windowed_ring_flash(q, k, v, mesh, *, axis_name, scale, window,
                         n_ring, t_local, interpret):
    cfg = _WinRingConfig(scale, window, n_ring, t_local, axis_name,
                         interpret)
    spec = P(None, axis_name, None, None)
    return shard_map(functools.partial(_win_ring_flash, cfg), mesh=mesh,
                     in_specs=(spec, spec, spec), out_specs=spec,
                     check_vma=False)(q, k, v)


def ring_self_attention_sharded(mesh: Mesh):
    """Convenience: returns a jitted fn(q, k, v, causal) bound to ``mesh``."""

    @functools.partial(jax.jit, static_argnames=("causal",))
    def fn(q, k, v, causal=False):
        return ring_attention(q, k, v, mesh, causal=causal)

    return fn
