"""Data-parallel training: synchronous all-reduce DP + parameter averaging.

Replaces the reference's two DP mechanisms (SURVEY §2.5):
1. Spark parameter averaging / gradient averaging
   (SparkDl4jMultiLayer.fitDataSet, spark/dl4j-spark/.../SparkDl4jMultiLayer
   .java:338-445) — broadcast params, independent local fits per partition,
   accumulator-sum + divide, aggregate updater state.
2. The Akka iterative-reduce parameter server (MasterActor.java:61,
   IterativeReduceWorkRouter.java:48-53).

``ParallelWrapper`` is the idiomatic TPU replacement: ONE SPMD program —
batch sharded over the mesh's ``data`` axis, params replicated; XLA GSPMD
inserts the gradient all-reduce over ICI. Mathematically identical to
training with the global batch on one device, with none of the reference's
host-side averaging machinery.

``ParameterAveragingTrainer`` keeps the reference's exact semantics
(independent replicas, periodic averaging — local SGD) for parity testing
and for DCN-separated multi-slice topologies where per-step all-reduce is
too expensive: replicas live on a leading axis sharded over ``data``; the
local step is ``jax.vmap``-ed; averaging is a mean over the replica axis
(XLA lowers it to an all-reduce when sharded). Updater state is averaged
with the params, matching the reference's UpdaterAggregator.
"""

from __future__ import annotations

import functools
import logging
from typing import Any, Optional

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deeplearning4j_tpu import dtypes as dtypes_mod
from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.nn.updater import apply_updater, lr_policy_scale

logger = logging.getLogger(__name__)
from deeplearning4j_tpu.parallel.mesh import (
    DATA_AXIS, MeshSpec, build_mesh)


class ParallelWrapper:
    """Synchronous data-parallel fit over a mesh (the ParallelWrapper role
    named in the reference's roadmap; here it is a thin pjit wrapper).

    Usage::

        wrapper = ParallelWrapper(net, mesh=build_mesh())
        wrapper.fit(iterator)        # global batch must divide mesh 'data' size
    """

    def __init__(self, network, mesh: Optional[Mesh] = None,
                 donate: bool = True, fsdp: bool = False):
        """``fsdp=True`` shards parameters AND updater state over the
        ``data`` axis (ZeRO-3, parallel/fsdp.py) instead of replicating —
        per-device state drops ~N×; GSPMD all-gathers weights on use and
        reduce-scatters gradients. Batch sizes must then divide the data
        axis (no ragged-tail fallback: it would need a gather/reshard
        round-trip per tail)."""
        self.network = network
        self.mesh = mesh or build_mesh()
        self._donate = donate
        self.fsdp = fsdp
        self._epoch_steps = {}  # fused SPMD epoch program per (shuffle, K, guard, stride)
        network._ensure_init()
        self._place_params()

    @property
    def data_parallelism(self) -> int:
        return self.mesh.shape[DATA_AXIS]

    def _place_params(self):
        """Registry-driven placement: the sharding registry derives every
        leaf's spec from the mesh (replicated on pure-DP meshes, Megatron
        TP where the mesh has a ``model`` axis), composed with FSDP over
        ``data`` via ``with_fsdp`` when ``fsdp=True``. The derived
        param/updater shardings are kept for the epoch program's
        out_shardings pin."""
        from deeplearning4j_tpu.parallel.sharding_registry import (
            ShardingRegistry)

        net = self.network
        reg = ShardingRegistry.for_network(net, self.mesh)
        if self.fsdp:
            reg = reg.with_fsdp(net.params)
        self._registry = reg
        self._param_shardings = reg.param_shardings(net.params)
        self._upd_shardings = reg.state_shardings(net.updater_state)
        reg.place_network(net)

    def request_reshard(self, mesh) -> None:
        """Request a mid-run elastic reshard of an in-flight
        ``fit_epochs`` run (``None`` = back to one device). Forwards to
        the wrapped network — the chunk driver reads the pending-mesh
        latch off the network — and the wrapper's own reshard callback
        re-pins its per-mesh programs at the next chunk boundary."""
        self.network.request_reshard(mesh)

    def _apply_reshard(self, mesh, cache) -> None:
        """The chunk driver's reshard actuator for the wrapper path:
        snapshot the trainable state to host, swap the wrapper onto the
        new mesh, drop every per-mesh artifact (epoch programs with
        pinned out_shardings, the FSDP re-jitted step, FSDP sharding
        specs), re-place state, and re-place the dataset cache. Values
        are untouched — only placement changes."""
        net = self.network
        net.params, net.updater_state, net.net_state = jax.device_get(
            (net.params, net.updater_state, net.net_state))
        self.mesh = mesh if mesh is not None else build_mesh(
            MeshSpec(data=1), devices=jax.devices()[:1])
        self._epoch_steps.clear()
        self.__dict__.pop("_fsdp_train_step", None)
        self._place_params()
        cache.respec(self.mesh)

    @functools.cached_property
    def _fsdp_train_step(self):  # dl4j-lint: disable=adhoc-out-shardings -- shardings sourced from the registry (with_fsdp); only the jit pin lives here
        """The network's step re-jitted with out_shardings pinned to the
        registry's FSDP specs so donated updates keep state sharded
        across steps."""
        return jax.jit(
            self.network._step_impl,
            donate_argnums=(0, 1, 2) if self._donate else (),
            out_shardings=(self._param_shardings, self._upd_shardings,
                           None, None, None))

    def _shard_batch(self, arr):
        from deeplearning4j_tpu.parallel.sharding_registry import (
            batch_sharding)

        if arr is None:
            return None
        return jax.device_put(
            jnp.asarray(arr), batch_sharding(self.mesh, np.ndim(arr)))

    def fit(self, data, num_epochs: int = 1):
        """fit(DataSetIterator | DataSet). Batches are sharded over 'data';
        the jitted step is the network's own — GSPMD handles the rest.

        TBPTT and non-SGD-solver configurations are NOT sharded: they
        delegate wholly to the network's own fit (windowed/solver
        semantics preserved, single device) rather than silently taking
        different steps on the mesh."""
        net = self.network
        if not self._shardable():
            if self.fsdp:
                # the network's own fit path has no pinned out_shardings:
                # one step would silently re-replicate the state and lose
                # the N-fold memory saving fsdp=True was chosen for
                raise ValueError(
                    "ParallelWrapper(fsdp=True) does not support "
                    "TBPTT/non-SGD/pretrain/SCORE-lr/iterations>1 "
                    "configs; use fsdp=False (replicated DP) for these")
            from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

            reason = ("non-shardable config (TBPTT/non-SGD/pretrain/"
                      "SCORE-lr/iterations>1)"
                      if isinstance(net, MultiLayerNetwork)
                      else f"{type(net).__name__} does not speak the "
                           "MLN sharded-step protocol")
            logger.info("ParallelWrapper: %s — delegating to the "
                        "network's own fit path (single device)", reason)
            net.fit(data, num_epochs=num_epochs)
            return self
        if isinstance(data, DataSet):
            self._fit_one(data)
            return self
        for _ in range(num_epochs):
            if hasattr(data, "reset"):
                data.reset()
            for ds in data:
                self._fit_one(ds)
        return self

    def _shardable(self) -> bool:
        """Configs whose per-batch semantics the sharded one-step path
        preserves exactly — the same exclusion list as
        MultiLayerNetwork.fit_steps (multilayer.py). Only
        MultiLayerNetwork speaks the sharded step protocol
        (_train_step(lr_scale)/_sgd_step/_lr_scale_host); every other
        model (e.g. ComputationGraph off the CLI) delegates to its own
        fit path rather than crashing mid-mesh-setup."""
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

        if not isinstance(self.network, MultiLayerNetwork):
            return False
        from deeplearning4j_tpu.nn.conf.enums import (
            BackpropType, LearningRatePolicy, OptimizationAlgorithm)

        conf = self.network.conf
        gc = conf.global_conf
        return (gc.optimization_algo
                == OptimizationAlgorithm.STOCHASTIC_GRADIENT_DESCENT
                and conf.backprop_type != BackpropType.TRUNCATED_BPTT
                and not getattr(conf, "pretrain", False)
                and gc.lr_policy != LearningRatePolicy.SCORE
                and max(1, gc.iterations) == 1)

    def _fit_one(self, ds: DataSet):
        net = self.network
        dp = self.data_parallelism
        if ds.num_examples() % dp:
            if self.fsdp:
                raise ValueError(
                    f"FSDP requires batch sizes divisible by the data "
                    f"axis (got {ds.num_examples()} vs dp={dp}); pad or "
                    f"drop the tail batch")
            # ragged tail batch (e.g. last CSV batch): ONE unsharded
            # optimizer step — same per-batch step count as the sharded
            # path (net.fit would run gc.iterations steps and over-weight
            # the smallest batch); params are replicated, so it is exact
            logger.debug(
                "batch of %d not divisible by dp=%d; running unsharded",
                ds.num_examples(), dp)
            net._sgd_step(ds)
            net._post_iteration()
            return
        step = self._fsdp_train_step if self.fsdp else net._train_step
        with self.mesh:
            net._rng, rng = jax.random.split(net._rng)
            (net.params, net.updater_state, net.net_state, _, loss) = step(
                net.params, net.updater_state, net.net_state,
                jnp.asarray(net.iteration_count, jnp.int32),
                jnp.asarray(net._lr_scale_host, jnp.float32),
                self._shard_batch(ds.features), self._shard_batch(ds.labels),
                self._shard_batch(ds.features_mask), self._shard_batch(ds.labels_mask),
                rng, None,
            )
        net.score_value = float(loss)
        net._post_iteration()

    # ------------------------------------------------------------------
    # whole-epoch fusion over the mesh: the SPMD composition of
    # ParallelWrapper's batch sharding with fit_epochs' one-program-per-
    # chunk design (perf/epoch_cache.py) — batch sharded over 'data',
    # params/updater replicated (or FSDP-sharded), GSPMD inserting the
    # per-step gradient all-reduce; still ONE dispatch per epoch chunk
    # at any device count.
    # ------------------------------------------------------------------
    def fused_epochs_supported(self) -> bool:
        """The wrapped network's own fused-path matrix; the wrapper adds
        no further exclusions (the chunk program is the network's)."""
        supported = getattr(self.network, "fused_epochs_supported", None)
        return bool(supported and supported())

    def build_epoch_cache(self, data, accum_steps: Optional[int] = None):
        """HBM dataset cache with every batch SHARDED over the mesh's
        ``data`` axis — each chip holds B/n rows of every batch, so the
        cacheable dataset size scales linearly with chip count.
        ``accum_steps=None`` resolves ``DL4J_ACCUM_STEPS``."""
        return self.network.build_epoch_cache(
            data, mesh=self.mesh, accum_steps=accum_steps)

    def _epoch_program(self, shuffle: bool, accum_steps: int,  # dl4j-lint: disable=adhoc-out-shardings -- shardings sourced from the registry; only the jit pin lives here
                       guard: bool = False, metrics_stride: int = 0):
        """The network's pure chunk program jitted for SPMD execution:
        out_shardings pinned to the registry's per-leaf specs so donated
        params/updater state STAY in their registry layout (replicated,
        TP-sharded, FSDP-sharded, or a composition) across chunks instead
        of whatever the partitioner would pick. With the numeric sentinel
        compiled in (``guard``) the program returns an extra output — the
        ``[E, N]`` trip history — replicated like the loss history; the
        telemetry metrics pack (``metrics_stride``) appends another
        replicated ``[E, N, 4]`` output after it."""
        from deeplearning4j_tpu.monitor.profile import ProfiledProgram
        from deeplearning4j_tpu.parallel.sharding_registry import (
            replicated_sharding)

        key = (shuffle, accum_steps, guard, metrics_stride)
        fn = self._epoch_steps.get(key)
        if fn is None:
            repl = replicated_sharding(self.mesh)
            out = (self._param_shardings, self._upd_shardings, repl, repl)
            if guard:
                out = out + (repl,)
            if metrics_stride:
                out = out + (repl,)
            fn = ProfiledProgram(
                jax.jit(self.network._epoch_run_fn(shuffle, accum_steps,
                                                   guard, metrics_stride),
                        donate_argnums=(0, 1, 2) if self._donate else (),
                        out_shardings=out),
                name="ParallelWrapper", key=key)
            self._epoch_steps[key] = fn
        return fn

    def fit_epochs(self, data, num_epochs: int, *, shuffle: bool = True,
                   chunk_epochs: Optional[int] = None,
                   accum_steps: Optional[int] = None,
                   guard: Optional[str] = None, telemetry=None,
                   on_chunk=None):
        """``fit_epochs`` as ONE donated SPMD program per epoch chunk:
        E epochs x N batches of `lax.scan` with the batch axis sharded
        over the mesh ``data`` axis, params/updater replicated (or
        sharded when ``fsdp=True``), the per-epoch reshuffle permuting
        the unsharded batch-index axis (shard-local gathers, no
        resharding collective) and GSPMD inserting one gradient
        all-reduce per step. ``accum_steps=K`` scans K microbatches per
        updater apply; ``telemetry=`` compiles the in-program metrics
        pack in (an extra replicated ``[E, N, 4]`` output — see
        MultiLayerNetwork.fit_epochs). Returns the ``[E, N]`` loss
        history, or ``None``
        when a fallback ran (unsupported config -> the network's own
        fallback matrix; over-budget dataset -> per-batch streaming
        through ``AsyncDataSetIterator`` device prefetch — sharded via
        the wrapper's step for MultiLayerNetwork, the network's own
        single-device fit for ComputationGraph, which does not speak the
        per-batch sharded-step protocol)."""
        from deeplearning4j_tpu.monitor import fused_metrics_stride
        from deeplearning4j_tpu.perf.epoch_cache import (
            DeviceDataSetCache, DeviceMultiDataSetCache,
            accum_steps_default, drive_epoch_chunks, effective_accum_steps,
            stream_epochs)
        from deeplearning4j_tpu.resilience.guard import nan_guard_policy

        net = self.network
        net._ensure_init()
        if num_epochs <= 0:
            return None
        if not (getattr(net.conf, "backprop", True)
                or getattr(net.conf, "pretrain", False)):
            return None  # fit() trains nothing in this configuration
        if accum_steps is None:
            accum_steps = accum_steps_default()
        prebuilt = isinstance(data, (DeviceDataSetCache,
                                     DeviceMultiDataSetCache))
        if not self.fused_epochs_supported():
            if prebuilt:
                raise ValueError(
                    "this configuration needs the per-step fit loop — "
                    "pass the original iterator, not a prebuilt cache")
            # the network's own fit_epochs owns the fallback matrix;
            # fsdp has no unsharded fallback (wrapper.fit raises for it)
            if self.fsdp:
                raise ValueError(
                    "ParallelWrapper(fsdp=True) cannot run this "
                    "configuration's per-step fallback; use fsdp=False")
            net.fit_epochs(data, num_epochs, shuffle=shuffle,
                           chunk_epochs=chunk_epochs)
            return None
        cache = data if prebuilt else self.build_epoch_cache(
            data, accum_steps=accum_steps)
        if cache is None:
            # over budget even sharded: stream per batch THROUGH the
            # sharded step (wrapper.fit), link hidden by device prefetch.
            # ComputationGraph has no per-batch sharded step: fsdp=True
            # would raise mid-stream from wrapper.fit, so fail HERE with
            # the actionable levers instead; fsdp=False delegates to the
            # graph's own single-device fit (wrapper.fit logs it).
            from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

            if self.fsdp and not isinstance(net, MultiLayerNetwork):
                raise ValueError(
                    "dataset exceeds the per-shard cache budget and "
                    "ComputationGraph has no fsdp streaming fallback — "
                    "raise DL4J_DEVICE_CACHE_MB, set "
                    "DL4J_CACHE_DTYPE=bfloat16, or increase accum_steps")
            stream_epochs(self, data, num_epochs)
            return None
        accum = effective_accum_steps(accum_steps, cache.batch)
        multi = isinstance(cache, DeviceMultiDataSetCache)
        guard = nan_guard_policy() if guard is None else guard
        guarded = guard != "off"
        stride = fused_metrics_stride(telemetry)

        def launch(epoch_keys):
            # resolved per launch, not per run: a mid-run elastic
            # reshard clears the program cache and this must pick up
            # the program re-pinned to the NEW mesh
            step = self._epoch_program(shuffle, accum, guarded, stride)
            with self.mesh:
                if multi:
                    out = step(
                        net.params, net.updater_state, net.net_state,
                        jnp.asarray(net.iteration_count, jnp.int32),
                        jnp.asarray(net._lr_scale_host, jnp.float32),
                        cache.features, cache.labels, cache.features_masks,
                        cache.labels_masks, epoch_keys)
                else:
                    out = step(
                        net.params, net.updater_state, net.net_state,
                        jnp.asarray(net.iteration_count, jnp.int32),
                        jnp.asarray(net._lr_scale_host, jnp.float32),
                        cache.features, cache.labels, cache.features_mask,
                        cache.labels_mask, epoch_keys)
            (net.params, net.updater_state, net.net_state) = out[:3]
            hist = out[3]
            trips = out[4] if guarded else None
            mets = out[-1] if stride else None
            return hist, trips, mets

        def replay_step(params, upd, nst, it, i, rng):
            # DL4J_NAN_GUARD=raise localization replays through the
            # network's own per-step math — accumulation split included
            # (same per-microbatch rng stream as the fused run) — on the
            # replicated layout; fine as a pre-raise diagnostic even
            # under FSDP, where it temporarily re-replicates the state
            # it is about to abort with
            with self.mesh:
                if multi:
                    args = (params, upd, nst, jnp.asarray(it, jnp.int32),
                            tuple(x[i] for x in cache.features),
                            tuple(y[i] for y in cache.labels),
                            None if cache.features_masks is None
                            else tuple(m[i] for m in cache.features_masks),
                            tuple(m[i] for m in cache.labels_masks), rng)
                    if accum > 1:
                        p, u, s, loss, _ = net._accum_step_impl(*args,
                                                                accum)
                    else:
                        p, u, s, loss, _ = net._train_step(*args, None)
                else:
                    args = (params, upd, nst, jnp.asarray(it, jnp.int32),
                            jnp.asarray(net._lr_scale_host, jnp.float32),
                            cache.features[i], cache.labels[i],
                            None if cache.features_mask is None
                            else cache.features_mask[i],
                            cache.labels_mask[i], rng)
                    if accum > 1:
                        p, u, s, _, loss = net._accum_step_impl(*args,
                                                                accum)
                    else:
                        p, u, s, _, loss = net._train_step(*args, None)
            return p, u, s, loss

        return drive_epoch_chunks(
            net, cache, num_epochs, chunk_epochs, launch,
            shuffle=shuffle, guard=guard, replay_step=replay_step,
            on_chunk=on_chunk,
            reshard=lambda new_mesh: self._apply_reshard(new_mesh, cache))

    def output(self, x):
        x = np.asarray(x)
        if x.shape[0] % self.data_parallelism == 0:
            x = self._shard_batch(x)  # else: unsharded fallback
        with self.mesh:
            return self.network.output(x)

    # -- model-like surface so trainers (early stopping, solvers) can use
    #    the wrapper interchangeably with the wrapped network (the role of
    #    BaseSparkEarlyStoppingTrainer's SparkDl4jMultiLayer handle,
    #    spark/.../BaseSparkEarlyStoppingTrainer.java:301) ---------------
    @property
    def score_value(self) -> float:
        return self.network.score_value

    def score(self, ds) -> float:
        """Scoring forward sharded over the mesh (no host gather: the
        sharded device arrays feed the jitted score fn directly)."""
        net = self.network
        if (ds.num_examples() % self.data_parallelism
                or not hasattr(net, "_score_fn")):
            return net.score(ds)
        with self.mesh:
            val = net._score_fn(
                net.params, net.net_state,
                self._shard_batch(ds.features), self._shard_batch(ds.labels),
                self._shard_batch(ds.features_mask),
                self._shard_batch(ds.labels_mask))
        net.score_value = val
        return net.score_value

    def clone(self):
        return self.network.clone()

    @property
    def conf(self):
        return self.network.conf

    def evaluate(self, data):
        """Distributed evaluation: each batch's forward shards over the
        mesh; per-batch Evaluations merge on host — the reference's
        map-side EvaluateFlatMapFunction + Evaluation.merge reduce
        (SparkDl4jMultiLayer.evaluate :576-607) with the map side compiled.
        Batches whose size does not divide the mesh run unsharded."""
        from deeplearning4j_tpu.eval.evaluation import Evaluation

        if isinstance(data, DataSet):
            batches = [data]
        else:
            if hasattr(data, "reset"):
                data.reset()
            batches = data
        total = Evaluation()
        for ds in batches:
            out = np.asarray(self.output(ds.features))
            part = Evaluation()
            part.eval(np.asarray(ds.labels), out,
                      mask=None if ds.labels_mask is None
                      else np.asarray(ds.labels_mask))
            total.merge(part)
        return total


class ParameterAveragingTrainer:
    """Reference-parity DP: N independent replicas + periodic averaging.

    Semantics match SparkDl4jMultiLayer with ``averageEachIteration=false``:
    each replica runs ``averaging_frequency`` local updater steps on its own
    shard of every global batch, then params AND updater state are averaged
    across replicas (UpdaterAggregator behavior).
    """

    def __init__(self, network, num_replicas: Optional[int] = None,
                 averaging_frequency: int = 1, mesh: Optional[Mesh] = None):
        network._ensure_init()
        self.network = network
        self.mesh = mesh or build_mesh()
        self.num_replicas = num_replicas or self.mesh.shape[DATA_AXIS]
        self.averaging_frequency = max(1, averaging_frequency)
        self._stacked: Optional[Any] = None  # [R, ...] params
        self._stacked_upd: Optional[Any] = None
        self._local_steps = 0

    # ------------------------------------------------------------------
    def _stack(self, tree):  # dl4j-lint: disable=adhoc-out-shardings -- replica-axis stacking is local-SGD semantics, not model placement; registry axes do not apply
        r = self.num_replicas
        stacked = jax.tree_util.tree_map(
            lambda p: jnp.broadcast_to(p[None], (r,) + p.shape), tree)
        # shard the replica axis over 'data' when it divides evenly;
        # otherwise replicate (sharding here is an optimization, not
        # semantics)
        if r % self.mesh.shape[DATA_AXIS] == 0:
            spec = lambda p: P(DATA_AXIS, *([None] * (p.ndim - 1)))
        else:
            spec = lambda p: P()
        return jax.tree_util.tree_map(
            lambda p: jax.device_put(p, NamedSharding(self.mesh, spec(p))), stacked)

    @functools.cached_property
    def _replica_step(self):
        net = self.network
        gc = net.conf.global_conf

        def one_replica(params, upd, state, iteration, x, y):
            def loss_fn(p):
                return net._loss_and_state(p, state, x, y, None, None,
                                           rng=None, train=True)

            (loss, (new_state, _)), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params)
            scale = lr_policy_scale(
                gc.lr_policy, iteration, gc.lr_policy_decay_rate,
                gc.lr_policy_steps, gc.lr_policy_power, gc.lr_schedule,
                base_lr=gc.learning_rate)
            new_params, new_upd = {}, {}
            for i, spec in enumerate(net.updater_specs):
                si = str(i)
                steps_i, upd_i = apply_updater(
                    spec, grads[si], upd[si], scale, iteration + 1)
                new_params[si] = jax.tree_util.tree_map(
                    lambda p, s: p - s.astype(p.dtype), params[si], steps_i)
                new_upd[si] = upd_i
            return new_params, new_upd, new_state, loss

        vstep = jax.vmap(one_replica, in_axes=(0, 0, None, None, 0, 0),
                         out_axes=(0, 0, None, 0))

        def step(stacked_params, stacked_upd, state, iteration, xs, ys):
            with dtypes_mod.policy_scope(net._policy):
                return vstep(stacked_params, stacked_upd, state, iteration, xs, ys)

        return jax.jit(step, donate_argnums=(0, 1))

    @functools.cached_property
    def _average(self):
        def avg(stacked):
            return jax.tree_util.tree_map(lambda p: jnp.mean(p, axis=0), stacked)

        return jax.jit(avg)

    # ------------------------------------------------------------------
    def fit(self, data, num_epochs: int = 1):
        net = self.network
        if isinstance(data, DataSet):
            batches = [data]
        else:
            batches = data
        for _ in range(num_epochs):
            if hasattr(batches, "reset"):
                batches.reset()
            for ds in batches:
                self._fit_one(ds)
        self._sync_down(force=True)
        return self

    def _fit_one(self, ds: DataSet):
        net = self.network
        r = self.num_replicas
        n = ds.num_examples()
        if n % r:
            raise ValueError(f"batch {n} not divisible by {r} replicas")
        if self._stacked is None:
            self._stacked = self._stack(net.params)
            self._stacked_upd = self._stack(net.updater_state)
        per = n // r
        xs = jnp.asarray(ds.features).reshape((r, per) + ds.features.shape[1:])
        ys = jnp.asarray(ds.labels).reshape((r, per) + ds.labels.shape[1:])
        with self.mesh:
            self._stacked, self._stacked_upd, net.net_state, losses = (
                self._replica_step(
                    self._stacked, self._stacked_upd, net.net_state,
                    jnp.asarray(net.iteration_count, jnp.int32), xs, ys))
        net.score_value = float(jnp.mean(losses))
        self._local_steps += 1
        if self._local_steps % self.averaging_frequency == 0:
            self._sync_down()
        net._post_iteration()

    def _sync_down(self, force: bool = False):
        """Average replicas → replicated params (+ updater state), restack."""
        if self._stacked is None:
            return
        net = self.network
        with self.mesh:
            net.params = self._average(self._stacked)
            net.updater_state = self._average(self._stacked_upd)
        if force:
            self._stacked = None
            self._stacked_upd = None
        else:
            self._stacked = self._stack(net.params)
            self._stacked_upd = self._stack(net.updater_state)
