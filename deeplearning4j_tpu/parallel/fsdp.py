"""FSDP / ZeRO-3: parameters + optimizer state sharded over the data axis.

Beyond the reference's scale-out inventory: every strategy in SURVEY §2.5
replicates the full parameter vector per worker (Spark broadcast at
SparkDl4jMultiLayer.java:374-382, Akka Hazelcast maps, YARN HDFS) — at
2015 model sizes that was fine. The modern TPU counterpart shards the
parameters, gradients, AND optimizer state across the data-parallel axis:
each device holds 1/N of every tensor, XLA's GSPMD partitioner inserts
the all-gathers when a weight is used and reduce-scatters for its
gradient, and per-device HBM for state drops by ~N×. This module is the
"annotate shardings, let XLA insert collectives" recipe — no hand-written
communication.

Design: a leaf is sharded along its LARGEST mesh-divisible dimension
(ties → first); leaves with no divisible dimension (scalars, small
biases) stay replicated — their memory is negligible and replication
avoids padding. ``FSDP.jit_step`` pins ``out_shardings`` to the same
specs so state STAYS sharded across steps instead of being re-replicated
by the partitioner's default choice.
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deeplearning4j_tpu.parallel.mesh import (
    DATA_AXIS,
    batch_sharding as mesh_mod_batch_sharding,
)


def fsdp_spec(shape: Tuple[int, ...], mesh: Mesh,
              axis: str = DATA_AXIS) -> P:
    """PartitionSpec sharding the largest dimension divisible by the mesh
    axis size; replicated when nothing divides (scalars, odd biases)."""
    n = mesh.shape[axis]
    best = None
    for i, d in enumerate(shape):
        if d % n == 0 and d >= n and (best is None or d > shape[best]):
            best = i
    if best is None:
        return P()
    entries: list = [None] * len(shape)
    entries[best] = axis
    return P(*entries)


def fsdp_shardings(tree: Any, mesh: Mesh, axis: str = DATA_AXIS) -> Any:  # dl4j-lint: disable=adhoc-out-shardings -- sanctioned FSDP spec builder; the registry composes fsdp_spec via with_fsdp
    """Per-leaf NamedShardings for an arbitrary pytree (optimizer-state
    leaves mirror their parameter's shape, so the same rule applies)."""
    return jax.tree_util.tree_map(
        lambda leaf: NamedSharding(mesh, fsdp_spec(jnp.shape(leaf), mesh,
                                                   axis)), tree)


def shard_tree(tree: Any, mesh: Mesh, axis: str = DATA_AXIS, *,
               with_shardings: bool = False) -> Any:
    """Place every leaf on the mesh under its FSDP sharding. With
    ``with_shardings=True`` returns ``(placed_tree, shardings)``."""
    shardings = fsdp_shardings(tree, mesh, axis)
    placed = jax.tree_util.tree_map(jax.device_put, tree, shardings)
    return (placed, shardings) if with_shardings else placed


class FSDP:
    """Generic ZeRO-3 wrapper around a ``(params, opt_state, *batch) ->
    (params, opt_state, aux)`` step function.

    >>> trainer = FSDP(mesh, lm.params, lm.opt_state)
    >>> step = trainer.jit_step(lm._step_body())
    >>> lm.params, lm.opt_state = trainer.params, trainer.opt_state
    >>> lm.fit_batch(tokens, train_step=step)

    ``params``/``opt_state`` are re-placed sharded at construction;
    ``jit_step`` pins matching ``out_shardings`` (donated inputs) so each
    step consumes and produces 1/N-per-device state.
    """

    _DONATED = object()

    def __init__(self, mesh: Mesh, params: Any, opt_state: Any,
                 axis: str = DATA_AXIS):
        self.mesh = mesh
        self.axis = axis
        self._params, self.param_shardings = shard_tree(
            params, mesh, axis, with_shardings=True)
        self._opt_state, self.opt_shardings = shard_tree(
            opt_state, mesh, axis, with_shardings=True)

    @property
    def params(self):
        return self._checked(self._params, "params")

    @property
    def opt_state(self):
        return self._checked(self._opt_state, "opt_state")

    def _checked(self, val, name):
        if val is FSDP._DONATED:
            raise RuntimeError(
                f"FSDP.{name} was donated to a jit_step call; the live "
                "state is what that step returned (take ownership of "
                ".params/.opt_state BEFORE the first step, as in the "
                "class docstring)")
        return val

    def jit_step(self, step_fn: Callable, *, donate: bool = True,  # dl4j-lint: disable=adhoc-out-shardings -- pins the FSDP specs this wrapper owns; registry-era callers pass registry shardings
                 aux_sharding: Optional[Any] = None) -> Callable:
        """Jit ``step_fn(params, opt_state, *args) -> (params, opt_state,
        aux)`` with out_shardings pinned to the FSDP specs. ``aux`` is
        left unconstrained (or pass ``aux_sharding``).

        With ``donate=True`` the first call invalidates whatever buffers
        this trainer still references, so the wrapper drops them — a
        later ``.params`` read raises a clear error instead of jax's
        deleted-buffer one."""
        fn = jax.jit(
            step_fn,
            donate_argnums=(0, 1) if donate else (),
            out_shardings=(self.param_shardings, self.opt_shardings,
                           aux_sharding))
        if not donate:
            return fn

        def wrapper(*args, **kwargs):
            self._params = self._opt_state = FSDP._DONATED
            return fn(*args, **kwargs)

        return wrapper

    def batch_sharding(self, ndim: int) -> NamedSharding:
        """Standard data-parallel batch sharding (leading dim)."""
        return mesh_mod_batch_sharding(self.mesh, ndim, self.axis)
