"""Multi-host cluster runtime: launcher, liveness, elastic restart.

Replaces the reference's distributed *runtimes* (SURVEY §3.4): the Akka
MasterActor/WorkerActor parameter server with its heartbeat eviction
(actor/core/actor/MasterActor.java:141-171 — evict workers silent >= 120 s,
re-dispatch their jobs) and the YARN ApplicationMaster's container restart
+ ProgressReport RPC. On TPU the data plane needs none of that — a pod runs
ONE SPMD program and XLA collectives synchronize it — so what remains is:

- ``initialize_distributed``: bring the hosts into one JAX runtime
  (``jax.distributed.initialize`` over DCN) with retry, replacing the
  Akka-cluster / YARN bootstrap.
- ``HeartbeatMonitor``: background liveness thread against a StateTracker —
  the MasterActor heartbeat map, minus the actors.
- ``FaultTolerantTrainer``: checkpoint-every-N-iterations + resume-latest,
  replacing ModelSavingActor persistence and giving the crash-restart story:
  a relaunched process calls ``resume()`` and continues from the last saved
  {conf JSON, params, updater state} zip (ModelSerializer format,
  util/ModelSerializer.java:31-96).
"""

from __future__ import annotations

import glob
import hashlib
import json
import logging
import os
import threading
import time
from dataclasses import dataclass
from typing import Callable, List, Optional

from deeplearning4j_tpu.parallel.statetracker import StateTracker
from deeplearning4j_tpu.resilience import RetryError, RetryPolicy, faults
from deeplearning4j_tpu.resilience.watchdog import StepWatchdog
from deeplearning4j_tpu.utils.fileio import atomic_write_text

logger = logging.getLogger(__name__)

DEFAULT_EVICTION_TIMEOUT_S = 120.0  # MasterActor parity


@dataclass
class ClusterConfig:
    """Multi-host topology (maps onto jax.distributed.initialize)."""

    coordinator_address: Optional[str] = None  # "host:port"
    num_processes: int = 1
    process_id: int = 0
    heartbeat_interval_s: float = 5.0
    eviction_timeout_s: float = DEFAULT_EVICTION_TIMEOUT_S


def initialize_distributed(config: ClusterConfig, retries: int = 3,
                           retry_delay_s: float = 5.0,
                           policy: Optional[RetryPolicy] = None) -> bool:
    """Join the multi-host JAX runtime; returns True when initialized.

    Single-process configs are a no-op (False). Failures retry under the
    shared :class:`RetryPolicy` (exponential backoff + jitter; pass
    ``policy`` to control it — ``retries``/``retry_delay_s`` are the
    legacy knobs and seed the default policy). The reference's equivalent
    is YARN re-requesting containers / Akka cluster re-join.
    """
    if config.num_processes <= 1 or config.coordinator_address is None:
        return False
    if policy is None:
        policy = RetryPolicy(max_attempts=retries,
                             base_delay_s=retry_delay_s,
                             max_delay_s=4 * retry_delay_s)

    def init():
        faults.fault_point("distributed.init")
        import jax

        jax.distributed.initialize(
            coordinator_address=config.coordinator_address,
            num_processes=config.num_processes,
            process_id=config.process_id,
        )

    try:
        policy.call(init)
    except RetryError as e:
        raise RuntimeError(
            f"jax.distributed.initialize failed after {e.attempts} attempts"
        ) from e.last
    return True


class HeartbeatMonitor:
    """Posts worker heartbeats on a timer; the coordinator side calls
    ``evict()`` to drop silent workers and requeue their jobs."""

    def __init__(self, tracker: StateTracker, worker_id: str,
                 interval_s: float = 5.0,
                 eviction_timeout_s: float = DEFAULT_EVICTION_TIMEOUT_S):
        self.tracker = tracker
        self.worker_id = worker_id
        self.interval_s = interval_s
        self.eviction_timeout_s = eviction_timeout_s
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    @classmethod
    def from_config(cls, tracker: StateTracker, worker_id: str,
                    config: ClusterConfig) -> "HeartbeatMonitor":
        return cls(tracker, worker_id,
                   interval_s=config.heartbeat_interval_s,
                   eviction_timeout_s=config.eviction_timeout_s)

    def _post(self) -> None:
        # liveness must degrade, not crash: a transient tracker error
        # (shared-fs hiccup, injected fault) skips one beat and keeps the
        # thread alive — eviction only triggers after MANY missed beats
        try:
            self.tracker.heartbeat(self.worker_id)
        except Exception:  # noqa: BLE001
            logger.warning("heartbeat post failed for %s (will retry on "
                           "next interval)", self.worker_id, exc_info=True)

    def start(self) -> "HeartbeatMonitor":
        if self._thread is not None:
            if self._thread.is_alive():
                return self
            self._thread = None  # crashed/finished thread: allow restart
        # a FRESH event captured by THIS thread's closure — stop() of a
        # previous incarnation (possibly still draining its join timeout)
        # can then never stop the new thread, and vice versa
        stop = threading.Event()
        self._stop = stop
        self._post()

        def run():
            while not stop.wait(self.interval_s):
                self._post()

        self._thread = threading.Thread(target=run, daemon=True,
                                        name=f"heartbeat-{self.worker_id}")
        self._thread.start()
        return self

    def stop(self) -> None:
        thread, stop = self._thread, self._stop
        if thread is None:
            return  # idempotent: stop() after stop() is a no-op
        self._thread = None
        stop.set()
        thread.join(timeout=self.interval_s + 1.0)

    def __enter__(self) -> "HeartbeatMonitor":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    def evict(self, timeout_s: Optional[float] = None) -> List[str]:
        return self.tracker.evict_stale(
            timeout_s if timeout_s is not None else self.eviction_timeout_s)


class FaultTolerantTrainer:
    """Checkpoint/resume training loop (elastic recovery).

    Wraps any network with ``fit(DataSet)`` + the ModelSerializer contract.
    Saves ``ckpt-<iteration>.zip`` every ``checkpoint_every`` iterations and
    retains the newest ``keep`` checkpoints. ``resume()`` restores the
    newest VERIFIED checkpoint (params + updater state + iteration counter)
    so a relaunched process continues where the dead one stopped — the TPU
    replacement for Hazelcast state replication + actor restart.

    Integrity contract: every save publishes a ``.sha256`` manifest sidecar
    (hash + size + iteration, written atomically AFTER the zip). ``resume``
    walks checkpoints newest → oldest and restores the first one whose
    bytes match its manifest and whose archive loads — a truncated or
    corrupt newest checkpoint (crash mid-write, bit-rot on shared storage)
    falls back to the next-older one instead of crashing or silently
    loading garbage. A checkpoint without a sidecar (pre-manifest writer)
    is *unverified*: it is still attempted, but any load error falls
    through to older candidates.
    """

    def __init__(self, network, checkpoint_dir: str,
                 checkpoint_every: int = 10, keep: int = 3,
                 tracker: Optional[StateTracker] = None,
                 worker_id: str = "worker-0",
                 heartbeat_interval_s: float = 5.0,
                 step_deadline_s: Optional[float] = None,
                 on_stall: Optional[Callable[[float], None]] = None):
        self.network = network
        self.dir = checkpoint_dir
        self.every = max(1, checkpoint_every)
        self.keep = max(1, keep)
        self.tracker = tracker
        self.worker_id = worker_id
        self.heartbeat_interval_s = heartbeat_interval_s
        self.step_deadline_s = step_deadline_s
        self.on_stall = on_stall
        os.makedirs(checkpoint_dir, exist_ok=True)

    # ------------------------------------------------------------------
    def _ckpt_path(self, iteration: int) -> str:
        return os.path.join(self.dir, f"ckpt-{iteration:012d}.zip")

    @staticmethod
    def _manifest_path(ckpt_path: str) -> str:
        return ckpt_path + ".sha256"

    def checkpoints(self) -> List[str]:
        return sorted(glob.glob(os.path.join(self.dir, "ckpt-*.zip")))

    def latest_checkpoint(self) -> Optional[str]:
        cks = self.checkpoints()
        return cks[-1] if cks else None

    # -- integrity -----------------------------------------------------
    @staticmethod
    def _sha256(path: str) -> str:
        h = hashlib.sha256()
        with open(path, "rb") as f:
            for chunk in iter(lambda: f.read(1 << 20), b""):
                h.update(chunk)
        return h.hexdigest()

    def _write_manifest(self, path: str) -> None:
        manifest = {
            "sha256": self._sha256(path),
            "size": os.path.getsize(path),
            "iteration": self.network.iteration_count,
            "format": "dl4j-tpu-ckpt-manifest-v1",
        }
        atomic_write_text(self._manifest_path(path), json.dumps(manifest))

    def verify_checkpoint(self, path: str) -> str:
        """``"ok"`` (manifest matches), ``"unverified"`` (no manifest —
        legacy writer), or ``"corrupt"`` (size/hash mismatch, i.e. a
        partial write or bit-rot)."""
        try:
            with open(self._manifest_path(path)) as f:
                manifest = json.load(f)
        except (FileNotFoundError, json.JSONDecodeError):
            return "unverified"
        try:
            if os.path.getsize(path) != manifest.get("size"):
                return "corrupt"
            if self._sha256(path) != manifest.get("sha256"):
                return "corrupt"
        except OSError:
            return "corrupt"
        return "ok"

    # -- save / resume -------------------------------------------------
    def save(self) -> str:
        from deeplearning4j_tpu.utils.serializer import ModelSerializer

        faults.fault_point("checkpoint.save")
        path = self._ckpt_path(self.network.iteration_count)
        tmp = path + ".tmp"
        ModelSerializer.write_model(self.network, tmp, save_updater=True)
        os.replace(tmp, path)
        self._write_manifest(path)
        for old in self.checkpoints()[:-self.keep]:
            os.unlink(old)
            try:
                os.unlink(self._manifest_path(old))
            except FileNotFoundError:
                pass  # legacy checkpoint without a sidecar
        if self.tracker is not None:
            self.tracker.put_meta("latest_checkpoint", path)
        return path

    def _resume_candidates(self) -> List[str]:
        """Newest → oldest, with the tracker's replicated pointer appended
        as a last resort (it may point outside self.dir after elastic
        restart onto a different host)."""
        cands = list(reversed(self.checkpoints()))
        if self.tracker is not None:
            meta = self.tracker.get_meta("latest_checkpoint")
            if meta and meta not in cands:
                cands.append(meta)
        return cands

    def resume(self) -> bool:
        """Restore the newest checkpoint that passes integrity
        verification AND loads cleanly; older checkpoints are fallbacks.
        Returns True when one was restored, False when none exists (a
        corrupt-only directory raises: silently starting from scratch
        when state was expected is the one thing recovery must not do).
        """
        from deeplearning4j_tpu.utils.serializer import ModelSerializer

        candidates = self._resume_candidates()
        saw_corrupt = []
        for path in candidates:
            faults.fault_point("checkpoint.restore")
            if not os.path.exists(path):
                continue
            verdict = self.verify_checkpoint(path)
            if verdict == "corrupt":
                logger.warning(
                    "checkpoint %s failed integrity verification; falling "
                    "back to an older checkpoint", path)
                saw_corrupt.append(path)
                continue
            try:
                restored = ModelSerializer.restore(path, load_updater=True)
            except Exception as e:  # noqa: BLE001 — any load error ⇒ next
                logger.warning(
                    "checkpoint %s (%s) failed to load (%s); falling back "
                    "to an older checkpoint", path, verdict, e)
                saw_corrupt.append(path)
                continue
            net = self.network
            net.params = restored.params
            net.updater_state = restored.updater_state
            net.net_state = restored.net_state
            net.iteration_count = restored.iteration_count
            if saw_corrupt:
                logger.warning("resumed from fallback %s (skipped %d bad "
                               "checkpoint(s))", path, len(saw_corrupt))
            return True
        if saw_corrupt:
            raise RuntimeError(
                f"all {len(saw_corrupt)} checkpoint(s) under {self.dir} "
                f"are corrupt or unloadable; refusing to silently restart "
                f"from scratch (newest: {saw_corrupt[0]})")
        return False

    # ------------------------------------------------------------------
    def fit(self, data, num_epochs: int = 1,
            on_iteration: Optional[Callable[[int], None]] = None):
        """Epoch loop with periodic checkpointing + heartbeats. With
        ``step_deadline_s`` set, a :class:`StepWatchdog` flags steps that
        hang past the deadline (``on_stall`` picks the policy: log /
        evict / abort — default logs)."""
        net = self.network
        monitor = None
        watchdog = None
        if self.tracker is not None:
            monitor = HeartbeatMonitor(
                self.tracker, self.worker_id,
                interval_s=self.heartbeat_interval_s).start()
        if self.step_deadline_s is not None:
            watchdog = StepWatchdog(self.step_deadline_s,
                                    on_stall=self.on_stall).start()
        try:
            for _ in range(num_epochs):
                if hasattr(data, "reset"):
                    data.reset()
                batches = [data] if not hasattr(data, "__iter__") else data
                for ds in batches:
                    net.fit(ds)
                    if watchdog is not None:
                        watchdog.beat()
                    if net.iteration_count % self.every == 0:
                        self.save()
                    if on_iteration is not None:
                        on_iteration(net.iteration_count)
            self.save()
        finally:
            if watchdog is not None:
                watchdog.stop()
            if monitor is not None:
                monitor.stop()
        return self
