"""Multi-host cluster runtime: launcher, liveness, elastic restart.

Replaces the reference's distributed *runtimes* (SURVEY §3.4): the Akka
MasterActor/WorkerActor parameter server with its heartbeat eviction
(actor/core/actor/MasterActor.java:141-171 — evict workers silent >= 120 s,
re-dispatch their jobs) and the YARN ApplicationMaster's container restart
+ ProgressReport RPC. On TPU the data plane needs none of that — a pod runs
ONE SPMD program and XLA collectives synchronize it — so what remains is:

- ``initialize_distributed``: bring the hosts into one JAX runtime
  (``jax.distributed.initialize`` over DCN) with retry, replacing the
  Akka-cluster / YARN bootstrap.
- ``HeartbeatMonitor``: background liveness thread against a StateTracker —
  the MasterActor heartbeat map, minus the actors.
- ``FaultTolerantTrainer``: checkpoint-every-N-iterations + resume-latest,
  replacing ModelSavingActor persistence and giving the crash-restart story:
  a relaunched process calls ``resume()`` and continues from the last saved
  {conf JSON, params, updater state} zip (ModelSerializer format,
  util/ModelSerializer.java:31-96).
"""

from __future__ import annotations

import glob
import os
import threading
import time
from dataclasses import dataclass
from typing import Callable, List, Optional

from deeplearning4j_tpu.parallel.statetracker import StateTracker

DEFAULT_EVICTION_TIMEOUT_S = 120.0  # MasterActor parity


@dataclass
class ClusterConfig:
    """Multi-host topology (maps onto jax.distributed.initialize)."""

    coordinator_address: Optional[str] = None  # "host:port"
    num_processes: int = 1
    process_id: int = 0
    heartbeat_interval_s: float = 5.0
    eviction_timeout_s: float = DEFAULT_EVICTION_TIMEOUT_S


def initialize_distributed(config: ClusterConfig, retries: int = 3,
                           retry_delay_s: float = 5.0) -> bool:
    """Join the multi-host JAX runtime; returns True when initialized.

    Single-process configs are a no-op (False). Failures retry with delay —
    the reference's equivalent is YARN re-requesting containers / Akka
    cluster re-join.
    """
    if config.num_processes <= 1 or config.coordinator_address is None:
        return False
    import jax

    last_err: Optional[Exception] = None
    for _ in range(retries):
        try:
            jax.distributed.initialize(
                coordinator_address=config.coordinator_address,
                num_processes=config.num_processes,
                process_id=config.process_id,
            )
            return True
        except Exception as e:  # noqa: BLE001 — init raises RuntimeError/grpc
            last_err = e
            time.sleep(retry_delay_s)
    raise RuntimeError(
        f"jax.distributed.initialize failed after {retries} attempts"
    ) from last_err


class HeartbeatMonitor:
    """Posts worker heartbeats on a timer; the coordinator side calls
    ``evict()`` to drop silent workers and requeue their jobs."""

    def __init__(self, tracker: StateTracker, worker_id: str,
                 interval_s: float = 5.0,
                 eviction_timeout_s: float = DEFAULT_EVICTION_TIMEOUT_S):
        self.tracker = tracker
        self.worker_id = worker_id
        self.interval_s = interval_s
        self.eviction_timeout_s = eviction_timeout_s
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    @classmethod
    def from_config(cls, tracker: StateTracker, worker_id: str,
                    config: ClusterConfig) -> "HeartbeatMonitor":
        return cls(tracker, worker_id,
                   interval_s=config.heartbeat_interval_s,
                   eviction_timeout_s=config.eviction_timeout_s)

    def start(self) -> "HeartbeatMonitor":
        if self._thread is not None:
            return self
        self._stop = threading.Event()  # support stop() → start() restart
        self.tracker.heartbeat(self.worker_id)

        def run():
            while not self._stop.wait(self.interval_s):
                self.tracker.heartbeat(self.worker_id)

        self._thread = threading.Thread(target=run, daemon=True,
                                        name=f"heartbeat-{self.worker_id}")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=self.interval_s + 1.0)
            self._thread = None

    def __enter__(self) -> "HeartbeatMonitor":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    def evict(self, timeout_s: Optional[float] = None) -> List[str]:
        return self.tracker.evict_stale(
            timeout_s if timeout_s is not None else self.eviction_timeout_s)


class FaultTolerantTrainer:
    """Checkpoint/resume training loop (elastic recovery).

    Wraps any network with ``fit(DataSet)`` + the ModelSerializer contract.
    Saves ``ckpt-<iteration>.zip`` every ``checkpoint_every`` iterations and
    retains the newest ``keep`` checkpoints. ``resume()`` restores the
    newest checkpoint (params + updater state + iteration counter) so a
    relaunched process continues where the dead one stopped — the TPU
    replacement for Hazelcast state replication + actor restart.
    """

    def __init__(self, network, checkpoint_dir: str,
                 checkpoint_every: int = 10, keep: int = 3,
                 tracker: Optional[StateTracker] = None,
                 worker_id: str = "worker-0",
                 heartbeat_interval_s: float = 5.0):
        self.network = network
        self.dir = checkpoint_dir
        self.every = max(1, checkpoint_every)
        self.keep = max(1, keep)
        self.tracker = tracker
        self.worker_id = worker_id
        self.heartbeat_interval_s = heartbeat_interval_s
        os.makedirs(checkpoint_dir, exist_ok=True)

    # ------------------------------------------------------------------
    def _ckpt_path(self, iteration: int) -> str:
        return os.path.join(self.dir, f"ckpt-{iteration:012d}.zip")

    def checkpoints(self) -> List[str]:
        return sorted(glob.glob(os.path.join(self.dir, "ckpt-*.zip")))

    def latest_checkpoint(self) -> Optional[str]:
        cks = self.checkpoints()
        return cks[-1] if cks else None

    def save(self) -> str:
        from deeplearning4j_tpu.utils.serializer import ModelSerializer

        path = self._ckpt_path(self.network.iteration_count)
        tmp = path + ".tmp"
        ModelSerializer.write_model(self.network, tmp, save_updater=True)
        os.replace(tmp, path)
        for old in self.checkpoints()[:-self.keep]:
            os.unlink(old)
        if self.tracker is not None:
            self.tracker.put_meta("latest_checkpoint", path)
        return path

    def resume(self) -> bool:
        """Restore the newest checkpoint into the wrapped network.
        Returns True when a checkpoint was found."""
        from deeplearning4j_tpu.utils.serializer import ModelSerializer

        path = self.latest_checkpoint()
        if path is None and self.tracker is not None:
            path = self.tracker.get_meta("latest_checkpoint")
        if path is None or not os.path.exists(path):
            return False
        restored = ModelSerializer.restore(path, load_updater=True)
        net = self.network
        net.params = restored.params
        net.updater_state = restored.updater_state
        net.net_state = restored.net_state
        net.iteration_count = restored.iteration_count
        return True

    # ------------------------------------------------------------------
    def fit(self, data, num_epochs: int = 1,
            on_iteration: Optional[Callable[[int], None]] = None):
        """Epoch loop with periodic checkpointing + heartbeats."""
        net = self.network
        monitor = None
        if self.tracker is not None:
            monitor = HeartbeatMonitor(
                self.tracker, self.worker_id,
                interval_s=self.heartbeat_interval_s).start()
        try:
            for _ in range(num_epochs):
                if hasattr(data, "reset"):
                    data.reset()
                batches = [data] if not hasattr(data, "__iter__") else data
                for ds in batches:
                    net.fit(ds)
                    if net.iteration_count % self.every == 0:
                        self.save()
                    if on_iteration is not None:
                        on_iteration(net.iteration_count)
            self.save()
        finally:
            if monitor is not None:
                monitor.stop()
        return self
