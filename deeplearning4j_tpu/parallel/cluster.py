"""Multi-host cluster runtime: launcher, liveness, elastic restart.

Replaces the reference's distributed *runtimes* (SURVEY §3.4): the Akka
MasterActor/WorkerActor parameter server with its heartbeat eviction
(actor/core/actor/MasterActor.java:141-171 — evict workers silent >= 120 s,
re-dispatch their jobs) and the YARN ApplicationMaster's container restart
+ ProgressReport RPC. On TPU the data plane needs none of that — a pod runs
ONE SPMD program and XLA collectives synchronize it — so what remains is:

- ``initialize_distributed``: bring the hosts into one JAX runtime
  (``jax.distributed.initialize`` over DCN) with retry, replacing the
  Akka-cluster / YARN bootstrap.
- ``HeartbeatMonitor``: background liveness thread against a StateTracker —
  the MasterActor heartbeat map, minus the actors.
- ``FaultTolerantTrainer``: checkpoint-every-N-iterations + resume-latest,
  replacing ModelSavingActor persistence and giving the crash-restart story:
  a relaunched process calls ``resume()`` and continues from the last saved
  {conf JSON, params, updater state} zip (ModelSerializer format,
  util/ModelSerializer.java:31-96).
"""

from __future__ import annotations

import concurrent.futures
import glob
import hashlib
import json
import logging
import os
import threading
import time
from dataclasses import dataclass
from typing import Callable, List, Optional

from deeplearning4j_tpu.monitor import metrics, record_counter, tracer
from deeplearning4j_tpu.parallel.statetracker import StateTracker
from deeplearning4j_tpu.resilience import RetryError, RetryPolicy, faults
from deeplearning4j_tpu.resilience.preemption import PreemptionGuard
from deeplearning4j_tpu.resilience.watchdog import StepWatchdog
from deeplearning4j_tpu.utils.fileio import atomic_write_text

logger = logging.getLogger(__name__)

DEFAULT_EVICTION_TIMEOUT_S = 120.0  # MasterActor parity


@dataclass
class ClusterConfig:
    """Multi-host topology (maps onto jax.distributed.initialize)."""

    coordinator_address: Optional[str] = None  # "host:port"
    num_processes: int = 1
    process_id: int = 0
    heartbeat_interval_s: float = 5.0
    eviction_timeout_s: float = DEFAULT_EVICTION_TIMEOUT_S


def initialize_distributed(config: ClusterConfig, retries: int = 3,
                           retry_delay_s: float = 5.0,
                           policy: Optional[RetryPolicy] = None) -> bool:
    """Join the multi-host JAX runtime; returns True when initialized.

    Single-process configs are a no-op (False). Failures retry under the
    shared :class:`RetryPolicy` (exponential backoff + jitter; pass
    ``policy`` to control it — ``retries``/``retry_delay_s`` are the
    legacy knobs and seed the default policy). The reference's equivalent
    is YARN re-requesting containers / Akka cluster re-join.
    """
    if config.num_processes <= 1 or config.coordinator_address is None:
        return False
    if policy is None:
        policy = RetryPolicy(max_attempts=retries,
                             base_delay_s=retry_delay_s,
                             max_delay_s=4 * retry_delay_s)

    def init():
        faults.fault_point("distributed.init")
        import jax

        jax.distributed.initialize(
            coordinator_address=config.coordinator_address,
            num_processes=config.num_processes,
            process_id=config.process_id,
        )

    try:
        policy.call(init)
    except RetryError as e:
        raise RuntimeError(
            f"jax.distributed.initialize failed after {e.attempts} attempts"
        ) from e.last
    return True


class HeartbeatMonitor:
    """Posts worker heartbeats on a timer; the coordinator side calls
    ``evict()`` to drop silent workers and requeue their jobs.

    ``payload_fn`` (optional) is called before every beat and its dict
    rides along as the beat's compact metrics payload (step time,
    goodput, last-chunk loss — whatever the worker wants the master's
    fleet view to see). A failing ``payload_fn`` degrades to a
    payload-less beat — liveness must never depend on telemetry — and a
    tracker whose ``heartbeat`` predates the ``metrics=`` parameter
    gets the legacy payload-less call."""

    def __init__(self, tracker: StateTracker, worker_id: str,
                 interval_s: float = 5.0,
                 eviction_timeout_s: float = DEFAULT_EVICTION_TIMEOUT_S,
                 payload_fn: Optional[Callable[[], Optional[dict]]] = None):
        self.tracker = tracker
        self.worker_id = worker_id
        self.interval_s = interval_s
        self.eviction_timeout_s = eviction_timeout_s
        self.payload_fn = payload_fn
        self._supports_metrics: Optional[bool] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    @classmethod
    def from_config(cls, tracker: StateTracker, worker_id: str,
                    config: ClusterConfig) -> "HeartbeatMonitor":
        return cls(tracker, worker_id,
                   interval_s=config.heartbeat_interval_s,
                   eviction_timeout_s=config.eviction_timeout_s)

    def _post(self) -> None:
        # liveness must degrade, not crash: a transient tracker error
        # (shared-fs hiccup, injected fault) skips one beat and keeps the
        # thread alive — eviction only triggers after MANY missed beats
        payload = None
        if self.payload_fn is not None:
            try:
                payload = self.payload_fn()
            except Exception:  # noqa: BLE001 — telemetry never blocks liveness
                logger.debug("heartbeat payload_fn failed for %s; "
                             "posting payload-less beat", self.worker_id,
                             exc_info=True)
        try:
            if payload is None or not self._tracker_takes_metrics():
                self.tracker.heartbeat(self.worker_id)
            else:
                self.tracker.heartbeat(self.worker_id, metrics=payload)
        except Exception:  # noqa: BLE001
            logger.warning("heartbeat post failed for %s (will retry on "
                           "next interval)", self.worker_id, exc_info=True)

    def _tracker_takes_metrics(self) -> bool:
        # signature inspection, cached, instead of catching TypeError
        # from the live call: a TypeError the tracker itself raises
        # (e.g. a non-JSON-serializable payload value) must surface as
        # a warning, not be misread as "pre-payload implementation" and
        # silently demote every future beat to payload-less
        if self._supports_metrics is None:
            import inspect

            try:
                params = inspect.signature(
                    self.tracker.heartbeat).parameters.values()
                self._supports_metrics = any(
                    p.name == "metrics"
                    or p.kind is inspect.Parameter.VAR_KEYWORD
                    for p in params)
            except (TypeError, ValueError):  # uninspectable callable
                self._supports_metrics = True
        return self._supports_metrics

    def start(self) -> "HeartbeatMonitor":
        if self._thread is not None:
            if self._thread.is_alive():
                return self
            self._thread = None  # crashed/finished thread: allow restart
        # a FRESH event captured by THIS thread's closure — stop() of a
        # previous incarnation (possibly still draining its join timeout)
        # can then never stop the new thread, and vice versa
        stop = threading.Event()
        self._stop = stop
        self._post()

        def run():
            while not stop.wait(self.interval_s):
                self._post()

        self._thread = threading.Thread(target=run, daemon=True,
                                        name=f"heartbeat-{self.worker_id}")
        self._thread.start()
        return self

    def stop(self) -> None:
        thread, stop = self._thread, self._stop
        if thread is None:
            return  # idempotent: stop() after stop() is a no-op
        self._thread = None
        stop.set()
        thread.join(timeout=self.interval_s + 1.0)

    def __enter__(self) -> "HeartbeatMonitor":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    def evict(self, timeout_s: Optional[float] = None) -> List[str]:
        return self.tracker.evict_stale(
            timeout_s if timeout_s is not None else self.eviction_timeout_s)


def _log_failed_save(fut: "concurrent.futures.Future") -> None:
    if fut.cancelled():
        return
    exc = fut.exception()
    if exc is not None:
        logger.warning("background checkpoint write failed: %s", exc,
                       exc_info=exc)


class FaultTolerantTrainer:
    """Checkpoint/resume training loop (elastic recovery).

    Wraps any network with ``fit(DataSet)`` + the ModelSerializer contract.
    Saves ``ckpt-<iteration>.zip`` every ``checkpoint_every`` iterations and
    retains the newest ``keep`` checkpoints. ``resume()`` restores the
    newest VERIFIED checkpoint (params + updater state + iteration counter)
    so a relaunched process continues where the dead one stopped — the TPU
    replacement for Hazelcast state replication + actor restart.

    Integrity contract: every save publishes a ``.sha256`` manifest sidecar
    (hash + size + iteration, written atomically AFTER the zip). ``resume``
    walks checkpoints newest → oldest and restores the first one whose
    bytes match its manifest and whose archive loads — a truncated or
    corrupt newest checkpoint (crash mid-write, bit-rot on shared storage)
    falls back to the next-older one instead of crashing or silently
    loading garbage. A checkpoint without a sidecar (pre-manifest writer)
    is *unverified*: it is still attempted, but any load error falls
    through to older candidates.
    """

    def __init__(self, network, checkpoint_dir: str,
                 checkpoint_every: int = 10, keep: int = 3,
                 tracker: Optional[StateTracker] = None,
                 worker_id: str = "worker-0",
                 heartbeat_interval_s: float = 5.0,
                 step_deadline_s: Optional[float] = None,
                 on_stall: Optional[Callable[[float], None]] = None):
        self.network = network
        # ``network`` may be a ParallelWrapper; serialization and cursor
        # bookkeeping always target the real model underneath, while
        # fit/fit_epochs go through the handle the caller gave us
        self.model = getattr(network, "network", network)
        self.dir = checkpoint_dir
        self.every = max(1, checkpoint_every)
        self.keep = max(1, keep)
        self.tracker = tracker
        self.worker_id = worker_id
        self.heartbeat_interval_s = heartbeat_interval_s
        self.step_deadline_s = step_deadline_s
        self.on_stall = on_stall
        self.preempted = False  # last fit/fit_epochs stopped on preemption
        self._save_executor: Optional[
            concurrent.futures.ThreadPoolExecutor] = None
        self._pending_save: Optional[concurrent.futures.Future] = None
        os.makedirs(checkpoint_dir, exist_ok=True)

    # ------------------------------------------------------------------
    def _ckpt_path(self, iteration: int) -> str:
        return os.path.join(self.dir, f"ckpt-{iteration:012d}.zip")

    @staticmethod
    def _manifest_path(ckpt_path: str) -> str:
        return ckpt_path + ".sha256"

    def checkpoints(self) -> List[str]:
        return sorted(glob.glob(os.path.join(self.dir, "ckpt-*.zip")))

    def latest_checkpoint(self) -> Optional[str]:
        cks = self.checkpoints()
        return cks[-1] if cks else None

    # -- integrity -----------------------------------------------------
    @staticmethod
    def _sha256(path: str) -> str:
        h = hashlib.sha256()
        with open(path, "rb") as f:
            for chunk in iter(lambda: f.read(1 << 20), b""):
                h.update(chunk)
        return h.hexdigest()

    def _write_manifest(self, path: str, iteration: int) -> None:
        manifest = {
            "sha256": self._sha256(path),
            "size": os.path.getsize(path),
            "iteration": iteration,
            "format": "dl4j-tpu-ckpt-manifest-v1",
        }
        atomic_write_text(self._manifest_path(path), json.dumps(manifest))

    def verify_checkpoint(self, path: str) -> str:
        """``"ok"`` (manifest matches), ``"unverified"`` (no manifest —
        legacy writer), or ``"corrupt"`` (size/hash mismatch, i.e. a
        partial write or bit-rot)."""
        with tracer().span("checkpoint.verify",
                           path=os.path.basename(path)) as sp:
            sp.attrs["verdict"] = verdict = self._verify_impl(path)
        return verdict

    def _verify_impl(self, path: str) -> str:
        try:
            with open(self._manifest_path(path)) as f:
                manifest = json.load(f)
        except (FileNotFoundError, json.JSONDecodeError):
            return "unverified"
        try:
            if os.path.getsize(path) != manifest.get("size"):
                return "corrupt"
            if self._sha256(path) != manifest.get("sha256"):
                return "corrupt"
        except OSError:
            return "corrupt"
        return "ok"

    # -- save / resume -------------------------------------------------
    def _write_checkpoint(self, model, path: str) -> str:
        """Serialize ``model`` (live network or host snapshot) to
        ``path`` with the full integrity ritual: tmp + rename, manifest
        sidecar, prune, tracker pointer. Runs on the caller's thread for
        ``save`` and on the writer thread for ``save_async``. Write
        latency lands in the ``checkpoint_write_seconds`` histogram and a
        ``checkpoint.write`` span — the signal that tells a slow shared
        filesystem apart from a wedged chunk."""
        from deeplearning4j_tpu.utils.serializer import ModelSerializer

        # background=True marks writes on the save_async writer thread:
        # they overlap compute, so the run ledger books them as hidden
        # rather than checkpoint badput
        with tracer().span("checkpoint.write",
                           path=os.path.basename(path),
                           iteration=model.iteration_count,
                           background=threading.current_thread().name
                           .startswith("ckpt-writer")) as sp:
            tmp = path + ".tmp"
            ModelSerializer.write_model(model, tmp, save_updater=True)
            os.replace(tmp, path)
            self._write_manifest(path, model.iteration_count)
            for old in self.checkpoints()[:-self.keep]:
                os.unlink(old)
                try:
                    os.unlink(self._manifest_path(old))
                except FileNotFoundError:
                    pass  # legacy checkpoint without a sidecar
            if self.tracker is not None:
                self.tracker.put_meta("latest_checkpoint", path)
        metrics().histogram(
            "checkpoint_write_seconds",
            "zip + sha256 manifest + prune wall time").observe(
            sp.duration_s)
        record_counter("checkpoint_saves_total")
        return path

    def save(self) -> str:
        faults.fault_point("checkpoint.save")
        self.wait_for_saves()  # never interleave with an async write
        return self._write_checkpoint(
            self.model, self._ckpt_path(self.model.iteration_count))

    # -- async save ----------------------------------------------------
    def _snapshot_model(self):
        """A frozen host-side copy of the model for the background
        writer: same class (so ModelSerializer dispatches identically),
        state trees gathered to host numpy ONCE — blocking only on the
        chunk that produced them, never on the write — plus the training
        cursors the preemption contract checkpoints. The live network is
        free to dispatch (and donate its buffers to) the next chunk the
        moment this returns. Only MultiLayerNetwork/ComputationGraph
        speak this snapshot surface; other model types (TransformerLM)
        return None and ``save_async`` degrades to a synchronous
        ``save``."""
        import jax

        net = self.model
        if not hasattr(net, "conf") or not hasattr(net, "updater_state"):
            return None
        snap = object.__new__(type(net))
        snap.conf = net.conf
        snap.params = jax.device_get(net.params)
        snap.updater_state = jax.device_get(net.updater_state)
        snap.net_state = jax.device_get(net.net_state)
        snap.iteration_count = net.iteration_count
        snap._initialized = True
        if hasattr(net, "_rng"):
            snap._rng = jax.device_get(net._rng)
        snap._lr_scale_host = getattr(net, "_lr_scale_host", 1.0)
        snap._epoch_cursor = getattr(net, "_epoch_cursor", 0)
        snap._step_cursor = getattr(net, "_step_cursor", 0)
        return snap

    def save_async(self) -> "concurrent.futures.Future":
        """``save()`` split at the device/host boundary: the device->host
        copy happens NOW (so the bytes are immutable), the zip + manifest
        write happens on a single background writer thread — the next
        chunk dispatches while the previous checkpoint serializes.
        Returns the Future of the checkpoint path; ``wait_for_saves``
        joins it. Writes are serialized on one thread, so a slow disk
        backs saves up instead of corrupting them."""
        faults.fault_point("checkpoint.save")
        # the snapshot is the only part the host BLOCKS on — its span is
        # the "how long did save_async stall training" answer
        with tracer().span("checkpoint.snapshot") as sp:
            snap = self._snapshot_model()
        metrics().histogram(
            "checkpoint_snapshot_seconds",
            "device->host state copy (the blocking part of save_async)"
        ).observe(sp.duration_s)
        if snap is None:  # model type without the snapshot surface
            fut: concurrent.futures.Future = concurrent.futures.Future()
            try:
                fut.set_result(self._write_checkpoint(
                    self.model, self._ckpt_path(self.model.iteration_count)))
            except BaseException as e:  # noqa: BLE001
                fut.set_exception(e)
            return fut
        if self._save_executor is None:
            self._save_executor = concurrent.futures.ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="ckpt-writer")
        fut = self._save_executor.submit(
            self._write_checkpoint, snap,
            self._ckpt_path(snap.iteration_count))
        # a failed background write must never vanish just because a
        # newer save superseded it before anyone joined the future
        fut.add_done_callback(_log_failed_save)
        self._pending_save = fut
        return fut

    def wait_for_saves(self, timeout: Optional[float] = None):
        """Block until the in-flight async checkpoint (if any) is on
        disk; re-raises a failed write. Returns its path or None. Also
        retires the (non-daemon) writer thread so an idle trainer never
        delays interpreter shutdown; the next ``save_async`` spins a
        fresh one."""
        fut, self._pending_save = self._pending_save, None
        if fut is None:
            return None
        try:
            return fut.result(timeout=timeout)
        finally:
            ex, self._save_executor = self._save_executor, None
            if ex is not None:
                ex.shutdown(wait=False)

    def _resume_candidates(self) -> List[str]:
        """Newest → oldest, with the tracker's replicated pointer appended
        as a last resort (it may point outside self.dir after elastic
        restart onto a different host)."""
        cands = list(reversed(self.checkpoints()))
        if self.tracker is not None:
            meta = self.tracker.get_meta("latest_checkpoint")
            if meta and meta not in cands:
                cands.append(meta)
        return cands

    def resume(self, mesh=None, fsdp: bool = False) -> bool:
        """Restore the newest checkpoint that passes integrity
        verification AND loads cleanly; older checkpoints are fallbacks.
        Returns True when one was restored, False when none exists (a
        corrupt-only directory raises: silently starting from scratch
        when state was expected is the one thing recovery must not do).

        Beyond the weights, resume restores the TRAINING state a
        preemption-safe checkpoint carries: the epoch RNG key (so the
        per-chunk key splits — and therefore every future epoch
        permutation, re-derived via the pure ``epoch_schedule`` — continue
        the dead run's exact stream), the host LR scale, and the
        epoch/step cursors ``fit``/``fit_epochs`` use to skip
        already-consumed work instead of restarting the epoch.

        Elastic re-sharding: ``mesh=`` re-lays-out the restored state for
        a DIFFERENT data-parallel width than the one the checkpoint was
        saved at — replicated over the new mesh by default, FSDP-sharded
        over its ``data`` axis with ``fsdp=True``. The checkpoint stores
        full host tensors (GSPMD's sharding is a layout, not a format),
        so any checkpoint restores onto any mesh; callers then rebuild
        the epoch cache under the new per-shard HBM budget
        (``build_epoch_cache(mesh=...)`` / ``ParallelWrapper``), which
        replicates-and-streams cleanly when the batch axis no longer
        divides the new width."""
        with tracer().span("checkpoint.resume") as resume_span:
            return self._resume_impl(mesh, fsdp, resume_span)

    def _resume_impl(self, mesh, fsdp: bool, resume_span) -> bool:
        from deeplearning4j_tpu.utils.serializer import ModelSerializer

        candidates = self._resume_candidates()
        saw_corrupt = []
        for path in candidates:
            faults.fault_point("checkpoint.restore")
            if not os.path.exists(path):
                continue
            verdict = self.verify_checkpoint(path)
            if verdict == "corrupt":
                logger.warning(
                    "checkpoint %s failed integrity verification; falling "
                    "back to an older checkpoint", path)
                saw_corrupt.append(path)
                continue
            try:
                restored = ModelSerializer.restore(path, load_updater=True)
            except Exception as e:  # noqa: BLE001 — any load error ⇒ next
                logger.warning(
                    "checkpoint %s (%s) failed to load (%s); falling back "
                    "to an older checkpoint", path, verdict, e)
                saw_corrupt.append(path)
                continue
            net = self.model
            net.params = restored.params
            net.updater_state = restored.updater_state
            net.net_state = restored.net_state
            net.iteration_count = restored.iteration_count
            for attr in ("_rng", "_lr_scale_host", "_epoch_cursor",
                         "_step_cursor"):
                if hasattr(restored, attr):
                    setattr(net, attr, getattr(restored, attr))
            if mesh is not None:
                self._reshard(mesh, fsdp)
            if saw_corrupt:
                logger.warning("resumed from fallback %s (skipped %d bad "
                               "checkpoint(s))", path, len(saw_corrupt))
            resume_span.attrs.update(restored=os.path.basename(path),
                                     skipped=len(saw_corrupt))
            record_counter("checkpoint_resumes_total", outcome="restored")
            return True
        resume_span.attrs["skipped"] = len(saw_corrupt)
        if saw_corrupt:
            raise RuntimeError(
                f"all {len(saw_corrupt)} checkpoint(s) under {self.dir} "
                f"are corrupt or unloadable; refusing to silently restart "
                f"from scratch (newest: {saw_corrupt[0]})")
        return False

    def _reshard(self, mesh, fsdp: bool) -> None:  # dl4j-lint: disable=adhoc-out-shardings -- restore-path placement on a freshly restored model; mirrors registry replicated layout
        """Place the restored state on ``mesh``: replicated (the layout
        the fused SPMD programs pin) or FSDP-sharded over ``data``."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        net = self.model
        repl = NamedSharding(mesh, P())
        if fsdp:
            from deeplearning4j_tpu.parallel.fsdp import shard_tree

            net.params = shard_tree(net.params, mesh)
            net.updater_state = shard_tree(net.updater_state, mesh)
        else:
            net.params = jax.device_put(net.params, repl)
            net.updater_state = jax.device_put(net.updater_state, repl)
        net.net_state = jax.device_put(net.net_state, repl)

    # ------------------------------------------------------------------
    def fit(self, data, num_epochs: int = 1,
            on_iteration: Optional[Callable[[int], None]] = None,
            preemption: Optional[PreemptionGuard] = None):
        """Epoch loop with periodic checkpointing + heartbeats. With
        ``step_deadline_s`` set, a :class:`StepWatchdog` flags steps that
        hang past the deadline (``on_stall`` picks the policy: log /
        evict / abort — default logs).

        Preemption + mid-epoch resume: pass (or default-construct via
        ``preemption=PreemptionGuard()``) a guard and the loop polls it
        per batch — on request it checkpoints synchronously and returns
        with ``self.preempted = True``. Every checkpoint records the
        STEP cursor (batches consumed in the in-progress epoch), and a
        resumed run skips exactly that many leading batches instead of
        restarting the epoch — with a deterministic iterator order this
        continues the epoch where the dead process stopped."""
        net = self.network
        model = self.model
        self.preempted = False
        monitor = None
        watchdog = None
        if self.tracker is not None:
            monitor = HeartbeatMonitor(
                self.tracker, self.worker_id,
                interval_s=self.heartbeat_interval_s).start()
        if self.step_deadline_s is not None:
            watchdog = StepWatchdog(self.step_deadline_s,
                                    on_stall=self.on_stall).start()
        # a checkpoint taken mid-epoch stored how many batches of the
        # in-progress epoch were already consumed; skip exactly those
        skip = int(getattr(model, "_step_cursor", 0) or 0)
        try:
            if preemption is not None:
                preemption.install()
            for _ in range(num_epochs):
                if hasattr(data, "reset"):
                    data.reset()
                batches = [data] if not hasattr(data, "__iter__") else data
                for step_idx, ds in enumerate(batches):
                    if skip:
                        skip -= 1
                        continue
                    net.fit(ds)
                    model._step_cursor = step_idx + 1
                    if watchdog is not None:
                        watchdog.beat()
                    if model.iteration_count % self.every == 0:
                        self.save()
                    if on_iteration is not None:
                        on_iteration(model.iteration_count)
                    if preemption is not None and preemption.check():
                        self.save()
                        self.preempted = True
                        return self
                model._step_cursor = 0
            self.save()
        finally:
            if preemption is not None:
                preemption.uninstall()
            if watchdog is not None:
                watchdog.stop()
            if monitor is not None:
                monitor.stop()
        return self

    def fit_epochs(self, data, num_epochs: int, *,
                   chunk_epochs: Optional[int] = 1,
                   save_every_chunks: int = 1,
                   preemption: Optional[PreemptionGuard] = None,
                   **fit_kw):
        """Preemption-safe fused training: ``network.fit_epochs`` with a
        chunk-boundary hook that (a) checkpoints asynchronously every
        ``save_every_chunks`` chunks — device->host copy now, zip write
        on the background writer, the next chunk dispatching immediately
        — and (b) polls the :class:`PreemptionGuard` (SIGTERM or an
        injected ``preempt.chunk`` fault): on request it takes one final
        SYNCHRONOUS verified checkpoint and stops cleanly with
        ``self.preempted = True``.

        The resume contract is bitwise: the checkpoint carries the epoch
        RNG key and the epoch cursor, the per-chunk key splits are a pure
        function of the key, and every epoch's permutation re-derives
        from its key inside the program — so ``resume()`` followed by the
        SAME ``fit_epochs`` call trains the remaining epochs on exactly
        the key stream the uninterrupted run would have used, landing on
        identical final params (identical to the last ulp across a
        device-count change too, up to the gradient all-reduce's
        summation order — see docs/resilience.md). Returns the loss
        history of the epochs run in THIS process (None if none
        remained)."""
        net = self.network
        model = self.model
        self.preempted = False
        guard = preemption or PreemptionGuard()
        start = int(getattr(model, "_epoch_cursor", 0) or 0)
        if start >= num_epochs:
            logger.info("fit_epochs: checkpoint cursor already at epoch "
                        "%d of %d — nothing to do", start, num_epochs)
            return None
        model._epoch_cursor = start
        model._step_cursor = 0
        chunks = {"n": 0}

        def on_chunk(done: int) -> bool:
            # the trainer owns the ABSOLUTE cursor (done is relative to
            # this process's run); chunk boundaries are epoch-aligned
            model._epoch_cursor = start + done
            model._step_cursor = 0
            chunks["n"] += 1
            if guard.check():
                # final checkpoint must be ON DISK and verified before
                # we report a clean stop — synchronous by design
                self.save()
                self.preempted = True
                return True
            if chunks["n"] % max(1, save_every_chunks) == 0:
                self.save_async()
            return False

        with guard:
            hist = net.fit_epochs(data, num_epochs - start,
                                  chunk_epochs=chunk_epochs,
                                  on_chunk=on_chunk, **fit_kw)
            self.wait_for_saves()
            if not self.preempted:
                # fallback paths (streaming / per-step) never fire
                # on_chunk; a completed run is complete either way
                model._epoch_cursor = num_epochs
                self.save()
                # the CHECKPOINT keeps cursor=num_epochs so a crash-
                # restart loop that re-runs this job is idempotent
                # (resume -> nothing left -> no retraining); the LIVE
                # model resets so another interactive fit_epochs call
                # trains again instead of silently no-oping
                model._epoch_cursor = 0
        return hist
