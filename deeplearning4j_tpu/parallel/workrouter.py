"""Work routing: synchronous iterative-reduce vs asynchronous Hogwild.

Re-design of the reference's scaleout SPI and its two dispatch policies:
``deeplearning4j-scaleout-api/.../workrouter/WorkRouter.java`` with
``IterativeReduceWorkRouter.java:48-53`` (master waits until
``updates.size() >= workers.size()`` before averaging + redistribution) and
``HogWildWorkRouter.java:32`` ("Async updates" — apply each worker's update
as it lands, no barrier); performers per
``perform/BaseMultiLayerNetworkWorkPerformer.java`` (deserialize conf JSON,
fit on the job's DataSet, emit flat params) and aggregation per
``aggregator/INDArrayAggregator`` (parameter averaging).

The actor system is gone: workers are threads or processes sharing a
``StateTracker`` (in-memory or file-backed), the master loop is
``DistributedTrainer`` (the ``DeepLearning4jDistributed.train()`` role,
SURVEY §3.4), and the heavy math inside each perform() is the normal jitted
device step. This layer exists for the reference's *control-plane* parity —
in-slice gradient sync should use ``ParallelWrapper``'s XLA collectives
instead (SURVEY §7.7a).
"""

from __future__ import annotations

import logging
import statistics
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

from deeplearning4j_tpu.monitor import metrics, record_counter, tracer
from deeplearning4j_tpu.parallel.statetracker import StateTracker

logger = logging.getLogger(__name__)


# ---------------------------------------------------------------------------
# SPI
# ---------------------------------------------------------------------------


class WorkerPerformer:
    """perform(job payload) → flat update array (WorkerPerformer.java)."""

    def perform(self, payload: Any) -> np.ndarray:
        raise NotImplementedError

    def update(self, params: np.ndarray) -> None:
        """Receive redistributed parameters (WorkerPerformer.update)."""


class NetworkWorkPerformer(WorkerPerformer):
    """Fit a MultiLayerNetwork on each job's DataSet and emit flat params
    (BaseMultiLayerNetworkWorkPerformer.java: conf JSON in, params out)."""

    def __init__(self, conf_json: str, fit_epochs: int = 1):
        from deeplearning4j_tpu.nn.conf.neural_net import (
            MultiLayerConfiguration)
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

        self.network = MultiLayerNetwork(
            MultiLayerConfiguration.from_json(conf_json)).init()
        self.fit_epochs = fit_epochs

    def perform(self, payload: Any) -> np.ndarray:
        from deeplearning4j_tpu.datasets.dataset import DataSet

        ds = DataSet(np.asarray(payload["features"], np.float32),
                     np.asarray(payload["labels"], np.float32))
        self.network.fit(ds, num_epochs=self.fit_epochs)
        return self.network.get_flat_params()

    def update(self, params: np.ndarray) -> None:
        self.network.set_flat_params(np.asarray(params))


def update_straggler_flags(samples: Dict[str, float], flagged: set,
                           ratio: float, *, id_label: str,
                           value_key: str, counter_name: str,
                           event_name: str,
                           min_reporting: int = 3) -> Optional[float]:
    """Shared outlier rule for fleet views (training master tick AND the
    serve-fleet controller): a member whose sample exceeds ``ratio`` x
    the fleet median gets flagged — with the evidence (value, median,
    ratio) on the timeline — and un-flagged on recovery. Requires at
    least ``min_reporting`` members so one slow pair cannot nominate
    each other. Mutates ``flagged`` in place; returns the median used
    (None when below the reporting floor)."""
    if len(samples) < min_reporting:
        return None
    median = statistics.median(samples.values())
    for member, value in samples.items():
        slow = median > 0 and value > ratio * median
        if slow and member not in flagged:
            flagged.add(member)
            record_counter(counter_name, **{id_label: member})
            tracer().event(event_name,
                           **{id_label: member,
                              value_key: round(value, 4),
                              "median_s": round(median, 4),
                              "ratio": ratio})
        elif not slow:
            flagged.discard(member)
    return median


def average_aggregator(updates: Sequence[np.ndarray]) -> np.ndarray:
    """INDArrayAggregator: element-wise mean (parameter averaging)."""
    if not updates:
        raise ValueError("no updates to aggregate")
    return np.mean(np.stack([np.asarray(u) for u in updates]), axis=0)


class WorkRouter:
    """Decides when worker updates become the new global parameters."""

    def __init__(self, tracker: StateTracker,
                 aggregator: Callable[[Sequence[np.ndarray]], np.ndarray]
                 = average_aggregator):
        self.tracker = tracker
        self.aggregator = aggregator
        self.rounds = 0

    def post(self, worker_id: str, update: np.ndarray) -> None:
        raise NotImplementedError

    def step(self, num_workers: int) -> bool:
        """Master tick; True when global params advanced this tick."""
        raise NotImplementedError

    def current_params(self) -> Optional[np.ndarray]:
        got = self.tracker.get_array("global_params")
        return None if got is None else np.asarray(got, np.float32)

    def _publish(self, params: np.ndarray) -> None:
        # binary channel: flat params are MBs — never JSON-encode them
        self.tracker.put_array("global_params", np.asarray(params))
        self.rounds += 1


class IterativeReduceWorkRouter(WorkRouter):
    """Barrier semantics (IterativeReduceWorkRouter.java:48-53): aggregate
    only once EVERY worker has posted, then redistribute. The barrier peeks
    entry KEYS only (no array reads on the poll path) and counts distinct
    workers; consumption is an atomic drain, so updates posted between peek
    and drain — including a second post from a fast worker — are
    aggregated, never dropped."""

    def post(self, worker_id: str, update: np.ndarray) -> None:
        self.tracker.post_update(worker_id, update)

    def step(self, num_workers: int) -> bool:
        keys = self.tracker.posted_update_keys()
        distinct = {self.tracker.update_worker(k) for k in keys}
        if len(distinct) < num_workers:
            return False
        updates = self.tracker.drain_updates()
        if not updates:
            return False
        self._publish(self.aggregator(
            [updates[k] for k in sorted(updates)]))
        return True


class HogwildWorkRouter(WorkRouter):
    """Async semantics (HogWildWorkRouter.java:32): each update folds into
    the global params immediately — no waiting on stragglers. The fold is
    serialized per router instance (in-process workers); cross-process
    Hogwild should give each process its own router over a shared tracker
    and accept last-write races on the published params, as the reference
    does by design."""

    def __init__(self, tracker: StateTracker, mix: float = 0.5, **kw):
        super().__init__(tracker, **kw)
        self.mix = mix  # how far to move toward the incoming update
        self._fold_lock = threading.Lock()

    def post(self, worker_id: str, update: np.ndarray) -> None:
        with self._fold_lock:  # read-modify-write must not drop updates
            cur = self.current_params()
            new = (np.asarray(update, np.float32) if cur is None
                   else (1.0 - self.mix) * cur
                   + self.mix * np.asarray(update, np.float32))
            self._publish(new)

    def step(self, num_workers: int) -> bool:
        return False  # nothing gated on the master


# ---------------------------------------------------------------------------
# the master/worker loop (DeepLearning4jDistributed.train(), in-process)
# ---------------------------------------------------------------------------


class DistributedTrainer:
    """Run jobs through N worker threads under a router's policy.

    In-process stand-in for the actor runtime (MasterActor poll loop
    :106-139 + WorkerActor pool :183-203), testable on one host the way the
    reference's ``BaseTestDistributed`` boots an embedded actor system.
    """

    def __init__(self, tracker: StateTracker, router: WorkRouter,
                 performer_factory: Callable[[], WorkerPerformer],
                 num_workers: int = 2, poll_s: float = 0.01,
                 max_attempts: int = 3, join_timeout_s: float = 60.0,
                 eviction_timeout_s: Optional[float] = None,
                 heartbeat_interval_s: float = 1.0,
                 straggler_ratio: float = 3.0,
                 autopilot=None):
        self.tracker = tracker
        self.router = router
        self.performer_factory = performer_factory
        self.num_workers = num_workers
        self.poll_s = poll_s
        self.max_attempts = max_attempts
        self.join_timeout_s = join_timeout_s
        # MasterActor heartbeat eviction: with a timeout set, the master
        # tick drops workers silent >= timeout and requeues their claimed
        # jobs — a killed worker cannot wedge the run. The timeout must
        # comfortably exceed the beat interval or live workers get evicted
        # on ordinary scheduling jitter and their jobs double-executed.
        if (eviction_timeout_s is not None
                and eviction_timeout_s <= 2 * heartbeat_interval_s):
            raise ValueError(
                f"eviction_timeout_s ({eviction_timeout_s}) must exceed "
                f"2x heartbeat_interval_s ({heartbeat_interval_s}): a "
                f"single missed beat would evict a live worker")
        self.eviction_timeout_s = eviction_timeout_s
        self.heartbeat_interval_s = heartbeat_interval_s
        # fleet view: a worker whose step time exceeds straggler_ratio x
        # the fleet median gets flagged (>=3 reporting workers, so one
        # slow pair can't nominate each other)
        self.straggler_ratio = float(straggler_ratio)
        # the goodput autopilot (observe→act over the fleet gauges this
        # tick aggregates): pass an instance, or set DL4J_AUTOPILOT=1 for
        # the default policy with the trainer's own evict path wired in
        if autopilot is None:
            from deeplearning4j_tpu.resilience.autopilot import (
                GoodputAutopilot, autopilot_enabled)

            if autopilot_enabled():
                # silence threshold = the eviction timeout (one policy,
                # two detectors); 120 s is MasterActor parity when the
                # trainer runs without timeout-based eviction
                autopilot = GoodputAutopilot(
                    silence_s=(eviction_timeout_s
                               if eviction_timeout_s is not None
                               else 120.0))
        self.autopilot = autopilot
        if autopilot is not None:
            autopilot.bind(
                evict=lambda w, d: self.evict_worker(w, decision=d),
                readmit=lambda w, d: self.readmitted.append(w))
        self.performers: List[WorkerPerformer] = []
        self.errors: List[str] = []
        self.evicted: List[str] = []
        self.readmitted: List[str] = []
        self.eviction_log: List[dict] = []  # decisions + their evidence
        self.stragglers: set = set()
        self.monitors: Dict[str, Any] = {}
        # per-worker stop events: a TARGETED eviction (autopilot
        # straggler decision) must stop that worker's loop and beats —
        # otherwise the evicted worker re-registers on its next beat,
        # re-claims its own requeued job, and the fleet flaps
        # evict/readmit forever while the straggler keeps dragging
        self._worker_stops: Dict[str, threading.Event] = {}
        self._stats_lock = threading.Lock()
        self._worker_stats: Dict[str, Dict[str, Any]] = {}
        self._last_fleet_tick = 0.0

    def _worker_loop(self, worker_id: str, performer: WorkerPerformer,
                     stop: threading.Event,
                     worker_stop: Optional[threading.Event] = None
                     ) -> None:
        from deeplearning4j_tpu.parallel.cluster import HeartbeatMonitor

        # beats come from a background monitor thread, NOT the work loop:
        # a long perform() (first-call XLA compile, a big job) must not go
        # silent and get spuriously evicted + double-executed. Only a dead
        # process — which takes its monitor thread with it — stops beating.
        # Each beat carries the worker's compact metrics payload (step
        # time, jobs, last loss, process goodput) for the master's fleet
        # view; payload failures degrade to payload-less liveness.
        monitor = HeartbeatMonitor(
            self.tracker, worker_id,
            interval_s=self.heartbeat_interval_s,
            payload_fn=lambda: self._heartbeat_payload(worker_id)).start()
        self.monitors[worker_id] = monitor
        try:
            self._worker_poll(worker_id, performer, stop, worker_stop)
        finally:
            monitor.stop()

    def _worker_poll(self, worker_id: str, performer: WorkerPerformer,
                     stop: threading.Event,
                     worker_stop: Optional[threading.Event] = None
                     ) -> None:
        while not stop.is_set() and not (worker_stop is not None
                                         and worker_stop.is_set()):
            job = self.tracker.claim_job(worker_id)
            if job is None:
                time.sleep(self.poll_s)
                continue
            try:
                latest = self.router.current_params()
                if latest is not None:
                    performer.update(latest)
                t0 = time.monotonic()
                update = performer.perform(job.payload)
                self._note_step(worker_id, performer,
                                time.monotonic() - t0)
                self.router.post(worker_id, update)
                self.tracker.complete_job(job.job_id)
            except Exception as e:
                # a poison job must not kill the worker pool: bounded
                # requeue, permanent failure after max_attempts, error kept
                # for the master (JobFailed protocol)
                import traceback

                self.errors.append(
                    f"{job.job_id} attempt {job.attempts}: "
                    f"{traceback.format_exc()}")
                requeue = job.attempts < self.max_attempts
                self.tracker.fail_job(job.job_id, requeue=requeue)

    # -- fleet telemetry -------------------------------------------------
    def _note_step(self, worker_id: str, performer: WorkerPerformer,
                   step_s: float) -> None:
        loss = None
        score = getattr(getattr(performer, "network", None), "_score",
                        None)
        if score is not None:
            try:
                loss = float(score)  # control-plane thread, one scalar
            except (TypeError, ValueError):
                loss = None
        with self._stats_lock:
            prev = self._worker_stats.get(worker_id, {})
            self._worker_stats[worker_id] = {
                "step_s": float(step_s),
                "jobs": int(prev.get("jobs", 0)) + 1,
                "last_loss": loss,
            }

    def _heartbeat_payload(self, worker_id: str) -> Optional[dict]:
        with self._stats_lock:
            stats = self._worker_stats.get(worker_id)
            payload = None if stats is None else dict(stats)
        if payload is not None:
            try:
                from deeplearning4j_tpu.monitor.ledger import run_ledger

                payload["goodput_pct"] = run_ledger().last_run_goodput()
            except Exception:  # the beat must post regardless
                pass
        return payload

    def fleet_tick(self) -> Dict[str, dict]:
        """One master-side aggregation pass over the fleet's heartbeat
        payloads: per-worker gauges (step time, goodput, last loss) land
        in the registry, and step-time outliers — more than
        ``straggler_ratio`` x the fleet median, with at least three
        workers reporting — are flagged as stragglers, with the evidence
        (step time, median, ratio) on the timeline. Returns the
        per-worker payload map (tests read it)."""
        fleet: Dict[str, dict] = {}
        reg = metrics()
        for w in self.tracker.workers():
            m = self.tracker.heartbeat_metrics(w)
            if not m:
                continue
            fleet[w] = m
            if isinstance(m.get("step_s"), (int, float)):
                reg.gauge("fleet_worker_step_seconds",
                          "per-worker step time from heartbeat payloads"
                          ).set(float(m["step_s"]), worker=w)
            if isinstance(m.get("goodput_pct"), (int, float)):
                reg.gauge("fleet_worker_goodput_pct",
                          "per-worker run-ledger goodput"
                          ).set(float(m["goodput_pct"]), worker=w)
            if isinstance(m.get("last_loss"), (int, float)):
                reg.gauge("fleet_worker_last_loss",
                          "per-worker last-chunk loss"
                          ).set(float(m["last_loss"]), worker=w)
        steps = {w: float(m["step_s"]) for w, m in fleet.items()
                 if isinstance(m.get("step_s"), (int, float))}
        update_straggler_flags(steps, self.stragglers,
                               self.straggler_ratio, id_label="worker",
                               value_key="step_s",
                               counter_name="fleet_stragglers_total",
                               event_name="fleet.straggler")
        reg.gauge("fleet_workers", "workers with live heartbeats"
                  ).set(float(len(self.tracker.workers())))
        reg.gauge("fleet_stragglers",
                  "workers currently flagged as stragglers"
                  ).set(float(len(self.stragglers)))
        return fleet

    def evict_worker(self, worker_id: str, *, decision=None,
                     reason: str = "autopilot") -> dict:
        """Targeted eviction through the SAME evidence-logged path the
        master tick's stale sweep uses: evidence gathered (beat age +
        last payload), jobs requeued via the tracker, the decision
        appended to ``eviction_log``, counter bumped, ``fleet.evict``
        event on the timeline. The autopilot's evict actuator lands
        here, so an autopilot-directed eviction is indistinguishable in
        the audit trail from a timeout one — except for its recorded
        reason."""
        now = time.time()
        t = self.tracker.last_heartbeat(worker_id)
        evidence = {
            "worker": worker_id,
            "reason": (reason if decision is None
                       else f"autopilot:{decision.reason}"),
            "silent_s": None if t is None else round(now - t, 3),
            "timeout_s": self.eviction_timeout_s,
            "t_wall": now,
            "last_metrics": self.tracker.heartbeat_metrics(worker_id),
        }
        # stop the worker FOR REAL (loop + beats), not just its tracker
        # record: a still-running straggler would re-register on its next
        # beat and re-claim its own requeued job — evict/readmit flap
        wstop = self._worker_stops.get(worker_id)
        if wstop is not None:
            wstop.set()
        monitor = self.monitors.get(worker_id)
        if monitor is not None:
            monitor.stop()
        self.tracker.evict_worker(worker_id)
        self.evicted.append(worker_id)
        self.stragglers.discard(worker_id)
        self.eviction_log.append(evidence)
        record_counter("fleet_evictions_total", worker=worker_id)
        tracer().event("fleet.evict", **{
            k: v for k, v in evidence.items()
            if isinstance(v, (str, int, float, bool))})
        return evidence

    def autopilot_tick(self, fleet: Dict[str, dict]) -> None:
        """Feed the autopilot exactly what this master tick already
        holds: the payload map, the straggler set, and the last-beat
        timestamps. Decisions act through the bound actuators (evict →
        :meth:`evict_worker`); the observe pass itself must never take
        the training loop down."""
        if self.autopilot is None:
            return
        try:
            self.autopilot.observe(
                fleet, stragglers=set(self.stragglers),
                last_beat={w: self.tracker.last_heartbeat(w)
                           for w in self.tracker.workers()})
        except Exception:  # noqa: BLE001 — act layer is best-effort
            logger.exception("autopilot observe pass failed")

    def _evict_tick(self) -> List[str]:
        """Evict stale workers AND record each decision with the
        evidence that justified it — beat age vs timeout plus the last
        metrics payload the dead worker reported — so a postmortem can
        audit why the master dropped someone. Evidence (a second beat
        read + a metrics read) is gathered ONLY for workers already
        past the timeout: the common all-alive tick costs the same one
        read per worker it always did."""
        now = time.time()
        evidence = {}
        for w in self.tracker.workers():
            t = self.tracker.last_heartbeat(w)
            if t is not None and now - t < self.eviction_timeout_s:
                continue  # alive: no evidence needed, no extra I/O
            evidence[w] = {
                "silent_s": None if t is None else round(now - t, 3),
                "last_metrics": self.tracker.heartbeat_metrics(w),
            }
        stale = self.tracker.evict_stale(self.eviction_timeout_s)
        for w in stale:
            decision = {"worker": w,
                        "timeout_s": self.eviction_timeout_s,
                        "t_wall": now, **evidence.get(w, {})}
            self.eviction_log.append(decision)
            record_counter("fleet_evictions_total", worker=w)
            # the tracer event forwards into the flight ring on its own
            # (trace._record) — no explicit flight write, or evictions
            # would double-count in the postmortem tally
            tracer().event("fleet.evict", **decision)
        return stale

    def train(self, timeout_s: float = 120.0,
              raise_on_failed_jobs: bool = True) -> np.ndarray:
        """Drain all pending jobs; returns the final global params."""
        stop = threading.Event()
        self.performers = [self.performer_factory()
                           for _ in range(self.num_workers)]
        self._worker_stops = {f"worker-{i}": threading.Event()
                              for i in range(self.num_workers)}
        threads = [
            threading.Thread(
                target=self._worker_loop,
                args=(f"worker-{i}", p, stop,
                      self._worker_stops[f"worker-{i}"]), daemon=True)
            for i, p in enumerate(self.performers)
        ]
        for t in threads:
            t.start()
        deadline = time.monotonic() + timeout_s
        try:
            while time.monotonic() < deadline:
                self.router.step(self.num_workers)
                # fleet aggregation is throttled to the beat cadence —
                # re-reading every payload each 10 ms poll would hammer a
                # file-backed tracker for data that changes once a beat
                now_mono = time.monotonic()
                if now_mono - self._last_fleet_tick >= max(
                        self.poll_s, self.heartbeat_interval_s):
                    self._last_fleet_tick = now_mono
                    self.autopilot_tick(self.fleet_tick())
                if self.eviction_timeout_s is not None:
                    stale = self._evict_tick()
                    if stale:
                        self.evicted.extend(stale)
                        self.errors.append(
                            f"evicted stale worker(s) {stale}; their "
                            f"claimed jobs were requeued")
                pending = self.tracker.jobs(status="pending")
                claimed = self.tracker.jobs(status="claimed")
                if not pending and not claimed:
                    break
                time.sleep(self.poll_s)
            else:
                raise TimeoutError(
                    "jobs not drained in time"
                    + (f"; worker errors: {self.errors[-1]}"
                       if self.errors else ""))
        finally:
            stop.set()
            # a worker mid-perform (e.g. first-call XLA compile) must land
            # its post before the leftover drain below, or its finished
            # job's training would be lost — wait generously and surface a
            # straggler instead of silently proceeding
            for t in threads:
                t.join(timeout=self.join_timeout_s)
            stragglers = [t.name for t in threads if t.is_alive()]
            if stragglers:
                self.errors.append(
                    f"worker threads still running after "
                    f"{self.join_timeout_s}s: {stragglers}; their updates "
                    f"may be excluded from the returned params")
        params = self.router.current_params()
        # a final partial barrier round (fewer posts than workers) still
        # carries finished jobs' training — fold it in, never discard
        leftover = self.tracker.drain_updates()
        if leftover:
            vals = [leftover[k] for k in sorted(leftover)]
            if params is not None:
                vals.append(params)
            params = self.router.aggregator(vals)
        if raise_on_failed_jobs and self.tracker.jobs(status="failed"):
            raise RuntimeError(
                f"{len(self.tracker.jobs(status='failed'))} job(s) failed "
                f"permanently; last error:\n"
                f"{self.errors[-1] if self.errors else '(none recorded)'}")
        return params
