"""Distributed training over jax.sharding meshes.

This package replaces the ENTIRE ``deeplearning4j-scaleout`` tree (Spark
parameter averaging, Akka actor parameter server, YARN iterative reduce,
Hazelcast state tracking — SURVEY §2.5/§3.3/§3.4) with the TPU-native
model: one jitted SPMD program over a ``jax.sharding.Mesh``, XLA inserting
all-reduce/all-gather collectives over ICI — plus the greenfield
parallelisms the reference never had (tensor parallel, sequence/context
parallel ring attention).

Modes:
- ``ParallelWrapper`` (data_parallel.py) — synchronous DP: batch sharded over
  the ``data`` axis, gradients all-reduced by GSPMD. The drop-in functional
  replacement for SparkDl4jMultiLayer.fitDataSet.
- ``ParameterAveragingTrainer`` (data_parallel.py) — exact parameter-averaging
  semantics (independent local fits, periodic averaging) for parity with the
  reference's Spark/Akka mode, expressed as a vmapped local-SGD step.
- ``TensorParallel`` sharding rules (tensor_parallel.py) — param/activation
  PartitionSpecs over a ``model`` axis.
- ``ring_attention`` (ring_attention.py) — context parallelism over a
  ``sequence`` axis via shard_map + ppermute; composes with sliding-window
  banding (only in-band ring hops run).
- ``ulysses_attention`` (ulysses.py) — the all-to-all flavor of sequence
  parallelism: reshard sequence↔heads, attend locally over the full
  sequence, reshard back. ``TransformerLM(sp_impl="ulysses")`` switches a
  model onto it.
- ``spmd_pipeline`` (pipeline_parallel.py) — GPipe microbatch pipelining over
  a ``pipe`` axis via shard_map + ppermute.
- ``moe_ffn`` (expert_parallel.py) — GShard-style mixture-of-experts with
  expert-axis sharding; dispatch/combine all-to-alls derived by GSPMD.
"""

from deeplearning4j_tpu.parallel.mesh import (  # noqa: F401
    MeshSpec,
    build_mesh,
    local_device_count,
)
from deeplearning4j_tpu.parallel.data_parallel import (  # noqa: F401
    ParallelWrapper,
    ParameterAveragingTrainer,
)
from deeplearning4j_tpu.parallel.pipeline_parallel import (  # noqa: F401
    pipeline_train_step,
    spmd_pipeline,
    split_microbatches,
    stack_stage_params,
    shard_stage_params,
)
from deeplearning4j_tpu.parallel.expert_parallel import (  # noqa: F401
    MoEConfig,
    init_moe_params,
    moe_ffn,
    shard_moe_params,
)
from deeplearning4j_tpu.parallel.ring_attention import (  # noqa: F401
    ring_attention,
    ring_self_attention_sharded,
)
from deeplearning4j_tpu.parallel.ulysses import (  # noqa: F401
    ulysses_attention,
    ulysses_self_attention_sharded,
)
from deeplearning4j_tpu.parallel.fsdp import (  # noqa: F401
    FSDP,
    fsdp_shardings,
    fsdp_spec,
    shard_tree,
)
from deeplearning4j_tpu.parallel.statetracker import (  # noqa: F401
    FileStateTracker,
    InMemoryStateTracker,
    Job,
    StateTracker,
)
from deeplearning4j_tpu.parallel.cluster import (  # noqa: F401
    ClusterConfig,
    FaultTolerantTrainer,
    HeartbeatMonitor,
    initialize_distributed,
)
from deeplearning4j_tpu.parallel.registry import ConfigRegistry  # noqa: F401
from deeplearning4j_tpu.parallel.workrouter import (  # noqa: F401
    DistributedTrainer,
    HogwildWorkRouter,
    IterativeReduceWorkRouter,
    NetworkWorkPerformer,
    WorkRouter,
    WorkerPerformer,
    average_aggregator,
)
