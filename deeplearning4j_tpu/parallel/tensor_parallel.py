"""Tensor parallelism: Megatron-style sharded GEMMs over the ``model`` axis.

Greenfield relative to the reference (SURVEY §2.5: "NOT present in the
reference: tensor/model parallelism"), but required of a modern TPU
framework. Expressed as sharding *rules* over the same network abstraction —
not a separate runtime: params get NamedShardings; GSPMD partitions the
jitted train step and inserts the all-reduces.

Scheme: alternating column/row parallelism for stacked dense-like layers —
layer 2k's W is column-sharded P(None, "model") (output features split, no
communication on the forward GEMM), layer 2k+1's W is row-sharded
P("model", None) (contracting dim split, one psum after) — the classic
two-GEMM pattern that needs a single all-reduce per pair. Recurrent layers
column-shard the gate dimension; embedding tables row-shard the vocab.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deeplearning4j_tpu.nn.conf import layers as L
from deeplearning4j_tpu.parallel.mesh import MODEL_AXIS


def _dense_spec(column: bool) -> Dict[str, P]:
    if column:
        return {"W": P(None, MODEL_AXIS), "b": P(MODEL_AXIS)}
    return {"W": P(MODEL_AXIS, None), "b": P()}


def _lstm_spec() -> Dict[str, P]:
    # gate dim (4n) column-sharded; recurrence contracts the replicated n
    return {"W": P(None, MODEL_AXIS), "RW": P(None, MODEL_AXIS),
            "b": P(MODEL_AXIS), "pI": P(MODEL_AXIS), "pF": P(MODEL_AXIS),
            "pO": P(MODEL_AXIS)}


def param_specs_for_network(conf) -> Dict[str, Any]:
    """PartitionSpec tree matching a MultiLayerConfiguration's param tree."""
    return param_specs_for_layers(
        (str(i), lc) for i, lc in enumerate(conf.layers))


def param_specs_for_layers(items) -> Dict[str, Any]:
    """The Megatron layer rules over any keyed layer-conf sequence —
    MultiLayerNetwork passes indexed layers, the sharding registry passes
    a ComputationGraph's named layers in topological order (so the
    column/row dense alternation follows dataflow)."""
    specs: Dict[str, Any] = {}
    dense_count = 0
    for si, lc in items:
        if isinstance(lc, (L.DenseLayer, L.OutputLayer, L.AutoEncoder)):
            # Output layers stay replicated: their n_out is the class count,
            # usually tiny and followed by a softmax over the full axis.
            if isinstance(lc, L.OutputLayer):
                specs[si] = {k: P() for k in ("W", "b")}
                if isinstance(lc, L.AutoEncoder):
                    specs[si]["vb"] = P()
                continue
            specs[si] = _dense_spec(column=(dense_count % 2 == 0))
            if isinstance(lc, L.AutoEncoder):
                specs[si]["vb"] = P()
            dense_count += 1
        elif isinstance(lc, (L.GravesLSTM, L.LSTM)):
            specs[si] = _lstm_spec()
        elif isinstance(lc, L.GravesBidirectionalLSTM):
            specs[si] = {"fwd": _lstm_spec(), "bwd": _lstm_spec()}
        elif isinstance(lc, L.GRU):
            specs[si] = {"W": P(None, MODEL_AXIS), "RW": P(None, MODEL_AXIS),
                         "b": P(MODEL_AXIS)}
        elif isinstance(lc, L.EmbeddingLayer):
            specs[si] = {"W": P(MODEL_AXIS, None), "b": P()}
        elif isinstance(lc, L.ConvolutionLayer):
            # channels-out sharded: each model shard computes a slice of
            # output feature maps
            specs[si] = {"W": P(None, None, None, MODEL_AXIS), "b": P(MODEL_AXIS)}
        else:
            specs[si] = _replicated_like_layer(lc)
    return specs


def _replicated_like_layer(lc) -> Any:
    return _ReplicateAll()


class _ReplicateAll:
    """Sentinel: replicate every leaf of this layer's params."""


def shard_network_params(network, mesh: Mesh,  # dl4j-lint: disable=adhoc-out-shardings -- sanctioned legacy TP placement builder; the sharding registry (for_network) is the registry-era path
                         specs: Optional[Dict[str, Any]] = None) -> None:
    """device_put the network's params (and mirrored updater state) with
    tensor-parallel NamedShardings. The subsequent jitted train step is then
    partitioned by GSPMD along those shardings."""
    network._ensure_init()
    specs = specs or param_specs_for_network(network.conf)

    def place(tree, spec):
        if isinstance(spec, _ReplicateAll):
            return jax.device_put(tree, NamedSharding(mesh, P()))
        if isinstance(tree, dict):
            return {k: place(v, spec[k] if isinstance(spec, dict) and k in spec else P())
                    for k, v in tree.items()}
        return jax.device_put(tree, NamedSharding(mesh, spec))

    network.params = {
        si: place(sub, specs.get(si, _ReplicateAll()))
        for si, sub in network.params.items()
    }

    def place_state(tree, spec):
        # updater state mirrors param shapes (possibly nested one level for
        # adam {m, v}); shard each leaf like its param
        if isinstance(tree, dict):
            return {k: place_state(v, spec[k] if isinstance(spec, dict) and k in spec else spec)
                    for k, v in tree.items()}
        if tree.ndim == 0 or tree.size == 0:
            return jax.device_put(tree, NamedSharding(mesh, P()))
        if isinstance(spec, (_ReplicateAll,)) or spec is None:
            return jax.device_put(tree, NamedSharding(mesh, P()))
        if len(spec) == tree.ndim:
            return jax.device_put(tree, NamedSharding(mesh, spec))
        return jax.device_put(tree, NamedSharding(mesh, P()))

    network.updater_state = {
        si: place_state(sub, specs.get(si, _ReplicateAll()))
        for si, sub in network.updater_state.items()
    }
    network.net_state = jax.device_put(
        network.net_state, NamedSharding(mesh, P()))
