"""Ulysses sequence parallelism: all-to-all head↔sequence resharding.

The second of the two long-context strategies SURVEY §7.7d calls for
(alongside ``ring_attention``): DeepSpeed-Ulysses-style context parallelism.
Inputs arrive sharded on the SEQUENCE axis ([B, T/P, H, D] per device); an
``all_to_all`` over the sequence axis re-shards to head parallelism
([B, T, H/P, D] — every device sees the FULL sequence for its subset of
heads), plain softmax attention runs locally with no communication inside
the kernel, and a second all-to-all restores sequence sharding. Two
collectives per attention call versus ring attention's P permutes — the
better trade when heads ≥ devices and ICI all-to-all bandwidth is plentiful
(the scaling-book recipe); ring attention wins when T is huge and overlap
matters. Both ride the same mesh axes, so callers can switch per layer.

No counterpart exists in the reference (pre-attention codebase, SURVEY §5
"long-context: absent") — this is greenfield capability the TPU build is
required to provide.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from deeplearning4j_tpu.compat import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from deeplearning4j_tpu.ops.attention import NEG_INF, causal_band_mask
from deeplearning4j_tpu.parallel.mesh import SEQUENCE_AXIS


def _local_attention(q, k, v, *, causal: bool, t_offset_q=0, window=None):
    """Plain softmax attention on full-sequence blocks [B, T, h, D].
    ``window`` (requires causal) keeps k in ``(q - window, q]`` via the
    shared ``ops.attention.causal_band_mask``."""
    scale = 1.0 / jnp.sqrt(q.shape[-1]).astype(jnp.float32)
    qf = q.astype(jnp.float32)
    kf = k.astype(jnp.float32)
    logits = jnp.einsum("bqhd,bkhd->bhqk", qf, kf) * scale
    if causal:
        mask = causal_band_mask(q.shape[1], k.shape[1], window=window,
                                q_offset=t_offset_q)
        logits = jnp.where(mask[None, None], logits, NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", probs, v.astype(jnp.float32))
    return out.astype(q.dtype)


def ulysses_attention(q, k, v, mesh: Mesh, causal: bool = False,
                      axis_name: str = SEQUENCE_AXIS, window=None):
    """Self-attention over sequence-sharded [B, T, H, D] inputs.

    ``H`` must be divisible by the sequence-axis size (each device owns
    H/P heads during the compute phase). ``window`` (requires causal)
    applies sliding-window masking inside the local full-sequence
    attention — the all-to-alls are unchanged.
    """
    if window is not None and (not causal or window < 1):
        raise ValueError("window requires causal=True and window >= 1")
    if axis_name not in mesh.shape or mesh.shape[axis_name] == 1:
        return _local_attention(q, k, v, causal=causal, window=window)
    n_seq = mesh.shape[axis_name]
    if q.shape[2] % n_seq:
        raise ValueError(
            f"num_heads {q.shape[2]} not divisible by sequence-parallel "
            f"degree {n_seq}")

    def body(q_blk, k_blk, v_blk):
        # [B, T/P, H, D] → all-to-all → [B, T, H/P, D]: split the head
        # axis across devices, concatenate the sequence axis
        def seq_to_head(x):
            return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                                  tiled=True)

        def head_to_seq(x):
            return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                                  tiled=True)

        qh = seq_to_head(q_blk)
        kh = seq_to_head(k_blk)
        vh = seq_to_head(v_blk)
        out = _local_attention(qh, kh, vh, causal=causal, window=window)
        return head_to_seq(out)

    spec = P(None, axis_name, None, None)
    return shard_map(body, mesh=mesh, in_specs=(spec, spec, spec),
                     out_specs=spec, check_vma=False)(q, k, v)


def ulysses_self_attention_sharded(mesh: Mesh):
    """Convenience: jitted fn(q, k, v, causal) bound to ``mesh`` (mirrors
    ``ring_self_attention_sharded``)."""

    @functools.partial(jax.jit, static_argnames=("causal",))
    def fn(q, k, v, causal=False):
        return ulysses_attention(q, k, v, mesh, causal=causal)

    return fn
