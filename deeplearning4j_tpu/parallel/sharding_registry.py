"""One mesh for everything: the DP×TP×PP sharding-spec registry.

Before this module, every parallel wrapper carried its own ad-hoc
``NamedSharding``/``out_shardings`` call sites — the epoch cache placed
batches one way, ``ParallelWrapper`` pinned program outputs another,
``tensor_parallel``/``fsdp`` each invented their own placement walk, and
the serving engine sharded over nothing. This module is the single point
of truth GSPMD (arXiv 2105.04663) asks for: ONE named mesh over the
``data`` × ``model`` × ``pipe`` axes (``parallel/mesh.py`` names), and
ONE per-model registry mapping every parameter, updater-state, and
activation leaf to a ``PartitionSpec``. Training (`fit_epochs`), the
DP/FSDP wrapper, elastic topology reshard (arXiv 2112.01075 — a full
host tensor lands on ANY topology, so 8×1 → 4×2 is a device_put with
the new mesh's specs), and the serving decode engine all consume the
SAME specs, so a model's placement story is written exactly once.

Registry contract (the "no silent replication" rule): every leaf of the
model's param tree MUST be covered by an explicit spec — a ``P()``
(replicate, on purpose) or a sharded spec. An unmapped leaf raises
:class:`UnmappedLeafError` at registry construction instead of silently
falling back to replicated, because a silently-replicated large leaf is
an HBM regression nobody sees until a model stops fitting.

Lint: dl4j-lint rule 9 (``adhoc-out-shardings``) flags ``NamedSharding(``
construction and ``out_shardings=`` keywords OUTSIDE this module; the
handful of sanctioned low-level builders (``mesh.py``, ``fsdp.py``, ...)
carry per-site suppressions with reasons, and everything else routes
through :func:`named` / the registry API.

Env knobs (resolved by :func:`mesh_from_env`):

- ``DL4J_MESH_SHAPE`` — ``"8x1"`` / ``"4x2"`` / ``"2x2x2"`` as
  data×model[×pipe]; the full-topology override.
- ``DL4J_TP_SHARDS`` — just the ``model`` axis size; ``data`` takes the
  remaining devices (``MeshSpec(data=-1, model=N)``).
"""

from __future__ import annotations

import logging
import os
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deeplearning4j_tpu.parallel.mesh import (
    DATA_AXIS,
    MODEL_AXIS,
    PIPE_AXIS,
    MeshSpec,
    build_mesh,
)

logger = logging.getLogger(__name__)

__all__ = [
    "UnmappedLeafError",
    "ShardingRegistry",
    "named",
    "replicated_sharding",
    "batch_spec",
    "batch_sharding",
    "stage_spec",
    "model_axis_size",
    "pipe_axis_size",
    "parse_mesh_shape",
    "mesh_from_env",
]


class UnmappedLeafError(KeyError):
    """A param/updater leaf has no PartitionSpec in the registry — the
    registry refuses to guess (silent replication is an HBM regression,
    silent sharding a numerics one)."""


# ---------------------------------------------------------------------------
# sanctioned sharding builders — the ONE module where NamedSharding is
# constructed for model/batch placement (dl4j-lint rule 9 exempts this file)
# ---------------------------------------------------------------------------
def named(mesh: Mesh, spec: P) -> NamedSharding:
    """THE sanctioned ``NamedSharding`` constructor: modules that need a
    concrete sharding build it here so rule 9 keeps ad-hoc construction
    out of the rest of the tree."""
    return NamedSharding(mesh, spec)


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    """Fully-replicated placement on ``mesh``."""
    return named(mesh, P())


def batch_spec(ndim: int, *, stacked: bool = False,
               axis: str = DATA_AXIS) -> P:
    """The activation/batch PartitionSpec: batch dim over ``data``,
    everything else replicated. ``stacked=True`` is the epoch cache's
    ``[N, B, ...]`` layout (N batches resident; the BATCH dim is axis 1)."""
    if stacked:
        return P(None, axis, *([None] * max(0, ndim - 2)))
    return P(axis, *([None] * max(0, ndim - 1)))


def batch_sharding(mesh: Mesh, ndim: int, *, stacked: bool = False,
                   axis: str = DATA_AXIS) -> NamedSharding:
    return named(mesh, batch_spec(ndim, stacked=stacked, axis=axis))


def stage_spec(ndim: int, *, axis: str = PIPE_AXIS) -> P:
    """Stacked pipeline-stage params ``[S, ...]``: leading stage axis over
    ``pipe`` (the layout ``pipeline_parallel.spmd_pipeline`` consumes)."""
    return P(axis, *([None] * max(0, ndim - 1)))


def model_axis_size(mesh: Optional[Mesh]) -> int:
    """Size of the ``model`` (tensor-parallel) axis; 1 when absent."""
    if mesh is None:
        return 1
    return int(mesh.shape.get(MODEL_AXIS, 1))


def pipe_axis_size(mesh: Optional[Mesh]) -> int:
    """Size of the ``pipe`` (pipeline) axis; 1 when absent."""
    if mesh is None:
        return 1
    return int(mesh.shape.get(PIPE_AXIS, 1))


# ---------------------------------------------------------------------------
# env-driven mesh resolution
# ---------------------------------------------------------------------------
def parse_mesh_shape(text: str) -> MeshSpec:
    """``"8x1"`` / ``"4x2"`` / ``"2x2x2"`` → MeshSpec(data, model[, pipe]).
    One value means pure DP; a fourth value is rejected (the registry
    axes are data×model×pipe)."""
    parts = [p.strip() for p in str(text).lower().split("x") if p.strip()]
    if not 1 <= len(parts) <= 3:
        raise ValueError(
            f"DL4J_MESH_SHAPE={text!r} must be DPxTP or DPxTPxPP "
            "(e.g. '8x1', '4x2', '2x2x2')")
    try:
        dims = [int(p) for p in parts]
    except ValueError:
        raise ValueError(
            f"DL4J_MESH_SHAPE={text!r}: non-integer mesh dimension")
    if any(d < 1 for d in dims):
        raise ValueError(f"DL4J_MESH_SHAPE={text!r}: dims must be >= 1")
    dims += [1] * (3 - len(dims))
    return MeshSpec(data=dims[0], model=dims[1], pipe=dims[2])


def mesh_from_env(devices: Optional[Sequence] = None) -> Optional[Mesh]:
    """Resolve ``DL4J_MESH_SHAPE`` (full topology, wins) then
    ``DL4J_TP_SHARDS`` (model axis only, data takes the rest) into a
    built mesh; ``None`` when neither is set."""
    shape = os.environ.get("DL4J_MESH_SHAPE", "").strip()
    if shape:
        return build_mesh(parse_mesh_shape(shape), devices=devices)
    tp = os.environ.get("DL4J_TP_SHARDS", "").strip()
    if tp:
        n = int(tp)
        if n < 1:
            raise ValueError(f"DL4J_TP_SHARDS={tp!r} must be >= 1")
        return build_mesh(MeshSpec(data=-1, model=n), devices=devices)
    return None


# ---------------------------------------------------------------------------
# strict spec-tree expansion
# ---------------------------------------------------------------------------
def _is_leaf(x) -> bool:
    return not isinstance(x, (dict, list, tuple))


def _expand(tree, spec, path: Tuple[Any, ...], name: str):
    """Expand a (possibly sentinel-bearing) spec tree against the model's
    actual param tree, leaf for leaf. Structure mismatches and missing
    keys raise :class:`UnmappedLeafError` naming the leaf path."""
    from deeplearning4j_tpu.parallel.tensor_parallel import _ReplicateAll

    if isinstance(spec, _ReplicateAll):
        # explicit whole-subtree replicate declaration — expand to P()
        # per leaf so lookups stay total
        if isinstance(tree, dict):
            return {k: _expand(v, spec, path + (k,), name)
                    for k, v in tree.items()}
        if isinstance(tree, (list, tuple)):
            return [_expand(v, spec, path + (i,), name)
                    for i, v in enumerate(tree)]
        return P()
    if isinstance(tree, dict):
        if not isinstance(spec, dict):
            raise UnmappedLeafError(
                f"registry[{name}]: param subtree at {path!r} is a dict "
                f"but its spec is {type(spec).__name__}")
        out = {}
        for k, v in tree.items():
            if k not in spec:
                raise UnmappedLeafError(
                    f"registry[{name}]: no PartitionSpec for param leaf "
                    f"{path + (k,)!r} — every leaf needs an explicit "
                    "spec (P() to replicate on purpose)")
            out[k] = _expand(v, spec[k], path + (k,), name)
        return out
    if isinstance(tree, (list, tuple)):
        if not isinstance(spec, (list, tuple)) or len(spec) != len(tree):
            raise UnmappedLeafError(
                f"registry[{name}]: param list at {path!r} has "
                f"{len(tree)} entries but the spec does not match")
        return [_expand(v, s, path + (i,), name)
                for i, (v, s) in enumerate(zip(tree, spec))]
    if not isinstance(spec, P):
        raise UnmappedLeafError(
            f"registry[{name}]: spec for leaf {path!r} is "
            f"{type(spec).__name__}, expected PartitionSpec")
    return spec


def _replicate_all_tree(tree):
    """Explicit replicate-everything spec tree matching ``tree``."""
    if isinstance(tree, dict):
        return {k: _replicate_all_tree(v) for k, v in tree.items()}
    if isinstance(tree, (list, tuple)):
        return [_replicate_all_tree(v) for v in tree]
    return P()


def _divisible_or_replicated(tree, spec, mesh, name, path=()):
    """Demote specs whose sharded dimension does not tile the mesh axis
    to an explicit P() — LOUDLY (a warning naming the leaf), never
    silently: uneven sharding is unsupported by device_put, and an
    in-dim split that does not divide would be numerically wrong anyway
    (the GQA wk/wv fallback generalized to every leaf)."""
    if isinstance(tree, dict):
        return {k: _divisible_or_replicated(v, spec[k], mesh, name,
                                            path + (k,))
                for k, v in tree.items()}
    if isinstance(tree, (list, tuple)):
        return [_divisible_or_replicated(v, s, mesh, name, path + (i,))
                for i, (v, s) in enumerate(zip(tree, spec))]
    shape = getattr(tree, "shape", None)
    if shape is None or spec == P():
        return spec
    if len(spec) > len(shape):
        logger.warning(
            "registry[%s]: spec %s for leaf %r has more entries than its "
            "rank %d — replicating", name, spec, path, len(shape))
        return P()
    for i, entry in enumerate(spec):
        if entry is None:
            continue
        axes = entry if isinstance(entry, (tuple, list)) else (entry,)
        n = 1
        for ax in axes:
            n *= int(mesh.shape.get(ax, 1))
        if n > 1 and shape[i] % n:
            logger.warning(
                "registry[%s]: leaf %r dim %d (size %d) does not tile "
                "mesh axes %r (size %d) — replicating this leaf",
                name, path, i, shape[i], axes, n)
            return P()
    return spec


class ShardingRegistry:
    """Per-model mapping of every param/updater/activation leaf to a
    PartitionSpec on one named mesh.

    Construction goes through the classmethods — ``for_network`` (MLN and
    ComputationGraph, reusing ``tensor_parallel``'s Megatron-style layer
    rules when the mesh carries a ``model`` axis) and ``for_transformer``
    (``TransformerLM.param_specs``). Both expand the spec tree strictly
    against the model's live param tree: every leaf covered, unmapped
    leaves raise. The registry then answers every placement question the
    framework asks — param/updater shardings (``place_network``), batch
    placement (``batch_sharding``), fused-program ``out_shardings``
    (``epoch_out_shardings``), serving KV-pool specs
    (``kv_pool_spec``/``kv_scale_spec``), and the collective-axis
    declaration the contract checker enforces (``declared_axes``).
    """

    def __init__(self, mesh: Mesh, spec_tree, *, name: str = "model"):
        self.mesh = mesh
        self.name = name
        self.spec_tree = spec_tree

    # ------------------------------------------------------------------
    # constructors
    # ------------------------------------------------------------------
    @classmethod
    def for_network(cls, net, mesh: Mesh) -> "ShardingRegistry":
        """Registry for a MultiLayerNetwork or ComputationGraph: TP layer
        specs over ``model`` when the mesh carries that axis (>1), else
        explicit replicate-all. Strict against ``net.params``."""
        net._ensure_init()
        name = type(net).__name__
        if model_axis_size(mesh) > 1:
            raw = _network_specs(net)
        else:
            raw = _replicate_all_tree(net.params)
        expanded = _expand(net.params, raw, (), name)
        return cls(mesh,
                   _divisible_or_replicated(net.params, expanded, mesh,
                                            name),
                   name=name)

    @classmethod
    def for_transformer(cls, lm, mesh: Mesh, *,
                        shard_data_embed: bool = False) -> "ShardingRegistry":
        """Registry for a TransformerLM: the model's own Megatron
        ``param_specs`` over ``model`` when present, else replicate-all."""
        lm._ensure_init()
        if model_axis_size(mesh) > 1:
            raw = lm.param_specs(mesh=mesh,
                                 shard_data_embed=shard_data_embed)
        else:
            raw = _replicate_all_tree(lm.params)
        expanded = _expand(lm.params, raw, (), "TransformerLM")
        return cls(mesh,
                   _divisible_or_replicated(lm.params, expanded, mesh,
                                            "TransformerLM"),
                   name="TransformerLM")

    @classmethod
    def for_embedding_tables(cls, tables: Dict[str, Any], mesh: Mesh, *,
                             row_shard: bool = False,
                             name: str = "Word2Vec") -> "ShardingRegistry":
        """Registry for sparse embedding tables (word2vec's syn0/syn1neg,
        GloVe's w/wc): ``row_shard=True`` splits the VOCAB dim over
        ``model`` — ``P('model', None)``, the layout GSPMD partitions the
        fused skip-gram program's gathers/scatters around once a table
        outgrows one chip — else explicit replicate-all (the DP path:
        every device carries the tables, deltas all-reduce over
        ``data``). Same strictness as the network constructors: uneven
        vocab demotes LOUDLY via ``_divisible_or_replicated``."""
        if row_shard and model_axis_size(mesh) > 1:
            raw = {k: P(MODEL_AXIS, None) for k in tables}
        else:
            raw = _replicate_all_tree(tables)
        expanded = _expand(tables, raw, (), name)
        return cls(mesh,
                   _divisible_or_replicated(tables, expanded, mesh, name),
                   name=name)

    # ------------------------------------------------------------------
    # lookup
    # ------------------------------------------------------------------
    def spec_for(self, *path) -> P:
        """Strict leaf lookup by path (e.g. ``spec_for("0", "W")``)."""
        node = self.spec_tree
        for i, key in enumerate(path):
            try:
                node = node[key]
            except (KeyError, IndexError, TypeError):
                raise UnmappedLeafError(
                    f"registry[{self.name}]: no PartitionSpec at "
                    f"{tuple(path[:i + 1])!r}")
        if not isinstance(node, P):
            raise UnmappedLeafError(
                f"registry[{self.name}]: {tuple(path)!r} names a subtree, "
                "not a leaf")
        return node

    def leaf_specs(self, tree) -> List[P]:
        """Flat specs aligned with ``tree_flatten(tree)`` order; strict —
        a tree with leaves the registry does not cover raises."""
        flat, treedef = jax.tree_util.tree_flatten(tree)
        try:
            flat_spec = treedef.flatten_up_to(self.spec_tree)
        except (ValueError, KeyError, TypeError) as e:
            raise UnmappedLeafError(
                f"registry[{self.name}]: param tree does not match the "
                f"registered spec tree ({e})")
        for s in flat_spec:
            if not isinstance(s, P):
                raise UnmappedLeafError(
                    f"registry[{self.name}]: non-PartitionSpec entry "
                    f"{s!r} in expanded specs")
        return flat_spec

    def param_shardings(self, tree):
        """Pytree of NamedShardings matching ``tree``'s structure — what
        a jit's ``out_shardings`` pin or a placement walk consumes."""
        flat, treedef = jax.tree_util.tree_flatten(tree)
        specs = self.leaf_specs(tree)
        return jax.tree_util.tree_unflatten(
            treedef, [named(self.mesh, s) for s in specs])

    # ------------------------------------------------------------------
    # placement
    # ------------------------------------------------------------------
    def place(self, tree):
        """device_put every param leaf under its registered spec."""
        flat, treedef = jax.tree_util.tree_flatten(tree)
        specs = self.leaf_specs(tree)
        return jax.tree_util.tree_unflatten(treedef, [
            jax.device_put(x, named(self.mesh, s))
            for x, s in zip(flat, specs)
        ])

    def state_shardings(self, state_tree):
        """NamedShardings for an updater/optimizer-state tree that NESTS
        (possibly zero or one level of dict, e.g. adam ``{m, v}``) below
        the param leaves. A state leaf inherits its param's spec when the
        ranks agree (the PR-14 rule tensor_parallel proved out); scalars,
        empties, and rank-mismatched leaves replicate."""
        return self._walk_state(state_tree, self.spec_tree, ())

    def _walk_state(self, tree, spec, path):
        if isinstance(tree, dict):
            out = {}
            for k, v in tree.items():
                sub = spec[k] if isinstance(spec, dict) and k in spec else spec
                if isinstance(spec, dict) and k not in spec and isinstance(v, (dict, list, tuple)):
                    raise UnmappedLeafError(
                        f"registry[{self.name}]: updater subtree at "
                        f"{path + (k,)!r} has no matching param spec")
                out[k] = self._walk_state(v, sub, path + (k,))
            return out
        if isinstance(tree, (list, tuple)):
            subs = (spec if isinstance(spec, (list, tuple))
                    and len(spec) == len(tree) else [spec] * len(tree))
            return [self._walk_state(v, s, path + (i,))
                    for i, (v, s) in enumerate(zip(tree, subs))]
        nd = getattr(tree, "ndim", None)
        size = getattr(tree, "size", None)
        if (nd in (None, 0) or size == 0 or not isinstance(spec, P)
                or len(spec) != nd):
            return named(self.mesh, P())
        return named(self.mesh, spec)

    def place_state(self, state_tree):
        """device_put an updater/optimizer-state tree mirroring params."""
        sh = self.state_shardings(state_tree)
        return jax.tree_util.tree_map(
            jax.device_put, state_tree, sh,
            is_leaf=lambda x: x is None)

    def place_network(self, net) -> "ShardingRegistry":
        """Place a network's full trainable state — params under the
        registered specs, updater state mirrored leaf-for-leaf, net state
        replicated — and stamp the registry on the network for the
        contract checker (``net._sharding_registry``)."""
        net.params = self.place(net.params)
        net.updater_state = self.place_state(net.updater_state)
        net.net_state = jax.device_put(net.net_state,
                                       replicated_sharding(self.mesh))
        net._sharding_registry = self
        return self

    def with_fsdp(self, params) -> "ShardingRegistry":
        """Compose FSDP (arXiv 2004.13336 weight-update sharding over
        ``data``) with the registered TP specs: leaves the registry
        replicates get their largest data-divisible dim sharded over
        ``data``; TP-sharded leaves keep their TP spec (sharding the
        same leaf over both axes would need a spec merge GSPMD cannot
        always honor — the composition stays memory-dominant either
        way)."""
        from deeplearning4j_tpu.parallel.fsdp import fsdp_spec

        flat, treedef = jax.tree_util.tree_flatten(params)
        specs = self.leaf_specs(params)
        composed = [
            fsdp_spec(x.shape, self.mesh) if s == P() else s
            for x, s in zip(flat, specs)
        ]
        return ShardingRegistry(
            self.mesh, jax.tree_util.tree_unflatten(treedef, composed),
            name=self.name + "+fsdp")

    # ------------------------------------------------------------------
    # activations / datasets / programs
    # ------------------------------------------------------------------
    def batch_sharding(self, ndim: int, *,
                       stacked: bool = False) -> NamedSharding:
        """Activation/batch placement: batch dim over ``data``."""
        return batch_sharding(self.mesh, ndim, stacked=stacked)

    def epoch_out_shardings(self, params_tree, state_tree, *,
                            guard: bool = False, metrics_stride: int = 0):
        """``out_shardings`` tuple for the fused epoch program: params
        and updater state pinned to their registered specs (donated
        buffers keep their layout across chunks), net state and the
        loss/trip/metrics histories replicated."""
        repl = replicated_sharding(self.mesh)
        out = (self.param_shardings(params_tree),
               self.state_shardings(state_tree), repl, repl)
        if guard:
            out = out + (repl,)
        if metrics_stride:
            out = out + (repl,)
        return out

    # ------------------------------------------------------------------
    # serving: the KV slot pool shares the model's mesh + specs
    # ------------------------------------------------------------------
    def kv_pool_spec(self, n_kv_heads: int) -> P:
        """Spec for a ``[L, S, T_max, Hkv, Dh]`` K/V pool: heads tile the
        ``model`` axis (the same Megatron head split the attention params
        use), so each TP shard holds ``Hkv/tp`` heads of every slot and
        the pool budget becomes per-shard. Falls back to replicated —
        loudly — when the kv heads do not tile the axis (the GQA
        fallback ``TransformerLM.param_specs`` mirrors: wk/wv replicate
        too, so the pool layout always matches what the projections
        emit)."""
        tp = model_axis_size(self.mesh)
        if tp > 1 and n_kv_heads % tp == 0:
            return P(None, None, None, MODEL_AXIS, None)
        if tp > 1:
            logger.warning(
                "KV pool TP fallback: %d kv heads do not tile the model "
                "axis (size %d) — pool stays replicated", n_kv_heads, tp)
        return P()

    def kv_scale_spec(self, n_kv_heads: int) -> P:
        """int8 scale sidecar ``[L, S, Hkv]``: same head split."""
        pool = self.kv_pool_spec(n_kv_heads)
        if pool == P():
            return P()
        return P(None, None, MODEL_AXIS)

    # ------------------------------------------------------------------
    # contracts
    # ------------------------------------------------------------------
    @property
    def declared_axes(self) -> set:
        """Mesh axes this registry maps anything over — the ONLY axes a
        collective in this model's programs may reduce/permute over
        (``analysis/contracts.check_network_contracts`` enforces it).
        ``data`` is always declared (batch sharding is part of the
        registry's activation mapping); ``pipe`` is declared when the
        mesh carries it (stage params ride ``stage_spec``)."""
        axes = {DATA_AXIS}
        for s in jax.tree_util.tree_leaves(
                self.spec_tree,
                is_leaf=lambda x: isinstance(x, P)):
            if isinstance(s, P):
                for entry in s:
                    if entry is None:
                        continue
                    if isinstance(entry, (tuple, list)):
                        axes.update(entry)
                    else:
                        axes.add(entry)
        if pipe_axis_size(self.mesh) > 1:
            axes.add(PIPE_AXIS)
        return axes & set(self.mesh.axis_names) | {DATA_AXIS}

    def describe(self) -> Dict[str, Any]:
        """Artifact-ready summary (bench mesh_sweep embeds it)."""
        n_sharded = 0
        n_total = 0
        for s in jax.tree_util.tree_leaves(
                self.spec_tree, is_leaf=lambda x: isinstance(x, P)):
            if isinstance(s, P):
                n_total += 1
                if s != P():
                    n_sharded += 1
        return {
            "model": self.name,
            "mesh": {k: int(v) for k, v in self.mesh.shape.items()},
            "declared_axes": sorted(self.declared_axes),
            "leaves": n_total,
            "sharded_leaves": n_sharded,
        }


def _network_specs(net):
    """TP spec tree for either network class, via tensor_parallel's
    Megatron layer rules. MLN's layers come indexed off the list conf;
    the graph's come named, walked in topological order so the
    column/row dense alternation follows dataflow."""
    from deeplearning4j_tpu.parallel.tensor_parallel import (
        param_specs_for_layers,
        param_specs_for_network,
    )

    conf = net.conf
    layers = getattr(conf, "layers", None)
    if isinstance(layers, dict):  # ComputationGraph: {name: LayerConf}
        order = [n for n in conf.topological_order if n in layers]
        order += [n for n in layers if n not in order]
        return param_specs_for_layers([(n, layers[n]) for n in order])
    return param_specs_for_network(conf)
