"""Mesh construction helpers.

Axis-name conventions used across the framework (the scaling-book
vocabulary):
- ``data``     — batch (data parallel; gradient all-reduce rides ICI)
- ``model``    — tensor parallel (sharded GEMMs)
- ``sequence`` — context parallel (ring attention)
- ``pipe``     — pipeline stages
- ``expert``   — expert parallel (MoE)
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence, Tuple

import numpy as np
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
MODEL_AXIS = "model"
SEQUENCE_AXIS = "sequence"
PIPE_AXIS = "pipe"
EXPERT_AXIS = "expert"


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    """Declarative mesh shape; -1 means 'all remaining devices'."""

    data: int = -1
    model: int = 1
    sequence: int = 1
    pipe: int = 1
    expert: int = 1

    def resolve(self, n_devices: int) -> Tuple[Tuple[str, int], ...]:
        fixed = {
            MODEL_AXIS: self.model,
            SEQUENCE_AXIS: self.sequence,
            PIPE_AXIS: self.pipe,
            EXPERT_AXIS: self.expert,
        }
        known = 1
        for v in fixed.values():
            known *= v
        data = self.data
        if data == -1:
            if n_devices % known:
                raise ValueError(
                    f"{n_devices} devices not divisible by fixed axes product {known}")
            data = n_devices // known
        total = data * known
        if total != n_devices:
            raise ValueError(
                f"mesh {data}x{known} != device count {n_devices}")
        axes = [(DATA_AXIS, data)]
        for name, size in fixed.items():
            if size > 1:
                axes.append((name, size))
        return tuple(axes)


def local_device_count() -> int:
    return len(jax.devices())


def data_axis_size(mesh: Optional[Mesh]) -> int:
    """Size of the ``data`` axis of ``mesh`` (1 when mesh is None or the
    axis was dropped) — the data-parallel shard count a batch splits
    into. Shared by the epoch cache's per-shard budget accounting and
    the DP wrappers."""
    if mesh is None:
        return 1
    return int(mesh.shape.get(DATA_AXIS, 1))


def build_mesh(
    spec: Optional[MeshSpec] = None,
    devices: Optional[Sequence] = None,
) -> Mesh:
    """Build a Mesh over the given (default: all) devices.

    Axes of size 1 are dropped, so a pure-DP mesh is 1-D ("data",) and a
    DP×TP mesh is 2-D ("data", "model"). Device order follows
    ``jax.devices()``, which on TPU enumerates chips so that adjacent ids
    share ICI links — keeping the innermost mesh axis on the fastest
    interconnect, per the GSPMD model.
    """
    spec = spec or MeshSpec()
    devices = list(devices if devices is not None else jax.devices())
    axes = spec.resolve(len(devices))
    names = tuple(n for n, _ in axes)
    sizes = tuple(s for _, s in axes)
    dev_array = np.asarray(devices).reshape(sizes)
    return Mesh(dev_array, names)


def replicated(mesh: Mesh) -> NamedSharding:  # dl4j-lint: disable=adhoc-out-shardings -- mesh-level primitive the sharding registry composes
    return NamedSharding(mesh, P())


def batch_sharding(mesh: Mesh, ndim: int = 2,  # dl4j-lint: disable=adhoc-out-shardings -- mesh-level primitive the sharding registry composes
                   axis: str = DATA_AXIS) -> NamedSharding:
    """Shard axis 0 (batch) over ``axis``; replicate the rest."""
    return NamedSharding(mesh, P(axis, *([None] * (ndim - 1))))


def shard_leading_axis(tree, mesh: Mesh, axis_name: str):  # dl4j-lint: disable=adhoc-out-shardings -- mesh-level primitive the sharding registry composes (stage_spec)
    """device_put every leaf with its leading dim sharded over ``axis_name``
    (replicated everywhere else). When the axis was dropped from the mesh
    (size 1), leaves are fully replicated."""
    def put(leaf):
        if axis_name not in mesh.shape:
            return jax.device_put(leaf, NamedSharding(mesh, P()))
        spec = P(axis_name, *([None] * (leaf.ndim - 1)))
        return jax.device_put(leaf, NamedSharding(mesh, spec))
    return jax.tree.map(put, tree)
