"""ctypes bindings for the C++ host runtime (native/dl4j_host.cpp).

The reference's native layer was external C++ (libnd4j BLAS/CUDA + Canova
ETL, SURVEY §0/§2.2). Here the *compute* native layer is XLA/PJRT (bundled
with JAX); this module is the native *host* layer: record parsing and
read-ahead streaming off the Python heap.

The shared library is compiled on first use with g++ (no pybind11 in the
image; plain C ABI + ctypes) and cached next to this file. Every entry
point has a pure-Python fallback — ``is_available()`` is advisory, and
callers degrade gracefully when the toolchain is missing.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional, Tuple

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(os.path.dirname(os.path.dirname(_HERE)),
                    "native", "dl4j_host.cpp")
_SO = os.path.join(_HERE, "_dl4j_host.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_load_failed = False


def _build() -> bool:
    # compile to a private temp path, then atomically publish: concurrent
    # processes (multi-host launcher workers) must never dlopen a torn .so
    tmp = f"{_SO}.build-{os.getpid()}"
    cmd = ["g++", "-O3", "-std=c++17", "-shared", "-fPIC", "-pthread",
           _SRC, "-o", tmp]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=300)
        os.replace(tmp, _SO)
        return True
    except (subprocess.SubprocessError, FileNotFoundError, OSError):
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return False


def _bind(lib: ctypes.CDLL) -> ctypes.CDLL:
    c_p, c_i64, c_i32 = ctypes.c_void_p, ctypes.c_int64, ctypes.c_int
    lib.dl4j_buf_data.restype = ctypes.POINTER(ctypes.c_float)
    lib.dl4j_buf_data.argtypes = [c_p]
    lib.dl4j_buf_size.restype = c_i64
    lib.dl4j_buf_size.argtypes = [c_p]
    lib.dl4j_buf_ndim.restype = c_i32
    lib.dl4j_buf_ndim.argtypes = [c_p]
    lib.dl4j_buf_dims.restype = None
    lib.dl4j_buf_dims.argtypes = [c_p, ctypes.POINTER(c_i64)]
    lib.dl4j_buf_free.restype = None
    lib.dl4j_buf_free.argtypes = [c_p]
    lib.dl4j_csv_parse.restype = c_p
    lib.dl4j_csv_parse.argtypes = [ctypes.c_char_p, ctypes.c_char, c_i64]
    lib.dl4j_svmlight_parse.restype = c_p
    lib.dl4j_svmlight_parse.argtypes = [ctypes.c_char_p, c_i64, c_i32]
    lib.dl4j_idx_parse.restype = c_p
    lib.dl4j_idx_parse.argtypes = [ctypes.c_char_p]
    lib.dl4j_stream_open.restype = c_p
    lib.dl4j_stream_open.argtypes = [ctypes.c_char_p, c_i64, c_i64]
    lib.dl4j_stream_next.restype = c_i64
    lib.dl4j_stream_next.argtypes = [c_p, ctypes.c_char_p]
    lib.dl4j_stream_close.restype = None
    lib.dl4j_stream_close.argtypes = [c_p]
    return lib


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _load_failed
    if _lib is not None or _load_failed:
        return _lib
    with _lock:
        if _lib is not None or _load_failed:
            return _lib
        if not os.path.exists(_SO) or (
                os.path.exists(_SRC)
                and os.path.getmtime(_SRC) > os.path.getmtime(_SO)):
            if not os.path.exists(_SRC) or not _build():
                _load_failed = True
                return None
        try:
            _lib = _bind(ctypes.CDLL(_SO))
        except OSError:
            _load_failed = True
            return None
        return _lib


def is_available() -> bool:
    return _load() is not None


def _buf_to_flat(lib, handle) -> Tuple[np.ndarray, Tuple[int, ...]]:
    """Copy a native buffer out as (flat float32 array, header dims).
    The flat size may exceed prod(dims) — e.g. SVMLight appends labels."""
    try:
        size = lib.dl4j_buf_size(handle)
        ndim = lib.dl4j_buf_ndim(handle)
        dims = (ctypes.c_int64 * max(ndim, 1))()
        lib.dl4j_buf_dims(handle, dims)
        shape = tuple(dims[i] for i in range(ndim))
        if size == 0:  # empty vector: .data() is NULL
            return np.zeros((0,), np.float32), shape
        flat = np.ctypeslib.as_array(lib.dl4j_buf_data(handle),
                                     shape=(size,)).astype(np.float32,
                                                           copy=True)
        return flat, shape
    finally:
        lib.dl4j_buf_free(handle)


def csv_to_array(path: str, delimiter: str = ",",
                 skip_lines: int = 0) -> Optional[np.ndarray]:
    """Parse an all-numeric CSV into [rows, cols] float32. None when the
    file is non-numeric/ragged (caller uses the Python text path) or the
    native library is unavailable."""
    lib = _load()
    if lib is None or len(delimiter) != 1:
        return None
    h = lib.dl4j_csv_parse(path.encode(), delimiter.encode(), skip_lines)
    if not h:
        return None
    flat, shape = _buf_to_flat(lib, h)
    return flat.reshape(shape)


def svmlight_to_arrays(path: str, num_features: int,
                       zero_based: bool = False
                       ) -> Optional[Tuple[np.ndarray, np.ndarray]]:
    """Parse SVMLight into (features [rows, n], labels [rows])."""
    lib = _load()
    if lib is None:
        return None
    h = lib.dl4j_svmlight_parse(path.encode(), num_features,
                                1 if zero_based else 0)
    if not h:
        return None
    # buffer layout: rows*n features then rows labels (dims = [rows, n])
    flat, (rows, n) = _buf_to_flat(lib, h)
    feats = flat[:rows * n].reshape(rows, n)
    labels = flat[rows * n:rows * n + rows]
    return feats, labels


def idx_to_array(path: str) -> Optional[np.ndarray]:
    """Parse an idx (MNIST) file into a float32 array with header dims."""
    lib = _load()
    if lib is None:
        return None
    h = lib.dl4j_idx_parse(path.encode())
    if not h:
        return None
    flat, shape = _buf_to_flat(lib, h)
    return flat.reshape(shape)


class FileStreamer:
    """Background read-ahead over a binary file of fixed-size chunks.

    The native analogue of AsyncDataSetIterator's prefetch thread: a C++
    thread fills a bounded ring; ``next()`` blocks on the condition
    variable, never the file. Iterate to EOF or ``close()`` early.
    """

    def __init__(self, path: str, chunk_bytes: int, capacity: int = 4):
        lib = _load()
        if lib is None:
            raise RuntimeError("native library unavailable")
        self._lib = lib
        self.chunk_bytes = chunk_bytes
        # one reusable receive buffer: next() calls are serialized per
        # streamer, and a fresh create_string_buffer per chunk would zero +
        # copy every chunk twice on the hot prefetch path
        self._buf = ctypes.create_string_buffer(chunk_bytes)
        self._h = lib.dl4j_stream_open(path.encode(), chunk_bytes, capacity)
        if not self._h:
            raise OSError(f"cannot stream {path}")

    def next(self) -> Optional[bytes]:
        if self._h is None:  # closed: C side would deref NULL
            return None
        got = self._lib.dl4j_stream_next(self._h, self._buf)
        if got == 0:
            return None
        return self._buf.raw[:got]

    def __iter__(self):
        while (b := self.next()) is not None:
            yield b

    def close(self) -> None:
        if self._h:
            self._lib.dl4j_stream_close(self._h)
            self._h = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def __del__(self):
        # a dropped streamer must release the C++ reader thread + FILE*
        try:
            self.close()
        except Exception:
            pass
