"""Graph embeddings: graph structure, random walks, DeepWalk.

Reference: deeplearning4j-graph (SURVEY §2.6) — ``graph/Graph.java`` (221;
adjacency-list IGraph), ``data/GraphLoader`` (170), ``iterator/
RandomWalkIterator`` (133) / ``WeightedRandomWalkIterator`` (156),
``models/deepwalk/DeepWalk.java`` (253; skip-gram-with-HS over random
walks, ``GraphHuffman`` 130), ``GraphVectorsImpl`` (107),
``loader/GraphVectorSerializer`` (82).
"""

from .graph import Graph, GraphLoader
from .walks import NoEdgeHandling, RandomWalkIterator, WeightedRandomWalkIterator
from .deepwalk import DeepWalk, GraphHuffman
from .serializer import GraphVectorSerializer

__all__ = [
    "Graph", "GraphLoader", "RandomWalkIterator",
    "WeightedRandomWalkIterator", "NoEdgeHandling", "DeepWalk",
    "GraphHuffman", "GraphVectorSerializer",
]
