"""Random-walk sequence generators over a Graph.

Reference: ``iterator/RandomWalkIterator.java:133`` (uniform next-vertex
choice, NoEdgeHandling SELF_LOOP vs EXCEPTION) and
``WeightedRandomWalkIterator.java:156`` (edge-weight-proportional choice).
"""

from __future__ import annotations

import enum
from typing import Iterator, Optional

import numpy as np

from .graph import Graph


class NoEdgeHandling(enum.Enum):
    SELF_LOOP_ON_DISCONNECTED = "self_loop"
    EXCEPTION_ON_DISCONNECTED = "exception"


class RandomWalkIterator:
    """Uniform random walks of fixed length, one starting at each vertex."""

    def __init__(self, graph: Graph, walk_length: int,
                 seed: int = 12345,
                 no_edge_handling: NoEdgeHandling =
                 NoEdgeHandling.SELF_LOOP_ON_DISCONNECTED):
        self.graph = graph
        self.walk_length = walk_length
        self.seed = seed
        self.no_edge_handling = no_edge_handling
        self.reset()

    def reset(self):
        self._rng = np.random.default_rng(self.seed)
        self._order = self._rng.permutation(self.graph.num_vertices)
        self._pos = 0

    def has_next(self) -> bool:
        return self._pos < len(self._order)

    def _choose_next(self, vertex: int) -> int:
        neighbors = self.graph.connected_vertices(vertex)
        if not neighbors:
            if (self.no_edge_handling
                    is NoEdgeHandling.EXCEPTION_ON_DISCONNECTED):
                raise RuntimeError(
                    f"vertex {vertex} has no outgoing edges")
            return vertex  # self loop
        return int(neighbors[self._rng.integers(len(neighbors))])

    def next(self) -> np.ndarray:
        """Next walk as an int array [walk_length + 1]."""
        start = int(self._order[self._pos])
        self._pos += 1
        walk = [start]
        cur = start
        for _ in range(self.walk_length):
            cur = self._choose_next(cur)
            walk.append(cur)
        return np.asarray(walk, np.int32)

    def __iter__(self) -> Iterator[np.ndarray]:
        while self.has_next():
            yield self.next()


class WeightedRandomWalkIterator(RandomWalkIterator):
    """Random walks with next-vertex probability ∝ edge weight."""

    def _choose_next(self, vertex: int) -> int:
        neighbors = self.graph.weighted_neighbors(vertex)
        if not neighbors:
            if (self.no_edge_handling
                    is NoEdgeHandling.EXCEPTION_ON_DISCONNECTED):
                raise RuntimeError(
                    f"vertex {vertex} has no outgoing edges")
            return vertex
        idx = [n for n, _ in neighbors]
        w = np.asarray([wt for _, wt in neighbors], np.float64)
        if np.any(w < 0):
            raise ValueError(
                f"vertex {vertex} has negative edge weights; weighted "
                "walks require non-negative weights")
        total = w.sum()
        if total <= 0:
            return int(idx[self._rng.integers(len(idx))])
        return int(self._rng.choice(idx, p=w / total))
